"""Resilience subsystem tests: durable snapshots under corruption, the
fault injector, and the self-healing loop (watchdog, rewind, preemption)
— the chaos acceptance bars of ISSUE 3 as unit tests."""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp, checkpoint
from apex_tpu.models.mlp import MLP, cross_entropy_loss
from apex_tpu.optimizers import FusedAdam
from apex_tpu.resilience import (CheckpointCorruptError, CorruptCheckpoint,
                                 DivergenceError, DurableCheckpointManager,
                                 FaultInjector, FlakyIO, HangStep, NaNStorm,
                                 Preempt, ResilienceConfig,
                                 SimulatedPreemption, WatchdogTimeout,
                                 retry_io, run_resilient, validate_incident,
                                 verify_snapshot)


def _workload(min_loss_scale=2.0 ** 14):
    """Tiny amp-O2 loop; min_loss_scale high so storms pin the scale in
    2 overflows instead of 16."""
    model = MLP(features=(32,))
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16)))["params"]
    a = amp.initialize(optimizer=FusedAdam(lr=1e-2), opt_level="O2",
                       min_loss_scale=min_loss_scale, verbosity=0)
    step = jax.jit(amp.make_train_step(
        a, lambda p, x, y: cross_entropy_loss(
            model.apply({"params": p}, x), y)))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 16))
    y = jax.random.randint(jax.random.PRNGKey(2), (32,), 0, 10)
    return a, step, a.init(params), lambda i: (x, y)


# ---------------------------------------------------------------------------
# retry_io
# ---------------------------------------------------------------------------

def test_retry_io_backoff_schedule(monkeypatch):
    sleeps = []
    monkeypatch.setattr(time, "sleep", sleeps.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 3:
            raise OSError("transient")
        return "ok"

    assert retry_io(flaky, retries=3, backoff_s=0.1) == "ok"
    assert calls["n"] == 4
    np.testing.assert_allclose(sleeps, [0.1, 0.2, 0.4])


def test_retry_io_exhaustion_raises(monkeypatch):
    monkeypatch.setattr(time, "sleep", lambda s: None)
    with pytest.raises(OSError):
        retry_io(lambda: (_ for _ in ()).throw(OSError("dead")), retries=2)


def test_retry_io_non_oserror_propagates_immediately():
    calls = {"n": 0}

    def bug():
        calls["n"] += 1
        raise ValueError("a bug, not weather")

    with pytest.raises(ValueError):
        retry_io(bug, retries=5)
    assert calls["n"] == 1


# ---------------------------------------------------------------------------
# durable snapshots under injected damage
# ---------------------------------------------------------------------------

def test_injected_truncation_restore_lands_on_last_good(tmp_path):
    """ISSUE acceptance: after checkpoint truncation the next restore
    lands on the last good (checksum-verified) snapshot."""
    _a, step, state, batch = _workload()
    inj = FaultInjector([CorruptCheckpoint(step=2, kind="truncate")], seed=3)
    mgr = DurableCheckpointManager(str(tmp_path), on_commit=inj.on_commit)
    for i in range(3):
        state, _ = step(state, *batch(i))
        mgr.save(i, state)
    mgr.wait()
    assert any(e["fault"] == "corrupt_checkpoint" for e in inj.events)

    restored, _ = mgr.restore(state)
    assert mgr.last_restore["step"] == 1          # 2 was damaged
    assert mgr.last_restore["skipped"][0]["step"] == 2
    ok, problems = verify_snapshot(str(tmp_path / "step_00000002"))
    assert not ok and problems


def test_bitflip_corruption_detected(tmp_path):
    _a, step, state, batch = _workload()
    inj = FaultInjector([CorruptCheckpoint(step=1, kind="corrupt")], seed=5)
    mgr = DurableCheckpointManager(str(tmp_path), on_commit=inj.on_commit)
    mgr.save(0, state)
    state2, _ = step(state, *batch(0))
    mgr.save(1, state2)
    mgr.wait()
    restored, _ = mgr.restore(state)
    assert mgr.last_restore["step"] == 0


def test_all_snapshots_corrupt_raises(tmp_path):
    _a, _step, state, _batch = _workload()
    mgr = DurableCheckpointManager(str(tmp_path))
    mgr.save(0, state)
    mgr.wait()
    for name in os.listdir(tmp_path / "step_00000000"):
        if name.endswith(".npy"):
            (tmp_path / "step_00000000" / name).write_bytes(b"rot")
    with pytest.raises(CheckpointCorruptError):
        mgr.restore(state)


def test_stale_tmp_dir_ignored_and_cleaned(tmp_path):
    """A crash mid-stage leaves a .tmp-* dir; it must never be restored
    from and a fresh manager clears it."""
    _a, _step, state, _batch = _workload()
    mgr = DurableCheckpointManager(str(tmp_path))
    mgr.save(4, state)
    mgr.wait()
    stale = tmp_path / ".tmp-step_00000009-dead"
    stale.mkdir()
    (stale / "leaf_00000.npy").write_bytes(b"partial")
    mgr2 = DurableCheckpointManager(str(tmp_path))
    assert not stale.exists()
    assert mgr2.latest_step() == 4


def test_background_save_error_surfaces_on_wait(tmp_path):
    _a, _step, state, _batch = _workload()
    inj = FaultInjector([FlakyIO(op="save", fails=1)])
    # io_retries=0 pins the surfacing path; with retries the worker
    # absorbs transient IO itself (tested below)
    mgr = DurableCheckpointManager(str(tmp_path), io_hook=inj.io_hook,
                                   io_retries=0)
    mgr.save(0, state)
    with pytest.raises(RuntimeError, match="background checkpoint save"):
        mgr.wait()


def test_async_save_retries_transient_io_in_worker(tmp_path, monkeypatch):
    """The default (async) manager must absorb flaky IO via in-worker
    retries — retrying at the enqueueing caller cannot help, the failed
    write is already off its hands."""
    import apex_tpu.resilience.loop as loop_mod
    monkeypatch.setattr(loop_mod.time, "sleep", lambda s: None)
    _a, _step, state, _batch = _workload()
    inj = FaultInjector([FlakyIO(op="save", fails=2)])
    mgr = DurableCheckpointManager(str(tmp_path), io_hook=inj.io_hook,
                                   io_retries=3, io_backoff_s=0.01)
    mgr.save(0, state)
    mgr.wait()                       # no error: absorbed on the 3rd try
    assert mgr.latest_step() == 0


def test_async_save_safe_under_buffer_donation(tmp_path):
    """save() must gather to host on the calling thread: under a
    donate_argnums train step the device buffers are invalidated as soon
    as the next step is dispatched, so a worker-side gather would read
    deleted arrays and silently lose the snapshot."""
    a, step, state, batch = _workload()
    donating = jax.jit(lambda st, x, y: step(st, x, y), donate_argnums=0)
    state, _ = donating(state, *batch(0))
    want = jax.tree.map(np.asarray, state)   # host copy before donation
    mgr = DurableCheckpointManager(str(tmp_path))
    mgr.save(0, state)
    state, _ = donating(state, *batch(1))    # donates the saved buffers
    mgr.wait()                               # must not have raced
    restored, _ = mgr.restore(state)
    for (p, got), leaf in zip(
            jax.tree_util.tree_leaves_with_path(restored),
            jax.tree.leaves(want)):
        np.testing.assert_array_equal(np.asarray(got), leaf,
                                      err_msg=jax.tree_util.keystr(p))


def test_close_stops_writer_thread(tmp_path):
    _a, _step, state, _batch = _workload()
    mgr = DurableCheckpointManager(str(tmp_path))
    mgr.save(0, state)
    mgr.close()
    assert mgr._worker is None or not mgr._worker.is_alive()


# ---------------------------------------------------------------------------
# re-save crash window + transient-IO restore (ROADMAP open items a, b)
# ---------------------------------------------------------------------------

def test_resave_crash_window_keeps_old_snapshot(tmp_path, monkeypatch):
    """ROADMAP item (a): a crash while re-saving an existing step must
    never lose the last good snapshot — the old directory is renamed
    aside before the commit and restored on failure, not rmtree'd."""
    import apex_tpu.resilience.durable as durable

    p_old = {"w": np.arange(4.0)}
    durable.write_snapshot(str(tmp_path), 7, p_old)

    real_replace = os.replace

    def exploding(src, dst):
        if os.path.basename(str(src)).startswith(".tmp-"):
            raise OSError(5, "simulated crash in the commit window")
        return real_replace(src, dst)

    monkeypatch.setattr(durable.os, "replace", exploding)
    with pytest.raises(OSError):
        durable.write_snapshot(str(tmp_path), 7, {"w": np.arange(4.0) * 2})
    monkeypatch.undo()

    # the OLD snapshot survived the failed commit, under its final name
    values, manifest = durable.read_snapshot(str(tmp_path / "step_00000007"))
    assert manifest["step"] == 7
    np.testing.assert_array_equal(next(iter(values.values())), p_old["w"])
    # no aside/tmp litter either
    assert [n for n in os.listdir(tmp_path)
            if n.startswith((".old-", ".tmp-"))] == []


def test_resave_commits_new_payload_and_drops_aside(tmp_path):
    import apex_tpu.resilience.durable as durable

    durable.write_snapshot(str(tmp_path), 7, {"w": np.arange(4.0)})
    durable.write_snapshot(str(tmp_path), 7, {"w": np.arange(4.0) * 2})
    values, _ = durable.read_snapshot(str(tmp_path / "step_00000007"))
    np.testing.assert_array_equal(next(iter(values.values())),
                                  np.arange(4.0) * 2)
    assert [n for n in os.listdir(tmp_path)
            if n.startswith((".old-", ".tmp-"))] == []


def test_process_crash_between_aside_and_commit_recovers(tmp_path):
    """The hard window — process death after rename-aside, before the
    commit rename: manager construction must rename the aside copy back
    (it IS the last good snapshot), while post-commit aside garbage is
    swept."""
    import apex_tpu.resilience.durable as durable

    durable.write_snapshot(str(tmp_path), 2, {"w": np.ones(3)})
    final = tmp_path / "step_00000002"
    os.replace(final, tmp_path / ".old-step_00000002-123-456")

    mgr = DurableCheckpointManager(str(tmp_path))
    assert final.is_dir()
    assert not (tmp_path / ".old-step_00000002-123-456").exists()
    assert mgr.latest_step() == 2
    ok, problems = verify_snapshot(str(final))
    assert ok, problems

    # post-commit garbage variant: both exist -> the aside is swept
    durable.write_snapshot(str(tmp_path), 2, {"w": np.ones(3) * 2})
    stale = tmp_path / ".old-step_00000002-9-9"
    stale.mkdir()
    DurableCheckpointManager(str(tmp_path))
    assert not stale.exists() and final.is_dir()
    values, _ = durable.read_snapshot(str(final))
    np.testing.assert_array_equal(next(iter(values.values())),
                                  np.ones(3) * 2)


def test_transient_leaf_read_oserror_is_retryable(tmp_path, monkeypatch):
    """ROADMAP item (b): a transient leaf-read OSError must propagate
    from read_snapshot — wrapping it as CheckpointCorruptError made
    retry_io-driven restores silently fall back to an older step."""
    import builtins

    from apex_tpu.resilience.durable import read_snapshot

    _a, step, state, batch = _workload()
    mgr = DurableCheckpointManager(str(tmp_path))
    mgr.save(0, state)
    state1, _ = step(state, *batch(0))
    mgr.save(1, state1)
    mgr.wait()

    real_open = builtins.open
    flakes = {"n": 2}

    def flaky_open(file, *a, **k):
        name = str(file)
        if "step_00000001" in name and "leaf_" in name and flakes["n"] > 0:
            flakes["n"] -= 1
            raise OSError(5, "Input/output error", name)
        return real_open(file, *a, **k)

    monkeypatch.setattr(builtins, "open", flaky_open)
    # the raw read raises the RETRYABLE class, not corruption
    with pytest.raises(OSError) as ei:
        read_snapshot(str(tmp_path / "step_00000001"))
    assert not isinstance(ei.value, CheckpointCorruptError)

    # through the loop's retry wrapper the SAME step restores — the
    # pre-fix behavior was a silent fallback to step 0
    restored, _ = retry_io(lambda: mgr.restore(state1), retries=3,
                           backoff_s=0.0)
    assert mgr.last_restore["step"] == 1
    assert flakes["n"] == 0


def test_missing_leaf_file_is_still_corrupt(tmp_path):
    """A leaf named by the manifest but absent on disk is structure
    damage (a truncated commit), not weather — stays corrupt so restore
    falls back."""
    import apex_tpu.resilience.durable as durable

    durable.write_snapshot(str(tmp_path), 0, {"w": np.ones(3)})
    os.unlink(tmp_path / "step_00000000" / "leaf_00000.npy")
    with pytest.raises(CheckpointCorruptError, match="missing"):
        durable.read_snapshot(str(tmp_path / "step_00000000"))


# ---------------------------------------------------------------------------
# the self-healing loop
# ---------------------------------------------------------------------------

def test_nan_storm_rewinds_and_converges(tmp_path):
    """ISSUE acceptance: under an injected NaN-grad storm the loop
    rewinds to the last good checkpoint (scaler re-initialized) and the
    run converges."""
    a, step, state, batch = _workload()
    inj = FaultInjector([NaNStorm(step=5, duration=6)])
    mgr = DurableCheckpointManager(str(tmp_path))
    cfg = ResilienceConfig(checkpoint_every=3, overflow_patience=3,
                           max_rewinds=2, watchdog_timeout_s=120.0)
    result = run_resilient(step, state, batch, 18, amp_obj=a, manager=mgr,
                           config=cfg, injector=inj)
    assert result.rewinds == 1
    rewind = [e for e in result.events if e["event"] == "rewind"][0]
    assert "pinned at min_loss_scale" in rewind["reason"]
    # converged: finite and better than the first recorded loss
    first, last = result.losses[0][1], result.losses[-1][1]
    assert np.isfinite(last) and last < first
    # scaler was re-initialized on rewind (storm left it at the floor)
    assert float(result.state.scaler_states[0].loss_scale) > 2.0 ** 14


def test_flaky_save_absorbed_by_retry(tmp_path):
    """Loop-level retry (manager's own retry pinned off via io_retries=0
    so the OSError actually reaches the loop)."""
    a, step, state, batch = _workload()
    inj = FaultInjector([FlakyIO(op="save", fails=2)])
    mgr = DurableCheckpointManager(str(tmp_path), async_save=False,
                                   io_hook=inj.io_hook, io_retries=0)
    cfg = ResilienceConfig(checkpoint_every=2, io_retries=3,
                           io_backoff_s=0.01)
    result = run_resilient(step, state, batch, 6, amp_obj=a, manager=mgr,
                           config=cfg, injector=inj)
    retries = [e for e in result.events if e["event"] == "save_retry"]
    assert retries and mgr.latest_step() is not None
    assert result.steps_completed == 6


def test_preemption_flushes_and_next_restore_is_good(tmp_path):
    """SIGTERM mid-step: run_resilient re-raises after flushing; a fresh
    process restores the last good snapshot and can finish the run."""
    a, step, state, batch = _workload()
    inj = FaultInjector([Preempt(step=7)])
    mgr = DurableCheckpointManager(str(tmp_path))
    out = tmp_path / "INCIDENT_preempt.json"
    cfg = ResilienceConfig(checkpoint_every=3, incident_path=str(out))
    with pytest.raises(SimulatedPreemption):
        run_resilient(step, state, batch, 12, amp_obj=a, manager=mgr,
                      config=cfg, injector=inj)
    rec = json.loads(out.read_text())
    assert rec["status"] == "preempted" and validate_incident(rec) == []

    # "restart": fresh manager + fresh template; lands on last good
    mgr2 = DurableCheckpointManager(str(tmp_path))
    restored, _ = mgr2.restore(a.init(jax.tree.map(np.asarray,
                                                   state.master_params)))
    assert mgr2.last_restore["step"] == 5      # saves at 2 and 5; 7 died
    result = run_resilient(step, restored, batch, 12, amp_obj=a,
                           manager=mgr2, config=cfg, injector=inj)
    assert np.isfinite(result.losses[-1][1])


def test_real_keyboard_interrupt_records_incident(tmp_path):
    """A real operator SIGINT (not the watchdog) must still leave a
    machine-checkable artifact — the r02 gap was exactly a run that died
    with no record."""
    a, step, state, _batch = _workload()
    out = tmp_path / "INCIDENT_interrupt.json"
    cfg = ResilienceConfig(incident_path=str(out))

    def interrupting_batch(i):
        if i == 3:
            raise KeyboardInterrupt
        return (jnp.zeros((32, 16)), jnp.zeros((32,), jnp.int32))

    with pytest.raises(KeyboardInterrupt):
        run_resilient(step, state, interrupting_batch, 8, amp_obj=a,
                      config=cfg)
    rec = json.loads(out.read_text())
    assert rec["status"] == "interrupted" and validate_incident(rec) == []


def test_watchdog_incident_within_budget(tmp_path):
    """ISSUE acceptance: a hung step produces an incident artifact within
    the watchdog budget and a graceful abort instead of a wedge."""
    a, step, state, batch = _workload()
    inj = FaultInjector([HangStep(step=2, seconds=1.5)])
    out = tmp_path / "INCIDENT_watchdog.json"
    cfg = ResilienceConfig(watchdog_timeout_s=0.3, watchdog_poll_s=0.02,
                           incident_path=str(out))
    t0 = time.monotonic()
    with pytest.raises(WatchdogTimeout):
        run_resilient(step, state, batch, 6, amp_obj=a, config=cfg,
                      injector=inj)
    elapsed = time.monotonic() - t0
    assert out.exists()
    written_at = os.path.getmtime(str(out))
    rec = json.loads(out.read_text())
    assert rec["status"] == "watchdog-timeout"
    assert validate_incident(rec) == []
    # artifact landed within the budget (+ slack for compile/poll), not
    # after the 1.5s hang resolved on its own
    assert written_at - t0 < 1.2 or elapsed < 2.5


def test_divergence_hard_fail_after_max_rewinds(tmp_path):
    """A storm that outlives the rewind budget must hard-fail with a
    structured incident, not loop forever."""
    a, step, state, batch = _workload()
    inj = FaultInjector([NaNStorm(step=2, duration=1000)])
    mgr = DurableCheckpointManager(str(tmp_path))
    out = tmp_path / "INCIDENT_diverged.json"
    cfg = ResilienceConfig(checkpoint_every=2, overflow_patience=2,
                           max_rewinds=1, incident_path=str(out))
    with pytest.raises(DivergenceError):
        run_resilient(step, state, batch, 40, amp_obj=a, manager=mgr,
                      config=cfg, injector=inj)
    rec = json.loads(out.read_text())
    assert rec["status"] == "diverged" and validate_incident(rec) == []


def test_no_checkpoint_to_rewind_to(tmp_path):
    a, step, state, batch = _workload()
    inj = FaultInjector([NaNStorm(step=0, duration=1000)])
    cfg = ResilienceConfig(checkpoint_every=0, overflow_patience=2)
    with pytest.raises(DivergenceError, match="no checkpoint"):
        run_resilient(step, state, batch, 20, amp_obj=a, config=cfg,
                      injector=inj)


def test_normal_overflow_skip_is_not_pathological():
    """A single overflow (scale far above the floor) is amp's normal
    transient — the sentinel must NOT rewind or fail."""
    a, step, state, batch = _workload(min_loss_scale=1.0)
    inj = FaultInjector([NaNStorm(step=3, duration=1)])
    cfg = ResilienceConfig(overflow_patience=3)
    result = run_resilient(step, state, batch, 8, amp_obj=a, config=cfg,
                           injector=inj)
    assert result.rewinds == 0
    assert result.steps_completed == 8
    assert np.isfinite(result.losses[-1][1])


def test_managerless_non_ampstate_checkpoints(tmp_path):
    """ROADMAP item (c): managerless run_resilient with a generic
    pytree state used to crash in ckpt.state_dict(st) at the first
    checkpoint step despite the hasattr guards."""
    def step_fn(st, x):
        w = st["w"] - 0.1 * x
        return {"w": w}, {"loss": jnp.sum(w ** 2)}

    cfg = ResilienceConfig(checkpoint_every=2)
    result = run_resilient(jax.jit(step_fn), {"w": jnp.ones(4)},
                           lambda i: (jnp.full((4,), 0.01),), 6,
                           config=cfg)
    assert result.steps_completed == 6
    assert sum(1 for e in result.events if e["event"] == "checkpoint") == 3
    assert np.isfinite(result.losses[-1][1])


def test_managerless_non_ampstate_rewinds_from_memory_snapshot():
    """The in-memory snapshot must also restore a generic pytree state
    (the rewind path used AmpState-only load_state_dict)."""
    def step_fn(st, x):
        w = st["w"] * 0.9 + x
        return {"w": w}, {"loss": jnp.sum(w)}

    fired = {"done": False}

    def batch(i):
        if i == 4 and not fired["done"]:
            fired["done"] = True
            return (jnp.full((4,), jnp.nan),)
        return (jnp.full((4,), 0.1),)

    cfg = ResilienceConfig(checkpoint_every=2, max_rewinds=2)
    result = run_resilient(jax.jit(step_fn), {"w": jnp.ones(4)}, batch, 8,
                           config=cfg)
    assert result.rewinds == 1
    rewind = [e for e in result.events if e["event"] == "rewind"][0]
    assert rewind["to_step"] == 3          # snapshots at 1 and 3; 4 NaN'd
    assert result.steps_completed == 8
    assert np.all(np.isfinite(np.asarray(result.state["w"])))


def test_preflight_runs_after_every_rewind(tmp_path):
    """ISSUE 16: a rewind restores state whose re-lowered program may no
    longer match the fleet (the restored step can reshape the mesh) —
    the configured SPMD preflight must re-run after the restore, before
    the loop resumes issuing collectives."""
    a, step, state, batch = _workload()
    inj = FaultInjector([NaNStorm(step=5, duration=6)])
    mgr = DurableCheckpointManager(str(tmp_path))
    calls = []
    cfg = ResilienceConfig(checkpoint_every=3, overflow_patience=3,
                           max_rewinds=2, watchdog_timeout_s=120.0,
                           preflight=lambda st: calls.append(st))
    result = run_resilient(step, state, batch, 18, amp_obj=a, manager=mgr,
                           config=cfg, injector=inj)
    assert result.rewinds == 1 and len(calls) == 1
    # the preflight saw the RESTORED state, not the poisoned one
    assert np.all(np.isfinite(
        np.asarray(jax.tree.leaves(calls[0].master_params)[0])))
    pf = [e for e in result.events if e["event"] == "preflight"]
    assert pf and pf[0]["to_step"] == \
        [e for e in result.events if e["event"] == "rewind"][0]["to_step"]


def test_preflight_rejection_aborts_with_incident(tmp_path):
    """A post-rewind preflight failure means the restored step would
    deadlock the fleet: the loop must abort (re-raise) and leave a
    machine-checkable incident naming the rejection — not resume."""
    a, step, state, batch = _workload()
    inj = FaultInjector([NaNStorm(step=5, duration=6)])
    mgr = DurableCheckpointManager(str(tmp_path))
    out = tmp_path / "INCIDENT_preflight.json"
    cfg = ResilienceConfig(checkpoint_every=3, overflow_patience=3,
                           max_rewinds=2, watchdog_timeout_s=120.0,
                           incident_path=str(out),
                           preflight=lambda st: (_ for _ in ()).throw(
                               RuntimeError("rank 1 diverged: extra "
                                            "all-reduce")))
    with pytest.raises(RuntimeError, match="rank 1 diverged"):
        run_resilient(step, state, batch, 18, amp_obj=a, manager=mgr,
                      config=cfg, injector=inj)
    rec = json.loads(out.read_text())
    assert rec["status"] == "preflight-failed"
    assert validate_incident(rec) == []
    assert "post-rewind SPMD preflight rejected" in rec["summary"]
    assert "rank 1 diverged" in rec["summary"]


def test_run_without_faults_matches_plain_loop():
    """No faults, no checkpointing: run_resilient must be semantically
    transparent — same final state as the bare loop, bitwise."""
    a, step, state, batch = _workload()
    bare = state
    for i in range(5):
        bare, _ = step(bare, *batch(i))
    result = run_resilient(step, state, batch, 5, amp_obj=a)
    for got, want in zip(jax.tree.leaves(result.state),
                         jax.tree.leaves(bare)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
