"""Legacy / module-level amp API and fp16util wrapper parity.

Covers the reference's two secondary entry styles (SURVEY.md §2 items 5, 7,
10): ``amp.init`` → ``wrap_optimizer`` (``apex/amp/amp.py:68-177``,
``opt.py:9-103``), module-level ``amp.scale_loss`` resolving through the
active-amp global (``_amp_state``), and ``convert_module`` / ``FP16Model``
(``fp16util.py:44-84``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu import amp, fp16_utils
from apex_tpu.models.mlp import MLP, cross_entropy_loss


def _data(key=0, n=64):
    x = jax.random.normal(jax.random.PRNGKey(key), (n, 16))
    y = (jnp.abs(x[:, 0] * 10).astype(jnp.int32)) % 4
    return x, y


def _model():
    model = MLP(features=(32, 4))
    params = model.init(jax.random.PRNGKey(1), jnp.zeros((1, 16)))["params"]
    return model, params


def test_legacy_init_wrap_optimizer_trains():
    model, params = _model()
    handle = amp.init(enabled=True, verbose=False)
    try:
        assert handle.is_active and not handle.has_cache
        a = handle.wrap_optimizer(optax.sgd(0.1))
        state = a.init(params)

        def loss_fn(p, x, y):
            return cross_entropy_loss(model.apply({"params": p}, x), y)

        step = jax.jit(amp.make_train_step(a, loss_fn))
        x, y = _data()
        first = None
        for _ in range(5):
            state, m = step(state, x, y)
            first = m["loss"] if first is None else first
        assert float(m["loss"]) < float(first)
        # handle.scale_loss routes through the wrapped optimizer's scaler
        scaled = handle.scale_loss(jnp.asarray(2.0), state)
        np.testing.assert_allclose(
            float(scaled), 2.0 * float(state.scaler_states[0].loss_scale))
    finally:
        handle._deactivate()


def test_legacy_init_disabled_returns_noop():
    handle = amp.init(enabled=False)
    assert not handle.is_active
    assert float(handle.scale_loss(jnp.asarray(3.0), None)) == 3.0
    a = handle.wrap_optimizer(optax.sgd(0.1))
    assert not a.properties.enabled
    handle._deactivate()


def test_module_level_scale_loss_uses_active_amp():
    a = amp.initialize(optimizer=optax.sgd(0.1), opt_level="O2",
                       verbosity=0)
    assert amp.active_amp() is a
    _, params = _model()
    state = a.init(params)
    scaled = amp.scale_loss(jnp.asarray(1.5), state)
    np.testing.assert_allclose(
        float(scaled), 1.5 * float(state.scaler_states[0].loss_scale))


def test_convert_module_casts_all_floats():
    _, params = _model()
    half = fp16_utils.convert_module(params, jnp.bfloat16)
    assert all(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(half))
    back = fp16_utils.convert_module(half, jnp.float32)
    assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(back))


def test_fp16_model_wrapper():
    model, params = _model()
    wrapped = fp16_utils.FP16Model(
        lambda p, x: model.apply({"params": p}, x))
    half_params = wrapped.convert(params)
    assert all(l.dtype == jnp.bfloat16
               for l in jax.tree.leaves(half_params))
    x, _ = _data()
    out = wrapped(half_params, x)          # fp32 input cast to bf16 inside
    assert out.dtype == jnp.bfloat16
    ref = model.apply({"params": half_params},
                      x.astype(jnp.bfloat16))
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32))


def test_fp16_optimizer_step_with_closure():
    """Closure-driven step (reference fp16_optimizer.py:361-460): one call
    runs the scaled backward and the conditional update; result equals the
    explicit backward + step composition."""
    import optax
    from apex_tpu.fp16_utils.fp16_optimizer import FP16Optimizer

    params = {"w": jnp.arange(4, dtype=jnp.float32) / 4}
    opt = FP16Optimizer(optax.sgd(0.1), dynamic_loss_scale=True)
    state = opt.init(params)
    x = jnp.ones((4,))

    def loss_fn(p, x):
        return jnp.sum((p["w"].astype(jnp.float32) * x - 1.0) ** 2)

    s1, loss1, info1 = opt.step_with_closure(state, loss_fn, x)
    loss2, grads = opt.backward(state, loss_fn, x)
    s2, info2 = opt.step(state, grads)
    assert float(loss1) == float(loss2)
    for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(s2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not bool(info1["overflow"])
    # and it jits
    jitted = jax.jit(lambda s, x: opt.step_with_closure(s, loss_fn, x))
    s3, l3, _ = jitted(state, x)
    assert float(l3) == float(loss1)
