"""Disaggregated prefill/decode serving (:mod:`apex_tpu.serve.router`
+ :mod:`apex_tpu.serve.transfer`).

The acceptance contracts: (a) a mixed stream served by the fleet —
prefill on its own mesh slice, KV shipped device-to-device to decode
replicas on disjoint slices — produces outputs BITWISE equal to solo
:func:`apex_tpu.models.generate.generate`, in both transfer modes
(ship vs recompute-on-miss parity); (b) killing a decode replica
mid-stream loses its device state, yet every request re-prefills
elsewhere and still ends bitwise equal to solo (the chaos gate);
(c) each replica keeps ONE trace and one executable per program across
admit/transfer/retire — shipment installation included; (d) the router
records its admission-control gauges and transfer counters on the
shared obs registry at step boundaries, never on a compiled step.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.models import GPTModel, gpt_tiny
from apex_tpu.models.generate import generate
from apex_tpu.obs.metrics import Registry
from apex_tpu.serve import (
    DisaggRouter,
    Request,
    RouterConfig,
    ServeConfig,
    advance_key,
    sample_tokens,
    slice_fleet,
)
from apex_tpu.serve import transfer as transfer_mod


@pytest.fixture(scope="module")
def setup():
    cfg = gpt_tiny()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    a = amp.initialize(opt_level="O2", verbosity=0)
    params = a.model_params_from(params)      # bf16 serving layout
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (n,))
               for n in (5, 12, 3, 20, 9)]
    return cfg, params, prompts


SCFG = ServeConfig(num_slots=2, block_size=4, num_blocks=17,
                   max_blocks_per_slot=8, prefill_chunk=4)


@pytest.fixture(scope="module")
def fleet(setup):
    """ONE ship-mode fleet (1 prefill slice + 2 decode replicas on
    disjoint single-device slices) shared by the stream tests — every
    extra fleet is 3 more engines' worth of XLA compiles, and sharing
    it makes the one-trace pins span the whole module's history."""
    cfg, params, _ = setup
    return DisaggRouter(
        params, cfg, SCFG,
        RouterConfig(n_decode_replicas=2, transfer="ship"),
        registry=Registry())


def _solo(params, cfg, prompt, n):
    out = generate(params, cfg, jnp.asarray(prompt[None]), n)
    return np.asarray(out)[0, len(prompt):]


# ---------------------------------------------------------------------------
# topology
# ---------------------------------------------------------------------------

def test_slice_fleet_disjoint_and_validated():
    devs = jax.devices()
    slices = slice_fleet(devs, n_prefill_devices=2,
                         n_decode_replicas=3, devices_per_replica=2)
    table = slices.describe()
    flat = table["prefill"] + [d for r in table["decode"] for d in r]
    assert len(flat) == len(set(flat)) == 8     # pairwise disjoint
    assert slices.n_devices == 8
    with pytest.raises(ValueError, match="needs"):
        slice_fleet(devs[:2], n_decode_replicas=2,
                    devices_per_replica=2)
    with pytest.raises(ValueError, match=">= 1"):
        slice_fleet(devs, n_decode_replicas=0)


def test_fleet_replicas_pinned_to_their_slices(fleet):
    """Committed placement IS the isolation: each replica's donated
    carry (and so its compiled step) lives on its own slice's
    devices, disjoint from the prefill worker's."""
    table = fleet.slices.describe()
    pre_devs = {d.id for d in
                fleet.prefill.eng.carry["kc"].devices()}
    assert pre_devs == set(table["prefill"])
    for rep, expect in zip(fleet.replicas, table["decode"]):
        got = {d.id for d in rep.eng.carry["kc"].devices()}
        assert got == set(expect)
    all_slices = [set(table["prefill"])] + \
        [set(r) for r in table["decode"]]
    for i, a in enumerate(all_slices):
        for b in all_slices[i + 1:]:
            assert not (a & b)


# ---------------------------------------------------------------------------
# the stream: ship-mode parity, trace pins, router metrics
# ---------------------------------------------------------------------------

def test_mixed_stream_ship_matches_solo_bitwise(setup, fleet):
    """The tier-1 fleet smoke: 5 mixed-length requests through 1
    prefill worker + 2 decode replicas (4 decode slots total, so the
    router HOLDS one request under admission control mid-stream),
    KV shipped between slices — every output bitwise equal to its
    solo generate() run."""
    cfg, params, prompts = setup
    news = (8, 6, 10, 4, 7)
    for i, (p, n) in enumerate(zip(prompts, news)):
        fleet.submit(Request(uid=f"r{i}", prompt=p, max_new_tokens=n))
    fleet.step()
    # 5 requests into 4 slots: admission control held the overflow
    assert fleet.metrics.gauge("serve_router_queue_depth").value >= 1
    out = fleet.run()
    for i, (p, n) in enumerate(zip(prompts, news)):
        np.testing.assert_array_equal(
            out[f"r{i}"], _solo(params, cfg, p, n),
            err_msg=f"r{i} diverged from solo through the fleet")
    # transfer accounting: every request shipped once, bytes moved
    m = fleet.metrics
    assert m.counter("serve_kv_shipments_total").value == 5
    assert m.counter("serve_kv_transfer_bytes").value > 0
    assert m.counter("serve_reroute_total").value == 0
    # every installed shipment moved its replica's admission-dispatch
    # marker: a KV-install scatter landing inside a contprof capture
    # window must discard that window exactly like a prefill would
    assert sum(r.eng._admission_dispatches
               for r in fleet.replicas) == 5
    # drained: router + per-replica gauges back to idle
    assert m.gauge("serve_router_queue_depth").value == 0
    for i in range(2):
        assert m.gauge(f"serve_replica{i}_queue_depth").value == 0
        assert m.gauge(f"serve_replica{i}_slot_occupancy").value == 0
        assert m.gauge(f"serve_replica{i}_block_utilization").value == 0


def test_one_trace_one_executable_per_replica(setup, fleet):
    """The static-shape contract across the WHOLE module's fleet
    history (admit/transfer/retire, both replicas, admission holds):
    one python trace and one jit-cache entry per compiled program —
    the decode step AND the shipment install on each replica, the
    prefill chunk AND the KV gather on the worker."""
    pre = fleet.prefill
    assert pre.eng.trace_counts["prefill"] == 1
    assert pre.eng.trace_counts["decode"] == 0   # the worker never decodes
    assert pre.trace_counts["gather"] == 1
    assert pre.eng._prefill_chunk._cache_size() == 1
    for rep in fleet.replicas:
        assert rep.eng.trace_counts == {"decode": 1, "prefill": 0,
                                        "sample1": 0}
        assert rep.trace_counts["install"] == 1
        assert rep.eng._decode_step._cache_size() == 1


def test_recompute_mode_parity(setup, fleet):
    """Transfer-path vs recompute-on-miss parity: the same stream
    served with transfer='recompute' (requests re-prefill on their
    decode replica; zero bytes shipped) is bitwise identical to the
    ship-mode outputs — the two KV paths are interchangeable, which
    is what makes recompute a safe fallback."""
    cfg, params, prompts = setup
    router = DisaggRouter(
        params, cfg, SCFG,
        RouterConfig(n_decode_replicas=2, transfer="recompute"),
        registry=Registry())
    news = (8, 6, 10, 4, 7)
    for i, (p, n) in enumerate(zip(prompts, news)):
        router.submit(Request(uid=f"q{i}", prompt=p, max_new_tokens=n))
    out = router.run()
    for i, (p, n) in enumerate(zip(prompts, news)):
        np.testing.assert_array_equal(out[f"q{i}"],
                                      _solo(params, cfg, p, n))
    m = router.metrics
    assert m.counter("serve_kv_transfer_bytes").value == 0
    assert m.counter("serve_kv_shipments_total").value == 0


# ---------------------------------------------------------------------------
# failure semantics: the chaos gate
# ---------------------------------------------------------------------------

def test_replica_kill_reroutes_and_stays_bitwise(setup):
    """THE chaos acceptance gate: kill a decode replica mid-stream
    (device state lost), its in-flight requests re-prefill elsewhere
    from the router's streamed-token log, and every final output —
    rerouted ones included — is bitwise equal to solo generate()."""
    cfg, params, prompts = setup
    router = DisaggRouter(
        params, cfg, SCFG,
        RouterConfig(n_decode_replicas=2, transfer="ship"),
        registry=Registry())
    news = (8, 6, 10, 4, 7)
    for i, (p, n) in enumerate(zip(prompts, news)):
        router.submit(Request(uid=f"k{i}", prompt=p, max_new_tokens=n))
    for _ in range(3):
        router.step()
    victim = max(router.replicas,
                 key=lambda r: r.eng.sched.n_active()).index
    rerouted = router.kill_replica(victim)
    assert rerouted                      # the kill hit live requests
    assert router.kill_replica(victim) == []     # idempotent
    out = router.run()
    for i, (p, n) in enumerate(zip(prompts, news)):
        np.testing.assert_array_equal(
            out[f"k{i}"], _solo(params, cfg, p, n),
            err_msg=f"k{i} diverged after the replica kill")
    m = router.metrics
    assert m.counter("serve_reroute_total").value == len(rerouted)
    # the dead replica took no further work; the survivor did it all
    assert not router.replicas[victim].alive
    assert router.replicas[victim].eng.sched.n_active() in (0, 1, 2)
    survivor = router.replicas[1 - victim]
    assert survivor.eng.sched.idle()


@pytest.mark.slow
def test_sampled_requests_resume_exact_prng_chain(setup):
    """A killed replica's SAMPLED requests also recover bitwise: the
    per-slot PRNG chain position is the draw count, so the router's
    advance_key re-derivation resumes exactly where the dead device
    was — pinned by comparing against an uninterrupted fleet."""
    cfg, params, prompts = setup

    def run(kill):
        router = DisaggRouter(
            params, cfg, SCFG,
            RouterConfig(n_decode_replicas=2, transfer="ship"),
            registry=Registry())
        router.submit(Request(uid="s0", prompt=prompts[0],
                              max_new_tokens=10, temperature=1.0,
                              top_k=50, top_p=0.9, seed=7))
        router.submit(Request(uid="s1", prompt=prompts[1],
                              max_new_tokens=8, temperature=0.8,
                              seed=3))
        if kill:
            for _ in range(3):
                router.step()
            busiest = max(router.replicas,
                          key=lambda r: r.eng.sched.n_active())
            router.kill_replica(busiest.index)
        return router.run()

    base, killed = run(False), run(True)
    for uid in ("s0", "s1"):
        np.testing.assert_array_equal(base[uid], killed[uid])


def test_advance_key_replays_the_sampling_chain():
    """``advance_key(seed_key, n)`` == the key after ``n``
    sample_tokens draws — the identity the kill recovery rests on."""
    logits = jnp.zeros((1, 16), jnp.float32)
    chained = jax.random.PRNGKey(7)[None].astype(jnp.uint32)
    for _ in range(5):
        _, chained = sample_tokens(logits, chained, jnp.ones(1),
                                   jnp.zeros(1, jnp.int32),
                                   jnp.ones(1))
    derived = advance_key(jax.random.PRNGKey(7), 5)
    np.testing.assert_array_equal(np.asarray(chained[0]),
                                  np.asarray(derived))


def test_mono_and_fleet_match_solo_on_ulp_adversarial_stream(setup):
    """The tie class is DEAD: PR 10's verification drive found a
    19-token prompt whose batched decode step greedy-diverged from
    solo ``generate()`` at token 8 with bitwise-identical caches — an
    exactly-tied bf16 logit pair whose ranking flipped with XLA:CPU
    fusion context (the lm-head matmul rematerialized per consumer,
    and ``jnp.argmax``'s tie-break followed whichever copy it fused
    with).  ``models.generate.pin_logits`` now materializes the
    logits once per program and ``greedy_argmax`` breaks exact ties
    by lowest index reassociation-proof, so the mono engine, the
    fleet, AND solo all agree bitwise on the adversarial stream —
    asserted as a tier-1 EQUALITY (this was the slow-marked
    fleet==mono regression test while the divergence lived; the
    speculative-decoding verifier's greedy token-match gate depends
    on this class staying dead)."""
    cfg, params, _ = setup
    rng = np.random.RandomState(5)
    for n in (4, 7, 10, 13, 16):          # the draw sequence that
        rng.randint(0, cfg.vocab_size, (n,))   # produced the tie case
    prompt = rng.randint(0, cfg.vocab_size, (19,))
    solo = _solo(params, cfg, prompt, 9)
    from apex_tpu.serve import ServeEngine
    eng = ServeEngine(params, cfg, SCFG, registry=Registry())
    eng.submit(Request(uid="x", prompt=prompt, max_new_tokens=9))
    mono = eng.run()["x"]
    np.testing.assert_array_equal(
        mono, solo, err_msg="mono engine vs solo: the ulp-tie "
        "divergence class is back (pin_logits/greedy_argmax)")
    router = DisaggRouter(
        params, cfg, SCFG,
        RouterConfig(n_decode_replicas=2, transfer="ship"),
        registry=Registry())
    router.submit(Request(uid="x", prompt=prompt, max_new_tokens=9))
    np.testing.assert_array_equal(router.run()["x"], solo)


# ---------------------------------------------------------------------------
# transfer mechanics (no model, no engine)
# ---------------------------------------------------------------------------

def test_gather_install_roundtrip_routes_trash():
    """Shipment format mechanics on raw pools: gather through a
    trash-padded source row, install through a DIFFERENT trash-padded
    destination row — real blocks land at the destination's physical
    ids, padding writes collapse onto the destination trash block,
    and the key lands at the traced slot index."""
    L, NB, BS, H, D = 2, 6, 4, 2, 3
    rng = np.random.RandomState(0)
    src_kc = jnp.asarray(rng.standard_normal((L, NB, BS, H, D)),
                         jnp.float32)
    src = {"kc": src_kc, "vc": src_kc * 2.0,
           "keys": jnp.zeros((2, 2), jnp.uint32)}
    gather = transfer_mod.make_gather(("kc", "vc"))
    src_row = jnp.asarray([3, 5, 0, 0], jnp.int32)   # 2 real + trash pad
    shipped = gather(src, src_row)
    assert shipped["kc"].shape == (L, 4, BS, H, D)
    np.testing.assert_array_equal(np.asarray(shipped["kc"][:, 0]),
                                  np.asarray(src_kc[:, 3]))
    install = transfer_mod.make_install(("kc", "vc"))
    dst = {"kc": jnp.zeros((L, NB, BS, H, D)),
           "vc": jnp.zeros((L, NB, BS, H, D)),
           "keys": jnp.zeros((2, 2), jnp.uint32)}
    dst_row = jnp.asarray([1, 2, 0, 0], jnp.int32)
    key = jnp.asarray([11, 22], jnp.uint32)
    out = install(dst, dst_row, shipped, jnp.int32(1), key)
    np.testing.assert_array_equal(np.asarray(out["kc"][:, 1]),
                                  np.asarray(src_kc[:, 3]))
    np.testing.assert_array_equal(np.asarray(out["kc"][:, 2]),
                                  np.asarray(src_kc[:, 5]))
    # non-destination blocks untouched; padding only hit the trash
    np.testing.assert_array_equal(np.asarray(out["kc"][:, 3]), 0.0)
    np.testing.assert_array_equal(np.asarray(out["kc"][:, 4]), 0.0)
    np.testing.assert_array_equal(np.asarray(out["keys"][1]),
                                  np.asarray(key))
    np.testing.assert_array_equal(np.asarray(out["keys"][0]), 0)
    # the byte count the router charges serve_kv_transfer_bytes with
    assert transfer_mod.shipment_bytes(shipped, key) == \
        2 * shipped["kc"].size * 4 + 8


def test_router_config_and_submit_validation(setup, fleet):
    with pytest.raises(ValueError, match="transfer"):
        RouterConfig(transfer="teleport")
    with pytest.raises(ValueError, match="admit_block_util"):
        RouterConfig(admit_block_util=0.0)
    with pytest.raises(ValueError, match="non-empty"):
        fleet.submit(Request(uid="e", prompt=np.zeros(0, np.int32),
                             max_new_tokens=4))
    with pytest.raises(ValueError, match="context"):
        fleet.submit(Request(uid="big",
                             prompt=np.zeros(30, np.int32),
                             max_new_tokens=8))


# ---------------------------------------------------------------------------
# fleet cold start: the per-slice AOT cache
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_cold_start_probes_per_slice_entries(setup, tmp_path):
    """Every replica cold-starts through ``ServeConfig.aot_cache``:
    the first fleet compiles and exports one lint-gated entry PER
    SLICE (device ids join the cache key — a PJRT executable is
    pinned to its devices, so slices must not share entries), and a
    restarted fleet LOADS every replica's executable instead of
    compiling — tokens bitwise identical."""
    cfg, params, prompts = setup
    scfg = dataclasses.replace(SCFG, aot_cache=str(tmp_path))

    def build():
        return DisaggRouter(
            params, cfg, scfg,
            RouterConfig(n_decode_replicas=2, transfer="ship"),
            registry=Registry())

    r1 = build()
    assert all(rep.eng.aot_info["source"] == "compile"
               for rep in r1.replicas)
    keys = {rep.eng.aot_info["key"] for rep in r1.replicas}
    assert len(keys) == 2                 # per-slice keys, no sharing
    r1.submit(Request(uid="a", prompt=prompts[0], max_new_tokens=6))
    out1 = r1.run()
    r2 = build()                          # the restarted fleet
    assert all(rep.eng.aot_info["source"] == "cache"
               for rep in r2.replicas)
    r2.submit(Request(uid="a", prompt=prompts[0], max_new_tokens=6))
    out2 = r2.run()
    np.testing.assert_array_equal(out1["a"], out2["a"])
