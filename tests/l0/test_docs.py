"""Doc-build validation (reference parity: docs/source/conf.py + sphinx
build).  When sphinx is installed the full ``sphinx-build -W`` runs; the
structural checks below run everywhere (this environment has no sphinx and
no pip), so toctree rot and broken autodoc targets fail CI either way.
"""

import importlib
import re
import subprocess
import sys
from pathlib import Path

import pytest

DOCS = Path(__file__).resolve().parents[2] / "docs" / "source"


def test_conf_exists_and_parses():
    conf = DOCS / "conf.py"
    assert conf.exists()
    ns: dict = {"__file__": str(conf)}
    code = compile(conf.read_text(), str(conf), "exec")
    exec(code, ns)  # noqa: S102 - our own conf.py
    assert ns["project"] == "apex_tpu"
    assert "sphinx.ext.autodoc" in ns["extensions"]


def test_index_toctree_covers_all_pages():
    index = (DOCS / "index.rst").read_text()
    listed = set(re.findall(r"^   ([a-z_0-9]+)$", index, re.M))
    pages = {p.stem for p in DOCS.glob("*.rst")} - {"index"}
    missing = pages - listed
    assert not missing, f"rst pages not reachable from index toctree: {missing}"
    ghosts = listed - pages
    assert not ghosts, f"toctree entries without an rst page: {ghosts}"


def test_crossref_targets_resolve():
    """Every ``:mod:``/``:class:``/``:func:`` role naming a fully-qualified
    ``apex_tpu`` object must resolve against the live package — the
    structural equivalent of a ``-W`` autodoc build for these hand-written
    API pages."""
    roles = set()
    for p in DOCS.glob("*.rst"):
        roles |= set(re.findall(r":(?:mod|class|func):`~?(apex_tpu[\w.]*)`",
                                p.read_text()))
    assert roles, "no apex_tpu cross-references found"
    for name in sorted(roles):
        parts = name.split(".")
        obj = None
        for cut in range(len(parts), 0, -1):
            try:
                obj = importlib.import_module(".".join(parts[:cut]))
            except ImportError:
                continue
            for attr in parts[cut:]:
                obj = getattr(obj, attr)  # AttributeError = broken ref
            break
        assert obj is not None, f"unresolvable doc reference: {name}"


def test_sphinx_build_clean():
    pytest.importorskip("sphinx")
    out = subprocess.run(
        [sys.executable, "-m", "sphinx", "-W", "-b", "html", str(DOCS),
         "/tmp/apex_tpu_docs_build"],
        capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
