"""Doc-build validation (reference parity: docs/source/conf.py + sphinx
build).  This environment has no sphinx and no way to obtain one (no
egress; ``docutils``/``alabaster``/``imagesize``/``snowballstemmer``
absent too), so the build check is **never skipped**: when sphinx is
importable the real ``sphinx-build -W`` runs, otherwise the pinned
substitute ``tools/rst_check.py`` enforces the same warning classes
(unknown directives/roles, short title adornments, dead :doc:/include
targets, unlexable code-block languages, unbalanced literals) — and its
own detection power is verified here against planted defects.
"""

import importlib
import re
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
DOCS = REPO / "docs" / "source"
sys.path.insert(0, str(REPO / "tools"))


def test_conf_exists_and_parses():
    conf = DOCS / "conf.py"
    assert conf.exists()
    ns: dict = {"__file__": str(conf)}
    code = compile(conf.read_text(), str(conf), "exec")
    exec(code, ns)  # noqa: S102 - our own conf.py
    assert ns["project"] == "apex_tpu"
    assert "sphinx.ext.autodoc" in ns["extensions"]


def test_index_toctree_covers_all_pages():
    index = (DOCS / "index.rst").read_text()
    listed = set(re.findall(r"^   ([a-z_0-9]+)$", index, re.M))
    pages = {p.stem for p in DOCS.glob("*.rst")} - {"index"}
    missing = pages - listed
    assert not missing, f"rst pages not reachable from index toctree: {missing}"
    ghosts = listed - pages
    assert not ghosts, f"toctree entries without an rst page: {ghosts}"


def test_crossref_targets_resolve():
    """Every ``:mod:``/``:class:``/``:func:`` role naming a fully-qualified
    ``apex_tpu`` object must resolve against the live package — the
    structural equivalent of a ``-W`` autodoc build for these hand-written
    API pages."""
    roles = set()
    for p in DOCS.glob("*.rst"):
        roles |= set(re.findall(r":(?:mod|class|func):`~?(apex_tpu[\w.]*)`",
                                p.read_text()))
    assert roles, "no apex_tpu cross-references found"
    for name in sorted(roles):
        parts = name.split(".")
        obj = None
        for cut in range(len(parts), 0, -1):
            try:
                obj = importlib.import_module(".".join(parts[:cut]))
            except ImportError:
                continue
            for attr in parts[cut:]:
                obj = getattr(obj, attr)  # AttributeError = broken ref
            break
        assert obj is not None, f"unresolvable doc reference: {name}"


def test_docs_build_clean():
    """``sphinx-build -W`` when sphinx exists; the strict rst_check
    substitute otherwise — never skipped."""
    try:
        importlib.import_module("sphinx")
    except ImportError:
        from rst_check import check_tree
        problems = check_tree(DOCS)
        assert not problems, "\n".join(problems)
        return
    out = subprocess.run(
        [sys.executable, "-m", "sphinx", "-W", "-b", "html", str(DOCS),
         "/tmp/apex_tpu_docs_build"],
        capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]


def _check_snippet(tmp_path, name, text, extra=None):
    from rst_check import check_file
    (tmp_path / "index.rst").write_text("Index\n=====\n")
    for fname, body in (extra or {}).items():
        (tmp_path / fname).write_text(body)
    p = tmp_path / name
    p.write_text(text)
    return check_file(p, tmp_path)


def test_rst_check_catches_planted_defects(tmp_path):
    """The substitute checker must actually detect each warning class
    it claims — otherwise the no-skip build check is a rubber stamp."""
    cases = {
        "unknown directive": ".. automodul:: apex_tpu\n",
        "unknown role": "see :fnc:`apex_tpu.amp.initialize`\n",
        "short adornment": "A long section title\n===\n",
        "dead doc target": "see :doc:`no_such_page`\n",
        "dead include": ".. literalinclude:: ../nope.py\n",
        "bad code language": ".. code-block:: pythn\n\n   x = 1\n",
        "unbalanced literal": "an ``unclosed literal here\n\nnext\n",
        "tab": "a\tb\n",
        # directive BODIES are real RST — a bare '.. note::' must not
        # exempt its content from validation (review repro)
        "bad role inside admonition":
            ".. note::\n\n   see :fnc:`bad_role`\n",
        "unknown directive inside admonition":
            ".. warning::\n\n   .. automodul:: x\n",
        "rotted toctree entry":
            ".. toctree::\n   :maxdepth: 2\n\n   no_such_page\n",
    }
    for label, text in cases.items():
        problems = _check_snippet(tmp_path, "page.rst", text)
        assert problems, f"planted defect not caught: {label}"


def test_rst_check_accepts_valid_constructs(tmp_path):
    text = (
        "A title\n=======\n\n"
        "Prose with a ``literal that\nwraps lines`` and a "
        ":func:`~apex_tpu.amp.initialize` role, :doc:`other`.\n\n"
        ".. code-block:: python\n\n   x = {'not rst': True}\n\n"
        ".. literalinclude:: snippet.py\n\n"
        "Literal block follows::\n\n   .. not_a_directive:: ignored\n"
    )
    problems = _check_snippet(
        tmp_path, "page.rst", text,
        extra={"other.rst": "Other\n=====\n", "snippet.py": "pass\n"})
    assert not problems, problems


def test_rst_check_clean_on_repo_docs():
    from rst_check import check_tree
    assert check_tree(DOCS) == []
