"""Cross-feature amp integrations (VERDICT r2 items 2/3/6).

The reference wires LARC into amp explicitly (``apex/amp/_initialize.py:155``,
``apex/amp/handle.py:88``); here the composition is
``amp.initialize(optimizer=LARC(inner, lr))`` and these tests pin it against
regression: the optax chain must receive the fp32 *master* params (LARC's
trust ratio reads them), run after unscaling, and leave the overflow-skip
machinery intact.

The cast-cache equivalence tests demonstrate the documented position on the
reference's O1 weight-cast cache (``apex/amp/utils.py:87-119``, guarded by
``tests/L0/run_amp/test_cache.py:31-96``): under XLA there is nothing to
cache — every step re-casts the *current* fp32 params inside the trace, so
train→eval→train transitions can never see a stale half copy.  The claimed
equivalence is asserted, not assumed: a reused compiled train step produces
bit-identical updates to cold fresh computations around an interleaved eval.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu import amp
from apex_tpu.models.mlp import MLP, cross_entropy_loss
from apex_tpu.optimizers import LARC

LR = 0.1
TRUST = 0.02
EPS = 1e-8


def _setup(seed=0, features=(16, 4), dim=8, batch=32):
    model = MLP(features=features)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, dim)))["params"]
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(batch, dim).astype(np.float32))
    y = jnp.asarray(rng.randint(0, features[-1], batch))
    def loss_fn(p, xb, yb):
        return cross_entropy_loss(model.apply({"params": p}, xb), yb)
    return model, params, x, y, loss_fn


# ---------------------------------------------------------------------------
# amp x LARC


def test_amp_larc_trains_and_descends():
    """The composition the reference builds in ``_initialize.py:155``:
    amp O2 + dynamic scaling around a LARC-wrapped inner optimizer."""
    _, params, x, y, loss_fn = _setup()
    a = amp.initialize(optimizer=LARC(optax.sgd(LR), LR,
                                      trust_coefficient=TRUST),
                       opt_level="O2", verbosity=0)
    state = a.init(params)
    step = jax.jit(amp.make_train_step(a, loss_fn))
    losses = []
    for _ in range(40):
        state, m = step(state, x, y)
        losses.append(float(m["loss"]))
    assert np.all(np.isfinite(losses))
    # LARC's trust ratio shrinks the effective lr (that's its job), so
    # descent is slower than plain sgd — require steady progress, not
    # sgd-speed progress
    assert losses[-1] < losses[0] - 0.3
    assert float(m["loss_scale"]) == 2.0 ** 16  # no spurious overflows


def test_amp_larc_saturated_clip_equals_plain_inner():
    """clip mode caps the adaptive ratio at 1 (``LARC.py:82-86``): with a
    huge trust coefficient every leaf saturates, so the wrapped run must
    equal the plain-inner run bit for bit — pinning that LARC sits in the
    chain as a pure gradient transformation (no lr double-count, no
    reordering around the unscale)."""
    _, params, x, y, loss_fn = _setup(seed=1)

    def run(optimizer):
        a = amp.initialize(optimizer=optimizer, opt_level="O2",
                           verbosity=0)
        state = a.init(params)
        step = jax.jit(amp.make_train_step(a, loss_fn))
        for _ in range(5):
            state, _ = step(state, x, y)
        return state.master_params

    wrapped = run(LARC(optax.sgd(LR), LR, trust_coefficient=1e6))
    plain = run(optax.sgd(LR))
    for w, p in zip(jax.tree.leaves(wrapped), jax.tree.leaves(plain)):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(p))


def test_amp_larc_step_matches_manual_composition():
    """One O2 step against an independently-computed reference: bf16 grads
    of the scaled loss, scaler unscale, LARC's trust math in fp32 on the
    *masters* (the params amp hands the chain), then the sgd update —
    mirroring each dtype cast the real path performs."""
    _, params, x, y, loss_fn = _setup(seed=2)
    a = amp.initialize(optimizer=LARC(optax.sgd(LR), LR,
                                      trust_coefficient=TRUST, eps=EPS),
                       opt_level="O2", verbosity=0)
    state = a.init(params)
    step = jax.jit(amp.make_train_step(a, loss_fn))
    new_state, m = step(state, x, y)
    assert not bool(m["overflow"])

    params_c = a.model_params(state)
    # a.run mirrors the real step's input casting (batch -> bf16 under O2)
    g = jax.grad(lambda p: a.scale_loss(a.run(loss_fn, p, x, y),
                                        state))(params_c)
    gu, finite = a.scaler.unscale(g, state.scaler_states[0])
    assert bool(finite)

    def expect(master, grad):
        g32 = np.asarray(grad, np.float32)
        p32 = np.asarray(master, np.float32)
        p_n, g_n = np.linalg.norm(p32), np.linalg.norm(g32)
        rate = min(TRUST * p_n / (g_n + EPS) / LR, 1.0)
        scaled = (g32 * rate if p_n > 0 and g_n > 0 else g32)
        # the larc stage emits at the grad dtype; sgd scales by -lr
        larc_out = jnp.asarray(scaled).astype(grad.dtype)
        return np.asarray(master) + np.asarray(
            jnp.asarray(-LR, larc_out.dtype) * larc_out, np.float32)

    got = jax.tree.leaves(new_state.master_params)
    want = jax.tree.map(expect, state.master_params, gu)
    for g_leaf, w_leaf in zip(got, jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(g_leaf), w_leaf,
                                   rtol=1e-5, atol=1e-6)


def test_amp_larc_overflow_still_skips():
    """The conditional-step machinery must wrap the whole chain: an inf
    grad skips LARC + inner update and halves the scale."""
    _, params, x, y, loss_fn = _setup(seed=3)
    a = amp.initialize(optimizer=LARC(optax.sgd(LR), LR),
                       opt_level="O2", verbosity=0)
    state = a.init(params)
    x_bad = x.at[0, 0].set(jnp.inf)
    step = jax.jit(amp.make_train_step(a, loss_fn))
    new_state, m = step(state, x_bad, y)
    assert bool(m["overflow"])
    for old, new in zip(jax.tree.leaves(state.master_params),
                        jax.tree.leaves(new_state.master_params)):
        np.testing.assert_array_equal(np.asarray(old), np.asarray(new))
    assert float(new_state.scaler_states[0].loss_scale) == 2.0 ** 15


# ---------------------------------------------------------------------------
# cast-cache equivalence (train -> eval -> train)


def test_train_eval_train_casts_are_never_stale():
    """Port of the cache-guard axis of ``test_cache.py:31-96``: after a
    param update and an interleaved eval forward, the next train step must
    see casts of the *updated* params.  The reused compiled step (the only
    place a stale half copy could hide) must match a cold, freshly-traced
    computation at every point — bit-identical, not tolerance-close."""
    model, params, x, y, loss_fn = _setup(seed=4)
    a = amp.initialize(optimizer=optax.sgd(LR), opt_level="O1",
                       verbosity=0)
    state = a.init(params)
    step = jax.jit(amp.make_train_step(a, loss_fn))      # reused across modes

    state1, m1 = step(state, x, y)

    # eval forward between the train steps (train->eval transition);
    # O1 keeps params fp32, so the masters ARE the eval params
    eval_logits = jax.jit(model.apply)({"params": state1.master_params}, x)
    assert bool(jnp.all(jnp.isfinite(eval_logits.astype(jnp.float32))))

    state2, m2 = step(state1, x, y)                      # eval->train

    # cold path: a brand-new Amp + train step traced from scratch on the
    # same numbers — the "uncached" reference
    a_cold = amp.initialize(optimizer=optax.sgd(LR), opt_level="O1",
                            verbosity=0)
    cold1, _ = jax.jit(amp.make_train_step(a_cold, loss_fn))(
        a_cold.init(params), x, y)
    cold2, _ = jax.jit(amp.make_train_step(a_cold, loss_fn))(cold1, x, y)

    for got, want in zip(jax.tree.leaves(state2.master_params),
                         jax.tree.leaves(cold2.master_params)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_repeated_casts_track_fp32_reference():
    """The other half of the cache claim: per-step casting from fp32
    params (what every step does) stays within bf16 tolerance of the pure
    fp32 run across a train->eval->train sequence — correctness of the
    cast-per-use scheme itself, not just its statelessness."""
    _, params, x, y, loss_fn = _setup(seed=5)

    def run(level):
        a = amp.initialize(optimizer=optax.sgd(LR), opt_level=level,
                           verbosity=0)
        state = a.init(params)
        step = jax.jit(amp.make_train_step(a, loss_fn))
        state, _ = step(state, x, y)
        state, m = step(state, x, y)
        return float(m["loss"])

    np.testing.assert_allclose(run("O1"), run("O0"), rtol=0.05, atol=0.02)


def test_init_masters_never_alias_caller_params():
    """Donation safety: ``a.init(params)`` must CLONE every leaf
    (reference ``_initialize.py`` clones masters).  ``astype`` to an
    unchanged dtype aliases in JAX, so without the clone a
    ``donate_argnums=(0,)`` step deletes the caller's params — a later
    ``a.init(params)`` then dies with "Array has been deleted" (an
    opaque INVALID_ARGUMENT on TPU).  Pinned for every opt level and a
    non-floating leaf."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import amp

    params = {"w": jnp.ones((4, 4), jnp.float32),
              "b": jnp.zeros((4,), jnp.float32),
              "step_count": jnp.zeros((), jnp.int32)}
    for lvl in ("O0", "O1", "O2", "O3"):
        a = amp.initialize(opt_level=lvl, verbosity=0)
        state = a.init(params)
        src = {jax.tree_util.keystr(k): v
               for k, v in jax.tree_util.tree_leaves_with_path(params)}
        for path, leaf in jax.tree_util.tree_leaves_with_path(
                state.master_params):
            key = jax.tree_util.keystr(path)
            assert leaf is not src[key], (lvl, key)
        # deleting the state must leave params fully usable
        jax.tree.map(lambda x: x.delete(), state.master_params)
        assert float(params["w"].sum()) == 16.0
