"""Port of the reference's largest conformance suite: the mixed-precision
control-flow matrix (``tests/L0/run_amp/test_multiple_models_optimizers_losses.py``,
762 LoC).

Matrix per topology: opt level {O0..O3} x shared/per-loss scalers x injected
inf at a chosen {iteration, tensor-dtype location, backward pass, model},
asserting per-iteration unscaled grads and final params against an
unscaled fp32-reference run (which replays the expected skip pattern).

Mapping notes (SURVEY.md section 7 design stance):

- The reference drives ``with amp.scale_loss(loss_i, optimizer_j, loss_id=k)``
  per backward; each exit unscales into master grads, runs scaler ``k``'s
  ``update_scale``, and arms ``skip_step`` on every optimizer passed
  (``handle.py:110-150``).  Here the same composition is explicit:
  ``Amp.unscale_gradients`` + ``Amp.update_scaler`` + ``Amp.step_if``
  (or ``Amp.apply_gradients_multi`` for the one-optimizer topologies).
- ``how_to_zero`` {none, model, optimizer} has no analog: functional grads
  are fresh by construction, which is the semantics all three spellings
  converge to in the reference.
- The fp16 leaf is bfloat16 here (TPU-native); all test values are small
  dyadic rationals exactly representable in bf16, preserving the reference's
  exact-comparison design.
- ``cast_model_type=False`` (model left at incoming dtypes) maps to
  ``cast_model_dtype=False``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import amp

HALF = jnp.bfloat16
X = jnp.ones((2,), jnp.float32)
OPT_LEVELS = ("O0", "O1", "O2", "O3")


def make_model(unique):
    """MyModel's params (reference :16-28): an fp32 and a half-dtype leaf."""
    return {
        "w0": unique + jnp.arange(2, dtype=jnp.float32),
        "w1": (1.0 + unique + jnp.arange(2, dtype=jnp.float32)).astype(HALF),
    }


def model_loss(params, x=X):
    """MyModel.ops: ``((x * w0.float()) * w1.float()).sum()``."""
    return ((x * params["w0"].astype(jnp.float32))
            * params["w1"].astype(jnp.float32)).sum()


def sgd_by_group(lr_by_key, momentum):
    """torch.optim.SGD with per-param-group lr: ``buf = m*buf + g;
    p -= lr*buf`` == optax.sgd(lr, momentum=m) routed per top-level key."""
    return optax.multi_transform(
        {k: optax.sgd(lr, momentum=momentum) for k, lr in lr_by_key.items()},
        param_labels=lambda params: {
            k: jax.tree.map(lambda _: k, v) for k, v in params.items()})


def tree_add(a, b):
    return jax.tree.map(jnp.add, a, b)


def reference_dtype_params(params, opt_level):
    """Param tree for the fp32-reference run.  Under O2 the amp run carries
    fp32 masters — and, with the model cast disabled, computes on them — so
    its exact reference is an all-fp32 run.  (The torch original compared
    fp32 masters against an fp16-model run and passed only because fp16's
    10 mantissa bits absorb 3 iterations of this arithmetic; bf16's 7 do
    not — SURVEY.md section 7, "bitwise L1 conformance".)  The other levels
    step the incoming mixed-dtype params directly, so the reference keeps
    the bf16 leaf."""
    if opt_level == "O2":
        return jax.tree.map(lambda x: x.astype(jnp.float32), params)
    return params


def tree_allclose(a, b, **kw):
    flat_a, flat_b = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(flat_a) == len(flat_b)
    for la, lb in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(la, np.float32),
                                   np.asarray(lb, np.float32), **kw)


def inject_inf_into(grads, model_key, loc):
    """Plant an inf in grads[model_key][w0|w1][0] (reference :139-150:
    ``model.weight{0,1}.grad[0] = inf`` — fp32 -> w0, fp16 -> w1)."""
    leaf = "w0" if loc == "fp32" else "w1"
    g = grads[model_key][leaf]
    grads = dict(grads)
    grads[model_key] = dict(grads[model_key])
    grads[model_key][leaf] = g.at[0].set(jnp.inf)
    return grads


def case_grid(opt_level, which_backwards=(0, 1),
              which_models_by_backward=None):
    """The inject-inf grid of the reference: O1/O2 (dynamic-scaler levels)
    also run with an inf planted at iteration {0,1} x loc x backward
    (x model, when a backward spans several models)."""
    cases = [dict(inject_inf=-1, inject_inf_loc=None, which_backward=None,
                  which_model=None)]
    if opt_level in ("O1", "O2"):
        for inject_inf in (0, 1):
            for loc in ("fp16", "fp32"):
                for wb in which_backwards:
                    models = (which_models_by_backward[wb]
                              if which_models_by_backward else (None,))
                    for wm in models:
                        cases.append(dict(inject_inf=inject_inf,
                                          inject_inf_loc=loc,
                                          which_backward=wb, which_model=wm))
    return cases


def init_amp(opt_level, tx, num_losses):
    a = amp.initialize(optimizer=tx, opt_level=opt_level,
                       cast_model_dtype=False, num_losses=num_losses,
                       half_dtype=HALF, verbosity=0)
    return a


def seed_scales(state, num_losses):
    """The reference pins ``loss_scalers[0]._loss_scale = 4.0`` (and 16.0 for
    a second scaler) so scaled values stay exact (:116-119)."""
    sstates = list(state.scaler_states)
    sstates[0] = sstates[0]._replace(loss_scale=jnp.asarray(4.0, jnp.float32))
    if num_losses == 2:
        sstates[1] = sstates[1]._replace(
            loss_scale=jnp.asarray(16.0, jnp.float32))
    return state._replace(scaler_states=tuple(sstates))


# ---------------------------------------------------------------------------
# topology 1+2: N models, 2 losses, ONE optimizer (reference :45-169,170-325)
# ---------------------------------------------------------------------------

def _run_one_optimizer_case(n_models, opt_level, use_multiple_loss_scalers,
                            case):
    """Shared driver: loss0/loss1 each touch a subset of models; grads
    accumulate into one optimizer through per-loss scalers."""
    if n_models == 2:
        lrs = {"m0": 0.25, "m1": 0.5}
        loss_parts = [("m0",), ("m1",)]          # loss_j = sum of models
    else:
        lrs = {"m0": 0.25, "m1": 0.5, "m2": 0.125}
        loss_parts = [("m0", "m2"), ("m1", "m2")]  # reference :183-186

    def loss_fn(j):
        def f(params):
            return sum(model_loss(params[k]) for k in loss_parts[j])
        return f

    momentum = 0.125
    params0 = {f"m{i}": make_model(1 + i) for i in range(n_models)}

    # ---- fp32 reference run (no amp): 2 iters, grads + final params ----
    tx = sgd_by_group(lrs, momentum)
    ref_params = reference_dtype_params(params0, opt_level)
    ref_opt = tx.init(ref_params)
    reference_grads = []
    for _ in range(2):
        g0 = jax.grad(loss_fn(0))(ref_params)
        g1 = jax.grad(loss_fn(1))(ref_params)
        g = tree_add(g0, g1)
        reference_grads.append(g)
        updates, ref_opt = tx.update(g, ref_opt, ref_params)
        ref_params = optax.apply_updates(ref_params, updates)
    final_params = ref_params

    # ---- amp run ----
    num_losses = 2 if use_multiple_loss_scalers else 1
    loss_ids = [0, 1] if use_multiple_loss_scalers else [0, 0]
    iters = 3 if case["inject_inf"] >= 0 else 2

    a = init_amp(opt_level, sgd_by_group(lrs, momentum), num_losses)
    state = seed_scales(a.init(params0), num_losses)

    unskipped = 0
    for i in range(iters):
        params_c = a.model_params(state)
        grads = []
        for j in (0, 1):
            gj = jax.grad(
                lambda p, j=j: a.scale_loss(
                    a.run(lambda q: loss_fn(j)(q), p),
                    state, loss_id=loss_ids[j]))(params_c)
            if i == case["inject_inf"] and case["which_backward"] == j:
                key = (f"m{case['which_model']}"
                       if case["which_model"] is not None else f"m{j}")
                gj = inject_inf_into(gj, key, case["inject_inf_loc"])
            grads.append(gj)

        if i != case["inject_inf"]:
            combined = None
            for j in (0, 1):
                uj, _ = a.unscale_gradients(state, grads[j],
                                            loss_id=loss_ids[j])
                combined = uj if combined is None else tree_add(combined, uj)
            tree_allclose(combined, reference_grads[unskipped],
                          rtol=1e-6, atol=0)
            unskipped += 1

        state, info = a.apply_gradients_multi(state, grads,
                                              loss_ids=loss_ids)
        assert bool(info["overflow"]) == (i == case["inject_inf"])

    tree_allclose(state.master_params, final_params, rtol=1e-6, atol=0)


# The four-topology matrix sums to ~85s of jit compiles on the 2-vCPU
# tier-1 box (ROADMAP wall-clock item): the smallest topology stays
# tier-1 as the fast representative — it exercises the full opt-level x
# scaler-sharing x inject-inf grid through the same helper the larger
# topologies drive — and the other three are slow-marked.

@pytest.mark.parametrize("use_multiple_loss_scalers", (True, False))
@pytest.mark.parametrize("opt_level", OPT_LEVELS)
def test_2models2losses1optimizer(opt_level, use_multiple_loss_scalers):
    for case in case_grid(opt_level):
        _run_one_optimizer_case(2, opt_level, use_multiple_loss_scalers, case)


@pytest.mark.slow
@pytest.mark.parametrize("use_multiple_loss_scalers", (True, False))
@pytest.mark.parametrize("opt_level", OPT_LEVELS)
def test_3models2losses1optimizer(opt_level, use_multiple_loss_scalers):
    # which_model: backward 0 spans models {0,2}; backward 1 spans {1,2}
    # (reference :227-233).
    for case in case_grid(opt_level,
                          which_models_by_backward={0: (0, 2), 1: (1, 2)}):
        _run_one_optimizer_case(3, opt_level, use_multiple_loss_scalers, case)


# ---------------------------------------------------------------------------
# topology 3: 2 models, 2 losses, 2 optimizers (reference :326-515)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("use_multiple_loss_scalers", (True, False))
@pytest.mark.parametrize("opt_level", OPT_LEVELS)
def test_2models2losses2optimizers(opt_level, use_multiple_loss_scalers):
    num_losses = 2 if use_multiple_loss_scalers else 1
    loss_ids = [0, 1] if use_multiple_loss_scalers else [0, 0]

    def run_reference(iters, skip, skip_pairs):
        """fp32 run replaying the expected skip pattern
        (what_got_skipped variants, reference :358-404)."""
        p0 = reference_dtype_params({"m0": make_model(1)}, opt_level)
        p1 = reference_dtype_params({"m1": make_model(2)}, opt_level)
        tx0 = sgd_by_group({"m0": 0.25}, momentum=0.125)
        tx1 = sgd_by_group({"m1": 0.5}, momentum=0.25)
        o0, o1 = tx0.init(p0), tx1.init(p1)
        grads_seen = []
        for i in range(iters):
            g0 = jax.grad(lambda p: model_loss(p["m0"]))(p0)
            g1 = jax.grad(lambda p: model_loss(p["m1"]))(p1)
            if i not in skip:
                grads_seen.append((g0, g1))
            if (i, 0) not in skip_pairs:
                u, o0 = tx0.update(g0, o0, p0)
                p0 = optax.apply_updates(p0, u)
            if (i, 1) not in skip_pairs:
                u, o1 = tx1.update(g1, o1, p1)
                p1 = optax.apply_updates(p1, u)
        return grads_seen, (p0, p1)

    for case in case_grid(opt_level):
        inject, wb = case["inject_inf"], case["which_backward"]
        iters = 3 if inject >= 0 else 2
        # overflow in backward j skips optimizer j only (scale_loss binds
        # one optimizer per context here, reference :446-449).
        skip_pairs = {(inject, wb)} if inject >= 0 else set()
        skip = {inject} if inject >= 0 else set()
        ref_grads, (ref_p0, ref_p1) = run_reference(iters, skip, skip_pairs)

        tx0 = sgd_by_group({"m0": 0.25}, momentum=0.125)
        tx1 = sgd_by_group({"m1": 0.5}, momentum=0.25)
        a0 = init_amp(opt_level, tx0, num_losses)
        a1 = init_amp(opt_level, tx1, num_losses)
        # Scalers are GLOBAL per loss_id in the reference (_amp_state
        # .loss_scalers), shared across optimizers: keep them in state0.
        s0 = seed_scales(a0.init({"m0": make_model(1)}), num_losses)
        s1 = a1.init({"m1": make_model(2)})

        unskipped = 0
        for i in range(iters):
            pc0, pc1 = a0.model_params(s0), a1.model_params(s1)
            g0 = jax.grad(lambda p: a0.scale_loss(
                a0.run(lambda q: model_loss(q["m0"]), p), s0,
                loss_id=loss_ids[0]))(pc0)
            g1 = jax.grad(lambda p: a1.scale_loss(
                a1.run(lambda q: model_loss(q["m1"]), p), s0,
                loss_id=loss_ids[1]))(pc1)
            if i == inject:
                if wb == 0:
                    g0 = inject_inf_into(g0, "m0", case["inject_inf_loc"])
                else:
                    g1 = inject_inf_into(g1, "m1", case["inject_inf_loc"])

            u0, f0 = a0.unscale_gradients(s0, g0, loss_id=loss_ids[0])
            u1, f1 = a0.unscale_gradients(s0, g1, loss_id=loss_ids[1])
            s0, ov0 = a0.update_scaler(s0, loss_ids[0], f0)
            s0, ov1 = a0.update_scaler(s0, loss_ids[1], f1)

            if i != inject:
                tree_allclose(u0, ref_grads[unskipped][0], rtol=1e-6, atol=0)
                tree_allclose(u1, ref_grads[unskipped][1], rtol=1e-6, atol=0)
                unskipped += 1

            s0 = a0.step_if(s0, u0, ov0)
            s1 = a1.step_if(s1, u1, ov1)

        tree_allclose(s0.master_params, ref_p0, rtol=1e-6, atol=0)
        tree_allclose(s1.master_params, ref_p1, rtol=1e-6, atol=0)


# ---------------------------------------------------------------------------
# topology 4: 3 models, 2 losses, 2 optimizers; loss1 spans both optimizers
# (reference :516-762)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("use_multiple_loss_scalers", (True, False))
@pytest.mark.parametrize("opt_level", OPT_LEVELS)
def test_3models2losses2optimizers(opt_level, use_multiple_loss_scalers):
    num_losses = 2 if use_multiple_loss_scalers else 1
    loss_ids = [0, 1] if use_multiple_loss_scalers else [0, 0]

    # optimizer0: model0 (lr .25) + model1 (lr 1.0), momentum .5;
    # optimizer1: model2 (lr .5), momentum .25 (reference :585-590).
    # loss0 = m0 + m1 (optimizer0 only); loss1 = m2 + m1 (both optimizers).
    def make_txs():
        return (sgd_by_group({"m0": 0.25, "m1": 1.0}, momentum=0.5),
                sgd_by_group({"m2": 0.5}, momentum=0.25))

    def loss0(p0):
        return model_loss(p0["m0"]) + model_loss(p0["m1"])

    def loss1(p0, p1):
        return model_loss(p1["m2"]) + model_loss(p0["m1"])

    def run_reference(iters, skip_pairs):
        p0 = reference_dtype_params(
            {"m0": make_model(1), "m1": make_model(2)}, opt_level)
        p1 = reference_dtype_params({"m2": make_model(3)}, opt_level)
        tx0, tx1 = make_txs()
        o0, o1 = tx0.init(p0), tx1.init(p1)
        grads_seen = []
        skipped_iters = {i for i, _ in skip_pairs}
        for i in range(iters):
            g0 = jax.grad(loss0)(p0)
            g1p0, g1p1 = jax.grad(loss1, argnums=(0, 1))(p0, p1)
            if i not in skipped_iters:
                grads_seen.append((tree_add(g0, g1p0), g1p1))
            if (i, 0) not in skip_pairs:
                u, o0 = tx0.update(tree_add(g0, g1p0), o0, p0)
                p0 = optax.apply_updates(p0, u)
            if (i, 1) not in skip_pairs:
                u, o1 = tx1.update(g1p1, o1, p1)
                p1 = optax.apply_updates(p1, u)
        return grads_seen, (p0, p1)

    for case in case_grid(opt_level,
                          which_models_by_backward={0: (0, 1), 1: (2, 1)}):
        inject, wb, wm = (case["inject_inf"], case["which_backward"],
                          case["which_model"])
        iters = 3 if inject >= 0 else 2
        # Overflow in backward 0 skips optimizer0; overflow in backward 1
        # skips BOTH (scale_loss(loss1, [optimizer0, optimizer1]),
        # reference :605-617 variant runs).
        if inject >= 0:
            skip_pairs = ({(inject, 0)} if wb == 0
                          else {(inject, 0), (inject, 1)})
        else:
            skip_pairs = set()
        ref_grads, (ref_p0, ref_p1) = run_reference(iters, skip_pairs)

        tx0, tx1 = make_txs()
        a0 = init_amp(opt_level, tx0, num_losses)
        a1 = init_amp(opt_level, tx1, num_losses)
        s0 = seed_scales(a0.init({"m0": make_model(1), "m1": make_model(2)}),
                         num_losses)
        s1 = a1.init({"m2": make_model(3)})

        unskipped = 0
        for i in range(iters):
            pc0, pc1 = a0.model_params(s0), a1.model_params(s1)
            g0 = jax.grad(lambda p: a0.scale_loss(
                a0.run(loss0, p), s0, loss_id=loss_ids[0]))(pc0)
            g1p0, g1p1 = jax.grad(
                lambda p, q: a0.scale_loss(
                    a0.run(lambda pp, qq: loss1(pp, qq), p, q), s0,
                    loss_id=loss_ids[1]),
                argnums=(0, 1))(pc0, pc1)
            if i == inject:
                if wb == 0:
                    g0 = inject_inf_into(g0, f"m{wm}",
                                         case["inject_inf_loc"])
                elif wm == 2:
                    g1p1 = inject_inf_into(g1p1, "m2",
                                           case["inject_inf_loc"])
                else:
                    g1p0 = inject_inf_into(g1p0, "m1",
                                           case["inject_inf_loc"])

            u0, f0 = a0.unscale_gradients(s0, g0, loss_id=loss_ids[0])
            u1p0, f1a = a0.unscale_gradients(s0, g1p0, loss_id=loss_ids[1])
            u1p1, f1b = a0.unscale_gradients(s0, g1p1, loss_id=loss_ids[1])
            f1 = jnp.logical_and(f1a, f1b)  # one overflow buf per backward
            s0, ov0 = a0.update_scaler(s0, loss_ids[0], f0)
            s0, ov1 = a0.update_scaler(s0, loss_ids[1], f1)

            if i != inject:
                tree_allclose(tree_add(u0, u1p0), ref_grads[unskipped][0],
                              rtol=1e-6, atol=0)
                tree_allclose(u1p1, ref_grads[unskipped][1],
                              rtol=1e-6, atol=0)
                unskipped += 1

            s0 = a0.step_if(s0, tree_add(u0, u1p0),
                            jnp.logical_or(ov0, ov1))
            s1 = a1.step_if(s1, u1p1, ov1)

        tree_allclose(s0.master_params, ref_p0, rtol=1e-6, atol=0)
        tree_allclose(s1.master_params, ref_p1, rtol=1e-6, atol=0)
