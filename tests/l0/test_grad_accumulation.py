"""Gradient accumulation over micro-batches (the reference's
``delay_unscale`` / stashed-grad iteration, ``_process_optimizer.py:125-129``
+ shared overflow buffer across unscales).

Contract: N micro-batches accumulated through ``scaler.unscale`` /
``unscale_with_stashed`` then one ``apply_gradients(grads, stashed_grads)``
must (a) equal a single step whose loss is the sum of the per-micro
losses, and (b) skip the step when ANY micro-batch overflowed — the
stashed-path finite check covers the combined grads, so stale infs are
caught without caller cooperation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import amp
from apex_tpu.models.mlp import MLP, cross_entropy_loss

N_MICRO = 4
BATCH = 32


def _setup(seed=0):
    model = MLP(features=(16, 4))
    params = model.init(jax.random.PRNGKey(1), jnp.zeros((1, 8)))["params"]
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(N_MICRO * BATCH, 8).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 4, N_MICRO * BATCH))
    a = amp.initialize(optimizer=optax.sgd(0.1), opt_level="O2",
                       verbosity=0)
    return model, params, a, x, y


def _micro_grads(model, a, state, x, y, i):
    """Scaled grads of micro-batch i at compute precision."""
    params_c = a.model_params(state)
    xb = x[i * BATCH:(i + 1) * BATCH]
    yb = y[i * BATCH:(i + 1) * BATCH]

    def scaled_loss(p):
        loss = cross_entropy_loss(model.apply({"params": p}, xb), yb)
        return a.scale_loss(loss, state)

    return jax.grad(scaled_loss)(params_c)


def test_accumulated_equals_big_batch_step():
    model, params, a, x, y = _setup()
    state = a.init(params)
    sstate = state.scaler_states[0]

    # --- accumulation path ---
    accum = None
    for i in range(N_MICRO - 1):
        g = _micro_grads(model, a, state, x, y, i)
        if accum is None:
            accum, _ = a.scaler.unscale(g, sstate)
        else:
            accum, _ = a.scaler.unscale_with_stashed(g, accum, sstate)
    g_last = _micro_grads(model, a, state, x, y, N_MICRO - 1)
    acc_state, info = a.apply_gradients(state, g_last, stashed_grads=accum)
    assert not bool(info["overflow"])

    # --- single step on the summed loss ---
    params_c = a.model_params(state)

    def scaled_sum_loss(p):
        total = 0.0
        for i in range(N_MICRO):
            xb = x[i * BATCH:(i + 1) * BATCH]
            yb = y[i * BATCH:(i + 1) * BATCH]
            total = total + cross_entropy_loss(
                model.apply({"params": p}, xb), yb)
        return a.scale_loss(total, state)

    g_big = jax.grad(scaled_sum_loss)(params_c)
    big_state, info2 = a.apply_gradients(state, g_big)
    assert not bool(info2["overflow"])

    # bf16 compute: per-micro grads round differently from the one big
    # backward; observed diffs ~2e-4 absolute
    for acc, big in zip(jax.tree.leaves(acc_state.master_params),
                        jax.tree.leaves(big_state.master_params)):
        np.testing.assert_allclose(np.asarray(acc), np.asarray(big),
                                   rtol=1e-2, atol=5e-4)


def test_inf_in_early_micro_batch_skips_step():
    model, params, a, x, y = _setup(1)
    state = a.init(params)
    sstate = state.scaler_states[0]

    g0 = _micro_grads(model, a, state, x, y, 0)
    # plant an inf in micro-batch 0 (the reference's shared overflow buffer
    # would remember it across the iteration's unscale calls)
    g0 = jax.tree.map(lambda t: t.at[(0,) * t.ndim].set(jnp.inf), g0)
    accum, f0 = a.scaler.unscale(g0, sstate)
    assert not bool(f0)

    g1 = _micro_grads(model, a, state, x, y, 1)
    new_state, info = a.apply_gradients(state, g1, stashed_grads=accum)
    assert bool(info["overflow"])
    # step skipped: params unchanged, scale halved
    for old, new in zip(jax.tree.leaves(state.master_params),
                        jax.tree.leaves(new_state.master_params)):
        np.testing.assert_array_equal(np.asarray(old), np.asarray(new))
    assert float(new_state.scaler_states[0].loss_scale) == \
        float(sstate.loss_scale) / 2


def test_scaler_level_stashed_check_is_arg0_only():
    """The raw scaler primitive keeps the reference's arg-0 policy
    (``scaler.py:167-172``): a stale inf in the stash does NOT trip the
    per-call flag — the combined-tree check happens in apply_gradients."""
    model, params, a, x, y = _setup(2)
    state = a.init(params)
    sstate = state.scaler_states[0]
    g0 = _micro_grads(model, a, state, x, y, 0)
    g0 = jax.tree.map(lambda t: t.at[(0,) * t.ndim].set(jnp.inf), g0)
    accum, _ = a.scaler.unscale(g0, sstate)
    g1 = _micro_grads(model, a, state, x, y, 1)
    _, f = a.scaler.unscale_with_stashed(g1, accum, sstate)
    assert bool(f)   # per-call flag sees only the new grads


def test_make_train_step_accum_matches_big_batch():
    """make_train_step(accum_steps=N): the compiled accumulation loop must
    match the single large-batch mean-loss step (same params update, same
    reported loss) — the Reducer's every-N cadence as one jit."""
    model, params, a, x, y = _setup()
    def loss_fn(p, xb, yb):
        return cross_entropy_loss(model.apply({"params": p}, xb), yb)

    big = jax.jit(amp.make_train_step(a, loss_fn))
    accum = jax.jit(amp.make_train_step(a, loss_fn,
                                        accum_steps=N_MICRO))
    s_big, m_big = big(a.init(params), x, y)
    s_acc, m_acc = accum(a.init(params), x, y)
    np.testing.assert_allclose(float(m_acc["loss"]), float(m_big["loss"]),
                               rtol=1e-5)
    for la, lb in zip(jax.tree.leaves(s_acc.master_params),
                      jax.tree.leaves(s_big.master_params)):
        # mean-of-micro-means vs full-batch mean reassociates the
        # reduction; bf16 compute wobbles at ~1e-5 absolute
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=1e-3, atol=5e-5)


def test_make_train_step_accum_overflow_in_any_micro_skips():
    """An inf produced by any micro-batch must skip the whole accumulated
    step (the shared overflow buffer across unscales)."""
    model, params, a, x, y = _setup()
    x_bad = x.at[2 * BATCH + 1, 0].set(jnp.inf)  # poisons micro-batch 2

    def loss_fn(p, xb, yb):
        return cross_entropy_loss(model.apply({"params": p}, xb), yb)

    accum = jax.jit(amp.make_train_step(a, loss_fn, accum_steps=N_MICRO))
    state0 = a.init(params)
    state1, m = accum(state0, x_bad, y)
    assert bool(m["overflow"])
    for la, lb in zip(jax.tree.leaves(state1.master_params),
                      jax.tree.leaves(state0.master_params)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_make_train_step_accum_rejects_indivisible_batch():
    model, params, a, x, y = _setup()
    def loss_fn(p, xb, yb):
        return cross_entropy_loss(model.apply({"params": p}, xb), yb)
    accum = amp.make_train_step(a, loss_fn, accum_steps=3)
    with pytest.raises(ValueError, match="divisible"):
        jax.eval_shape(accum, a.init(params), x, y)
