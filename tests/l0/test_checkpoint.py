"""Checkpoint/resume tests — the capability gap §5.4 flags in the
reference (scaler state lost on restart) must not exist here."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import amp, checkpoint
from apex_tpu.models.mlp import MLP, cross_entropy_loss
from apex_tpu.optimizers import FusedAdam


def _setup():
    model = MLP(features=(32,))
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16)))["params"]
    a = amp.initialize(optimizer=FusedAdam(lr=1e-2), opt_level="O2",
                       verbosity=0)
    step = jax.jit(amp.make_train_step(
        a, lambda p, x, y: cross_entropy_loss(
            model.apply({"params": p}, x), y)))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 16))
    y = jax.random.randint(jax.random.PRNGKey(2), (32,), 0, 10)
    return a, step, x, y, params


def test_state_dict_roundtrip():
    a, step, x, y, params = _setup()
    state = a.init(params)
    for _ in range(3):
        state, _ = step(state, x, y)

    d = checkpoint.state_dict(state, extras={"epoch": np.int32(7)})
    template = jax.tree.map(jnp.zeros_like, state)
    restored, extras = checkpoint.load_state_dict(template, d)

    for got, want in zip(jax.tree.leaves(restored), jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert int(extras["epoch"]) == 7


def test_resume_continues_identically():
    """Save at step 3, keep training to 6; restore at 3 and retrain — the
    two step-6 states must match exactly (scaler included)."""
    a, step, x, y, params = _setup()
    state = a.init(params)
    for _ in range(3):
        state, _ = step(state, x, y)
    d = checkpoint.state_dict(state)

    cont = state
    for _ in range(3):
        cont, _ = step(cont, x, y)

    resumed, _ = checkpoint.load_state_dict(
        jax.tree.map(jnp.zeros_like, state), d)
    for _ in range(3):
        resumed, _ = step(resumed, x, y)

    for got, want in zip(jax.tree.leaves(resumed), jax.tree.leaves(cont)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_scaler_state_persists():
    """The reference's gap: loss-scale value and unskipped counter must
    survive a round-trip."""
    a, step, x, y, params = _setup()
    state = a.init(params)
    # force an overflow so the scale moves off its init value
    state, m = step(state, x.at[0, 0].set(jnp.inf), y)
    assert bool(m["overflow"])
    d = checkpoint.state_dict(state)
    restored, _ = checkpoint.load_state_dict(
        jax.tree.map(jnp.zeros_like, state), d)
    assert float(restored.scaler_states[0].loss_scale) == \
        float(state.scaler_states[0].loss_scale) == 32768.0
    assert int(restored.scaler_states[0].unskipped) == \
        int(state.scaler_states[0].unskipped)


def test_load_state_dict_missing_leaf_names_first_path():
    """ISSUE 3 satellite: a checkpoint missing a leaf must raise naming
    the first diverging tree path, not a cryptic tree/zip error."""
    a, step, x, y, params = _setup()
    state = a.init(params)
    d = checkpoint.state_dict(state)
    del d["master_params"]["AmpDense_0"]["bias"]
    template = jax.tree.map(jnp.zeros_like, state)
    with pytest.raises(ValueError) as ei:
        checkpoint.load_state_dict(template, d)
    msg = str(ei.value)
    assert "structural mismatch" in msg
    assert "AmpDense_0" in msg and "bias" in msg
    assert "missing from checkpoint" in msg


def test_load_state_dict_extra_leaf_names_first_path():
    a, step, x, y, params = _setup()
    state = a.init(params)
    d = checkpoint.state_dict(state)
    d["opt_state"] = dict(d["opt_state"]) if isinstance(d["opt_state"], dict) \
        else d["opt_state"]
    d["master_params"]["bogus_layer"] = {"kernel": np.zeros((2, 2))}
    template = jax.tree.map(jnp.zeros_like, state)
    with pytest.raises(ValueError) as ei:
        checkpoint.load_state_dict(template, d)
    msg = str(ei.value)
    assert "bogus_layer" in msg and "not in template" in msg


def test_load_state_dict_scaler_count_mismatch_is_structural():
    a, step, x, y, params = _setup()
    state = a.init(params)
    d = checkpoint.state_dict(state)
    d["scaler_states"] = d["scaler_states"] + d["scaler_states"]
    template = jax.tree.map(jnp.zeros_like, state)
    with pytest.raises(ValueError, match="structural mismatch"):
        checkpoint.load_state_dict(template, d)


def test_manager_restore_structural_mismatch_raises_not_falls_back(tmp_path):
    """A VALID snapshot + wrong template is a user error: restore must
    raise the structural message, not silently fall back to an older
    snapshot as if the newest were corrupt."""
    a, step, x, y, params = _setup()
    state = a.init(params)
    mgr = checkpoint.CheckpointManager(str(tmp_path))
    mgr.save(0, state, extras={"epoch": np.int32(3)})
    mgr.wait()
    template = jax.tree.map(jnp.zeros_like, state)
    with pytest.raises(ValueError) as ei:
        mgr.restore(template)          # saved WITH extras, template without
    assert "epoch" in str(ei.value)


def test_checkpoint_manager_retention(tmp_path):
    a, step, x, y, params = _setup()
    state = a.init(params)
    mgr = checkpoint.CheckpointManager(str(tmp_path), max_to_keep=2)
    for i in range(4):
        state, _ = step(state, x, y)
        mgr.save(i, state, extras={"epoch": np.int32(i)})
    assert mgr.latest_step() == 3

    template = jax.tree.map(jnp.zeros_like, state)
    restored, extras = mgr.restore(template,
                                   extras={"epoch": np.int32(0)})
    assert int(extras["epoch"]) == 3
    for got, want in zip(jax.tree.leaves(restored), jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
