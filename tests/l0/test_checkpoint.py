"""Checkpoint/resume tests — the capability gap §5.4 flags in the
reference (scaler state lost on restart) must not exist here."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import amp, checkpoint
from apex_tpu.models.mlp import MLP, cross_entropy_loss
from apex_tpu.optimizers import FusedAdam


def _setup():
    model = MLP(features=(32,))
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16)))["params"]
    a = amp.initialize(optimizer=FusedAdam(lr=1e-2), opt_level="O2",
                       verbosity=0)
    step = jax.jit(amp.make_train_step(
        a, lambda p, x, y: cross_entropy_loss(
            model.apply({"params": p}, x), y)))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 16))
    y = jax.random.randint(jax.random.PRNGKey(2), (32,), 0, 10)
    return a, step, x, y, params


def test_state_dict_roundtrip():
    a, step, x, y, params = _setup()
    state = a.init(params)
    for _ in range(3):
        state, _ = step(state, x, y)

    d = checkpoint.state_dict(state, extras={"epoch": np.int32(7)})
    template = jax.tree.map(jnp.zeros_like, state)
    restored, extras = checkpoint.load_state_dict(template, d)

    for got, want in zip(jax.tree.leaves(restored), jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert int(extras["epoch"]) == 7


def test_resume_continues_identically():
    """Save at step 3, keep training to 6; restore at 3 and retrain — the
    two step-6 states must match exactly (scaler included)."""
    a, step, x, y, params = _setup()
    state = a.init(params)
    for _ in range(3):
        state, _ = step(state, x, y)
    d = checkpoint.state_dict(state)

    cont = state
    for _ in range(3):
        cont, _ = step(cont, x, y)

    resumed, _ = checkpoint.load_state_dict(
        jax.tree.map(jnp.zeros_like, state), d)
    for _ in range(3):
        resumed, _ = step(resumed, x, y)

    for got, want in zip(jax.tree.leaves(resumed), jax.tree.leaves(cont)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_scaler_state_persists():
    """The reference's gap: loss-scale value and unskipped counter must
    survive a round-trip."""
    a, step, x, y, params = _setup()
    state = a.init(params)
    # force an overflow so the scale moves off its init value
    state, m = step(state, x.at[0, 0].set(jnp.inf), y)
    assert bool(m["overflow"])
    d = checkpoint.state_dict(state)
    restored, _ = checkpoint.load_state_dict(
        jax.tree.map(jnp.zeros_like, state), d)
    assert float(restored.scaler_states[0].loss_scale) == \
        float(state.scaler_states[0].loss_scale) == 32768.0
    assert int(restored.scaler_states[0].unskipped) == \
        int(state.scaler_states[0].unskipped)


def test_checkpoint_manager_retention(tmp_path):
    a, step, x, y, params = _setup()
    state = a.init(params)
    mgr = checkpoint.CheckpointManager(str(tmp_path), max_to_keep=2)
    for i in range(4):
        state, _ = step(state, x, y)
        mgr.save(i, state, extras={"epoch": np.int32(i)})
    assert mgr.latest_step() == 3

    template = jax.tree.map(jnp.zeros_like, state)
    restored, extras = mgr.restore(template,
                                   extras={"epoch": np.int32(0)})
    assert int(extras["epoch"]) == 3
    for got, want in zip(jax.tree.leaves(restored), jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
