"""Fleet observability (ISSUE 13): request tracing, the incident
flight recorder, and the one registry-merge layer.

Contracts under test: (a) :class:`apex_tpu.obs.RequestTracer` — the
closed event vocabulary, id minting, retired-trace bounding, span
derivation, chrome-trace export; (b) trace integrity under chaos —
kill the busiest decode replica mid-stream and the rerouted request's
trace reconstructs prefill -> ship -> decode on replica A, the reroute
naming A, re-prefill -> decode on replica B, while outputs stay
BITWISE vs solo ``generate()`` and the graph-lint syncs pass stays
clean on the instrumented compiled step; (c) the stdlib TRACE schema's
contradiction rejection (non-nesting spans, token accounting vs the
engines' own counters, reroutes naming live replicas, self-
contradicting gates) and the committed ``TRACE_r01.json``;
(d) :class:`apex_tpu.obs.FlightRecorder` — ring bound, ordering, the
INCIDENT schema's grown ``flight`` field; (e) :mod:`apex_tpu.obs.
fleet` — counter sums, bucket-union quantiles pinned against the old
``bench._merged_decode_quantile`` math on a recorded fixture, gauge
tables.
"""

import copy
import json
import pathlib
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.analysis import trace as trace_schema
from apex_tpu.models import GPTModel, gpt_tiny
from apex_tpu.models.generate import generate
from apex_tpu.obs import FlightRecorder, RequestTracer, fleet
from apex_tpu.obs import reqtrace
from apex_tpu.obs.metrics import Histogram, Registry
from apex_tpu.resilience.incidents import make_incident, validate_incident
from apex_tpu.serve import (
    DisaggRouter,
    Request,
    RouterConfig,
    ServeConfig,
    ServeEngine,
)

REPO = pathlib.Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO / "tools"))


# ---------------------------------------------------------------------------
# RequestTracer mechanics
# ---------------------------------------------------------------------------

def test_tracer_vocabulary_is_closed_and_pinned():
    tr = RequestTracer()
    with pytest.raises(ValueError, match="vocabulary"):
        tr.record("decode", "u", "engine")       # typo'd kind is loud
    # the stdlib schema must carry the SAME vocabulary (it cannot
    # import the jax-adjacent obs package — gate_hygiene loads it by
    # file path)
    assert trace_schema.EVENT_KINDS == reqtrace.EVENT_KINDS
    assert trace_schema.TOKEN_KINDS == reqtrace.TOKEN_KINDS


def test_tracer_mint_lifecycle_and_token_sum():
    tr = RequestTracer()
    tid = tr.mint("a")
    assert tid == tr.mint("a")          # re-mint = same request
    tr.record("enqueue", "a", "router")
    tr.record("admit", "a", "prefill", slot=0, first_token=3,
              prompt_len=4, tokens=1)
    tr.record("decode_step", "a", "replica0", step=1, token=5,
              batch=2, tokens=1)
    tr.record("retire", "a", "replica0", tokens_out=2)
    assert tr.tokens_of("a") == 2
    doc = tr.to_doc_requests()["a"]
    assert [e["kind"] for e in doc["events"]] == [
        "enqueue", "admit", "decode_step", "retire"]
    assert doc["tokens"] == 2
    # spans: one root + one residency segment per contiguous where-run
    spans = doc["spans"]
    assert spans[0]["parent"] == -1
    assert [s["name"] for s in spans[1:]] == ["router", "prefill",
                                              "replica0"]
    assert trace_schema._validate_spans("a", spans) == []


def test_tracer_bounds_retired_traces():
    tr = RequestTracer(max_retired=2)
    for i in range(4):
        tr.record("enqueue", f"u{i}", "router")
        tr.record("retire", f"u{i}", "engine", tokens_out=0)
    assert tr.dropped == 2
    assert tr.events("u0") == [] and tr.events("u1") == []
    assert tr.events("u3") != []


def test_tracer_hard_cap_evicts_never_retired_traces():
    """Regression (review round 3): a request that never retires
    (abandoned client) must not hold its event list forever — total
    traces are capped at 2 * max_retired, oldest-minted evicted."""
    tr = RequestTracer(max_retired=2)
    for i in range(7):
        tr.record("enqueue", f"u{i}", "router")   # nobody retires
    assert len(tr.uids()) == 4
    assert tr.dropped == 3
    assert tr.events("u0") == [] and tr.events("u6") != []


def test_tracer_chrome_trace_export_shape():
    tr = RequestTracer()
    tr.record("enqueue", "a", "router")
    tr.record("admit", "a", "prefill", tokens=1)
    tr.record("decode_step", "a", "replica0", step=1, token=2,
              batch=1, tokens=1)
    tr.record("reroute", "a", "router", from_replica=0)
    tr.record("retire", "a", "replica1", tokens_out=2)
    ct = tr.to_chrome_trace()
    evs = ct["traceEvents"]
    json.dumps(ct)                       # serializable end to end
    procs = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert procs == {"/fleet:router", "/fleet:prefill",
                     "/fleet:replica0", "/fleet:replica1"}
    assert any(e["ph"] == "X" for e in evs)          # residency spans
    instants = [e for e in evs if e["ph"] == "i"]
    assert {"reroute", "retire"} <= {e["name"] for e in instants}
    assert instants[0]["args"].get("from_replica", 0) in (0,)


# ---------------------------------------------------------------------------
# the chaos trace-integrity drill (the ISSUE-13 acceptance test)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def traced_kill_drill():
    """ONE traced fleet + kill drill shared by the integrity tests
    (three engines' worth of compiles): 4 requests through 1 prefill
    worker + 2 two-slot decode replicas, the busiest replica killed
    after 3 fleet steps, the stream drained, and a TRACE document
    built exactly the way ``tools/trace_report.py`` builds the
    committed artifact."""
    cfg = gpt_tiny()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    params = amp.initialize(
        opt_level="O2", verbosity=0).model_params_from(params)
    scfg = ServeConfig(num_slots=2, block_size=4, num_blocks=17,
                       max_blocks_per_slot=8, prefill_chunk=4)
    tracer = RequestTracer()
    flight = FlightRecorder()
    router = DisaggRouter(
        params, cfg, scfg,
        RouterConfig(n_decode_replicas=2, transfer="ship"),
        registry=Registry(), tracer=tracer, flight=flight)
    labels = ["prefill", "replica0", "replica1"]
    regs = [router.prefill.eng.metrics] + [r.eng.metrics
                                           for r in router.replicas]
    tok0 = [r.counter("serve_tokens_total").value for r in regs]
    rng = np.random.RandomState(3)
    reqs = [(rng.randint(0, cfg.vocab_size, (12 // (i + 1) + 2,)), 8)
            for i in range(4)]
    for i, (p, n) in enumerate(reqs):
        router.submit(Request(uid=f"c{i}", prompt=p, max_new_tokens=n))
    for _ in range(3):
        router.step()
    victim = max(router.replicas,
                 key=lambda r: r.eng.sched.n_active()).index
    rerouted = router.kill_replica(victim)
    out = router.run()
    per = {lbl: round(reg.counter("serve_tokens_total").value - t0)
           for lbl, reg, t0 in zip(labels, regs, tok0)}
    doc_reqs = tracer.to_doc_requests()
    delta = round(sum(per.values()))
    tokens = sum(r["tokens"] for r in doc_reqs.values())
    bitwise = all(
        np.array_equal(
            out[f"c{i}"],
            np.asarray(generate(params, cfg, jnp.asarray(p[None]),
                                n))[0, len(p):])
        for i, (p, n) in enumerate(reqs))
    doc = {
        "round": 1, "platform": "cpu",
        "config": {"model": "gpt_tiny"},
        "requests": doc_reqs,
        "engine": {"serve_tokens_total": per, "delta_total": delta},
        "chaos": {"killed": [int(victim)], "rerouted": rerouted},
        "gate": {"bitwise_ok": bool(bitwise),
                 "tokens_ok": delta == tokens,
                 "ok": bool(bitwise and delta == tokens)},
    }
    return {"doc": doc, "victim": victim, "rerouted": rerouted,
            "flight": flight, "router": router}


def test_killed_request_trace_reconstructs_both_replicas(
        traced_kill_drill):
    """THE integrity gate: a rerouted request's lifecycle shows
    prefill -> ship -> decode on replica A, the reroute naming A,
    re-prefill -> ship -> decode on replica B != A, retire — while
    every output stayed bitwise vs solo (gate.bitwise_ok)."""
    d = traced_kill_drill
    assert d["doc"]["gate"]["bitwise_ok"] is True
    assert d["rerouted"], "the drill must actually reroute something"
    victim = d["victim"]
    checked = 0
    for uid in d["rerouted"]:
        evs = d["doc"]["requests"][uid]["events"]
        kinds = [(e["kind"], e["where"]) for e in evs]
        ri = [i for i, e in enumerate(evs)
              if e["kind"] == "reroute"][0]
        assert evs[ri]["from_replica"] == victim
        before, after = evs[:ri], evs[ri + 1:]
        # decode work BEFORE the reroute ran on the killed replica
        # (requests rerouted out of the engine-local queue never
        # decoded there — skip those for the residency assertion)
        decoded_before = [e for e in before
                          if e["kind"] == "decode_step"]
        if not decoded_before:
            continue
        checked += 1
        assert all(e["where"] == f"replica{victim}"
                   for e in decoded_before)
        assert any(e[0] == "admit" and e[1] == "prefill"
                   for e in kinds[:ri])
        assert any(e["kind"] == "kv_install"
                   and e["where"] == f"replica{victim}"
                   for e in before)
        # ... and AFTER it: a fresh prefill, then decode on a live
        # replica that is NOT the killed one
        assert any(e["kind"] == "admit" and e["where"] == "prefill"
                   for e in after)
        decoded_after = {e["where"] for e in after
                         if e["kind"] == "decode_step"}
        assert decoded_after and f"replica{victim}" not in decoded_after
        assert evs[-1]["kind"] == "retire"
    assert checked >= 1, "no rerouted request had decoded pre-kill"


def test_drill_document_validates_and_accounts_tokens(
        traced_kill_drill):
    """The drill's document is schema-valid, its token accounting
    closes against the engines' own counters, and the flight ring
    recorded the kill + every reroute."""
    d = traced_kill_drill
    assert trace_schema.validate_trace(d["doc"]) == []
    assert d["doc"]["gate"]["tokens_ok"] is True
    dump = d["flight"].dump()
    kinds = [e["kind"] for e in dump["events"]]
    assert kinds.count("reroute") == len(d["rerouted"])
    assert "replica_kill" in kinds
    assert dump["events"][kinds.index("replica_kill")]["replica"] \
        == d["victim"]
    # the fleet merge layer agrees with the per-engine table
    router = d["router"]
    merged = fleet.merge_registries(
        [router.prefill.eng.metrics]
        + [r.eng.metrics for r in router.replicas])
    assert merged.counter("serve_reroute_total").value == 0  # router's
    got = merged.counter("serve_tokens_total").value
    assert round(got) >= d["doc"]["engine"]["delta_total"]


def test_kill_incident_record_is_schema_valid(traced_kill_drill,
                                               tmp_path):
    """RouterConfig.incident_path: the replica death leaves a
    schema-valid incident carrying the resolved router metrics AND
    the flight ring's tail (the grown INCIDENT ``flight`` field)."""
    import dataclasses
    d = traced_kill_drill
    router = d["router"]
    path = tmp_path / "INCIDENT_kill.json"
    router.rcfg = dataclasses.replace(router.rcfg,
                                      incident_path=str(path))
    router._write_kill_incident(int(d["victim"]), list(d["rerouted"]))
    rec = json.loads(path.read_text())
    assert validate_incident(rec) == []
    assert rec["status"] == "replica-killed"
    assert rec["replica"] == d["victim"]
    assert set(rec["rerouted"]) == set(d["rerouted"])
    kinds = [e["kind"] for e in rec["flight"]["events"]]
    assert "replica_kill" in kinds and "reroute" in kinds


def test_syncs_pass_clean_on_instrumented_decode_step():
    """Tracing is host-side bookkeeping at step boundaries: the
    compiled decode step is UNCHANGED, which the graph-lint syncs
    pass proves — zero host callbacks, zero static-scalar retrace
    hazards, zero errors on the instrumented serve lane (the same bar
    OBS_r02.json commits)."""
    import graph_lint
    rep = graph_lint.lint_serve("serve_step", passes=("syncs",))
    syncs = rep.by_pass("syncs")
    assert sum(1 for f in syncs if f.op == "host-callback") == 0
    assert sum(1 for f in syncs if f.op == "static-scalar") == 0
    assert len(rep.errors) == 0


# ---------------------------------------------------------------------------
# TRACE schema contradiction rejection + the committed artifact
# ---------------------------------------------------------------------------

def _minimal_doc():
    return {
        "round": 1, "platform": "cpu", "config": {},
        "requests": {
            "a": {
                "trace_id": "t00001",
                "events": [
                    {"seq": 1, "ts": 0.0, "kind": "enqueue",
                     "where": "router"},
                    {"seq": 2, "ts": 0.1, "kind": "admit",
                     "where": "prefill", "tokens": 1},
                    {"seq": 3, "ts": 0.2, "kind": "decode_step",
                     "where": "replica0", "tokens": 1},
                    {"seq": 4, "ts": 0.3, "kind": "retire",
                     "where": "replica0", "tokens_out": 2},
                ],
                "spans": [
                    {"name": "request", "where": "*", "t0": 0.0,
                     "t1": 0.3, "parent": -1},
                    {"name": "router", "where": "router", "t0": 0.0,
                     "t1": 0.0, "parent": 0},
                    {"name": "prefill", "where": "prefill", "t0": 0.1,
                     "t1": 0.1, "parent": 0},
                    {"name": "replica0", "where": "replica0",
                     "t0": 0.2, "t1": 0.3, "parent": 0},
                ],
                "tokens": 2,
            },
        },
        "engine": {"serve_tokens_total": {"prefill": 1, "replica0": 1},
                   "delta_total": 2},
        "chaos": {"killed": [], "rerouted": []},
        "gate": {"bitwise_ok": True, "tokens_ok": True, "ok": True},
    }


def test_trace_schema_accepts_minimal_valid():
    assert trace_schema.validate_trace(_minimal_doc()) == []


def test_trace_schema_rejects_nonnesting_spans():
    doc = _minimal_doc()
    doc["requests"]["a"]["spans"][3]["t1"] = 9.0   # escapes the root
    assert any("nest" in p for p in trace_schema.validate_trace(doc))


def test_trace_schema_rejects_token_mismatch():
    doc = _minimal_doc()
    doc["engine"]["delta_total"] = 5
    doc["engine"]["serve_tokens_total"]["replica0"] = 4
    doc["gate"]["tokens_ok"] = True     # lying gate: also caught
    probs = trace_schema.validate_trace(doc)
    assert any("serve_tokens_total delta" in p for p in probs)
    assert any("tokens_ok" in p for p in probs)
    # per-request recorded total disagreeing with its own events
    doc2 = _minimal_doc()
    doc2["requests"]["a"]["tokens"] = 7
    assert any("token-carrying events" in p
               for p in trace_schema.validate_trace(doc2))


def test_trace_schema_rejects_reroute_without_kill():
    doc = _minimal_doc()
    doc["requests"]["a"]["events"].insert(
        3, {"seq": 4, "ts": 0.25, "kind": "reroute", "where": "router",
            "from_replica": 1})
    doc["requests"]["a"]["events"][4]["seq"] = 5
    doc["chaos"] = {"killed": [], "rerouted": ["a"]}
    probs = trace_schema.validate_trace(doc)
    assert any("never lost" in p for p in probs)
    # and a chaos block whose rerouted list disagrees with the events
    doc["chaos"] = {"killed": [1], "rerouted": []}
    probs = trace_schema.validate_trace(doc)
    assert any("uids with reroute events" in p for p in probs)


def test_trace_schema_rejects_contradictory_gate():
    doc = _minimal_doc()
    doc["gate"]["ok"] = True
    doc["gate"]["bitwise_ok"] = False
    assert any("gate.ok" in p for p in trace_schema.validate_trace(doc))


def test_trace_schema_rejects_broken_lifecycle():
    doc = _minimal_doc()
    doc["requests"]["a"]["events"][0]["kind"] = "admit"
    assert any("begin with 'enqueue'" in p
               for p in trace_schema.validate_trace(doc))
    doc2 = _minimal_doc()
    doc2["requests"]["a"]["events"][1]["ts"] = -1.0   # time reversal
    assert any("precedes" in p
               for p in trace_schema.validate_trace(doc2))


def test_committed_trace_artifact_validates_and_tells_the_story():
    """The committed TRACE_r01.json (the c16 disagg chaos run): schema
    valid, gate ok, the killed request's lifecycle reconstructed
    across TWO replicas, decode-token totals agreeing with the
    engines' own counters."""
    path = REPO / "TRACE_r01.json"
    assert path.exists(), "TRACE_r01.json must be committed"
    assert trace_schema.validate_trace_file(str(path)) == []
    doc = json.loads(path.read_text())
    assert doc["gate"]["ok"] is True
    assert doc["gate"]["bitwise_ok"] is True
    killed = set(doc["chaos"]["killed"])
    assert killed and doc["chaos"]["rerouted"]
    crossed = 0
    for uid in doc["chaos"]["rerouted"]:
        wheres = {e["where"]
                  for e in doc["requests"][uid]["events"]
                  if e["kind"] in ("decode_step", "kv_install")}
        replicas = {w for w in wheres if w.startswith("replica")}
        if len(replicas) >= 2:
            crossed += 1
            assert any(int(w[len("replica"):]) in killed
                       for w in replicas)
    assert crossed >= 1, \
        "no rerouted request's trace spans two replicas"
    total = sum(r["tokens"] for r in doc["requests"].values())
    assert total == doc["engine"]["delta_total"]


# ---------------------------------------------------------------------------
# flight recorder + the INCIDENT flight field
# ---------------------------------------------------------------------------

def test_flight_ring_bounds_orders_and_counts_drops():
    fr = FlightRecorder(capacity=3)
    for i in range(5):
        fr.note("step", step=i)
    dump = fr.dump()
    assert dump["capacity"] == 3 and dump["dropped"] == 2
    assert [e["step"] for e in dump["events"]] == [2, 3, 4]
    ts = [e["ts"] for e in dump["events"]]
    assert ts == sorted(ts)
    with pytest.raises(ValueError, match="kind"):
        fr.note("")


def test_flight_note_metrics_is_resolved_state_only():
    reg = Registry()
    reg.counter("c_total").inc(3)
    reg.gauge("g").set(1.5)
    reg.histogram("h").observe(0.25)
    fr = FlightRecorder()
    fr.note_metrics(reg)
    ev = fr.dump()["events"][0]
    assert ev["kind"] == "metrics"
    assert ev["values"]["c_total"] == 3.0
    assert ev["values"]["g"] == 1.5
    assert ev["values"]["h"] == {"count": 1, "sum": 0.25}


def test_incident_schema_validates_flight_field():
    rec = make_incident("recovered", "s", ["e"],
                        flight=FlightRecorder().dump())
    assert validate_incident(rec) == []
    # the r02-era shape (no flight) stays valid
    assert validate_incident({"status": "x", "utc": "t",
                              "evidence": ["e"]}) == []
    bad = copy.deepcopy(rec)
    bad["flight"]["events"] = [{"ts": 1.0, "kind": "a"},
                               {"ts": 0.5, "kind": "b"}]
    assert any("ordered" in p for p in validate_incident(bad))
    bad2 = copy.deepcopy(rec)
    bad2["flight"] = {"capacity": 1, "dropped": 0,
                      "events": [{"ts": 0.0, "kind": "a"},
                                 {"ts": 0.1, "kind": "b"}]}
    assert any("capacity" in p for p in validate_incident(bad2))
    bad3 = copy.deepcopy(rec)
    bad3["flight"]["events"] = [{"kind": "a"}]
    assert any("'ts'" in p for p in validate_incident(bad3))
    bad4 = copy.deepcopy(rec)
    bad4["flight"] = "tail"
    assert any("object" in p for p in validate_incident(bad4))


def test_run_resilient_result_carries_flight_history():
    """The loop's ring records steps/checkpoints and rides both the
    RunResult and every incident it writes (the chaos smoke pins the
    fault/rewind content; this pins the plumbing)."""
    import chaos_run
    from apex_tpu.resilience import ResilienceConfig, run_resilient
    _amp, step_fn, state, batch_fn = chaos_run.build_workload(0)
    res = run_resilient(step_fn, state, batch_fn, 4,
                        config=ResilienceConfig(checkpoint_every=2),
                        registry=Registry())
    kinds = [e["kind"] for e in res.flight.dump()["events"]]
    assert kinds.count("step") == 4
    assert "checkpoint" in kinds and "metrics" in kinds


# ---------------------------------------------------------------------------
# obs.fleet: the one merge implementation
# ---------------------------------------------------------------------------

def test_merged_quantile_pinned_against_old_bench_math():
    """The recorded-fixture pin: obs.fleet.merged_quantile must
    reproduce the OLD bench._merged_decode_quantile math (inlined
    here as the frozen reference) exactly, windows and stale-max
    guard included — bench and a production scrape can never
    disagree because there is one copy."""
    import math as _math

    def old_bench_math(pairs, q):           # bench.py@PR10, verbatim
        merged = Histogram(Registry(), "_merged_decode_window")
        for hist, mark in pairs:
            merged.counts = merged.counts + (hist.counts - mark[0])
            merged.sum += hist.sum - mark[1]
            merged.count += hist.count - mark[2]
            if hist._max > mark[3]:
                merged._max = max(merged._max, hist._max)
        return merged.quantile(q)

    reg = Registry()
    rng = np.random.default_rng(7)
    h1, h2 = Histogram(reg, "a"), Histogram(reg, "b")
    h1.observe(12.0)                        # pre-mark compile outlier
    m1, m2 = h1.state(), h2.state()
    h1.observe(rng.uniform(0.001, 0.004, 200))
    h2.observe(rng.uniform(0.002, 0.05, 300))
    h2.observe(40.0)                        # post-mark overflow obs
    pairs = [(h1, m1), (h2, m2)]
    for q in (0.1, 0.5, 0.9, 0.99, 1.0):
        old = old_bench_math(pairs, q)
        new = fleet.merged_quantile(pairs, q)
        assert new == old or (
            _math.isnan(new) and _math.isnan(old)), (q, new, old)


def test_merge_histograms_rejects_mixed_ladders():
    reg = Registry()
    h1 = Histogram(reg, "a", buckets=(0.1, 0.2))
    h2 = Histogram(reg, "b", buckets=(0.1, 0.3))
    with pytest.raises(ValueError, match="bucket"):
        fleet.merge_histograms([(h1, None), (h2, None)])
    with pytest.raises(ValueError, match="at least one"):
        fleet.merge_histograms([])


def test_merge_registries_sums_counters_unions_histograms():
    r1, r2 = Registry(), Registry()
    r1.counter("tok_total").inc(5)
    r2.counter("tok_total").inc(7)
    r1.gauge("util").set(0.5)
    r2.gauge("util").set(0.9)
    r1.histogram("lat").observe([0.001] * 10)
    r2.histogram("lat").observe([0.004] * 10)
    merged = fleet.merge_registries([r1, r2])
    assert merged.counter("tok_total").value == 12
    h = merged.histogram("lat")
    assert h.count == 20
    assert abs(h.sum - 0.05) < 1e-12
    # gauges never merge into a scalar — they tabulate
    assert "util" not in merged._instruments
    table = fleet.gauge_table([r1, r2], labels=["replica0", "replica1"])
    assert table["util"] == {"replica0": 0.5, "replica1": 0.9}
    assert fleet.counter_sum([r1, r2], "tok_total") == 12
    assert fleet.counter_sum([r1, r2], "absent_total") == 0
    with pytest.raises(TypeError, match="not a counter"):
        fleet.counter_sum([r1], "util")


def test_merge_registries_rejects_kind_drift():
    r1, r2 = Registry(), Registry()
    r1.counter("x")
    r2.gauge("x")
    with pytest.raises(TypeError, match="vocabulary"):
        fleet.merge_registries([r1, r2])


def test_gauge_table_label_mismatch_is_loud():
    with pytest.raises(ValueError, match="labels"):
        fleet.gauge_table([Registry()], labels=["a", "b"])


# ---------------------------------------------------------------------------
# the OBS_r02 tracing lane (schema bar)
# ---------------------------------------------------------------------------

def test_obs_schema_enforces_tracing_budget():
    """The optional ``tracing`` section (r02+): per-event record cost
    gated at <= 1% of the bench-smoke decode step; the r01 shape
    (no tracing section) stays valid."""
    from apex_tpu.analysis import obs as obs_schema
    doc = json.loads((REPO / "OBS_r02.json").read_text())
    assert obs_schema.validate_obs(doc) == []
    assert doc["tracing"]["overhead_pct"] <= 1.0
    over = copy.deepcopy(doc)
    over["tracing"]["overhead_pct"] = 1.7
    assert any("budget" in p for p in obs_schema.validate_obs(over))
    broken = copy.deepcopy(doc)
    del broken["tracing"]["per_event_us"]
    assert any("per_event_us" in p
               for p in obs_schema.validate_obs(broken))
    legacy = copy.deepcopy(doc)
    del legacy["tracing"]
    assert obs_schema.validate_obs(legacy) == []


def test_flight_and_tracer_stay_ordered_under_concurrent_noters():
    """Regression (review round 2): timestamps are stamped INSIDE the
    lock, so a watchdog thread racing the main loop can never append
    ring/trace events whose ts go backwards (which the incident and
    TRACE schemas reject)."""
    import threading

    fr = FlightRecorder(capacity=4096)
    tr = RequestTracer()

    def hammer(tag):
        for i in range(300):
            fr.note("step", thread=tag, i=i)
            tr.record("decode_step", "u", f"replica{tag}", step=i,
                      token=0, batch=1, tokens=1)

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ts = [e["ts"] for e in fr.dump()["events"]]
    assert ts == sorted(ts)
    evs = tr.events("u")
    assert [e["seq"] for e in evs] == sorted(e["seq"] for e in evs)
    assert [e["ts"] for e in evs] == sorted(e["ts"] for e in evs)
