"""Loss-scaler state machine tests.

Conformance to reference ``apex/amp/scaler.py`` semantics: init 2**16,
halve on overflow, double after scale_window clean steps, min/max clamps,
static scale never moves but still skips.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.amp.scaler import LossScaler, all_finite


def test_dynamic_defaults():
    s = LossScaler()
    st = s.init_state()
    assert float(st.loss_scale) == 2.0 ** 16
    assert int(st.unskipped) == 0


def test_overflow_halves_and_resets():
    s = LossScaler()
    st = s.init_state()
    st, skip = s.update(st, jnp.asarray(False))  # overflow
    assert bool(skip)
    assert float(st.loss_scale) == 2.0 ** 15
    assert int(st.unskipped) == 0


def test_window_doubles():
    s = LossScaler(scale_window=3)
    st = s.init_state()
    for i in range(3):
        st, skip = s.update(st, jnp.asarray(True))
        assert not bool(skip)
    assert float(st.loss_scale) == 2.0 ** 17
    assert int(st.unskipped) == 0


def test_max_scale_cap():
    s = LossScaler(scale_window=1, max_loss_scale=2.0 ** 17)
    st = s.init_state()
    for _ in range(5):
        st, _ = s.update(st, jnp.asarray(True))
    assert float(st.loss_scale) == 2.0 ** 17


def test_min_scale_floor():
    s = LossScaler(min_loss_scale=2.0 ** 15)
    st = s.init_state()
    for _ in range(5):
        st, _ = s.update(st, jnp.asarray(False))
    assert float(st.loss_scale) == 2.0 ** 15


def test_overflow_storm_respects_floor_and_pinned_flag_flips_exactly():
    """ISSUE 3 satellite: an overflow storm must never push the scale
    below min_loss_scale, and pinned_at_floor must flip exactly when the
    scale REACHES the floor — one step earlier it is still False."""
    s = LossScaler(min_loss_scale=2.0 ** 10)
    st = s.init_state()
    assert not bool(s.pinned_at_floor(st))
    for k in range(1, 21):                   # 20-step storm
        st, skip = s.update(st, jnp.asarray(False))
        assert bool(skip)
        expected = max(2.0 ** (16 - k), 2.0 ** 10)
        assert float(st.loss_scale) == expected
        assert float(st.loss_scale) >= 2.0 ** 10
        # floor reached after exactly 6 halvings: 2^16 -> 2^10
        assert bool(s.pinned_at_floor(st)) == (k >= 6)


def test_default_floor_is_one():
    s = LossScaler()     # min_loss_scale=None -> update clamps at 1.0
    assert s.floor == 1.0
    st = s.init_state()
    for _ in range(40):
        st, _ = s.update(st, jnp.asarray(False))
    assert float(st.loss_scale) == 1.0
    assert bool(s.pinned_at_floor(st))


def test_pinned_flag_clears_when_scale_grows_off_floor():
    s = LossScaler(min_loss_scale=2.0 ** 15, scale_window=2)
    st = s.init_state()
    st, _ = s.update(st, jnp.asarray(False))         # 2^16 -> 2^15: pinned
    assert bool(s.pinned_at_floor(st))
    for _ in range(2):                                # clean window: doubles
        st, _ = s.update(st, jnp.asarray(True))
    assert float(st.loss_scale) == 2.0 ** 16
    assert not bool(s.pinned_at_floor(st))


def test_static_scale_never_pinned():
    s = LossScaler(loss_scale=128.0)
    st = s.init_state()
    st, _ = s.update(st, jnp.asarray(False))
    assert not bool(s.pinned_at_floor(st))


def test_static_scale_never_moves_but_skips():
    s = LossScaler(loss_scale=128.0)
    st = s.init_state()
    assert float(st.loss_scale) == 128.0
    st, skip = s.update(st, jnp.asarray(False))
    assert bool(skip)
    assert float(st.loss_scale) == 128.0
    st, skip = s.update(st, jnp.asarray(True))
    assert not bool(skip)
    assert float(st.loss_scale) == 128.0


def test_unscale_and_finite_flag():
    s = LossScaler(loss_scale=4.0)
    st = s.init_state()
    grads = {"a": jnp.asarray([4.0, 8.0], jnp.bfloat16),
             "b": jnp.asarray([[2.0]], jnp.bfloat16)}
    out, finite = s.unscale(grads, st)
    assert bool(finite)
    assert out["a"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out["a"]), [1.0, 2.0])
    np.testing.assert_allclose(np.asarray(out["b"]), [[0.5]])

    grads["a"] = grads["a"].at[1].set(jnp.inf)
    _, finite = s.unscale(grads, st)
    assert not bool(finite)


def test_unscale_with_stashed_checks_only_new():
    s = LossScaler(loss_scale=2.0)
    st = s.init_state()
    new = {"a": jnp.asarray([2.0, 4.0])}
    stashed = {"a": jnp.asarray([jnp.inf, 1.0])}  # stale inf must NOT trip
    out, finite = s.unscale_with_stashed(new, stashed, st)
    assert bool(finite)
    assert not np.isfinite(np.asarray(out["a"])[0])  # but result keeps it
    np.testing.assert_allclose(np.asarray(out["a"])[1], 3.0)


def test_all_finite_on_mixed_tree():
    tree = {"x": jnp.ones((3,)), "n": jnp.asarray([1, 2]),  # ints ignored
            "y": (jnp.zeros((2, 2)),)}
    assert bool(all_finite(tree))
    tree["y"] = (jnp.asarray([[1.0, jnp.nan], [0.0, 0.0]]),)
    assert not bool(all_finite(tree))


@pytest.mark.experimental
class TestAllFinitePacked:
    """Numerics pin for the PARKED flat-packed finite check
    (``ops/pallas/experimental/finite_pack.py`` — measured −1.8 to
    −3.5% end-to-end vs the per-leaf path, kept per the experimental-
    namespace convention).  It must agree with the production
    ``all_finite`` on every placement of a non-finite value so the
    negative result stays reproducible."""

    @pytest.fixture(autouse=True)
    def pallas_mode(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")

    def _tree(self, nan_at=None, dtype=jnp.float32):
        import numpy as np
        leaves = [np.random.RandomState(i).randn(5, 7).astype(dtype)
                  for i in range(4)]
        if nan_at is not None:
            i, val = nan_at
            leaves[i][2, 3] = val
        return {"a": jnp.asarray(leaves[0]),
                "b": {"c": jnp.asarray(leaves[1]),
                      "d": jnp.asarray(leaves[2])},
                "e": jnp.asarray(leaves[3]),
                "ints": jnp.arange(3)}

    @staticmethod
    def _packed(tree):
        from apex_tpu.ops.pallas.experimental.finite_pack import (
            all_finite_packed)
        return all_finite_packed(tree)

    def test_clean_tree_is_finite(self):
        assert bool(self._packed(self._tree()))
        assert bool(all_finite(self._tree()))

    @pytest.mark.parametrize("leaf_i", [0, 1, 2, 3])
    @pytest.mark.parametrize("val", [jnp.nan, jnp.inf, -jnp.inf])
    def test_detects_nonfinite_in_any_leaf(self, leaf_i, val):
        tree = self._tree(nan_at=(leaf_i, val))
        assert not bool(self._packed(tree))
        assert not bool(all_finite(tree))  # parked and production agree

    def test_mixed_dtype_groups(self):
        tree = {"f32": jnp.ones((33,), jnp.float32),
                "bf16": jnp.ones((17,), jnp.bfloat16),
                "f16": jnp.full((9,), jnp.nan, jnp.float16)}
        assert not bool(self._packed(tree))
        tree["f16"] = jnp.ones((9,), jnp.float16)
        assert bool(self._packed(tree))

    def test_bf16_leaf_nan(self):
        tree = {"g": jnp.asarray([1.0, 2.0], jnp.bfloat16)
                .at[1].set(jnp.nan)}
        assert not bool(self._packed(tree))

    def test_empty_and_int_only(self):
        assert bool(self._packed({}))
        assert bool(self._packed({"i": jnp.arange(4)}))

    def test_inside_jit(self):
        tree = self._tree(nan_at=(2, jnp.inf))

        @jax.jit
        def f(t):
            return self._packed(t)
        assert not bool(f(tree))
        assert bool(f(self._tree()))


def test_update_inside_jit():
    s = LossScaler()

    @jax.jit
    def step(st, ok):
        return s.update(st, ok)

    st = s.init_state()
    st, skip = step(st, jnp.asarray(False))
    assert bool(skip)
    assert float(st.loss_scale) == 2.0 ** 15
