"""Seeded-bug fixtures for the Pallas kernel sanitizer
(:mod:`apex_tpu.analysis.pallas_lint`).

Every rule id gets a minimal kernel built to trip it AND a clean twin
that differs only in the one property the rule checks — so a rule that
goes quiet (regression) or noisy (false positive) fails here, not in a
committed KERNLINT round.  The shipped-kernel assertions pin the
sweep's headline claims (adam donation aliasing is sound both ways,
the layer-norm backward routes over-budget widths to the fallback)
as importable regression tests.
"""

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

from apex_tpu.analysis import kernlint, pallas_lint  # noqa: E402


def _error_ids(report):
    return sorted({f.op for f in report.findings
                   if f.severity == "error"})


def _copy_k(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def _accum_k(x_ref, o_ref):
    o_ref[...] += x_ref[...]


_X = jnp.ones((4 * 8, 128), jnp.float32)


def _call(out_shape, in_map, out_map, grid, sem, kern=_copy_k,
          scratch=(), **kw):
    """One-input one-output 8x128-block pallas_call fixture factory."""
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[pl.BlockSpec((8, 128), in_map)],
        out_specs=pl.BlockSpec((8, 128), out_map),
        out_shape=jax.ShapeDtypeStruct(out_shape, jnp.float32),
        scratch_shapes=list(scratch),
        compiler_params=dict(mosaic=dict(dimension_semantics=sem)),
        interpret=True, **kw)(_X)


def _lint(*call_args, **call_kw):
    return pallas_lint.lint_fn(lambda x: _call(*call_args, **call_kw),
                               _X)


# ---------------------------------------------------------------------------
# the rule lists cannot drift
# ---------------------------------------------------------------------------

def test_rule_lists_pinned_equal():
    """kernlint.py mirrors the rule ids so gate_hygiene stays
    stdlib-only; this pin is what keeps the mirror honest."""
    assert tuple(pallas_lint.RULES) == tuple(kernlint.RULES)
    assert len(set(pallas_lint.RULES)) == 6


# ---------------------------------------------------------------------------
# seeded bugs: one fixture per rule id + a clean twin
# ---------------------------------------------------------------------------

def test_parallel_race_fires_on_colliding_writes():
    # all four parallel grid points write output block (0, 0)
    rep = _lint((4 * 8, 128), lambda i: (i, 0), lambda i: (0, 0),
                (4,), ("parallel",))
    assert "pallas-parallel-race" in _error_ids(rep)


def test_parallel_race_clean_twin_disjoint_blocks():
    rep = _lint((4 * 8, 128), lambda i: (i, 0), lambda i: (i, 0),
                (4,), ("parallel",))
    assert _error_ids(rep) == []


def test_seq_accum_parallel_fires_on_parallel_accumulator():
    # dw-style accumulator (read-modify-write of a revisited block)
    # under a dim declared parallel: the accumulation order does not
    # exist on a parallel dim
    rep = _lint((8, 128), lambda i: (i, 0), lambda i: (0, 0),
                (4,), ("parallel",), kern=_accum_k)
    assert "pallas-seq-accum-parallel" in _error_ids(rep)


def test_seq_accum_clean_twin_arbitrary_dim():
    # the identical accumulator on a sequential grid is the layer-norm
    # backward pattern — legal
    rep = _lint((8, 128), lambda i: (i, 0), lambda i: (0, 0),
                (4,), ("arbitrary",), kern=_accum_k)
    assert _error_ids(rep) == []


def test_oob_unmasked_fires_on_shifted_index_map():
    # input walk starts one whole block past the data
    rep = _lint((4 * 8, 128), lambda i: (i + 1, 0), lambda i: (i, 0),
                (4,), ("arbitrary",))
    assert "pallas-oob-unmasked" in _error_ids(rep)


def test_oob_clean_twin_overhanging_tail_is_masked():
    # a ragged last block ORIGINATING inside the array is the legal
    # Mosaic-masked tail (the layer-norm forward relies on it)
    y = jnp.ones((28, 128), jnp.float32)

    def f(x):
        return pl.pallas_call(
            _copy_k, grid=(4,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((28, 128), jnp.float32),
            interpret=True)(x)
    assert _error_ids(pallas_lint.lint_fn(f, y)) == []


def test_uncovered_output_fires_on_short_grid():
    # grid of 3 over a 4-block output: the last block is never written
    rep = _lint((4 * 8, 128), lambda i: (i, 0), lambda i: (i, 0),
                (3,), ("arbitrary",))
    assert "pallas-uncovered-output" in _error_ids(rep)


def test_uncovered_clean_twin_full_grid():
    rep = _lint((4 * 8, 128), lambda i: (i, 0), lambda i: (i, 0),
                (4,), ("arbitrary",))
    assert _error_ids(rep) == []


def test_vmem_overflow_fires_on_giant_scratch():
    def scratch_k(x_ref, o_ref, s_ref):
        o_ref[...] = x_ref[...]
    rep = _lint((4 * 8, 128), lambda i: (i, 0), lambda i: (i, 0),
                (4,), ("arbitrary",), kern=scratch_k,
                scratch=[pltpu.VMEM((4096, 4096), jnp.float32)])  # 64 MiB
    assert "pallas-vmem-overflow" in _error_ids(rep)


def test_vmem_clean_twin_small_scratch():
    def scratch_k(x_ref, o_ref, s_ref):
        o_ref[...] = x_ref[...]
    rep = _lint((4 * 8, 128), lambda i: (i, 0), lambda i: (i, 0),
                (4,), ("arbitrary",), kern=scratch_k,
                scratch=[pltpu.VMEM((8, 128), jnp.float32)])
    assert _error_ids(rep) == []


def test_alias_race_fires_on_torn_conditional_store():
    # donated alias whose ONLY store hides under pl.when: grid points
    # where the predicate is false leave the aliased block torn
    def torn_k(x_ref, o_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            o_ref[...] = x_ref[...] * 2.0
    rep = _lint((4 * 8, 128), lambda i: (i, 0), lambda i: (i, 0),
                (4,), ("arbitrary",), kern=torn_k,
                input_output_aliases={0: 0})
    assert "pallas-alias-race" in _error_ids(rep)


def test_alias_race_fires_on_footprint_mismatch():
    # in-place alias where the read walks the array in the opposite
    # order to the write: block i reads data block 3-i AFTER the write
    # to block 3-i already clobbered it
    rep = _lint((4 * 8, 128), lambda i: (3 - i, 0), lambda i: (i, 0),
                (4,), ("arbitrary",), input_output_aliases={0: 0})
    assert "pallas-alias-race" in _error_ids(rep)


def test_alias_clean_twin_inplace_same_footprint():
    # the multi-tensor in-place pattern: unconditional store, read and
    # write footprints identical at every grid point
    rep = _lint((4 * 8, 128), lambda i: (i, 0), lambda i: (i, 0),
                (4,), ("arbitrary",), input_output_aliases={0: 0})
    assert _error_ids(rep) == []


# ---------------------------------------------------------------------------
# extraction + report plumbing
# ---------------------------------------------------------------------------

def test_no_pallas_call_reports_info_count_zero():
    rep = pallas_lint.lint_fn(lambda x: x * 2.0, _X)
    assert rep.ok
    calls = [f for f in rep.findings if f.op == "pallas-call"]
    assert len(calls) == 1 and calls[0].count == 0


def test_extracts_calls_nested_under_transforms():
    def f(x):
        def step(c, _):
            return _call((4 * 8, 128), lambda i: (i, 0),
                         lambda i: (i, 0), (4,), ("arbitrary",)), None
        y, _ = jax.lax.scan(step, x, None, length=2)
        return y
    jaxpr = jax.make_jaxpr(f)(_X)
    calls = pallas_lint.extract_pallas_calls(jaxpr)
    assert len(calls) == 1 and calls[0].grid == (4,)


# ---------------------------------------------------------------------------
# shipped kernels: the sweep's headline claims, pinned
# ---------------------------------------------------------------------------

def test_fused_adam_clean_both_donation_modes():
    """The PR-2 ``donate=`` aliasing audit: p/m/v in-place updates lint
    clean with donation ON and OFF (identical read/write footprints,
    unconditional stores)."""
    from apex_tpu.ops.pallas.adam_kernel import ADAM_PAD, packed_adam
    n = ADAM_PAD
    args = [jnp.ones((n,), jnp.float32) for _ in range(4)]
    kw = dict(step_size=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
              scale=1.0, weight_decay=0.01, eps_mode=0)
    for donate in (False, True):
        rep = pallas_lint.lint_fn(
            lambda p, m, v, g: packed_adam(p, m, v, g, donate=donate,
                                           **kw), *args)
        assert rep.ok, (donate, rep.format())


def test_layer_norm_supported_is_budget_aware():
    """Widths whose backward working set exceeds the VMEM ceiling are
    unsupported WITH a dtype (they route to the jnp fallback instead
    of shipping a kernel the sanitizer rejects)."""
    from apex_tpu.ops.pallas import layer_norm_kernels as lnk
    # dtype-less: the legacy alignment-only check
    assert lnk.supported(8192)
    # fp32 caps at n2=5376, bf16 at 10752 (the KERNLINT boundaries)
    assert lnk.supported(5376, jnp.float32)
    assert not lnk.supported(5504, jnp.float32)
    assert not lnk.supported(8192, jnp.float32)
    assert lnk.supported(10752, jnp.bfloat16)
    assert not lnk.supported(10880, jnp.bfloat16)
    assert not lnk.supported(16384, jnp.bfloat16)


def test_layer_norm_boundary_backward_lints_clean():
    """The widest supported fp32 shape's fwd+bwd pallas calls pass all
    six rules — the ``supported()`` boundary and the sanitizer's VMEM
    ceiling agree."""
    from apex_tpu.ops.pallas import layer_norm_kernels as lnk
    n2 = 5376
    x = jnp.ones((256, n2), jnp.float32)
    w = jnp.ones((n2,), jnp.float32)
    b = jnp.zeros((n2,), jnp.float32)

    def f(x, w, b):
        y, vjp = jax.vjp(
            lambda xx, ww, bb: lnk.layer_norm_fwd_vjp(xx, ww, bb, 1e-5),
            x, w, b)
        return vjp(y)
    rep = pallas_lint.lint_fn(f, x, w, b)
    assert rep.ok, rep.format()
    ncalls = sum(f.count for f in rep.findings
                 if f.op == "pallas-call")
    assert ncalls >= 2   # forward + fused backward


def test_fused_layer_norm_routes_overbudget_width_to_fallback(
        monkeypatch):
    """The call site honors the budget-aware ``supported()``: an
    8192-wide fp32 norm traces with ZERO pallas calls."""
    monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")
    from apex_tpu.normalization import fused_layer_norm_affine
    x = jnp.ones((8, 8192), jnp.float32)
    w = jnp.ones((8192,), jnp.float32)
    b = jnp.zeros((8192,), jnp.float32)
    jaxpr = jax.make_jaxpr(
        lambda x, w, b: fused_layer_norm_affine(x, w, b, 8192))(x, w, b)
    assert pallas_lint.extract_pallas_calls(jaxpr) == []


# ---------------------------------------------------------------------------
# the registered pass + CLI lane
# ---------------------------------------------------------------------------

def test_pass_registered_under_pallas_kernel():
    from apex_tpu.analysis.core import PASSES
    assert pallas_lint.PASS_NAME in PASSES


def test_graph_lint_pallas_lane_runs_via_cli(capsys):
    import graph_lint
    assert graph_lint.main(["--families", "mlp", "--lanes", "o1",
                            "--passes", "pallas"]) == 0
    out = capsys.readouterr().out
    assert '"pallas-kernel"' in out and '"ok": true' in out
