"""Paged KV cache invariants (:mod:`apex_tpu.serve.paged`).

Three contracts: the host-side block allocator's bookkeeping can never
lose or double-book a block; the page-table indirection is pure data
movement (gather-linearized contents BITWISE match a monolithic cache
fed the same token stream, and attention through it matches the
monolithic decode math bitwise); and slot reuse after retirement leaks
no stale KV into a new request's attention (the masking test).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.serve import paged
from apex_tpu.serve.paged import (
    TRASH_BLOCK,
    BlockAllocator,
    PoolExhausted,
    gather_slot_kv,
    make_pools,
    paged_attention,
    token_write_coords,
)

L, H, D, BS, MB = 2, 2, 8, 4, 4     # layers, heads, head_dim, block, blocks/slot
M = MB * BS                          # per-slot context


# ---------------------------------------------------------------------------
# allocator
# ---------------------------------------------------------------------------

def test_allocator_reserves_trash_and_accounts():
    a = BlockAllocator(8)
    assert a.free_count == 7                       # block 0 reserved
    got = a.alloc(3, "r0")
    assert TRASH_BLOCK not in got
    assert len(set(got)) == 3
    assert a.free_count == 4 and a.live_count == 3
    a.free(got, "r0")
    assert a.free_count == 7 and a.live_count == 0


def test_allocator_exhaustion_allocates_nothing():
    a = BlockAllocator(4)
    a.alloc(2, "r0")
    with pytest.raises(PoolExhausted):
        a.alloc(2, "r1")
    # the failed alloc must not have leaked partial blocks
    assert a.free_count == 1
    a.alloc(1, "r1")


def test_allocator_double_free_and_cross_owner_rejected():
    a = BlockAllocator(8)
    b0 = a.alloc(2, "r0")
    b1 = a.alloc(2, "r1")
    a.free(b0, "r0")
    with pytest.raises(ValueError, match="double free|not owned"):
        a.free(b0, "r0")
    with pytest.raises(ValueError, match="not owned"):
        a.free(b1, "r0")
    # the rejected call must not have half-released r1's blocks
    assert sorted(a.owned_by("r1")) == sorted(b1)


def test_allocator_rejects_degenerate_pool():
    with pytest.raises(ValueError):
        BlockAllocator(1)


# ---------------------------------------------------------------------------
# page-table indirection == monolithic cache, bitwise
# ---------------------------------------------------------------------------

def _random_stream(rng, n_slots, lengths):
    """Per-slot per-position K values shaped like one layer's writes."""
    return [rng.standard_normal((lengths[s], H, D)).astype(np.float32)
            for s in range(n_slots)]


def test_gather_bitwise_matches_monolithic_cache():
    """Write an interleaved multi-slot token stream through page
    tables (slot 1's blocks deliberately out of order and interleaved
    with slot 0's), then gather: contents equal the monolithic
    ``(S, M, H, D)`` cache fed the same stream, bit for bit."""
    rng = np.random.default_rng(0)
    n_slots = 2
    lengths = [9, 6]
    stream = _random_stream(rng, n_slots, lengths)

    kc, _ = make_pools(1, 9, BS, H, D, jnp.float32)
    # non-contiguous physical layout: logical order != physical order
    table = np.array([[1, 3, 5, TRASH_BLOCK],
                      [4, 2, TRASH_BLOCK, TRASH_BLOCK]], np.int32)
    mono = np.zeros((n_slots, M, H, D), np.float32)

    pool = kc[0]
    for s in range(n_slots):
        for t in range(lengths[s]):
            blocks, offs = token_write_coords(
                jnp.asarray([t], jnp.int32),
                jnp.asarray(table[s][None]), BS,
                jnp.asarray([True]))
            pool = pool.at[blocks[0], offs[0]].set(stream[s][t])
            mono[s, t] = stream[s][t]
    lin = gather_slot_kv(pool, jnp.asarray(table))
    got = np.asarray(lin)
    # every written position identical; unwritten positions are only
    # compared where the page table maps real blocks
    for s in range(n_slots):
        np.testing.assert_array_equal(got[s, :lengths[s]],
                                      mono[s, :lengths[s]])


def test_paged_attention_bitwise_matches_monolithic_math():
    """Attention through the gathered cache equals the monolithic
    decode einsum (:func:`apex_tpu.models.generate._attn_cached`)
    bitwise on the same contents and mask."""
    from apex_tpu.models.generate import _attn_cached
    rng = np.random.default_rng(1)
    n_slots, t = 2, 10
    kc, vc = make_pools(1, 9, BS, H, D, jnp.float32)
    table = np.array([[2, 1, 4, TRASH_BLOCK],
                      [3, 5, TRASH_BLOCK, TRASH_BLOCK]], np.int32)
    kpool, vpool = kc[0], vc[0]
    for s in range(n_slots):
        for pos in range(t):
            blocks, offs = token_write_coords(
                jnp.asarray([pos], jnp.int32),
                jnp.asarray(table[s][None]), BS, jnp.asarray([True]))
            kpool = kpool.at[blocks[0], offs[0]].set(
                rng.standard_normal((H, D)).astype(np.float32))
            vpool = vpool.at[blocks[0], offs[0]].set(
                rng.standard_normal((H, D)).astype(np.float32))
    k_lin = gather_slot_kv(kpool, jnp.asarray(table))
    v_lin = gather_slot_kv(vpool, jnp.asarray(table))
    q = jnp.asarray(rng.standard_normal((n_slots, 1, H, D)),
                    jnp.float32)
    valid = jnp.broadcast_to(jnp.arange(M) <= (t - 1),
                             (n_slots, 1, M))
    got = paged_attention(q, k_lin, v_lin, valid,
                          scale=1.0 / D ** 0.5)
    want = _attn_cached(q, k_lin, v_lin,
                        valid_mask=(jnp.arange(M) <= (t - 1))[None],
                        scale=1.0 / D ** 0.5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_slot_reuse_leaks_no_stale_kv():
    """Retire a long request, hand its physical blocks to a SHORTER
    one without zeroing: attention over the reused (stale-tailed)
    blocks must equal attention over a fresh zeroed pool bitwise — the
    validity mask, not buffer hygiene, is the isolation boundary."""
    rng = np.random.default_rng(2)
    scale = 1.0 / D ** 0.5
    table = jnp.asarray([[1, 2, TRASH_BLOCK, TRASH_BLOCK]], np.int32)

    def run(kpool, vpool, new_len):
        k_lin = gather_slot_kv(kpool, table)
        v_lin = gather_slot_kv(vpool, table)
        q = jnp.asarray(np.linspace(-1, 1, 1 * 1 * H * D,
                                    dtype=np.float32).reshape(1, 1, H, D))
        valid = (jnp.arange(M)[None, :] <= (new_len - 1))[None]  # (1,1,M)
        return paged_attention(q, k_lin, v_lin, valid, scale)

    new_writes_k = rng.standard_normal((3, H, D)).astype(np.float32)
    new_writes_v = rng.standard_normal((3, H, D)).astype(np.float32)

    def fill(kpool, vpool):
        for pos in range(3):
            blocks, offs = token_write_coords(
                jnp.asarray([pos], jnp.int32), table, BS,
                jnp.asarray([True]))
            kpool = kpool.at[blocks[0], offs[0]].set(new_writes_k[pos])
            vpool = vpool.at[blocks[0], offs[0]].set(new_writes_v[pos])
        return kpool, vpool

    # stale pool: blocks 1,2 full of a retired request's K/V
    kc, vc = make_pools(1, 4, BS, H, D, jnp.float32)
    stale_k = kc[0].at[1:3].set(
        jnp.asarray(rng.standard_normal((2, BS, H, D)), jnp.float32))
    stale_v = vc[0].at[1:3].set(
        jnp.asarray(rng.standard_normal((2, BS, H, D)), jnp.float32))
    kp, vp = fill(stale_k, stale_v)
    got = run(kp, vp, 3)

    kc2, vc2 = make_pools(1, 4, BS, H, D, jnp.float32)
    kp2, vp2 = fill(kc2[0], vc2[0])
    want = run(kp2, vp2, 3)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_token_write_coords_inactive_routes_to_trash():
    table = jnp.asarray([[3, 4, 5, 6], [7, 8, 1, 2]], np.int32)
    lengths = jnp.asarray([5, 9], jnp.int32)
    blocks, offs = token_write_coords(
        lengths, table, BS, jnp.asarray([True, False]))
    assert int(blocks[0]) == 4 and int(offs[0]) == 1   # 5 // 4, 5 % 4
    assert int(blocks[1]) == TRASH_BLOCK               # inactive lane
