"""Fused 1x1-conv backward kernel conformance (interpret mode on CPU).

The kernel is opt-in (measured slower in-model on v5e — see the module
docstring) but its numerics stay pinned: dx must match the lax transpose
exactly, dW to fp32-accumulation tolerance, and the routing predicate
must reject everything that is not a 1x1/stride-1/NHWC conv.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from apex_tpu.ops.pallas.experimental import conv1x1 as c1

pytestmark = pytest.mark.experimental

DN = ("NHWC", "HWIO", "NHWC")


def _grads(f, x, w, dy):
    def loss(x, w):
        return jnp.sum(f(x, w).astype(jnp.float32)
                       * dy.astype(jnp.float32))
    return jax.grad(loss, (0, 1))(x, w)


@pytest.mark.parametrize("b,s,cin,cout", [(2, 8, 64, 256), (2, 8, 256, 64),
                                          (1, 16, 128, 128)])
def test_bwd_matches_lax_transpose(b, s, cin, cout):
    kx, kw, kd = jax.random.split(jax.random.PRNGKey(cin + cout), 3)
    x = jax.random.normal(kx, (b, s, s, cin), jnp.float32)
    w = jax.random.normal(kw, (1, 1, cin, cout), jnp.float32) * 0.05
    dy = jax.random.normal(kd, (b, s, s, cout), jnp.float32)
    ref = _grads(lambda x, w: lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=DN), x, w, dy)
    got = _grads(c1.conv1x1, x, w, dy)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(ref[0]),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got[1]), np.asarray(ref[1]),
                               rtol=1e-4, atol=1e-3)


def test_remainder_m_falls_back():
    """B*H*W not divisible by any tile -> the lax transpose path (still
    correct, no crash)."""
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 3, 3, 64), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 64, 64),
                          jnp.float32) * 0.1
    dy = jnp.ones((1, 3, 3, 64), jnp.float32)
    ref = _grads(lambda x, w: lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=DN), x, w, dy)
    got = _grads(c1.conv1x1, x, w, dy)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=1e-5, atol=1e-5)


def test_routeable_predicate(monkeypatch):
    monkeypatch.setenv("APEX_TPU_FUSED_CONV1X1", "1")
    monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")
    x = jnp.zeros((2, 8, 8, 64), jnp.bfloat16)
    w11 = jnp.zeros((1, 1, 64, 128), jnp.bfloat16)
    ok = lambda **kw: c1.routeable(
        x, kw.pop("kernel", w11), kw.pop("strides", (1, 1)),
        kw.pop("padding", "SAME"), kw.pop("dn", DN), kw.pop("extra", {}))
    assert ok()
    # lax's None dimension_numbers default is NCHW/OIHW — never routed
    assert not ok(dn=None)
    assert not ok(kernel=jnp.zeros((3, 3, 64, 128), jnp.bfloat16))
    assert not ok(strides=(2, 2))
    assert not ok(padding=[(1, 1), (0, 0)])
    assert ok(padding=[(0, 0), (0, 0)])
    assert not ok(extra={"feature_group_count": 2})
    assert not ok(kernel=jnp.zeros((1, 1, 64, 128), jnp.float32))  # mixed

    monkeypatch.setenv("APEX_TPU_FUSED_CONV1X1", "0")
    assert not ok()


def test_vmem_tile_budget():
    """Tile selection caps the VMEM footprint (a 4096 tile at
    cin 512/cout 256 measured 20.75M > the 16M scoped limit on chip)."""
    t = c1._pick_tile(200704, 512, 256, 2)
    assert t is not None
    assert 2 * 2 * t * (2 * 512 + 256) <= 10 * 1024 * 1024
    assert c1._pick_tile(7 * 13, 64, 64, 2) is None
