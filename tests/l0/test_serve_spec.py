"""Speculative decoding in the serve engine (:mod:`apex_tpu.serve.spec`).

The acceptance contracts: (a) a spec-enabled mixed greedy stream —
including through a preemption and under the int8 KV cache — produces
outputs BITWISE equal to solo :func:`apex_tpu.models.generate.generate`
with measured acceptance > 0 and exactly ONE trace each for the draft
and verify steps; (b) sampled streams are bitwise equal to the
NON-speculative engine (the key-ladder verification draws exactly the
draws the baseline step would have made); (c) the per-slot PRNG chain
still advances one draw per EMITTED token under partial accepts
(``j < k``), so :func:`~apex_tpu.serve.sampling.advance_key` by draw
count — the router's replica-kill recovery — reconstructs the exact
key a spec-enabled slot holds; (d) the verify step carries no host
callback or retrace hazard (the graph-lint ``serve_verify`` lane's
runtime half).

The model is BRIEFLY TRAINED (the PR 8 pattern): a random-init model's
near-uniform logits put quantization/ulp noise above the argmax
margins, which tests tie-breaking rather than the speculation
machinery, and makes acceptance rates meaninglessly low.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp, analysis
from apex_tpu.models import GPTModel, gpt_tiny
from apex_tpu.models.generate import generate
from apex_tpu.obs.metrics import Registry
from apex_tpu.optimizers import FusedAdam
from apex_tpu.serve import (
    Request,
    ServeConfig,
    ServeEngine,
    SpecConfig,
    SpecEngine,
    advance_key,
    truncated_draft,
)


@pytest.fixture(scope="module")
def setup():
    """Briefly-trained gpt_tiny in the bf16 serving layout + mixed
    prompts drawn from its training distribution (real argmax margins,
    prompts the truncated draft can actually predict) — the ONE
    shared recipe, :func:`apex_tpu.models.gpt.train_toy_lm`."""
    from apex_tpu.models.gpt import train_toy_lm

    cfg, params, ids = train_toy_lm()
    prompts = [np.asarray(ids[i % 8, s:s + n], np.int32)
               for i, (s, n) in enumerate(
                   ((0, 5), (3, 12), (7, 3), (1, 20), (4, 9)))]
    return cfg, params, prompts


SCFG = ServeConfig(num_slots=2, block_size=4, num_blocks=17,
                   max_blocks_per_slot=8, prefill_chunk=4)


def _solo(params, cfg, prompt, n, kv_dtype=None):
    out = generate(params, cfg, jnp.asarray(prompt[None]), n,
                   kv_dtype=kv_dtype)
    return np.asarray(out)[0, len(prompt):]


@pytest.fixture(scope="module")
def engine(setup):
    """ONE spec engine (truncated layer-skip draft, k=3) shared by the
    greedy stream tests — every extra engine is four more XLA compiles
    (draft, verify, two prefills), and sharing makes the one-trace
    pins span the whole module's request history."""
    cfg, params, _ = setup
    dp, dcfg = truncated_draft(params, cfg, cfg.num_layers - 1)
    return SpecEngine(params, cfg, SCFG, dp, dcfg, SpecConfig(k=3),
                      registry=Registry())


def test_spec_mixed_stream_matches_solo_bitwise(setup, engine):
    """THE speculative-decoding gate: 5 mixed-length greedy requests
    through 2 slots with a truncated draft proposing 3 tokens per
    round — every output bitwise equal to its solo generate() run,
    measured acceptance rate > 0 (the draft is the target's own first
    layer, so it predicts the trained distribution), and ONE trace +
    one executable each for the draft and verify programs across the
    whole stream."""
    cfg, params, prompts = setup
    eng = engine
    news = (8, 6, 10, 4, 7)
    for i, (p, n) in enumerate(zip(prompts, news)):
        eng.submit(Request(uid=f"r{i}", prompt=p, max_new_tokens=n))
    out = eng.run()
    for i, (p, n) in enumerate(zip(prompts, news)):
        np.testing.assert_array_equal(
            out[f"r{i}"], _solo(params, cfg, p, n),
            err_msg=f"r{i} diverged from solo through speculation")
    assert eng.trace_counts["draft"] == 1
    assert eng.trace_counts["verify"] == 1
    assert eng.trace_counts["decode"] == 0      # never dispatched
    assert eng._draft_step._cache_size() == 1
    assert eng._verify_step._cache_size() == 1
    m = eng.metrics
    assert m.counter("serve_spec_rounds_total").value > 0
    proposed = m.counter("serve_spec_proposed_total").value
    accepted = m.counter("serve_spec_accepted_total").value
    assert proposed > 0 and accepted > 0
    rate = m.gauge("serve_spec_acceptance_rate").value
    assert rate == pytest.approx(accepted / proposed)
    # speculation must BEAT one-token-per-step: emitted decode tokens
    # per verify round strictly above 1 per active slot on average
    decode_tokens = m.counter("serve_tokens_total").value - 5
    rounds = m.counter("serve_spec_rounds_total").value
    assert decode_tokens > rounds, (
        f"{decode_tokens} tokens over {rounds} rounds: speculation "
        f"accepted nothing a plain engine wouldn't have emitted")


def test_spec_through_preemption_matches_solo(setup):
    """Block pressure preempts the youngest request mid-speculation
    (recompute-on-resume rebuilds BOTH the target and draft caches);
    every output — the evicted one included — still bitwise-matches
    solo."""
    cfg, params, prompts = setup
    scfg = ServeConfig(num_slots=3, block_size=4, num_blocks=9,
                       max_blocks_per_slot=8, prefill_chunk=4)
    dp, dcfg = truncated_draft(params, cfg, cfg.num_layers - 1)
    eng = SpecEngine(params, cfg, scfg, dp, dcfg, SpecConfig(k=3),
                     registry=Registry())
    reqs = [(prompts[1][:8], 8), (prompts[3][:8], 8), (prompts[4][:6], 6)]
    for i, (p, n) in enumerate(reqs):
        eng.submit(Request(uid=f"r{i}", prompt=p, max_new_tokens=n))
    out = eng.run()
    assert eng.metrics.counter("serve_preemptions_total").value == 1
    for i, (p, n) in enumerate(reqs):
        np.testing.assert_array_equal(
            out[f"r{i}"], _solo(params, cfg, p, n),
            err_msg=f"r{i} diverged from solo through preemption")
    assert eng.trace_counts["verify"] == 1
    assert eng.sched.allocator.live_count == 0


def test_spec_kv8_matches_solo_and_baseline(setup):
    """Speculation under the int8 KV cache: the verify step's
    quantize-on-write/fused-dequant path produces greedy streams
    bitwise equal to solo ``generate(kv_dtype="int8")`` AND to the
    non-speculative int8 engine (speculation adds zero drift on top
    of the quantization regime)."""
    cfg, params, prompts = setup
    scfg = dataclasses.replace(SCFG, kv_dtype="int8")
    dp, dcfg = truncated_draft(params, cfg, cfg.num_layers - 1)
    eng = SpecEngine(params, cfg, scfg, dp, dcfg, SpecConfig(k=3),
                     registry=Registry())
    base = ServeEngine(params, cfg, scfg, registry=Registry())
    news = (6, 8, 5)
    for i, (p, n) in enumerate(zip(prompts[:3], news)):
        eng.submit(Request(uid=f"r{i}", prompt=p, max_new_tokens=n))
        base.submit(Request(uid=f"r{i}", prompt=p, max_new_tokens=n))
    out, outb = eng.run(), base.run()
    for i, (p, n) in enumerate(zip(prompts[:3], news)):
        np.testing.assert_array_equal(
            out[f"r{i}"], _solo(params, cfg, p, n, kv_dtype="int8"),
            err_msg=f"r{i}: spec+kv8 diverged from solo int8")
        np.testing.assert_array_equal(
            out[f"r{i}"], outb[f"r{i}"],
            err_msg=f"r{i}: spec+kv8 diverged from the baseline "
                    f"int8 engine")
    assert eng.metrics.counter("serve_spec_accepted_total").value > 0


def test_spec_sampled_streams_match_baseline_engine(setup):
    """Sampled slots: the verifier draws with the slot's key ladder
    through the same fused epilogue, so a spec-enabled sampled stream
    is bitwise the NON-spec engine's stream — the strong form of the
    distribution-exactness argument (the output IS the target's
    stream, not merely distributed like it)."""
    cfg, params, prompts = setup
    dp, dcfg = truncated_draft(params, cfg, cfg.num_layers - 1)
    eng = SpecEngine(params, cfg, SCFG, dp, dcfg, SpecConfig(k=3),
                     registry=Registry())
    base = ServeEngine(params, cfg, SCFG, registry=Registry())
    for e in (eng, base):
        e.submit(Request(uid="s", prompt=prompts[0], max_new_tokens=8,
                         temperature=0.8, top_k=12, seed=7))
        e.submit(Request(uid="g", prompt=prompts[2], max_new_tokens=6))
    out, outb = eng.run(), base.run()
    np.testing.assert_array_equal(out["s"], outb["s"])
    np.testing.assert_array_equal(out["g"], outb["g"])


def test_advance_key_chain_identity_under_partial_accepts(setup):
    """Satellite: the draw-count chain under speculative drafts.  A
    spec round emits 1..k+1 tokens, but the slot's PRNG chain must
    advance EXACTLY one draw per emitted token — so after any prefix
    of the stream, ``advance_key(PRNGKey(seed), draws)`` (the
    router's replica-kill reconstruction,
    ``DisaggRouter.kill_replica``) equals the key the slot actually
    holds.  Checked at EVERY step boundary of a sampled stream whose
    rounds include partial accepts (0 < j < k) — the case where a
    mis-specified ladder index would silently skip or replay
    draws."""
    cfg, params, prompts = setup
    dp, dcfg = truncated_draft(params, cfg, cfg.num_layers - 1)
    eng = SpecEngine(params, cfg, SCFG, dp, dcfg, SpecConfig(k=3),
                     registry=Registry())
    eng.submit(Request(uid="s", prompt=prompts[1], max_new_tokens=12,
                       temperature=0.7, top_k=20, seed=11))
    eng._admit_and_evict()
    slot = next(i for i in range(eng.sched.num_slots)
                if eng.sched.slots[i] is not None)
    emit_counts = []
    while eng.sched.slots[slot] is not None:
        before = len(eng.sched.slots[slot].emitted)
        eng.step()
        s = eng.sched.slots[slot]
        if s is None:
            break
        emit_counts.append(len(s.emitted) - before)
        draws = len(s.request.prior_tokens) + len(s.emitted)
        want = np.asarray(advance_key(jax.random.PRNGKey(11), draws))
        got = np.asarray(eng.carry["keys"][slot])
        np.testing.assert_array_equal(
            got, want,
            err_msg=f"after {draws} draws (round emitted "
                    f"{emit_counts[-1]}): slot key is not the "
                    f"draw-count chain — kill_replica recovery would "
                    f"resume the wrong PRNG state")
    # the interesting regime actually happened: at least one round
    # emitted more than the baseline 1 token (an accept), and the
    # rounds were not uniformly full accepts either
    assert any(c > 1 for c in emit_counts), (
        f"no round accepted anything ({emit_counts}); the chain "
        f"identity was only checked at the trivial j=0 point")


def test_full_reach_requests_do_not_wrap_writes(setup):
    """Review-found corruption class: a request whose footprint fills
    the ENTIRE slot reach (prompt + budget == max_blocks_per_slot x
    block_size) decodes to its very last token with the verify step's
    trailing rows at positions past the reach.  Unmasked, their
    clip+modulo write coordinates WRAP onto live early positions —
    silently corrupting history the emitted rows attend to in the
    same dispatch (writes land before the gather).  A low-acceptance
    draft maximizes the exposure (lengths advance by 1, so rounds
    straddle the boundary); outputs must stay bitwise solo.  The
    draft cache-fill step shares the same masking (it writes up to
    ``L + k``)."""
    cfg, params, prompts = setup
    # 16-token prompt + 8-token budget == 6 blocks x 4 exactly
    scfg = ServeConfig(num_slots=2, block_size=4, num_blocks=13,
                       max_blocks_per_slot=6, prefill_chunk=4)
    # a deliberately WRONG draft (random init): acceptance ~0
    from apex_tpu.models import GPTModel
    import apex_tpu.amp as amp_mod
    bad = GPTModel(cfg).init(jax.random.PRNGKey(99),
                             jnp.zeros((1, 4), jnp.int32))["params"]
    bad = amp_mod.initialize(
        opt_level="O2", verbosity=0).model_params_from(bad)
    eng = SpecEngine(params, cfg, scfg, bad, cfg, SpecConfig(k=3),
                     registry=Registry())
    rng = np.random.RandomState(3)
    cases = [rng.randint(0, cfg.vocab_size, (16,)) for _ in range(4)]
    for i, p in enumerate(cases):
        eng.submit(Request(uid=f"w{i}", prompt=p, max_new_tokens=8))
    out = eng.run()
    for i, p in enumerate(cases):
        np.testing.assert_array_equal(
            out[f"w{i}"], _solo(params, cfg, p, 8),
            err_msg=f"w{i}: end-of-reach verify rows wrapped their "
                    f"writes onto live positions")


def test_spec_config_and_draft_validation(setup):
    cfg, params, _ = setup
    with pytest.raises(ValueError, match="k="):
        SpecConfig(k=0)
    with pytest.raises(ValueError, match="num_layers"):
        truncated_draft(params, cfg, cfg.num_layers)
    with pytest.raises(ValueError, match="vocab"):
        bad_cfg = dataclasses.replace(cfg, vocab_size=cfg.vocab_size + 1)
        SpecEngine(params, cfg, SCFG, params, bad_cfg,
                   registry=Registry())


def test_spec_profiler_partitions_latency_histograms(setup, engine):
    """The continuous-profiler contract holds on the SPECULATIVE
    engine too: attaching a profiler drives real capture windows, a
    captured round's latency lands in serve_profiled_step_seconds
    (never the gated histogram the SLO/latency gates judge), and the
    two partitions cover every dispatched round exactly.  The
    classifier builds from the VERIFY program — the target's
    per-round work."""
    from apex_tpu.obs import contprof

    cfg, params, prompts = setup
    eng = engine
    reg = eng.metrics
    gated_before = reg.histogram("serve_decode_step_seconds").count
    prof = contprof.serve_profiler(
        eng, config=contprof.ContProfConfig(
            capture_every=3, capture_steps=2, warmup_steps=1,
            max_windows=1, max_overhead_pct=None))
    try:
        rounds_before = eng._steps_dispatched
        for i, p in enumerate(prompts[:2]):
            eng.submit(Request(uid=f"prof{i}", prompt=p,
                               max_new_tokens=16))
        eng.run()
        rounds = eng._steps_dispatched - rounds_before
        gated = reg.histogram("serve_decode_step_seconds").count \
            - gated_before
        profiled = reg.histogram("serve_profiled_step_seconds").count
        captured = sum(w["steps"] for w in prof.windows) \
            + sum(w["steps"] for w in prof.discarded)
        assert len(prof.windows) + len(prof.discarded) == 1
        assert profiled == captured == 2
        assert gated + profiled == rounds
        for w in prof.windows:
            assert w["total_ps"] > 0
    finally:
        eng.profiler = None


def test_verify_step_has_no_host_sync_or_retrace_hazard(setup, engine):
    """The syncs pass over the ACTUAL lowered b×(k+1) verify step: no
    host callback, no statically-bound numeric scalar (the runtime
    half is the one-trace pin above; the full pass matrix runs in the
    graph-lint ``serve_verify`` lane)."""
    eng = engine
    s = eng.sched
    k = eng.spec.k
    lowered = eng._verify_step.lower(
        eng.top, eng.stacked, eng.carry,
        jnp.zeros((s.num_slots, k), jnp.int32),
        jnp.asarray(s.last_tok), jnp.asarray(s.lengths),
        jnp.asarray(s.active), jnp.asarray(s.page_table),
        jnp.asarray(s.temperature), jnp.asarray(s.top_k),
        jnp.asarray(s.top_p))
    ctx = analysis.build_context(lowered, compile=True)
    rep = analysis.run_passes(ctx, passes=("syncs", "donation"))
    assert rep.ok, rep.format()
    assert not [f for f in rep.by_pass("syncs")
                if f.op in ("host-callback", "static-scalar")], \
        rep.format()
