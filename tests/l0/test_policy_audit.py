"""O1 policy-audit coverage (VERDICT r3 #6 / missing #3).

The reference's O1 guarantee is structural — the whole ``torch``
namespace is patched (``apex/amp/amp.py:68-177``), so no model can
escape the cast lists.  apex_tpu's guarantee is *checked* instead:
``amp.audit`` walks the lowered StableHLO and flags FP32-list work
executing in 16-bit.  These tests pin (a) the walker's parsing against
crafted StableHLO spellings, (b) that a policy-escaping model (raw
``jnp`` softmax on bf16) IS flagged, and (c) that all four in-tree
model families' O1 forwards audit clean — the continuously-enforced
version of the namespace-patch guarantee.
"""

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))

from apex_tpu import amp  # noqa: E402


# ---------------------------------------------------------------------------
# (a) parser pins on crafted StableHLO
# ---------------------------------------------------------------------------

def test_flags_16bit_blacklist_pointwise():
    txt = """
    %0 = stablehlo.exponential %a : tensor<8x16xbf16>
    %1 = stablehlo.log %b : tensor<4xf16>
    %2 = stablehlo.rsqrt %c : tensor<2x2xbf16>
    """
    rep = amp.audit_text(txt)
    assert not rep["ok"]
    ops = {(v["op"], v["dtype"]) for v in rep["violations"]}
    assert ops == {("exponential", "bf16"), ("log", "f16"),
                   ("rsqrt", "bf16")}


def test_fp32_blacklist_ops_are_clean():
    txt = """
    %0 = stablehlo.exponential %a : tensor<8x16xf32>
    %1 = stablehlo.log %b : tensor<4xf32>
    """
    assert amp.audit_text(txt)["ok"]


def test_half_safe_activations_not_flagged():
    # tanh/logistic/erf stay in autocast dtype in the reference too
    txt = """
    %0 = stablehlo.tanh %a : tensor<8xbf16>
    %1 = stablehlo.logistic %b : tensor<8xbf16>
    %2 = chlo.erf %c : tensor<8xbf16>
    """
    assert amp.audit_text(txt)["ok"]


def test_reduce_accumulation_dtype_rules():
    # max-reduce is exact in any dtype; add-reduce in bf16 is lossy;
    # jnp's own upcast pattern (f32 operand) is clean
    flagged = ("%0 = stablehlo.reduce(%x init: %c) applies stablehlo.add "
               "across dimensions = [1] : (tensor<8x16xbf16>, "
               "tensor<bf16>) -> tensor<8xbf16>")
    exact = flagged.replace("stablehlo.add", "stablehlo.maximum")
    upcast = flagged.replace("bf16", "f32")
    assert not amp.audit_text(flagged)["ok"]
    assert amp.audit_text(exact)["ok"]
    assert amp.audit_text(upcast)["ok"]
    rep = amp.audit_text(flagged)
    assert rep["violations"][0]["category"] == "16-bit accumulation"


def test_info_counters():
    txt = """
    %0 = stablehlo.dot_general %a, %b : (tensor<4x8xf32>, tensor<8x4xf32>) -> tensor<4x4xf32>
    %1 = stablehlo.convolution(%x, %w) : (tensor<1x8x8x3xbf16>, tensor<3x3x3x8xbf16>) -> tensor<1x8x8x8xbf16>
    %2 = stablehlo.custom_call @tpu_custom_call(%q) : (tensor<4xf32>) -> tensor<4xf32>
    """
    rep = amp.audit_text(txt)
    assert rep["ok"]
    assert rep["fp32_matmul_count"] == 1  # the bf16 conv is a half hit
    assert rep["custom_call_count"] == 1


def test_violation_aggregation_counts():
    txt = "\n".join("%%%d = stablehlo.exponential %%a : tensor<4xbf16>"
                    % i for i in range(3))
    rep = amp.audit_text(txt)
    assert len(rep["violations"]) == 1
    assert rep["violations"][0]["count"] == 3
    assert "exponential" in amp.format_report(rep)


# ---------------------------------------------------------------------------
# (b) a policy-escaping model IS caught end-to-end
# ---------------------------------------------------------------------------

def test_raw_jnp_softmax_escape_is_flagged():
    """A user model calling raw jax.nn.softmax on bf16 activations
    bypasses amp.ops — exactly the coverage gap the audit closes."""
    def escaped(w, x):
        h = jnp.matmul(x, w).astype(jnp.bfloat16)
        return jax.nn.softmax(h, axis=-1).astype(jnp.float32).sum()

    w = jnp.ones((8, 8), jnp.float32)
    x = jnp.ones((4, 8), jnp.float32)
    rep = amp.audit(escaped, w, x)
    assert not rep["ok"]
    assert any(v["op"] == "exponential" and v["dtype"] == "bf16"
               for v in rep["violations"])


def test_amp_ops_softmax_is_clean():
    """The same model through the policy layer audits clean: amp.ops
    casts softmax inputs to fp32 per the FP32 list."""
    from apex_tpu.amp import ops as amp_ops
    a = amp.initialize(opt_level="O1", verbosity=0)

    def policied(w, x):
        h = amp_ops.matmul(x, w)
        return amp_ops.softmax(h, axis=-1).astype(jnp.float32).sum()

    w = jnp.ones((8, 8), jnp.float32)
    x = jnp.ones((4, 8), jnp.float32)
    rep = amp.audit(lambda *args: a.run(policied, *args), w, x)
    assert rep["ok"], rep["violations"]


# ---------------------------------------------------------------------------
# (c) the four in-tree families' O1 forwards audit clean
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("family", ["mlp", "resnet", "gpt", "bert"])
def test_model_family_o1_forward_is_policy_clean(family):
    sys.path.insert(0, str(REPO / "tools"))
    import policy_audit
    fn, args = policy_audit.CASES[family]()
    rep = amp.audit(fn, *args)
    assert rep["ok"], (family, rep["violations"])


def test_region_form_reduce_is_flagged():
    """The generic (multi-result / custom-reducer) reduce prints its
    header without an ``applies`` clause — the adds live in a reducer
    REGION.  A bf16 accumulation in that form must still be flagged."""
    import jax.numpy as jnp
    from jax import lax

    def escaped(x):
        s, p = lax.reduce((x, x), (jnp.bfloat16(0), jnp.bfloat16(1)),
                          lambda a, b: (a[0] + b[0], a[1] * b[1]), (0,))
        return s.astype(jnp.float32).sum() + p.astype(jnp.float32).sum()

    rep = amp.audit(escaped, jnp.ones((8, 4), jnp.bfloat16))
    assert not rep["ok"]
    assert any(v["op"] == "reduce" and v["dtype"] == "bf16"
               for v in rep["violations"])


def test_region_form_max_reduce_is_clean():
    # an exact (max) reducer region must not trip the accumulation flag
    txt = """
    %0 = stablehlo.reduce(%arg0 init: %cst) across dimensions = [0] : (tensor<8x4xbf16>, tensor<bf16>) -> tensor<4xbf16>
     reducer(%a: tensor<bf16>, %b: tensor<bf16>) {
      %1 = stablehlo.maximum %a, %b : tensor<bf16>
      stablehlo.return %1 : tensor<bf16>
    }
    """
    assert amp.audit_text(txt)["ok"]


def test_ops_after_reducer_region_not_misattributed():
    # an add AFTER the region closes is a plain add, not an accumulation
    txt = """
    %0 = stablehlo.reduce(%arg0 init: %cst) across dimensions = [0] : (tensor<8x4xbf16>, tensor<bf16>) -> tensor<4xbf16>
     reducer(%a: tensor<bf16>, %b: tensor<bf16>) {
      %1 = stablehlo.maximum %a, %b : tensor<bf16>
      stablehlo.return %1 : tensor<bf16>
    }
    %2 = stablehlo.add %x, %y : tensor<4xbf16>
    """
    assert amp.audit_text(txt)["ok"]
