"""Type-promotion policy tests (port of ``tests/L0/run_amp/test_promotion.py``
and the add_param_group lifecycle, ``test_add_param_group.py:34-148``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import amp
from apex_tpu.amp import ops as amp_ops
from apex_tpu.amp.policy import resolve

HALF = jnp.bfloat16
PROPS = resolve(opt_level="O1", half_dtype=HALF)


def _ctx():
    return amp_ops.cast_context(PROPS)


def test_binary_promote_widest_type():
    h = jnp.ones((4,), HALF)
    f = jnp.ones((4,), jnp.float32)
    with _ctx():
        assert amp_ops.add(h, h).dtype == HALF
        assert amp_ops.add(h, f).dtype == jnp.float32
        assert amp_ops.mul(f, h).dtype == jnp.float32
        assert amp_ops.maximum(h, f).dtype == jnp.float32


def test_sequence_promote_cat_stack():
    h = jnp.ones((4,), HALF)
    f = jnp.ones((4,), jnp.float32)
    with _ctx():
        assert amp_ops.concatenate([h, h]).dtype == HALF
        assert amp_ops.concatenate([h, f]).dtype == jnp.float32
        assert amp_ops.stack([f, h]).dtype == jnp.float32


def test_banned_bce_raises_on_half():
    h = jnp.full((4,), 0.5, HALF)
    with _ctx():
        with pytest.raises(NotImplementedError):
            amp_ops.binary_cross_entropy(h, h)
    # fp32 inputs pass
    with _ctx():
        out = amp_ops.binary_cross_entropy(jnp.full((4,), 0.5),
                                           jnp.full((4,), 0.5))
        assert jnp.isfinite(out)


def test_disable_casts_suspends_policy():
    h = jnp.ones((4,), HALF)
    with _ctx():
        assert amp_ops.exp(h).dtype == jnp.float32      # blacklist casts up
        with amp_ops.disable_casts():
            assert amp_ops.exp(h).dtype == HALF          # passthrough


def test_no_policy_is_passthrough():
    h = jnp.ones((4,), HALF)
    assert amp_ops.add(h, jnp.ones((4,), jnp.float32)).dtype == jnp.float32
    assert amp_ops.exp(h).dtype == HALF


# --- add_param_group lifecycle (reference test_add_param_group.py) ---------


def _loss(params, x):
    out = x
    for k in sorted(params):
        out = out @ params[k]["w"]
    return jnp.sum(jnp.square(out))


def test_add_params_preserves_existing_optimizer_state():
    from apex_tpu.optimizers import FusedAdam
    rng = np.random.RandomState(0)
    p0 = {"g0": {"w": jnp.asarray(rng.randn(8, 8).astype(np.float32))}}
    x = jnp.asarray(rng.randn(16, 8).astype(np.float32))

    a = amp.initialize(optimizer=FusedAdam(lr=1e-2), opt_level="O2",
                       verbosity=0)
    state = a.init(p0)
    step = jax.jit(amp.make_train_step(a, _loss))
    for _ in range(3):
        state, _ = step(state, x)

    p1 = {"g1": {"w": jnp.asarray(rng.randn(8, 8).astype(np.float32))}}
    state2 = a.add_params(state, p1)
    assert set(state2.master_params) == {"g0", "g1"}
    # existing moments grafted, not reset — and the graft must actually
    # cover array leaves (a vacuous match set would hide a total reset)
    flat2 = {jax.tree_util.keystr(k): v for k, v in
             jax.tree_util.tree_leaves_with_path(state2.opt_state)}
    flat1 = {jax.tree_util.keystr(k): v for k, v in
             jax.tree_util.tree_leaves_with_path(state.opt_state)}
    matched = 0
    for key, old in flat1.items():
        if hasattr(old, "shape") and old.shape and key in flat2:
            np.testing.assert_array_equal(np.asarray(flat2[key]),
                                          np.asarray(old))
            matched += 1
    assert matched >= 2, "graft matched no moment arrays"
    # training continues over the union
    step2 = jax.jit(amp.make_train_step(a, _loss))
    state3, metrics = step2(state2, x)
    assert bool(jnp.isfinite(metrics["loss"]))
    assert not np.allclose(np.asarray(state3.master_params["g1"]["w"]),
                           np.asarray(state2.master_params["g1"]["w"]))


def _decoupled_loss(params, x):
    # each group's gradient is independent of the others
    return sum(jnp.sum(jnp.square(x @ params[k]["w"])) for k in sorted(params))


@pytest.mark.parametrize("opt", ["adam", "lamb"])
def test_add_params_new_group_starts_at_step_zero(opt):
    """Reference semantics (fused_adam.py:119-125 state per param /
    add_param_group): a group added mid-training starts bias correction at
    step 0 — its first update must be bit-identical to a fresh optimizer's
    first step on the same gradients."""
    from apex_tpu.optimizers import FusedAdam, FusedLAMB
    # LAMB's global-norm clip couples groups; disable it so the new
    # group's update depends only on its own gradients
    make = FusedAdam if opt == "adam" else \
        (lambda lr: FusedLAMB(lr=lr, max_grad_norm=0.0))
    rng = np.random.RandomState(1)
    w0 = jnp.asarray(rng.randn(8, 8).astype(np.float32))
    w1 = jnp.asarray(rng.randn(8, 8).astype(np.float32))
    x = jnp.asarray(rng.randn(16, 8).astype(np.float32))

    a = amp.initialize(optimizer=make(lr=1e-2), opt_level="O2", verbosity=0)
    state = a.init({"g0": {"w": w0}})
    step = jax.jit(amp.make_train_step(a, _decoupled_loss))
    for _ in range(3):
        state, _ = step(state, x)
    state = a.add_params(state, {"g1": {"w": w1}})
    state_after, _ = step(state, x)

    # per-leaf counters: existing group at 4, new group at 1
    ls = state_after.opt_state.leaf_step
    assert int(ls["g0"]["w"]) == 4
    assert int(ls["g1"]["w"]) == 1
    assert int(state_after.opt_state.step) == 4  # global schedule counter

    # fresh optimizer, first step on g1 alone: identical update
    b = amp.initialize(optimizer=make(lr=1e-2), opt_level="O2", verbosity=0)
    fresh = b.init({"g1": {"w": w1}})
    fresh_after, _ = jax.jit(amp.make_train_step(b, _decoupled_loss))(
        fresh, x)
    np.testing.assert_array_equal(
        np.asarray(state_after.master_params["g1"]["w"]),
        np.asarray(fresh_after.master_params["g1"]["w"]))


def test_add_params_rejects_overlap_and_nondict():
    a = amp.initialize(optimizer=optax.sgd(0.1), opt_level="O2",
                       verbosity=0)
    state = a.init({"g0": jnp.ones((2,))})
    with pytest.raises(ValueError):
        a.add_params(state, {"g0": jnp.ones((2,))})
    with pytest.raises(TypeError):
        a.add_params(state, [jnp.ones((2,))])
