"""FusedAdam conformance tests.

Port of ``tests/L0/run_mixed_adam/test_mixed_adam.py:8-179``: reference-vs-
fused param drift below 1e-3 over 7 iterations, multiple dtypes/options, and
the flat-buffer FP16Optimizer behaviors (``test_fp16_optimizer.py:33-129``)
including grad clipping and overflow skip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.optimizers import (
    FP16Optimizer,
    FusedAdam,
    adam_step,
    fused_adam,
)


def tree_randn(key, shapes):
    keys = jax.random.split(key, len(shapes))
    return {f"p{i}": jax.random.normal(k, s, jnp.float32)
            for i, (k, s) in enumerate(zip(keys, shapes))}


SHAPES = [(17,), (64, 31), (128,)]


def run_fused(params, grads_seq, **kw):
    tx = fused_adam(learning_rate=1e-3, **kw)
    state = tx.init(params)
    for g in grads_seq:
        updates, state = tx.update(g, state, params)
        params = optax.apply_updates(params, updates)
    return params


def run_optax(params, grads_seq, weight_decay=0.0):
    # optax adam: eps outside sqrt? optax uses eps added after sqrt -> same
    # as our EPS_MODE_OUTSIDE default.
    tx = optax.adam(1e-3, b1=0.9, b2=0.999, eps=1e-8)
    state = tx.init(params)
    for g in grads_seq:
        if weight_decay:
            g = jax.tree.map(lambda gg, p: gg + weight_decay * p, g, params)
        updates, state = tx.update(g, state, params)
        params = optax.apply_updates(params, updates)
    return params


def max_abs_diff(a, b):
    return max(float(jnp.max(jnp.abs(x - y)))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


@pytest.mark.parametrize("weight_decay", [0.0, 0.01])
def test_drift_vs_reference_adam(weight_decay):
    key = jax.random.PRNGKey(0)
    params = tree_randn(key, SHAPES)
    grads_seq = [tree_randn(jax.random.PRNGKey(i + 1), SHAPES)
                 for i in range(7)]
    fused = run_fused(params, grads_seq, weight_decay=weight_decay)
    ref = run_optax(params, grads_seq, weight_decay=weight_decay)
    assert max_abs_diff(fused, ref) < 1e-3


def test_scale_descales_grads():
    params = {"w": jnp.ones((32,), jnp.float32)}
    g = {"w": jnp.full((32,), 8.0, jnp.float32)}
    a = run_fused(params, [g], scale=8.0)
    b = run_fused(params, [{"w": jnp.ones((32,), jnp.float32)}])
    assert max_abs_diff(a, b) < 1e-7


def test_eps_mode_inside():
    params = {"w": jnp.ones((16,), jnp.float32)}
    g = {"w": jnp.ones((16,), jnp.float32)}
    out_in = run_fused(params, [g], eps_inside_sqrt=True)
    out_out = run_fused(params, [g], eps_inside_sqrt=False)
    # modes differ slightly but both step in the same direction
    assert max_abs_diff(out_in, out_out) < 1e-3
    assert float(out_in["w"][0]) < 1.0 and float(out_out["w"][0]) < 1.0


@pytest.mark.parametrize("n_pads", [2, 4])
def test_adam_step_pallas_matches_jnp(monkeypatch, n_pads):
    # n_pads=2 -> 16 rows (the 8-row tile-floor blocks); n_pads=4 ->
    # 32 rows (the larger 32-row blocks) — both grid geometries pinned
    from apex_tpu.ops.pallas.adam_kernel import ADAM_PAD
    n = ADAM_PAD * n_pads
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.randn(n).astype(np.float32))
    m = jnp.asarray(rng.rand(n).astype(np.float32))
    v = jnp.asarray(rng.rand(n).astype(np.float32))
    g = jnp.asarray(rng.randn(n).astype(np.float32))
    kw = dict(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
              step=jnp.asarray(3, jnp.int32), scale=2.0, weight_decay=0.01,
              p_copy_dtype=jnp.bfloat16)
    monkeypatch.setenv("APEX_TPU_KERNELS", "jnp")
    ref = adam_step(p, m, v, g, **kw)
    monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")
    got = adam_step(p, m, v, g, **kw)
    for r, o in zip(ref, got):
        assert r.dtype == o.dtype
        np.testing.assert_allclose(np.asarray(r, np.float32),
                                   np.asarray(o, np.float32),
                                   rtol=1e-5, atol=1e-6)


class TestFP16Optimizer:
    def make(self, **kw):
        params = {"a": jnp.ones((33,), jnp.float32) * 0.5,
                  "b": jnp.ones((8, 9), jnp.float32)}
        opt = FP16Optimizer(params, lr=1e-2, **kw)
        return params, opt, opt.init()

    def test_step_moves_params(self):
        params, opt, state = self.make()
        grads = jax.tree.map(lambda p: jnp.ones_like(p, jnp.bfloat16),
                             opt.model_params(state))
        state, params_half, info = opt.step(state, grads)
        assert not bool(info["overflow"])
        assert params_half["a"].dtype == jnp.bfloat16
        assert float(params_half["a"][0]) < 0.5

    def test_overflow_skips(self):
        params, opt, state = self.make(dynamic_loss_scale=True)
        before = np.asarray(state.master)
        grads = jax.tree.map(
            lambda p: jnp.full(p.shape, jnp.inf, jnp.bfloat16),
            opt.model_params(state))
        state, _, info = opt.step(state, grads)
        assert bool(info["overflow"])
        np.testing.assert_array_equal(before, np.asarray(state.master))
        assert float(state.scaler_state.loss_scale) == 2.0 ** 15
        assert int(state.step) == 0

    def test_loss_scale_descale(self):
        # grads arrive pre-scaled by the loss scale; step result must match
        # an unscaled run (fp16_optimizer.py combined_scale semantics).
        params, opt_s, state_s = self.make(static_loss_scale=4.0)
        _, opt_u, state_u = self.make(static_loss_scale=1.0)
        g = jax.tree.map(lambda p: jnp.ones_like(p, jnp.float32),
                         opt_s.model_params(state_s))
        g4 = jax.tree.map(lambda x: x * 4.0, g)
        state_s, ph_s, _ = opt_s.step(state_s, g4)
        state_u, ph_u, _ = opt_u.step(state_u, g)
        np.testing.assert_allclose(np.asarray(state_s.master),
                                   np.asarray(state_u.master), rtol=1e-6)

    def test_grad_clipping_via_combined_scale(self):
        params, opt, state = self.make(max_grad_norm=1.0)
        big = jax.tree.map(lambda p: jnp.full(p.shape, 10.0, jnp.float32),
                           opt.model_params(state))
        state2, _, info = opt.step(state, big)
        # total numel = 33 + 72 = 105; norm = 10*sqrt(105) >> 1 → clipped.
        # effective grad after clip has norm 1 → max step ~ lr
        delta = np.abs(np.asarray(state2.master) - np.asarray(state.master))
        assert delta.max() <= 1e-2 + 1e-6

    def test_state_dict_roundtrip(self):
        params, opt, state = self.make(dynamic_loss_scale=True)
        grads = jax.tree.map(lambda p: jnp.ones_like(p, jnp.bfloat16),
                             opt.model_params(state))
        state, _, _ = opt.step(state, grads)
        d = opt.state_dict(state)
        restored = opt.load_state_dict(d)
        np.testing.assert_array_equal(np.asarray(state.master),
                                      np.asarray(restored.master))
        assert float(restored.scaler_state.loss_scale) == \
            float(state.scaler_state.loss_scale)


def _bitwise_trees(kind):
    rng = np.random.RandomState(7)
    mk = lambda *s: jnp.asarray(rng.randn(*s).astype(np.float32))
    if kind == "mixed":
        params = {"w": mk(17, 9), "b": mk(33),
                  "s": jnp.asarray(0.7, jnp.float32), "t": mk(2, 3, 5)}
        grads = {"w": mk(17, 9), "b": mk(33),
                 "s": jnp.asarray(0.2, jnp.float32), "t": mk(2, 3, 5)}
        return params, grads
    # "ragged": 11 leaves -> 13 aligned chunks (one leaf spans 3), so the
    # retuned kernel's 8-chunk grid steps leave a RAGGED tail block (13 %
    # 8 = 5) riding the padded step table — plus single-tile leaves (one
    # exact chunk) and an exactly-two-chunk leaf (empty tail within the
    # leaf).  The geometry axis the round-6 retune added must stay
    # invisible to the math.
    shapes = [(1024,), (2048,), (2100,), (64,), (5,), (8, 16), (1,),
              (33,), (128,), (7, 3), (512,)]
    params = {f"p{i}": mk(*s) for i, s in enumerate(shapes)}
    grads = {f"p{i}": mk(*s) for i, s in enumerate(shapes)}
    return params, grads


@pytest.mark.parametrize("tree", ["mixed", "ragged"])
def test_packed_tree_update_bitwise_matches_per_leaf(monkeypatch, tree):
    """The whole-tree packed path (one kernel pass over the aligned pack,
    per-tensor step sizes via the chunk->tensor table) must be BIT-identical
    to the per-leaf jnp path — the L1 ext-vs-no-ext conformance contract —
    across mixed shapes, a scalar leaf, weight decay, a non-unit scale,
    and (the round-6 geometry retune) a tree whose chunk count leaves a
    ragged tail under the multi-chunk grid blocks.

    The ragged tree is held to ONE ULP instead of bitwise: XLA's FMA
    contraction of the final ``p - step·m/denom`` differs between the
    per-leaf fusion and the kernel graph for a handful of elements at
    these shapes — measured identically on the PRE-retune kernel (seed),
    so it is a property of the two jit graphs, not of the geometry; the
    geometry axis itself is pinned bit-exact in
    test_kernel_geometry.py::test_packed_adam_block_override_is_pure_geometry."""
    from apex_tpu.optimizers.fused_adam import fused_adam

    params, grads = _bitwise_trees(tree)
    tx = fused_adam(learning_rate=3e-3, weight_decay=0.01, scale=128.0)

    # both paths under jit: XLA's FMA contraction must apply to both or
    # neither for a bitwise comparison (training always runs jitted).
    # Distinct lambdas: jax.jit caches traces by function identity, and the
    # kernel-path choice is baked in at trace time.
    monkeypatch.setenv("APEX_TPU_KERNELS", "jnp")
    state = tx.init(params)
    u_ref, s_ref = jax.jit(lambda g, s, p: tx.update(g, s, p))(
        grads, state, params)

    monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")
    monkeypatch.setenv("APEX_TPU_ADAM_PACKED", "1")
    # confirm the packed path actually engages (sys.modules: the package
    # attr "fused_adam" is the function, shadowing the submodule)
    import sys
    fa = sys.modules["apex_tpu.optimizers.fused_adam"]
    called = {}
    orig = fa._packed_tree_update

    def spy(*a, **k):
        called["x"] = True
        return orig(*a, **k)

    monkeypatch.setattr(fa, "_packed_tree_update", spy)
    u_got, s_got = jax.jit(lambda g, s, p: tx.update(g, s, p))(
        grads, state, params)
    assert called, "packed tree path did not engage under pallas mode"

    for r, o in zip(jax.tree.leaves((u_ref, s_ref.m, s_ref.v)),
                    jax.tree.leaves((u_got, s_got.m, s_got.v))):
        if tree == "mixed":
            np.testing.assert_array_equal(np.asarray(r), np.asarray(o))
        else:
            # ragged: one-ulp FMA-contraction slack (see docstring).
            # The slack is ABSOLUTE at the O(1) param scale: the compared
            # updates are deltas (new_p - p), so a 1-ulp difference in
            # new_p surfaces as ~1e-5 RELATIVE to the small delta.
            np.testing.assert_allclose(np.asarray(r), np.asarray(o),
                                       rtol=2e-7, atol=1.2e-7)
    assert jax.tree.all(jax.tree.map(
        lambda a, b: bool((a == b).all()), s_ref.leaf_step, s_got.leaf_step))
