"""Lint-gated AOT export (ISSUE 10): ``apex_tpu.analysis.export`` +
``tools/aot_export.py``.

The acceptance path lives here: the mlp train lane exports through the
full gate matrix, reloads from the content-addressed cache in a FRESH
process (subprocess --verify-reload), and the reloaded executable's
outputs are BITWISE equal to the freshly compiled ones; a seeded
``io_callback`` lane is refused with the documented
``export-host-callback`` finding id; cache invalidation (key mismatch
on mesh/policy/jax-version → miss + fallback compile) and corruption
(truncated or bit-flipped entry → skipped with a warning) are pinned;
and the committed EXPORT_r01.json stays schema-valid.
"""

import json
import pickle
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

from apex_tpu import analysis  # noqa: E402
from apex_tpu.analysis import export as aot  # noqa: E402
from apex_tpu.analysis import export_schema  # noqa: E402

import aot_export  # noqa: E402


# ---------------------------------------------------------------------------
# the export-compat pass
# ---------------------------------------------------------------------------

def test_io_callback_fires_export_host_callback():
    from jax.experimental import io_callback

    def step(x):
        y = x * 2.0
        io_callback(lambda v: None, None, y.sum(), ordered=True)
        return y.sum()

    rep = analysis.analyze(step, jnp.ones((8, 8)),
                           passes=("export-compat",), compile=False)
    assert not rep.ok
    assert any(f.op == "export-host-callback" for f in rep.errors)


def test_platform_custom_call_fires_and_allowlist_is_quiet():
    line = ('  %0 = stablehlo.custom_call @lapack_sgeqrf'
            '(%arg0) : (tensor<4x4xf32>) -> tensor<4x4xf32>')
    ctx = analysis.PassContext(stablehlo_text=line)
    out = analysis.PASSES["export-compat"](ctx)
    assert len(out) == 1 and out[0].op == "export-platform-call"
    ok_line = ('  %0 = stablehlo.custom_call @Sharding(%arg0) : '
               '(tensor<4x4xf32>) -> tensor<4x4xf32>')
    assert analysis.PASSES["export-compat"](
        analysis.PassContext(stablehlo_text=ok_line)) == []


def test_infeed_fires_export_host_callback():
    ctx = analysis.PassContext(
        stablehlo_text='  %0 = "stablehlo.infeed"(%tok) : ...')
    out = analysis.PASSES["export-compat"](ctx)
    assert len(out) == 1 and out[0].op == "export-host-callback"


def test_static_capture_fires():
    jitted = jax.jit(lambda x, n: x * n, static_argnums=(1,))
    rep = analysis.analyze(jitted, jnp.ones((4,)), 3,
                           passes=("export-compat",), compile=False)
    assert not rep.ok
    assert any(f.op == "export-static-capture" for f in rep.errors)


def test_baked_constant_fires_and_clean_program_is_quiet():
    big = jax.random.normal(jax.random.PRNGKey(0), (512, 640))
    rep = analysis.analyze(lambda x: x @ big, jnp.ones((4, 512)),
                           passes=("export-compat",), compile=False)
    assert not rep.ok
    assert any(f.op == "export-baked-constant"
               and f.bytes == 512 * 640 * 4 for f in rep.errors)
    rep2 = analysis.analyze(lambda x, w: x @ w, jnp.ones((4, 512)), big,
                            passes=("export-compat",), compile=False)
    assert rep2.ok and not rep2.findings


# ---------------------------------------------------------------------------
# cache-key derivation: any part drift is a different key
# ---------------------------------------------------------------------------

def test_key_parts_discriminate_module_mesh_policy_version():
    from apex_tpu.amp import policy as policy_lib
    o1 = policy_lib.resolve(opt_level="O1")
    o2 = policy_lib.resolve(opt_level="O2")
    base = aot.key_parts("module text", mesh="cpu[1]", policy=o1)
    same = aot.key_parts("module text", mesh="cpu[1]", policy=o1)
    assert aot.cache_key(base) == aot.cache_key(same)
    for other in (
            aot.key_parts("module text 2", mesh="cpu[1]", policy=o1),
            aot.key_parts("module text", mesh="tpu[8]", policy=o1),
            aot.key_parts("module text", mesh="cpu[1]", policy=o2),
            aot.key_parts("module text", mesh="cpu[1]", policy=o1,
                          versions={"jax": "9.9.9", "jaxlib": "9.9.9",
                                    "backend": "cpu"})):
        assert aot.cache_key(other) != aot.cache_key(base)


# ---------------------------------------------------------------------------
# write/load invariants: an executable enters AND leaves the cache clean
# ---------------------------------------------------------------------------

def _small_exported(cache_dir):
    """Export a tiny clean program; returns (key, parts, compiled,
    args)."""
    jitted = jax.jit(lambda x, y: {"s": (x @ y).sum(), "p": x + y})
    args = (jnp.ones((16, 16)), jnp.full((16, 16), 2.0))
    lowered = aot.lower_quiet(jitted, *args)
    compiled = lowered.compile()
    ctx = analysis.build_context(lowered)
    report = analysis.run_passes(
        ctx, passes=("donation", "constant-capture", "syncs",
                     "export-compat"))
    parts = aot.key_parts(lowered.as_text(),
                          mesh=aot.mesh_descriptor(lowered))
    key = aot.cache_key(parts)
    aot.write_entry(cache_dir, key, parts, compiled, report,
                    lane="unit")
    return key, parts, compiled, args


def test_write_refuses_dirty_report(tmp_path):
    jitted = jax.jit(lambda x: x * 2)
    compiled = jitted.lower(jnp.ones((4,))).compile()
    dirty = analysis.Report(
        (analysis.Finding("export-compat", "error", "seeded",
                          op="export-host-callback"),),
        ("export-compat",))
    with pytest.raises(aot.ExportRefused) as e:
        aot.write_entry(tmp_path, "k" * 64, {}, compiled, dirty)
    assert e.value.finding_id == "export-host-callback"
    # ...and a clean report WITHOUT the export-compat pass is refused
    # too: serializability is part of the gate
    clean_but_unchecked = analysis.Report((), ("donation",))
    with pytest.raises(aot.ExportRefused) as e2:
        aot.write_entry(tmp_path, "k" * 64, {}, compiled,
                        clean_but_unchecked)
    assert e2.value.finding_id == "export-compat-not-run"
    assert not any(tmp_path.iterdir())   # nothing entered the cache


def test_round_trip_hit_is_bitwise_equal(tmp_path):
    key, parts, compiled, args = _small_exported(tmp_path)
    hit = aot.load_entry(tmp_path, key)
    assert hit is not None
    loaded, manifest = hit
    assert manifest["key"] == key and manifest["lint"]["ok"]
    o1, o2 = compiled(*args), loaded(*args)
    for a, b in zip(jax.tree.leaves(o1), jax.tree.leaves(o2)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_plain_miss_is_silent(tmp_path):
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("error")   # any warning would fail
        assert aot.load_entry(tmp_path, "0" * 64) is None


@pytest.mark.parametrize("corruption", ["bitflip", "truncate",
                                        "manifest_lint", "manifest_key"])
def test_corrupt_entry_skipped_with_warning(tmp_path, corruption):
    key, _, compiled, args = _small_exported(tmp_path)
    entry = tmp_path / key
    blob_path = entry / "executable.bin"
    if corruption == "bitflip":
        raw = bytearray(blob_path.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        blob_path.write_bytes(bytes(raw))
    elif corruption == "truncate":
        blob_path.write_bytes(blob_path.read_bytes()[:100])
    elif corruption == "manifest_lint":
        doc = json.loads((entry / "manifest.json").read_text())
        doc["lint"]["ok"] = False     # a dirty gate must not serve
        (entry / "manifest.json").write_text(json.dumps(doc))
    elif corruption == "manifest_key":
        doc = json.loads((entry / "manifest.json").read_text())
        doc["key_parts"]["mesh"] = "tpu[8]"   # parts no longer hash
        (entry / "manifest.json").write_text(json.dumps(doc))
    with pytest.warns(RuntimeWarning, match="skipped"):
        assert aot.load_entry(tmp_path, key) is None
    # ...and probe falls back to a fresh compile on the same key
    jitted = jax.jit(lambda x, y: {"s": (x @ y).sum(), "p": x + y})
    with pytest.warns(RuntimeWarning):
        compiled2, info = aot.probe(jitted, *args,
                                    cache_dir=str(tmp_path))
    assert info["source"] == "compile"
    o1, o2 = compiled(*args), compiled2(*args)
    for a, b in zip(jax.tree.leaves(o1), jax.tree.leaves(o2)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_write_entry_same_key_keeps_existing(tmp_path):
    """Same key == same content: a second writer must keep the
    existing complete entry (never replace it under a concurrent
    reader's feet) and still report success."""
    key, _, _, _ = _small_exported(tmp_path)
    manifest_path = tmp_path / key / "manifest.json"
    before = manifest_path.read_text()
    key2, _, _, _ = _small_exported(tmp_path)   # same program again
    assert key2 == key
    assert manifest_path.read_text() == before  # untouched, not rewritten
    assert aot.load_entry(tmp_path, key) is not None


def test_write_entry_heals_poisoned_entry(tmp_path):
    """A corrupt entry (truncated blob under an intact manifest) made
    the caller miss — re-export under the same key must REBUILD it,
    or the poison would force every future replica through a fresh
    compile forever."""
    key, _, _, _ = _small_exported(tmp_path)
    blob_path = tmp_path / key / "executable.bin"
    blob_path.write_bytes(blob_path.read_bytes()[:50])
    with pytest.warns(RuntimeWarning):
        assert aot.load_entry(tmp_path, key) is None
    key2, _, _, _ = _small_exported(tmp_path)   # heals, not keeps
    assert key2 == key
    assert aot.load_entry(tmp_path, key) is not None


# ---------------------------------------------------------------------------
# probe: hit/miss semantics and key invalidation
# ---------------------------------------------------------------------------


def test_probe_refuses_static_capture_from_cache(tmp_path):
    """The gate path sees static captures exactly as analyze() does: a
    jit specialized on a statically-bound scalar is refused with the
    documented id — otherwise the cache would mint one entry per
    value."""
    jitted = jax.jit(lambda x, n: x * n, static_argnums=(1,))
    x = jnp.ones((4,))
    _, info = aot.probe(jitted, x, 3, cache_dir=str(tmp_path),
                        export_on_miss=True,
                        gate_passes=("export-compat",))
    assert info["source"] == "compile"
    assert info["exported"] is False
    assert info["refused"] == "export-static-capture"
    assert aot.list_entries(tmp_path) == []

def test_probe_miss_exports_then_hits_bitwise(tmp_path):
    jitted = jax.jit(lambda x: (x * 3).sum())
    x = jnp.arange(64, dtype=jnp.float32)
    c1, i1 = aot.probe(jitted, x, cache_dir=str(tmp_path),
                       export_on_miss=True,
                       gate_passes=("donation", "constant-capture",
                                    "syncs", "export-compat"))
    assert i1["source"] == "compile" and i1["exported"] is True
    c2, i2 = aot.probe(jitted, x, cache_dir=str(tmp_path))
    assert i2["source"] == "cache" and i2["key"] == i1["key"]
    assert np.asarray(c1(x)).tobytes() == np.asarray(c2(x)).tobytes()


def test_probe_key_mismatch_on_mesh_policy_version_misses(tmp_path,
                                                          monkeypatch):
    from apex_tpu.amp import policy as policy_lib
    jitted = jax.jit(lambda x: (x * 3).sum())
    x = jnp.arange(64, dtype=jnp.float32)
    gate = ("donation", "constant-capture", "syncs", "export-compat")
    _, i1 = aot.probe(jitted, x, cache_dir=str(tmp_path),
                      export_on_miss=True, gate_passes=gate)
    assert i1["exported"] is True
    # same everything → hit
    _, hit = aot.probe(jitted, x, cache_dir=str(tmp_path))
    assert hit["source"] == "cache"
    # a different mesh topology → different key → miss
    _, m1 = aot.probe(jitted, x, cache_dir=str(tmp_path),
                      mesh="tpu[8]")
    assert m1["source"] == "compile" and m1["key"] != i1["key"]
    # a different resolved policy → miss
    _, m2 = aot.probe(jitted, x, cache_dir=str(tmp_path),
                      policy=policy_lib.resolve(opt_level="O2"))
    assert m2["source"] == "compile" and m2["key"] != i1["key"]
    # a different jax version → miss (a PJRT executable is pinned)
    monkeypatch.setattr(aot, "runtime_versions",
                        lambda: {"jax": "9.9.9", "jaxlib": "9.9.9",
                                 "backend": "future"})
    _, m3 = aot.probe(jitted, x, cache_dir=str(tmp_path))
    assert m3["source"] == "compile" and m3["key"] != i1["key"]


# ---------------------------------------------------------------------------
# the tool: mlp lane round trip (fresh process) + the seeded refusal
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tool_cache(tmp_path_factory):
    """One mlp_o1 + seeded run of the tool's pipeline, shared by the
    round-trip and refusal tests (the mlp O2 + serve lanes ride the
    committed-artifact check and the slow full-tool test)."""
    cache = tmp_path_factory.mktemp("aot_cache")
    lanes = aot_export.run_lanes(["mlp_o1", "seeded"], str(cache))
    return cache, lanes


def test_mlp_lane_exports_clean(tool_cache):
    _, lanes = tool_cache
    rec = lanes["mlp_o1_train"]
    assert rec["export_ok"] and rec["lint"]["ok"]
    assert rec["bitwise_equal"] is True
    assert rec["compile_s"] > 0 and rec["load_s"] >= 0
    assert len(rec["cache_key"]) == 64


def test_seeded_io_callback_refused_with_documented_id(tool_cache):
    cache, lanes = tool_cache
    rec = lanes["seeded_io_callback"]
    assert rec["export_ok"] is False
    assert rec["refused"] == "export-host-callback"
    assert not rec["lint"]["ok"]
    # nothing of it entered the cache: every entry present is the mlp's
    assert all(m.get("lane") == "mlp_o1_train"
               for m in aot.list_entries(cache))


def test_reload_in_fresh_process_is_bitwise_equal(tool_cache,
                                                  tmp_path):
    """The acceptance round trip: a SEPARATE python process loads only
    the cache entry (no model build, no trace) and reproduces the
    exporting process's outputs bit for bit."""
    cache, lanes = tool_cache
    key = lanes["mlp_o1_train"]["cache_key"]
    jitted, args, _, _ = aot_export.build_lane("mlp_o1")
    compiled = jitted.lower(*args).compile()
    inputs = [np.asarray(x) for x in
              jax.tree.leaves(aot_export._copy_args(args))]
    out = compiled(*aot_export._copy_args(args))
    expected = [np.asarray(x) for x in jax.tree.leaves(out)]
    io_path = tmp_path / "io.pkl"
    with open(io_path, "wb") as f:
        pickle.dump({"inputs": inputs, "expected": expected}, f)
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "aot_export.py"),
         "--verify-reload", key, "--io", str(io_path),
         "--cache-dir", str(cache)],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict == {"hit": True, "bitwise_equal": True,
                       "lane": "mlp_o1_train"}


# ---------------------------------------------------------------------------
# serve engine + train-step startup probes
# ---------------------------------------------------------------------------

def _tiny_serve(cache):
    from apex_tpu import amp
    from apex_tpu.models.gpt import GPTModel, gpt_tiny
    from apex_tpu.serve import Request, ServeConfig, ServeEngine

    cfg = gpt_tiny()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    a = amp.initialize(opt_level="O2", verbosity=0)
    params = a.model_params_from(params)
    scfg = ServeConfig(num_slots=2, block_size=4, num_blocks=9,
                       max_blocks_per_slot=4, prefill_chunk=4,
                       aot_cache=cache)
    eng = ServeEngine(params, cfg, scfg)
    eng.submit(Request("a", np.arange(5), max_new_tokens=6))
    return eng, eng.run()


def test_serve_engine_probe_miss_then_hit_same_tokens(tmp_path):
    eng1, out1 = _tiny_serve(str(tmp_path))
    assert eng1.aot_info["source"] == "compile"
    assert eng1.aot_info["exported"] is True
    eng2, out2 = _tiny_serve(str(tmp_path))
    assert eng2.aot_info["source"] == "cache"
    assert eng2.aot_info["key"] == eng1.aot_info["key"]
    # one trace for the key-derivation lowering (content addressing
    # needs the module text), and none after: the loaded executable
    # serves the whole stream without another python-body execution
    assert eng2.trace_counts["decode"] == 1
    assert np.array_equal(out1["a"], out2["a"])


def test_serve_engine_env_cache_fallback(tmp_path, monkeypatch):
    """One env var enables the fleet: ``APEX_TPU_AOT_CACHE`` makes an
    engine with no explicit ``aot_cache`` probe (and populate) the
    shared cache."""
    monkeypatch.setenv("APEX_TPU_AOT_CACHE", str(tmp_path))
    eng, _ = _tiny_serve(None)
    assert eng.aot_info is not None
    assert eng.aot_info["source"] == "compile"
    assert eng.aot_info["exported"] is True
    assert aot.list_entries(tmp_path)
    # the lint/export lowering surface survives the probe: with the
    # env var set, graph_lint's serve lane (and the export tool) still
    # get a lowerable jit from the engine, never a Compiled
    assert hasattr(eng._decode_step, "lower")


def test_make_train_step_probe_miss_then_hit_bitwise(tmp_path):
    import policy_audit
    from apex_tpu import amp
    from apex_tpu.optimizers import FusedAdam

    loss_fn, p0, batch = policy_audit.RAW_CASES["mlp"]()
    a = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level="O1",
                       verbosity=0)

    def run(cache):
        state = a.init(p0)
        if cache is None:
            step = jax.jit(amp.make_train_step(a, loss_fn),
                           donate_argnums=0)
        else:
            step = amp.make_train_step(a, loss_fn, aot_cache=cache)
        for _ in range(2):
            state, metrics = step(state, *batch)
        return float(metrics["loss"]), getattr(step, "aot_info", None)

    l_miss, i_miss = run(str(tmp_path))
    assert i_miss["source"] == "compile" and i_miss["exported"]
    l_hit, i_hit = run(str(tmp_path))
    assert i_hit["source"] == "cache"
    l_plain, _ = run(None)
    assert l_miss == l_hit == l_plain


# ---------------------------------------------------------------------------
# the EXPORT schema + the committed artifact
# ---------------------------------------------------------------------------

def _valid_export_doc():
    key = "a" * 64
    return {
        "round": 1, "platform": "cpu",
        "versions": {"jax": "0.4.37"},
        "cache": {"dir": ".aot_cache", "entries": 1},
        "lanes": {
            "mlp_o1_train": {
                "export_ok": True, "cache_key": key,
                "module_sha256": "b" * 64,
                "lint": {"ok": True, "counts": {"info": 3}},
                "compile_s": 0.3, "load_s": 0.01, "load_ratio": 0.03,
                "bitwise_equal": True},
            "seeded_io_callback": {
                "export_ok": False,
                "refused": "export-host-callback",
                "lint": {"ok": False, "counts": {"error": 2}}},
        },
        "cold_start": {"lane": "mlp_o1_train", "compile_s": 0.3,
                       "load_s": 0.01, "load_ratio": 0.03,
                       "budget": 0.5, "ok": True},
    }


def test_export_schema_valid_doc_passes():
    assert export_schema.validate_export(_valid_export_doc()) == []


def test_export_schema_contradictory_verdicts_fail():
    # exported with a FAILING gating lint report
    doc = _valid_export_doc()
    doc["lanes"]["mlp_o1_train"]["lint"]["ok"] = False
    assert any("contradictory" in p
               for p in export_schema.validate_export(doc))
    # exported without a passing bitwise round trip
    doc = _valid_export_doc()
    doc["lanes"]["mlp_o1_train"]["bitwise_equal"] = False
    assert any("bitwise" in p
               for p in export_schema.validate_export(doc))
    # refused without the documented finding id
    doc = _valid_export_doc()
    del doc["lanes"]["seeded_io_callback"]["refused"]
    assert any("finding id" in p
               for p in export_schema.validate_export(doc))
    # cold_start 'ok' contradicting its own numbers
    doc = _valid_export_doc()
    doc["cold_start"]["load_ratio"] = 0.9
    assert any("cold_start" in p
               for p in export_schema.validate_export(doc))
    # no lanes at all
    assert any("lanes" in p
               for p in export_schema.validate_export(
                   {"round": 1, "platform": "cpu"}))


def test_emit_export_doc_is_schema_valid(tmp_path):
    doc = _valid_export_doc()
    lanes = doc["lanes"]
    lanes["serve_step"] = dict(lanes["mlp_o1_train"],
                               cache_key="c" * 64)
    out = tmp_path / "EXPORT_r77.json"
    problems = aot_export.emit_export(str(out), lanes, tmp_path)
    assert problems == 0
    assert export_schema.validate_export_file(str(out)) == []
    written = json.loads(out.read_text())
    assert written["cold_start"]["lane"] == "serve_step"
    assert written["round"] == 77


def test_committed_export_artifact_validates():
    """EXPORT_r01.json is the schema's reference instance: the mlp
    O1/O2 + serve lanes exported clean with passing round trips, the
    seeded violation refused with the documented id, and the serve
    cold-start gate (load <= 0.5x compile) holding."""
    arts = sorted(REPO.glob("EXPORT_r*.json"))
    assert arts, "no committed EXPORT_r*.json"
    doc = json.loads(arts[-1].read_text())
    assert export_schema.validate_export_file(str(arts[-1])) == []
    lanes = doc["lanes"]
    for name in ("mlp_o1_train", "mlp_o2_train", "serve_step"):
        assert lanes[name]["export_ok"] and \
            lanes[name]["bitwise_equal"], name
    assert lanes["seeded_io_callback"]["refused"] == \
        "export-host-callback"
    assert doc["cold_start"]["lane"] == "serve_step"
    assert doc["cold_start"]["ok"] is True
    assert doc["cold_start"]["load_ratio"] <= 0.5


# ---------------------------------------------------------------------------
# bench sources the cold-start gate from the artifact
# ---------------------------------------------------------------------------

def test_bench_cold_start_gate_reads_artifact(tmp_path):
    import bench
    # no artifact → nothing to gate
    assert bench.check_export_cold_start(str(tmp_path)) is None
    # a passing artifact → ok, numbers surfaced verbatim
    doc = _valid_export_doc()
    (tmp_path / "EXPORT_r01.json").write_text(json.dumps(doc))
    out = bench.check_export_cold_start(str(tmp_path))
    assert out["ok"] is True and out["load_ratio"] == 0.03
    assert out["artifact"] == "EXPORT_r01.json"
    # the newest round wins, and a violating ratio fails the gate
    # even when the artifact CLAIMS ok (bench re-derives the verdict)
    bad = _valid_export_doc()
    bad["cold_start"].update(load_ratio=0.9, ok=True)
    (tmp_path / "EXPORT_r02.json").write_text(json.dumps(bad))
    out2 = bench.check_export_cold_start(str(tmp_path))
    assert out2["artifact"] == "EXPORT_r02.json"
    assert out2["ok"] is False
    # ...and the absolute gate trips through gate_exit_code with or
    # without a --compare baseline
    rc = bench.gate_exit_code({"ok": True, "export_cold_start": out2},
                              compare_given=False)
    assert rc == 2
    rc_ok = bench.gate_exit_code({"ok": True,
                                  "export_cold_start": out},
                                 compare_given=False)
    assert rc_ok == 0
