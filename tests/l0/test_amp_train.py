"""End-to-end amp training tests — Milestone A of SURVEY.md §7.

The MNIST-MLP O1 run must track an fp32 reference run within tolerance
(BASELINE config 1), and the O0–O3 levels must produce the documented
dtype/master-weight behavior.  This is the port of the reference's
``test_multiple_models_optimizers_losses.py`` conformance axis, adapted to
tolerance-based comparison per SURVEY.md §7 "Bitwise L1 conformance".
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import amp
from apex_tpu.models.mlp import MLP, cross_entropy_loss


def make_data(key, n=64, dim=28 * 28, classes=10):
    kx, ky = jax.random.split(key)
    x = jax.random.normal(kx, (n, dim), jnp.float32)
    y = jax.random.randint(ky, (n,), 0, classes)
    return x, y


def train(opt_level, steps=20, lr=0.05, enabled=True, loss_scale=None):
    model = MLP(features=(64, 64))
    key = jax.random.PRNGKey(0)
    params = model.init(key, jnp.zeros((1, 28 * 28)))["params"]

    a = amp.initialize(
        apply_fn=lambda p, x: model.apply({"params": p}, x),
        optimizer=optax.sgd(lr),
        opt_level=opt_level, enabled=enabled, loss_scale=loss_scale,
        verbosity=0)
    state = a.init(params)

    step = jax.jit(amp.make_train_step(
        a, lambda p, x, y: cross_entropy_loss(
            model.apply({"params": p}, x), y)))

    x, y = make_data(jax.random.PRNGKey(1))  # fixed batch: loss must drop
    losses = []
    for i in range(steps):
        state, metrics = step(state, x, y)
        losses.append(float(metrics["loss"]))
    return np.array(losses), state, a


def test_o1_matches_fp32_reference():
    ref_losses, _, _ = train("O0")
    o1_losses, _, _ = train("O1")
    assert np.all(np.isfinite(o1_losses))
    # bf16 compute tracks fp32 loss curve within a loose tolerance
    np.testing.assert_allclose(o1_losses, ref_losses, rtol=0.1, atol=0.05)
    # and training actually works
    assert o1_losses[-1] < o1_losses[0] * 0.7


def test_o2_masters_stay_fp32_and_track_reference():
    ref_losses, _, _ = train("O0")
    o2_losses, state, a = train("O2")
    for leaf in jax.tree.leaves(state.master_params):
        assert leaf.dtype == jnp.float32
    compute = a.model_params(state)
    leaves = jax.tree.leaves(compute)
    assert any(l.dtype == jnp.bfloat16 for l in leaves)
    np.testing.assert_allclose(o2_losses, ref_losses, rtol=0.1, atol=0.05)


def test_o3_pure_half():
    _, state, a = train("O3")
    for leaf in jax.tree.leaves(a.model_params(state)):
        assert leaf.dtype == jnp.bfloat16


def test_disabled_passthrough():
    d_losses, state, _ = train("O1", enabled=False)
    ref_losses, _, _ = train("O0")
    np.testing.assert_allclose(d_losses, ref_losses, rtol=1e-5, atol=1e-6)


def test_overflow_skips_step_and_halves_scale():
    model = MLP(features=(16,))
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4)))["params"]
    a = amp.initialize(optimizer=optax.sgd(0.1), opt_level="O2", verbosity=0)
    state = a.init(params)
    before = jax.tree.leaves(state.master_params)[0]

    # Inject inf grads (the reference's planted-inf tests,
    # test_multiple_models_optimizers_losses.py:69-80).
    grads = jax.tree.map(lambda p: jnp.full(p.shape, jnp.inf, jnp.bfloat16),
                         a.model_params(state))
    state, info = jax.jit(a.apply_gradients)(state, grads)
    assert bool(info["overflow"])
    assert float(info["loss_scale"]) == 2.0 ** 15
    after = jax.tree.leaves(state.master_params)[0]
    np.testing.assert_array_equal(np.asarray(before), np.asarray(after))


def test_static_loss_scale_o2():
    losses, _, _ = train("O2", loss_scale=128.0)
    assert np.all(np.isfinite(losses))


def test_multiple_losses_independent_scalers():
    params = {"w": jnp.ones((4,), jnp.float32)}
    a = amp.initialize(optimizer=optax.sgd(0.1), opt_level="O2",
                       num_losses=2, verbosity=0)
    state = a.init(params)
    good = {"w": jnp.ones((4,), jnp.bfloat16)}
    bad = {"w": jnp.full((4,), jnp.inf, jnp.bfloat16)}
    state, info0 = a.apply_gradients(state, bad, loss_id=0)
    state, info1 = a.apply_gradients(state, good, loss_id=1)
    assert float(state.scaler_states[0].loss_scale) == 2.0 ** 15
    assert float(state.scaler_states[1].loss_scale) == 2.0 ** 16
