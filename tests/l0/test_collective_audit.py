"""The dryrun's HLO collective audit (``__graft_entry__._collective_audit``)
is the communication-volume regression surface for multi-chip configs
(VERDICT r2 item 8): pin its parsing against representative compiled-HLO
spellings — sync ops, async start/done pairs (counted once), and tuple
shapes — so audit numbers stay trustworthy."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from __graft_entry__ import _collective_audit  # noqa: E402


HLO = """
  %all-reduce.1 = f32[8,16]{1,0} all-reduce(f32[8,16]{1,0} %p), to_apply=%add
  %ag-start = (f32[4]{0}, f32[32]{0}) all-gather-start(f32[4]{0} %x), dimensions={0}
  %ag-done = f32[32]{0} all-gather-done((f32[4]{0}, f32[32]{0}) %ag-start)
  %cp = bf16[2,8]{1,0} collective-permute(bf16[2,8]{1,0} %y), source_target_pairs={{0,1}}
  %a2a = (f32[16]{0}) all-to-all(f32[16]{0} %z), dimensions={0}
  %rs = f32[4]{0} reduce-scatter(f32[32]{0} %w), dimensions={0}, to_apply=%add
  %not-a-collective = f32[8]{0} add(f32[8]{0} %a, f32[8]{0} %b)
"""

# Real-TPU spellings: tiled layouts embed parentheses inside the shape
# ({0:T(256)}), and async reduce-scatter's result is the SMALLEST tuple
# element (the operand is world_size x bigger).
HLO_TPU = """
  %ag2-start = (f32[4]{0:T(256)}, f32[32]{0:T(256)}) all-gather-start(f32[4]{0:T(256)} %x), dimensions={0}
  %ag2-done = f32[32]{0:T(256)} all-gather-done((f32[4]{0:T(256)}, f32[32]{0:T(256)}) %ag2-start)
  %rs2-start = (f32[32]{0:T(256)}, f32[4]{0:T(256)}) reduce-scatter-start(f32[32]{0:T(256)} %w), dimensions={0}, to_apply=%add
  %rs2-done = f32[4]{0:T(256)} reduce-scatter-done((f32[32]{0:T(256)}, f32[4]{0:T(256)}) %rs2-start)
"""


def test_counts_and_bytes():
    audit = _collective_audit(HLO)
    assert audit["all-reduce"] == {"count": 1, "bytes": 8 * 16 * 4}
    # async pair: only the -start's largest tuple element (the result
    # buffer) counts, so sync and async spellings audit identically,
    # and the -done is skipped
    assert audit["all-gather"] == {"count": 1, "bytes": 32 * 4}
    assert audit["collective-permute"] == {"count": 1, "bytes": 2 * 8 * 2}
    assert audit["all-to-all"] == {"count": 1, "bytes": 16 * 4}
    assert audit["reduce-scatter"] == {"count": 1, "bytes": 4 * 4}
    assert "add" not in audit and len(audit) == 5


def test_empty_program_has_no_collectives():
    assert _collective_audit("%r = f32[2]{0} add(%a, %b)") == {}


def test_tpu_tiled_layouts_and_async_reduce_scatter():
    """Async spellings with tiled layouts must audit the same bytes as
    their sync equivalents — all-gather picks the largest tuple element,
    reduce-scatter the smallest (its result is the small buffer)."""
    audit = _collective_audit(HLO_TPU)
    assert audit["all-gather"] == {"count": 1, "bytes": 32 * 4}
    assert audit["reduce-scatter"] == {"count": 1, "bytes": 4 * 4}
