"""tools/chaos_run.py smoke — tier-1 regression gate for the fault
injector itself (ISSUE 3 satellite): CPU, tiny model, two faults
(NaN-grad storm + checkpoint truncation), and the emitted artifact must
satisfy the incident schema gate hygiene enforces."""

import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO / "tools"))

import pytest

import chaos_run  # noqa: E402

from apex_tpu.resilience import validate_incident  # noqa: E402


def test_chaos_smoke_nan_storm_fast(tmp_path):
    """Tier-1 fast smoke (~3s): a nan-grad storm alone, fewer steps —
    the injector/rewind/incident path stays continuously enforced
    while the two-fault 27s run above rides ``-m slow`` (ROADMAP
    item 6's last named tier-1 heavy)."""
    out = tmp_path / "INCIDENT_fast_smoke.json"
    rc = chaos_run.main([
        "--steps", "8",
        "--faults", "nan_storm@3",
        "--checkpoint-every", "2",
        "--ckpt-dir", str(tmp_path / "ckpt"),
        "--out", str(out),
    ])
    assert rc == 0
    rec = json.loads(out.read_text())
    assert validate_incident(rec) == []
    assert rec["status"] == "recovered"
    assert "nan_storm" in json.dumps(rec)
    # the incident flight recorder (ISSUE 13): the dumped tail is
    # schema-valid (validate_incident above covered the shape) and
    # actually CONTAINS the injected fault's events — the nan-storm
    # firings and the rewind they forced — not just end-state gauges
    assert chaos_run.check_flight(rec, ["nan_storm@3"], 1) == []
    kinds = [e["kind"] for e in rec["flight"]["events"]]
    assert "rewind" in kinds and "fault" in kinds
    assert any(e.get("fault") == "nan_storm"
               for e in rec["flight"]["events"])
    # ring events stay ordered and bounded by the stated capacity
    ts = [e["ts"] for e in rec["flight"]["events"]]
    assert ts == sorted(ts)
    assert len(ts) <= rec["flight"]["capacity"]


def test_flight_survives_fault_payloads_with_kind_key(tmp_path):
    """Regression (review round): CorruptCheckpoint's injector event
    carries its own ``kind`` key ("truncate"); mirroring it into the
    flight ring must prefix the colliding field, not explode
    ``FlightRecorder.note``'s signature (which aborted the run
    mid-loop before the fix)."""
    out = tmp_path / "INCIDENT_kind_collision.json"
    rc = chaos_run.main([
        "--steps", "6",
        "--faults", "ckpt_truncate@2",
        "--checkpoint-every", "2",
        "--ckpt-dir", str(tmp_path / "ckpt"),
        "--out", str(out),
    ])
    assert rc == 0
    rec = json.loads(out.read_text())
    assert validate_incident(rec) == []
    faults = [e for e in rec["flight"]["events"]
              if e["kind"] == "fault"]
    assert any(e.get("fault") == "corrupt_checkpoint"
               and e.get("fault_kind") == "truncate" for e in faults)


def test_chaos_run_long_run_keeps_early_faults_in_tail(tmp_path):
    """Regression (review round): the flight ring is sized to the run
    so an early injected fault is never evicted from the tail
    check_flight judges the run by — a recovered long run must not
    exit 1 because its own black box forgot the crash."""
    out = tmp_path / "INCIDENT_long.json"
    rc = chaos_run.main([
        "--steps", "90",
        "--faults", "nan_storm@3:2",
        "--checkpoint-every", "30",
        "--patience", "2",
        "--ckpt-dir", str(tmp_path / "ckpt"),
        "--out", str(out),
    ])
    assert rc == 0
    rec = json.loads(out.read_text())
    assert rec["flight"]["capacity"] >= 90 * 4
    assert chaos_run.check_flight(rec, ["nan_storm@3:2"], None) == []


@pytest.mark.slow
def test_chaos_smoke_nan_storm_plus_truncation(tmp_path):
    out = tmp_path / "INCIDENT_chaos_smoke.json"
    rc = chaos_run.main([
        "--steps", "18",
        "--faults", "nan_storm@5", "ckpt_truncate@7",
        "--checkpoint-every", "3",
        "--ckpt-dir", str(tmp_path / "ckpt"),
        "--out", str(out),
    ])
    assert rc == 0
    rec = json.loads(out.read_text())
    assert validate_incident(rec) == []
    assert rec["status"] == "recovered"

    flat = json.dumps(rec)
    # both faults demonstrably fired...
    assert "nan_storm" in flat and "corrupt_checkpoint" in flat
    # ...and the loop rewound past the truncated snapshot
    assert '"event": "rewind"' in flat or '"rewind"' in flat


def test_parse_fault_specs():
    from apex_tpu.resilience import (CorruptCheckpoint, FlakyIO, HangStep,
                                     NaNStorm, Preempt, SlowIO)
    assert chaos_run.parse_fault("nan_storm@5") == NaNStorm(step=5,
                                                           duration=6)
    assert chaos_run.parse_fault("nan_storm@5:9") == NaNStorm(step=5,
                                                              duration=9)
    assert chaos_run.parse_fault("ckpt_truncate@7") == CorruptCheckpoint(
        step=7, kind="truncate")
    assert chaos_run.parse_fault("ckpt_corrupt@7") == CorruptCheckpoint(
        step=7, kind="corrupt")
    assert chaos_run.parse_fault("preempt@3") == Preempt(step=3)
    assert chaos_run.parse_fault("hang@2:0.5") == HangStep(step=2,
                                                           seconds=0.5)
    assert chaos_run.parse_fault("flaky_io:3") == FlakyIO(op="save", fails=3)
    assert chaos_run.parse_fault("slow_io:0.2") == SlowIO(op="save",
                                                          seconds=0.2)


def test_parse_fault_rank_kill_shared_vocabulary():
    """ISSUE 18 satellite: the fleet drill's rank-kill fault parses
    through the SAME grammar as every other spec (one injector
    vocabulary for the in-process and fleet lanes)."""
    from apex_tpu.resilience import RankKill
    assert chaos_run.parse_fault("rank_kill@10:1") == RankKill(step=10,
                                                              rank=1)
    assert chaos_run.parse_fault("rank_kill@4") == RankKill(step=4)
    with pytest.raises(SystemExit):
        chaos_run.parse_fault("rank_kill")       # a kill needs a step
    with pytest.raises(SystemExit):
        chaos_run.parse_fault("warp_core@3")     # unknown fault name


def test_fleet_lane_requires_exactly_one_rank_kill():
    """``--fleet`` refuses to start the multi-process drill without
    exactly one rank_kill fault (and nothing else): the other fault
    kinds are not SPMD-consistent across a real process mesh."""
    for faults in ([], ["nan_storm@3"], ["rank_kill@5", "rank_kill@9"],
                   ["rank_kill@5", "hang@2:0.5"]):
        with pytest.raises(SystemExit, match="exactly one rank_kill"):
            chaos_run.main(["--fleet", "--faults", *faults]
                           if faults else ["--fleet"])
