"""Continuous-batching serve engine (:mod:`apex_tpu.serve`).

The acceptance contracts: (a) a mixed stream of short/long requests
completes through continuous batching with per-request outputs
bitwise-equal to solo :func:`apex_tpu.models.generate.generate` runs;
(b) admission/retirement/preemption across the whole stream never
changes a compiled-step shape — ONE trace and one executable serve
everything (the runtime side of the static-shape contract; the
graph-lint serve lane checks it statically); (c) the fused sampling
epilogue draws on device with per-slot knobs that never retrace.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp, analysis
from apex_tpu.models import GPTModel, gpt_tiny
from apex_tpu.models.generate import generate
from apex_tpu.obs.metrics import Registry
from apex_tpu.serve import Request, ServeConfig, ServeEngine
from apex_tpu.serve.sampling import sample_tokens


@pytest.fixture(scope="module")
def setup():
    cfg = gpt_tiny()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    a = amp.initialize(opt_level="O2", verbosity=0)
    params = a.model_params_from(params)      # bf16 serving layout
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (n,))
               for n in (5, 12, 3, 20, 9)]
    return cfg, params, prompts


@pytest.fixture(scope="module")
def engine(setup):
    """ONE engine shared by the stream tests (tier-1 budget: each
    ServeEngine re-jits its closures, so every extra instance is a
    fresh XLA compile) — sharing it also makes the one-trace
    assertions cover the whole module's request history."""
    cfg, params, _ = setup
    scfg = ServeConfig(num_slots=2, block_size=4, num_blocks=17,
                       max_blocks_per_slot=8, prefill_chunk=4)
    # a private registry: the metric assertions below count THIS
    # engine's scripted history, not whatever else the process served
    return ServeEngine(params, cfg, scfg, registry=Registry())


def _solo(params, cfg, prompt, n):
    out = generate(params, cfg, jnp.asarray(prompt[None]), n)
    return np.asarray(out)[0, len(prompt):]


def test_mixed_stream_matches_solo_and_never_retraces(setup, engine):
    """The tier-1 serve smoke: admit 5 requests of mixed lengths into
    2 slots (continuous batching over a paged cache, greedy), outputs
    bitwise-equal to solo generate() per request, ONE decode trace and
    one compiled executable across every admit/retire boundary."""
    cfg, params, prompts = setup
    eng = engine
    news = (8, 6, 10, 4, 7)
    for i, (p, n) in enumerate(zip(prompts, news)):
        eng.submit(Request(uid=f"r{i}", prompt=p, max_new_tokens=n))
    out = eng.run()
    for i, (p, n) in enumerate(zip(prompts, news)):
        want = _solo(params, cfg, p, n)
        np.testing.assert_array_equal(out[f"r{i}"], want,
                                      err_msg=f"r{i} diverged from solo")
    # the static-shape contract, at runtime: one python-body execution
    # per program AND one compiled entry in the jit cache
    assert eng.trace_counts == {"decode": 1, "prefill": 1, "sample1": 1}
    assert eng._decode_step._cache_size() == 1
    assert eng._prefill_chunk._cache_size() == 1
    # telemetry (apex_tpu.obs): the counters match the scripted
    # stream — 5 admissions, 5 retirements, no preemption, every
    # generated token counted, and the decode-step histogram observed
    # every step (this is the histogram bench.py reads p50/p99 from)
    m = eng.metrics
    assert m.counter("serve_admissions_total").value == 5
    assert m.counter("serve_retirements_total").value == 5
    assert m.counter("serve_preemptions_total").value == 0
    assert m.counter("serve_tokens_total").value == sum(news)
    h = m.histogram("serve_decode_step_seconds")
    assert h.count > 0 and h.quantile(0.5) > 0
    # drained: gauges back to idle
    assert m.gauge("serve_queue_depth").value == 0
    assert m.gauge("serve_slot_occupancy").value == 0
    assert m.gauge("serve_block_utilization").value == 0


def test_decode_step_has_no_host_sync_or_retrace_hazard(setup):
    """The syncs pass (analysis/syncs.py retrace machinery) over the
    engine's ACTUAL lowered decode step: no host callback on the token
    loop, no statically-bound numeric scalar that would retrace."""
    cfg, params, prompts = setup
    scfg = ServeConfig(num_slots=2, block_size=4, num_blocks=9,
                       max_blocks_per_slot=4, prefill_chunk=4)
    eng = ServeEngine(params, cfg, scfg)
    s = eng.sched
    lowered = eng._decode_step.lower(
        eng.top, eng.stacked, eng.carry,
        jnp.asarray(s.last_tok), jnp.asarray(s.lengths),
        jnp.asarray(s.active), jnp.asarray(s.page_table),
        jnp.asarray(s.temperature), jnp.asarray(s.top_k),
        jnp.asarray(s.top_p))
    ctx = analysis.build_context(lowered, compile=True)
    rep = analysis.run_passes(ctx, passes=("syncs", "donation"))
    assert rep.ok, rep.format()
    assert not [f for f in rep.by_pass("syncs")
                if f.op in ("host-callback", "static-scalar")], \
        rep.format()


def test_preemption_recompute_preserves_outputs(setup):
    """Block pressure with a free slot preempts the youngest request
    (recompute-on-resume); every request — including the evicted one —
    still matches its solo run, and eviction fires exactly once (a
    continuation never evicts its evictor back)."""
    cfg, params, prompts = setup
    scfg = ServeConfig(num_slots=3, block_size=4, num_blocks=9,
                       max_blocks_per_slot=8, prefill_chunk=4)
    eng = ServeEngine(params, cfg, scfg, registry=Registry())
    preempts = []
    orig = eng.sched.preempt
    eng.sched.preempt = lambda slot, key: (preempts.append(slot),
                                           orig(slot, key))[1]
    reqs = [(prompts[0][:8], 8), (prompts[1][:8], 8), (prompts[3][:6], 6)]
    for i, (p, n) in enumerate(reqs):
        eng.submit(Request(uid=f"r{i}", prompt=p, max_new_tokens=n))
    out = eng.run()
    assert len(preempts) == 1
    for i, (p, n) in enumerate(reqs):
        np.testing.assert_array_equal(out[f"r{i}"],
                                      _solo(params, cfg, p, n))
    # pool bookkeeping drained clean
    assert eng.sched.allocator.live_count == 0
    # telemetry: 3 fresh admissions + 1 continuation re-admission,
    # exactly one preemption, 3 retirements (the preempted request
    # retires once, under its own uid)
    m = eng.metrics
    assert m.counter("serve_admissions_total").value == 4
    assert m.counter("serve_preemptions_total").value == 1
    assert m.counter("serve_retirements_total").value == 3


def test_submit_validation():
    """Scheduler-level admission validation needs no engine (and no
    jax): context overflow, empty prompt, zero budget, over-pool
    footprint."""
    from apex_tpu.serve import SlotScheduler
    sched = SlotScheduler(num_slots=2, num_blocks=9, block_size=4,
                          max_blocks_per_slot=4)          # context 16
    with pytest.raises(ValueError, match="context"):
        sched.submit(Request(uid="big",
                             prompt=np.zeros(20, np.int32),
                             max_new_tokens=8))           # 20 + 8 > 16
    with pytest.raises(ValueError, match="non-empty"):
        sched.submit(Request(uid="empty",
                             prompt=np.zeros(0, np.int32),
                             max_new_tokens=4))
    with pytest.raises(ValueError, match="non-empty"):
        sched.submit(Request(uid="zero",
                             prompt=np.zeros(4, np.int32),
                             max_new_tokens=0))


def test_one_token_budget_finishes_on_prefill(setup, engine):
    cfg, params, prompts = setup
    engine.submit(Request(uid="one", prompt=prompts[0],
                          max_new_tokens=1))
    out = engine.run()
    np.testing.assert_array_equal(out["one"],
                                  _solo(params, cfg, prompts[0], 1))


def test_sampling_seeded_per_request_and_knobs_do_not_retrace(setup,
                                                              engine):
    """Per-request PRNG chains: same seed → identical stream even with
    different batch-mates; different seed → different stream; greedy
    and sampling slots share the one compiled step (trace count still
    1 across the whole module's greedy AND sampling history)."""
    cfg, params, prompts = setup
    for uid, seed, temp in (("a", 7, 1.0), ("b", 7, 1.0),
                            ("c", 8, 1.0), ("g", 0, 0.0)):
        engine.submit(Request(uid=uid, prompt=prompts[0],
                              max_new_tokens=8, temperature=temp,
                              top_k=50, top_p=0.9, seed=seed))
    out = engine.run()
    np.testing.assert_array_equal(out["a"], out["b"])
    assert not np.array_equal(out["a"], out["c"])
    np.testing.assert_array_equal(out["g"],
                                  _solo(params, cfg, prompts[0], 8))
    assert engine.trace_counts["decode"] == 1   # knob mix never retraced


# ---------------------------------------------------------------------------
# fused sampling epilogue as a pure function
# ---------------------------------------------------------------------------

def test_sample_tokens_greedy_and_topk1_agree():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((3, 32)), jnp.float32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(3, dtype=jnp.uint32))
    greedy, _ = sample_tokens(logits, keys,
                              jnp.zeros(3), jnp.zeros(3, jnp.int32),
                              jnp.ones(3))
    np.testing.assert_array_equal(np.asarray(greedy),
                                  np.argmax(np.asarray(logits), -1))
    # top_k=1 at any temperature can only emit the argmax
    k1, _ = sample_tokens(logits, keys, jnp.full(3, 2.0),
                          jnp.ones(3, jnp.int32), jnp.ones(3))
    np.testing.assert_array_equal(np.asarray(k1),
                                  np.argmax(np.asarray(logits), -1))


def test_sample_tokens_topk_topp_restrict_support():
    """With top_k=3 every draw lands in the 3 highest logits; with a
    tiny top_p only the head of the distribution survives."""
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.standard_normal((1, 64)), jnp.float32)
    top3 = set(np.argsort(-np.asarray(logits[0]))[:3].tolist())
    key = jax.random.PRNGKey(0)[None]
    seen = set()
    for i in range(50):
        tok, key = sample_tokens(logits, key, jnp.full(1, 1.5),
                                 jnp.full(1, 3, jnp.int32),
                                 jnp.ones(1))
        seen.add(int(tok[0]))
    assert seen <= top3 and len(seen) > 1
    # top_p ~ 0: only the single most-probable token survives
    tok, _ = sample_tokens(logits, jax.random.PRNGKey(9)[None],
                           jnp.full(1, 2.0), jnp.zeros(1, jnp.int32),
                           jnp.full(1, 1e-6))
    assert int(tok[0]) == int(np.argmax(np.asarray(logits)))


def test_sample_tokens_chains_keys():
    logits = jnp.zeros((2, 16), jnp.float32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(2, dtype=jnp.uint32))
    _, k1 = sample_tokens(logits, keys, jnp.ones(2),
                          jnp.zeros(2, jnp.int32), jnp.ones(2))
    assert not np.array_equal(np.asarray(keys), np.asarray(k1))
