"""RNN stack tests.

Port of ``tests/L0/run_amp/test_rnn.py:10-116`` adapted to the scanned-cell
implementation: every cell type forward+backward, stacked and bidirectional
shapes, hidden-state dtype under O1, projection, and an LSTM-vs-flax
reference check.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import flax.linen as fnn

from apex_tpu import amp
from apex_tpu import rnn as apex_rnn

T, B, F, H = 5, 3, 4, 8

# The padded-batch and per-sequence runs take different MXU tilings on
# hardware (bf16-multipass f32 accumulation differs by batch shape), the
# same precision class as the flash-attention suite's on-chip tolerance.
_ON_CPU = jax.default_backend() == "cpu"
VTOL = dict(rtol=1e-5, atol=1e-6) if _ON_CPU else dict(rtol=4e-2, atol=5e-3)


def data(seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(T, B, F)
                       .astype(np.float32))


@pytest.mark.parametrize("mode", ["relu", "tanh", "gru", "lstm", "mlstm"])
def test_forward_backward(mode):
    model = apex_rnn.RNN(mode=mode, hidden_size=H)
    x = data()
    params = model.init(jax.random.PRNGKey(0), x)
    (ys, finals), grads = jax.value_and_grad(
        lambda p: (lambda o: jnp.sum(o[0] ** 2))(model.apply(p, x)),
        has_aux=False)(params), None
    ys_out, _ = model.apply(params, x)
    assert ys_out.shape == (T, B, H)
    g = jax.grad(lambda p: jnp.sum(model.apply(p, x)[0] ** 2))(params)
    assert all(bool(jnp.isfinite(l).all()) for l in jax.tree.leaves(g))
    assert all(float(jnp.abs(l).max()) > 0 for l in jax.tree.leaves(g)
               if l.ndim == 2)


def test_stacked_bidirectional_shapes():
    model = apex_rnn.LSTM(hidden_size=H, num_layers=3, bidirectional=True)
    x = data()
    params = model.init(jax.random.PRNGKey(0), x)
    ys, finals = model.apply(params, x)
    assert ys.shape == (T, B, 2 * H)
    assert len(finals) == 3
    fin_f, fin_b = finals[0]
    assert fin_f.h.shape == (B, H) and fin_b.c.shape == (B, H)


def test_recurrent_projection():
    model = apex_rnn.LSTM(hidden_size=H, output_size=6)
    x = data()
    params = model.init(jax.random.PRNGKey(0), x)
    ys, finals = model.apply(params, x)
    assert ys.shape == (T, B, 6)
    assert finals[0].h.shape == (B, 6)   # projected h re-enters recurrence
    assert finals[0].c.shape == (B, H)


def test_lstm_matches_flax_reference():
    """Same weights → same outputs as flax's LSTMCell (gate order i,f,g,o)."""
    model = apex_rnn.LSTM(hidden_size=H)
    x = data(1)
    params = model.init(jax.random.PRNGKey(0), x)
    p = params["params"]["layer_0_fwd"]

    cell = fnn.OptimizedLSTMCell(features=H)
    # flax LSTMCell params: ii/if/ig/io (kernel from input), hi/hf/hg/ho
    w_ih = np.asarray(p["w_ih"])  # (F, 4H) order i,f,g,o
    w_hh = np.asarray(p["w_hh"])
    b = np.asarray(p["b_ih"]) + np.asarray(p["b_hh"])
    carry = (jnp.zeros((B, H)), jnp.zeros((B, H)))
    flax_params = {"params": {
        "ii": {"kernel": w_ih[:, 0:H]}, "if": {"kernel": w_ih[:, H:2*H]},
        "ig": {"kernel": w_ih[:, 2*H:3*H]}, "io": {"kernel": w_ih[:, 3*H:]},
        "hi": {"kernel": w_hh[:, 0:H], "bias": b[0:H]},
        "hf": {"kernel": w_hh[:, H:2*H], "bias": b[H:2*H]},
        "hg": {"kernel": w_hh[:, 2*H:3*H], "bias": b[2*H:3*H]},
        "ho": {"kernel": w_hh[:, 3*H:], "bias": b[3*H:]},
    }}
    # flax carry is (c, h)
    c = jnp.zeros((B, H))
    h = jnp.zeros((B, H))
    outs = []
    for t in range(T):
        (c, h), y = cell.apply(flax_params, (c, h), x[t])
        outs.append(y)
    ref = jnp.stack(outs)
    ys, _ = model.apply(params, x)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_o1_casts_rnn_matmuls():
    """Under an O1 cast context the recurrence runs in bf16
    (the rnn_compat capability: RNN compute follows the policy)."""
    model = apex_rnn.GRU(hidden_size=H)
    x = data()
    params = model.init(jax.random.PRNGKey(0), x)
    with amp.cast_context(amp.O1()):
        ys, _ = model.apply(params, x)
    assert ys.dtype == jnp.bfloat16
    ys32, _ = model.apply(params, x)
    np.testing.assert_allclose(np.asarray(ys, np.float32), np.asarray(ys32),
                               atol=0.05)


def test_initial_state_passthrough():
    model = apex_rnn.Tanh(hidden_size=H)
    x = data()
    params = model.init(jax.random.PRNGKey(0), x)
    h0 = jnp.ones((B, H))
    ys, finals = model.apply(params, x, [h0])
    ys_zero, _ = model.apply(params, x)
    assert not np.allclose(np.asarray(ys[0]), np.asarray(ys_zero[0]))


@pytest.mark.parametrize("mode", ["tanh", "gru", "lstm"])
def test_variable_length_matches_per_sequence(mode):
    """The PackedSequence analog (reference test_rnn.py:104-116): a padded
    batch with seq_lengths must match running each sequence unpadded, with
    zero outputs in the padded region and final state at t = length-1."""
    model = apex_rnn.RNN(mode=mode, hidden_size=H)
    x = data()
    lengths = jnp.asarray([T, 3, 1], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x)
    ys, finals = model.apply(params, x, seq_lengths=lengths)
    assert ys.shape == (T, B, H)
    for b in range(B):
        L = int(lengths[b])
        ys_b, fin_b = model.apply(params, x[:L, b:b + 1, :])
        np.testing.assert_allclose(np.asarray(ys[:L, b]),
                                   np.asarray(ys_b[:, 0]), **VTOL)
        # padded region is zero
        np.testing.assert_array_equal(np.asarray(ys[L:, b]), 0.0)
        # final state matches the unpadded run's final state
        fin_full = jax.tree.leaves(finals[0])
        fin_solo = jax.tree.leaves(fin_b[0])
        for lf, ls in zip(fin_full, fin_solo):
            np.testing.assert_allclose(np.asarray(lf[b]), np.asarray(ls[0]),
                                       **VTOL)


def test_variable_length_bidirectional():
    """Reverse direction processes x[L-1]..x[0] per sequence — the padded
    tail contributes nothing (pad_packed_sequence semantics)."""
    model = apex_rnn.RNN(mode="gru", hidden_size=H, bidirectional=True)
    x = data()
    lengths = jnp.asarray([T, 3, 2], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x)
    ys, _ = model.apply(params, x, seq_lengths=lengths)
    assert ys.shape == (T, B, 2 * H)
    for b in range(B):
        L = int(lengths[b])
        ys_b, _ = model.apply(params, x[:L, b:b + 1, :])
        np.testing.assert_allclose(np.asarray(ys[:L, b]),
                                   np.asarray(ys_b[:, 0]), **VTOL)
        np.testing.assert_array_equal(np.asarray(ys[L:, b]), 0.0)


def test_variable_length_grads_flow_only_through_valid_steps():
    model = apex_rnn.RNN(mode="lstm", hidden_size=H)
    x = data()
    lengths = jnp.asarray([T, 3, 1], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x)

    def loss(xin):
        ys, _ = model.apply(params, xin, seq_lengths=lengths)
        return jnp.sum(ys ** 2)

    gx = jax.grad(loss)(x)
    # no gradient reaches padded inputs
    for b in range(B):
        L = int(lengths[b])
        np.testing.assert_array_equal(np.asarray(gx[L:, b]), 0.0)
        assert float(jnp.abs(gx[:L, b]).max()) > 0
