"""Multi-tensor op fuzz tests.

Port of the reference kernel-fuzz harness (``tests/L0/run_amp/
test_multi_tensor_scale.py:36-126`` and siblings): cross-product of sizes
straddling chunk boundaries × chunk sizes × list repetition × dtypes,
asserting value correctness AND overflow-flag detection with nan/inf planted
at the first/last element of the first/last tensor.  Additionally asserts
pallas(interpret)-vs-jnp path equality — the ext-vs-no-ext conformance axis.
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.multi_tensor import (
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_scale,
)

CHUNK = 2048 * 32
SIZES = [1, 129, 33333, CHUNK - 1, CHUNK, CHUNK + 1]


def make_list(sizes, dtype, seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(s).astype(np.float32)).astype(dtype)
            for s in sizes]


@pytest.mark.parametrize("mode", ["jnp", "pallas"])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_scale_values(monkeypatch, mode, dtype):
    monkeypatch.setenv("APEX_TPU_KERNELS", mode)
    xs = make_list(SIZES, dtype)
    outs, flag = multi_tensor_scale(CHUNK, [xs], 0.5)
    assert int(flag) == 0
    for x, o in zip(xs, outs):
        assert o.dtype == dtype
        np.testing.assert_allclose(
            np.asarray(o, np.float32), np.asarray(x, np.float32) * 0.5,
            rtol=1e-2 if dtype == jnp.bfloat16 else 1e-6)


@pytest.mark.parametrize("chunk", [2048 * 32, 4096])
@pytest.mark.parametrize("repeat", [1, 7])
def test_scale_chunk_boundaries(monkeypatch, chunk, repeat):
    monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")
    xs = make_list([chunk - 1, chunk, chunk + 1] * repeat, jnp.float32)
    outs, flag = multi_tensor_scale(chunk, [xs], 2.0)
    assert int(flag) == 0
    for x, o in zip(xs, outs):
        np.testing.assert_allclose(np.asarray(o), np.asarray(x) * 2.0,
                                   rtol=1e-6)


@pytest.mark.parametrize("mode", ["jnp", "pallas"])
@pytest.mark.parametrize("bad", [np.inf, -np.inf, np.nan])
@pytest.mark.parametrize("t_idx,e_pos", [(0, 0), (0, -1), (-1, 0), (-1, -1)])
def test_scale_overflow_flag(monkeypatch, mode, bad, t_idx, e_pos):
    monkeypatch.setenv("APEX_TPU_KERNELS", mode)
    xs = make_list([100, CHUNK + 3, 77], jnp.float32)
    xs[t_idx] = xs[t_idx].at[e_pos].set(bad)
    _, flag = multi_tensor_scale(CHUNK, [xs], 1.0)
    assert int(flag) == 1


def test_scale_out_dtype_conversion(monkeypatch):
    monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")
    xs = make_list([513, 2049], jnp.bfloat16)
    outs, _ = multi_tensor_scale(CHUNK, [xs], 1.0, out_dtype=jnp.float32)
    for x, o in zip(xs, outs):
        assert o.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(o),
                                   np.asarray(x, np.float32), rtol=1e-2)


@pytest.mark.parametrize("mode", ["jnp", "pallas"])
@pytest.mark.parametrize("arg_to_check", [-1, 0, 1])
def test_axpby(monkeypatch, mode, arg_to_check):
    monkeypatch.setenv("APEX_TPU_KERNELS", mode)
    xs = make_list([100, 4097], jnp.float32, seed=1)
    ys = make_list([100, 4097], jnp.float32, seed=2)
    outs, flag = multi_tensor_axpby(CHUNK, [xs, ys], 2.0, 3.0,
                                    arg_to_check=arg_to_check)
    assert int(flag) == 0
    for x, y, o in zip(xs, ys, outs):
        np.testing.assert_allclose(np.asarray(o),
                                   2.0 * np.asarray(x) + 3.0 * np.asarray(y),
                                   rtol=1e-5)


@pytest.mark.parametrize("mode", ["jnp", "pallas"])
def test_axpby_arg_to_check_policy(monkeypatch, mode):
    monkeypatch.setenv("APEX_TPU_KERNELS", mode)
    xs = make_list([257], jnp.float32, seed=1)
    ys = make_list([257], jnp.float32, seed=2)
    ys[0] = ys[0].at[5].set(np.inf)
    # checking only x: stale inf in y must NOT trip (scaler.py:167-172)
    _, flag = multi_tensor_axpby(CHUNK, [xs, ys], 1.0, 1.0, arg_to_check=0)
    assert int(flag) == 0
    _, flag = multi_tensor_axpby(CHUNK, [xs, ys], 1.0, 1.0, arg_to_check=1)
    assert int(flag) == 1
    _, flag = multi_tensor_axpby(CHUNK, [xs, ys], 1.0, 1.0, arg_to_check=-1)
    assert int(flag) == 1


def test_axpby_fp32_accumulator_precision(monkeypatch):
    """bf16 new grads into an fp32 accumulator must not round the
    accumulator (the review-flagged regression)."""
    monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")
    xs = [jnp.full((256,), 1.0, jnp.bfloat16)]
    ys = [jnp.full((256,), 1000.0, jnp.float32) + 0.25]
    outs, _ = multi_tensor_axpby(CHUNK, [xs, ys], 1.0, 1.0, arg_to_check=0,
                                 out_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(outs[0]), 1001.25, rtol=1e-6)


@pytest.mark.parametrize("mode", ["jnp", "pallas"])
def test_l2norm(monkeypatch, mode):
    monkeypatch.setenv("APEX_TPU_KERNELS", mode)
    xs = make_list([100, CHUNK + 1, 333], jnp.float32)
    total, per = multi_tensor_l2norm(CHUNK, [xs], per_tensor=True)
    ref_per = np.array([np.linalg.norm(np.asarray(x)) for x in xs])
    ref_total = np.sqrt((ref_per ** 2).sum())
    np.testing.assert_allclose(float(total), ref_total, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(per), ref_per, rtol=1e-5)


@pytest.mark.parametrize("chunk", [2048 * 32, 4096])
def test_l2norm_per_tensor_fused_boundaries(monkeypatch, chunk):
    """The fused per-tensor path (aligned pack + per-chunk sumsq +
    segment reduce): sizes straddling chunk boundaries, a scalar, and a
    mixed-dtype list — must agree with per-leaf numpy norms and with the
    jnp path."""
    monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")
    xs = make_list([1, chunk - 1, chunk, chunk + 1, 3 * chunk + 17],
                   jnp.float32, seed=3)
    xs.append(jnp.asarray(2.5, jnp.float32))          # scalar leaf
    xs.append(jnp.ones((257,), jnp.bfloat16) * 0.5)   # second dtype group
    total, per = multi_tensor_l2norm(chunk, [xs], per_tensor=True)
    ref_per = np.array([np.linalg.norm(np.asarray(x, np.float32).ravel())
                        for x in xs])
    np.testing.assert_allclose(np.asarray(per), ref_per, rtol=1e-5)
    np.testing.assert_allclose(float(total), np.sqrt((ref_per ** 2).sum()),
                               rtol=1e-5)
    monkeypatch.setenv("APEX_TPU_KERNELS", "jnp")
    total_j, per_j = multi_tensor_l2norm(chunk, [xs], per_tensor=True)
    np.testing.assert_allclose(np.asarray(per), np.asarray(per_j), rtol=1e-6)
    np.testing.assert_allclose(float(total), float(total_j), rtol=1e-6)


def test_mixed_dtype_list_groups(monkeypatch):
    monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")
    xs = [jnp.ones((10,), jnp.float32), jnp.ones((20,), jnp.bfloat16),
          jnp.ones((30,), jnp.float32)]
    outs, flag = multi_tensor_scale(CHUNK, [xs], 3.0)
    assert int(flag) == 0
    assert [o.dtype for o in outs] == [jnp.float32, jnp.bfloat16, jnp.float32]
    for x, o in zip(xs, outs):
        np.testing.assert_allclose(np.asarray(o, np.float32), 3.0, rtol=1e-2)
