"""tools/gate_hygiene.py — the gate's memory must be committed.

The repo-level test IS the tier-1 wiring (VERDICT r5 weak #7): a round
whose gate-baseline artifacts are modified-but-uncommitted fails the
suite, so the ladder/kernel-gate memory can never drift silently past a
green tier-1.  The unit tests pin the verdict classes on throwaway git
repos.
"""

import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO / "tools"))

import gate_hygiene  # noqa: E402


def test_repo_gate_artifacts_committed():
    """Tier-1 wiring: THIS checkout's gate baselines are tracked and
    clean (skip-records pass — e.g. a tarball export without git)."""
    verdict = gate_hygiene.check(str(REPO))
    assert verdict["ok"], verdict


def _git(repo, *args):
    subprocess.run(["git", "-C", str(repo), "-c", "user.email=t@t",
                    "-c", "user.name=t", *args], check=True,
                   capture_output=True)


@pytest.fixture
def tmp_repo(tmp_path):
    try:
        _git(tmp_path, "init", "-q")
    except (OSError, subprocess.CalledProcessError):
        pytest.skip("git unavailable")
    for name in gate_hygiene.REQUIRED:
        (tmp_path / name).write_text("{}")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-q", "-m", "seed")
    return tmp_path


def test_clean_repo_passes(tmp_repo):
    verdict = gate_hygiene.check(str(tmp_repo))
    assert verdict["ok"], verdict


def test_modified_baseline_fails(tmp_repo):
    (tmp_repo / "BENCH_LADDER_BASELINES.json").write_text('{"drift": 1}')
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert verdict["dirty"] == ["BENCH_LADDER_BASELINES.json"]
    assert gate_hygiene.main(["--repo", str(tmp_repo)]) == 1


def test_untracked_round_artifact_fails(tmp_repo):
    (tmp_repo / "KERNELBENCH_r06.json").write_text("{}")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert verdict["untracked"] == ["KERNELBENCH_r06.json"]
    # ...and committing it restores green
    _git(tmp_repo, "add", "KERNELBENCH_r06.json")
    _git(tmp_repo, "commit", "-q", "-m", "r06 artifact")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_missing_required_fails(tmp_repo):
    _git(tmp_repo, "rm", "-q", "SCALING_SWEEP.json")
    _git(tmp_repo, "commit", "-q", "-m", "drop")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert verdict["missing"] == ["SCALING_SWEEP.json"]


def test_non_repo_records_skip(tmp_path):
    verdict = gate_hygiene.check(str(tmp_path))
    assert verdict["ok"] and "skipped" in verdict


def test_non_gate_files_ignored(tmp_repo):
    (tmp_repo / "scratch.json").write_text("{}")
    (tmp_repo / "KERNELBENCH.json").write_text("{}")  # un-numbered out
    assert gate_hygiene.check(str(tmp_repo))["ok"]
