"""tools/gate_hygiene.py — the gate's memory must be committed.

The repo-level test IS the tier-1 wiring (VERDICT r5 weak #7): a round
whose gate-baseline artifacts are modified-but-uncommitted fails the
suite, so the ladder/kernel-gate memory can never drift silently past a
green tier-1.  The unit tests pin the verdict classes on throwaway git
repos.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO / "tools"))

import gate_hygiene  # noqa: E402


def test_repo_gate_artifacts_committed():
    """Tier-1 wiring: THIS checkout's gate baselines are tracked and
    clean (skip-records pass — e.g. a tarball export without git)."""
    verdict = gate_hygiene.check(str(REPO))
    assert verdict["ok"], verdict


def _git(repo, *args):
    subprocess.run(["git", "-C", str(repo), "-c", "user.email=t@t",
                    "-c", "user.name=t", *args], check=True,
                   capture_output=True)


@pytest.fixture
def tmp_repo(tmp_path):
    try:
        _git(tmp_path, "init", "-q")
    except (OSError, subprocess.CalledProcessError):
        pytest.skip("git unavailable")
    for name in gate_hygiene.REQUIRED:
        (tmp_path / name).write_text("{}")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-q", "-m", "seed")
    return tmp_path


def test_clean_repo_passes(tmp_repo):
    verdict = gate_hygiene.check(str(tmp_repo))
    assert verdict["ok"], verdict


def test_modified_baseline_fails(tmp_repo):
    (tmp_repo / "BENCH_LADDER_BASELINES.json").write_text('{"drift": 1}')
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert verdict["dirty"] == ["BENCH_LADDER_BASELINES.json"]
    assert gate_hygiene.main(["--repo", str(tmp_repo)]) == 1


def test_untracked_round_artifact_fails(tmp_repo):
    (tmp_repo / "KERNELBENCH_r06.json").write_text("{}")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert verdict["untracked"] == ["KERNELBENCH_r06.json"]
    # ...and committing it restores green
    _git(tmp_repo, "add", "KERNELBENCH_r06.json")
    _git(tmp_repo, "commit", "-q", "-m", "r06 artifact")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_missing_required_fails(tmp_repo):
    _git(tmp_repo, "rm", "-q", "SCALING_SWEEP.json")
    _git(tmp_repo, "commit", "-q", "-m", "drop")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert verdict["missing"] == ["SCALING_SWEEP.json"]


def test_non_repo_records_skip(tmp_path):
    verdict = gate_hygiene.check(str(tmp_path))
    assert verdict["ok"] and "skipped" in verdict


def test_non_gate_files_ignored(tmp_repo):
    (tmp_repo / "scratch.json").write_text("{}")
    (tmp_repo / "KERNELBENCH.json").write_text("{}")  # un-numbered out
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def _incidents_module(repo):
    """The schema validator the tmp repo's check will load — copy the
    real one in, like a real checkout has."""
    src = REPO / "apex_tpu" / "resilience" / "incidents.py"
    dst = repo / "apex_tpu" / "resilience"
    dst.mkdir(parents=True, exist_ok=True)
    (dst / "incidents.py").write_text(src.read_text())


def test_committed_incident_validated_against_schema(tmp_repo):
    """ISSUE 3 satellite: a committed INCIDENT_r*.json that does not
    validate (here: no evidence list, no timestamp) fails hygiene."""
    _incidents_module(tmp_repo)
    (tmp_repo / "INCIDENT_r07_bad.json").write_text(
        '{"status": "partial"}')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad incident")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("INCIDENT_r07_bad.json" in p
               for p in verdict["invalid_incidents"])
    assert gate_hygiene.main(["--repo", str(tmp_repo)]) == 1


def test_valid_incident_passes_schema(tmp_repo):
    _incidents_module(tmp_repo)
    (tmp_repo / "INCIDENT_r07_ok.json").write_text(json.dumps({
        "status": "recovered", "utc": "2026-08-03T00:00:00Z",
        "summary": "chaos run", "evidence": ["rewound at step 8"]}))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "good incident")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_uncommitted_incident_artifact_fails(tmp_repo):
    """A fresh INCIDENT_rN.json is round evidence the moment it exists —
    parked-but-untracked must fail like the KERNELBENCH artifacts do."""
    _incidents_module(tmp_repo)
    (tmp_repo / "INCIDENT_r08_new.json").write_text(json.dumps({
        "status": "recovered", "utc": "2026-08-03T00:00:00Z",
        "evidence": ["x"]}))
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert verdict["untracked"] == ["INCIDENT_r08_new.json"]


def test_truncated_incident_json_is_invalid(tmp_repo):
    _incidents_module(tmp_repo)
    (tmp_repo / "INCIDENT_r09_trunc.json").write_text('{"status": "par')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "truncated")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert any("unreadable incident JSON" in p
               for p in verdict["invalid_incidents"])


def test_repo_r02_incident_validates():
    """The pre-existing wedge record is the schema's reference instance;
    it must stay valid."""
    assert gate_hygiene._validate_incidents(str(REPO)) == []


# ---------------------------------------------------------------------------
# ISSUE 4: MEMLINT_r*.json is gate memory too
# ---------------------------------------------------------------------------

def _memlint_module(repo):
    """The schema validator the tmp repo's check will load — copy the
    real one in, like a real checkout has."""
    src = REPO / "apex_tpu" / "analysis" / "memlint.py"
    dst = repo / "apex_tpu" / "analysis"
    dst.mkdir(parents=True, exist_ok=True)
    (dst / "memlint.py").write_text(src.read_text())


def _valid_memlint():
    return {"round": 4, "platform": "cpu", "lanes": {
        "mlp_o1_train": {"ok": True, "peak_hbm_bytes": 10,
                         "donation": [], "cost": {}, "findings": {}}}}


def test_committed_memlint_validated_against_schema(tmp_repo):
    """A committed MEMLINT_r*.json that does not validate (here: no
    lanes at all) fails hygiene like a bad incident record."""
    _memlint_module(tmp_repo)
    (tmp_repo / "MEMLINT_r04_bad.json").write_text('{"round": 4}')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad memlint")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("MEMLINT_r04_bad.json" in p
               for p in verdict["invalid_memlints"])
    assert gate_hygiene.main(["--repo", str(tmp_repo)]) == 1


def test_valid_memlint_passes_schema(tmp_repo):
    _memlint_module(tmp_repo)
    (tmp_repo / "MEMLINT_r04_ok.json").write_text(
        json.dumps(_valid_memlint()))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "good memlint")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_uncommitted_memlint_artifact_fails(tmp_repo):
    """A fresh MEMLINT_rN.json is gate memory the moment it exists —
    parked-but-untracked must fail like the KERNELBENCH artifacts do."""
    _memlint_module(tmp_repo)
    (tmp_repo / "MEMLINT_r05_new.json").write_text(
        json.dumps(_valid_memlint()))
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert verdict["untracked"] == ["MEMLINT_r05_new.json"]


def test_repo_memlint_validates():
    """The committed MEMLINT artifact is the schema's reference
    instance; it must stay valid."""
    assert gate_hygiene._validate_memlints(str(REPO)) == []


# ---------------------------------------------------------------------------
# ISSUE 6: DECODE_DECOMPOSE_r*.json is gate memory too
# ---------------------------------------------------------------------------

def _decompose_module(repo):
    src = REPO / "apex_tpu" / "analysis" / "decode_decompose.py"
    dst = repo / "apex_tpu" / "analysis"
    dst.mkdir(parents=True, exist_ok=True)
    (dst / "decode_decompose.py").write_text(src.read_text())


def _valid_decompose(other_frac=0.01):
    named = (1.0 - other_frac) / 6
    fr = {k: round(named, 4) for k in
          ("param_read", "kv_read", "kv_write", "attention",
           "sampling", "host_sync")}
    fr["other"] = other_frac
    total = 1_000_000
    buckets = {k: int(v * total) for k, v in fr.items()}
    return {"round": 1, "platform": "cpu",
            "config": {"batch": 8, "prefill": 2048, "new_tokens": 256},
            "step_bytes": {"total": sum(buckets.values()),
                           "buckets": buckets},
            "device_time_fractions": fr,
            "coverage": round(1.0 - other_frac, 4)}


def test_committed_decompose_validated_against_schema(tmp_repo):
    _decompose_module(tmp_repo)
    (tmp_repo / "DECODE_DECOMPOSE_r07_bad.json").write_text(
        '{"round": 7}')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad decompose")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("DECODE_DECOMPOSE_r07_bad.json" in p
               for p in verdict["invalid_decomposes"])
    assert gate_hygiene.main(["--repo", str(tmp_repo)]) == 1


def test_decompose_coverage_bar_enforced(tmp_repo):
    """The >= 90% named-bucket coverage ACCEPTANCE bar is schema-level:
    a committed decomposition whose 'explanation' is 20% unexplained
    remainder fails hygiene."""
    _decompose_module(tmp_repo)
    (tmp_repo / "DECODE_DECOMPOSE_r08_thin.json").write_text(
        json.dumps(_valid_decompose(other_frac=0.2)))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "thin decompose")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("coverage" in p for p in verdict["invalid_decomposes"])


def test_valid_decompose_passes_schema(tmp_repo):
    _decompose_module(tmp_repo)
    (tmp_repo / "DECODE_DECOMPOSE_r09_ok.json").write_text(
        json.dumps(_valid_decompose()))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "good decompose")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_uncommitted_decompose_artifact_fails(tmp_repo):
    _decompose_module(tmp_repo)
    (tmp_repo / "DECODE_DECOMPOSE_r10_new.json").write_text(
        json.dumps(_valid_decompose()))
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert verdict["untracked"] == ["DECODE_DECOMPOSE_r10_new.json"]


def test_repo_decompose_validates():
    """The committed DECODE_DECOMPOSE artifact is the schema's
    reference instance; it must stay valid (and over the coverage
    bar)."""
    assert gate_hygiene._validate_decomposes(str(REPO)) == []


# ---------------------------------------------------------------------------
# ISSUE 7: OBS_r*.json and DECODE_PROFILE_r*.json are gate memory too
# ---------------------------------------------------------------------------

def _analysis_module(repo, stem):
    src = REPO / "apex_tpu" / "analysis" / f"{stem}.py"
    dst = repo / "apex_tpu" / "analysis"
    dst.mkdir(parents=True, exist_ok=True)
    (dst / f"{stem}.py").write_text(src.read_text())


def _valid_obs(overhead_pct=0.4):
    return {"round": 1, "platform": "cpu",
            "overhead": {"steps": 40, "bare_s": 0.5,
                         "instrumented_s": 0.5,
                         "overhead_pct": overhead_pct},
            "syncs": {"clean": True,
                      "lanes": {"serve_step": {"host_callbacks": 0,
                                               "static_scalars": 0,
                                               "errors": 0}}},
            "export": {"metrics": [{"name": "x", "type": "counter"}]}}


def test_committed_obs_validated_against_schema(tmp_repo):
    _analysis_module(tmp_repo, "obs")
    (tmp_repo / "OBS_r07_bad.json").write_text('{"round": 7}')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad obs")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("OBS_r07_bad.json" in p for p in verdict["invalid_obs"])
    assert gate_hygiene.main(["--repo", str(tmp_repo)]) == 1


def test_obs_overhead_budget_bar_enforced(tmp_repo):
    """The <1% instrumentation-overhead ACCEPTANCE bar is
    schema-level: a committed OBS record over budget fails hygiene."""
    _analysis_module(tmp_repo, "obs")
    (tmp_repo / "OBS_r08_slow.json").write_text(
        json.dumps(_valid_obs(overhead_pct=1.8)))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "slow obs")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("budget" in p for p in verdict["invalid_obs"])


def test_valid_obs_passes_and_untracked_fails(tmp_repo):
    _analysis_module(tmp_repo, "obs")
    (tmp_repo / "OBS_r09_ok.json").write_text(json.dumps(_valid_obs()))
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert verdict["untracked"] == ["OBS_r09_ok.json"]
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "good obs")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_committed_profile_validated_against_schema(tmp_repo):
    _analysis_module(tmp_repo, "decode_profile")
    (tmp_repo / "DECODE_PROFILE_r07_bad.json").write_text('{"round": 7}')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad profile")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("DECODE_PROFILE_r07_bad.json" in p
               for p in verdict["invalid_profiles"])


def test_repo_obs_and_profile_validate():
    """The committed OBS_r01 / DECODE_PROFILE_r01 artifacts are the
    schemas' reference instances; they must stay valid."""
    assert gate_hygiene._validate_obs(str(REPO)) == []
    assert gate_hygiene._validate_profiles(str(REPO)) == []


# ---------------------------------------------------------------------------
# ISSUE 9: CONVERGENCE_r*.json schema validation
# ---------------------------------------------------------------------------

def _valid_convergence():
    return {"platform": "cpu", "all_ok": True,
            "o4_mnist": {"name": "o4_mnist", "ok": True},
            "int8_kv_decode": {"name": "int8_kv_decode", "ok": True},
            "anchors": {"ngram1_nats_per_byte": 3.15}}


def test_committed_convergence_validated_against_schema(tmp_repo):
    _analysis_module(tmp_repo, "convergence")
    (tmp_repo / "CONVERGENCE_r07_bad.json").write_text('{"x": 1}')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad convergence")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("CONVERGENCE_r07_bad.json" in p
               for p in verdict["invalid_convergences"])
    assert gate_hygiene.main(["--repo", str(tmp_repo)]) == 1


def test_convergence_all_ok_must_match_lanes(tmp_repo):
    """all_ok contradicting the lanes' ok flags is schema-invalid (the
    verdict must be derivable from the document alone); a consistent
    document — and the legacy round-2 single-record shape — pass."""
    _analysis_module(tmp_repo, "convergence")
    bad = _valid_convergence()
    bad["o4_mnist"]["ok"] = False           # all_ok still True
    (tmp_repo / "CONVERGENCE_r08_lie.json").write_text(json.dumps(bad))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "contradictory convergence")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert any("contradicts" in p
               for p in verdict["invalid_convergences"])

    good = _valid_convergence()
    legacy = {"platform": "tpu", "ok": True, "epochs": 3}
    (tmp_repo / "CONVERGENCE_r08_lie.json").write_text(json.dumps(good))
    (tmp_repo / "CONVERGENCE_r02_legacy.json").write_text(
        json.dumps(legacy))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "good convergence")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


# ---------------------------------------------------------------------------
# ISSUE 10: EXPORT_r*.json is gate memory too
# ---------------------------------------------------------------------------

def _valid_export():
    return {"round": 1, "platform": "cpu",
            "versions": {"jax": "0.4.37"},
            "lanes": {
                "mlp_o1_train": {
                    "export_ok": True, "cache_key": "a" * 64,
                    "module_sha256": "b" * 64,
                    "lint": {"ok": True, "counts": {}},
                    "compile_s": 0.3, "load_s": 0.01,
                    "bitwise_equal": True}},
            "cold_start": {"lane": "mlp_o1_train", "compile_s": 0.3,
                           "load_s": 0.01, "load_ratio": 0.03,
                           "budget": 0.5, "ok": True}}


def test_committed_export_validated_against_schema(tmp_repo):
    _analysis_module(tmp_repo, "export_schema")
    (tmp_repo / "EXPORT_r07_bad.json").write_text('{"round": 7}')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad export")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("EXPORT_r07_bad.json" in p
               for p in verdict["invalid_exports"])
    assert gate_hygiene.main(["--repo", str(tmp_repo)]) == 1


def test_export_contradictory_verdict_fails_hygiene(tmp_repo):
    """The an-executable-only-enters-clean invariant is schema-level:
    a committed record claiming export_ok over a FAILING lint report
    fails hygiene."""
    _analysis_module(tmp_repo, "export_schema")
    doc = _valid_export()
    doc["lanes"]["mlp_o1_train"]["lint"]["ok"] = False
    (tmp_repo / "EXPORT_r08_lie.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "contradictory export")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert any("contradictory" in p for p in verdict["invalid_exports"])


def test_valid_export_passes_and_untracked_fails(tmp_repo):
    _analysis_module(tmp_repo, "export_schema")
    (tmp_repo / "EXPORT_r09_ok.json").write_text(
        json.dumps(_valid_export()))
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert verdict["untracked"] == ["EXPORT_r09_ok.json"]
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "good export")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_repo_export_validates():
    """The committed EXPORT artifact is the schema's reference
    instance; it must stay valid."""
    assert gate_hygiene._validate_exports(str(REPO)) == []


# ---------------------------------------------------------------------------
# ISSUE 11: SERVE_DISAGG_r*.json is gate memory too
# ---------------------------------------------------------------------------

def _valid_serve_disagg():
    return {
        "round": 1, "platform": "cpu",
        "config": {"model": "gpt_tiny", "concurrency": 16,
                   "prefill": 64, "new_tokens": 16, "block_size": 4},
        "topology": {"n_devices": 16, "transfer": "ship",
                     "prefill_devices": [0],
                     "replica_devices": [[1], [2]]},
        "mono": {"num_slots": 16, "tok_s": 2000.0, "p50_ms": 8.0,
                 "p99_ms": 12.0, "steps": 14, "retraces": 1},
        "disagg": {"slots_per_replica": 8, "n_replicas": 2,
                   "tok_s": 1600.0, "p50_ms": 4.0, "p99_ms": 6.0,
                   "per_replica": [{"steps": 14, "p50_ms": 4.0,
                                    "p99_ms": 6.0}] * 2,
                   "kv_transfer_bytes": 655488, "shipments": 16,
                   "reroutes": 0},
        "chaos": {"killed_replica": 0, "rerouted": 2,
                  "bitwise_ok": True},
        "gate": {"p99_ok": True, "ok": True},
    }


def test_committed_serve_disagg_validated_against_schema(tmp_repo):
    _analysis_module(tmp_repo, "serve_disagg")
    (tmp_repo / "SERVE_DISAGG_r07_bad.json").write_text('{"round": 7}')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad serve-disagg")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("SERVE_DISAGG_r07_bad.json" in p
               for p in verdict["invalid_serve_disaggs"])
    assert gate_hygiene.main(["--repo", str(tmp_repo)]) == 1


def test_serve_disagg_contradictory_verdict_fails_hygiene(tmp_repo):
    """The p99 gate verdict must be derivable from its own numbers: a
    record claiming p99_ok while disagg p99 exceeds mono p99 fails
    hygiene — the A/B cannot rot into an unearned 'ok'."""
    _analysis_module(tmp_repo, "serve_disagg")
    doc = _valid_serve_disagg()
    doc["disagg"]["p99_ms"] = 20.0      # over mono's 12.0, gate says ok
    (tmp_repo / "SERVE_DISAGG_r08_lie.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "contradictory serve-disagg")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert any("CONTRADICTORY" in p
               for p in verdict["invalid_serve_disaggs"])


def test_serve_disagg_overlapping_slices_fail_hygiene(tmp_repo):
    """Disjointness is the topology's whole claim: shared devices
    between the prefill slice and a decode replica are schema-invalid
    (overlap fakes the disaggregation)."""
    _analysis_module(tmp_repo, "serve_disagg")
    doc = _valid_serve_disagg()
    doc["topology"]["replica_devices"] = [[0], [2]]   # 0 = prefill dev
    (tmp_repo / "SERVE_DISAGG_r09_overlap.json").write_text(
        json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "overlapping slices")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert any("OVERLAP" in p for p in verdict["invalid_serve_disaggs"])


def test_serve_disagg_chaos_failure_breaks_ok(tmp_repo):
    """gate.ok over a failed chaos drill is contradictory: the fleet
    gate includes the failure semantics, not just the latency win."""
    _analysis_module(tmp_repo, "serve_disagg")
    doc = _valid_serve_disagg()
    doc["chaos"]["bitwise_ok"] = False   # gate.ok still True
    (tmp_repo / "SERVE_DISAGG_r10_chaos.json").write_text(
        json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "chaos contradiction")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert any("CONTRADICTORY" in p
               for p in verdict["invalid_serve_disaggs"])


def test_valid_serve_disagg_passes_and_untracked_fails(tmp_repo):
    _analysis_module(tmp_repo, "serve_disagg")
    (tmp_repo / "SERVE_DISAGG_r11_ok.json").write_text(
        json.dumps(_valid_serve_disagg()))
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert verdict["untracked"] == ["SERVE_DISAGG_r11_ok.json"]
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "good serve-disagg")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_repo_serve_disagg_validates():
    """The committed SERVE_DISAGG artifact is the schema's reference
    instance; it must stay valid (and its gate must HOLD — the c16
    acceptance bar rides this assertion)."""
    assert gate_hygiene._validate_serve_disaggs(str(REPO)) == []
    arts = sorted(REPO.glob("SERVE_DISAGG_r*.json"))
    assert arts, "the disagg gate artifact must be committed"
    doc = json.loads(arts[-1].read_text())
    assert doc["gate"]["ok"] is True
    assert doc["disagg"]["p99_ms"] <= doc["mono"]["p99_ms"]
    assert doc["chaos"]["bitwise_ok"] is True
    assert doc["topology"]["n_devices"] >= 16
    assert doc["config"]["concurrency"] >= 16


def test_real_committed_convergence_artifacts_validate():
    """Every CONVERGENCE_r*.json in the real repo — the legacy r02
    shape through the r06 quant lanes — validates."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_conv_schema", REPO / "apex_tpu" / "analysis" / "convergence.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    arts = sorted(REPO.glob("CONVERGENCE_r*.json"))
    assert len(arts) >= 5
    for p in arts:
        assert mod.validate_convergence_file(str(p)) == [], p.name


# ---------------------------------------------------------------------------
# ISSUE 12: SCENARIO_r*.json — the serve scenario matrix is gate memory
# ---------------------------------------------------------------------------

def _valid_scenario():
    def cell(spec, tps):
        # decode_steps chosen so tokens_per_step IS tokens/steps (the
        # schema re-derives it — a free-floating number is rejected)
        c = {"config": {"context": 128, "new_tokens": 16,
                        "num_slots": 2, "arrival": "steady",
                        "sampling": "greedy", "kv8": False,
                        "spec": spec, "churn": False},
             "tok_s": 800.0, "p50_ms": 2.0, "p99_ms": 4.0,
             "decode_steps": int(round(60 / tps)), "decode_tokens": 60,
             "tokens_per_step": tps, "retraces": 1, "preemptions": 0,
             "gate": {"tail_ok": True, "retrace_ok": True, "ok": True}}
        if spec:
            c["acceptance_rate"] = 0.8
        return c

    cells = {}
    for i in range(5):
        cells[f"c{i}"] = cell(False, 2.0)
        cells[f"c{i}_spec"] = cell(True, 6.0)
    return {
        "round": 1, "platform": "cpu", "model": "gpt_tiny",
        "gate_k": 20.0, "cells": cells,
        "ab": [{"on": f"c{i}_spec", "off": f"c{i}",
                "tokens_per_step_on": 6.0, "tokens_per_step_off": 2.0,
                "spec_wins": True, "gated": i == 0}
               for i in range(5)],
        "gate": {"cells_ok": True, "ab_ok": True, "ok": True},
    }


def test_committed_scenario_validated_against_schema(tmp_repo):
    _analysis_module(tmp_repo, "scenario")
    (tmp_repo / "SCENARIO_r07_bad.json").write_text('{"round": 7}')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad scenario")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("SCENARIO_r07_bad.json" in p
               for p in verdict["invalid_scenarios"])
    assert gate_hygiene.main(["--repo", str(tmp_repo)]) == 1


def test_scenario_contradictory_cell_gate_fails_hygiene(tmp_repo):
    """A cell's tail verdict must be derivable from its own numbers:
    tail_ok over a p99 beyond K x p50 is a lie the schema rejects."""
    _analysis_module(tmp_repo, "scenario")
    doc = _valid_scenario()
    doc["cells"]["c0"]["p99_ms"] = 999.0   # >> 20 x p50, gate says ok
    (tmp_repo / "SCENARIO_r08_lie.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "contradictory cell")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert any("CONTRADICTORY" in p and "tail_ok" in p
               for p in verdict["invalid_scenarios"])


def test_scenario_ab_must_cite_real_numbers(tmp_repo):
    """An A/B row's tokens-per-step must MATCH the cells it cites and
    its spec_wins must derive from them — a won A/B over a lost pair
    is schema-invalid either way."""
    _analysis_module(tmp_repo, "scenario")
    doc = _valid_scenario()
    doc["ab"][0]["tokens_per_step_on"] = 1.0   # real cell says 6.0
    (tmp_repo / "SCENARIO_r09_cite.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "mismatched ab citation")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert any("does not match" in p
               for p in verdict["invalid_scenarios"])
    doc = _valid_scenario()
    doc["ab"][0].update(tokens_per_step_on=1.0,
                        tokens_per_step_off=2.0)
    doc["cells"]["c0_spec"]["tokens_per_step"] = 1.0  # spec LOST
    doc["cells"]["c0_spec"]["decode_steps"] = 60     # 60/60 = 1.0
    (tmp_repo / "SCENARIO_r09_cite.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "lost ab claims a win")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert any("spec_wins" in p for p in verdict["invalid_scenarios"])


def test_scenario_tokens_per_step_must_derive_from_counts(tmp_repo):
    """The A/B chain's anchor: a cell's tokens_per_step must BE its
    decode_tokens/decode_steps — a fabricated spec win that edited
    only the headline number (and the ab row citing it) is rejected
    by re-derivation, not trusted for matching itself."""
    _analysis_module(tmp_repo, "scenario")
    doc = _valid_scenario()
    doc["cells"]["c0_spec"]["tokens_per_step"] = 9.0
    doc["ab"][0]["tokens_per_step_on"] = 9.0     # cites "the cell"
    (tmp_repo / "SCENARIO_r13_fab.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "fabricated tokens_per_step")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert any("CONTRADICTORY record" in p and "tokens_per_step" in p
               for p in verdict["invalid_scenarios"])


def test_scenario_too_few_cells_fails_hygiene(tmp_repo):
    """The coverage bar: a committed scenario round under MIN_CELLS
    cells is not a matrix."""
    _analysis_module(tmp_repo, "scenario")
    doc = _valid_scenario()
    doc["cells"] = {k: doc["cells"][k] for k in ("c0", "c0_spec")}
    doc["ab"] = doc["ab"][:1]
    (tmp_repo / "SCENARIO_r10_thin.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "thin scenario round")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert any("MATRIX" in p or "matrix" in p
               for p in verdict["invalid_scenarios"])


def test_scenario_churn_cell_must_preempt(tmp_repo):
    _analysis_module(tmp_repo, "scenario")
    doc = _valid_scenario()
    doc["cells"]["c1"]["config"]["churn"] = True   # preemptions stays 0
    (tmp_repo / "SCENARIO_r11_churn.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "churnless churn cell")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert any("churned nothing" in p
               for p in verdict["invalid_scenarios"])


def test_valid_scenario_passes_and_untracked_fails(tmp_repo):
    _analysis_module(tmp_repo, "scenario")
    (tmp_repo / "SCENARIO_r12_ok.json").write_text(
        json.dumps(_valid_scenario()))
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert verdict["untracked"] == ["SCENARIO_r12_ok.json"]
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "good scenario")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_repo_scenario_validates():
    """The committed SCENARIO artifact is the schema's reference
    instance; it must stay valid — and its gate must HOLD (>= 10
    cells, every cell gate green, every gated spec-vs-baseline A/B
    won: the 'handles many scenarios' + speculative-latency-win
    acceptance bars ride this assertion)."""
    assert gate_hygiene._validate_scenarios(str(REPO)) == []
    arts = sorted(REPO.glob("SCENARIO_r*.json"))
    assert arts, "the scenario gate artifact must be committed"
    doc = json.loads(arts[-1].read_text())
    assert len(doc["cells"]) >= 10
    assert doc["gate"]["ok"] is True


# ---------------------------------------------------------------------------
# TRACE_r*.json — the request-trace artifacts (ISSUE 13)
# ---------------------------------------------------------------------------

def _resilience_module(repo, stem):
    src = REPO / "apex_tpu" / "resilience" / f"{stem}.py"
    dst = repo / "apex_tpu" / "resilience"
    dst.mkdir(parents=True, exist_ok=True)
    (dst / f"{stem}.py").write_text(src.read_text())


def _valid_trace():
    return {
        "round": 1, "platform": "cpu", "config": {"model": "gpt_tiny"},
        "requests": {
            "a": {
                "trace_id": "t00001",
                "events": [
                    {"seq": 1, "ts": 0.0, "kind": "enqueue",
                     "where": "router"},
                    {"seq": 2, "ts": 0.1, "kind": "admit",
                     "where": "prefill", "tokens": 1},
                    {"seq": 3, "ts": 0.2, "kind": "decode_step",
                     "where": "replica0", "tokens": 1},
                    {"seq": 4, "ts": 0.3, "kind": "retire",
                     "where": "replica0", "tokens_out": 2},
                ],
                "spans": [
                    {"name": "request", "where": "*", "t0": 0.0,
                     "t1": 0.3, "parent": -1},
                    {"name": "replica0", "where": "replica0",
                     "t0": 0.2, "t1": 0.3, "parent": 0},
                ],
                "tokens": 2,
            },
        },
        "engine": {"serve_tokens_total": {"prefill": 1, "replica0": 1},
                   "delta_total": 2},
        "chaos": {"killed": [], "rerouted": []},
        "gate": {"bitwise_ok": True, "tokens_ok": True, "ok": True},
    }


def test_committed_trace_validated_against_schema(tmp_repo):
    _analysis_module(tmp_repo, "trace")
    (tmp_repo / "TRACE_r09_bad.json").write_text(
        json.dumps({"round": 9}))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad trace")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("TRACE_r09_bad.json" in p
               for p in verdict["invalid_traces"])


def test_trace_token_contradiction_fails_hygiene(tmp_repo):
    """A trace whose token accounting disagrees with the engines' own
    counters is CONTRADICTORY and schema-invalid."""
    _analysis_module(tmp_repo, "trace")
    doc = _valid_trace()
    doc["engine"]["delta_total"] = 9
    doc["engine"]["serve_tokens_total"] = {"replica0": 9}
    (tmp_repo / "TRACE_r09_contra.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "contradictory trace")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("CONTRADICTION" in p for p in verdict["invalid_traces"])


def test_trace_nonnesting_spans_fail_hygiene(tmp_repo):
    _analysis_module(tmp_repo, "trace")
    doc = _valid_trace()
    doc["requests"]["a"]["spans"][1]["t1"] = 99.0
    (tmp_repo / "TRACE_r09_spans.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "non-nesting trace")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("nest" in p for p in verdict["invalid_traces"])


def test_valid_trace_passes_and_untracked_fails(tmp_repo):
    _analysis_module(tmp_repo, "trace")
    (tmp_repo / "TRACE_r09_ok.json").write_text(
        json.dumps(_valid_trace()))
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert verdict["untracked"] == ["TRACE_r09_ok.json"]
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "good trace")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_incident_flight_field_validated_by_hygiene(tmp_repo):
    """The INCIDENT schema's grown optional ``flight`` field rides
    the same committed-incident validation: an unordered or
    over-capacity ring tail fails tier-1."""
    _resilience_module(tmp_repo, "incidents")
    rec = {"status": "recovered", "utc": "2026-08-04T00:00:00Z",
           "evidence": ["e"],
           "flight": {"capacity": 4, "dropped": 0,
                      "events": [{"ts": 1.0, "kind": "step"},
                                 {"ts": 0.2, "kind": "rewind"}]}}
    (tmp_repo / "INCIDENT_r09_flight.json").write_text(json.dumps(rec))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "incident w/ bad flight")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("ordered" in p for p in verdict["invalid_incidents"])
    # fixed ordering -> valid
    rec["flight"]["events"][1]["ts"] = 1.5
    (tmp_repo / "INCIDENT_r09_flight.json").write_text(json.dumps(rec))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "incident w/ good flight")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_repo_trace_validates():
    """The committed TRACE artifact is the schema's reference
    instance; it must stay valid — and its gate must HOLD (the
    killed request's lifecycle reconstructed, token accounting
    closed against the engines: the ISSUE-13 acceptance bar rides
    tests/l0/test_reqtrace.py's deeper assertion; this is the
    hygiene wiring)."""
    assert gate_hygiene._validate_traces(str(REPO)) == []
    arts = sorted(REPO.glob("TRACE_r*.json"))
    assert arts, "the trace gate artifact must be committed"
    doc = json.loads(arts[-1].read_text())
    assert doc["gate"]["ok"] is True
    assert doc["chaos"]["killed"] and doc["chaos"]["rerouted"]
    assert doc["config"]["topology"]["n_devices"] >= 16


# ---------------------------------------------------------------------------
# ISSUE 14: recorded-variance + perf-timeline artifacts are gate memory
# ---------------------------------------------------------------------------

def _valid_variance():
    vals = [1.0, 1.1, 0.9, 1.05, 0.95]
    mean = sum(vals) / len(vals)
    std = (sum((v - mean) ** 2 for v in vals) / (len(vals) - 1)) ** 0.5
    return {
        "platform": "tpu", "device_kind": "v5e", "tiny": False,
        "round": 7,
        "entries": {"kernel:fused_adam": {
            "metric": "ms_per_step", "n": 5, "values": vals,
            "mean": round(mean, 6), "min": 0.9, "max": 1.1,
            "std": round(std, 6),
            "rel_spread": round((1.1 - 0.9) / mean, 4)}},
    }


def test_committed_variance_validated_against_schema(tmp_repo):
    _analysis_module(tmp_repo, "variance")
    (tmp_repo / "BENCH_VARIANCE_r07.json").write_text('{"tiny": 1}')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad variance")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("BENCH_VARIANCE_r07.json" in p
               for p in verdict["invalid_variances"])
    assert gate_hygiene.main(["--repo", str(tmp_repo)]) == 1


def test_variance_summary_must_derive_from_samples(tmp_repo):
    """A typed-in spread wide enough to excuse a floor drop is
    rejected: mean/std/rel_spread must re-derive from the recorded
    values."""
    _analysis_module(tmp_repo, "variance")
    doc = _valid_variance()
    doc["entries"]["kernel:fused_adam"]["rel_spread"] = 0.9
    (tmp_repo / "BENCH_VARIANCE_r08.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "typed-in spread")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert any("CONTRADICTORY" in p and "rel_spread" in p
               for p in verdict["invalid_variances"])


def test_valid_variance_passes_and_untracked_fails(tmp_repo):
    _analysis_module(tmp_repo, "variance")
    (tmp_repo / "BENCH_VARIANCE_r09.json").write_text(
        json.dumps(_valid_variance()))
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]        # parked-but-untracked
    assert verdict["untracked"] == ["BENCH_VARIANCE_r09.json"]
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "variance round")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def _valid_timeline(tmp_repo):
    """A minimal internally-consistent timeline covering the tmp
    repo's committed round artifacts (none beyond what the caller
    adds)."""
    coverage = {}
    series = {}
    sys.path.insert(0, str(REPO))
    from apex_tpu.analysis import timeline as tl
    for name in sorted(p.name for p in tmp_repo.glob("*_r*.json")):
        parsed = tl.parse_artifact_name(name)
        if parsed is None or parsed[0] == "TIMELINE":
            continue
        coverage.setdefault(parsed[0],
                            {"files": [], "rows": 0})["files"].append(
            name)
    series["BENCH|c|tok_s"] = {
        "family": "BENCH", "config": "c", "metric": "tok_s",
        "points": [{"round": 1, "value": 100.0, "commit": None}]}
    return {"round": 1, "head": None,
            "bands": {"default": 0.03, "per_series": {}},
            "series": series, "regressions": [],
            "coverage": coverage or {"BENCH": {"files": [],
                                               "rows": 0}},
            "gate": {"regressions": 0, "ok": True}}


def test_committed_timeline_validated_against_schema(tmp_repo):
    _analysis_module(tmp_repo, "timeline")
    (tmp_repo / "TIMELINE_r07.json").write_text('{"round": "x"}')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad timeline")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("TIMELINE_r07.json" in p
               for p in verdict["invalid_timelines"])


def test_newest_timeline_held_to_coverage_completeness(tmp_repo):
    """The staleness lint: a new committed round artifact the newest
    timeline never ingested fails hygiene — the timeline must be
    regenerated in the same round that adds gate artifacts."""
    _analysis_module(tmp_repo, "timeline")
    doc = _valid_timeline(tmp_repo)
    (tmp_repo / "TIMELINE_r08.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "timeline round")
    assert gate_hygiene.check(str(tmp_repo))["ok"]
    # a new artifact lands without a timeline refresh -> STALE
    (tmp_repo / "KERNELBENCH_r33.json").write_text("{}")
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "new round artifact")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("STALE" in p and "KERNELBENCH_r33" in p
               for p in verdict["invalid_timelines"])
    # refreshing the timeline restores green (only the NEWEST round
    # is held to the checkout; the old round stays internally valid)
    doc2 = _valid_timeline(tmp_repo)
    doc2["round"] = 9
    (tmp_repo / "TIMELINE_r09.json").write_text(json.dumps(doc2))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "refreshed timeline")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_repo_variance_and_timeline_validate():
    """The committed BENCH_VARIANCE_r01 + TIMELINE_r01 are the
    schemas' reference instances: valid against this checkout, the
    timeline covering every committed family, its regression table
    carrying the two known tpu-heads drops."""
    assert gate_hygiene._validate_variances(str(REPO)) == []
    assert gate_hygiene._validate_timelines(str(REPO)) == []
    arts = sorted(REPO.glob("TIMELINE_r*.json"))
    assert arts, "the timeline gate artifact must be committed"
    doc = json.loads(arts[-1].read_text())
    assert {r["series"] for r in doc["regressions"]} == {
        "BENCH|gpt_small_tpu_heads_o2|tok_s",
        "BENCH|bert_large_tpu_heads_lamb_o2|seq_s"}
    assert sorted(REPO.glob("BENCH_VARIANCE_r*.json")), \
        "the variance gate artifact must be committed"


# ---------------------------------------------------------------------------
# PROFILE_DRIFT_r*.json — the continuous-profile drift artifacts
# ---------------------------------------------------------------------------

def _valid_profile_drift():
    base = {"source": "first-window", "step_wall_s": 0.003,
            "fractions": {"param_read": 0.1, "kv_read": 0.6,
                          "kv_write": 0.05, "attention": 0.02,
                          "sampling": 0.15, "host_sync": 0.0,
                          "other": 0.08}}
    drifted = dict(base["fractions"], kv_read=0.8, sampling=0.0)
    clean_w = [{"index": 0, "fractions": dict(base["fractions"]),
                "step_wall_s": 0.003, "out_of_band": []},
               {"index": 1, "fractions": dict(base["fractions"]),
                "step_wall_s": 0.0031, "out_of_band": []}]
    exc = [{"metric": "kv_read", "value": 0.8, "baseline": 0.6,
            "delta": 0.2},
           {"metric": "sampling", "value": 0.0, "baseline": 0.15,
            "delta": -0.15}]
    seeded_w = [{"index": 0, "fractions": dict(base["fractions"]),
                 "step_wall_s": 0.003, "out_of_band": []},
                {"index": 1, "fractions": drifted,
                 "step_wall_s": 0.003, "out_of_band": exc},
                {"index": 2, "fractions": drifted,
                 "step_wall_s": 0.003, "out_of_band": exc}]
    return {"round": 1, "platform": "cpu", "kind": "serve-decode",
            "config": {}, "band": {"value": 0.05, "source": "test"},
            "k": 2,
            "sessions": {
                "clean": {"baseline": base, "windows": clean_w,
                          "drifts": [], "quiet": True},
                "seeded": {"baseline": base, "windows": seeded_w,
                           "seed": {"bucket": "kv_read",
                                    "factor": 2.0, "from_window": 1},
                           "drifts": [{"window": 2,
                                       "bucket": "kv_read",
                                       "windows_out": 2}],
                           "quiet": False}},
            "gate": {"clean_quiet": True, "seeded_caught": True,
                     "ok": True},
            "note": "test"}


def test_committed_profile_drift_validated_against_schema(tmp_repo):
    _analysis_module(tmp_repo, "profile_drift")
    (tmp_repo / "PROFILE_DRIFT_r07.json").write_text('{"round": 7}')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad drift record")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("PROFILE_DRIFT_r07.json" in p
               for p in verdict["invalid_profile_drifts"])
    assert gate_hygiene.main(["--repo", str(tmp_repo)]) == 1


def test_profile_drift_quiet_contradiction_fails_hygiene(tmp_repo):
    """A quiet verdict over recorded out-of-band windows that replay
    to a confirmed drift is the lie the schema exists to reject."""
    _analysis_module(tmp_repo, "profile_drift")
    doc = _valid_profile_drift()
    doc["sessions"]["seeded"]["drifts"] = []
    doc["sessions"]["seeded"]["quiet"] = True
    doc["gate"]["seeded_caught"] = False
    doc["gate"]["ok"] = False
    (tmp_repo / "PROFILE_DRIFT_r08.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "suppressed drift")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("CONTRADICTORY" in p
               for p in verdict["invalid_profile_drifts"])


def test_valid_profile_drift_passes_and_untracked_fails(tmp_repo):
    _analysis_module(tmp_repo, "profile_drift")
    (tmp_repo / "PROFILE_DRIFT_r09.json").write_text(
        json.dumps(_valid_profile_drift()))
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]            # parked-but-untracked
    assert verdict["untracked"] == ["PROFILE_DRIFT_r09.json"]
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "drift round")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_repo_profile_drift_validates():
    """The committed PROFILE_DRIFT_r01 is the schema's reference
    instance, and the committed OBS round carries the contprof lane
    (both ride the repo-level hygiene check in tier-1)."""
    assert gate_hygiene._validate_profile_drifts(str(REPO)) == []
    assert sorted(REPO.glob("PROFILE_DRIFT_r*.json")), \
        "the profile-drift gate artifact must be committed"


# ---------------------------------------------------------------------------
# FLEETLINT_r*.json — the cross-rank SPMD consistency artifacts
# ---------------------------------------------------------------------------

def _valid_fleetlint():
    rank = {"schedule_hash": "a" * 64, "opcode_hash": "b" * 64,
            "n_collectives": 3}
    return {"round": 1, "platform": "cpu", "n_ranks": 8,
            "lanes": {"ddp_o1_train": {"compare": "schedule",
                                       "consistent": True,
                                       "ranks": {"0": dict(rank),
                                                 "1": dict(rank)},
                                       "mismatches": []}},
            "gate": {"ok": True, "inconsistent_lanes": 0}}


def test_committed_fleetlint_validated_against_schema(tmp_repo):
    _analysis_module(tmp_repo, "fleetlint")
    (tmp_repo / "FLEETLINT_r07.json").write_text('{"round": 7}')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad fleet record")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("FLEETLINT_r07.json" in p
               for p in verdict["invalid_fleetlints"])
    assert gate_hygiene.main(["--repo", str(tmp_repo)]) == 1


def test_fleetlint_contradictory_verdict_fails_hygiene(tmp_repo):
    """A ``consistent`` lane verdict over disagreeing recorded per-rank
    schedule hashes is the lie the schema exists to reject — "every rank
    compiles the same collective schedule" must re-derive from the
    recorded hashes, not be asserted."""
    _analysis_module(tmp_repo, "fleetlint")
    doc = _valid_fleetlint()
    doc["lanes"]["ddp_o1_train"]["ranks"]["1"]["schedule_hash"] = "d" * 64
    doc["lanes"]["ddp_o1_train"]["mismatches"] = [
        {"ranks": ["0", "1"], "index": 0,
         "a": "all-reduce(bf16)", "b": "all-reduce(f32)"}]
    (tmp_repo / "FLEETLINT_r08.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "asserted fleet consistency")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("contradicts" in p for p in verdict["invalid_fleetlints"])


def test_valid_fleetlint_passes_and_untracked_fails(tmp_repo):
    _analysis_module(tmp_repo, "fleetlint")
    (tmp_repo / "FLEETLINT_r09.json").write_text(
        json.dumps(_valid_fleetlint()))
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]            # parked-but-untracked
    assert verdict["untracked"] == ["FLEETLINT_r09.json"]
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "fleet round")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_repo_fleetlint_validates():
    """The committed FLEETLINT_r01 is the schema's reference instance
    (it rides the repo-level hygiene check in tier-1)."""
    assert gate_hygiene._validate_fleetlints(str(REPO)) == []
    assert sorted(REPO.glob("FLEETLINT_r*.json")), \
        "the fleet SPMD gate artifact must be committed"


# ---------------------------------------------------------------------------
# ISSUE 17: PREFIXCACHE_r*.json — cross-request prefix sharing is gate memory
# ---------------------------------------------------------------------------

def _valid_prefixcache():
    # spans: one cold miss, two partial hits, one full-prompt CoW match
    # (dispatched floored at 1 — the CoW rewrite re-runs one token)
    spans = [
        {"uid": "q0", "prompt_len": 16, "matched": 0, "dispatched": 16},
        {"uid": "q1", "prompt_len": 16, "matched": 8, "dispatched": 8},
        {"uid": "q2", "prompt_len": 16, "matched": 8, "dispatched": 8},
        {"uid": "q3", "prompt_len": 16, "matched": 16, "dispatched": 1},
    ]
    return {
        "round": 1, "platform": "cpu",
        "config": {"model": "gpt_tiny", "concurrency": 4,
                   "system_prompt_tokens": 8, "prefill": 16,
                   "new_tokens": 4, "block_size": 4},
        "sharing": {
            "prefill_chunks": 5, "prefill_tokens_dispatched": 33,
            "admitted_requests": 4, "peak_live_blocks": 10,
            "admitted_requests_per_block": 0.4,
            "p50_ms": 1.9, "p99_ms": 3.2, "retraces": 1,
            "prefix": {"probes": 4, "hits": 3, "hit_rate": 0.75,
                       "hit_tokens": 31, "cow_copies": 1,
                       "shared_blocks_peak": 4, "cached_evictions": 0,
                       "requests": spans}},
        "baseline": {
            "prefill_chunks": 8, "prefill_tokens_dispatched": 64,
            "admitted_requests": 4, "peak_live_blocks": 16,
            "admitted_requests_per_block": 0.25,
            "p50_ms": 1.8, "p99_ms": 3.1, "retraces": 1},
        "bitwise_ok": True,
        "gate": {"hit_rate_ok": True, "ab_ok": True,
                 "bitwise_ok": True, "ok": True},
    }


def test_committed_prefixcache_validated_against_schema(tmp_repo):
    _analysis_module(tmp_repo, "prefixcache")
    (tmp_repo / "PREFIXCACHE_r07_bad.json").write_text('{"round": 7}')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad prefixcache")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("PREFIXCACHE_r07_bad.json" in p
               for p in verdict["invalid_prefixcaches"])
    assert gate_hygiene.main(["--repo", str(tmp_repo)]) == 1


def test_prefixcache_span_contradiction_fails_hygiene(tmp_repo):
    """A span claiming a full-prompt match re-dispatched NOTHING is the
    lie the schema exists to reject: dispatched must equal
    max(prompt_len - matched, 1) — the CoW rewrite always re-runs one
    token, so 'free' full hits cannot be typed in."""
    _analysis_module(tmp_repo, "prefixcache")
    doc = _valid_prefixcache()
    doc["sharing"]["prefix"]["requests"][3]["dispatched"] = 0
    (tmp_repo / "PREFIXCACHE_r08_span.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "free full hit")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("CONTRADICTORY" in p and "CoW" in p
               for p in verdict["invalid_prefixcaches"])


def test_prefixcache_hit_tokens_must_derive_from_spans(tmp_repo):
    """The headline skipped-token total must BE the span sum — an
    inflated hit_tokens (a faked saving) is rejected by re-derivation."""
    _analysis_module(tmp_repo, "prefixcache")
    doc = _valid_prefixcache()
    doc["sharing"]["prefix"]["hit_tokens"] = 999
    (tmp_repo / "PREFIXCACHE_r09_fab.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "inflated hit tokens")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert any("CONTRADICTORY" in p and "hit_tokens" in p
               for p in verdict["invalid_prefixcaches"])


def test_prefixcache_ab_verdict_must_derive_from_arms(tmp_repo):
    """gate.ab_ok over a baseline that dispatched FEWER tokens than the
    sharing arm is an unearned win; the verdict must re-derive."""
    _analysis_module(tmp_repo, "prefixcache")
    doc = _valid_prefixcache()
    doc["baseline"]["prefill_tokens_dispatched"] = 20   # < sharing's 33
    (tmp_repo / "PREFIXCACHE_r10_lie.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "unearned ab win")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert any("CONTRADICTORY verdict" in p and "ab_ok" in p
               for p in verdict["invalid_prefixcaches"])


def test_valid_prefixcache_passes_and_untracked_fails(tmp_repo):
    _analysis_module(tmp_repo, "prefixcache")
    (tmp_repo / "PREFIXCACHE_r11_ok.json").write_text(
        json.dumps(_valid_prefixcache()))
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]            # parked-but-untracked
    assert verdict["untracked"] == ["PREFIXCACHE_r11_ok.json"]
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "good prefixcache")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_repo_prefixcache_validates():
    """The committed PREFIXCACHE artifact is the schema's reference
    instance; it must stay valid — and its gate must HOLD (real hit
    rate, fewer dispatched prefill tokens, denser pool, bitwise parity:
    the ISSUE-17 acceptance bars ride this assertion)."""
    assert gate_hygiene._validate_prefixcaches(str(REPO)) == []
    arts = sorted(REPO.glob("PREFIXCACHE_r*.json"))
    assert arts, "the prefix-sharing gate artifact must be committed"
    doc = json.loads(arts[-1].read_text())
    assert doc["gate"]["ok"] is True
    assert doc["sharing"]["prefix"]["hit_rate"] > 0.5
    assert doc["sharing"]["prefill_tokens_dispatched"] \
        < doc["baseline"]["prefill_tokens_dispatched"]
    assert doc["sharing"]["admitted_requests_per_block"] \
        > doc["baseline"]["admitted_requests_per_block"]
    assert doc["bitwise_ok"] is True


# ---------------------------------------------------------------------------
# ISSUE 18: TRAINFLEET_r*.json — the elastic-fleet chaos drill is gate memory
# ---------------------------------------------------------------------------

def _trainfleet_modules(repo):
    """The trainfleet schema loads the incident sub-schema by relative
    path, so the tmp checkout needs both modules in place."""
    _analysis_module(repo, "trainfleet")
    _incidents_module(repo)


def _trainfleet_doc():
    """The committed drill artifact is the schema's reference instance —
    contradiction tests mutate a copy of the real thing, so they can
    never drift from what the drill actually emits."""
    return json.loads((REPO / "TRAINFLEET_r01.json").read_text())


def test_committed_trainfleet_validated_against_schema(tmp_repo):
    _trainfleet_modules(tmp_repo)
    (tmp_repo / "TRAINFLEET_r07.json").write_text('{"round": 7}')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad fleet drill record")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("TRAINFLEET_r07.json" in p
               for p in verdict["invalid_trainfleets"])
    assert gate_hygiene.main(["--repo", str(tmp_repo)]) == 1


def test_trainfleet_typed_in_steps_lost_rejected(tmp_repo):
    """``steps_lost`` must equal ``interrupted_step - restore_step`` —
    a typed-in smaller loss is the lie the schema exists to reject."""
    _trainfleet_modules(tmp_repo)
    doc = _trainfleet_doc()
    shrink = next(r for r in doc["recoveries"] if r["reason"] == "shrink")
    shrink["steps_lost"] = 0
    (tmp_repo / "TRAINFLEET_r08.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "optimistic fleet record")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("steps_lost" in p and "contradicts" in p
               for p in verdict["invalid_trainfleets"])


def test_trainfleet_contradictory_bitwise_rejected(tmp_repo):
    """A ``bitwise`` verdict the recorded digests refute (here: a
    shrink-replay digest that no longer matches the drill snapshot,
    while the flag still says True) fails hygiene — and flipping
    ``gate.ok`` against its own bitwise table fails the same way."""
    _trainfleet_modules(tmp_repo)
    doc = _trainfleet_doc()
    rank0 = next(iter(doc["replays"]["shrink"]["finals"]))
    doc["replays"]["shrink"]["finals"][rank0]["digest"] = "f" * 64
    (tmp_repo / "TRAINFLEET_r08.json").write_text(json.dumps(doc))
    contradicted_gate = _trainfleet_doc()
    contradicted_gate["gate"]["ok"] = False
    (tmp_repo / "TRAINFLEET_r09.json").write_text(
        json.dumps(contradicted_gate))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "asserted fleet verdicts")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    probs = verdict["invalid_trainfleets"]
    assert any("TRAINFLEET_r08" in p and
               "bitwise.shrink_matches_uninterrupted" in p for p in probs)
    assert any("TRAINFLEET_r09" in p and "gate.ok" in p for p in probs)


def test_trainfleet_regrown_rank_must_load_from_aot_cache(tmp_repo):
    """The elastic claim the AOT cache backs: a regrown generation that
    COMPILED its step (``aot.source != "cache"``) is schema-invalid."""
    _trainfleet_modules(tmp_repo)
    doc = _trainfleet_doc()
    last_gen = doc["generations"][-1]["gen"]
    for e in doc["events"]:
        if e.get("kind") == "aot" and e.get("gen") == last_gen:
            e["source"] = "compile"
    (tmp_repo / "TRAINFLEET_r08.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "cold fleet record")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("must LOAD from the AOT cache" in p
               for p in verdict["invalid_trainfleets"])


def test_trainfleet_membership_must_chain(tmp_repo):
    """A 'shrink' generation whose members are not a strict subset of
    its predecessor's is an incoherent story, not a recovery."""
    _trainfleet_modules(tmp_repo)
    doc = _trainfleet_doc()
    shrink_gen = next(g for g in doc["generations"]
                      if g["reason"] == "shrink")
    shrink_gen["members"] = doc["generations"][0]["members"]
    (tmp_repo / "TRAINFLEET_r08.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "unchained fleet record")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("strict subset" in p
               for p in verdict["invalid_trainfleets"])


def test_valid_trainfleet_passes_and_untracked_fails(tmp_repo):
    _trainfleet_modules(tmp_repo)
    (tmp_repo / "TRAINFLEET_r08.json").write_text(
        json.dumps(_trainfleet_doc()))
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]            # parked-but-untracked
    assert verdict["untracked"] == ["TRAINFLEET_r08.json"]
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "fleet drill round")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_repo_trainfleet_validates():
    """The committed TRAINFLEET artifact is the schema's reference
    instance, and its drill verdicts must HOLD: the kill was real, the
    recovery stayed within one checkpoint interval, every bitwise flag
    derived true (the ISSUE-18 acceptance bars ride this assertion)."""
    assert gate_hygiene._validate_trainfleets(str(REPO)) == []
    arts = sorted(REPO.glob("TRAINFLEET_r*.json"))
    assert arts, "the fleet chaos-drill artifact must be committed"
    doc = json.loads(arts[-1].read_text())
    assert doc["gate"]["ok"] is True
    assert all(doc["bitwise"].values())
    shrink = next(r for r in doc["recoveries"] if r["reason"] == "shrink")
    assert 0 <= shrink["steps_lost"] <= doc["config"]["checkpoint_every"]
    assert any(e["kind"] == "kill" for e in doc["events"])


# ---------------------------------------------------------------------------
# KERNLINT_r*.json — the Pallas kernel sanitizer sweep artifacts
# ---------------------------------------------------------------------------

def _valid_kernlint():
    rules = ["pallas-parallel-race", "pallas-alias-race",
             "pallas-oob-unmasked", "pallas-uncovered-output",
             "pallas-vmem-overflow", "pallas-seq-accum-parallel"]
    return {"round": 1, "platform": "cpu", "budget_mb": 16.0,
            "rules": rules,
            "kernels": {"fused_adam": {
                "ok": True, "configs": 2, "calls": 3,
                "findings": {r: 0 for r in rules}}},
            "gate": {"ok": True, "kernels_clean": 1,
                     "kernels_total": 1}}


def test_committed_kernlint_validated_against_schema(tmp_repo):
    _analysis_module(tmp_repo, "kernlint")
    (tmp_repo / "KERNLINT_r07.json").write_text('{"round": 7}')
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "bad kernel record")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("KERNLINT_r07.json" in p
               for p in verdict["invalid_kernlints"])
    assert gate_hygiene.main(["--repo", str(tmp_repo)]) == 1


def test_kernlint_contradictory_verdict_fails_hygiene(tmp_repo):
    """A clean kernel verdict sitting on recorded unwaived findings is
    the lie the schema exists to reject — "the kernels are race-free
    and under budget" must re-derive from the finding counts."""
    _analysis_module(tmp_repo, "kernlint")
    doc = _valid_kernlint()
    doc["kernels"]["fused_adam"]["findings"]["pallas-vmem-overflow"] = 2
    (tmp_repo / "KERNLINT_r08.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "asserted kernel cleanliness")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("contradicts" in p for p in verdict["invalid_kernlints"])


def test_kernlint_stale_waiver_fails_hygiene(tmp_repo):
    """A waiver citing a rule that never fired is dead documentation —
    it would silently excuse a FUTURE regression of that rule."""
    _analysis_module(tmp_repo, "kernlint")
    doc = _valid_kernlint()
    doc["kernels"]["fused_adam"]["waivers"] = {
        "pallas-oob-unmasked": "masked tail, verified by hand"}
    (tmp_repo / "KERNLINT_r08.json").write_text(json.dumps(doc))
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "stale kernel waiver")
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]
    assert any("stale waiver" in p for p in verdict["invalid_kernlints"])


def test_valid_kernlint_passes_and_untracked_fails(tmp_repo):
    _analysis_module(tmp_repo, "kernlint")
    (tmp_repo / "KERNLINT_r09.json").write_text(
        json.dumps(_valid_kernlint()))
    verdict = gate_hygiene.check(str(tmp_repo))
    assert not verdict["ok"]            # parked-but-untracked
    assert verdict["untracked"] == ["KERNLINT_r09.json"]
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-q", "-m", "kernel lint round")
    assert gate_hygiene.check(str(tmp_repo))["ok"]


def test_repo_kernlint_validates():
    """The committed KERNLINT_r01 is the schema's reference instance
    (it rides the repo-level hygiene check in tier-1)."""
    assert gate_hygiene._validate_kernlints(str(REPO)) == []
    assert sorted(REPO.glob("KERNLINT_r*.json")), \
        "the kernel sanitizer gate artifact must be committed"
