"""FusedLayerNorm tests.

Port of ``tests/L0/run_fused_layer_norm/test_fused_layer_norm.py:9-41``
(fused output vs reference path, affine and not) extended with gradient
checks and pallas(interpret)-vs-jnp conformance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.normalization import (
    FusedLayerNorm,
    fused_layer_norm,
    fused_layer_norm_affine,
)


def ref_layer_norm(x, w, b, nshape, eps=1e-5):
    n2 = int(np.prod(nshape))
    x32 = np.asarray(x, np.float32).reshape(-1, n2)
    mean = x32.mean(1, keepdims=True)
    var = x32.var(1, keepdims=True)
    y = (x32 - mean) / np.sqrt(var + eps)
    if w is not None:
        y = y * np.asarray(w, np.float32).reshape(1, n2)
    if b is not None:
        y = y + np.asarray(b, np.float32).reshape(1, n2)
    return y.reshape(x.shape)


@pytest.mark.parametrize("mode", ["jnp", "pallas"])
@pytest.mark.parametrize("affine", [False, True])
@pytest.mark.parametrize("shape,nshape", [((16, 32, 256), (256,)),
                                          ((8, 100), (100,)),
                                          ((4, 2, 3, 128), (128,))])
def test_forward_matches_reference(monkeypatch, mode, affine, shape, nshape):
    monkeypatch.setenv("APEX_TPU_KERNELS", mode)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(*shape).astype(np.float32))
    w = jnp.asarray(rng.rand(*nshape).astype(np.float32)) if affine else None
    b = jnp.asarray(rng.randn(*nshape).astype(np.float32)) if affine else None
    y = fused_layer_norm_affine(x, w, b, nshape)
    np.testing.assert_allclose(np.asarray(y), ref_layer_norm(x, w, b, nshape),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("mode", ["jnp", "pallas"])
def test_gradients_match_reference(monkeypatch, mode):
    monkeypatch.setenv("APEX_TPU_KERNELS", mode)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(160, 256).astype(np.float32))
    w = jnp.asarray(1.0 + 0.1 * rng.randn(256).astype(np.float32))
    b = jnp.asarray(0.1 * rng.randn(256).astype(np.float32))

    def fused_loss(x, w, b):
        return jnp.sum(jnp.sin(fused_layer_norm_affine(x, w, b, (256,))))

    def ref_loss(x, w, b):
        x32 = x.astype(jnp.float32)
        mean = x32.mean(-1, keepdims=True)
        var = x32.var(-1, keepdims=True)
        y = (x32 - mean) * jax.lax.rsqrt(var + 1e-5) * w + b
        return jnp.sum(jnp.sin(y))

    gf = jax.grad(fused_loss, argnums=(0, 1, 2))(x, w, b)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(x, w, b)
    for a, c in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("mode", ["jnp", "pallas"])
def test_bf16_input_fp32_stats(monkeypatch, mode):
    monkeypatch.setenv("APEX_TPU_KERNELS", mode)
    rng = np.random.RandomState(2)
    # large offset: fp32 stats keep precision where bf16 stats would not.
    # Reference runs on the SAME bf16-quantized input so only the stat/output
    # precision is under test, not input rounding.
    x = jnp.asarray((100.0 + rng.randn(64, 128)).astype(np.float32))
    xbf = x.astype(jnp.bfloat16)
    y_ref = fused_layer_norm(xbf.astype(jnp.float32), (128,))
    ybf = fused_layer_norm(xbf, (128,))
    assert ybf.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(ybf, np.float32),
                               np.asarray(y_ref), atol=0.05)


def test_module_api():
    m = FusedLayerNorm(normalized_shape=64)
    x = jnp.ones((4, 64))
    variables = m.init(jax.random.PRNGKey(0), x)
    assert variables["params"]["scale"].shape == (64,)
    assert variables["params"]["bias"].shape == (64,)
    y = m.apply(variables, x)
    # ones input → zero centered → y == bias == 0
    np.testing.assert_allclose(np.asarray(y), 0.0, atol=1e-5)

    m2 = FusedLayerNorm(normalized_shape=64, elementwise_affine=False)
    v2 = m2.init(jax.random.PRNGKey(0), x)
    assert "params" not in v2 or not v2["params"]


def test_rejects_bad_trailing_shape():
    x = jnp.ones((4, 32))
    with pytest.raises(AssertionError):
        fused_layer_norm(x, (64,))
