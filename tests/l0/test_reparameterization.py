"""Weight-norm reparameterization tests.

The reference subsystem is import-broken (SURVEY.md §0.3) and untested;
these tests define the intended semantics (torch.nn.utils.weight_norm
behavior, per the reference docstrings in
``apex/reparameterization/__init__.py``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu.models.mlp import MLP, cross_entropy_loss
from apex_tpu.reparameterization import (
    WeightNorm,
    apply_weight_norm,
    merge,
    remove_weight_norm,
    reparameterized_apply,
)


def _params():
    model = MLP(features=(16, 16), num_classes=4)
    p = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]
    return model, p


def test_decomposition_shapes_and_identity():
    model, p = _params()
    pw = apply_weight_norm(p)
    # kernels (2-d) decomposed, biases (1-d) untouched
    l0 = pw["AmpDense_0"]
    assert "kernel_g" in l0 and "kernel_v" in l0 and "kernel" not in l0
    assert "bias" in l0
    # per-output-channel g: kernel (in, out) → g (1, out)
    assert l0["kernel_g"].shape == (1, 16)
    merged = merge(pw, WeightNorm())
    for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(merged)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_dim_none_whole_tensor_norm():
    w = jax.random.normal(jax.random.PRNGKey(1), (8, 4))
    wn = WeightNorm(dim=None)
    aux = wn.reparameterize("kernel", w)
    assert aux["kernel_g"].shape == (1, 1)
    np.testing.assert_allclose(float(aux["kernel_g"][0, 0]),
                               float(jnp.linalg.norm(w)), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(wn.compute_weight("kernel", aux)),
                               np.asarray(w), atol=1e-6)


def test_effective_weight_norm_equals_g():
    """After scaling g, the effective weight's per-column norm equals g
    (magnitude/direction decoupling — the point of the method)."""
    w = jax.random.normal(jax.random.PRNGKey(2), (8, 4))
    wn = WeightNorm()
    aux = wn.reparameterize("kernel", w)
    aux["kernel_g"] = aux["kernel_g"] * 2.0
    merged = wn.compute_weight("kernel", aux)
    np.testing.assert_allclose(
        np.asarray(jnp.linalg.norm(merged, axis=0)),
        np.asarray(aux["kernel_g"][0]), rtol=1e-5)


def test_gradients_flow_and_training_improves():
    model, p = _params()
    pw = apply_weight_norm(p)
    apply_wn = reparameterized_apply(model.apply, WeightNorm())

    x = jax.random.normal(jax.random.PRNGKey(3), (64, 8))
    y = (x[:, 0] > 0).astype(jnp.int32)

    def loss_fn(pw):
        return cross_entropy_loss(apply_wn({"params": pw}, x), y)

    tx = optax.sgd(0.5)
    opt = tx.init(pw)
    l0 = float(loss_fn(pw))
    g = jax.grad(loss_fn)(pw)
    # every decomposed leaf gets a gradient
    assert float(jnp.abs(g["AmpDense_0"]["kernel_g"]).sum()) > 0
    assert float(jnp.abs(g["AmpDense_0"]["kernel_v"]).sum()) > 0

    @jax.jit
    def step(pw, opt):
        grads = jax.grad(loss_fn)(pw)
        updates, opt = tx.update(grads, opt)
        return optax.apply_updates(pw, updates), opt

    for _ in range(20):
        pw, opt = step(pw, opt)
    assert float(loss_fn(pw)) < l0


def test_remove_weight_norm_roundtrip_after_training():
    model, p = _params()
    pw = apply_weight_norm(p)
    # perturb g to make the effective weight differ from the original
    pw["AmpDense_0"]["kernel_g"] = pw["AmpDense_0"]["kernel_g"] * 1.5
    plain = remove_weight_norm(pw)
    assert "kernel" in plain["AmpDense_0"]
    assert "kernel_g" not in plain["AmpDense_0"]
    x = jax.random.normal(jax.random.PRNGKey(4), (4, 8))
    apply_wn = reparameterized_apply(model.apply, WeightNorm())
    np.testing.assert_allclose(
        np.asarray(apply_wn({"params": pw}, x)),
        np.asarray(model.apply({"params": plain}, x)), atol=1e-5)


def test_named_leaf_restriction():
    model, p = _params()
    pw = apply_weight_norm(p, name="kernel")
    assert "kernel_v" in pw["AmpDense_0"]
    pw2 = apply_weight_norm(p, name="nonexistent")
    assert jax.tree.structure(pw2) == jax.tree.structure(
        jax.tree.map(lambda x: x, p))
