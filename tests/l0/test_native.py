"""Native host-runtime library tests (``csrc/apex_tpu_C.cpp`` via
``apex_tpu._native``).

Test style follows the reference kernel fuzz harness
(``tests/L0/run_amp/test_multi_tensor_scale.py:36-126``): size
cross-products straddling chunk/partition boundaries, and value equality
against a pure-Python oracle.
"""

import numpy as np
import pytest

from apex_tpu import _native
from apex_tpu.ops import packing

SIZE_SETS = [
    [1],
    [7, 1, 33],
    [4096, 17, 4096],
    [2048 * 32, 2048 * 32 + 1, 2048 * 32 - 1, 1, 55],
]


def test_native_built():
    """The toolchain is baked into this environment; the native library must
    actually build here (fallback is for user machines without g++)."""
    assert _native.available, _native.import_err


@pytest.mark.parametrize("sizes", SIZE_SETS)
@pytest.mark.parametrize("dtype", [np.float32, np.float16, np.int32])
def test_flatten_unflatten_roundtrip(sizes, dtype):
    rng = np.random.default_rng(0)
    if np.issubdtype(dtype, np.floating):
        arrs = [rng.standard_normal(s).astype(dtype) for s in sizes]
    else:
        arrs = [rng.integers(-100, 100, s).astype(dtype) for s in sizes]
    flat = _native.flatten(arrs)
    np.testing.assert_array_equal(
        flat, np.concatenate([a.ravel() for a in arrs]))
    outs = _native.unflatten(flat, [a.shape for a in arrs])
    for a, b in zip(arrs, outs):
        np.testing.assert_array_equal(a, b)
        assert a.dtype == b.dtype


def test_flatten_multidim_shapes():
    arrs = [np.arange(24, dtype=np.float32).reshape(2, 3, 4),
            np.ones((5, 5), np.float32)]
    flat = _native.flatten(arrs)
    outs = _native.unflatten(flat, [(2, 3, 4), (5, 5)])
    np.testing.assert_array_equal(outs[0], arrs[0])
    np.testing.assert_array_equal(outs[1], arrs[1])


def test_flatten_rejects_mixed_dtype():
    with pytest.raises(ValueError):
        _native.flatten([np.ones(3, np.float32), np.ones(3, np.float16)])


def test_unflatten_rejects_size_mismatch():
    with pytest.raises(ValueError):
        _native.unflatten(np.ones(10, np.float32), [(3,), (3,)])


def _plan_oracle(numels, message, triggers=None):
    """Pure-Python reimplementation of the greedy bucketing
    (``apex/parallel/distributed.py:339-362``)."""
    ids, bucket, acc = [], 0, 0
    for i, n in enumerate(numels):
        ids.append(bucket)
        acc += n
        if acc >= message or (triggers is not None and triggers[i]):
            bucket += 1
            acc = 0
    return ids


@pytest.mark.parametrize("message", [1, 25, 100, 10 ** 9])
def test_plan_buckets_matches_oracle(message):
    rng = np.random.default_rng(1)
    numels = rng.integers(1, 50, 200).tolist()
    got = _native.plan_buckets(numels, message)
    np.testing.assert_array_equal(got, _plan_oracle(numels, message))


def test_plan_buckets_triggers():
    numels = [10] * 6
    trig = [False, False, True, False, False, False]
    got = _native.plan_buckets(numels, 10 ** 9, triggers=trig)
    np.testing.assert_array_equal(got, _plan_oracle(numels, 10 ** 9, trig))


def test_fingerprint_known_value():
    # FNV-1a 64 of "abc"
    assert _native.fingerprint64(b"abc") == 0xE71FA2190541574B


def test_fingerprint_array_vs_bytes():
    a = np.arange(100, dtype=np.float32)
    assert _native.fingerprint64(a) == _native.fingerprint64(a.tobytes())
    b = a.copy()
    b[50] = np.nextafter(b[50], np.inf)  # one ULP — digests must differ
    assert _native.fingerprint64(a) != _native.fingerprint64(b)


def test_host_pack_unpack():
    rng = np.random.default_rng(2)
    arrs = [rng.standard_normal(s).astype(np.float32)
            for s in [(3, 4), (128,), (1,)]]
    flat, meta = packing.host_pack(arrs)
    outs = packing.host_unpack(flat, meta)
    for a, b in zip(arrs, outs):
        np.testing.assert_array_equal(a, b)


def test_ddp_plan_buckets_api():
    import jax.numpy as jnp
    from apex_tpu.parallel import DistributedDataParallel
    ddp = DistributedDataParallel(axis_name="data", message_size=30)
    grads = {"a": jnp.zeros((5, 4)), "b": jnp.zeros(15), "c": jnp.zeros(40)}
    ids = ddp.plan_buckets(grads)
    # leaves in tree order: a(20), b(15), c(40) → [0, 0(35≥30 closes), 1]
    np.testing.assert_array_equal(ids, [0, 0, 1])
