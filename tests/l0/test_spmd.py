"""Cross-rank SPMD consistency lint (:mod:`apex_tpu.analysis.spmd`).

The fleet invariant is "every rank executes the same collective
schedule"; a violation is a hang, not an error message.  Each detector
class must (a) FIRE on a seeded divergence with its documented finding
id — ``spmd-schedule-mismatch`` (different op sequence: the static
deadlock), ``spmd-group-mismatch`` (same sequence, different channel
wiring), ``spmd-bytes-mismatch`` (the signSGD class: a sign-compressed
/ width-changed bucket on one rank), ``spmd-conditional-collective``
(a collective under a rank-divergent predicate) — and (b) stay QUIET on
rank-identical lowerings and the real DDP lanes.  The collective
schedule parser (both StableHLO and compiled-HLO spellings), the
fingerprint the runtime preflight exchanges, the FLEETLINT artifact
schema, and the graph_lint fleet lanes are pinned here too (ISSUE 16).
"""

import json
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

from apex_tpu import analysis  # noqa: E402
from apex_tpu.analysis import spmd  # noqa: E402
from apex_tpu.analysis.collectives import (canon_groups,  # noqa: E402
                                           collective_attrs,
                                           collective_audit,
                                           collective_table)
from apex_tpu.analysis.fleetlint import (validate_fleetlint,  # noqa: E402
                                         validate_fleetlint_file)
from apex_tpu.parallel import multiproc  # noqa: E402
from apex_tpu.parallel.distributed import (ReduceConfig,  # noqa: E402
                                           reduce_gradients)
from apex_tpu.utils.jax_compat import shard_map  # noqa: E402


def mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


def _text(fn, *args):
    return analysis.lower_quiet(jax.jit(fn), *args).as_text()


def _psum_text(extra=False, n=8):
    def f(x):
        g = jax.lax.psum(x, "data")
        if extra:
            g = g + jax.lax.pmax(x, "data")
        return g

    sm = shard_map(f, mesh=mesh(n), in_specs=P("data"), out_specs=P())
    return _text(sm, jnp.ones((n, 4), jnp.float32))


def _ops(findings):
    return [f.op for f in findings]


# ---------------------------------------------------------------------------
# the collective schedule: both lowering representations
# ---------------------------------------------------------------------------

def test_stablehlo_schedule_entries():
    sched = spmd.collective_schedule(_psum_text())
    assert len(sched) == 1
    e = sched[0]
    assert e["kind"] == "all-reduce" and e["variant"] == "sync"
    assert e["replica_groups"] == "{{0,1,2,3,4,5,6,7}}"
    assert e["dtypes"] == ["f32"] and e["bytes"] == 4 * 4  # f32[4] shard
    assert e["region"] is None


HLO_REGIONS = """
%body.1 (p: f32[4]) -> f32[4] {
  %ar.in = f32[4]{0} all-reduce(f32[4]{0} %p), channel_id=2, replica_groups={{0,1,2,3},{4,5,6,7}}, to_apply=%add
}
ENTRY %main.2 (q: f32[8]) -> f32[8] {
  %ag-start = (f32[1]{0}, f32[8]{0}) all-gather-start(f32[1]{0} %q), channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, use_global_device_ids=true, dimensions={0}
  %ag-done = f32[8]{0} all-gather-done((f32[1]{0}, f32[8]{0}) %ag-start)
}
"""


def test_compiled_hlo_schedule_regions_channels_async():
    sched = spmd.collective_schedule(HLO_REGIONS)
    assert [e["kind"] for e in sched] == ["all-reduce", "all-gather"]
    ar, ag = sched
    # the non-entry computation names the region; ENTRY is top level
    assert ar["region"] == "body.1" and ag["region"] is None
    assert ar["channel_id"] == 2
    assert ar["replica_groups"] == "{{0,1,2,3},{4,5,6,7}}"
    # the async pair yields ONE entry, result-buffer bytes, global ids
    assert ag["variant"] == "async" and ag["bytes"] == 8 * 4
    assert ag["use_global_device_ids"] is True


def test_fingerprint_ignores_text_layout_but_not_payload():
    text = _psum_text()
    sched = spmd.collective_schedule(text)
    shifted = spmd.collective_schedule("\n\n\n" + text)
    assert [e["lineno"] for e in sched] != [e["lineno"] for e in shifted]
    # lineno is layout, not semantics: fingerprints must agree
    assert spmd.schedule_fingerprint(sched) == \
        spmd.schedule_fingerprint(shifted)
    # ... and the opcode-only digest is a coarser hash than the full one
    assert spmd.schedule_fingerprint(sched, opcodes_only=True) != \
        spmd.schedule_fingerprint(sched)
    bumped = [dict(sched[0], bytes=sched[0]["bytes"] * 2)]
    assert spmd.schedule_fingerprint(bumped) != \
        spmd.schedule_fingerprint(sched)


def test_first_divergence_names_end_of_schedule():
    a = spmd.collective_schedule(_psum_text())
    assert spmd.first_divergence(a, list(a)) is None
    d = spmd.first_divergence(a, [])
    assert d is not None and d[0] == 0
    assert d[1].startswith("all-reduce(") and d[2] == "<end of schedule>"


# ---------------------------------------------------------------------------
# the four finding ids fire on seeded fixtures
# ---------------------------------------------------------------------------

def test_seeded_schedule_mismatch_fires():
    """One rank lowers an extra collective: the static deadlock."""
    findings = spmd.compare_lowerings(
        {"rank 0": _psum_text(), "rank 7": _psum_text(extra=True)})
    assert _ops(findings) == ["spmd-schedule-mismatch"]
    f = findings[0]
    assert f.severity == "error" and f.count == 1
    assert "deadlock" in f.message
    assert "<end of schedule>" in f.example


def test_identical_lowerings_are_quiet():
    assert spmd.compare_lowerings(
        {"rank 0": _psum_text(), "rank 1": _psum_text()}) == []


HLO_GROUPS_A = """
ENTRY %main.1 (p: f32[4]) -> f32[4] {
  %ar = f32[4]{0} all-reduce(f32[4]{0} %p), channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, use_global_device_ids=true, to_apply=%add
}
"""
HLO_GROUPS_B = HLO_GROUPS_A.replace("{{0,1,2,3,4,5,6,7}}",
                                    "{{0,1,2,3},{4,5,6,7}}")


def test_seeded_group_mismatch_fires():
    """Same op sequence, different replica_groups: ranks rendezvous on
    mismatched channels."""
    findings = spmd.diff_schedules(
        "rank 0", spmd.collective_schedule(HLO_GROUPS_A),
        "rank 5", spmd.collective_schedule(HLO_GROUPS_B))
    assert _ops(findings) == ["spmd-group-mismatch"]
    assert findings[0].severity == "error"
    assert "groups={{0,1,2,3,4,5,6,7}}" in findings[0].example
    assert "groups={{0,1,2,3},{4,5,6,7}}" in findings[0].example


def test_seeded_signsgd_bytes_mismatch_fires():
    """The fork's signSGD hack: one rank's gradient bucket travels
    sign-compressed at fp32 wire width while its peers send bf16 — the
    payload halves of the same all-reduce disagree."""
    def make(cfg):
        sm = shard_map(lambda g: reduce_gradients(g, "data", cfg),
                       mesh=mesh(), in_specs=P(), out_specs=P())
        return _text(sm, jnp.ones((16,), jnp.bfloat16))

    findings = spmd.diff_schedules(
        "rank 0", spmd.collective_schedule(make(ReduceConfig())),
        "rank 7", spmd.collective_schedule(make(ReduceConfig(
            allreduce_always_fp32=True, compression="sign"))))
    assert _ops(findings) == ["spmd-bytes-mismatch"]
    f = findings[0]
    assert f.severity == "error" and "signSGD" in f.message
    assert "bf16" in f.example and "f32" in f.example


def test_seeded_conditional_collective_fires():
    """A psum only some ranks reach: the enclosing branch predicate is
    derived from the rank index."""
    def f(x):
        return jax.lax.cond(jax.lax.axis_index("data") < 4,
                            lambda v: jax.lax.psum(v, "data"),
                            lambda v: v, x)

    sm = shard_map(f, mesh=mesh(), in_specs=P("data"),
                   out_specs=P("data"))
    text = _text(sm, jnp.ones((8, 4), jnp.float32))
    findings = spmd.conditional_collective_findings(text)
    assert "spmd-conditional-collective" in _ops(findings)
    f0 = [x for x in findings if x.op == "spmd-conditional-collective"][0]
    assert f0.severity == "error" and f0.lineno
    assert "rank-divergent predicate" in f0.message


def test_unconditional_collective_is_quiet():
    assert spmd.conditional_collective_findings(_psum_text()) == []


# ---------------------------------------------------------------------------
# reshape pairs: opcode sequence must survive a mesh reshape
# ---------------------------------------------------------------------------

def test_reshape_pair_opcode_consistent_is_info():
    findings = spmd.reshape_pair_findings(
        "mesh8", _psum_text(n=8), "mesh4", _psum_text(n=4))
    assert _ops(findings) == ["reshape-pair"]
    assert findings[0].severity == "info"
    assert "opcode-consistent" in findings[0].message


def test_reshape_pair_changed_sequence_is_error():
    findings = spmd.reshape_pair_findings(
        "mesh8", _psum_text(n=8), "mesh4", _psum_text(extra=True, n=4))
    assert _ops(findings) == ["spmd-schedule-mismatch"]
    assert "deadlock" in findings[0].message


# ---------------------------------------------------------------------------
# the registered pass
# ---------------------------------------------------------------------------

def test_spmd_pass_registered_and_reports_schedule():
    def f(x):
        return jax.lax.psum(x, "data")

    sm = jax.jit(shard_map(f, mesh=mesh(), in_specs=P("data"),
                           out_specs=P()))
    rep = analysis.analyze(sm, jnp.ones((8, 4), jnp.float32),
                           passes=("spmd-consistency",), compile=False)
    assert rep.ok and rep.passes == ("spmd-consistency",)
    sched_info = [f_ for f_ in rep.findings if f_.op == "schedule"]
    assert len(sched_info) == 1 and sched_info[0].count == 1


def test_spmd_pass_peers_option_diffs_against_context():
    def f(x):
        return jax.lax.psum(x, "data")

    sm = jax.jit(shard_map(f, mesh=mesh(), in_specs=P("data"),
                           out_specs=P()))
    rep = analysis.analyze(
        sm, jnp.ones((8, 4), jnp.float32),
        passes=("spmd-consistency",), compile=False,
        options={"spmd-consistency":
                 {"peers": {"rank 7": _psum_text(extra=True)}}})
    assert not rep.ok
    assert "spmd-schedule-mismatch" in [f_.op for f_ in rep.findings]


# ---------------------------------------------------------------------------
# collective_table wiring attributes (satellite: parser pins)
# ---------------------------------------------------------------------------

def test_canon_groups_spellings():
    assert canon_groups("{{0,1},{2,3}}") == "{{0,1},{2,3}}"
    # StableHLO dense form (whitespace and 2D brackets normalized)
    assert canon_groups("[[0, 1], [2, 3]]") == "{{0,1},{2,3}}"
    # iota form survives verbatim (no literal groups to normalize)
    assert canon_groups("[2,4]<=[8]") == "[2,4]<=[8]"


def test_collective_attrs_absent_defaults():
    attrs = collective_attrs("  %ar = f32[4]{0} all-reduce(f32[4]{0} %p)")
    assert attrs == {"channel_id": None, "replica_groups": None,
                     "use_global_device_ids": False}


def test_collective_table_records_channel_wiring():
    table = collective_table(HLO_REGIONS)
    ar, ag = table["all-reduce"], table["all-gather"]
    assert ar["channels"] == [2] and ag["channels"] == [1]
    assert ar["replica_groups"] == ["{{0,1,2,3},{4,5,6,7}}"]
    assert ag["replica_groups"] == ["{{0,1,2,3,4,5,6,7}}"]
    assert ag["global_ids"] == 1 and ar["global_ids"] == 0
    # the dryrun-compat audit shape is unchanged: {count, bytes} only
    assert collective_audit(HLO_REGIONS)["all-gather"] == {
        "count": 1, "bytes": 8 * 4}


# ---------------------------------------------------------------------------
# the runtime preflight (single process — the degenerate barrier)
# ---------------------------------------------------------------------------

def test_spmd_preflight_single_process_records_hashes():
    text = _psum_text()
    rec = multiproc.spmd_preflight(text, label="unit")
    assert rec["ok"] and rec["label"] == "unit"
    assert rec["n_ranks"] == 1 and rec["n_collectives"] == 1
    assert rec["schedule_hash"] == spmd.schedule_fingerprint(
        spmd.collective_schedule(text))
    # a zero-arg callable (the initialize() deferred form) works too
    rec2 = multiproc.spmd_preflight(lambda: text, label="unit")
    assert rec2["schedule_hash"] == rec["schedule_hash"]


def test_spmd_preflight_rejects_garbage():
    with pytest.raises(TypeError, match="lowering"):
        multiproc.spmd_preflight(42)


# ---------------------------------------------------------------------------
# FLEETLINT schema: contradiction-rejecting
# ---------------------------------------------------------------------------

def _valid_fleetlint():
    rank = {"schedule_hash": "a" * 64, "opcode_hash": "b" * 64,
            "n_collectives": 3}
    return {
        "round": 1, "platform": "cpu", "n_ranks": 8,
        "lanes": {
            "ddp_o1_train": {"compare": "schedule", "consistent": True,
                             "ranks": {"0": dict(rank), "1": dict(rank)},
                             "findings": {"info": 1}, "mismatches": []},
            "reshape_8to4": {"compare": "opcodes", "consistent": True,
                             "ranks": {"mesh8": dict(rank),
                                       "mesh4": dict(
                                           rank, schedule_hash="c" * 64)},
                             "mismatches": []},
        },
        "gate": {"ok": True, "inconsistent_lanes": 0},
    }


def test_valid_fleetlint_passes():
    assert validate_fleetlint(_valid_fleetlint()) == []


def test_fleetlint_contradictory_lane_verdict_rejected():
    """consistent=true over disagreeing recorded hashes is the lie the
    schema exists to reject (and vice versa)."""
    doc = _valid_fleetlint()
    doc["lanes"]["ddp_o1_train"]["ranks"]["1"]["schedule_hash"] = "d" * 64
    doc["lanes"]["ddp_o1_train"]["mismatches"] = [
        {"ranks": ["0", "1"], "index": 0, "a": "x", "b": "y"}]
    probs = validate_fleetlint(doc)
    assert any("contradicts" in p for p in probs)
    doc2 = _valid_fleetlint()
    doc2["lanes"]["ddp_o1_train"]["consistent"] = False
    assert any("contradicts" in p for p in validate_fleetlint(doc2))


def test_fleetlint_mismatch_rows_must_name_the_diverging_op():
    doc = _valid_fleetlint()
    lane = doc["lanes"]["ddp_o1_train"]
    lane["consistent"] = False
    lane["ranks"]["1"]["schedule_hash"] = "d" * 64
    # hashes disagree but no mismatch row: rejected
    probs = validate_fleetlint(doc)
    assert any("no mismatch row" in p for p in probs)
    lane["mismatches"] = [{"ranks": ["0", "nope"], "index": -1, "a": ""}]
    doc["gate"] = {"ok": False, "inconsistent_lanes": 1}
    probs = validate_fleetlint(doc)
    assert any("two recorded rank labels" in p for p in probs)
    assert any("'index'" in p for p in probs)
    assert any("side 'b'" in p for p in probs)


def test_fleetlint_gate_must_agree_with_lanes():
    doc = _valid_fleetlint()
    doc["gate"]["inconsistent_lanes"] = 2
    assert any("contradicts the lanes" in p for p in validate_fleetlint(doc))
    doc["gate"] = {"ok": False, "inconsistent_lanes": 0}
    assert any("gate.ok=False contradicts" in p
               for p in validate_fleetlint(doc))


def test_fleetlint_needs_two_sides_per_lane():
    doc = _valid_fleetlint()
    lane = doc["lanes"]["ddp_o1_train"]
    lane["ranks"] = {"0": lane["ranks"]["0"]}
    assert any("proves nothing" in p for p in validate_fleetlint(doc))


def test_repo_fleetlint_artifact_validates():
    """The committed FLEETLINT round is the schema's reference
    instance."""
    paths = sorted(REPO.glob("FLEETLINT_r*.json"))
    assert paths, "the fleet SPMD gate artifact must be committed"
    for p in paths:
        assert validate_fleetlint_file(str(p)) == [], p


# ---------------------------------------------------------------------------
# graph_lint fleet lanes
# ---------------------------------------------------------------------------

def test_fleet_ddp_lane_consistent_at_two_ranks():
    import graph_lint
    findings, rec = graph_lint.fleet_lane_result("ddp_o1_train", n_ranks=2)
    assert rec["compare"] == "schedule" and rec["consistent"]
    assert set(rec["ranks"]) == {"0", "1"} and rec["mismatches"] == []
    assert all(f.severity != "error" for f in findings)
    assert rec["ranks"]["0"]["n_collectives"] >= 2  # grad reduce + pmean


def test_fleet_reshape_lane_opcode_consistent():
    import graph_lint
    findings, rec = graph_lint.fleet_lane_result("reshape_8to4")
    assert rec["compare"] == "opcodes" and rec["consistent"]
    assert set(rec["ranks"]) == {"mesh8", "mesh4"}
    # a reshape legally changes groups, so the FULL hashes differ ...
    hashes = {r["schedule_hash"] for r in rec["ranks"].values()}
    assert len(hashes) == 2
    # ... while the opcode hashes agree (that is the lane's verdict)
    assert len({r["opcode_hash"] for r in rec["ranks"].values()}) == 1


def test_lint_fleet_skips_unrequested_passes():
    import graph_lint
    assert graph_lint.lint_fleet("ddp_o1_train",
                                 passes=("memory",)).passes == ()


def test_cli_fleet_lane_dispatch(monkeypatch, capsys):
    import graph_lint
    orig = graph_lint.lint_fleet

    def two_rank(lane, passes=None, n_ranks=None, _collect=None):
        return orig(lane, passes=passes, n_ranks=2, _collect=_collect)

    monkeypatch.setattr(graph_lint, "lint_fleet", two_rank)
    assert graph_lint.main(["--lanes", "fleet",
                            "--passes", "spmd-consistency"]) == 0
    out = capsys.readouterr().out
    for lane in graph_lint.FLEET_LANES:
        assert f'"lane": "{lane}"' in out
    for line in out.splitlines():
        rec = json.loads(line)
        assert rec["ok"], rec


def test_cli_emit_fleetlint_refuses_partial_configs():
    import graph_lint
    # the committed artifact must always cover the full lane/pass matrix
    with pytest.raises(SystemExit):
        graph_lint.main(["--emit-json", "FLEETLINT_r99.json",
                         "--lanes", "o1"])
    with pytest.raises(SystemExit):
        graph_lint.main(["--emit-json", "FLEETLINT_r99.json",
                         "--passes", "memory"])
    with pytest.raises(SystemExit):
        graph_lint.main(["--emit-json", "FLEETLINT_r99.json",
                         "--families", "mlp"])


def test_emit_fleetlint_writes_schema_valid_doc(tmp_path, monkeypatch):
    """The emitter and the schema can never drift: a (canned) emit
    round-trips through the validator."""
    import graph_lint

    rank = {"schedule_hash": "a" * 64, "opcode_hash": "b" * 64,
            "n_collectives": 3}

    def canned(lane, n_ranks=8):
        return [], {"compare": "schedule", "consistent": True,
                    "ranks": {"0": dict(rank), "1": dict(rank)},
                    "mismatches": []}

    monkeypatch.setattr(graph_lint, "fleet_lane_result", canned)
    path = tmp_path / "FLEETLINT_r07.json"
    assert graph_lint.emit_fleetlint(str(path)) == 0
    assert validate_fleetlint_file(str(path)) == []
    doc = json.loads(path.read_text())
    assert doc["round"] == 7
    assert set(doc["lanes"]) == set(graph_lint.FLEET_LANES)
