"""FusedLAMB unit tests.

The reference has no Python LAMB driver to test against (SURVEY.md §0: the
kernels exist, the optimizer never did), so the contract here is
(a) the authored driver's semantics — trust-ratio scaling, global-norm clip,
    decoupled-into-update weight decay — and
(b) pallas/jnp path equivalence, the ext-vs-no-ext axis of the reference L1
    harness applied to the LAMB stage1/2 kernels
    (``csrc/multi_tensor_lamb_stage_{1,2}.cu``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.optimizers import fused_lamb


def make_params(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rng.randn(37, 53).astype(np.float32)) * 0.1,
        "b": jnp.asarray(rng.randn(53).astype(np.float32)) * 0.01,
        "scalar": jnp.asarray(0.7, jnp.float32),
        "deep": {"k": jnp.asarray(rng.randn(8, 3, 5).astype(np.float32))},
    }


def run_steps(params, n_steps=4, seed=1, **kw):
    tx = fused_lamb(learning_rate=1e-2, **kw)
    state = tx.init(params)
    rng = np.random.RandomState(seed)
    for _ in range(n_steps):
        grads = jax.tree.map(
            lambda p: jnp.asarray(
                np.asarray(rng.randn(*p.shape), np.float32)), params)
        updates, state = tx.update(grads, state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
    return params, state


def test_step_moves_params():
    params = make_params()
    new_params, state = run_steps(params, n_steps=2)
    assert int(state.step) == 2
    moved = jax.tree.map(
        lambda a, b: float(jnp.abs(a - b).max()) > 0, params, new_params)
    assert all(jax.tree.leaves(moved))


def test_trust_ratio_scales_step():
    # Two identical-gradient tensors with different weight norms must get
    # different effective steps (stage 2's ‖p‖/‖update‖ ratio).
    params = {"small": jnp.full((64,), 0.01, jnp.float32),
              "big": jnp.full((64,), 10.0, jnp.float32)}
    tx = fused_lamb(learning_rate=1e-2, weight_decay=0.0, max_grad_norm=0.0)
    state = tx.init(params)
    grads = {"small": jnp.ones((64,), jnp.float32),
             "big": jnp.ones((64,), jnp.float32)}
    updates, _ = tx.update(grads, state, params)
    assert float(jnp.abs(updates["big"]).mean()) > \
        float(jnp.abs(updates["small"]).mean()) * 10


def test_global_norm_clip():
    params = {"w": jnp.ones((128,), jnp.float32)}
    big = {"w": jnp.full((128,), 100.0, jnp.float32)}
    u_clip, _ = fused_lamb(learning_rate=1e-2, max_grad_norm=1.0).update(
        big, fused_lamb().init(params), params)
    u_more, _ = fused_lamb(learning_rate=1e-2, max_grad_norm=1.0).update(
        {"w": big["w"] * 10}, fused_lamb().init(params), params)
    # Once clipping engages, scaling the gradient up changes nothing.
    np.testing.assert_allclose(np.asarray(u_clip["w"]),
                               np.asarray(u_more["w"]), rtol=1e-6)


@pytest.mark.parametrize("weight_decay", [0.0, 0.01])
@pytest.mark.parametrize("max_grad_norm", [0.0, 1.0])
def test_pallas_matches_jnp(monkeypatch, weight_decay, max_grad_norm):
    params = make_params()
    kw = dict(weight_decay=weight_decay, max_grad_norm=max_grad_norm,
              scale=2.0)
    monkeypatch.setenv("APEX_TPU_KERNELS", "jnp")
    ref_params, ref_state = run_steps(params, n_steps=3, **kw)
    monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")
    got_params, got_state = run_steps(params, n_steps=3, **kw)
    for r, o in zip(jax.tree.leaves(ref_params), jax.tree.leaves(got_params)):
        np.testing.assert_allclose(np.asarray(r), np.asarray(o),
                                   rtol=2e-5, atol=1e-7)
    for r, o in zip(jax.tree.leaves(ref_state.m) + jax.tree.leaves(ref_state.v),
                    jax.tree.leaves(got_state.m) + jax.tree.leaves(got_state.v)):
        np.testing.assert_allclose(np.asarray(r), np.asarray(o),
                                   rtol=2e-5, atol=1e-7)


def test_pallas_bias_correction_off(monkeypatch):
    params = make_params(seed=3)
    monkeypatch.setenv("APEX_TPU_KERNELS", "jnp")
    ref, _ = run_steps(params, n_steps=2, bias_correction=False)
    monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")
    got, _ = run_steps(params, n_steps=2, bias_correction=False)
    for r, o in zip(jax.tree.leaves(ref), jax.tree.leaves(got)):
        np.testing.assert_allclose(np.asarray(r), np.asarray(o),
                                   rtol=2e-5, atol=1e-7)


@pytest.mark.skipif(jax.default_backend() == "cpu",
                    reason="reproduces a TPU AOT layout pathology; "
                    "run with APEX_TPU_TEST_PLATFORM")
def test_packed_lamb_at_bert_base_scale():
    """Regression: a ~133M-param, 159-leaf tree (bert-base shape census)
    must pack/update/unpack without the (N/2, 2) pairs intermediate whose
    (8,128)-tiled layout allocates 64x the buffer (34 GB observed) — the
    reason pack_aligned concatenates chunk-shaped rows and unpack_aligned
    slices rows, not 1-D offsets."""
    from apex_tpu.models.bert import BertForPreTraining, bert_base
    from apex_tpu.optimizers.fused_lamb import _pallas_lamb_update

    model = BertForPreTraining(bert_base())
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.ones((1, 8), jnp.int32)))["params"]
    ps = [jnp.full(l.shape, 0.01, jnp.float32)
          for l in jax.tree.leaves(shapes)]
    gs = [jnp.full(p.shape, 1e-4, jnp.float32) for p in ps]
    zs = [jnp.zeros(p.shape, jnp.float32) for p in ps]

    bc = jnp.ones((len(ps),), jnp.float32)  # per-tensor (n_tensors,) tables

    @jax.jit
    def upd(gs, ps, ms, vs):
        deltas, nm, nv = _pallas_lamb_update(
            gs, ps, ms, vs, lr=jnp.float32(1e-3), beta1=0.9, beta2=0.999,
            eps=1e-6, weight_decay=0.01, clip=jnp.float32(1.0),
            bc1=bc, bc2=bc)
        return sum(jnp.sum(d.astype(jnp.float32)) for d in deltas)

    out = float(upd(gs, ps, zs, zs))
    assert np.isfinite(out) and out != 0.0
