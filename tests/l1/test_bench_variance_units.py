"""Units for tools/bench_variance.py (the real N≥5 runs happen on the
driver's chip): the stats shapes, the artifact contract the floor
no-ratchet-down rule consumes, and a CPU-safe tiny smoke.
"""

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO / "tools"))
sys.path.insert(0, str(REPO))

import bench_variance as bv  # noqa: E402


def test_stats_shape():
    s = bv._stats([1.0, 1.1, 0.9])
    assert s["n"] == 3 and s["mean"] == 1.0
    assert s["min"] == 0.9 and s["max"] == 1.1
    assert abs(s["rel_spread"] - 0.2) < 1e-9


def test_tiny_smoke_writes_consumable_artifact(tmp_path):
    """End-to-end at tiny N on CPU: the artifact parses, carries the
    tiny marker (so it can never justify a floor drop), and its entry
    keys match what bench.floor_change_allowed looks up."""
    out = tmp_path / "BENCH_VARIANCE.json"
    rc = bv.main(["--out", str(out), "--n", "2", "--tiny",
                  "--kernels", "mt_scale,fused_adam"])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["tiny"] is True
    for key in ("kernel:mt_scale", "kernel:fused_adam"):
        entry = doc["entries"][key]
        assert "error" not in entry, entry
        assert entry["metric"] == "ms_per_step" and entry["n"] == 2
        assert entry["rel_spread"] is not None
        assert "geometry" in entry

    import bench
    # a tiny artifact is NOT evidence for lowering a floor...
    assert not bench.floor_change_allowed("mt_scale", 0.75, 0.70, doc,
                                          kind="kernel")
    # ...but the same shape without the tiny marker and a covering
    # spread is — the exact consumption path of the erosion guard
    real = dict(doc, tiny=False)
    real["entries"]["kernel:mt_scale"]["rel_spread"] = 0.10
    assert bench.floor_change_allowed("mt_scale", 0.75, 0.70, real,
                                      kind="kernel")


def test_unknown_names_recorded_not_fatal(tmp_path):
    out = tmp_path / "V.json"
    rc = bv.main(["--out", str(out), "--n", "1", "--tiny",
                  "--kernels", "no_such_kernel"])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["entries"]["kernel:no_such_kernel"]["error"] \
        == "unknown kernel"


def test_stats_carry_std_and_validate():
    """The extended schema statistic: sample std recorded next to the
    spread, and every summary re-derivable from the recorded values
    (the variance schema's contradiction bar)."""
    s = bv._stats([1.0, 1.1, 0.9, 1.05, 0.95])
    assert s["std"] > 0
    from apex_tpu.analysis.variance import validate_variance
    doc = {"platform": "cpu", "tiny": False,
           "entries": {"kernel:k": {"metric": "ms_per_step", **s}}}
    assert validate_variance(doc) == []
    lied = dict(doc, entries={"kernel:k": {**s, "std": 9.0,
                                           "metric": "ms"}})
    assert any("std" in p for p in validate_variance(lied))


def test_round_numbered_artifact_schema_validated(tmp_path):
    """--round N emits BENCH_VARIANCE_rNN.json, schema-validated
    before writing, with the roofline_frac sub-stat the kernel floor
    derivation consumes."""
    out = tmp_path / "BENCH_VARIANCE_r07.json"
    rc = bv.main(["--out", str(out), "--round", "7", "--n", "2",
                  "--tiny", "--kernels", "mt_scale"])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["round"] == 7
    from apex_tpu.analysis.variance import validate_variance
    assert validate_variance(doc) == []
    sub = doc["entries"]["kernel:mt_scale"]["roofline_frac"]
    assert sub["n"] == 2 and sub["mean"] > 0


def test_load_variance_prefers_round_numbered(tmp_path):
    import bench
    (tmp_path / "BENCH_VARIANCE.json").write_text(
        '{"tiny": true, "entries": {}, "legacy": 1}')
    assert bench.load_variance(str(tmp_path))["legacy"] == 1
    (tmp_path / "BENCH_VARIANCE_r01.json").write_text(
        '{"tiny": true, "entries": {}, "round": 1}')
    (tmp_path / "BENCH_VARIANCE_r02.json").write_text(
        '{"tiny": true, "entries": {}, "round": 2}')
    assert bench.load_variance(str(tmp_path))["round"] == 2
