"""Units for tools/bench_variance.py (the real N≥5 runs happen on the
driver's chip): the stats shapes, the artifact contract the floor
no-ratchet-down rule consumes, and a CPU-safe tiny smoke.
"""

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO / "tools"))
sys.path.insert(0, str(REPO))

import bench_variance as bv  # noqa: E402


def test_stats_shape():
    s = bv._stats([1.0, 1.1, 0.9])
    assert s["n"] == 3 and s["mean"] == 1.0
    assert s["min"] == 0.9 and s["max"] == 1.1
    assert abs(s["rel_spread"] - 0.2) < 1e-9


def test_tiny_smoke_writes_consumable_artifact(tmp_path):
    """End-to-end at tiny N on CPU: the artifact parses, carries the
    tiny marker (so it can never justify a floor drop), and its entry
    keys match what bench.floor_change_allowed looks up."""
    out = tmp_path / "BENCH_VARIANCE.json"
    rc = bv.main(["--out", str(out), "--n", "2", "--tiny",
                  "--kernels", "mt_scale,fused_adam"])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["tiny"] is True
    for key in ("kernel:mt_scale", "kernel:fused_adam"):
        entry = doc["entries"][key]
        assert "error" not in entry, entry
        assert entry["metric"] == "ms_per_step" and entry["n"] == 2
        assert entry["rel_spread"] is not None
        assert "geometry" in entry

    import bench
    # a tiny artifact is NOT evidence for lowering a floor...
    assert not bench.floor_change_allowed("mt_scale", 0.75, 0.70, doc,
                                          kind="kernel")
    # ...but the same shape without the tiny marker and a covering
    # spread is — the exact consumption path of the erosion guard
    real = dict(doc, tiny=False)
    real["entries"]["kernel:mt_scale"]["rel_spread"] = 0.10
    assert bench.floor_change_allowed("mt_scale", 0.75, 0.70, real,
                                      kind="kernel")


def test_unknown_names_recorded_not_fatal(tmp_path):
    out = tmp_path / "V.json"
    rc = bv.main(["--out", str(out), "--n", "1", "--tiny",
                  "--kernels", "no_such_kernel"])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["entries"]["kernel:no_such_kernel"]["error"] \
        == "unknown kernel"
