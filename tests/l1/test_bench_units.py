"""Units for bench.py's harness pieces (the benchmark itself runs on the
driver's chip): the PJRT-init watchdog and the FLOP-count fallback."""

import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))

import bench  # noqa: E402


def test_probe_devices_returns_devices():
    devices = bench.probe_devices(60)
    assert devices, "CPU backend must enumerate"


def test_probe_devices_times_out_on_hang(monkeypatch):
    monkeypatch.setattr(bench.jax, "devices",
                        lambda *a: time.sleep(30))
    t0 = time.time()
    assert bench.probe_devices(1.0) is None
    assert time.time() - t0 < 5


def test_probe_devices_reraises_init_errors(monkeypatch):
    def boom():
        raise RuntimeError("plugin exploded")
    monkeypatch.setattr(bench.jax, "devices", boom)
    with pytest.raises(RuntimeError, match="plugin exploded"):
        bench.probe_devices(30)


def test_step_flops_fallback():
    class NoCost:
        def cost_analysis(self):
            raise NotImplementedError
    assert bench.step_flops(NoCost(), fallback=123.0) == 123.0

    class ListCost:
        def cost_analysis(self):
            return [{"flops": 7.0}]
    assert bench.step_flops(ListCost(), fallback=0.0) == 7.0

    class ZeroCost:  # some backends report 0 — fall back
        def cost_analysis(self):
            return {"flops": 0.0}
    assert bench.step_flops(ZeroCost(), fallback=5.0) == 5.0


def _write_bench(tmp_path, name, configs):
    import json
    p = tmp_path / name
    p.write_text(json.dumps({"configs": configs}))
    return str(p)


def test_compare_configs_flags_only_real_drops(tmp_path):
    prior = _write_bench(tmp_path, "BENCH_r03.json", {
        "resnet50_o2": {"img_s": 1000.0},
        "gpt_small_o2": {"tok_s": 50000.0},
        "bert_large_lamb_o2": {"seq_s": 100.0},
        "errored_before": {"error": "OOM"},
    })
    verdict = bench.compare_configs(prior, {
        "resnet50_o2": {"img_s": 960.0},        # -4%: within variance
        "gpt_small_o2": {"tok_s": 40000.0},     # -20%: regression
        "bert_large_lamb_o2": {"error": "OOM"},  # errored now: uncompared
        "errored_before": {"seq_s": 5.0},        # errored then: uncompared
        "brand_new_cfg": {"img_s": 1.0},         # no baseline: uncompared
    }, threshold=0.10)
    assert verdict["regressions"] == ["gpt_small_o2"]
    assert not verdict["ok"]
    assert verdict["deltas"]["resnet50_o2"] == -0.04
    assert set(verdict["uncompared"]) == {
        "bert_large_lamb_o2", "errored_before", "brand_new_cfg"}


def test_compare_configs_skips_batch_mismatch(tmp_path):
    """An OOM batch-ladder fallback (bench_gpt) changes the tok/s
    denominator; a config whose batch differs from the baseline's must
    be listed uncompared, not read as a 50% regression."""
    prior = _write_bench(tmp_path, "BENCH_r03.json", {
        "gpt_medium_tpu_o2": {"tok_s": 43500.0, "batch": 8},
        "gpt_small_o2": {"tok_s": 100000.0, "batch": 8},
    })
    verdict = bench.compare_configs(prior, {
        "gpt_medium_tpu_o2": {"tok_s": 25000.0, "batch": 4,
                              "oom_fallback_from_batch": 8},
        "gpt_small_o2": {"tok_s": 99000.0, "batch": 8},
    }, threshold=0.10)
    assert verdict["ok"] and not verdict["regressions"]
    assert "gpt_medium_tpu_o2" in verdict["uncompared"]
    assert verdict["deltas"].keys() == {"gpt_small_o2"}


def test_compare_configs_ok_within_threshold(tmp_path):
    prior = _write_bench(tmp_path, "BENCH_r03.json",
                         {"resnet50_o2": {"img_s": 1000.0}})
    verdict = bench.compare_configs(
        prior, {"resnet50_o2": {"img_s": 930.0}}, threshold=0.10)
    assert verdict["ok"] and not verdict["regressions"]


def test_compare_configs_unwraps_driver_artifact(tmp_path):
    import json
    p = tmp_path / "BENCH_r03.json"  # driver shape: payload under "parsed"
    p.write_text(json.dumps({
        "n": 3, "rc": 0, "tail": "...",
        "parsed": {"configs": {"resnet50_o2": {"img_s": 1000.0}}}}))
    verdict = bench.compare_configs(
        str(p), {"resnet50_o2": {"img_s": 800.0}}, threshold=0.10)
    assert verdict["regressions"] == ["resnet50_o2"]


def test_compare_against_real_r03_artifact():
    # the shipped round-3 artifact must be readable by the gate
    verdict = bench.compare_configs(
        str(REPO / "BENCH_r03.json"),
        {"resnet50_o2": {"img_s": 2461.55}}, threshold=0.10)
    assert verdict["deltas"]["resnet50_o2"] == 0.0
    assert verdict["ok"]


def test_compare_configs_unreadable_baseline_never_fails(tmp_path):
    bad = tmp_path / "BENCH_r99.json"
    bad.write_text("{not json")
    verdict = bench.compare_configs(str(bad), {"a": {"img_s": 1.0}})
    assert verdict["ok"] and "error" in verdict


def test_find_prior_bench_picks_newest_round(tmp_path):
    for n in (1, 3, 2):
        _write_bench(tmp_path, f"BENCH_r{n:02d}.json", {})
    assert bench.find_prior_bench(str(tmp_path)).endswith("BENCH_r03.json")
    assert bench.find_prior_bench(str(tmp_path / "empty")) is None


def test_repo_has_prior_bench_artifact():
    # the real repo carries round artifacts; the default gate must find one
    assert bench.find_prior_bench(str(REPO)) is not None


def test_mfu_vs_hfu_pass_counts():
    # MFU books 6 analytic attention passes (PaLM model-FLOPs
    # convention); HFU books the 7 the fused backward actually runs.
    assert bench.ATTN_MODEL_PASSES == 6
    assert bench.ATTN_FUSED_EXEC_PASSES == 7


def test_pallas_attn_compiled_detection():
    class Hlo:
        def __init__(self, txt):
            self._txt = txt

        def as_text(self):
            return self._txt

    # detection must be attention-specific: a fused-optimizer or
    # layer-norm custom call in the step must NOT vouch for the
    # attention kernel path (it would re-introduce the double count)
    assert bench._pallas_attn_compiled(Hlo(
        '%jvp_jit__flash_fwd__.1 = custom-call(...), '
        'custom_call_target="tpu_custom_call", metadata={op_name='
        '"jit(f)/jvp(jit(_flash_fwd))/pallas_call"}'))
    assert bench._pallas_attn_compiled(Hlo(
        'op_name="jit(f)/transpose(jvp(jit(_flash_bwd_fused)))/'
        'pallas_call"'))
    assert not bench._pallas_attn_compiled(Hlo(
        '%_lamb_stage1.3 = custom-call(...), '
        'custom_call_target="tpu_custom_call"'))
    assert not bench._pallas_attn_compiled(Hlo("fusion(...) dot(...)"))

    class NoText:
        def as_text(self):
            raise NotImplementedError
    assert bench._pallas_attn_compiled(NoText()) is None


def test_compare_configs_lists_prior_only_and_ungated(tmp_path):
    prior = _write_bench(tmp_path, "BENCH_r03.json", {
        "gpt_small_o2": {"tok_s": 50000.0},
        "resnet50_o2_hoststream": {"img_s": 400.0},
        "deleted_config": {"img_s": 9.0},
    })
    verdict = bench.compare_configs(prior, {
        "gpt_small_o2": {"tok_s": 49000.0},
        # wire-speed config: a 50% swing must NOT fail the gate
        "resnet50_o2_hoststream": {"img_s": 200.0},
    }, threshold=0.10)
    assert verdict["ok"]
    assert "resnet50_o2_hoststream" in verdict["uncompared"]
    assert "deleted_config" in verdict["uncompared"]  # baseline-only


def test_compare_configs_wrong_shape_baselines_never_crash(tmp_path):
    import json
    for i, payload in enumerate(
            ('{"configs": null}', "[1, 2, 3]", '{"parsed": 7}', "3")):
        p = tmp_path / f"BENCH_r9{i}.json"
        p.write_text(payload)
        verdict = bench.compare_configs(str(p), {"a": {"img_s": 1.0}})
        assert verdict["ok"] and "error" in verdict, payload


def test_compare_configs_ladder_substitutes_same_batch(tmp_path):
    """A batch-mismatched config with a persisted same-batch ladder
    baseline is gated like-for-like instead of listed uncompared
    (VERDICT r4 next #4)."""
    prior = _write_bench(tmp_path, "BENCH_r04.json", {
        "gpt_medium_tpu_o2": {"tok_s": 43500.0, "batch": 8},
    })
    ladder = {"gpt_medium_tpu_o2": {
        "4": {"tok_s": 50000.0, "batch": 4, "recorded": "2026-08-01"}}}
    # like-for-like b4-vs-b4: -4% is fine
    verdict = bench.compare_configs(prior, {
        "gpt_medium_tpu_o2": {"tok_s": 48000.0, "batch": 4}},
        threshold=0.10, ladder=ladder)
    assert verdict["ok"]
    assert verdict["deltas"]["gpt_medium_tpu_o2"] == -0.04
    assert verdict["ladder_compared"]["gpt_medium_tpu_o2"]["batch"] == 4
    # a real 20% drop vs the same-batch ladder rung DOES trip the gate
    verdict = bench.compare_configs(prior, {
        "gpt_medium_tpu_o2": {"tok_s": 40000.0, "batch": 4}},
        threshold=0.10, ladder=ladder)
    assert verdict["regressions"] == ["gpt_medium_tpu_o2"]
    # no ladder entry for the batch -> still uncompared, never guessed
    verdict = bench.compare_configs(prior, {
        "gpt_medium_tpu_o2": {"tok_s": 40000.0, "batch": 6}},
        threshold=0.10, ladder=ladder)
    assert "gpt_medium_tpu_o2" in verdict["uncompared"]


def test_compare_configs_ladder_covers_errored_prior(tmp_path):
    """The OOM scenario the ladder exists for: the prior round's entry
    ERRORED (or is missing entirely) — the same-batch rung must still
    gate the config instead of leaving it uncompared."""
    prior = _write_bench(tmp_path, "BENCH_r04.json", {
        "gpt_medium_tpu_o2": {"error": "RESOURCE_EXHAUSTED ..."},
    })
    ladder = {"gpt_medium_tpu_o2": {
        "4": {"tok_s": 50000.0, "batch": 4, "recorded": "2026-08-01"}}}
    verdict = bench.compare_configs(prior, {
        "gpt_medium_tpu_o2": {"tok_s": 40000.0, "batch": 4}},
        threshold=0.10, ladder=ladder)
    assert verdict["regressions"] == ["gpt_medium_tpu_o2"]
    assert verdict["ladder_compared"]["gpt_medium_tpu_o2"]["batch"] == 4
    # prior missing the config entirely: same story
    prior2 = _write_bench(tmp_path, "BENCH_r05.json", {})
    verdict = bench.compare_configs(prior2, {
        "gpt_medium_tpu_o2": {"tok_s": 49500.0, "batch": 4}},
        threshold=0.10, ladder=ladder)
    assert verdict["ok"]
    assert verdict["deltas"]["gpt_medium_tpu_o2"] == -0.01


def test_ladder_baselines_roundtrip(tmp_path):
    configs = {
        "gpt_medium_tpu_o2": {"tok_s": 49000.0, "batch": 4, "mfu": 0.58},
        "errored": {"error": "OOM"},
        "no_batch": {"tok_s": 5.0},
    }
    bench.update_ladder_baselines(str(tmp_path), configs)
    doc = bench.load_ladder_baselines(str(tmp_path))
    assert doc["gpt_medium_tpu_o2"]["4"]["tok_s"] == 49000.0
    assert "recorded" in doc["gpt_medium_tpu_o2"]["4"]
    assert "errored" not in doc and "no_batch" not in doc
    # updating a new rung keeps the old one
    bench.update_ladder_baselines(
        str(tmp_path), {"gpt_medium_tpu_o2": {"tok_s": 44000.0,
                                              "batch": 8}})
    doc = bench.load_ladder_baselines(str(tmp_path))
    assert set(doc["gpt_medium_tpu_o2"]) == {"4", "8"}


def test_repo_ladder_has_medium_b4_baseline():
    # the gate must be able to compare a b4 OOM-ladder landing
    doc = bench.load_ladder_baselines(str(REPO))
    assert doc["gpt_medium_tpu_o2"]["4"]["tok_s"] > 0


def test_mfu_floor_gate():
    floors = bench.MFU_FLOORS
    assert "resnet50_o2" in floors and "gpt_medium_tpu_o2" in floors
    gate = floors["resnet50_o2"] * (1 - bench.MFU_VARIANCE_BAND)
    # r4's measured 0.2983 (0.6% under the prose floor, inside chip-day
    # variance) passes the banded gate — the VERDICT weak-#2 resolution
    check = bench.check_mfu_floors({"resnet50_o2": {"mfu": 0.2983}})
    assert check["ok"] and check["checked"]["resnet50_o2"]["ok"]
    assert check["checked"]["resnet50_o2"]["gate"] == round(gate, 4)
    # a real efficiency loss does not
    check = bench.check_mfu_floors({"resnet50_o2": {"mfu": 0.27}})
    assert not check["ok"] and check["violations"] == ["resnet50_o2"]
    # errored/skipped/missing configs are not judged
    check = bench.check_mfu_floors({"resnet50_o2": {"error": "OOM"},
                                    "gpt_small_o2": {"mfu": None}})
    assert check["ok"] and not check["checked"]


def test_mfu_floors_cover_all_gated_tpu_configs():
    """Every non-wire-coupled TPU config with an MFU number must carry
    a published floor — a floor-less config is ungated efficiency."""
    import json
    doc = json.load(open(REPO / "BENCH_r04.json"))
    cfgs = doc.get("parsed", doc)["configs"]
    for name, rec in cfgs.items():
        if name in bench.UNGATED_CONFIGS or "mfu" not in rec:
            continue
        assert name in bench.MFU_FLOORS, name
        # floors sit at-or-below the r4 measured value: the gate fires
        # on future regressions, not retroactively
        assert bench.MFU_FLOORS[name] * (1 - bench.MFU_VARIANCE_BAND) \
            <= rec["mfu"], name


def test_bench_generate_tiny_cpu():
    """The decode bench path runs end-to-end on CPU with the tiny
    config (the real config runs on the driver's chip)."""
    r = bench.bench_generate(batch=2, prefill=16, new_tokens=8,
                             warmup=0, iters=1, peak=None, tiny=True)
    assert r["tok_s"] > 0 and r["batch"] == 2
    assert r["hbm_tok_s_ceiling"] > 0 and r["prefill"] == 16


def test_ladder_baselines_never_ratchet_down(tmp_path):
    """A slow chip-day must not lower a stored rung: only a >= rate
    overwrites (a lowered bar would mask the next real regression)."""
    fast = {"gpt_medium_tpu_o2": {"tok_s": 49000.0, "batch": 4}}
    slow = {"gpt_medium_tpu_o2": {"tok_s": 43000.0, "batch": 4}}
    faster = {"gpt_medium_tpu_o2": {"tok_s": 50500.0, "batch": 4}}
    bench.update_ladder_baselines(str(tmp_path), fast)
    bench.update_ladder_baselines(str(tmp_path), slow)
    doc = bench.load_ladder_baselines(str(tmp_path))
    assert doc["gpt_medium_tpu_o2"]["4"]["tok_s"] == 49000.0
    bench.update_ladder_baselines(str(tmp_path), faster)
    doc = bench.load_ladder_baselines(str(tmp_path))
    assert doc["gpt_medium_tpu_o2"]["4"]["tok_s"] == 50500.0


def test_gate_exit_code_absolute_gates_fire_without_compare():
    """MFU-floor and A/B-sign gates are absolute: they fail the run even
    when no --compare baseline was given (CI without a BENCH_r*.json
    must not silently pass an efficiency regression)."""
    bad_mfu = {"ok": True, "mfu_floors": {"ok": False,
                                          "violations": ["resnet50_o2"]},
               "ab_failures": []}
    bad_ab = {"ok": True, "mfu_floors": {"ok": True},
              "ab_failures": ["resnet50_pipeline_ab_64px"]}
    clean = {"ok": True, "mfu_floors": {"ok": True}, "ab_failures": []}
    assert bench.gate_exit_code(bad_mfu, compare_given=False) == 2
    assert bench.gate_exit_code(bad_ab, compare_given=False) == 2
    assert bench.gate_exit_code(clean, compare_given=False) == 0
    # CPU rounds have no MFU record at all — never gated on it
    assert bench.gate_exit_code({"ok": True, "mfu_floors": None,
                                 "ab_failures": []},
                                compare_given=False) == 0


def test_gate_exit_code_delta_gate_stays_opt_in():
    """Throughput deltas fail the run only under --compare; the
    unreadable-baseline early-return shape (no regressions/deltas keys)
    must not crash the gate either way."""
    regressed = {"ok": False, "mfu_floors": {"ok": True},
                 "ab_failures": [], "regressions": ["gpt_small_o2"],
                 "deltas": {"gpt_small_o2": -0.2}}
    assert bench.gate_exit_code(regressed, compare_given=False) == 0
    assert bench.gate_exit_code(regressed, compare_given=True) == 2
    unreadable = {"baseline": "BENCH_r99.json", "ok": True,
                  "error": "baseline unreadable: no configs map",
                  "mfu_floors": {"ok": False, "violations": ["x"]},
                  "ab_failures": []}
    assert bench.gate_exit_code(unreadable, compare_given=True) == 2


# ---------------------------------------------------------------------------
# Round-6 floor hygiene: kernel floors surfaced in the gate, and the
# no-ratchet-down rule over every published floor table.

#: Frozen snapshots of the floor tables as committed in round 6.  The
#: erosion guard below compares the LIVE tables against these: raising a
#: floor updates the snapshot in the same commit (fine — gains ratchet
#: the bar up); LOWERING one without a BENCH_VARIANCE.json entry whose
#: recorded spread covers the drop fails this suite.  Deleting a floor
#: is erosion too.
MFU_FLOOR_SNAPSHOT_R06 = {
    "resnet50_o2": 0.30,
    "resnet50_o3": 0.30,
    "resnet50_s2d_o2": 0.32,
    "gpt_small_o2": 0.41,
    "bert_large_lamb_o2": 0.49,
    "gpt_small_tpu_heads_o2": 0.54,
    "bert_large_tpu_heads_lamb_o2": 0.59,
    "gpt_small_tpu_heads_L8192_o2": 0.55,
    "gpt_small_tpu_heads_L16384_o2": 0.51,
    "gpt_medium_tpu_o2": 0.58,
}
KERNEL_FLOOR_SNAPSHOT_R06 = {
    "fused_adam": 0.30,
    "lamb_stage1": 0.17,
    "lamb_stage2": 0.12,
    "mt_scale": 0.75,
    "mt_axpby": 0.80,
    "mt_sumsq": 0.63,
    "layernorm_fwd": 0.34,
    "layernorm_fwd_bwd": 0.51,
}


def _kernel_floors():
    sys.path.insert(0, str(REPO / "tools"))
    import kernel_bench
    return kernel_bench.KERNEL_FLOORS


def test_floors_never_erode_without_variance_evidence():
    """Every floor change must be accompanied by recorded variance
    (VERDICT r5 weak #1: floors lowered on soft days absorb real
    regressions; the band then does the load-bearing work the floor was
    supposed to do)."""
    variance = bench.load_variance(str(REPO))
    for name, old in MFU_FLOOR_SNAPSHOT_R06.items():
        new = bench.MFU_FLOORS.get(name)
        assert new is not None, f"floor for {name} deleted (erosion)"
        assert bench.floor_change_allowed(name, old, new, variance), (
            f"{name}: floor lowered {old} -> {new} without a "
            "BENCH_VARIANCE.json entry covering the drop — run "
            "tools/bench_variance.py on chip and commit the artifact")
    kfloors = _kernel_floors()
    for name, old in KERNEL_FLOOR_SNAPSHOT_R06.items():
        new = kfloors.get(name)
        assert new is not None, f"kernel floor for {name} deleted"
        assert bench.floor_change_allowed(name, old, new, variance,
                                          kind="kernel"), (
            f"{name}: kernel floor lowered {old} -> {new} without "
            "variance evidence")


def test_floor_change_allowed_rule():
    """The rule itself: raise always; lower only with a non-tiny
    variance entry whose rel_spread covers the drop."""
    assert bench.floor_change_allowed("x", 0.30, 0.31, None)
    assert not bench.floor_change_allowed("x", 0.30, 0.29, None)
    doc = {"entries": {"config:x": {"rel_spread": 0.05},
                       "kernel:k": {"rel_spread": 0.10}}}
    # -3.3% drop inside the recorded 5% spread: allowed
    assert bench.floor_change_allowed("x", 0.30, 0.29, doc)
    # -17% drop far beyond it: refused
    assert not bench.floor_change_allowed("x", 0.30, 0.25, doc)
    # kernel floors key the kernel: namespace
    assert bench.floor_change_allowed("k", 0.17, 0.16, doc, kind="kernel")
    assert not bench.floor_change_allowed("x", 0.30, 0.29, doc,
                                          kind="kernel")
    # the MFU sub-statistic wins for configs when recorded
    mfu_doc = {"entries": {"config:x": {"rel_spread": 0.20,
                                        "mfu": {"rel_spread": 0.01}}}}
    assert not bench.floor_change_allowed("x", 0.30, 0.29, mfu_doc)
    # tiny-smoke artifacts are not evidence
    assert not bench.floor_change_allowed(
        "x", 0.30, 0.29, {"tiny": True,
                          "entries": {"config:x": {"rel_spread": 0.9}}})


def test_gate_exit_code_kernel_floors_absolute():
    """A kernel-floor violation from the committed KERNELBENCH artifact
    fails the model bench too — the 2%-of-step kernel regression cannot
    hide behind a green model round."""
    bad = {"ok": True, "mfu_floors": {"ok": True},
           "kernel_floors": {"ok": False, "violations": ["fused_adam"]},
           "ab_failures": []}
    assert bench.gate_exit_code(bad, compare_given=False) == 2
    # no kernel artifact at all (fresh checkout): never gated on it
    assert bench.gate_exit_code({"ok": True, "mfu_floors": {"ok": True},
                                 "kernel_floors": None,
                                 "ab_failures": []},
                                compare_given=False) == 0


def test_check_kernel_floor_artifact_reads_committed_round():
    """The repo's newest committed KERNELBENCH_r*.json passes the
    published floors (floors sit at-or-below the measured values, the
    MFU_FLOORS convention) and unreadable artifacts never fail."""
    out = bench.check_kernel_floor_artifact(str(REPO))
    assert out is not None and out["ok"], out
    assert out["artifact"].startswith("KERNELBENCH_r")
    # unreadable artifact: recorded, never failing
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        (Path(d) / "KERNELBENCH_r07.json").write_text("{not json")
        broken = bench.check_kernel_floor_artifact(d)
        assert broken["ok"] and "error" in broken
        assert bench.check_kernel_floor_artifact(
            tempfile.gettempdir() + "/definitely_empty_dir_xyz") is None


def test_check_floor_calibration_fails_loud_on_unimportable_floors(
        monkeypatch):
    """An unimportable KERNEL_FLOORS table must fail the calibration
    gate, never silently run with the floor half of the check off
    (the fail-loud contract in the docstring)."""
    ok = bench.check_floor_calibration(str(REPO))
    assert ok["ok"], ok
    monkeypatch.setitem(sys.modules, "kernel_bench", None)
    broken = bench.check_floor_calibration(str(REPO))
    assert not broken["ok"]
    assert "KERNEL_FLOORS not audited" in broken["error"]


# ---------------------------------------------------------------------------
# Round-6 decode serving: the decode-bandwidth floors and the serve
# bench (ISSUE 6).

#: Frozen snapshot of the decode hbm_frac floors as committed in round
#: 6 (the r05 measured values, now that DECODE_DECOMPOSE_r01.json
#: explains the b8 number) — same erosion rule as every floor table.
DECODE_FLOOR_SNAPSHOT_R06 = {
    "gpt_small_tpu_decode_b1": 0.54,
    "gpt_small_tpu_decode_b8": 0.43,
}


def test_decode_floors_never_erode_without_variance_evidence():
    variance = bench.load_variance(str(REPO))
    for name, old in DECODE_FLOOR_SNAPSHOT_R06.items():
        new = bench.DECODE_FLOORS.get(name)
        assert new is not None, f"decode floor for {name} deleted"
        assert bench.floor_change_allowed(name, old, new, variance), (
            f"{name}: decode floor lowered {old} -> {new} without "
            "variance evidence")


def test_decode_floor_gate():
    """hbm_frac under floor*(1-band) trips; at/over passes; errored or
    absent configs are skipped (optional-config semantics); a floor
    above the roofline ceiling fails loudly."""
    ok = bench.check_decode_floors(
        {"gpt_small_tpu_decode_b8": {"hbm_frac": 0.43}})
    assert ok["ok"] and ok["checked"]["gpt_small_tpu_decode_b8"]["ok"]
    low = bench.check_decode_floors(
        {"gpt_small_tpu_decode_b8": {"hbm_frac": 0.39}})
    assert not low["ok"]
    assert low["violations"] == ["gpt_small_tpu_decode_b8"]
    skipped = bench.check_decode_floors(
        {"gpt_small_tpu_decode_b8": {"error": "OOM"}})
    assert skipped["ok"] and not skipped["checked"]
    # hbm_frac of exactly 0.0 is a catastrophic regression, not a
    # missing value — it must TRIP the gate, never falsy-skip it
    zero = bench.check_decode_floors(
        {"gpt_small_tpu_decode_b8": {"hbm_frac": 0.0}})
    assert not zero["ok"]
    assert zero["violations"] == ["gpt_small_tpu_decode_b8"]
    try:
        bench.DECODE_FLOORS["__impossible"] = 1.2
        bad = bench.check_decode_floors({})
        assert not bad["ok"] and "__impossible" in bad["violations"]
    finally:
        del bench.DECODE_FLOORS["__impossible"]


def test_gate_exit_code_includes_decode_floors():
    bad = {"ok": True, "mfu_floors": {"ok": True},
           "decode_floors": {"ok": False,
                             "violations": ["gpt_small_tpu_decode_b8"]},
           "ab_failures": []}
    assert bench.gate_exit_code(bad, compare_given=False) == 2
    # CPU rounds record no decode gate — never gated on it
    assert bench.gate_exit_code(
        {"ok": True, "mfu_floors": None, "decode_floors": None,
         "ab_failures": []}, compare_given=False) == 0


def test_bench_generate_reports_roofline_bound():
    """The decode ceiling now rides the shared roofline machinery:
    the record names the binding resource (bandwidth at decode
    intensity)."""
    r = bench.bench_generate(batch=2, prefill=16, new_tokens=8,
                             warmup=0, iters=1, peak=None, tiny=True)
    assert r["bound"] == "bandwidth"
    assert r["hbm_tok_s_ceiling"] > 0 and 0 <= r["hbm_frac"]


def test_bench_generate_kv8_byte_model_derives_higher_ceiling():
    """The int8-KV config's ceiling comes from the int8 byte model
    through the SAME roofline_expectation call — never hand-written:
    at equal shapes the kv8 ceiling strictly exceeds the dense one
    (cache term halves, plus 4 bytes/position/layer of scales), and
    the record carries the byte-model evidence."""
    dense = bench.bench_generate(batch=2, prefill=16, new_tokens=8,
                                 warmup=0, iters=1, peak=None, tiny=True)
    kv8 = bench.bench_generate(batch=2, prefill=16, new_tokens=8,
                               warmup=0, iters=1, peak=None, tiny=True,
                               kv_dtype="int8")
    assert kv8["kv_dtype"] == "int8"
    assert kv8["hbm_tok_s_ceiling"] > dense["hbm_tok_s_ceiling"]
    # byte model: 1 byte/elem per cache + 4-byte scales per position
    from apex_tpu.models.gpt import gpt_tiny
    cfg = gpt_tiny()
    m = 16 + 8
    want = (2 * cfg.num_layers * 2 * m * cfg.hidden_size * 1
            + 2 * cfg.num_layers * 2 * m * 4)
    assert kv8["cache_bytes_per_step"] == want
    assert kv8["bound"] == "bandwidth"


def test_decode_floors_carry_kv8_config():
    """The committed kv8 floor exists (CPU-smoke-seeded,
    catastrophic-regression guard; on-chip ratchet is the next driver
    round's job) and sits under the roofline like every floor."""
    assert 0 < bench.DECODE_FLOORS["gpt_small_tpu_decode_kv8"] <= 1.0


def test_bench_serve_tiny_cpu():
    """The serve bench path end-to-end on CPU: offered-load sweep
    c1 -> c_slots, decode-step p50/p99, the latency-tail ab gate, and
    exactly one decode trace across the whole stream."""
    r = bench.bench_serve(warmup=1, iters=1, peak=None, tiny=True)
    assert r["tok_s"] > 0 and r["ab_ok"] is True
    assert r["p99_ms"] >= r["p50_ms"] > 0
    levels = r["offered_load"]
    assert set(levels) == {"c1", "c2"}
    assert all(v["retraces"] == 1 for v in levels.values())


def test_bench_serve_spec_tiny_cpu():
    """The speculative serve A/B end-to-end on CPU: the briefly
    trained model gives the layer-skip draft real margins, the same
    stream runs through both arms, and the gate — tokens per decode
    dispatch strictly greater with spec on, retraces == 1 both arms —
    holds (ab_ok rides gate_exit_code's absolute ab_failures lane
    like every other sign gate)."""
    r = bench.bench_serve_spec(warmup=1, iters=1, peak=None, tiny=True)
    assert r["ab_ok"] is True
    assert r["spec"]["tokens_per_step"] > r["baseline"]["tokens_per_step"]
    assert r["spec"]["retraces"] == 1 and r["baseline"]["retraces"] == 1
    assert r["spec"]["acceptance_rate"] > 0
    assert r["tok_s"] > 0 and r["p99_ms"] >= r["p50_ms"] > 0


def test_merged_decode_quantile_unions_replica_windows():
    """The fleet percentile is the union of the replicas' histogram
    windows through the SAME Histogram interpolation — two replicas
    with disjoint latency populations must merge to the population
    quantile, and pre-mark observations stay outside the window.
    (bench's private ``_merged_decode_quantile`` is GONE — this is
    the one public copy, ``apex_tpu.obs.fleet.merged_quantile``,
    which bench_serve_disagg now imports.)"""
    from apex_tpu.obs.fleet import merged_quantile
    from apex_tpu.obs.metrics import Histogram, Registry

    assert not hasattr(bench, "_merged_decode_quantile")
    reg = Registry()
    h1, h2 = Histogram(reg, "a"), Histogram(reg, "b")
    h1.observe(10.0)                    # pre-window (compile step)
    m1, m2 = h1.state(), h2.state()
    for _ in range(50):
        h1.observe(0.001)
        h2.observe(0.004)
    merged_p50 = merged_quantile([(h1, m1), (h2, m2)], 0.5)
    merged_p99 = merged_quantile([(h1, m1), (h2, m2)], 0.99)
    # half the union sits near 1 ms, the slow half near 4 ms: p50
    # lands between the two modes, p99 inside the slow replica's
    # bucket — and far under the excluded 10 s compile outlier
    assert 0.0005 < merged_p50 < 0.004
    assert 0.002 < merged_p99 < 0.01
    # stale-max guard: an overflow-bucket observation AFTER the mark
    # must interpolate toward the window's own max, never toward the
    # excluded pre-mark outlier — merged and single-histogram math
    # must agree exactly (h3's 100 s compile vs a 30 s window step)
    h3 = Histogram(reg, "c")
    h3.observe(100.0)
    m3 = h3.state()
    h3.observe(30.0)
    merged = merged_quantile([(h3, m3)], 0.99)
    assert merged == h3.quantile(0.99, since=m3)
    assert merged <= 30.0


@pytest.mark.slow
def test_bench_serve_disagg_tiny_cpu():
    """The disaggregated A/B path end-to-end on CPU: both arms serve
    the same stream, percentiles come from the engines' own
    histograms, the topology records disjoint slices, and every
    program keeps one trace.  (The committed SERVE_DISAGG artifact —
    generated by tools/serve_disagg.py at the full c16 shape — is the
    gated instance; this is the code-path smoke.)"""
    r = bench.bench_serve_disagg(warmup=1, iters=1, peak=None,
                                 n_replicas=2, slots_per_replica=2,
                                 prefill=16, new_tokens=8, tiny=True)
    assert "skipped" not in r, r
    assert r["mono"]["retraces"] == 1
    assert r["disagg"]["retraces"] == [1, 1]
    assert r["disagg"]["shipments"] == r["batch"] == 4
    assert r["disagg"]["kv_transfer_bytes"] > 0
    flat = r["topology"]["prefill"] + [
        d for rep in r["topology"]["decode"] for d in rep]
    assert len(flat) == len(set(flat))
    assert r["p99_ms"] >= r["p50_ms"] > 0


# ---------------------------------------------------------------------------
# ISSUE 14: statistical floor bands derived from recorded variance
# ---------------------------------------------------------------------------

def _variance_doc(entries, tiny=False):
    return {"platform": "tpu", "tiny": tiny, "entries": entries}


def test_derive_floor_bands_formula_and_ratchet():
    """floor = mean - k*std where evidence qualifies; hand floors are
    the frozen fallback; the no-ratchet-down rule applies to DERIVED
    candidates too (a candidate below the hand floor beyond the
    recorded spread is refused)."""
    hand = {"cfg": 0.40}
    # no artifact / tiny artifact / missing entry / OFF-CHIP artifact
    # (a full-size CPU run says nothing about TPU floors): hand stands
    cpu_doc = dict(_variance_doc({"config:cfg": {
        "mfu": {"n": 9, "mean": 0.10, "std": 0.01},
        "rel_spread": 0.9}}), platform="cpu")
    for doc in (None, _variance_doc({}, tiny=True), _variance_doc({}),
                cpu_doc):
        bands = bench.derive_floor_bands(hand, doc, kind="config",
                                         stat="mfu")
        assert bands["cfg"] == {"floor": 0.40, "source": "hand",
                                "provisional": False}
    # qualifying entry ABOVE the hand floor: derived, ratchets up
    doc = _variance_doc({"config:cfg": {
        "mfu": {"n": 5, "mean": 0.46, "std": 0.01},
        "rel_spread": 0.05}})
    rec = bench.derive_floor_bands(hand, doc, kind="config",
                                   stat="mfu")["cfg"]
    assert rec["source"] == "derived" and rec["floor"] == 0.44
    # candidate below the hand floor but INSIDE the recorded spread:
    # the statistical floor may honestly sit lower
    doc = _variance_doc({"config:cfg": {
        "mfu": {"n": 5, "mean": 0.40, "std": 0.005,
                "rel_spread": 0.06}, "rel_spread": 0.06}})
    rec = bench.derive_floor_bands(hand, doc, kind="config",
                                   stat="mfu")["cfg"]
    assert rec["source"] == "derived" and rec["floor"] == 0.39
    # candidate far below beyond the spread: REFUSED (no-ratchet-down)
    doc = _variance_doc({"config:cfg": {
        "mfu": {"n": 5, "mean": 0.30, "std": 0.01,
                "rel_spread": 0.02}, "rel_spread": 0.02}})
    rec = bench.derive_floor_bands(hand, doc, kind="config",
                                   stat="mfu")["cfg"]
    assert rec["source"] == "hand" and rec["floor"] == 0.40
    assert "no-ratchet-down" in rec["reason"]
    # insufficient samples: hand floor, reason recorded
    doc = _variance_doc({"config:cfg": {
        "mfu": {"n": 2, "mean": 0.46, "std": 0.01}}})
    rec = bench.derive_floor_bands(hand, doc, kind="config",
                                   stat="mfu")["cfg"]
    assert rec["source"] == "hand" and "insufficient" in rec["reason"]
    # the drop is judged by the spread of the SAME statistic the
    # floor gates: a wide spread on a DIFFERENT metric (here the
    # rate) is not evidence about hbm_frac — refused
    doc = _variance_doc({"config:cfg": {
        "rel_spread": 0.50,        # wide rate spread
        "hbm_frac": {"n": 5, "mean": 0.30, "std": 0.005,
                     "rel_spread": 0.02}}})
    rec = bench.derive_floor_bands(hand, doc, kind="config",
                                   stat="hbm_frac")["cfg"]
    assert rec["source"] == "hand" and "no-ratchet-down" in \
        rec["reason"]
    assert not bench.floor_change_allowed("cfg", 0.40, 0.30, doc,
                                          stat="hbm_frac")
    assert bench.floor_change_allowed("cfg", 0.40, 0.395, doc,
                                      stat="hbm_frac")


def test_frozen_fallback_no_floor_loosened_by_committed_artifact():
    """The acceptance bar: with the COMMITTED BENCH_VARIANCE_r*.json
    (a tiny CPU smoke until a chip round lands), every effective floor
    equals today's hand value exactly — consulting the artifact can
    never loosen a gate silently."""
    kfloors = _kernel_floors()
    for table, kind, stat in (
            (bench.MFU_FLOORS, "config", "mfu"),
            (bench.DECODE_FLOORS, "config", "hbm_frac"),
            (kfloors, "kernel", "roofline_frac")):
        eff, bands = bench.effective_floors(table, str(REPO),
                                            kind=kind, stat=stat)
        assert eff == dict(table), (kind, eff)
        assert all(b["source"] == "hand" for b in bands.values())


def test_gates_consult_derived_bands(monkeypatch, tmp_path):
    """check_mfu_floors/check_decode_floors with a search_dir apply
    the DERIVED floor (here: ratcheted up by synthetic evidence) and
    record its source — the 'demonstrably consult' bar."""
    import json as _json
    doc = _variance_doc({"config:gpt_small_o2": {
        "mfu": {"n": 6, "mean": 0.50, "std": 0.01},
        "rel_spread": 0.03}})
    (tmp_path / "BENCH_VARIANCE_r05.json").write_text(_json.dumps(doc))
    # measured 0.45: passes the hand floor 0.41, FAILS the derived
    # 0.48 gate (0.456) — the consultation is observable
    out = bench.check_mfu_floors({"gpt_small_o2": {"mfu": 0.45}},
                                 search_dir=str(tmp_path))
    assert out["checked"]["gpt_small_o2"]["source"] == "derived"
    assert out["checked"]["gpt_small_o2"]["floor"] == 0.48
    assert out["violations"] == ["gpt_small_o2"]
    # without the artifact the same measurement passes the hand floor
    ok = bench.check_mfu_floors({"gpt_small_o2": {"mfu": 0.45}})
    assert ok["ok"] and ok["checked"]["gpt_small_o2"]["source"] == "hand"


def test_kv8_floor_marked_provisional_in_gate_record():
    """The CPU-smoke-seeded kv8 entry is reported as UNMEASURED: the
    decode gate record and check_floor_calibration both name it
    provisional instead of passing it off as a floor."""
    assert "gpt_small_tpu_decode_kv8" in bench.PROVISIONAL_FLOORS
    out = bench.check_decode_floors(
        {"gpt_small_tpu_decode_kv8": {"hbm_frac": 0.002}})
    assert out["provisional"] == ["gpt_small_tpu_decode_kv8"]
    assert out["checked"]["gpt_small_tpu_decode_kv8"]["provisional"] \
        is True
    cal = bench.check_floor_calibration(str(REPO))
    assert cal["ok"], cal
    assert "gpt_small_tpu_decode_kv8" in cal["provisional_floors"]
    # measured floors are NOT provisional
    ok = bench.check_decode_floors(
        {"gpt_small_tpu_decode_b8": {"hbm_frac": 0.43}})
    assert "provisional" not in ok["checked"]["gpt_small_tpu_decode_b8"]
