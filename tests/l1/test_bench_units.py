"""Units for bench.py's harness pieces (the benchmark itself runs on the
driver's chip): the PJRT-init watchdog and the FLOP-count fallback."""

import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))

import bench  # noqa: E402


def test_probe_devices_returns_devices():
    devices = bench.probe_devices(60)
    assert devices, "CPU backend must enumerate"


def test_probe_devices_times_out_on_hang(monkeypatch):
    monkeypatch.setattr(bench.jax, "devices",
                        lambda *a: time.sleep(30))
    t0 = time.time()
    assert bench.probe_devices(1.0) is None
    assert time.time() - t0 < 5


def test_probe_devices_reraises_init_errors(monkeypatch):
    def boom():
        raise RuntimeError("plugin exploded")
    monkeypatch.setattr(bench.jax, "devices", boom)
    with pytest.raises(RuntimeError, match="plugin exploded"):
        bench.probe_devices(30)


def test_step_flops_fallback():
    class NoCost:
        def cost_analysis(self):
            raise NotImplementedError
    assert bench.step_flops(NoCost(), fallback=123.0) == 123.0

    class ListCost:
        def cost_analysis(self):
            return [{"flops": 7.0}]
    assert bench.step_flops(ListCost(), fallback=0.0) == 7.0

    class ZeroCost:  # some backends report 0 — fall back
        def cost_analysis(self):
            return {"flops": 0.0}
    assert bench.step_flops(ZeroCost(), fallback=5.0) == 5.0
