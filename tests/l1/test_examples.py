"""Example entry-point smoke tests.

The reference's examples were exercised only by the L1 shell harness on a
GPU rig (``tests/L1/common/run_test.sh``); here every example runs headless
at miniature scale in a fresh subprocess (default platform — the real chip
when present; the distributed ones pinned to a multi-device virtual CPU
mesh so their collectives actually run) so the user-facing entry points
cannot bitrot.  Runtime knobs are the examples' own CLI flags — the same
argparse surface the reference's harness drove.
"""

import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parents[2]

CASES = {
    "mnist_amp.py": ["--steps", "2", "--batch-size", "16"],
    "imagenet_main_amp.py": ["--steps", "2", "--batch-size", "2",
                             "--image-size", "32", "--arch", "resnet18"],
    # real data: train one epoch on sklearn digits + full validate() loop
    # (prec@1/prec@5 path, reference main_amp.py:439-489)
    # host-streamed input pipeline (uint8 numpy + overlapped H2D +
    # on-device normalize — apex_tpu.data, VERDICT r3 #4)
    "imagenet_main_amp.py --data-pipeline host": [
        "--steps", "3", "--batch-size", "2", "--image-size", "32",
        "--arch", "resnet18", "--data-pipeline", "host"],
    "imagenet_main_amp.py --data digits": [
        "--data", "digits", "--epochs", "1", "--batch-size", "256",
        "--image-size", "8", "--arch", "resnet18"],
    "bert_pretraining.py": ["--steps", "2", "--batch-size", "2",
                            "--seq-len", "32", "--size", "tiny"],
    "dcgan_main_amp.py": ["--steps", "2", "--batch-size", "4"],
    # distributed examples must actually be multi-device: force the
    # virtual CPU mesh so the collectives (DDP allreduce, ring rotation)
    # run for real
    "simple_ddp.py": ["--force-cpu", "--world-size", "8"],
    "long_context_attention.py": ["--seq-len", "512", "--heads", "2",
                                  "--head-dim", "32", "--force-cpu"],
    "pipeline_moe.py": ["--mode", "ep", "--steps", "2"],
    "pipeline_moe.py --mode pp": ["--mode", "pp", "--steps", "2"],
    "gpt_lm.py": ["--steps", "2", "--seq-len", "64", "--batch-size", "2",
                  "--seq-parallel", "--devices", "4", "--force-cpu"],
    # real text: byte-level LM over the stdlib sources + greedy sample
    "gpt_lm.py --data pysrc": [
        "--data", "pysrc", "--steps", "2", "--seq-len", "64",
        "--batch-size", "2", "--sample-bytes", "4", "--force-cpu",
        "--devices", "1"],
}


#: the full matrix of subprocess runs sums to ~190s on the 2-vCPU
#: tier-1 box (ROADMAP wall-clock item) — tier-1 keeps one fast
#: representative of each kind (single-device amp: mnist; virtual-mesh
#: distributed: simple_ddp) and slow-marks the rest; `-m slow` still
#: runs every entry point.
FAST_CASES = ("mnist_amp.py", "simple_ddp.py")


@pytest.mark.parametrize(
    "script",
    [pytest.param(s, id=s,
                  marks=() if s in FAST_CASES else (pytest.mark.slow,))
     for s in sorted(CASES)])
def test_example_runs(script):
    env = dict(os.environ,
               PYTHONPATH=f"{REPO}:" + os.environ.get("PYTHONPATH", ""))
    # conftest.py mutates XLA_FLAGS at import (virtual 8-device CPU mesh);
    # strip it so each example's own device-count/platform settings win
    flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                     if "xla_force_host_platform_device_count" not in f)
    env["XLA_FLAGS"] = flags
    out = subprocess.run(
        [sys.executable, str(REPO / "examples" / script.split()[0])]
        + CASES[script],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=str(REPO))
    assert out.returncode == 0, (script, out.stdout[-2000:],
                                 out.stderr[-2000:])
