"""Units for tools/kernel_bench.py (the microbench itself runs on the
driver's chip): the per-kernel regression gate and the byte accounting.
"""

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO / "tools"))

import kernel_bench as kb  # noqa: E402


def _write(tmp_path, kernels):
    p = tmp_path / "KERNELBENCH_r04.json"
    p.write_text(json.dumps({"kernels": kernels}))
    return str(p)


def test_compare_kernels_gates_slowdowns_only(tmp_path):
    prior = _write(tmp_path, {
        "fused_adam": {"ms_per_step": 1.0},
        "mt_scale": {"ms_per_step": 0.5},
        "lamb_stage1": {"ms_per_step": 2.0},
        "errored_before": {"error": "boom"},
    })
    verdict = kb.compare_kernels(prior, {
        "fused_adam": {"ms_per_step": 1.05},   # +5%: within variance
        "mt_scale": {"ms_per_step": 0.65},     # +30%: regression
        "lamb_stage1": {"ms_per_step": 1.2},   # faster: fine
        "errored_before": {"ms_per_step": 9.0},  # no prior time
        "brand_new": {"ms_per_step": 1.0},
    }, threshold=0.10)
    assert verdict["regressions"] == ["mt_scale"]
    assert not verdict["ok"]
    assert verdict["deltas"]["fused_adam"] == 0.05
    assert verdict["deltas"]["lamb_stage1"] == -0.4
    assert set(verdict["uncompared"]) == {"errored_before", "brand_new"}


def test_compare_kernels_unreadable_baseline_never_fails(tmp_path):
    bad = tmp_path / "KERNELBENCH_r99.json"
    bad.write_text("{not json")
    verdict = kb.compare_kernels(str(bad), {"a": {"ms_per_step": 1.0}})
    assert verdict["ok"] and "error" in verdict


def test_compare_kernels_refuses_mismatched_geometry(tmp_path):
    p = tmp_path / "KERNELBENCH_r04.json"
    p.write_text(json.dumps({
        "n_elements": 1 << 24, "ln_shape": [8192, 1024],
        "kernels": {"fused_adam": {"ms_per_step": 2.3}}}))
    # a 4x-larger current run must not read as a 4x regression
    verdict = kb.compare_kernels(
        str(p), {"fused_adam": {"ms_per_step": 9.8}}, 0.10,
        geometry={"n_elements": 1 << 26, "ln_shape": [1 << 17, 1024]})
    assert verdict["ok"] and "geometry mismatch" in verdict["error"]
    # matched geometry gates normally
    verdict = kb.compare_kernels(
        str(p), {"fused_adam": {"ms_per_step": 9.8}}, 0.10,
        geometry={"n_elements": 1 << 24, "ln_shape": [8192, 1024]})
    assert verdict["regressions"] == ["fused_adam"]


def test_byte_accounting_matches_docstring():
    n = 1 << 16
    assert kb.bench_fused_adam(n)[1] == 30.0 * n
    assert kb.bench_lamb_stage1(n)[1] == 28.0 * n
    assert kb.bench_lamb_stage2(n)[1] == 14.0 * n
    assert kb.bench_mt_scale(n)[1] == 8.0 * n
    assert kb.bench_mt_axpby(n)[1] == 12.0 * n
    assert kb.bench_mt_sumsq(n)[1] == 4.0 * n
    rows, hidden = 64, 512
    assert kb.bench_layernorm_fwd(rows, hidden)[1] == \
        4.0 * rows * hidden + 8.0 * rows


def test_tiny_suite_runs_everywhere():
    """End-to-end smoke at tiny shapes (interpret mode off-TPU): every
    kernel produces a timing record, none errors."""
    result = kb.run_suite(tiny=True)
    errs = {k: v["error"] for k, v in result["kernels"].items()
            if "error" in v}
    assert not errs, errs
    # tiny interpret-mode timings can degenerate to the clamp under
    # host contention (the difference quotient needs real device time);
    # 0.0 baselines are filtered by compare_kernels' truthiness check
    assert all(v["ms_per_step"] >= 0 for v in result["kernels"].values())


def test_geometry_recorded_per_kernel():
    """Every timed record names the geometry it measured (device kind +
    n_elements are top-level; block shape per kernel — the ISSUE-2
    artifact contract)."""
    result = kb.run_suite(tiny=True)
    assert result["device_kind"] is not None and "n_elements" in result
    for name, rec in result["kernels"].items():
        assert "error" in rec or ("geometry" in rec and "iters" in rec), \
            (name, rec)
        if "geometry" in rec:
            g = rec["geometry"]
            assert g["block_rows"] >= 1 and g["grid"] >= 1, (name, g)


def test_autotune_sweeps_and_chooses(monkeypatch):
    """--autotune sweeps each retunable kernel's knob and the chosen
    value is the fastest swept candidate."""
    # deterministic fake timer: bigger blocks "faster", candidate 64 best
    def fake_time(build, iters, trials=3):
        return {8: 9.0, 32: 5.0, 64: 1.0, 128: 2.0, 256: 3.0,
                1: 9.0, 2: 5.0, 4: 3.0, 16: 2.5, 512: 4.0}.get(
                    fake_time.cand, 1.0) * 1e-3
    calls = {}
    real = {}

    def spy_fn(name, fn):
        def wrapped(*a, **kw):
            knob, _ = kb.AUTOTUNE_KNOBS[name]
            fake_time.cand = kw.get(knob) or 0
            calls.setdefault(name, []).append(kw.get(knob))
            return fn(*a, **kw)
        return wrapped

    monkeypatch.setattr(kb, "_time_scan", fake_time)
    for name in ("fused_adam", "lamb_stage1"):
        fn = getattr(kb, f"bench_{name}")
        real[name] = fn
        monkeypatch.setattr(kb, f"bench_{name}", spy_fn(name, fn))
    result = kb.run_suite(tiny=True, autotune=True)
    adam = result["kernels"]["fused_adam"]
    assert adam["autotune"]["chosen"] == {"block_rows": 64}
    assert set(adam["autotune"]["swept_ms"]) == \
        {str(c) for c in kb.AUTOTUNE_KNOBS["fused_adam"][1]}
    lamb = result["kernels"]["lamb_stage1"]
    assert lamb["autotune"]["chosen"] == {"chunks_per_block": 16}
    # final timing ran at the chosen knob (last call per kernel)
    assert calls["fused_adam"][-1] == 64


def test_autotune_refuses_lint_rejected_candidates(monkeypatch):
    """A knob candidate the Pallas sanitizer rejects is recorded as a
    ``lint_rejected`` dict entry — never timed, never chosen — even
    when it would have swept fastest (the export-gate treatment)."""
    # shrink the VMEM budget so block_rows=256 overflows the working
    # set while block_rows=8 still fits (budget read at call time)
    monkeypatch.setenv("APEX_TPU_VMEM_BUDGET_MB", "0.25")
    monkeypatch.setattr(kb, "AUTOTUNE_KNOBS",
                        {"fused_adam": ("block_rows", (8, 256))})
    # fake timer makes the REJECTED candidate look fastest: only the
    # lint gate can keep it out of the knob table
    def fake_time(build, iters, trials=3):
        return 1e-3
    monkeypatch.setattr(kb, "_time_scan", fake_time)
    result = kb.run_suite(tiny=True, autotune=True)
    auto = result["kernels"]["fused_adam"]["autotune"]
    assert auto["swept_ms"]["256"] ==         {"lint_rejected": ["pallas-vmem-overflow"]}
    assert isinstance(auto["swept_ms"]["8"], float)
    assert auto["chosen"] == {"block_rows": 8}


def test_kernel_floor_gate():
    floors = kb.KERNEL_FLOORS
    assert "fused_adam" in floors and "lamb_stage1" in floors
    # the r05 measured values pass their own floors (the gate fires on
    # future regressions, not retroactively)
    import json as _json
    r05 = _json.load(open(REPO / "KERNELBENCH_r05.json"))
    check = kb.check_kernel_floors(r05["kernels"])
    assert check["ok"], check
    # a real bandwidth loss fails
    check = kb.check_kernel_floors({"fused_adam": {"roofline_frac": 0.20}})
    assert not check["ok"] and check["violations"] == ["fused_adam"]
    # a gated kernel that ERRORED (stopped running at all) fails the
    # gate too — the floor must not fail open on the worst regression
    check = kb.check_kernel_floors({"fused_adam": {"error": "boom"}})
    assert not check["ok"] and check["errored"] == ["fused_adam"]
    # kernels absent from a partial map are merely not judged
    check = kb.check_kernel_floors({})
    assert check["ok"] and not check["checked"]


def test_assert_floors_exits_nonzero_on_violation(monkeypatch, tmp_path):
    """`--assert-floors` is a real gate: exit 2 on a violated kernel
    floor, 0 when clean, and never armed without the flag."""
    violating = {
        "platform": "tpu", "device_kind": "TPU v5 lite",
        "n_elements": 1 << 26, "ln_shape": [1 << 17, 1024],
        "hbm_gbps_peak": 819.0,
        "kernels": {"fused_adam": {"ms_per_step": 30.0, "gb_moved": 2.0,
                                   "gbps": 67.0, "roofline_frac": 0.08,
                                   "iters": 60}}}
    monkeypatch.setattr(kb, "run_suite",
                        lambda tiny=False, autotune=False: dict(violating))
    out = str(tmp_path / "KB.json")
    assert kb.main(["--out", out, "--assert-floors"]) == 2
    assert kb.main(["--out", out]) == 0   # unarmed: recorded only
    import json as _json
    assert not _json.load(open(out))["floors"]["ok"]
    # clean run passes the armed gate
    clean = dict(violating)
    clean["kernels"] = {"fused_adam": {"ms_per_step": 3.0, "gb_moved": 2.0,
                                       "gbps": 670.0,
                                       "roofline_frac": 0.82, "iters": 60}}
    monkeypatch.setattr(kb, "run_suite",
                        lambda tiny=False, autotune=False: dict(clean))
    assert kb.main(["--out", out, "--assert-floors"]) == 0


def test_floors_skip_off_tpu(monkeypatch, tmp_path):
    """Off-chip (CPU smoke) roofline fractions are meaningless: the
    floors block records skipped and --assert-floors never fires."""
    cpu = {"platform": "cpu", "device_kind": "", "n_elements": 1 << 16,
           "ln_shape": [64, 512], "hbm_gbps_peak": 819.0,
           "kernels": {"fused_adam": {"ms_per_step": 1.0,
                                      "roofline_frac": 0.0001}}}
    monkeypatch.setattr(kb, "run_suite",
                        lambda tiny=False, autotune=False: dict(cpu))
    out = str(tmp_path / "KB.json")
    assert kb.main(["--out", out, "--tiny", "--assert-floors"]) == 0
    import json as _json
    doc = _json.load(open(out))
    assert doc["floors"]["ok"] and "skipped" in doc["floors"]


def test_check_kernel_floors_accepts_derived_override():
    """bench.py and main() pass variance-derived effective floors;
    the override is applied verbatim — a ratcheted-up derived floor
    fails a measurement the hand floor would pass."""
    measured = {"fused_adam": {"roofline_frac": 0.32}}
    assert kb.check_kernel_floors(measured)["ok"]          # hand 0.30
    out = kb.check_kernel_floors(measured,
                                 floors={"fused_adam": 0.36})
    assert not out["ok"] and out["violations"] == ["fused_adam"]
    assert out["checked"]["fused_adam"]["floor"] == 0.36


def test_effective_kernel_floors_frozen_fallback():
    """With the committed (tiny) variance artifact, the effective
    kernel floors equal the published hand table — nothing loosened —
    and every source records 'hand'."""
    floors, bands = kb.effective_kernel_floors()
    assert floors == dict(kb.KERNEL_FLOORS)
    assert all(b["source"] == "hand" for b in bands.values())
