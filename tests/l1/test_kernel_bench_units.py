"""Units for tools/kernel_bench.py (the microbench itself runs on the
driver's chip): the per-kernel regression gate and the byte accounting.
"""

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO / "tools"))

import kernel_bench as kb  # noqa: E402


def _write(tmp_path, kernels):
    p = tmp_path / "KERNELBENCH_r04.json"
    p.write_text(json.dumps({"kernels": kernels}))
    return str(p)


def test_compare_kernels_gates_slowdowns_only(tmp_path):
    prior = _write(tmp_path, {
        "fused_adam": {"ms_per_step": 1.0},
        "mt_scale": {"ms_per_step": 0.5},
        "lamb_stage1": {"ms_per_step": 2.0},
        "errored_before": {"error": "boom"},
    })
    verdict = kb.compare_kernels(prior, {
        "fused_adam": {"ms_per_step": 1.05},   # +5%: within variance
        "mt_scale": {"ms_per_step": 0.65},     # +30%: regression
        "lamb_stage1": {"ms_per_step": 1.2},   # faster: fine
        "errored_before": {"ms_per_step": 9.0},  # no prior time
        "brand_new": {"ms_per_step": 1.0},
    }, threshold=0.10)
    assert verdict["regressions"] == ["mt_scale"]
    assert not verdict["ok"]
    assert verdict["deltas"]["fused_adam"] == 0.05
    assert verdict["deltas"]["lamb_stage1"] == -0.4
    assert set(verdict["uncompared"]) == {"errored_before", "brand_new"}


def test_compare_kernels_unreadable_baseline_never_fails(tmp_path):
    bad = tmp_path / "KERNELBENCH_r99.json"
    bad.write_text("{not json")
    verdict = kb.compare_kernels(str(bad), {"a": {"ms_per_step": 1.0}})
    assert verdict["ok"] and "error" in verdict


def test_compare_kernels_refuses_mismatched_geometry(tmp_path):
    p = tmp_path / "KERNELBENCH_r04.json"
    p.write_text(json.dumps({
        "n_elements": 1 << 24, "ln_shape": [8192, 1024],
        "kernels": {"fused_adam": {"ms_per_step": 2.3}}}))
    # a 4x-larger current run must not read as a 4x regression
    verdict = kb.compare_kernels(
        str(p), {"fused_adam": {"ms_per_step": 9.8}}, 0.10,
        geometry={"n_elements": 1 << 26, "ln_shape": [1 << 17, 1024]})
    assert verdict["ok"] and "geometry mismatch" in verdict["error"]
    # matched geometry gates normally
    verdict = kb.compare_kernels(
        str(p), {"fused_adam": {"ms_per_step": 9.8}}, 0.10,
        geometry={"n_elements": 1 << 24, "ln_shape": [8192, 1024]})
    assert verdict["regressions"] == ["fused_adam"]


def test_byte_accounting_matches_docstring():
    n = 1 << 16
    assert kb.bench_fused_adam(n)[1] == 30.0 * n
    assert kb.bench_lamb_stage1(n)[1] == 28.0 * n
    assert kb.bench_lamb_stage2(n)[1] == 14.0 * n
    assert kb.bench_mt_scale(n)[1] == 8.0 * n
    assert kb.bench_mt_axpby(n)[1] == 12.0 * n
    assert kb.bench_mt_sumsq(n)[1] == 4.0 * n
    rows, hidden = 64, 512
    assert kb.bench_layernorm_fwd(rows, hidden)[1] == \
        4.0 * rows * hidden + 8.0 * rows


def test_tiny_suite_runs_everywhere():
    """End-to-end smoke at tiny shapes (interpret mode off-TPU): every
    kernel produces a timing record, none errors."""
    result = kb.run_suite(tiny=True)
    errs = {k: v["error"] for k, v in result["kernels"].items()
            if "error" in v}
    assert not errs, errs
    # tiny interpret-mode timings can degenerate to the clamp under
    # host contention (the difference quotient needs real device time);
    # 0.0 baselines are filtered by compare_kernels' truthiness check
    assert all(v["ms_per_step"] >= 0 for v in result["kernels"].values())
