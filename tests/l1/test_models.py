"""L1-style end-to-end workload tests at test scale.

Each of the five BASELINE configs gets a miniature end-to-end run: forward,
backward, one or more amp train steps, loss finite and decreasing where
meaningful.  The full-scale entry points live in ``examples/``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models import (
    BertForPreTraining,
    BertModel,
    Discriminator,
    Generator,
    ResNet18,
    bert_tiny,
    gan_losses,
    pretraining_loss,
)
from apex_tpu.optimizers import FusedAdam, fused_lamb
from apex_tpu.parallel import DistributedDataParallel, data_parallel_mesh
from apex_tpu.utils.jax_compat import shard_map


class TestResNet:
    def setup_method(self, _):
        self.model = ResNet18(num_classes=10, width=16)
        self.x = jnp.asarray(np.random.RandomState(0)
                             .randn(4, 32, 32, 3).astype(np.float32))

    # The ResNet-50 variants sum to ~50s of jit compiles on the 2-vCPU
    # tier-1 box (ROADMAP wall-clock item): the Bottleneck gradient run
    # and the full O2 FusedAdam step are slow-marked; the S2D stem
    # variant (Bottleneck-based, ~2s) and the ResNet18 forward/cast
    # tests stay tier-1 as the fast representatives.
    @pytest.mark.slow
    def test_bottleneck_variant_trains(self):
        """Small-scale coverage of the Bottleneck block — the block of the
        flagship ResNet-50 — since ResNet18 is BasicBlock-based."""
        from apex_tpu.models.resnet import ResNet50
        model = ResNet50(num_classes=10, width=8)
        x = self.x
        variables = model.init(jax.random.PRNGKey(0), x, train=True)
        logits, updated = model.apply(
            variables, x, train=True, mutable=["batch_stats"])
        assert logits.shape == (4, 10)
        assert bool(jnp.isfinite(logits).all())
        g = jax.grad(lambda p: jnp.sum(model.apply(
            {"params": p, "batch_stats": variables["batch_stats"]}, x,
            train=True, mutable=["batch_stats"])[0]))(variables["params"])
        assert all(bool(jnp.isfinite(l).all()) for l in jax.tree.leaves(g))

    def test_s2d_stem_variant(self):
        """The TPU-native space-to-depth stem keeps the stage geometry
        (same output head, spatial/4 stem output) and trains; non-
        divisible spatial dims fail loudly."""
        from apex_tpu.models.resnet import ResNet50S2D
        model = ResNet50S2D(num_classes=10, width=8)
        x = self.x
        variables = model.init(jax.random.PRNGKey(0), x, train=True)
        # stem conv runs on the 16x-channel space-to-depth reshuffle
        assert variables["params"]["stem_conv"]["kernel"].shape == \
            (2, 2, 48, 8)
        logits, _ = model.apply(variables, x, train=True,
                                mutable=["batch_stats"])
        assert logits.shape == (4, 10)
        assert bool(jnp.isfinite(logits).all())
        with pytest.raises(ValueError, match="divisible by 4"):
            model.init(jax.random.PRNGKey(0), x[:, :30], train=True)

    def init(self):
        return self.model.init(jax.random.PRNGKey(0), self.x, train=True)

    def test_forward_shapes_and_stats(self):
        variables = self.init()
        logits, updated = self.model.apply(
            variables, self.x, train=True, mutable=["batch_stats"])
        assert logits.shape == (4, 10)
        assert bool(jnp.isfinite(logits).all())
        # running stats moved
        stem_mean = updated["batch_stats"]["stem_bn"]["mean"]
        assert float(jnp.abs(stem_mean).max()) > 0

    @pytest.mark.slow
    def test_o2_train_step_with_fused_adam(self):
        variables = self.init()
        params, batch_stats = variables["params"], variables["batch_stats"]
        a = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level="O2",
                           verbosity=0)
        state = a.init(params)
        y = jnp.asarray(np.random.RandomState(1).randint(0, 10, (4,)))

        def loss_fn(p, x, y):
            logits, _ = self.model.apply(
                {"params": p, "batch_stats": batch_stats}, x, train=True,
                mutable=["batch_stats"])
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))

        step = jax.jit(amp.make_train_step(a, loss_fn))
        losses = []
        for _ in range(3):
            state, m = step(state, self.x, y)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_bn_params_stay_fp32_under_o2(self):
        variables = self.init()
        a = amp.initialize(optimizer=optax.sgd(0.1), opt_level="O2",
                           verbosity=0)
        state = a.init(variables["params"])
        compute = a.model_params(state)
        stem_bn_scale = compute["stem_bn"]["scale"]
        conv_kernel = compute["stem_conv"]["kernel"]
        assert stem_bn_scale.dtype == jnp.float32   # keep_batchnorm_fp32
        assert conv_kernel.dtype == jnp.bfloat16

    def test_sync_bn_conversion_and_ddp_step(self):
        from apex_tpu.parallel import convert_syncbn_model
        # first 8 devices: the x8 batch shards over an 8-wide mesh
        mesh = data_parallel_mesh(num_devices=8)
        sync_model = convert_syncbn_model(self.model, axis_name="data")
        assert sync_model.bn_axis_name == "data"
        variables = sync_model.init(jax.random.PRNGKey(0), self.x, train=True)
        x8 = jnp.asarray(np.random.RandomState(2)
                         .randn(8, 32, 32, 3).astype(np.float32))

        def fwd(v, xb):
            logits, _ = sync_model.apply(v, xb, train=True,
                                         mutable=["batch_stats"])
            return logits

        logits = shard_map(
            fwd, mesh=mesh, in_specs=(P(), P("data")),
            out_specs=P("data"))(variables, x8)
        assert logits.shape == (8, 10)
        assert bool(jnp.isfinite(logits).all())


class TestBert:
    def setup_method(self, _):
        self.cfg = bert_tiny()
        self.model = BertForPreTraining(self.cfg)
        rng = np.random.RandomState(0)
        B, L = 2, 16
        self.ids = jnp.asarray(rng.randint(0, self.cfg.vocab_size, (B, L)))
        self.mask = jnp.ones((B, L), jnp.int32)
        self.mlm_labels = jnp.asarray(
            rng.randint(0, self.cfg.vocab_size, (B, L)))
        self.mlm_mask = jnp.asarray((rng.rand(B, L) < 0.15)
                                    .astype(np.float32))
        self.nsp = jnp.asarray(rng.randint(0, 2, (B,)))

    def test_forward(self):
        variables = self.model.init(jax.random.PRNGKey(0), self.ids,
                                    attention_mask=self.mask)
        mlm, nsp = self.model.apply(variables, self.ids,
                                    attention_mask=self.mask)
        assert mlm.shape == (2, 16, self.cfg.vocab_size)
        assert nsp.shape == (2, 2)

    def test_lamb_pretraining_steps(self):
        variables = self.model.init(jax.random.PRNGKey(0), self.ids,
                                    attention_mask=self.mask)
        a = amp.initialize(optimizer=fused_lamb(learning_rate=1e-3),
                           opt_level="O2", verbosity=0)
        state = a.init(variables["params"])

        def loss_fn(p, ids, mask, mlm_labels, mlm_mask, nsp):
            mlm, nspl = self.model.apply({"params": p}, ids,
                                         attention_mask=mask)
            return pretraining_loss(mlm, nspl, mlm_labels=mlm_labels,
                                    nsp_labels=nsp, mlm_mask=mlm_mask)

        step = jax.jit(amp.make_train_step(a, loss_fn))
        losses = []
        for _ in range(3):
            state, m = step(state, self.ids, self.mask, self.mlm_labels,
                            self.mlm_mask, self.nsp)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]


class TestDCGAN:
    def test_two_loss_scaler_training(self):
        """The num_losses=2 machinery: independent scalers for G and D."""
        G, D = Generator(feature_maps=8, n_upsample=1), \
            Discriminator(feature_maps=8, n_down=2)
        rng = np.random.RandomState(0)
        z = jnp.asarray(rng.randn(4, 16).astype(np.float32))
        real = jnp.asarray(rng.rand(4, 16, 16, 3).astype(np.float32) * 2 - 1)

        gv = G.init(jax.random.PRNGKey(0), z, train=True)
        dv = D.init(jax.random.PRNGKey(1), real, train=True)

        a_g = amp.initialize(optimizer=optax.adam(2e-4), opt_level="O1",
                             verbosity=0)
        a_d = amp.initialize(optimizer=optax.adam(2e-4), opt_level="O1",
                             verbosity=0)
        gs = a_g.init(gv["params"])
        ds = a_d.init(dv["params"])

        def d_loss_fn(dp, gp):
            fake = G.apply({"params": gp, "batch_stats": gv["batch_stats"]},
                           z, train=True, mutable=["batch_stats"])[0]
            d_real = D.apply({"params": dp, "batch_stats": dv["batch_stats"]},
                             real, train=True, mutable=["batch_stats"])[0]
            d_fake = D.apply({"params": dp, "batch_stats": dv["batch_stats"]},
                             fake, train=True, mutable=["batch_stats"])[0]
            d_loss, _ = gan_losses(d_real, d_fake, d_fake)
            return d_loss

        def g_loss_fn(gp, dp):
            fake = G.apply({"params": gp, "batch_stats": gv["batch_stats"]},
                           z, train=True, mutable=["batch_stats"])[0]
            g_logits = D.apply(
                {"params": dp, "batch_stats": dv["batch_stats"]},
                fake, train=True, mutable=["batch_stats"])[0]
            _, g_loss = gan_losses(g_logits, g_logits, g_logits)
            return g_loss

        @jax.jit
        def step(gs, ds):
            d_grads = jax.grad(
                lambda dp: a_d.scaler.scale_loss(
                    d_loss_fn(dp, a_g.model_params(gs)),
                    ds.scaler_states[0]))(a_d.model_params(ds))
            ds2, d_info = a_d.apply_gradients(ds, d_grads)
            g_grads = jax.grad(
                lambda gp: a_g.scaler.scale_loss(
                    g_loss_fn(gp, a_d.model_params(ds2)),
                    gs.scaler_states[0]))(a_g.model_params(gs))
            gs2, g_info = a_g.apply_gradients(gs, g_grads)
            return gs2, ds2, d_info, g_info

        for _ in range(2):
            gs, ds, d_info, g_info = step(gs, ds)
        assert not bool(d_info["overflow"])
        assert not bool(g_info["overflow"])
        # scalers advanced independently
        assert float(ds.scaler_states[0].loss_scale) == 2.0 ** 16
        assert float(gs.scaler_states[0].loss_scale) == 2.0 ** 16


class TestBertScanRemat:
    """scan_layers / remat variants must match the unrolled loop exactly in
    values and gradients (scan reuses the same per-layer math; remat only
    changes the backward schedule, not the numbers)."""

    def _outputs_and_grads(self, cfg, params_loop=None):
        model = BertModel(cfg)
        ids = jnp.asarray(np.random.RandomState(0).randint(
            0, cfg.vocab_size, (2, 16)))
        if params_loop is None:
            variables = model.init(jax.random.PRNGKey(0), ids)
        else:
            variables = params_loop
        y = model.apply(variables, ids)

        def loss(v):
            return jnp.sum(model.apply(v, ids).astype(jnp.float32) ** 2)

        g = jax.grad(loss)(variables)
        return variables, y, g

    @staticmethod
    def _stack_loop_params(params, num_layers):
        """Rearrange layer_{i} param trees into the scanned stacked layout
        (layers/layer/... with a leading layer axis)."""
        p = dict(params["params"])
        layers = [p.pop(f"layer_{i}") for i in range(num_layers)]
        p["layers"] = {"layer": jax.tree.map(
            lambda *xs: jnp.stack(xs), *layers)}
        return {"params": p}

    def test_scan_and_remat_match_loop(self):
        import dataclasses as dc
        cfg_loop = dc.replace(bert_tiny(), scan_layers=False)
        v_loop, y_loop, g_loop = self._outputs_and_grads(cfg_loop)

        # remat on the unrolled loop: same params tree, same numbers
        cfg_lr = dc.replace(bert_tiny(), scan_layers=False, remat=True)
        _, y_lr, g_lr = self._outputs_and_grads(cfg_lr, v_loop)
        np.testing.assert_allclose(np.asarray(y_lr), np.asarray(y_loop),
                                   rtol=1e-5, atol=1e-5)
        for a, b in zip(jax.tree.leaves(g_lr), jax.tree.leaves(g_loop)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)

        for remat in (False, True):
            cfg = dc.replace(bert_tiny(), scan_layers=True, remat=remat)
            v = self._stack_loop_params(v_loop, cfg.num_layers)
            _, y, g = self._outputs_and_grads(cfg, v)
            np.testing.assert_allclose(np.asarray(y), np.asarray(y_loop),
                                       rtol=1e-5, atol=1e-5)
            g_restacked = self._stack_loop_params(g_loop, cfg.num_layers)
            for a, b in zip(jax.tree.leaves(g),
                            jax.tree.leaves(g_restacked)):
                # scan vs unrolled reassociates reductions: near-zero grad
                # elements wobble at ~1e-5 absolute; structure must agree
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-3, atol=1e-4)


class TestBertHeadWidthDispatch:
    """SelfAttention dispatches by head width under the kernel gate:
    narrow heads ride the head-major layout, wide heads (>= 128) the
    split+flash path — both must match the jnp einsum branch (which the
    tiny default configs alone never check for the wide branch)."""

    @pytest.mark.parametrize("num_heads,label", [(4, "narrow-32"),
                                                 (1, "wide-128")])
    def test_pallas_branches_match_jnp(self, monkeypatch, num_heads,
                                       label):
        import dataclasses as dc
        cfg = dc.replace(bert_tiny(), num_heads=num_heads)
        model = BertForPreTraining(cfg)
        rng = np.random.RandomState(3)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 16)))
        mask = jnp.ones((2, 16), jnp.int32).at[:, -3:].set(0)

        monkeypatch.setenv("APEX_TPU_KERNELS", "jnp")
        variables = model.init(jax.random.PRNGKey(0), ids,
                               attention_mask=mask)
        mlm_jnp, _ = model.apply(variables, ids, attention_mask=mask)

        monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")
        mlm_pl, _ = model.apply(variables, ids, attention_mask=mask)
        np.testing.assert_allclose(
            np.asarray(mlm_pl, np.float32), np.asarray(mlm_jnp, np.float32),
            rtol=5e-2, atol=5e-2, err_msg=label)
