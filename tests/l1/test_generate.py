"""KV-cached generation vs naive full-forward decoding.

The oracle re-runs ``GPTModel.apply`` on the whole growing sequence
every step (no cache) and takes the last-position argmax; the cached
decoder must produce the IDENTICAL token sequence (and matching final
logits) from the same training checkpoint — this pins the manual layer
math (fused LN, rope positions, fp32 softmax, gelu flavor), the cache
write offsets, and the decode-time causal mask all at once.
"""

import dataclasses as dc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTModel, gpt_tiny
from apex_tpu.models.generate import generate

B, L_PROMPT, NEW = 2, 12, 8


@pytest.fixture(scope="module")
def setup():
    cfg = gpt_tiny()
    model = GPTModel(cfg)
    rng = np.random.RandomState(0)
    prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L_PROMPT)))
    params = model.init(jax.random.PRNGKey(1), prompt)["params"]
    return cfg, model, params, prompt


def _naive_generate(model, params, prompt, steps):
    ids = prompt
    for _ in range(steps):
        logits = model.apply({"params": params}, ids)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(ids.dtype)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    return ids


def test_greedy_matches_naive_full_forward(setup):
    cfg, model, params, prompt = setup
    want = _naive_generate(model, params, prompt, NEW)
    got = generate(params, cfg, prompt, NEW)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_scan_layout_checkpoint(setup):
    """Scan-layout params (stacked ``layers/block``) decode to the same
    tokens as the loop layout they were stacked from."""
    cfg, model, params, prompt = setup
    p = dict(params)
    blocks = [p.pop(f"block_{i}") for i in range(cfg.num_layers)]
    p["layers"] = {"block": jax.tree.map(lambda *xs: jnp.stack(xs),
                                         *blocks)}
    want = generate(params, cfg, prompt, NEW)
    got = generate(p, cfg, prompt, NEW)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_temperature_sampling_deterministic_and_varied(setup):
    cfg, _, params, prompt = setup
    a = generate(params, cfg, prompt, NEW, temperature=1.0,
                 rng=jax.random.PRNGKey(7))
    b = generate(params, cfg, prompt, NEW, temperature=1.0,
                 rng=jax.random.PRNGKey(7))
    c = generate(params, cfg, prompt, NEW, temperature=1.0,
                 rng=jax.random.PRNGKey(8))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    # prompts are preserved verbatim
    np.testing.assert_array_equal(np.asarray(a[:, :L_PROMPT]),
                                  np.asarray(prompt))
    with pytest.raises(ValueError, match="rng"):
        generate(params, cfg, prompt, NEW, temperature=0.7)


def test_single_token_decode(setup):
    cfg, model, params, prompt = setup
    want = _naive_generate(model, params, prompt, 1)
    got = generate(params, cfg, prompt, 1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_tpu_head_geometry_config():
    """Wide heads (d=128 class for the tiny scale) decode exactly too —
    the geometry the TPU configs use."""
    cfg = dc.replace(gpt_tiny(), num_heads=2)
    model = GPTModel(cfg)
    rng = np.random.RandomState(3)
    prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (1, 6)))
    params = model.init(jax.random.PRNGKey(2), prompt)["params"]
    want = _naive_generate(model, params, prompt, 5)
    got = generate(params, cfg, prompt, 5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_prefill_accepts_concrete_zero_start(setup):
    """The multi-token prefill guard takes a CONCRETE 0-d zero, not just
    Python ``int`` 0 — a caller that keeps its position counter
    on-device (``jnp.int32(0)``) must hit the flash-prefill path, not a
    spurious NotImplementedError.  Logits must match the int-0 call."""
    from apex_tpu.models.generate import (_forward_cached,
                                          _stack_layer_params)
    cfg, _, params, prompt = setup
    stacked = _stack_layer_params(params, cfg.num_layers)
    top = {k: v for k, v in params.items() if not k.startswith("block_")}
    head_dim = cfg.hidden_size // cfg.num_heads
    m = L_PROMPT + 2

    def caches():
        kc = jnp.zeros((cfg.num_layers, B, m, cfg.num_heads, head_dim),
                       jnp.float32)
        return kc, jnp.zeros_like(kc)

    kc, vc = caches()
    want, _, _, _, _ = _forward_cached(top, stacked, cfg, prompt,
                                       kc, vc, start=0)
    kc, vc = caches()
    got, _, _, _, _ = _forward_cached(top, stacked, cfg, prompt,
                                      kc, vc, start=jnp.int32(0))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5)


def _prefill_fixture(setup, m):
    from apex_tpu.models.generate import _stack_layer_params
    cfg, _, params, prompt = setup
    stacked = _stack_layer_params(params, cfg.num_layers)
    top = {k: v for k, v in params.items() if not k.startswith("block_")}
    head_dim = cfg.hidden_size // cfg.num_heads

    def caches():
        kc = jnp.zeros((cfg.num_layers, B, m, cfg.num_heads, head_dim),
                       jnp.float32)
        return kc, jnp.zeros_like(kc)

    return cfg, top, stacked, prompt, caches


def test_chunked_prefill_matches_full_prefill_and_decode(setup):
    """A prompt appended in multi-token chunks at traced mid-sequence
    ``start`` values (the serve engine's admission path) must land the
    SAME cache contents bitwise and matching final logits as the
    one-shot flash prefill — the chunk attends to the cached history
    plus causally to itself through the einsum path — and the greedy
    token it implies must equal solo ``generate``'s (mid-stream
    admission cannot perturb decode)."""
    from apex_tpu.models.generate import _forward_cached
    cfg, _, params, prompt = setup
    m = L_PROMPT + 2
    _, top, stacked, _, caches = _prefill_fixture(setup, m)

    kc, vc = caches()
    want, kc_w, vc_w, _, _ = _forward_cached(top, stacked, cfg, prompt,
                                             kc, vc, start=0)
    kc, vc = caches()
    got = None
    for j in range(0, L_PROMPT, 4):
        got, kc, vc, _, _ = _forward_cached(top, stacked, cfg,
                                            prompt[:, j:j + 4], kc, vc,
                                            start=jnp.int32(j))
    # cache contents are pure data movement + the same per-position
    # math: bitwise equal.  Logits of the last chunk row go through a
    # different attention SHAPE (4-row einsum vs full flash prefill),
    # so they match to fp tolerance, not bitwise.
    np.testing.assert_array_equal(np.asarray(kc), np.asarray(kc_w))
    np.testing.assert_array_equal(np.asarray(vc), np.asarray(vc_w))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    first = jnp.argmax(got, axis=-1)
    solo = generate(params, cfg, prompt, 1)
    np.testing.assert_array_equal(np.asarray(first),
                                  np.asarray(solo[:, L_PROMPT]))
