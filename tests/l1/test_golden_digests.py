"""Stored-baseline digest comparison — the reference ``compare.py
--use_baseline`` mode (``tests/L1/common/compare.py:36-63``): every run's
per-iteration losses are diffed against a digest file saved from an earlier
run/build, so numerical drift across commits fails the suite until the
baseline is intentionally regenerated:

    APEX_TPU_REGEN_GOLDEN=1 python -m pytest tests/l1/test_golden_digests.py

The baseline is platform-specific (XLA:CPU vs XLA:TPU produce different —
each internally deterministic — float sequences); configs are compared only
on the platform they were recorded on and skipped elsewhere.

Each re-pinned digest carries a one-line provenance note in the
``_provenance`` block of ``golden_digests.json`` naming what moved it,
and a drift (:func:`explain_drift`) reports the old/new fingerprint,
the first diverging iteration, and the suspected knob — the config's
fused-kernel geometry axis when it has one, the XLA:CPU environment
when it doesn't — instead of a bare mismatch.
"""

import json
import os
from pathlib import Path

import jax
import numpy as np
import pytest

from tests.l1.harness import run_workload

GOLDEN = Path(__file__).with_name("golden_digests.json")

#: config name -> run_workload kwargs.  One per distinct numerics regime:
#: pure fp32, O1 cast ops, O2 master weights + fused optimizer, O3 static
#: scale, BN-in-fp32, and the overflow-skip state machine — widened in
#: round 4 (VERDICT r3 #5) with the fused-adam × keep-bn ×
#: static/dynamic-scale crosses toward the reference's swept surface
#: (``tests/L1/common/run_test.sh:1-150``).
CONFIGS = {
    "o0_fp32": dict(opt_level="O0"),
    "o1_dynamic": dict(opt_level="O1", loss_scale="dynamic"),
    "o2_dynamic_fused_adam": dict(opt_level="O2", loss_scale="dynamic",
                                  fused_adam=True),
    "o3_static128": dict(opt_level="O3", loss_scale=128.0),
    "o2_bn_keep_fp32": dict(opt_level="O2", keep_batchnorm_fp32=True,
                            with_bn=True),
    "o2_overflow_inject": dict(opt_level="O2", loss_scale="dynamic",
                               inject_inf_at=2),
    # round-4 widening: fused-adam × keep-bn × scale-mode crosses
    "o0_bn_fp32": dict(opt_level="O0", with_bn=True),
    "o1_static128": dict(opt_level="O1", loss_scale=128.0),
    "o1_bn_dynamic": dict(opt_level="O1", loss_scale="dynamic",
                          with_bn=True),
    "o1_overflow_inject": dict(opt_level="O1", loss_scale="dynamic",
                               inject_inf_at=2),
    "o2_static128_fused_adam": dict(opt_level="O2", loss_scale=128.0,
                                    fused_adam=True),
    "o2_bn_keep_fused_adam_dynamic": dict(
        opt_level="O2", loss_scale="dynamic", keep_batchnorm_fp32=True,
        fused_adam=True, with_bn=True),
    "o2_bn_keep_fused_adam_static128": dict(
        opt_level="O2", loss_scale=128.0, keep_batchnorm_fp32=True,
        fused_adam=True, with_bn=True),
    "o2_bn_nokeep_fused_adam_dynamic": dict(
        opt_level="O2", loss_scale="dynamic", keep_batchnorm_fp32=False,
        fused_adam=True, with_bn=True),
    "o3_dynamic": dict(opt_level="O3", loss_scale="dynamic"),
    "o3_bn_keep_static128": dict(
        opt_level="O3", loss_scale=128.0, keep_batchnorm_fp32=True,
        with_bn=True),
    "o3_bn_keep_fused_adam_static128": dict(
        opt_level="O3", loss_scale=128.0, keep_batchnorm_fp32=True,
        fused_adam=True, with_bn=True),
}


def _record(cfg_kwargs):
    d = run_workload(**cfg_kwargs)
    return {
        "fingerprint": d["fingerprint"],
        "losses": [float(x) for x in d["losses"]],
        "scales": [float(x) for x in d["scales"]],
        "overflows": [bool(x) for x in d["overflows"]],
    }


def _load():
    if not GOLDEN.exists():
        return {}
    return json.loads(GOLDEN.read_text())


def suspect_knob(cfg_kwargs: dict) -> str:
    """The geometry/config axis most likely behind a drift for this
    config — fused kernels first (their block geometry is the only
    numerics-relevant tuning surface), the XLA:CPU environment when the
    config exercises no fused kernel at all."""
    if cfg_kwargs.get("fused_adam"):
        return ("fused-adam block geometry "
                "(apex_tpu/ops/pallas/geometry.py selector / ADAM_PAD)")
    if cfg_kwargs.get("with_bn"):
        return ("batch-norm statistics path / layer-norm kernel row "
                "blocking (dγ/dβ accumulation order is digest contract)")
    return ("XLA:CPU codegen environment (no fused kernel in this "
            "config: SGD + jnp reference path)")


def explain_drift(name: str, cfg_kwargs: dict, want: dict,
                  got: dict) -> str:
    """Old/new digest, first diverging iteration, and the suspected
    knob — what a triager needs before deciding regenerate-vs-revert."""
    def differs(a, b):
        # NaN is a legitimate stored value (overflow-inject configs
        # record it by design): NaN-vs-NaN is a MATCH, not the
        # divergence point
        if isinstance(a, float) and isinstance(b, float) \
                and np.isnan(a) and np.isnan(b):
            return False
        return a != b

    diverge = next((i for i, (a, b) in enumerate(
        zip(want["losses"], got["losses"])) if differs(a, b)),
        None)
    if diverge is None and len(want["losses"]) != len(got["losses"]):
        # zip truncates to the shorter run — a missing/extra iteration
        # IS the divergence point, not a loss match
        diverge = min(len(want["losses"]), len(got["losses"]))
    lines = [
        f"numerical drift vs stored baseline for {name}:",
        f"  stored fingerprint:  {want['fingerprint']}",
        f"  current fingerprint: {got['fingerprint']}",
        f"  first diverging iteration: "
        f"{'none (loss match; scales/overflows differ)' if diverge is None else diverge}",
    ]
    if diverge is not None:
        def at(xs, i):
            return repr(xs[i]) if i < len(xs) else \
                f"<absent — run has {len(xs)} iteration(s)>"
        lines.append(f"    stored[{diverge}]={at(want['losses'], diverge)}"
                     f" current[{diverge}]={at(got['losses'], diverge)}")
    lines += [
        f"  suspected knob: {suspect_knob(cfg_kwargs)}",
        f"  stored losses:  {want['losses']}",
        f"  current losses: {got['losses']}",
        f"  stored scales:  {want['scales']}",
        f"  current scales: {got['scales']}",
        "If this change is intentional, regenerate with "
        "APEX_TPU_REGEN_GOLDEN=1, commit the new golden_digests.json, "
        "and record the cause in its _provenance block.",
    ]
    return "\n".join(lines)


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_golden_digest(name):
    platform = jax.devices()[0].platform
    stored = _load()
    if os.environ.get("APEX_TPU_REGEN_GOLDEN"):
        stored.setdefault(platform, {})[name] = _record(CONFIGS[name])
        GOLDEN.write_text(json.dumps(stored, indent=1, sort_keys=True)
                          + "\n")
        pytest.skip(f"regenerated baseline for {platform}/{name}")
    if platform not in stored or name not in stored[platform]:
        pytest.skip(f"no stored baseline for platform {platform!r}; "
                    f"regenerate with APEX_TPU_REGEN_GOLDEN=1")
    want = stored[platform][name]
    got = _record(CONFIGS[name])
    assert got["fingerprint"] == want["fingerprint"], \
        explain_drift(name, CONFIGS[name], want, got)
    # redundant with the fingerprint, but gives a readable diff on failure
    np.testing.assert_array_equal(got["losses"], want["losses"])
    np.testing.assert_array_equal(got["scales"], want["scales"])
    assert got["overflows"] == want["overflows"]


def test_explain_drift_names_digests_and_knob():
    """The drift report must carry old/new fingerprint, the first
    diverging iteration, and the suspected knob — never a bare
    mismatch."""
    want = {"fingerprint": 111, "losses": [1.0, 2.0, 3.0],
            "scales": [128.0], "overflows": [False]}
    got = {"fingerprint": 222, "losses": [1.0, 2.5, 3.0],
           "scales": [128.0], "overflows": [False]}
    msg = explain_drift("o2_x", {"fused_adam": True}, want, got)
    assert "111" in msg and "222" in msg
    assert "first diverging iteration: 1" in msg
    # NaN stored AND current (overflow-inject configs) is a match, not
    # the divergence point
    nan_want = {**want, "losses": [1.0, float("nan"), 3.0]}
    nan_got = {**got, "losses": [1.0, float("nan"), 3.5]}
    assert "first diverging iteration: 2" in explain_drift(
        "o1_overflow_inject", {}, nan_want, nan_got)
    # a run shorter than the baseline diverges at the truncation
    # point — never "none (loss match...)"
    short_got = {**got, "losses": [1.0, 2.0]}
    short_msg = explain_drift("o0_fp32", {}, want, short_got)
    assert "first diverging iteration: 2" in short_msg
    assert "<absent" in short_msg and "none (loss match" not in short_msg
    assert "geometry" in msg and "_provenance" in msg
    assert "fused-adam" in suspect_knob({"fused_adam": True})
    assert "batch-norm" in suspect_knob({"with_bn": True})
    assert "XLA:CPU" in suspect_knob({})


def test_repinned_digests_carry_provenance():
    """Every digest re-pinned at PR 4 has a one-line provenance note;
    the note names where the old value came from."""
    stored = _load()
    prov = stored.get("_provenance", {})
    for name in ("o0_fp32", "o0_bn_fp32", "o1_dynamic", "o1_static128",
                 "o1_bn_dynamic", "o1_overflow_inject"):
        assert f"cpu/{name}" in prov, f"missing provenance for {name}"
        assert "round-5 host" in prov[f"cpu/{name}"]
