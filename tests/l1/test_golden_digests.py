"""Stored-baseline digest comparison — the reference ``compare.py
--use_baseline`` mode (``tests/L1/common/compare.py:36-63``): every run's
per-iteration losses are diffed against a digest file saved from an earlier
run/build, so numerical drift across commits fails the suite until the
baseline is intentionally regenerated:

    APEX_TPU_REGEN_GOLDEN=1 python -m pytest tests/l1/test_golden_digests.py

The baseline is platform-specific (XLA:CPU vs XLA:TPU produce different —
each internally deterministic — float sequences); configs are compared only
on the platform they were recorded on and skipped elsewhere.
"""

import json
import os
from pathlib import Path

import jax
import numpy as np
import pytest

from tests.l1.harness import run_workload

GOLDEN = Path(__file__).with_name("golden_digests.json")

#: config name -> run_workload kwargs.  One per distinct numerics regime:
#: pure fp32, O1 cast ops, O2 master weights + fused optimizer, O3 static
#: scale, BN-in-fp32, and the overflow-skip state machine — widened in
#: round 4 (VERDICT r3 #5) with the fused-adam × keep-bn ×
#: static/dynamic-scale crosses toward the reference's swept surface
#: (``tests/L1/common/run_test.sh:1-150``).
CONFIGS = {
    "o0_fp32": dict(opt_level="O0"),
    "o1_dynamic": dict(opt_level="O1", loss_scale="dynamic"),
    "o2_dynamic_fused_adam": dict(opt_level="O2", loss_scale="dynamic",
                                  fused_adam=True),
    "o3_static128": dict(opt_level="O3", loss_scale=128.0),
    "o2_bn_keep_fp32": dict(opt_level="O2", keep_batchnorm_fp32=True,
                            with_bn=True),
    "o2_overflow_inject": dict(opt_level="O2", loss_scale="dynamic",
                               inject_inf_at=2),
    # round-4 widening: fused-adam × keep-bn × scale-mode crosses
    "o0_bn_fp32": dict(opt_level="O0", with_bn=True),
    "o1_static128": dict(opt_level="O1", loss_scale=128.0),
    "o1_bn_dynamic": dict(opt_level="O1", loss_scale="dynamic",
                          with_bn=True),
    "o1_overflow_inject": dict(opt_level="O1", loss_scale="dynamic",
                               inject_inf_at=2),
    "o2_static128_fused_adam": dict(opt_level="O2", loss_scale=128.0,
                                    fused_adam=True),
    "o2_bn_keep_fused_adam_dynamic": dict(
        opt_level="O2", loss_scale="dynamic", keep_batchnorm_fp32=True,
        fused_adam=True, with_bn=True),
    "o2_bn_keep_fused_adam_static128": dict(
        opt_level="O2", loss_scale=128.0, keep_batchnorm_fp32=True,
        fused_adam=True, with_bn=True),
    "o2_bn_nokeep_fused_adam_dynamic": dict(
        opt_level="O2", loss_scale="dynamic", keep_batchnorm_fp32=False,
        fused_adam=True, with_bn=True),
    "o3_dynamic": dict(opt_level="O3", loss_scale="dynamic"),
    "o3_bn_keep_static128": dict(
        opt_level="O3", loss_scale=128.0, keep_batchnorm_fp32=True,
        with_bn=True),
    "o3_bn_keep_fused_adam_static128": dict(
        opt_level="O3", loss_scale=128.0, keep_batchnorm_fp32=True,
        fused_adam=True, with_bn=True),
}


def _record(cfg_kwargs):
    d = run_workload(**cfg_kwargs)
    return {
        "fingerprint": d["fingerprint"],
        "losses": [float(x) for x in d["losses"]],
        "scales": [float(x) for x in d["scales"]],
        "overflows": [bool(x) for x in d["overflows"]],
    }


def _load():
    if not GOLDEN.exists():
        return {}
    return json.loads(GOLDEN.read_text())


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_golden_digest(name):
    platform = jax.devices()[0].platform
    stored = _load()
    if os.environ.get("APEX_TPU_REGEN_GOLDEN"):
        stored.setdefault(platform, {})[name] = _record(CONFIGS[name])
        GOLDEN.write_text(json.dumps(stored, indent=1, sort_keys=True)
                          + "\n")
        pytest.skip(f"regenerated baseline for {platform}/{name}")
    if platform not in stored or name not in stored[platform]:
        pytest.skip(f"no stored baseline for platform {platform!r}; "
                    f"regenerate with APEX_TPU_REGEN_GOLDEN=1")
    want = stored[platform][name]
    got = _record(CONFIGS[name])
    assert got["fingerprint"] == want["fingerprint"], (
        f"numerical drift vs stored baseline for {name}:\n"
        f"  stored losses: {want['losses']}\n"
        f"  current losses: {got['losses']}\n"
        f"  stored scales: {want['scales']}\n"
        f"  current scales: {got['scales']}\n"
        "If this change is intentional, regenerate with "
        "APEX_TPU_REGEN_GOLDEN=1 and commit the new golden_digests.json.")
    # redundant with the fingerprint, but gives a readable diff on failure
    np.testing.assert_array_equal(got["losses"], want["losses"])
    np.testing.assert_array_equal(got["scales"], want["scales"])
    assert got["overflows"] == want["overflows"]
