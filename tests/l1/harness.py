"""L1 conformance harness: train small workloads, produce loss digests.

Port of ``tests/L1/common/main_amp.py`` + ``compare.py``: the reference
trained the same workload twice — once with the CUDA extensions installed,
once Python-only — and asserted per-iteration loss *bitwise equality*
between the two installs.  Our two installs are the kernel paths
(``APEX_TPU_KERNELS=pallas`` vs ``jnp``, SURVEY.md §7 "Bitwise L1
conformance"); the digest is the per-iteration loss sequence plus its
native fingerprint (``csrc/apex_tpu_C.cpp`` ``apex_fingerprint64`` — the
analog of compare.py's stored digests).

Determinism contract (the ``--deterministic`` flag): fixed PRNG keys, fixed
synthetic data, single compiled path — two runs of the same config must
produce identical fingerprints.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
import flax.linen as nn

from apex_tpu import amp
from apex_tpu import _native
from apex_tpu.layers import Conv, Dense
from apex_tpu.models.mlp import MLP, cross_entropy_loss
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm


@contextmanager
def kernel_path(mode: str):
    """Select the fused (pallas) or reference (jnp) kernel path — the
    harness's with-ext / no-ext axis (``run_test.sh`` pip-reinstalled apex
    both ways; we flip APEX_TPU_KERNELS)."""
    old = os.environ.get("APEX_TPU_KERNELS")
    os.environ["APEX_TPU_KERNELS"] = mode
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("APEX_TPU_KERNELS", None)
        else:
            os.environ["APEX_TPU_KERNELS"] = old


class ConvBNNet(nn.Module):
    """Tiny conv net with BatchNorm — exercises keep_batchnorm_fp32."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = Conv(8, 3, name="conv1")(x)
        x = SyncBatchNorm(name="bn1")(x, use_running_average=not train)
        x = nn.relu(x)
        x = x.reshape(x.shape[0], -1)
        return Dense(self.num_classes, name="fc")(x)


def digest_name(kernels: str, opt_level: str, loss_scale, keep_bn,
                fused_adam: bool) -> str:
    """Reference digest file naming:
    ``<has_ext>_<opt_level>_<loss_scale>_<keep_bn>_<fused_adam>``."""
    return f"{kernels}_{opt_level}_{loss_scale}_{keep_bn}_{fused_adam}"


def run_workload(
    opt_level: str = "O1",
    loss_scale: Union[None, float, str] = None,
    keep_batchnorm_fp32=None,
    fused_adam: bool = False,
    with_bn: bool = False,
    steps: int = 6,
    batch: int = 32,
    seed: int = 0,
    kernels: str = "auto",
    inject_inf_at: Optional[int] = None,
) -> Dict:
    """Train a small workload deterministically; return its digest.

    ``inject_inf_at``: plant an inf in the input at that iteration — the
    fault-injection axis of the reference conformance suite
    (``test_multiple_models_optimizers_losses.py:69-80``).
    """
    with kernel_path(kernels):
        if with_bn:
            model = ConvBNNet()
            x0 = jnp.zeros((2, 8, 8, 3))
            variables = model.init(jax.random.PRNGKey(seed), x0, train=True)
            params = variables["params"]
            batch_stats = variables["batch_stats"]
        else:
            model = MLP(features=(64, 64))
            params = model.init(jax.random.PRNGKey(seed),
                                jnp.zeros((1, 32)))["params"]
            batch_stats = None

        tx = (FusedAdam(lr=1e-2) if fused_adam
              else optax.sgd(0.05, momentum=0.9))
        a = amp.initialize(optimizer=tx, opt_level=opt_level,
                           loss_scale=loss_scale,
                           keep_batchnorm_fp32=keep_batchnorm_fp32,
                           verbosity=0)
        state = a.init(params)

        if with_bn:
            def make_loss(stats):
                def loss_fn(p, xb, yb):
                    logits, mut = model.apply(
                        {"params": p, "batch_stats": stats}, xb,
                        train=True, mutable=["batch_stats"])
                    return (cross_entropy_loss(logits, yb),
                            mut["batch_stats"])
                return loss_fn

            def step(state, stats, xb, yb):
                inner = amp.make_train_step(a, make_loss(stats),
                                            has_aux=True)
                state, m = inner(state, xb, yb)
                return state, m["aux"], m

            step = jax.jit(step)
        else:
            inner = amp.make_train_step(
                a, lambda p, xb, yb: cross_entropy_loss(
                    model.apply({"params": p}, xb), yb))

            def step(state, stats, xb, yb):
                state, m = inner(state, xb, yb)
                return state, stats, m

            step = jax.jit(step)

        rng = np.random.RandomState(seed)
        if with_bn:
            data_x = rng.randn(steps, batch, 8, 8, 3).astype(np.float32)
        else:
            data_x = rng.randn(steps, batch, 32).astype(np.float32)
        data_y = rng.randint(0, 10, (steps, batch))

        losses, scales, overflows = [], [], []
        for i in range(steps):
            xb = jnp.asarray(data_x[i])
            if inject_inf_at is not None and i == inject_inf_at:
                xb = xb.at[0].set(jnp.inf)
            state, batch_stats, m = step(state, batch_stats, xb,
                                         jnp.asarray(data_y[i]))
            losses.append(float(m["loss"]))
            scales.append(float(m["loss_scale"]))
            overflows.append(bool(m["overflow"]))

        loss_arr = np.asarray(losses, dtype=np.float64)
        return {
            "losses": losses,
            "scales": scales,
            "overflows": overflows,
            "fingerprint": _native.fingerprint64(loss_arr),
            "final_params": state.master_params,
        }
