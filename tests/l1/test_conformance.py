"""L1 conformance cross-product (``tests/L1/common/run_test.sh:1-150``).

The reference asserted per-iteration loss bitwise equality between the
CUDA-ext and Python-only installs over {O0–O3} × {default, 1.0, 128.0,
dynamic loss scale} × keep_batchnorm variants.  Here the two installs are
the pallas(interpret) and jnp kernel paths; equality is exact (same dtypes,
same PRNG, SURVEY.md §7's redefined contract), and every config also gets
a tolerance check against the O0 fp32 reference run.
"""

import numpy as np
import pytest

from tests.l1.harness import digest_name, run_workload

OPT_LEVELS = ["O0", "O1", "O2", "O3"]
LOSS_SCALES = [None, 1.0, 128.0, "dynamic"]


@pytest.mark.parametrize("opt_level", OPT_LEVELS)
@pytest.mark.parametrize("loss_scale", [None, 128.0])
def test_fused_vs_reference_path_exact(opt_level, loss_scale):
    """The ext-vs-no-ext bitwise axis: fused (pallas) and reference (jnp)
    kernel paths must produce identical loss digests."""
    ref = run_workload(opt_level=opt_level, loss_scale=loss_scale,
                       kernels="jnp", fused_adam=True)
    fused = run_workload(opt_level=opt_level, loss_scale=loss_scale,
                         kernels="pallas", fused_adam=True)
    assert ref["fingerprint"] == fused["fingerprint"], (
        digest_name("jnp", opt_level, loss_scale, None, True),
        ref["losses"], fused["losses"])


@pytest.mark.parametrize("loss_scale", LOSS_SCALES)
def test_deterministic_reruns(loss_scale):
    """--deterministic contract: identical config → identical fingerprint."""
    a = run_workload(opt_level="O1", loss_scale=loss_scale)
    b = run_workload(opt_level="O1", loss_scale=loss_scale)
    assert a["fingerprint"] == b["fingerprint"]


@pytest.mark.parametrize("opt_level", ["O1", "O2", "O3"])
def test_mixed_precision_tracks_fp32(opt_level):
    """Every opt level's loss curve stays near the O0 fp32 reference
    (compare.py's stored-baseline axis, tolerance-based per SURVEY §7)."""
    ref = run_workload(opt_level="O0")
    got = run_workload(opt_level=opt_level)
    # bf16 compute: loose but meaningful tolerance; curves must co-descend
    np.testing.assert_allclose(got["losses"], ref["losses"],
                               rtol=0.1, atol=0.05)
    assert got["losses"][-1] < got["losses"][0]


@pytest.mark.parametrize("loss_scale", [128.0, "dynamic"])
@pytest.mark.parametrize("keep_bn", [True, False])
@pytest.mark.parametrize("opt_level", ["O2", "O3"])
def test_fused_adam_keep_bn_scale_cross_exact(opt_level, keep_bn,
                                              loss_scale):
    """The deeper run_test.sh crosses (VERDICT r3 #5): fused-adam ×
    keep-batchnorm × static/dynamic scale, asserted EXACT between the
    fused (pallas) and reference (jnp) kernel paths on a BN workload —
    the combos the reference swept twice-installed but round 3's
    equality matrix didn't cover."""
    kw = dict(opt_level=opt_level, loss_scale=loss_scale,
              keep_batchnorm_fp32=keep_bn, fused_adam=True, with_bn=True)
    ref = run_workload(kernels="jnp", **kw)
    fused = run_workload(kernels="pallas", **kw)
    assert ref["fingerprint"] == fused["fingerprint"], (
        digest_name("jnp", opt_level, loss_scale, keep_bn, True),
        ref["losses"], fused["losses"])


@pytest.mark.parametrize("keep_bn", [True, False])
@pytest.mark.parametrize("opt_level", ["O2", "O3"])
def test_keep_batchnorm_cross_product(opt_level, keep_bn):
    """BN workload across the keep_batchnorm_fp32 axis (run_test.sh's
    third loop variable)."""
    got = run_workload(opt_level=opt_level, keep_batchnorm_fp32=keep_bn,
                       with_bn=True)
    ref = run_workload(opt_level="O0", with_bn=True)
    np.testing.assert_allclose(got["losses"], ref["losses"],
                               rtol=0.15, atol=0.1)


def test_overflow_injection_skips_and_recovers():
    """Fault-injection axis: an inf at iteration 2 must trip the scaler
    (skip + halve) under dynamic scaling, and training must recover."""
    d = run_workload(opt_level="O2", loss_scale="dynamic", inject_inf_at=2,
                     steps=6)
    assert d["overflows"][2] is True
    assert not any(d["overflows"][:2]) and not any(d["overflows"][3:])
    # scale halved at the overflow step
    assert d["scales"][2] == d["scales"][1] / 2
    assert d["losses"][-1] < d["losses"][0]


def test_static_scale_unchanged_by_overflow():
    """Static loss scale: overflow skips the step but never rescales
    (reference LossScaler with dynamic=False, scaler.py:46-51)."""
    d = run_workload(opt_level="O2", loss_scale=128.0, inject_inf_at=2)
    assert d["overflows"][2] is True
    assert all(s == 128.0 for s in d["scales"])
