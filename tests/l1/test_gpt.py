"""GPT causal-LM tests: the long-context / sequence-parallel workload.

Beyond the reference (SURVEY.md section 5.7: apex has no long-context
story); checks causality, rope position handling under sequence sharding,
scan/remat equivalence, ring-attention equivalence on the virtual mesh,
and an amp-O2 training run.
"""

import dataclasses as dc

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models import GPTModel, gpt_tiny, lm_loss
from apex_tpu.optimizers import FusedAdam
from apex_tpu.utils.jax_compat import shard_map

B, L = 2, 32


def data(vocab):
    rng = np.random.RandomState(0)
    return jnp.asarray(rng.randint(0, vocab, (B, L)))


class TestGPT:
    def setup_method(self, _):
        self.cfg = gpt_tiny()
        self.model = GPTModel(self.cfg)
        self.ids = data(self.cfg.vocab_size)
        self.vars = self.model.init(jax.random.PRNGKey(0), self.ids)

    def test_forward_shape(self):
        logits = self.model.apply(self.vars, self.ids)
        assert logits.shape == (B, L, self.cfg.vocab_size)
        assert bool(jnp.isfinite(logits).all())

    def test_causality(self):
        """Changing a future token must not change earlier logits."""
        logits = self.model.apply(self.vars, self.ids)
        ids2 = self.ids.at[:, L // 2:].set(
            (self.ids[:, L // 2:] + 1) % self.cfg.vocab_size)
        logits2 = self.model.apply(self.vars, ids2)
        np.testing.assert_allclose(
            np.asarray(logits[:, :L // 2]),
            np.asarray(logits2[:, :L // 2]), rtol=1e-5, atol=1e-5)
        assert not np.allclose(np.asarray(logits[:, -1]),
                               np.asarray(logits2[:, -1]))

    def test_scan_and_remat_match_loop(self):
        logits = self.model.apply(self.vars, self.ids)
        p = dict(self.vars["params"])
        blocks = [p.pop(f"block_{i}") for i in range(self.cfg.num_layers)]
        p["layers"] = {"block": jax.tree.map(
            lambda *xs: jnp.stack(xs), *blocks)}
        stacked = {"params": p}
        for remat in (False, True):
            cfg = dc.replace(self.cfg, scan_layers=True, remat=remat)
            got = GPTModel(cfg).apply(stacked, self.ids)
            np.testing.assert_allclose(np.asarray(got), np.asarray(logits),
                                       rtol=1e-4, atol=1e-4)

    def test_sequence_parallel_matches_local(self):
        """Ring attention over a ("seq",) mesh with global rope positions
        reproduces the single-device logits."""
        sp = 4
        devs = jax.devices()[:sp]
        if len(devs) < sp:
            pytest.skip("needs 4 devices")
        mesh = Mesh(np.array(devs), ("seq",))
        cfg_sp = dc.replace(self.cfg, seq_axis_name="seq")
        model_sp = GPTModel(cfg_sp)
        local = self.model.apply(self.vars, self.ids)

        def fwd(v, ids_shard, pos_shard):
            return model_sp.apply(v, ids_shard, positions=pos_shard)

        positions = jnp.broadcast_to(jnp.arange(L)[None, :], (B, L))
        sharded = shard_map(
            fwd, mesh=mesh,
            in_specs=(P(), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"))(self.vars, self.ids, positions)
        np.testing.assert_allclose(np.asarray(sharded), np.asarray(local),
                                   rtol=2e-3, atol=2e-3)

    def test_amp_o2_training_descends(self):
        a = amp.initialize(optimizer=FusedAdam(lr=3e-3), opt_level="O2",
                           verbosity=0)
        state = a.init(self.vars["params"])

        def loss_fn(p, ids):
            logits = self.model.apply({"params": p}, ids)
            return lm_loss(logits[:, :-1], ids[:, 1:])

        step = jax.jit(amp.make_train_step(a, loss_fn))
        losses = []
        for _ in range(8):
            state, m = step(state, self.ids)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_lm_loss_mask(self):
        logits = self.model.apply(self.vars, self.ids)
        full = lm_loss(logits[:, :-1], self.ids[:, 1:])
        mask = jnp.ones((B, L - 1)).at[:, : (L - 1) // 2].set(0.0)
        half = lm_loss(logits[:, :-1], self.ids[:, 1:], mask=mask)
        assert float(full) != float(half)
        assert np.isfinite(float(half))


class TestGPTKernelPathParity:
    """The pallas branch of CausalSelfAttention (split projection +
    flash with in-kernel rope) must reproduce the jnp branch (explicit
    apply_rope + attention dispatcher) — same params, same logits.
    This pins the fused-rope wiring: q/k reach the kernel UNROTATED and
    the rotation happens on VMEM blocks (round-4 fast path)."""

    @pytest.mark.parametrize("num_heads,label", [(4, "narrow-16"),
                                                 (1, "wide-64")])
    def test_pallas_matches_jnp(self, monkeypatch, num_heads, label):
        cfg = dc.replace(gpt_tiny(), num_heads=num_heads)
        model = GPTModel(cfg)
        ids = data(cfg.vocab_size)

        monkeypatch.setenv("APEX_TPU_KERNELS", "jnp")
        variables = model.init(jax.random.PRNGKey(0), ids)
        logits_jnp = model.apply(variables, ids)

        monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")
        logits_pl = model.apply(variables, ids)
        np.testing.assert_allclose(
            np.asarray(logits_pl, np.float32),
            np.asarray(logits_jnp, np.float32),
            rtol=5e-2, atol=5e-2, err_msg=label)

        # gradients through the fused-rope custom VJP agree too
        def loss(v):
            lg = model.apply(v, ids)
            return lm_loss(lg[:, :-1], ids[:, 1:])

        monkeypatch.setenv("APEX_TPU_KERNELS", "jnp")
        g_jnp = jax.grad(loss)(variables)
        monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")
        g_pl = jax.grad(loss)(variables)
        for a, b in zip(jax.tree.leaves(g_pl), jax.tree.leaves(g_jnp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-2, atol=5e-2,
                                       err_msg=label)


def test_tpu_head_geometry_same_params():
    """The TPU-native config factories change only the head split:
    head_dim 128 (full MXU lane width) at an identical parameter count
    to the conventional shapes — the claim behind gpt_small_tpu /
    gpt_medium_tpu / bert_large_tpu (docs/source/attention.rst)."""
    from apex_tpu.models.bert import (
        BertForPreTraining, bert_large, bert_large_tpu)
    from apex_tpu.models.gpt import gpt_medium_tpu, gpt_small, gpt_small_tpu

    def n_params(init_fn):
        shapes = jax.eval_shape(init_fn)["params"]
        return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(shapes))

    def count(model_cls, cfg):
        m = model_cls(cfg)
        return n_params(lambda: m.init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32)))

    for model_cls, conv, tpu in (
            (GPTModel, gpt_small(), gpt_small_tpu()),
            (BertForPreTraining, bert_large(), bert_large_tpu())):
        assert tpu.hidden_size // tpu.num_heads == 128
        assert count(model_cls, conv) == count(model_cls, tpu)
    med = gpt_medium_tpu()
    assert med.hidden_size // med.num_heads == 128
