"""Unit tests for the imagenet example's eval-path helpers (reference
``main_amp.py:439-489`` ``validate``/``accuracy`` and ``:462-478``
``adjust_learning_rate``), imported from the example file directly."""

import importlib.util
import sys
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[2]


@pytest.fixture(scope="module")
def ex():
    spec = importlib.util.spec_from_file_location(
        "imagenet_main_amp", REPO / "examples" / "imagenet_main_amp.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_accuracy_topk(ex):
    logits = jnp.asarray([
        [0.1, 0.9, 0.0, 0.0],   # top1=1
        [0.8, 0.1, 0.05, 0.05],  # top1=0
        [0.0, 0.2, 0.3, 0.5],   # top1=3, top2 includes 2
    ])
    target = jnp.asarray([1, 1, 2])
    p1, p2 = ex.accuracy(logits, target, topk=(1, 2))
    # sample0 correct@1; sample1 correct@2 (class 1 is 2nd); sample2
    # correct@2 (class 2 is 2nd)
    np.testing.assert_allclose(float(p1), 100.0 / 3, rtol=1e-6)
    np.testing.assert_allclose(float(p2), 100.0, rtol=1e-6)


def test_lr_schedule_warmup_and_decay(ex):
    base, len_epoch, warm = 0.1, 10, 5
    lr = ex.make_lr_schedule(base, len_epoch, warm)
    # linear warmup: first step tiny, end of warmup = base
    assert float(lr(0)) == pytest.approx(base * 1 / (warm * len_epoch))
    assert float(lr(warm * len_epoch - 1)) == pytest.approx(base, rel=1e-6)
    # reference decay points: factor = epoch//30 (+1 from epoch 80), so
    # epoch 30 -> base/10, 60 -> base/100, 80 -> base/1000
    assert float(lr(30 * len_epoch)) == pytest.approx(base * 0.1, rel=1e-6)
    assert float(lr(60 * len_epoch)) == pytest.approx(base * 0.01, rel=1e-6)
    assert float(lr(80 * len_epoch)) == pytest.approx(base * 1e-3, rel=1e-5)


def test_digits_split_deterministic(ex):
    tx1, ty1, vx1, vy1, nc1 = ex.load_digits(8)
    tx2, ty2, vx2, vy2, nc2 = ex.load_digits(8)
    assert nc1 == nc2 == 10
    assert len(vy1) == 360 and len(ty1) == 1437
    np.testing.assert_array_equal(ty1, ty2)
    np.testing.assert_array_equal(vy1, vy2)
    # train/val are disjoint rows of the same shuffled corpus: identical
    # split across calls (checkpoint resume sees the same data)
    np.testing.assert_array_equal(tx1[0], tx2[0])
    assert tx1.shape[1:] == (8, 8, 3)
    # resize path produces the requested spatial size
    tx3, *_ = ex.load_digits(16)
    assert tx3.shape[1:] == (16, 16, 3)
