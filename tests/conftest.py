"""Test config: force a 16-device CPU platform before jax initializes.

This is the test strategy SURVEY.md §4.3 prescribes: every collective
component gets a multi-device test runnable without TPU hardware via
``--xla_force_host_platform_device_count`` (strictly better than the
reference, which could only test distributed paths on a multi-GPU rig).
16 devices (was 8) carries the disaggregated-serving fleet topology —
1 prefill slice + decode replicas on disjoint slices at the c16 bench
shape — while every older multi-device test keeps slicing its first 8.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=16").strip()

import jax  # noqa: E402  (import after env setup)

# The environment's sitecustomize pins jax_platforms to the TPU plugin;
# override at the config level (env vars are ignored) so tests run on the
# virtual 8-device CPU platform.  Set APEX_TPU_TEST_PLATFORM to the hardware
# platform's plugin name to run the suite on real chips instead — validates
# the Pallas kernels compiled by Mosaic rather than in interpret mode (e.g.
# "tpu", or "axon" under the tunnel where the chip registers as an
# experimental platform; multi-device tests will fail where they need >1
# chip).
jax.config.update("jax_platforms",
                  os.environ.get("APEX_TPU_TEST_PLATFORM", "cpu"))
jax.config.update("jax_threefry_partitionable", True)
