# Sphinx configuration for the apex_tpu documentation.
#
# Role parity with the reference's docs/source/conf.py (a sphinx-quickstart
# autodoc setup pointing at the package root); written for this tree rather
# than copied.  Build with ``make docs`` at the repo root or
# ``sphinx-build -W docs/source docs/build``.

import os
import sys

# repo root on sys.path so autodoc can import apex_tpu without an install
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

project = "apex_tpu"
copyright = "2026, apex_tpu authors"
author = "apex_tpu authors"
release = "0.1"

extensions = [
    "sphinx.ext.autodoc",
    "sphinx.ext.autosummary",
    "sphinx.ext.napoleon",       # google/numpy docstring styles
    "sphinx.ext.viewcode",
    "sphinx.ext.intersphinx",
]

# zero-egress environments: resolve intersphinx targets only if an
# inventory is available locally (none by default)
intersphinx_mapping = {}

templates_path = []
exclude_patterns = []

# jax/flax/optax are heavyweight; autodoc imports the real ones (they are
# installed here).  Mock nothing by default; add names if the doc build
# environment lacks them.
autodoc_mock_imports = []
autodoc_member_order = "bysource"
autosummary_generate = False

napoleon_google_docstring = True
napoleon_numpy_docstring = True

try:  # rtd theme if present, stock alabaster otherwise
    import sphinx_rtd_theme  # noqa: F401
    html_theme = "sphinx_rtd_theme"
except ImportError:
    html_theme = "alabaster"

html_static_path = []

# -W builds: warnings are errors; keep the nitpick list empty so missing
# cross-references surface instead of accumulating
nitpicky = False
