"""LARC — Layer-wise Adaptive Rate Clipping.

Port of ``apex/parallel/LARC.py:6-97``: an optimizer *wrapper* that rescales
each parameter's gradient by an adaptive rate
``trust_coefficient · ‖p‖ / (‖g‖ + weight_decay·‖p‖ + eps)`` before the inner
optimizer runs.  ``clip=True`` caps the ratio at the inner learning rate
(``min(adaptive_lr / lr, 1)``, ``LARC.py:82-86``); ``clip=False`` is pure
scaling mode.  Weight decay is folded into the gradient and zeroed for the
inner step (``LARC.py:88-97``).

Expressed as an optax gradient transformation to be chained *before* the
inner optimizer: ``optax.chain(larc(lr, ...), optax.sgd(lr))``, or use the
:func:`LARC` convenience wrapper.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def larc(learning_rate, trust_coefficient: float = 0.02, clip: bool = True,
         eps: float = 1e-8, weight_decay: float = 0.0
         ) -> optax.GradientTransformation:
    """The gradient-rescaling stage of LARC (``LARC.py:68-97``)."""

    def init(params):
        return optax.EmptyState()

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("larc requires params")
        lr = learning_rate(0) if callable(learning_rate) else learning_rate
        lr = jnp.asarray(lr, jnp.float32)

        def leaf(g, p):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
            g_norm = jnp.sqrt(jnp.sum(jnp.square(g32)))
            adaptive_lr = (trust_coefficient * p_norm
                           / (g_norm + p_norm * weight_decay + eps))
            if clip:
                # Inner optimizer multiplies by lr, so cap the ratio at 1
                # (LARC.py:82-86).
                decayed = jnp.minimum(adaptive_lr / lr, 1.0)
            else:
                # Scaling mode: grad scaled by the raw adaptive rate; the
                # inner lr multiplies on top (LARC.py:87).
                decayed = adaptive_lr
            scaled = (g32 + weight_decay * p32) * decayed
            # Reference applies LARC only where both norms are nonzero,
            # leaving the grad untouched otherwise (LARC.py:78-81).
            out = jnp.where((p_norm > 0) & (g_norm > 0), scaled, g32)
            return out.astype(g.dtype)

        return jax.tree.map(leaf, grads, params), state

    return optax.GradientTransformation(init, update)


def LARC(optimizer: optax.GradientTransformation, learning_rate,
         trust_coefficient: float = 0.02, clip: bool = True,
         eps: float = 1e-8, weight_decay: float = 0.0
         ) -> optax.GradientTransformation:
    """Wrap an inner optimizer with LARC (reference constructor shape,
    ``LARC.py:54-66``)."""
    return optax.chain(
        larc(learning_rate, trust_coefficient=trust_coefficient, clip=clip,
             eps=eps, weight_decay=weight_decay),
        optimizer)
