"""apex_tpu.optimizers — fused optimizers.

Reference surface: ``apex/optimizers/__init__.py:1-2`` exports ``FusedAdam``
and ``FP16_Optimizer``; this package adds ``FusedLAMB`` (the driver the
reference snapshot ships kernels for but never wrote — SURVEY.md §0) and
``LARC`` (which the reference keeps in ``apex.parallel``; re-exported there
too).
"""

from apex_tpu.optimizers.fp16_optimizer import FlatFP16State, FP16Optimizer
from apex_tpu.optimizers.fused_adam import (
    EPS_MODE_INSIDE,
    EPS_MODE_OUTSIDE,
    FusedAdam,
    FusedAdamState,
    adam_step,
    fused_adam,
)
from apex_tpu.optimizers.fused_lamb import FusedLAMB, FusedLAMBState, fused_lamb
from apex_tpu.optimizers.larc import LARC, larc

# Reference-spelling alias (apex.optimizers.FP16_Optimizer).
FP16_Optimizer = FP16Optimizer

__all__ = [
    "FusedAdam", "fused_adam", "FusedAdamState", "adam_step",
    "EPS_MODE_INSIDE", "EPS_MODE_OUTSIDE",
    "FusedLAMB", "fused_lamb", "FusedLAMBState",
    "FP16Optimizer", "FP16_Optimizer", "FlatFP16State",
    "LARC", "larc",
]
