"""FusedAdam — Adam with fused descale / moment update / param update.

Port of ``apex/optimizers/fused_adam.py:5-147`` + the kernel
``csrc/fused_adam_cuda_kernel.cu:20-56``: one elementwise pass per parameter
that (1) descales the incoming gradient by a *combined* scale folding loss
scale and global-norm clip, (2) updates the Adam moments, (3) applies the
step with either eps-inside-sqrt or eps-outside-sqrt (``eps_mode``), and
(4) optionally writes back a half-precision param copy (``p_copy``).
Bias correction is precomputed outside the elementwise pass
(``fused_adam_cuda_kernel.cu:83-91``), and weight decay is folded into the
gradient L2-style (``:40-41``).

Two surfaces:

- :func:`adam_step` — the raw fused update on (p, m, v, g) arrays (works on
  leaves or packed flat buffers); the Pallas kernel implements exactly this
  signature on TPU.
- :func:`fused_adam` — an optax ``GradientTransformation`` for drop-in use
  with the rest of the framework (and :class:`apex_tpu.amp.Amp`).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.ops import use_pallas

#: eps added to sqrt(v) ("eps outside sqrt", mode 0 of the CUDA kernel's
#: MODE_0/MODE_1 dispatch, fused_adam_cuda_kernel.cu:29-37).
EPS_MODE_OUTSIDE = 0
#: eps added under the sqrt: denom = sqrt(v + eps).
EPS_MODE_INSIDE = 1


def _adam_math(p32, m32, v32, g32, *, beta1, beta2, eps, step_size, scale,
               weight_decay, eps_mode):
    """The per-element recurrence of ``adam_cuda_kernel`` (``:21-56``)."""
    g32 = g32 / scale
    if weight_decay:
        g32 = g32 + weight_decay * p32
    m32 = beta1 * m32 + (1.0 - beta1) * g32
    v32 = beta2 * v32 + (1.0 - beta2) * g32 * g32
    if eps_mode == EPS_MODE_INSIDE:
        denom = jnp.sqrt(v32 + eps)
    else:
        denom = jnp.sqrt(v32) + eps
    p32 = p32 - step_size * m32 / denom
    return p32, m32, v32


def adam_step(p: jax.Array, m: jax.Array, v: jax.Array, g: jax.Array,
              *, lr, beta1: float, beta2: float, eps: float, step: jax.Array,
              scale=1.0, weight_decay: float = 0.0, eps_mode: int = EPS_MODE_OUTSIDE,
              bias_correction: bool = True, p_copy_dtype=None,
              donate: bool = False):
    """One fused Adam update. All math in fp32 regardless of storage dtype.

    Returns ``(new_p, new_m, new_v[, p_copy])``.  ``step`` is the 1-based step
    count *after* this update (the reference increments state['step'] before
    calling the kernel, ``fused_adam.py:119-133``).

    ``donate=True`` aliases the (p, m, v) buffers in-place on the Pallas
    path (``input_output_aliases``) — ONLY for callers whose inputs are
    dead after the call: under the loss-scale skip-``cond`` the old state
    stays live and XLA's inserted copies invert the win (see the
    adam_kernel module docstring for the on-chip measurement).
    """
    if bias_correction:
        bc1 = 1.0 - jnp.power(beta1, step.astype(jnp.float32))
        bc2 = 1.0 - jnp.power(beta2, step.astype(jnp.float32))
        step_size = lr * jnp.sqrt(bc2) / bc1
    else:
        step_size = jnp.asarray(lr, jnp.float32)

    from apex_tpu.ops.pallas.adam_kernel import ADAM_PAD, packed_adam
    if use_pallas() and p.ndim == 1 and p.size % ADAM_PAD == 0:
        return packed_adam(p, m, v, g, step_size=step_size, beta1=beta1,
                           beta2=beta2, eps=eps, scale=scale,
                           weight_decay=weight_decay, eps_mode=eps_mode,
                           p_copy_dtype=p_copy_dtype, donate=donate)

    p32, m32, v32, g32 = (x.astype(jnp.float32) for x in (p, m, v, g))
    p32, m32, v32 = _adam_math(
        p32, m32, v32, g32, beta1=beta1, beta2=beta2, eps=eps,
        step_size=step_size, scale=jnp.asarray(scale, jnp.float32),
        weight_decay=weight_decay, eps_mode=eps_mode)
    out = (p32.astype(p.dtype), m32.astype(m.dtype), v32.astype(v.dtype))
    if p_copy_dtype is not None:
        out = out + (p32.astype(p_copy_dtype),)
    return out


def _tree_within_capacity(ps) -> bool:
    """Same streaming budget as the LAMB packed path; shared predicate."""
    from apex_tpu.ops.pallas.lamb_kernels import tree_within_packed_capacity
    return tree_within_packed_capacity(ps)


def _packed_tree_update(ps, ms, vs, gs, ss, treedef, step, *, lr, beta1,
                        beta2, eps, scale, weight_decay, eps_mode,
                        bias_correction):
    """Whole-tree fused Adam: ONE kernel pass over the aligned-packed
    (p, m, v, g) quadruple — the reference's one-multi_tensor_apply-launch
    economics (``fused_adam.py:126-147``) — with per-tensor step sizes
    (per-leaf bias correction) through the chunk→tensor SMEM table."""
    import numpy as _np

    from apex_tpu.ops.packing import (
        leaf_sizes, pack_aligned, pack_into, unpack_aligned)
    from apex_tpu.ops.pallas.adam_kernel import packed_adam_tree
    from apex_tpu.ops.pallas.lamb_kernels import grown_chunk

    new_ss = [s + 1 for s in ss]
    steps_f = jnp.stack([s.astype(jnp.float32) for s in new_ss])
    if bias_correction:
        bc1 = 1.0 - jnp.power(beta1, steps_f)
        bc2 = 1.0 - jnp.power(beta2, steps_f)
        step_sizes = lr * jnp.sqrt(bc2) / bc1
    else:
        step_sizes = jnp.broadcast_to(jnp.asarray(lr, jnp.float32),
                                      steps_f.shape)

    chunk = grown_chunk(sum(leaf_sizes(ps)))
    p_flat, meta = pack_aligned([p.astype(jnp.float32) for p in ps], chunk)
    m_flat = pack_into(ms, meta)
    v_flat = pack_into(vs, meta)
    g_flat = pack_into([g.astype(jnp.float32) for g in gs], meta)
    ids = jnp.asarray(_np.array(meta.chunk_ids), jnp.int32)

    new_p_flat, new_m_flat, new_v_flat = packed_adam_tree(
        p_flat, m_flat, v_flat, g_flat, step_sizes[ids], beta1=beta1,
        beta2=beta2, eps=eps, scale=scale, weight_decay=weight_decay,
        eps_mode=eps_mode, chunk_size=chunk)

    deltas = unpack_aligned(new_p_flat - p_flat, meta)
    updates = [d.astype(p.dtype) for d, p in zip(deltas, ps)]
    return (jax.tree.unflatten(treedef, updates),
            FusedAdamState(
                step=step,
                m=jax.tree.unflatten(treedef, unpack_aligned(new_m_flat,
                                                             meta)),
                v=jax.tree.unflatten(treedef, unpack_aligned(new_v_flat,
                                                             meta)),
                leaf_step=jax.tree.unflatten(treedef, new_ss)))


class FusedAdamState(NamedTuple):
    """``step`` is the global schedule counter; ``leaf_step`` holds one
    scalar count per param leaf — the analog of the reference's per-param
    ``state['step']`` (``fused_adam.py:119-125``), so params added
    mid-training (``Amp.add_params``) start their bias correction at 0."""
    step: jax.Array
    m: Any
    v: Any
    leaf_step: Any


def fused_adam(learning_rate=1e-3, beta1: float = 0.9, beta2: float = 0.999,
               eps: float = 1e-8, eps_inside_sqrt: bool = False,
               weight_decay: float = 0.0, bias_correction: bool = True,
               scale=1.0) -> optax.GradientTransformation:
    """optax transformation with FusedAdam semantics
    (``fused_adam.py:5-56`` constructor args; ``amsgrad`` is rejected just as
    the reference raises ``RuntimeError`` for it).

    ``learning_rate`` may be a float or an optax schedule; ``scale`` is the
    *combined* descale divisor (loss scale × clip factor) applied to grads
    inside the fused pass (``fused_adam.py:98-104``).
    """
    eps_mode = EPS_MODE_INSIDE if eps_inside_sqrt else EPS_MODE_OUTSIDE

    def init(params):
        zeros = lambda t: jax.tree.map(
            lambda x: jnp.zeros(x.shape, jnp.float32), t)
        return FusedAdamState(step=jnp.zeros((), jnp.int32),
                              m=zeros(params), v=zeros(params),
                              leaf_step=jax.tree.map(
                                  lambda x: jnp.zeros((), jnp.int32), params))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adam requires params")
        step = state.step + 1
        lr = learning_rate(step) if callable(learning_rate) else learning_rate

        ps, treedef = jax.tree.flatten(params)
        ms = treedef.flatten_up_to(state.m)
        vs = treedef.flatten_up_to(state.v)
        gs = treedef.flatten_up_to(grads)
        ss = treedef.flatten_up_to(state.leaf_step)

        # Whole-tree packed path: opt-in (APEX_TPU_ADAM_PACKED=1).  Unlike
        # CUDA, where multi_tensor_apply wins by amortizing launch
        # overhead, on TPU the per-leaf updates below compile into a
        # handful of XLA fusions with negligible dispatch cost, while
        # packing pays a pack/unpack HBM round-trip every step — keep the
        # persistent-flat representation (FP16Optimizer) for steady-state
        # packing and this path for when profiling shows the fusion count
        # itself is the bottleneck.  Round-3 A/B on one v5e chip settled
        # the default: packed is 13% SLOWER end-to-end on RN50 b256
        # (161 conv-scale leaves to gather/scatter) and -0.8% on
        # GPT-small b8/L2048 — per-leaf stays.
        import os
        if (os.environ.get("APEX_TPU_ADAM_PACKED") == "1" and use_pallas()
                and ps and _tree_within_capacity(ps)):
            return _packed_tree_update(
                ps, ms, vs, gs, ss, treedef, step, lr=lr, beta1=beta1,
                beta2=beta2, eps=eps, scale=scale,
                weight_decay=weight_decay, eps_mode=eps_mode,
                bias_correction=bias_correction)

        updates, new_m, new_v, new_s = [], [], [], []
        for p, m, v, g, s in zip(ps, ms, vs, gs, ss):
            s = s + 1
            new_p, nm, nv = adam_step(
                p, m, v, g, lr=lr, beta1=beta1, beta2=beta2, eps=eps,
                step=s, scale=scale, weight_decay=weight_decay,
                eps_mode=eps_mode, bias_correction=bias_correction)
            updates.append((new_p.astype(jnp.float32)
                            - p.astype(jnp.float32)).astype(p.dtype))
            new_m.append(nm)
            new_v.append(nv)
            new_s.append(s)
        return (jax.tree.unflatten(treedef, updates),
                FusedAdamState(step=step,
                               m=jax.tree.unflatten(treedef, new_m),
                               v=jax.tree.unflatten(treedef, new_v),
                               leaf_step=jax.tree.unflatten(treedef, new_s)))

    return optax.GradientTransformation(init, update)


# Class-style facade mirroring the reference's constructor spelling.
def FusedAdam(lr=1e-3, bias_correction=True, betas=(0.9, 0.999), eps=1e-8,
              eps_inside_sqrt=False, weight_decay=0.0, max_grad_norm=0.0,
              amsgrad=False) -> optax.GradientTransformation:
    """Reference-signature constructor (``fused_adam.py:5-49``)."""
    if amsgrad:
        raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
    if max_grad_norm:
        raise RuntimeError(
            "max_grad_norm is handled by FP16Optimizer's fused grad-norm path "
            "(apex_tpu.optimizers.FP16Optimizer(clip_grad_norm=...)), not here "
            "— matching the reference where FusedAdam receives the combined "
            "scale from its wrapper.")
    return fused_adam(learning_rate=lr, beta1=betas[0], beta2=betas[1],
                      eps=eps, eps_inside_sqrt=eps_inside_sqrt,
                      weight_decay=weight_decay, bias_correction=bias_correction)
