"""Flat-buffer FP16 optimizer driving FusedAdam.

Port of ``apex/optimizers/fp16_optimizer.py:4-274`` — the *fused* wrapper:
per group the reference flattens the fp16 params into one contiguous tensor,
keeps a single flat fp32 master, computes the flat grad norm, folds loss
scale + global-norm clip into one ``combined_scale``, and hands everything to
``fused_adam_cuda.adam`` so unscale + clip + step + fp16 writeback is a
single kernel (``:103-152``).

Here the flat master / m / v live as packed 1-D fp32 buffers in the state;
the per-step work is: flatten incoming half grads (XLA: one concat it
schedules as copies), one fused norm, one fused Adam pass over the flat
buffers (Pallas on TPU), and an unravel of the half ``p_copy`` back to the
param pytree.  Overflow skipping and the optimizer's *own* dynamic scale
(init ``2**16``, factor 2, window 1000 — ``:72-86``) stay on device.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.amp.scaler import LossScaler, LossScaleState
from apex_tpu.optimizers.fused_adam import (
    EPS_MODE_INSIDE,
    EPS_MODE_OUTSIDE,
    adam_step,
)


class FlatFP16State(NamedTuple):
    master: jax.Array   # flat fp32 params
    m: jax.Array        # flat fp32 exp_avg
    v: jax.Array        # flat fp32 exp_avg_sq
    step: jax.Array     # i32
    scaler_state: LossScaleState


class FP16Optimizer:
    """Fused flat-buffer FP16 optimizer (reference
    ``apex/optimizers/fp16_optimizer.py``).

    Construct with the model's initial fp32 params (which fixes the flat
    layout), then drive ``state = opt.init()`` /
    ``state, params_half, info = opt.step(state, model_grads)`` inside jit.
    """

    def __init__(self, init_params: Any, lr: float = 1e-3,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 eps_inside_sqrt: bool = False, weight_decay: float = 0.0,
                 bias_correction: bool = True,
                 static_loss_scale: float = 1.0,
                 dynamic_loss_scale: bool = False,
                 max_grad_norm: float = 0.0,
                 model_dtype=jnp.bfloat16,
                 pad_to: "int | None" = None):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.eps_mode = EPS_MODE_INSIDE if eps_inside_sqrt else EPS_MODE_OUTSIDE
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.max_grad_norm = max_grad_norm
        self.model_dtype = model_dtype
        self.scaler = (LossScaler(loss_scale="dynamic", init_scale=2.0 ** 16,
                                  scale_window=1000)
                       if dynamic_loss_scale
                       else LossScaler(loss_scale=static_loss_scale))

        leaves, self._treedef = jax.tree.flatten(init_params)
        self._shapes = [l.shape for l in leaves]
        self._sizes = [int(np.prod(s)) if s else 1 for s in self._shapes]
        total = sum(self._sizes)
        # Pad so the Pallas fused-Adam path tiles cleanly (reference pads via
        # chunked multi_tensor launches instead).  The default is the
        # (8, 1024) fp32 tile (``packing.streaming_pad``) — the retuned
        # kernel's only remaining alignment; its block geometry handles
        # ragged row counts itself, so no block-multiple padding here.
        from apex_tpu.ops import packing
        self._padded = (packing.round_up(max(total, 1), pad_to) if pad_to
                        else packing.streaming_pad(total))
        self._total = total
        self._init_flat = self._flatten(leaves, jnp.float32)

    # -- layout helpers --------------------------------------------------
    def _flatten(self, leaves, dtype) -> jax.Array:
        flat = jnp.concatenate([jnp.ravel(l).astype(dtype) for l in leaves])
        if self._padded != self._total:
            flat = jnp.pad(flat, (0, self._padded - self._total))
        return flat

    def _unravel(self, flat: jax.Array):
        out, off = [], 0
        for shape, size in zip(self._shapes, self._sizes):
            out.append(jax.lax.dynamic_slice_in_dim(flat, off, size)
                       .reshape(shape))
            off += size
        return jax.tree.unflatten(self._treedef, out)

    # -- API -------------------------------------------------------------
    def init(self) -> FlatFP16State:
        z = jnp.zeros((self._padded,), jnp.float32)
        return FlatFP16State(master=self._init_flat, m=z, v=z,
                             step=jnp.zeros((), jnp.int32),
                             scaler_state=self.scaler.init_state())

    def model_params(self, state: FlatFP16State) -> Any:
        """Half view of the flat master, as the original param pytree
        (the reference re-aliases model params as views into the flat buffer,
        ``:57-70``; here the unravel is fused into consumers by XLA)."""
        return self._unravel(state.master.astype(self.model_dtype))

    def step(self, state: FlatFP16State, model_grads: Any
             ) -> Tuple[FlatFP16State, Any, dict]:
        """One fused update from *scaled* half grads (reference ``step``,
        ``:130-172``)."""
        gleaves = self._treedef.flatten_up_to(model_grads)
        flat_g = self._flatten(gleaves, jnp.float32)

        # Flat grad norm in fp32 (reference _compute_grad_norm, :103-128 —
        # but no D2H sync here; overflow stays a device flag).
        sumsq = jnp.sum(jnp.square(flat_g))
        grad_norm = jnp.sqrt(sumsq)
        finite = jnp.isfinite(sumsq)

        scale = state.scaler_state.loss_scale
        combined_scale = scale
        if self.max_grad_norm and self.max_grad_norm > 0:
            # unscaled norm / max_norm > 1 → grow the descale divisor
            # (reference :141-148 folds clipping into combined_scale).
            clip = (grad_norm / scale) / self.max_grad_norm
            combined_scale = jnp.where(clip > 1.0, scale * clip, scale)

        step = state.step + 1
        new_p, new_m, new_v, p_half = adam_step(
            state.master, state.m, state.v, flat_g,
            lr=self.lr, beta1=self.beta1, beta2=self.beta2, eps=self.eps,
            step=step, scale=combined_scale, weight_decay=self.weight_decay,
            eps_mode=self.eps_mode, bias_correction=self.bias_correction,
            p_copy_dtype=self.model_dtype)

        new_sstate, overflow = self.scaler.update(state.scaler_state, finite)
        keep = lambda new, old: jnp.where(overflow, old, new)
        new_state = FlatFP16State(
            master=keep(new_p, state.master),
            m=keep(new_m, state.m),
            v=keep(new_v, state.v),
            step=jnp.where(overflow, state.step, step),
            scaler_state=new_sstate)
        params_half = self._unravel(
            keep(p_half, state.master.astype(self.model_dtype)))
        info = {"overflow": overflow, "loss_scale": new_sstate.loss_scale,
                "grad_norm": grad_norm}
        return new_state, params_half, info

    # -- checkpointing (reference :211-274) ------------------------------
    def state_dict(self, state: FlatFP16State) -> dict:
        return {"master": state.master, "m": state.m, "v": state.v,
                "step": state.step,
                "loss_scale": state.scaler_state.loss_scale,
                "unskipped": state.scaler_state.unskipped}

    def load_state_dict(self, d: dict) -> FlatFP16State:
        return FlatFP16State(
            master=d["master"], m=d["m"], v=d["v"], step=d["step"],
            scaler_state=LossScaleState(
                loss_scale=jnp.asarray(d["loss_scale"], jnp.float32),
                unskipped=jnp.asarray(d["unskipped"], jnp.int32)))
