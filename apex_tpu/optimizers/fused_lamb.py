"""FusedLAMB — layer-wise adaptive large-batch optimizer.

The reference snapshot ships the two LAMB CUDA kernels but **no** Python
driver (SURVEY.md §0: ``csrc/multi_tensor_lamb_stage_1.cu``,
``multi_tensor_lamb_stage_2.cu`` are exported from ``amp_C`` yet
``apex/optimizers/__init__.py:1-2`` never grew a ``FusedLAMB``).  This driver
is authored from the kernel semantics:

- **Stage 1** (``multi_tensor_lamb_stage_1.cu:17-121``): gradients divided by
  the *clipped global norm* factor (global-norm clipping folded into the
  pass) and by the loss scale; Adam moment update; per-tensor
  ``update = m̂ / (sqrt(v̂) + eps) + weight_decay · p`` with bias correction
  computed host-side.
- **Stage 2** (``multi_tensor_lamb_stage_2.cu:18-92``): per-tensor trust
  ratio ``‖p‖ / ‖update‖`` (falling back to 1 when either norm is zero, i.e.
  the plain ``lr`` step), then ``p -= lr · ratio · update``.

Per-tensor norms ride per-leaf fp32 reductions (see
:func:`apex_tpu.ops.multi_tensor.multi_tensor_l2norm` per-tensor note).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu.ops import use_pallas


class FusedLAMBState(NamedTuple):
    """``step`` is the global schedule counter; ``leaf_step`` holds one
    scalar count per param leaf (reference per-param ``state['step']``
    semantics, ``fused_adam.py:119-125``) so params added mid-training
    start their bias correction at 0."""
    step: jax.Array
    m: Any
    v: Any
    leaf_step: Any


def _within_pallas_capacity(ps) -> bool:
    """Larger-than-budget trees take the jnp path instead of failing Mosaic
    compilation; see :func:`tree_within_packed_capacity`."""
    from apex_tpu.ops.pallas.lamb_kernels import tree_within_packed_capacity
    return tree_within_packed_capacity(ps)


def _pallas_lamb_update(gs32, ps, ms, vs, *, lr, beta1, beta2, eps,
                        weight_decay, clip, bc1, bc2):
    """Whole-tree two-stage LAMB via the Pallas kernels
    (:mod:`apex_tpu.ops.pallas.lamb_kernels`).  ``bc1``/``bc2`` are
    per-tensor ``(n_tensors,)`` bias-correction factors (resolved to
    per-chunk tables through ``AlignedMeta.chunk_ids``).  Returns flat
    per-leaf lists ``(deltas, new_m, new_v)``."""
    from apex_tpu.ops.packing import (
        leaf_sizes, pack_aligned, pack_into, unpack_aligned)
    from apex_tpu.ops.pallas.lamb_kernels import (
        grown_chunk, packed_lamb_stage1)

    # Scale the chunk so the SMEM chunk->scalar tables stay bounded (~128 KiB
    # against the ~1 MiB SMEM budget) regardless of model size.  Callers
    # guarantee total <= MAX_CHUNKS * LAMB_CHUNK_MAX so the grown chunk
    # stays within the VMEM budget (see _within_pallas_capacity).
    chunk = grown_chunk(sum(leaf_sizes(ps)))

    g_flat, meta = pack_aligned(gs32, chunk)
    p_flat = pack_into([p.astype(jnp.float32) for p in ps], meta)
    m_flat = pack_into(ms, meta)
    v_flat = pack_into(vs, meta)
    n_chunks = meta.padded // chunk
    ids = jnp.asarray(np.array(meta.chunk_ids), jnp.int32)

    decay = jnp.full((n_chunks,), weight_decay, jnp.float32)
    # Stage 1 with the norm reductions FUSED into the streaming pass
    # (with_norms): the per-chunk ‖p‖²/‖update‖² partials ride SMEM
    # accumulator tables keyed by the chunk→tensor map, so the flat p/u
    # buffers are not re-read between the stages (8N bytes saved vs the
    # round-5 per_tensor_sumsq_from_packed passes — same partials, same
    # segment add, one read earlier).
    u_flat, new_m_flat, new_v_flat, p_sq, u_sq = packed_lamb_stage1(
        g_flat, p_flat, m_flat, v_flat, decay,
        beta1=beta1, beta2=beta2, eps=eps, inv_scale=1.0 / clip,
        bc1=bc1[ids], bc2=bc2[ids], chunk_size=chunk, with_norms=True)

    # Per-tensor ‖p‖ / ‖update‖ between the stages (the per-tensor output
    # of multi_tensor_l2norm feeding lamb stage 2 in the reference).
    n_tensors = len(meta.shapes)
    p_norm = jnp.sqrt(
        jnp.zeros((n_tensors,), jnp.float32).at[ids].add(p_sq))
    u_norm = jnp.sqrt(
        jnp.zeros((n_tensors,), jnp.float32).at[ids].add(u_sq))
    ratio_t = jnp.where((p_norm > 0) & (u_norm > 0),
                        p_norm / jnp.maximum(u_norm, 1e-38), 1.0)
    chunk_ratio = lr * ratio_t[ids]

    # The optax transform needs the DELTA, and stage 2's
    # ``p - ratio*u`` minus ``p`` IS ``-ratio*u`` — so the p read/write
    # (and, with the kernels' in-place aliasing, the full p copy XLA
    # must insert because p stays live for the subtraction) is dead
    # weight: compute the delta straight from the update and the
    # per-chunk trust ratio.  Also avoids the ``(p - r*u) - p``
    # cancellation rounding.  ``packed_lamb_stage2`` remains the
    # reference-parity export (multi_tensor_lamb_stage_2) for callers
    # that materialize new params.
    delta_flat = (-(u_flat.reshape(-1, chunk)
                    * chunk_ratio[:, None])).reshape(-1)
    deltas = unpack_aligned(delta_flat, meta)
    return (deltas,
            unpack_aligned(new_m_flat, meta),
            unpack_aligned(new_v_flat, meta))


def fused_lamb(learning_rate=1e-3, beta1: float = 0.9, beta2: float = 0.999,
               eps: float = 1e-6, weight_decay: float = 0.01,
               max_grad_norm: float = 1.0, bias_correction: bool = True,
               scale=1.0) -> optax.GradientTransformation:
    """optax transformation with the two-stage LAMB semantics above.

    ``max_grad_norm`` is the global-norm clip threshold of stage 1 (pass 0 to
    disable); ``scale`` is the loss-scale divisor like FusedAdam's.
    """

    def init(params):
        zeros = lambda t: jax.tree.map(
            lambda x: jnp.zeros(x.shape, jnp.float32), t)
        return FusedLAMBState(step=jnp.zeros((), jnp.int32),
                              m=zeros(params), v=zeros(params),
                              leaf_step=jax.tree.map(
                                  lambda x: jnp.zeros((), jnp.int32), params))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_lamb requires params")
        step = state.step + 1
        lr = learning_rate(step) if callable(learning_rate) else learning_rate
        lr = jnp.asarray(lr, jnp.float32)

        gs, treedef = jax.tree.flatten(grads)
        ps = treedef.flatten_up_to(params)
        ms = treedef.flatten_up_to(state.m)
        vs = treedef.flatten_up_to(state.v)
        ss = [s + 1 for s in treedef.flatten_up_to(state.leaf_step)]
        new_leaf_step = jax.tree.unflatten(treedef, ss)

        gs32 = [g.astype(jnp.float32) / jnp.asarray(scale, jnp.float32)
                for g in gs]

        # Per-tensor bias correction from the per-leaf step counts.
        steps_f = jnp.stack([s.astype(jnp.float32) for s in ss]) \
            if ss else jnp.zeros((0,), jnp.float32)
        if bias_correction:
            bc1_ = 1.0 - jnp.power(beta1, steps_f)
            bc2_ = 1.0 - jnp.power(beta2, steps_f)
        else:
            bc1_ = bc2_ = jnp.ones_like(steps_f)

        # Stage-1 global-norm clip factor (lamb_stage_1.cu
        # clipped_global_norm); shared by both execution paths (aligned-pack
        # padding is zero, so per-leaf and flat-buffer norms agree).
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in gs32))
        if max_grad_norm and max_grad_norm > 0:
            clip = jnp.maximum(gnorm / max_grad_norm, 1.0)
        else:
            clip = jnp.asarray(1.0, jnp.float32)

        if use_pallas() and gs32 and _within_pallas_capacity(ps):
            deltas, new_ms, new_vs = _pallas_lamb_update(
                gs32, ps, ms, vs, lr=lr, beta1=beta1, beta2=beta2, eps=eps,
                weight_decay=weight_decay, clip=clip, bc1=bc1_, bc2=bc2_)
            updates = [d.astype(p.dtype) for d, p in zip(deltas, ps)]
            return (jax.tree.unflatten(treedef, updates),
                    FusedLAMBState(
                        step=step,
                        m=jax.tree.unflatten(treedef, new_ms),
                        v=jax.tree.unflatten(treedef, new_vs),
                        leaf_step=new_leaf_step))

        updates, new_m, new_v = [], [], []
        for i, (p, m, v, g) in enumerate(zip(ps, ms, vs, gs32)):
            bc1, bc2 = bc1_[i], bc2_[i]
            p32 = p.astype(jnp.float32)
            g = g / clip
            m = beta1 * m + (1.0 - beta1) * g
            v = beta2 * v + (1.0 - beta2) * g * g
            m_hat = m / bc1
            v_hat = v / bc2
            upd = m_hat / (jnp.sqrt(v_hat) + eps) + weight_decay * p32
            # Stage 2: per-tensor trust ratio.
            p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
            u_norm = jnp.sqrt(jnp.sum(jnp.square(upd)))
            ratio = jnp.where((p_norm > 0) & (u_norm > 0),
                              p_norm / jnp.maximum(u_norm, 1e-38), 1.0)
            updates.append((-lr * ratio * upd).astype(p.dtype))
            new_m.append(m)
            new_v.append(v)

        return (jax.tree.unflatten(treedef, updates),
                FusedLAMBState(step=step,
                               m=jax.tree.unflatten(treedef, new_m),
                               v=jax.tree.unflatten(treedef, new_v),
                               leaf_step=new_leaf_step))

    return optax.GradientTransformation(init, update)


def FusedLAMB(lr=1e-3, bias_correction=True, betas=(0.9, 0.999), eps=1e-6,
              weight_decay=0.01, max_grad_norm=1.0) -> optax.GradientTransformation:
    """Constructor spelled like FusedAdam's (the driver the reference never
    wrote; BASELINE config 4 requires it)."""
    return fused_lamb(learning_rate=lr, beta1=betas[0], beta2=betas[1],
                      eps=eps, weight_decay=weight_decay,
                      max_grad_norm=max_grad_norm,
                      bias_correction=bias_correction)
