"""Pallas TPU kernels for FusedLayerNorm forward/backward.

TPU-native equivalent of ``csrc/layer_norm_cuda_kernel.cu``:

- forward (``cuApplyLayerNorm``, ``:279-324``): per-row (μ, 1/σ) in fp32 —
  the Welford/Chan warp dance collapses to a VPU row reduction — then the
  elementwise normalize + affine, saving (mean, invvar) as residuals exactly
  like the CUDA host side (``layer_norm_cuda.cpp:132,154``).
- backward: the CUDA version splits γ/β grads into a two-stage reduction
  (``cuComputePartGradGammaBeta``/``cuComputeGradGammaBeta``, ``:404-522``)
  plus ``cuComputeGradInput`` (``:523-640``).  Here one kernel computes
  ``dx`` per row-block and *accumulates* ``dγ``/``dβ`` partials across the
  sequential TPU grid into a single output tile — the grid itself is the
  second reduction stage.

Rows are padded to a block multiple in the wrapper (padded rows produce
garbage stats that are sliced away; they cannot NaN because the input pad is
zeros and eps > 0).  Feature dims not divisible by 128 fall back to the jnp
path at the call site (`supported`).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import on_tpu, sds

_BLOCK_ROWS = 128


def supported(n2: int) -> bool:
    return n2 % 128 == 0 and n2 <= 16384


def _fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, inv_ref, *, eps,
                affine):
    x = x_ref[...].astype(jnp.float32)
    mean = x.mean(axis=1, keepdims=True)
    xc = x - mean
    var = (xc * xc).mean(axis=1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    y = xc * inv
    if affine:
        y = y * w_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    mean_ref[...] = mean
    inv_ref[...] = inv


def _bwd_kernel(dy_ref, x_ref, w_ref, mean_ref, inv_ref,
                dx_ref, dw_ref, db_ref, *, affine):
    i = pl.program_id(0)
    dy = dy_ref[...].astype(jnp.float32)
    x = x_ref[...].astype(jnp.float32)
    mean = mean_ref[...]
    inv = inv_ref[...]
    xhat = (x - mean) * inv
    if affine:
        wdy = dy * w_ref[...].astype(jnp.float32)
    else:
        wdy = dy
    # grad_input (cuComputeGradInput): dx = inv*(wdy - mean(wdy) - xhat*mean(wdy*xhat))
    m1 = wdy.mean(axis=1, keepdims=True)
    m2 = (wdy * xhat).mean(axis=1, keepdims=True)
    dx_ref[...] = (inv * (wdy - m1 - xhat * m2)).astype(dx_ref.dtype)
    # γ/β partials accumulated across the sequential grid.
    part_dw = (dy * xhat).sum(axis=0, keepdims=True)
    part_db = dy.sum(axis=0, keepdims=True)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    dw_ref[...] += part_dw
    db_ref[...] += part_db


def _pad_rows(x: jax.Array, rows: int) -> jax.Array:
    pad = (-rows) % _BLOCK_ROWS
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    return x


@functools.partial(jax.jit, static_argnames=("eps", "affine"))
def _forward(x2d, w, b, eps: float, affine: bool):
    n1, n2 = x2d.shape
    xp = _pad_rows(x2d, n1)
    rows = xp.shape[0]
    grid = rows // _BLOCK_ROWS
    w2 = (w if w is not None else jnp.ones((n2,), jnp.float32)).reshape(1, n2)
    b2 = (b if b is not None else jnp.zeros((n2,), jnp.float32)).reshape(1, n2)
    y, mean, inv = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, affine=affine),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, n2), lambda i: (i, 0)),
            pl.BlockSpec((1, n2), lambda i: (0, 0)),
            pl.BlockSpec((1, n2), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_BLOCK_ROWS, n2), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            sds((rows, n2), x2d.dtype, x2d),
            sds((rows, 1), jnp.float32, x2d),
            sds((rows, 1), jnp.float32, x2d),
        ],
        interpret=not on_tpu(),
    )(xp, w2, b2)
    return y[:n1], mean[:n1], inv[:n1]


@functools.partial(jax.jit, static_argnames=("affine",))
def _backward(dy, x2d, w, mean, inv, affine: bool):
    n1, n2 = x2d.shape
    dyp = _pad_rows(dy, n1)
    xp = _pad_rows(x2d, n1)
    meanp = _pad_rows(mean, n1)
    # Pad inv with ones (zeros are fine too: dy pad rows are zero so all
    # partials vanish; ones avoid 0*inf style surprises).
    invp = _pad_rows(inv, n1)
    rows = xp.shape[0]
    grid = rows // _BLOCK_ROWS
    w2 = (w if w is not None else jnp.ones((n2,), jnp.float32)).reshape(1, n2)
    dx, dw, db = pl.pallas_call(
        functools.partial(_bwd_kernel, affine=affine),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, n2), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, n2), lambda i: (i, 0)),
            pl.BlockSpec((1, n2), lambda i: (0, 0)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_BLOCK_ROWS, n2), lambda i: (i, 0)),
            pl.BlockSpec((1, n2), lambda i: (0, 0)),
            pl.BlockSpec((1, n2), lambda i: (0, 0)),
        ],
        out_shape=[
            sds((rows, n2), x2d.dtype, x2d, dy, w),
            sds((1, n2), jnp.float32, x2d, dy, w),
            sds((1, n2), jnp.float32, x2d, dy, w),
        ],
        interpret=not on_tpu(),
    )(dyp, xp, w2, meanp, invp)
    return dx[:n1], dw.reshape(n2), db.reshape(n2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ln_affine(x2d, w, b, eps):
    y, _, _ = _forward(x2d, w, b, eps, affine=True)
    return y


def _ln_affine_fwd(x2d, w, b, eps):
    y, mean, inv = _forward(x2d, w, b, eps, affine=True)
    return y, (x2d, w, mean, inv)


def _ln_affine_bwd(eps, res, dy):
    x2d, w, mean, inv = res
    dx, dw, db = _backward(dy, x2d, w, mean, inv, affine=True)
    return dx, dw.astype(w.dtype), db.astype(w.dtype)


_ln_affine.defvjp(_ln_affine_fwd, _ln_affine_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _ln_plain(x2d, eps):
    y, _, _ = _forward(x2d, None, None, eps, affine=False)
    return y


def _ln_plain_fwd(x2d, eps):
    y, mean, inv = _forward(x2d, None, None, eps, affine=False)
    return y, (x2d, mean, inv)


def _ln_plain_bwd(eps, res, dy):
    x2d, mean, inv = res
    dx, _, _ = _backward(dy, x2d, None, mean, inv, affine=False)
    return (dx,)


_ln_plain.defvjp(_ln_plain_fwd, _ln_plain_bwd)


def layer_norm_fwd_vjp(x2d: jax.Array, w: Optional[jax.Array],
                       b: Optional[jax.Array], eps: float) -> jax.Array:
    """Differentiable fused layer norm on a (n1, n2) view."""
    if w is not None:
        return _ln_affine(x2d, w, b, eps)
    return _ln_plain(x2d, eps)
