"""Pallas TPU kernels for FusedLayerNorm forward/backward.

TPU-native equivalent of ``csrc/layer_norm_cuda_kernel.cu``:

- forward (``cuApplyLayerNorm``, ``:279-324``): per-row (μ, 1/σ) in fp32 —
  the Welford/Chan warp dance collapses to a VPU row reduction — then the
  elementwise normalize + affine, saving (mean, invvar) as residuals exactly
  like the CUDA host side (``layer_norm_cuda.cpp:132,154``).
- backward: the CUDA version splits γ/β grads into a two-stage reduction
  (``cuComputePartGradGammaBeta``/``cuComputeGradGammaBeta``, ``:404-522``)
  plus ``cuComputeGradInput`` (``:523-640``).  Here one kernel computes
  ``dx`` per row-block and *accumulates* ``dγ``/``dβ`` partials across the
  sequential TPU grid into a single output tile — the grid itself is the
  second reduction stage.

Forward geometry (round 6 retune) comes from the shared selector
(:mod:`apex_tpu.ops.pallas.geometry`): per-row statistics make the block
size numerics-free, so the forward streams the largest row block whose
double-buffered working set fits the VMEM budget, with ragged row counts
riding Mosaic's masked last block (no padding pass at all) and the grid
declared ``parallel`` so the pipeliner overlaps DMA with the row
reductions.  The BACKWARD keeps the fixed 128-row blocks: its dγ/dβ
partials accumulate across the sequential grid, so the block size sets
the summation ORDER — part of the bit-exact digest contract the L1
conformance tier pins — and its rows stay padded to the block multiple.
Feature dims not divisible by 128 — or wide enough that the backward's
fixed-row blocks no longer fit double-buffered in the VMEM budget —
fall back to the jnp path at the call site (`supported`).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import on_tpu, sds
from apex_tpu.ops.pallas import geometry

_BLOCK_ROWS = 128


def fwd_block_rows(n1: int, n2: int, x_dtype,
                   block_rows: "int | None" = None) -> int:
    """Forward row block from the shared selector: x in + y out + the
    8 B/row fp32 stats, 16-row multiples (the bf16 sublane floor)."""
    if block_rows:
        return block_rows
    xb = jnp.dtype(x_dtype).itemsize
    return geometry.select_block_rows(
        max(n1, 1), row_bytes=n2 * 2 * xb + 8, multiple_of=16)


def supported(n2: int, dtype=None) -> bool:
    """Whether the fused pallas path handles an ``n2``-wide feature dim.

    With a ``dtype`` the check is budget-aware: the BACKWARD streams
    x/dy/dx blocks of fixed ``_BLOCK_ROWS`` rows (the block size sets
    the dγ/dβ summation order — part of the bit-exact digest contract —
    so it cannot shrink with the feature dim), and a wide-enough row
    no longer fits double-buffered in VMEM.  Those shapes route to the
    jnp fallback instead of shipping a kernel the Pallas sanitizer
    rejects with ``pallas-vmem-overflow`` (fp32 caps near n2=5376 at
    the default budget, bf16 near n2=10752)."""
    if n2 % 128 != 0 or n2 > 16384:
        return False
    if dtype is None:
        return True
    itemsize = jnp.dtype(dtype).itemsize
    streams = 2 * 3 * _BLOCK_ROWS * n2 * itemsize   # x, dy, dx x2 buffers
    tables = 3 * 4 * n2 + 2 * 2 * 4 * _BLOCK_ROWS   # w/dw/db + mean/inv
    return streams + tables <= 2 * geometry.vmem_budget()


def _fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, inv_ref, *, eps,
                affine):
    x = x_ref[...].astype(jnp.float32)
    mean = x.mean(axis=1, keepdims=True)
    xc = x - mean
    var = (xc * xc).mean(axis=1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    y = xc * inv
    if affine:
        y = y * w_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    mean_ref[...] = mean
    inv_ref[...] = inv


def _bwd_kernel(dy_ref, x_ref, w_ref, mean_ref, inv_ref,
                dx_ref, dw_ref, db_ref, *, affine):
    i = pl.program_id(0)
    dy = dy_ref[...].astype(jnp.float32)
    x = x_ref[...].astype(jnp.float32)
    mean = mean_ref[...]
    inv = inv_ref[...]
    xhat = (x - mean) * inv
    if affine:
        wdy = dy * w_ref[...].astype(jnp.float32)
    else:
        wdy = dy
    # grad_input (cuComputeGradInput): dx = inv*(wdy - mean(wdy) - xhat*mean(wdy*xhat))
    m1 = wdy.mean(axis=1, keepdims=True)
    m2 = (wdy * xhat).mean(axis=1, keepdims=True)
    dx_ref[...] = (inv * (wdy - m1 - xhat * m2)).astype(dx_ref.dtype)
    # γ/β partials accumulated across the sequential grid.
    part_dw = (dy * xhat).sum(axis=0, keepdims=True)
    part_db = dy.sum(axis=0, keepdims=True)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    dw_ref[...] += part_dw
    db_ref[...] += part_db


def _pad_rows(x: jax.Array, rows: int) -> jax.Array:
    pad = (-rows) % _BLOCK_ROWS
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    return x


@functools.partial(jax.jit,
                   static_argnames=("eps", "affine", "block_rows"))
def _forward(x2d, w, b, eps: float, affine: bool,
             block_rows: "int | None" = None):
    n1, n2 = x2d.shape
    br = fwd_block_rows(n1, n2, x2d.dtype, block_rows)
    grid = -(-n1 // br)   # ragged tail rides the masked last block
    w2 = (w if w is not None else jnp.ones((n2,), jnp.float32)).reshape(1, n2)
    b2 = (b if b is not None else jnp.zeros((n2,), jnp.float32)).reshape(1, n2)
    y, mean, inv = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, affine=affine),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((br, n2), lambda i: (i, 0)),
            pl.BlockSpec((1, n2), lambda i: (0, 0)),
            pl.BlockSpec((1, n2), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, n2), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            sds((n1, n2), x2d.dtype, x2d),
            sds((n1, 1), jnp.float32, x2d),
            sds((n1, 1), jnp.float32, x2d),
        ],
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel",)),
        interpret=not on_tpu(),
    )(x2d, w2, b2)
    return y, mean, inv


@functools.partial(jax.jit, static_argnames=("affine",))
def _backward(dy, x2d, w, mean, inv, affine: bool):
    n1, n2 = x2d.shape
    dyp = _pad_rows(dy, n1)
    xp = _pad_rows(x2d, n1)
    meanp = _pad_rows(mean, n1)
    # Pad inv with ones (zeros are fine too: dy pad rows are zero so all
    # partials vanish; ones avoid 0*inf style surprises).
    invp = _pad_rows(inv, n1)
    rows = xp.shape[0]
    grid = rows // _BLOCK_ROWS
    w2 = (w if w is not None else jnp.ones((n2,), jnp.float32)).reshape(1, n2)
    dx, dw, db = pl.pallas_call(
        functools.partial(_bwd_kernel, affine=affine),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, n2), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, n2), lambda i: (i, 0)),
            pl.BlockSpec((1, n2), lambda i: (0, 0)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_BLOCK_ROWS, n2), lambda i: (i, 0)),
            pl.BlockSpec((1, n2), lambda i: (0, 0)),
            pl.BlockSpec((1, n2), lambda i: (0, 0)),
        ],
        out_shape=[
            sds((rows, n2), x2d.dtype, x2d, dy, w),
            sds((1, n2), jnp.float32, x2d, dy, w),
            sds((1, n2), jnp.float32, x2d, dy, w),
        ],
        interpret=not on_tpu(),
    )(dyp, xp, w2, meanp, invp)
    return dx[:n1], dw.reshape(n2), db.reshape(n2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ln_affine(x2d, w, b, eps):
    y, _, _ = _forward(x2d, w, b, eps, affine=True)
    return y


def _ln_affine_fwd(x2d, w, b, eps):
    y, mean, inv = _forward(x2d, w, b, eps, affine=True)
    return y, (x2d, w, mean, inv)


def _ln_affine_bwd(eps, res, dy):
    x2d, w, mean, inv = res
    dx, dw, db = _backward(dy, x2d, w, mean, inv, affine=True)
    return dx, dw.astype(w.dtype), db.astype(w.dtype)


_ln_affine.defvjp(_ln_affine_fwd, _ln_affine_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _ln_plain(x2d, eps):
    y, _, _ = _forward(x2d, None, None, eps, affine=False)
    return y


def _ln_plain_fwd(x2d, eps):
    y, mean, inv = _forward(x2d, None, None, eps, affine=False)
    return y, (x2d, mean, inv)


def _ln_plain_bwd(eps, res, dy):
    x2d, mean, inv = res
    dx, _, _ = _backward(dy, x2d, None, mean, inv, affine=False)
    return (dx,)


_ln_plain.defvjp(_ln_plain_fwd, _ln_plain_bwd)


def layer_norm_fwd_vjp(x2d: jax.Array, w: Optional[jax.Array],
                       b: Optional[jax.Array], eps: float) -> jax.Array:
    """Differentiable fused layer norm on a (n1, n2) view."""
    if w is not None:
        return _ln_affine(x2d, w, b, eps)
    return _ln_plain(x2d, eps)
