"""Fused one-pass backward for 1x1 stride-1 NHWC convolutions.

The RN50 profile (``tools/conv_attrib.py``, round 3) shows the early-stage
1x1 convolutions are HBM-bound and their backward runs far below the
memory roofline: XLA emits separate dgrad and wgrad convolutions, reading
the (large) ``dy`` twice, and its small-channel conv kernels leave a
further ~2x on the floor (stage0 1x1 bwd measured 0.09 MFU vs a 0.2
roofline ceiling; stage2/3 equivalents reach 0.4-0.7).

A 1x1 stride-1 conv is a matmul over the flattened ``(B*H*W, C)`` view,
so its whole backward is two matmuls sharing ``dy``:

    dx = dy @ W^T          (M, cout) x (cout, cin)
    dW = x^T @ dy          (cin, M) x (M, cout), accumulated over M tiles

This kernel walks M tiles once, computing the ``dx`` tile and
accumulating ``dW`` in a VMEM fp32 scratch — ``dy`` and ``x`` are each
read exactly once, at memory roofline, independent of channel count.
The forward stays on the XLA conv (already roofline-bound; nothing to
win there).  The reference has no analog (cuDNN fuses neither).

**Measured result (round 3, v5e, RN50 b256): the kernel wins in
isolation but LOSES in the model, so it is OFF by default.**  Each
fused call beats the XLA dgrad+wgrad pair (~1.53 ms vs ~1.7 ms for the
stage0 shapes), but XLA fuses the surrounding elementwise chain (relu
mask, BN-backward pieces, is-finite checks) directly into its conv
operands; routing the backward into a custom call forces those
producers/consumers into separate materialized passes and the whole
step regresses 106 -> 168 ms.  A future variant would have to absorb
the BN-backward epilogue to pay for the fusion boundary.  Kept opt-in
(``APEX_TPU_FUSED_CONV1X1=1``) with numerics pinned by
``tests/l0/test_conv1x1.py``.

Routing: :func:`conv1x1` is invoked from ``apex_tpu.amp.ops`` for
eligible convs (1x1 kernel, stride 1, NHWC, no dilation/groups) when
enabled; non-TPU backends use the plain lax path
(``apex_tpu.ops.use_pallas``).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import on_tpu, sds as _sds, use_pallas

_DN = ("NHWC", "HWIO", "NHWC")
#: M-tile candidates, largest first; the tile must divide B*H*W exactly
#: (no masking pass — remainder shapes fall back to the lax backward).
_TILES = (4096, 2048, 1024, 512, 256, 128, 64, 32, 16, 8)


def enabled() -> bool:
    # default OFF: measured slower in-model (see module docstring)
    return os.environ.get("APEX_TPU_FUSED_CONV1X1", "0") == "1" \
        and use_pallas()


def _pick_tile(m: int, cin: int, cout: int, itemsize: int):
    """Largest tile that divides m AND fits the ~16 MB VMEM budget:
    double-buffered x/dy/dx tiles + the fp32 dW scratch + W (measured
    limit: tile 4096 at cin 512/cout 256 hit 20.75M > 16M on v5e)."""
    fixed = 4 * cin * cout + itemsize * cin * cout
    for t in _TILES:
        tiles = 2 * itemsize * t * (2 * cin + cout)   # x+dx, dy, 2x buf
        if m % t == 0 and tiles + fixed <= 10 * 1024 * 1024:
            return t
    return None


def _bwd_kernel(x_ref, dy_ref, w_ref, dx_ref, dw_ref, dw_scr, *, nm):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw_scr[...] = jnp.zeros_like(dw_scr)

    dy = dy_ref[...]                                   # (tm, cout)
    # dx tile: dy @ W^T — contraction over cout (the big channel dim for
    # the expensive early-stage expansions), fp32 accumulation on the MXU
    dx_ref[...] = lax.dot_general(
        dy, w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dx_ref.dtype)
    # dW accumulation: x^T @ dy over the tile's M rows
    dw_scr[...] += lax.dot_general(
        x_ref[...], dy, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # (cin, cout)

    @pl.when(i == nm - 1)
    def _emit():
        dw_ref[...] = dw_scr[...].astype(dw_ref.dtype)


@functools.partial(jax.jit, static_argnames=("tile",))
def _bwd_fused(xm, dym, w2, *, tile):
    m, cin = xm.shape
    cout = dym.shape[1]
    nm = m // tile
    dx, dw = pl.pallas_call(
        functools.partial(_bwd_kernel, nm=nm),
        grid=(nm,),
        in_specs=[
            pl.BlockSpec((tile, cin), lambda i: (i, 0)),
            pl.BlockSpec((tile, cout), lambda i: (i, 0)),
            pl.BlockSpec((cin, cout), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tile, cin), lambda i: (i, 0)),
            pl.BlockSpec((cin, cout), lambda i: (0, 0)),
        ],
        out_shape=[
            _sds((m, cin), xm.dtype, xm, dym),
            _sds((cin, cout), w2.dtype, xm, dym),
        ],
        scratch_shapes=[pltpu.VMEM((cin, cout), jnp.float32)],
        interpret=not on_tpu(),
    )(xm, dym, w2)
    return dx, dw


@jax.custom_vjp
def conv1x1(x, w):
    """1x1 stride-1 NHWC conv: XLA forward, fused Pallas backward.

    ``x``: (B, H, W, cin); ``w``: (1, 1, cin, cout).
    """
    return lax.conv_general_dilated(x, w, (1, 1), "VALID",
                                    dimension_numbers=_DN)


def _fwd_rule(x, w):
    return conv1x1(x, w), (x, w)


def _bwd_rule(saved, dy):
    x, w = saved
    b, h, wd, cin = x.shape
    cout = w.shape[-1]
    m = b * h * wd
    tile = _pick_tile(m, cin, cout, x.dtype.itemsize)
    if tile is None:
        # remainder-shaped inputs: the plain transpose (two lax convs)
        _, vjp = jax.vjp(
            lambda x_, w_: lax.conv_general_dilated(
                x_, w_, (1, 1), "VALID", dimension_numbers=_DN), x, w)
        return vjp(dy)
    dx, dw = _bwd_fused(x.reshape(m, cin), dy.reshape(m, cout),
                        w.reshape(cin, cout), tile=tile)
    return dx.reshape(x.shape), dw.reshape(w.shape)


conv1x1.defvjp(_fwd_rule, _bwd_rule)


def routeable(x, kernel, window_strides, padding, dimension_numbers,
              kwargs) -> bool:
    """Is this conv an eligible 1x1 stride-1 NHWC case?"""
    if not enabled() or kwargs:
        return False
    if getattr(x, "ndim", 0) != 4 or getattr(kernel, "ndim", 0) != 4:
        return False
    if kernel.shape[0] != 1 or kernel.shape[1] != 1:
        return False
    if tuple(window_strides) != (1, 1):
        return False
    # Only explicit NHWC/HWIO/NHWC routes: lax's None default means
    # NCHW/OIHW-ordered operands, which this kernel would silently
    # misinterpret as an NHWC matmul.
    if dimension_numbers is None or tuple(dimension_numbers) != _DN:
        return False
    if x.dtype != kernel.dtype or x.dtype not in (jnp.bfloat16,
                                                  jnp.float32,
                                                  jnp.float16):
        return False
    # SAME == VALID for a 1x1/stride-1 window; explicit zero pads too.
    if isinstance(padding, str):
        return padding in ("SAME", "VALID")
    try:
        return all(tuple(p) == (0, 0) for p in padding)
    except TypeError:
        return False
