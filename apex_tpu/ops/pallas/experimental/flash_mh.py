"""Multi-head BLHD-native Pallas flash attention (no layout transposes).

The original kernel (:mod:`apex_tpu.ops.pallas.flash_attention`) walks a
``(B*H, L, D)`` view, which costs a materialized relayout of q/k/v/o (and
their gradients) at the custom-call boundary — measured ~9% of a GPT-small
step (``copy`` + ``copy-done`` fusions), the round-2 "parked transpose"
item.  This kernel reads the model's native ``(B, L, H*D)`` layout
directly (a free reshape of ``(B, L, H, D)``):

- grid ``(B, q_block, k_block)``; blocks carry ALL heads: ``(1, bq, H*D)``
  — Mosaic-legal (the lane dim is the full fused ``H*D``);
- heads are walked by an unrolled in-kernel loop over 64/128-lane slices;
  per-head online-softmax state lives in ``(H, bq, W)`` VMEM scratch;
- the logsumexp/delta sidecars are ``(B, L, H)`` — exactly the public
  ring-attention convention, no repacking.

The backward is the same one-pass fused scheme as the BHLD kernel (dk/dv
in VMEM scratch over the q walk, dq in per-k-block fp32 partial planes)
with all heads per step.  Above the dq-partials HBM budget it falls back
to the BHLD two-pass kernels (transposes and all) — long-context configs
route through :mod:`apex_tpu.attention.ring` anyway.

**Measured result (round 3, v5e): NOT the production path.**  At
B8/L2048/H12/D64 the per-(b, iq, ik)-step unrolled head loop measures
fwd 2.08-2.49 ms vs 1.56 ms for the BHLD kernel (and fwd+bwd 6.2 vs
4.8): Mosaic keeps every head's fp32 score tile live across the unroll,
so blocks must shrink to fit VMEM, and the 64-lane head slices add
relayout work the BHLD view never pays.  The transpose savings this
kernel was built for turned out cheaper to capture at the model level
(head-major projections + ``flash_attention(layout="bhld")``, +3% on
BERT).  Kept with numerics pinned (``tests/l0/test_flash_mh.py``) as
the starting point for a future Mosaic with cheaper sub-lane slicing.

Head widths: D must be a multiple of 8; D=64 heads sit two-per-128-lane
plane and slice at 64-lane offsets (a Mosaic sublane-shuffle, paid once
per block load, amortized over the k walk).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import on_tpu, sds as _sds
from apex_tpu.ops.pallas.flash_attention import (
    NEG_INF, _causal_dispatch, _causal_mask, _ceil_to, _default_block)

_STATS_W = 128


def _heads(hd: int, d: int) -> int:
    if d <= 0 or hd % d:
        raise ValueError(f"head_dim {d} must divide fused width {hd}")
    return hd // d


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, causal, has_bias, block_q,
                block_k, nk, num_heads, head_dim):
    ik = pl.program_id(2)
    iq = pl.program_id(1)
    H, D = num_heads, head_dim

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q_start = iq * block_q
    k_start = ik * block_k
    live = (not causal) or (k_start <= q_start + block_q - 1)
    straddle = k_start + block_k - 1 > q_start

    def _update(masked):
        q = q_ref[0]                               # (bq, H*D)
        k = k_ref[0]                               # (bk, H*D)
        if masked:
            mask = _causal_mask(block_q, block_k, q_start, k_start)
        for h in range(H):
            sl = slice(h * D, (h + 1) * D)
            s = lax.dot_general(
                q[:, sl], k[:, sl], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)      # (bq, bk)
            if has_bias:
                s = s + bias_ref[0]
            if masked:
                s = jnp.where(mask, s, NEG_INF)
            m_prev = m_scr[h]                      # (bq, W)
            m_cur = jnp.max(s, axis=1, keepdims=True)
            m_new = jnp.maximum(m_prev,
                                jnp.broadcast_to(m_cur, m_prev.shape))
            corr = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new[:, :1])
            if has_bias:
                p = jnp.where(bias_ref[0] > NEG_INF / 2, p, 0.0)
                if masked:
                    p = jnp.where(mask, p, 0.0)
            l_scr[h] = l_scr[h] * corr + jnp.broadcast_to(
                p.sum(axis=1, keepdims=True), m_prev.shape)
            pv = lax.dot_general(
                p.astype(v_ref.dtype), v_ref[0][:, sl],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)      # (bq, D)
            acc_scr[h] = acc_scr[h] * corr[:, :1] + pv
            m_scr[h] = m_new

    _causal_dispatch(causal, live, straddle, _update)

    @pl.when(ik == nk - 1)
    def _emit():
        outs, lses = [], []
        for h in range(H):
            l = l_scr[h][:, :1]                    # (bq, 1)
            safe_l = jnp.where(l == 0.0, 1.0, l)
            outs.append((acc_scr[h] / safe_l).astype(o_ref.dtype))
            lses.append(jnp.where(l == 0.0, NEG_INF,
                                  m_scr[h][:, :1] + jnp.log(safe_l)))
        o_ref[0] = jnp.concatenate(outs, axis=1)
        lse_ref[0] = jnp.concatenate(lses, axis=1)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      bias_ref, dqp_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                      *, causal, has_bias, block_q, block_k, nq,
                      num_heads, head_dim):
    iq = pl.program_id(2)
    ik = pl.program_id(1)
    H, D = num_heads, head_dim

    @pl.when(iq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    q_start = iq * block_q
    k_start = ik * block_k
    live = (not causal) or (k_start <= q_start + block_q - 1)
    straddle = k_start + block_k - 1 > q_start

    def _update(masked):
        q = q_ref[0]
        k = k_ref[0]
        do = do_ref[0]
        if masked:
            mask = _causal_mask(block_q, block_k, q_start, k_start)
        dq_parts = []
        for h in range(H):
            sl = slice(h * D, (h + 1) * D)
            s = lax.dot_general(
                q[:, sl], k[:, sl], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if has_bias:
                s = s + bias_ref[0]
            if masked and not has_bias:
                s = jnp.where(mask, s, NEG_INF)
            lse_col = lse_ref[0][:, h:h + 1]              # (bq, 1)
            p = jnp.exp(s - lse_col)
            if has_bias:
                if masked:
                    p = jnp.where(mask, p, 0.0)
                p = jnp.where(bias_ref[0] > NEG_INF / 2, p, 0.0)
                p = jnp.where(lse_col > NEG_INF / 2, p, 0.0)
            dv_scr[h] += lax.dot_general(
                p.astype(do.dtype), do[:, sl], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)       # (bk, D)
            dp = lax.dot_general(
                do[:, sl], v_ref[0][:, sl], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - delta_ref[0][:, h:h + 1])
            ds_c = ds.astype(q.dtype)
            dk_scr[h] += lax.dot_general(
                ds_c, q[:, sl], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dq_parts.append(lax.dot_general(
                ds_c, k[:, sl], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))      # (bq, D)
        dqp_ref[0, 0] = jnp.concatenate(dq_parts, axis=1)

    def _zero_dead():
        dqp_ref[0, 0] = jnp.zeros_like(dqp_ref[0, 0])

    _causal_dispatch(causal, live, straddle, _update, dead=_zero_dead)

    @pl.when(iq == nq - 1)
    def _emit():
        dk_ref[0] = jnp.concatenate(
            [dk_scr[h].astype(dk_ref.dtype) for h in range(H)], axis=1)
        dv_ref[0] = jnp.concatenate(
            [dv_scr[h].astype(dv_ref.dtype) for h in range(H)], axis=1)


@functools.partial(jax.jit, static_argnames=("causal", "has_bias",
                                             "block_q", "block_k",
                                             "head_dim"))
def _mh_fwd(q3, k3, v3, bias, *, causal, has_bias, block_q, block_k,
            head_dim):
    b, lp, hd = q3.shape
    H, D = _heads(hd, head_dim), head_dim
    nq, nk = lp // block_q, lp // block_k

    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, has_bias=has_bias,
                          block_q=block_q, block_k=block_k, nk=nk,
                          num_heads=H, head_dim=D),
        grid=(b, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, hd), lambda b_, iq, ik: (b_, iq, 0)),
            pl.BlockSpec((1, block_k, hd), lambda b_, iq, ik: (b_, ik, 0)),
            pl.BlockSpec((1, block_k, hd), lambda b_, iq, ik: (b_, ik, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b_, iq, ik: (b_, 0, ik)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, hd), lambda b_, iq, ik: (b_, iq, 0)),
            pl.BlockSpec((1, block_q, H), lambda b_, iq, ik: (b_, iq, 0)),
        ],
        out_shape=[
            _sds((b, lp, hd), q3.dtype, q3),
            _sds((b, lp, H), jnp.float32, q3),
        ],
        scratch_shapes=[
            pltpu.VMEM((H, block_q, _STATS_W), jnp.float32),
            pltpu.VMEM((H, block_q, _STATS_W), jnp.float32),
            pltpu.VMEM((H, block_q, D), jnp.float32),
        ],
        interpret=not on_tpu(),
    )(q3, k3, v3, bias)
    return o, lse


@functools.partial(jax.jit, static_argnames=("causal", "has_bias",
                                             "block_q", "block_k",
                                             "head_dim"))
def _mh_bwd_fused(q3, k3, v3, do3, lse, delta, bias, *, causal, has_bias,
                  block_q, block_k, head_dim):
    b, lp, hd = q3.shape
    H, D = _heads(hd, head_dim), head_dim
    nq, nk = lp // block_q, lp // block_k

    dq_part, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, causal=causal,
                          has_bias=has_bias, block_q=block_q,
                          block_k=block_k, nq=nq, num_heads=H,
                          head_dim=D),
        grid=(b, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, hd), lambda b_, ik, iq: (b_, iq, 0)),
            pl.BlockSpec((1, block_k, hd), lambda b_, ik, iq: (b_, ik, 0)),
            pl.BlockSpec((1, block_k, hd), lambda b_, ik, iq: (b_, ik, 0)),
            pl.BlockSpec((1, block_q, hd), lambda b_, ik, iq: (b_, iq, 0)),
            pl.BlockSpec((1, block_q, H), lambda b_, ik, iq: (b_, iq, 0)),
            pl.BlockSpec((1, block_q, H), lambda b_, ik, iq: (b_, iq, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b_, ik, iq: (b_, 0, ik)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, hd),
                         lambda b_, ik, iq: (ik, b_, iq, 0)),
            pl.BlockSpec((1, block_k, hd), lambda b_, ik, iq: (b_, ik, 0)),
            pl.BlockSpec((1, block_k, hd), lambda b_, ik, iq: (b_, ik, 0)),
        ],
        out_shape=[
            _sds((nk, b, lp, hd), jnp.float32, q3),
            _sds((b, lp, hd), q3.dtype, q3),
            _sds((b, lp, hd), q3.dtype, q3),
        ],
        scratch_shapes=[pltpu.VMEM((H, block_k, D), jnp.float32),
                        pltpu.VMEM((H, block_k, D), jnp.float32)],
        interpret=not on_tpu(),
    )(q3, k3, v3, do3, lse, delta, bias)
    return dq_part.sum(axis=0).astype(q3.dtype), dk, dv


def _pad_l(t, lp):
    if t.shape[1] != lp:
        t = jnp.pad(t, ((0, 0), (0, lp - t.shape[1]), (0, 0)))
    return t


def _vmem_fits(block_q, block_k, hd, H, D, itemsize) -> bool:
    """Stats + acc scratch and double-buffered blocks within ~11 MB
    (16 MB scoped limit minus headroom for the transient score tile —
    the bwd's scratch is (H, bk, D) x2 which the max() term covers).

    Budgets INPUT blocks and OUTPUT blocks: the backward's outputs are
    fp32 dq-partials (block_q, hd) plus dk/dv blocks (block_k, hd),
    each double-buffered by the pipeline — omitting them let block
    selection exceed the intended headroom near the cap (ADVICE r3)."""
    scr = 4 * H * block_q * (2 * _STATS_W) \
        + 4 * H * max(block_q, block_k) * D * 2
    blocks = 2 * itemsize * hd * (2 * block_q + 2 * block_k)
    out_blocks = 2 * 4 * block_q * hd \
        + 2 * itemsize * hd * (block_q + 2 * block_k)
    score = 4 * block_q * block_k * 2
    return scr + blocks + out_blocks + score <= 11 * 1024 * 1024


def _mh_default_blocks(l, hd, H, D, itemsize):
    """Largest (block_q, block_k) pair fitting the VMEM budget — the
    all-heads blocks scale with H*D, so the BHLD defaults (512/1024)
    blow the 16 MB scoped limit (measured 18 M at 1024 for H12 D64)."""
    lcap = _ceil_to(l, 128)
    for bq, bk in ((512, 512), (256, 512), (256, 256), (128, 512),
                   (128, 256), (128, 128), (8, 128)):
        if bq > lcap or bk > lcap:
            continue
        if _vmem_fits(bq, bk, hd, H, D, itemsize):
            return min(bq, lcap), min(bk, lcap)
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _mh_flash(q3, k3, v3, bias, scale, causal, block_q, block_k, has_bias,
              head_dim):
    (o, lse), _ = _mh_core(q3, k3, v3, bias, scale, causal, block_q,
                           block_k, has_bias, head_dim)
    return o, lse


def _mh_core(q3, k3, v3, bias, scale, causal, block_q, block_k, has_bias,
             head_dim):
    qf = q3 * jnp.asarray(scale, q3.dtype)
    o, lse = _mh_fwd(qf, k3, v3, bias, causal=causal, has_bias=has_bias,
                     block_q=block_q, block_k=block_k, head_dim=head_dim)
    return (o, lse), (qf, k3, v3, o, lse, bias)


def _mh_fwd_rule(q3, k3, v3, bias, scale, causal, block_q, block_k,
                 has_bias, head_dim):
    outs, res = _mh_core(q3, k3, v3, bias, scale, causal, block_q,
                         block_k, has_bias, head_dim)
    return outs, res


def _mh_bwd_rule(scale, causal, block_q, block_k, has_bias, head_dim,
                 saved, cotangents):
    do3, dlse = cotangents
    qf, k3, v3, o, lse, bias = saved
    b, lp, hd = qf.shape
    H, D = _heads(hd, head_dim), head_dim
    # delta_i = sum_D(o * do) - dlse, per head: (B, Lp, H) fp32
    od = (o.astype(jnp.float32).reshape(b, lp, H, D)
          * do3.astype(jnp.float32).reshape(b, lp, H, D)).sum(-1)
    delta = od - dlse.astype(jnp.float32)
    partials_bytes = (lp // block_k) * b * lp * hd * 4
    from apex_tpu.ops.pallas.flash_attention import _fused_bwd_max_bytes
    if partials_bytes <= _fused_bwd_max_bytes():
        dq, dk, dv = _mh_bwd_fused(qf, k3, v3, do3, lse, delta, bias,
                                   causal=causal, has_bias=has_bias,
                                   block_q=block_q, block_k=block_k,
                                   head_dim=D)
    else:
        # Extreme-context fallback: the BHLD two-pass kernels (pays the
        # relayout, but this regime routes through ring attention in
        # practice).
        from apex_tpu.ops.pallas import flash_attention as fa

        def to_bhld(t):
            return jnp.moveaxis(t.reshape(b, lp, H, D), 2, 1).reshape(
                b * H, lp, D)

        lse_w = jnp.broadcast_to(
            jnp.moveaxis(lse, 2, 1).reshape(b * H, lp, 1),
            (b * H, lp, fa._STATS_W))
        dlse_f = jnp.moveaxis(dlse.astype(jnp.float32), 2, 1
                              ).reshape(b * H, lp)
        dqf, dkf, dvf = fa._flash_bwd(
            to_bhld(qf), to_bhld(k3), to_bhld(v3), to_bhld(o),
            to_bhld(do3), lse_w, bias, dlse_f, causal=causal,
            has_bias=has_bias, block_q=block_q, block_k=block_k,
            num_heads=H)

        def from_bhld(t):
            return jnp.moveaxis(t.reshape(b, H, lp, D), 1, 2).reshape(
                b, lp, hd)

        dq, dk, dv = from_bhld(dqf), from_bhld(dkf), from_bhld(dvf)
    dq = dq * jnp.asarray(scale, dq.dtype)
    return dq, dk, dv, jnp.zeros_like(bias)


_mh_flash.defvjp(_mh_fwd_rule, _mh_bwd_rule)


def flash_attention_mh(q, k, v, *, causal=False, kv_mask=None, scale=None,
                       block_q=None, block_k=None, return_lse=False):
    """BLHD-native multi-head flash attention, ``(B, L, H, D)`` API.

    Same contract as :func:`apex_tpu.ops.pallas.flash_attention.
    flash_attention`; the compute never materializes a ``(B*H, L, D)``
    relayout.  Requirements: ``Lq == Lk`` and D a multiple of 8.
    """
    b, l, h, d = q.shape
    if d % 8:
        raise ValueError(f"head_dim {d} must be a multiple of 8 (Mosaic "
                         f"sublane alignment of the in-kernel head slices)")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if block_q is None or block_k is None:
        picked = _mh_default_blocks(l, h * d, h, d, q.dtype.itemsize)
        if picked is None:
            raise ValueError(
                f"flash_attention_mh: no block size fits VMEM for "
                f"H*D={h * d} — use the BHLD kernel for this geometry")
        block_q = picked[0] if block_q is None else block_q
        block_k = picked[1] if block_k is None else block_k
    block_q = min(block_q, _ceil_to(l, 128))
    block_k = min(block_k, _ceil_to(l, 128))
    block_q = max(8, _ceil_to(int(block_q), 8))
    block_k = max(128, _ceil_to(int(block_k), 128))
    lp = _ceil_to(l, math.lcm(int(block_q), int(block_k)))

    q3 = _pad_l(q.reshape(b, l, h * d), lp)
    k3 = _pad_l(k.reshape(b, l, h * d), lp)
    v3 = _pad_l(v.reshape(b, l, h * d), lp)
    padded = lp != l
    has_bias = kv_mask is not None or (padded and not causal)
    if kv_mask is not None:
        # padded key columns must never attend: pad the additive bias
        # with NEG_INF (causal alone can't hide them under a user mask)
        bias = jnp.where(kv_mask, 0.0, NEG_INF).astype(jnp.float32)
        bias = jnp.pad(bias, ((0, 0), (0, lp - l)),
                       constant_values=NEG_INF)[:, None, :]
    elif has_bias:
        pad_mask = jnp.arange(lp) < l
        bias = jnp.broadcast_to(
            jnp.where(pad_mask, 0.0, NEG_INF)[None, None, :],
            (b, 1, lp)).astype(jnp.float32)
    else:
        # untouched placeholder (has_bias=False: kernels never read it)
        bias = jnp.zeros((b, 1, lp), jnp.float32)

    o, lse = _mh_flash(q3, k3, v3, bias, float(scale), bool(causal),
                       int(block_q), int(block_k), has_bias, int(d))
    o = o[:, :l].reshape(b, l, h, d)
    if not return_lse:
        return o
    return o, lse[:, :l]
