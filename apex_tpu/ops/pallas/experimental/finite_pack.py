"""Parked: flat-packed gradient finite check (measured-negative).

The idea: ``apex_tpu.amp.scaler.all_finite`` lowers to ~one
reduce-to-scalar fusion per gradient leaf (~50 for gpt-small), and a
profile of the d=64 train step shows an ``is-finite_reduce_fusion.*`` +
``cond`` bucket worth ~16% of device time (``D64_DECOMPOSE_r05.json``).
This module packs the leaves per dtype into one flat buffer and checks
them with ONE Pallas pass (the read-only half of ``_scale_kernel``'s
in-pass overflow flag, reference ``multi_tensor_scale_kernel.cu:57-71``)
streaming at the ~500 GB/s of ``packed_sumsq``.

Why it loses (same-day v5e A/B, B8 L2048 amp-O2 train step):

- per-leaf baseline:      gpt-small 107.4K tok/s, tpu-heads 138.2K
- flat-packed (this):     gpt-small 105.5K (−1.8%), tpu-heads 133.3K
  (−3.5%)
- no check at all:        tpu-heads 141.1K (+2.1%)

The profiled 16% bucket is an attribution artifact: XLA **fuses the
per-leaf is-finite reduction into the gradient fusions that read the
grads anyway** (the fusion is *named* after its reduce root but carries
the unscale/cast traffic too), so the per-leaf checks' true marginal
cost is only ~2.1% — and the packed path's explicit concat copy
(one extra write+read of the full gradient set that fuses into nothing)
costs more than that.  The remaining ~2.1% could only be recovered by
folding the check into the optimizer's existing flat-pack (which lives
inside the skip-``cond`` whose predicate the check feeds — a chicken-
and-egg restructuring), not by a standalone pass.

Kept numerics-pinned per the experimental-namespace convention; nothing
imports this on a default path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import on_tpu, sds
from apex_tpu.ops.pallas.multi_tensor_kernels import _block, _view2d

#: large blocks keep the pass bandwidth-bound (the LAMB-size lesson)
FINITE_CHUNK = 2048 * 32


def _nonfinite_kernel(x_ref, flag_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        flag_ref[0] = 0

    nonfinite = jnp.logical_not(
        jnp.isfinite(x_ref[...].astype(jnp.float32))).any()

    @pl.when(nonfinite)
    def _flag():
        flag_ref[0] = 1


@functools.partial(jax.jit, static_argnames=("chunk_size",))
def packed_nonfinite(flat: jax.Array,
                     chunk_size: int = FINITE_CHUNK) -> jax.Array:
    """int32 flag: 1 iff ANY element of the flat buffer is inf/nan.
    ``flat`` must be padded to a multiple of ``chunk_size`` (finite
    pad) — a ragged tail would silently go unchecked."""
    n = flat.shape[0]
    assert n % chunk_size == 0, \
        f"pad flat buffers to {chunk_size} (got {n})"
    n_chunks = n // chunk_size
    br = _block(chunk_size)
    flag = pl.pallas_call(
        _nonfinite_kernel,
        grid=(n_chunks,),
        in_specs=[pl.BlockSpec(br, lambda i: (i, 0))],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        out_shape=sds((1,), jnp.int32, flat),
        interpret=not on_tpu(),
    )(_view2d(flat))
    return flag[0]


def all_finite_packed(tree) -> jax.Array:
    """Drop-in for ``amp.scaler.all_finite`` over the packed kernel —
    the parked variant the A/B above measured against."""
    leaves = [jnp.asarray(leaf) for leaf in jax.tree.leaves(tree)
              if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact)]
    if not leaves:
        return jnp.asarray(True)
    by_dtype: dict = {}
    for leaf in leaves:
        # Mosaic has no f16 vector type ("Unsupported type in mosaic
        # dialect: 'f16'", found on-chip); the f32 upcast is exact and
        # preserves inf/nan, so f16 leaves join the f32 group
        if leaf.dtype == jnp.float16:
            leaf = leaf.astype(jnp.float32)
        by_dtype.setdefault(leaf.dtype, []).append(leaf.ravel())
    flags = []
    for flats in by_dtype.values():
        flat = flats[0] if len(flats) == 1 else jnp.concatenate(flats)
        pad = -flat.shape[0] % FINITE_CHUNK
        if pad:
            flat = jnp.pad(flat, (0, pad))   # zero pad: finite
        flags.append(packed_nonfinite(flat, FINITE_CHUNK))
    nonfinite = flags[0] if len(flags) == 1 else jnp.stack(flags).max()
    return nonfinite == 0
