"""apex_tpu.ops.pallas.experimental — measured-negative parked kernels.

Everything in this package is a REAL, numerics-pinned implementation that
was benchmarked in-model on the v5e chip and measured SLOWER than the
production path it was built to replace.  Nothing here is imported by a
default code path; each module's docstring records the measurement and
the mechanism (why it loses), so the negative result is reproducible and
the idea is not silently re-tried.

Current inventory:

- :mod:`.flash_mh` — multi-head BLHD-native flash attention (head-packed
  score tiles).  2.08-2.49 ms vs 1.56 ms for the production BHLD kernel
  at d=64: Mosaic keeps per-head fp32 score tiles live across unrolled
  head loops, so packing heads raises VMEM pressure instead of MXU
  occupancy.
- :mod:`.conv1x1` — fused 1x1-conv backward.  Wins isolated, −58%
  end-to-end in ResNet-50: pulling the conv out of XLA breaks the
  elementwise-into-conv-operand fusions (BN/relu chains) that the
  surrounding graph relies on.
- :mod:`.finite_pack` — flat-packed gradient finite check.  −1.8 to
  −3.5% end-to-end vs the per-leaf ``all_finite``: the profiler's 16%
  "is-finite" bucket is an attribution artifact (XLA fuses the per-leaf
  reduction into gradient fusions that read the grads anyway; true
  marginal cost ~2.1%), and the packed path's concat copy fuses into
  nothing.

Tests for these modules carry the ``experimental`` pytest marker; the
on-chip suite (``tools/onchip_run.py``) keeps ONE numerics pin per
kernel so drift is still caught without spending chip minutes on shelf
inventory.  Production kernels live one package up in
``apex_tpu/ops/pallas/``.
"""
