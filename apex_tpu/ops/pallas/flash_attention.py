"""Pallas TPU flash attention (forward + hand-written backward).

The reference has no attention kernels (a 2019 CNN/RNN-era library), but
this framework treats long-context as first-class: the sequence-parallel
paths (:mod:`apex_tpu.attention.ring`) and the BERT family need an
attention primitive that never materializes the ``(L, L)`` score matrix in
HBM.  This is the classic blockwise online-softmax scheme (Dao et al.,
FlashAttention — pattern, not code) mapped onto the TPU:

- the grid walks ``(batch·heads, q_block, k_block)`` with the k dimension
  innermost; Mosaic's sequential grid makes the k-walk a legal accumulation
  over VMEM scratch (running max ``m``, normalizer ``l``, fp32 ``acc``) —
  the role CUDA shared-memory tiling plays for the GPU kernels;
- score/softmax arithmetic is fp32 regardless of storage dtype (the amp
  blacklist rule for softmax), matmuls ride the MXU with
  ``preferred_element_type=float32``;
- the backward recomputes probability blocks from the saved logsumexp;
  by default one fused pass produces dq, dk and dv together (dk/dv
  accumulate in VMEM scratch, dq lands in per-k-block fp32 partial
  planes summed outside — see ``_fused_bwd_max_bytes``), falling back
  to the classic two-pass scheme (a ``dq`` pass with k innermost, a
  ``dk/dv`` pass with q innermost) when the partials buffer would
  exceed the budget.  No ``(L, L)`` tensor ever hits HBM either way.

Masking: ``kv_mask`` (key padding) arrives as an additive fp32 bias row
``(B, L)`` (0 = attend, ``NEG_INF`` = ignore); causal masking is computed
from block offsets inside the kernel.  Fully-masked query rows produce
``l = 0`` and emit zeros (masked-softmax convention, matching
``apex_tpu.attention``).

Rotary embeddings (``rope=(cos, sin)``) are applied *inside* the kernel:
q/k blocks are rotated in VMEM right before the score matmul, the saved
residuals stay unrotated, and the backward kernels rotate again for the
probability recompute and inverse-rotate the dq/dk accumulators at emit
(the rotation is orthogonal, so ``d(unrotated) = R^T · d(rotated)`` is
the same lane-rotation with the sine negated).  The rotated q/k never
exist in HBM — this is what lets the head-major GPT path stay a pure
reshape end to end (round 3 measured the out-of-kernel rotation
re-materializing the layout, net -3%).  Tables arrive as full-width
``(B, L, D)`` pairs (see :func:`apex_tpu.ops.rope.rope_kernel_tables`)
and are held VMEM-resident per batch when they fit
(``_ROPE_RESIDENT_MAX_BYTES`` per side) or streamed per block above that.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import on_tpu, sds as _sds

_LANES = 128
#: Minor-dim width for the per-row stats tensors (lse, delta) in HBM.
#: Full lane width (128) is what jax's TPU flash kernel uses too: narrower
#: widths save HBM (the stats are per-row scalars) but force Mosaic
#: relayouts in the backward inner loop — measured on BERT-large L=512:
#: width 1 → 10.6 seq/s, width 8 → 16.7, width 128 → 24.9.  The footprint
#: only matters at extreme sequence lengths (2·BH·L·512 bytes).
_STATS_W = _LANES
NEG_INF = -1e30

#: Per-side byte budget (cos + sin whole tables) under which the rope
#: tables ride a single (1, Lp, D) VMEM block per batch — the index map
#: is constant across the inner grid walk, so Mosaic fetches them once
#: per batch instead of re-DMAing a (block, D) pair every step (at
#: d=64/bf16 the per-step table traffic would otherwise double the
#: k-side stream).  Above the budget (long contexts) the tables stream
#: per block; those regimes run 1024-wide blocks where compute dominates
#: the extra DMA.
_ROPE_RESIDENT_MAX_BYTES = 1 << 20


def _ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m


def _rot(x, cos, sin):
    """Rotate a ``(rows, D)`` block in fp32: ``x·cos + rot_half(x)·sin``
    where ``rot_half`` maps lane ``j`` to ``x[(j + D/2) mod D]`` and the
    tables arrive pre-signed (``sin = [-sin, sin]`` — see
    :func:`apex_tpu.ops.rope.rope_kernel_tables`); the inverse rotation
    is the same call with ``-sin``.  The lane rotation is spelled as a
    two-slice concat, VMEM-local in Mosaic."""
    half = x.shape[-1] // 2
    xr = jnp.concatenate([x[:, half:], x[:, :half]], axis=1)
    return (x.astype(jnp.float32) * cos.astype(jnp.float32)
            + xr.astype(jnp.float32) * sin.astype(jnp.float32))


def _rope_nrefs(rope_mode) -> int:
    """How many rope refs a kernel receives for this mode."""
    return {None: 0, "resident": 2, "stream": 4}[rope_mode]


def _rope_q(rope_refs, rope_mode, q_start, block_q):
    """(cos, sin) for the current q block.  Resident mode slices the
    whole-(Lp, D) tables held in VMEM (block starts are multiples of the
    8-sublane granularity, so the dynamic slice is layout-aligned);
    stream mode reads the per-block pipelined refs."""
    if rope_mode == "resident":
        cos_ref, sin_ref = rope_refs
        return (cos_ref[0, pl.ds(q_start, block_q), :],
                sin_ref[0, pl.ds(q_start, block_q), :])
    return rope_refs[0][0], rope_refs[1][0]


def _rope_k(rope_refs, rope_mode, k_start, block_k):
    if rope_mode == "resident":
        cos_ref, sin_ref = rope_refs
        return (cos_ref[0, pl.ds(k_start, block_k), :],
                sin_ref[0, pl.ds(k_start, block_k), :])
    return rope_refs[2][0], rope_refs[3][0]


def _causal_mask(bq, bk, q_start, k_start):
    qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return qpos >= kpos


def _causal_dispatch(causal, live, straddle, update, dead=None):
    """Shared block-dispatch stanza of the four kernels: fully-visible
    live blocks skip the iota/compare/where mask work, only
    diagonal-straddling blocks pay it (~60% of live blocks skip at
    L=2048 with 512 blocks).  ``dead`` optionally runs on non-live
    blocks (the fused backward zeroes its dq partial plane there)."""
    if causal:
        pl.when(jnp.logical_and(live, straddle))(lambda: update(True))
        pl.when(jnp.logical_and(live, jnp.logical_not(straddle)))(
            lambda: update(False))
        if dead is not None:
            pl.when(jnp.logical_not(live))(dead)
    else:
        update(False)


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, *rest, causal, has_bias,
                rope_mode, block_q, block_k, nk):
    rope_refs = rest[:_rope_nrefs(rope_mode)]
    o_ref, lse_ref, m_scr, l_scr, acc_scr = rest[_rope_nrefs(rope_mode):]
    ik = pl.program_id(2)
    iq = pl.program_id(1)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q_start = iq * block_q
    k_start = ik * block_k
    # Whole block strictly above the diagonal contributes nothing.
    live = (not causal) or (k_start <= q_start + block_q - 1)
    # Only diagonal-straddling blocks need the iota/compare/where mask
    # work; fully-below-diagonal blocks are entirely visible.  (~60% of
    # live blocks skip the mask at L=2048 with 512-blocks.)
    straddle = k_start + block_k - 1 > q_start

    def _update(masked):
        # Matmul operands keep their storage dtype: bf16 inputs ride the
        # MXU at full rate, fp32 inputs keep exact fp32 semantics.
        # Accumulation is always fp32 (preferred_element_type), and every
        # softmax/statistics op stays fp32 — the amp fp32-softmax policy
        # is about the *reduction* precision, not MXU operand storage.
        # The softmax scale is folded into q by the caller (one (L, d)
        # pass instead of an (L, L) one here).
        q = q_ref[0]                              # (bq, d)
        k = k_ref[0]                              # (bk, d)
        if rope_mode:
            cq, sq = _rope_q(rope_refs, rope_mode, q_start, block_q)
            ck, sk = _rope_k(rope_refs, rope_mode, k_start, block_k)
            q = _rot(q, cq, sq).astype(q_ref.dtype)
            k = _rot(k, ck, sk).astype(k_ref.dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bq, bk)
        if has_bias:
            s = s + bias_ref[0]                   # (1, bk) broadcast
        if masked:
            mask = _causal_mask(block_q, block_k, q_start, k_start)
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[...]                        # (bq, LANES) replicated
        m_cur = jnp.max(s, axis=1, keepdims=True)  # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        corr = jnp.exp(m_prev - m_new)             # (bq, LANES)
        p = jnp.exp(s - m_new[:, :1])              # (bq, bk)
        # Masked entries need no explicit zeroing here: s == NEG_INF and
        # a finite m_new make exp underflow to exactly 0 (causal rows
        # always see the diagonal, so m_new is finite in every live
        # block).  Only the bias path can produce fully-masked rows
        # (m_new == NEG_INF -> exp(0) == 1), so only it re-zeroes.
        if has_bias:
            p = jnp.where(bias_ref[0] > NEG_INF / 2, p, 0.0)
            if masked:
                p = jnp.where(mask, p, 0.0)
        l_new = l_scr[...] * corr + jnp.broadcast_to(
            p.sum(axis=1, keepdims=True), m_prev.shape)
        # p rides the MXU in the storage dtype (the flash convention: the
        # probabilities are cast to the value dtype for the PV matmul;
        # the fp32 accumulator keeps the reduction exact).
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, d)
        acc_scr[...] = acc_scr[...] * corr[:, :1] + pv
        m_scr[...] = m_new
        l_scr[...] = l_new

    _causal_dispatch(causal, live, straddle, _update)

    @pl.when(ik == nk - 1)
    def _emit():
        l = l_scr[:, :_STATS_W]                     # (bq, W) replicated
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / safe_l[:, :1]).astype(o_ref.dtype)
        lse_ref[0] = jnp.where(l == 0.0, NEG_INF,
                               m_scr[:, :_STATS_W] + jnp.log(safe_l))


def _bwd_p(q, k, bias_row, lse_col, *, masked, has_bias, q_start, k_start,
           block_q, block_k):
    """Recompute the probability block from the saved logsumexp.
    ``q`` is pre-scaled by the caller; ``bias_row``: (1, bk);
    ``lse_col``: (bq, 1).  ``masked`` says this block straddles the
    causal diagonal (fully-visible blocks skip the mask work).  Without
    a bias, masked entries and NEG_INF rows cannot make exp misfire
    (s - lse underflows to 0 for s == NEG_INF, and lse is finite for
    every causal row), so the explicit zeroing wheres exist only on the
    bias path."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if has_bias:
        s = s + bias_row
    if masked and not has_bias:
        s = jnp.where(_causal_mask(block_q, block_k, q_start, k_start),
                      s, NEG_INF)
    p = jnp.exp(s - lse_col)
    if has_bias:
        if masked:
            p = jnp.where(_causal_mask(block_q, block_k, q_start, k_start),
                          p, 0.0)
        p = jnp.where(bias_row > NEG_INF / 2, p, 0.0)
        # lse == NEG_INF marks fully-masked rows: their p must be 0.
        p = jnp.where(lse_col > NEG_INF / 2, p, 0.0)
    return p


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref,
               *rest, causal, has_bias, rope_mode, block_q, block_k, nk):
    rope_refs = rest[:_rope_nrefs(rope_mode)]
    dq_ref, dq_scr = rest[_rope_nrefs(rope_mode):]
    ik = pl.program_id(2)
    iq = pl.program_id(1)

    @pl.when(ik == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    q_start = iq * block_q
    k_start = ik * block_k
    live = (not causal) or (k_start <= q_start + block_q - 1)
    straddle = k_start + block_k - 1 > q_start

    def _update(masked):
        q = q_ref[0]
        k = k_ref[0]
        if rope_mode:
            cq, sq = _rope_q(rope_refs, rope_mode, q_start, block_q)
            ck, sk = _rope_k(rope_refs, rope_mode, k_start, block_k)
            q = _rot(q, cq, sq).astype(q_ref.dtype)
            k = _rot(k, ck, sk).astype(k_ref.dtype)
        p = _bwd_p(q, k, bias_ref[0], lse_ref[0][:, :1], masked=masked,
                   has_bias=has_bias, q_start=q_start, k_start=k_start,
                   block_q=block_q, block_k=block_k)
        do = do_ref[0]
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        # The softmax scale lives in the pre-scaled q (and is applied to
        # dq once, outside the kernel) — no (bq, bk) scale pass here.
        ds = p * (dp - delta_ref[0][:, :1])
        # ds is cast to the storage dtype for its MXU op (flash bwd
        # convention); the fp32 scratch accumulator carries the sum.
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _causal_dispatch(causal, live, straddle, _update)

    @pl.when(ik == nk - 1)
    def _emit():
        dq = dq_scr[...]
        if rope_mode:
            # The accumulated dq is w.r.t. the ROTATED q; chain through
            # the orthogonal rotation: R^T = the same lane-rotation with
            # the sine negated.
            cq, sq = _rope_q(rope_refs, rope_mode, q_start, block_q)
            dq = _rot(dq, cq, -sq)
        dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref,
                *rest, causal, has_bias, rope_mode, block_q, block_k, nq):
    rope_refs = rest[:_rope_nrefs(rope_mode)]
    dk_ref, dv_ref, dk_scr, dv_scr = rest[_rope_nrefs(rope_mode):]
    iq = pl.program_id(2)
    ik = pl.program_id(1)

    @pl.when(iq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    q_start = iq * block_q
    k_start = ik * block_k
    live = (not causal) or (k_start <= q_start + block_q - 1)
    straddle = k_start + block_k - 1 > q_start

    def _update(masked):
        q = q_ref[0]
        k = k_ref[0]
        if rope_mode:
            cq, sq = _rope_q(rope_refs, rope_mode, q_start, block_q)
            ck, sk = _rope_k(rope_refs, rope_mode, k_start, block_k)
            q = _rot(q, cq, sq).astype(q_ref.dtype)
            k = _rot(k, ck, sk).astype(k_ref.dtype)
        p = _bwd_p(q, k, bias_ref[0], lse_ref[0][:, :1], masked=masked,
                   has_bias=has_bias, q_start=q_start, k_start=k_start,
                   block_q=block_q, block_k=block_k)
        do = do_ref[0]
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bk, d)
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dk = ds^T @ q_scaled is exact: d(s)/d(k) carries the scale via
        # the pre-scaled q, so no (bq, bk) scale pass is needed.
        ds = p * (dp - delta_ref[0][:, :1])              # (bq, bk)
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _causal_dispatch(causal, live, straddle, _update)

    @pl.when(iq == nq - 1)
    def _emit():
        dk = dk_scr[...]
        if rope_mode:
            ck, sk = _rope_k(rope_refs, rope_mode, k_start, block_k)
            dk = _rot(dk, ck, -sk)
        dk_ref[0] = dk.astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      bias_ref, *rest, causal, has_bias, rope_mode,
                      block_q, block_k, nq):
    """One-pass backward: p/dp are computed once per block pair and feed
    dq, dk and dv together (the two-pass kernels recompute them, costing
    an extra score matmul + exp per pair).  Grid (bh, ik, iq): dk/dv
    accumulate in VMEM scratch over the inner q walk; dq can't (it's
    indexed by iq), so each k block writes its dq contribution to its
    own fp32 partial plane, summed by XLA outside — O(nk) extra HBM, so
    the caller only picks this kernel when nk is small."""
    rope_refs = rest[:_rope_nrefs(rope_mode)]
    dqp_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest[_rope_nrefs(rope_mode):]
    iq = pl.program_id(2)
    ik = pl.program_id(1)

    @pl.when(iq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    q_start = iq * block_q
    k_start = ik * block_k
    live = (not causal) or (k_start <= q_start + block_q - 1)
    straddle = k_start + block_k - 1 > q_start

    def _update(masked):
        q = q_ref[0]
        k = k_ref[0]
        if rope_mode:
            cq, sq = _rope_q(rope_refs, rope_mode, q_start, block_q)
            ck, sk = _rope_k(rope_refs, rope_mode, k_start, block_k)
            q = _rot(q, cq, sq).astype(q_ref.dtype)
            k = _rot(k, ck, sk).astype(k_ref.dtype)
        p = _bwd_p(q, k, bias_ref[0], lse_ref[0][:, :1], masked=masked,
                   has_bias=has_bias, q_start=q_start, k_start=k_start,
                   block_q=block_q, block_k=block_k)
        do = do_ref[0]
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bk, d)
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])              # (bq, bk)
        ds_c = ds.astype(q.dtype)
        dk_scr[...] += jax.lax.dot_general(
            ds_c, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dqp = jax.lax.dot_general(
            ds_c, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, d) fp32
        if rope_mode:
            # Rotation is linear, so inverse-rotating each partial plane
            # equals inverse-rotating their sum (done outside otherwise).
            cq, sq = _rope_q(rope_refs, rope_mode, q_start, block_q)
            dqp = _rot(dqp, cq, -sq)
        dqp_ref[0, 0] = dqp

    def _zero_dead():
        # Dead blocks still own their dq partial plane slot: zero it.
        dqp_ref[0, 0] = jnp.zeros_like(dqp_ref[0, 0])

    _causal_dispatch(causal, live, straddle, _update, dead=_zero_dead)

    @pl.when(iq == nq - 1)
    def _emit():
        dk = dk_scr[...]
        if rope_mode:
            ck, sk = _rope_k(rope_refs, rope_mode, k_start, block_k)
            dk = _rot(dk, ck, -sk)
        dk_ref[0] = dk.astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _rope_inputs(cos_t, sin_t, rope_mode, h, lp, d, block_q, block_k,
                 q_pos, k_pos):
    """(operands, in_specs) for the rope tables of one pallas_call.
    ``q_pos``/``k_pos`` say which grid axis (1 or 2) carries the q/k
    block index in the calling kernel's grid order.  Resident mode: one
    whole-``(Lp, D)`` block per table with a constant index map — Mosaic
    fetches it once per batch and the kernel slices per block.  Stream
    mode: per-block pipelined (cos_q, sin_q, cos_k, sin_k)."""
    if not rope_mode:
        return [], []
    if rope_mode == "resident":
        spec = pl.BlockSpec((1, lp, d), lambda g0, g1, g2: (g0 // h, 0, 0))
        return [cos_t, sin_t], [spec, spec]

    def _m(pos):
        if pos == 1:
            return lambda g0, g1, g2: (g0 // h, g1, 0)
        return lambda g0, g1, g2: (g0 // h, g2, 0)

    qspec = pl.BlockSpec((1, block_q, d), _m(q_pos))
    kspec = pl.BlockSpec((1, block_k, d), _m(k_pos))
    return [cos_t, sin_t, cos_t, sin_t], [qspec, qspec, kspec, kspec]


def _delta(of, do_f, dlse_f):
    """Per-row backward offset ``sum(o * do) - dlse`` in fp32, broadcast
    to the ``_STATS_W`` stats width: a cotangent on the logsumexp folds
    into the backward as ``ds_ij = p_ij (dp_ij - delta_i + dlse_i)``
    (since dlse_i/ds_ij = p_ij); zero-cotangent callers pay nothing.
    Shared by both backward implementations so the fold stays in one
    place."""
    bh, lp = of.shape[0], of.shape[1]
    delta = jnp.sum(of.astype(jnp.float32) * do_f.astype(jnp.float32),
                    axis=-1, keepdims=True)                    # (bh, lp, 1)
    delta = delta - dlse_f[..., None]
    return jnp.broadcast_to(delta, (bh, lp, _STATS_W))


@functools.partial(jax.jit,
                   static_argnames=("causal", "has_bias", "rope_mode",
                                    "block_q", "block_k", "num_heads"))
def _flash_bwd_fused(qf, kf, vf, of, do_f, lse, bias, cos_t, sin_t, dlse_f,
                     *, causal, has_bias, rope_mode, block_q, block_k,
                     num_heads):
    bh, lp, d = qf.shape
    nq, nk = lp // block_q, lp // block_k
    h = num_heads
    delta = _delta(of, do_f, dlse_f)
    rope_ops, rope_specs = _rope_inputs(cos_t, sin_t, rope_mode, h, lp, d,
                                        block_q, block_k, q_pos=2, k_pos=1)

    dq_part, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, causal=causal,
                          has_bias=has_bias, rope_mode=rope_mode,
                          block_q=block_q, block_k=block_k, nq=nq),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh_, ik, iq: (bh_, iq, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, ik, iq: (bh_, ik, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, ik, iq: (bh_, ik, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh_, ik, iq: (bh_, iq, 0)),
            pl.BlockSpec((1, block_q, _STATS_W),
                         lambda bh_, ik, iq: (bh_, iq, 0)),
            pl.BlockSpec((1, block_q, _STATS_W),
                         lambda bh_, ik, iq: (bh_, iq, 0)),
            pl.BlockSpec((1, 1, block_k),
                         lambda bh_, ik, iq: (bh_ // h, 0, ik)),
        ] + rope_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bh_, ik, iq: (ik, bh_, iq, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, ik, iq: (bh_, ik, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, ik, iq: (bh_, ik, 0)),
        ],
        out_shape=[
            _sds((nk, bh, lp, d), jnp.float32, qf),
            _sds((bh, lp, d), qf.dtype, qf),
            _sds((bh, lp, d), qf.dtype, qf),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=not on_tpu(),
    )(qf, kf, vf, do_f, lse, delta, bias, *rope_ops)
    dq = dq_part.sum(axis=0).astype(qf.dtype)
    return dq, dk, dv


def _fused_bwd_max_bytes() -> int:
    """HBM budget for the fused backward's (groups, BH, L, d) fp32
    dq-partials buffer; the gate is its size, not the block count —
    fused still wins at nk=16 when the buffer fits (gpt-small-tpu
    L=16384: 805 MB partials, +6% step throughput over two-pass).
    Above this budget the extra HBM outweighs the saved recompute and
    the two-pass kernels take over (extreme contexts / big batches).

    ``APEX_TPU_FLASH_FUSED_BWD_MAX_BYTES`` overrides (0 forces the
    two-pass path) so memory-tight configs can steer without
    monkeypatching."""
    import os
    env = os.environ.get("APEX_TPU_FLASH_FUSED_BWD_MAX_BYTES")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            raise ValueError(
                f"APEX_TPU_FLASH_FUSED_BWD_MAX_BYTES must be a plain "
                f"integer byte count, got {env!r}") from None
    return 1 << 30


def _pad_bhld(t, lp, layout="blhd"):
    """(B, L, H, D) or (B, H, L, D) → (BH, Lp, D), zero sequence padding.

    The ``bhld`` layout is the transpose-free fast path: models that
    emit per-head-major q/k/v (the projection dot absorbs the transpose
    for free — measured round 3) reach the kernel with a pure reshape,
    skipping the materialized relayout the ``blhd`` view needs (~6
    copies of (B, L, E) per transformer layer, fwd+bwd)."""
    if layout == "bhld":
        b, h, l, d = t.shape
        t = t.reshape(b * h, l, d)
    else:
        b, l, h, d = t.shape
        t = jnp.moveaxis(t, 2, 1).reshape(b * h, l, d)
    if lp != l:
        t = jnp.pad(t, ((0, 0), (0, lp - l), (0, 0)))
    return t


def _prep(q, k, v, bias, block_q, block_k, layout="blhd"):
    """q/k/v (see ``_pad_bhld``) → padded (BH, Lp, D); pad the additive
    key bias with ``NEG_INF`` so padded keys never attend."""
    l = q.shape[2] if layout == "bhld" else q.shape[1]
    lp = _ceil_to(l, math.lcm(block_q, block_k))
    if bias is not None:
        if lp != l:
            bias = jnp.pad(bias, ((0, 0), (0, lp - l)),
                           constant_values=NEG_INF)
        bias = bias[:, None, :]        # (B, 1, Lp): Mosaic-legal row blocks
    return (_pad_bhld(q, lp, layout), _pad_bhld(k, lp, layout),
            _pad_bhld(v, lp, layout), bias, lp)


def _unprep(t, b, l, h, d, layout="blhd"):
    t = t.reshape(b, h, -1, d)[:, :, :l, :]
    return t if layout == "bhld" else jnp.moveaxis(t, 1, 2)


@functools.partial(jax.jit,
                   static_argnames=("causal", "has_bias", "rope_mode",
                                    "block_q", "block_k", "num_heads"))
def _flash_fwd(qf, kf, vf, bias, cos_t, sin_t, *, causal, has_bias,
               rope_mode, block_q, block_k, num_heads):
    bh, lp, d = qf.shape
    nq, nk = lp // block_q, lp // block_k
    grid = (bh, nq, nk)
    h = num_heads
    rope_ops, rope_specs = _rope_inputs(cos_t, sin_t, rope_mode, h, lp, d,
                                        block_q, block_k, q_pos=1, k_pos=2)

    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, has_bias=has_bias,
                          rope_mode=rope_mode, block_q=block_q,
                          block_k=block_k, nk=nk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh_, iq, ik: (bh_, iq, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, iq, ik: (bh_, ik, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, iq, ik: (bh_, ik, 0)),
            pl.BlockSpec((1, 1, block_k),
                         lambda bh_, iq, ik: (bh_ // h, 0, ik)),
        ] + rope_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh_, iq, ik: (bh_, iq, 0)),
            pl.BlockSpec((1, block_q, _STATS_W),
                         lambda bh_, iq, ik: (bh_, iq, 0)),
        ],
        out_shape=[
            _sds((bh, lp, d), qf.dtype, qf),
            # logsumexp replicated across the stats minor dim (see
            # _STATS_W).
            _sds((bh, lp, _STATS_W), jnp.float32, qf),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=not on_tpu(),
    )(qf, kf, vf, bias, *rope_ops)
    return o, lse


@functools.partial(jax.jit,
                   static_argnames=("causal", "has_bias", "rope_mode",
                                    "block_q", "block_k", "num_heads"))
def _flash_bwd(qf, kf, vf, of, do_f, lse, bias, cos_t, sin_t, dlse_f, *,
               causal, has_bias, rope_mode, block_q, block_k, num_heads):
    bh, lp, d = qf.shape
    nq, nk = lp // block_q, lp // block_k
    h = num_heads
    delta = _delta(of, do_f, dlse_f)

    common_in = [qf, kf, vf, do_f, lse, delta, bias]
    rope_ops_q, rope_specs_q = _rope_inputs(cos_t, sin_t, rope_mode, h, lp,
                                            d, block_q, block_k,
                                            q_pos=1, k_pos=2)
    rope_ops_k, rope_specs_k = _rope_inputs(cos_t, sin_t, rope_mode, h, lp,
                                            d, block_q, block_k,
                                            q_pos=2, k_pos=1)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, has_bias=has_bias,
                          rope_mode=rope_mode, block_q=block_q,
                          block_k=block_k, nk=nk),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh_, iq, ik: (bh_, iq, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, iq, ik: (bh_, ik, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, iq, ik: (bh_, ik, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh_, iq, ik: (bh_, iq, 0)),
            pl.BlockSpec((1, block_q, _STATS_W),
                         lambda bh_, iq, ik: (bh_, iq, 0)),
            pl.BlockSpec((1, block_q, _STATS_W),
                         lambda bh_, iq, ik: (bh_, iq, 0)),
            pl.BlockSpec((1, 1, block_k),
                         lambda bh_, iq, ik: (bh_ // h, 0, ik)),
        ] + rope_specs_q,
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh_, iq, ik: (bh_, iq, 0)),
        out_shape=_sds((bh, lp, d), qf.dtype, qf),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=not on_tpu(),
    )(*common_in, *rope_ops_q)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, has_bias=has_bias,
                          rope_mode=rope_mode, block_q=block_q,
                          block_k=block_k, nq=nq),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh_, ik, iq: (bh_, iq, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, ik, iq: (bh_, ik, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, ik, iq: (bh_, ik, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh_, ik, iq: (bh_, iq, 0)),
            pl.BlockSpec((1, block_q, _STATS_W),
                         lambda bh_, ik, iq: (bh_, iq, 0)),
            pl.BlockSpec((1, block_q, _STATS_W),
                         lambda bh_, ik, iq: (bh_, iq, 0)),
            pl.BlockSpec((1, 1, block_k),
                         lambda bh_, ik, iq: (bh_ // h, 0, ik)),
        ] + rope_specs_k,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh_, ik, iq: (bh_, ik, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, ik, iq: (bh_, ik, 0)),
        ],
        out_shape=[
            _sds((bh, lp, d), qf.dtype, qf),
            _sds((bh, lp, d), qf.dtype, qf),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=not on_tpu(),
    )(*common_in, *rope_ops_k)
    return dq, dk, dv


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(6, 7, 8, 9, 10, 11, 12))
def _flash(q, k, v, bias, cos_t, sin_t, scale, causal, block_q, block_k,
           has_bias, rope_mode, layout):
    (out, lse_pub), _ = _flash_core(q, k, v, bias, cos_t, sin_t, scale,
                                    causal, block_q, block_k, has_bias,
                                    rope_mode, layout)
    return out, lse_pub


def _lse_public(lse, b, l, h):
    """Internal (BH, Lp, W) logsumexp → public (B, L, H) fp32."""
    return jnp.moveaxis(lse[:, :, 0].reshape(b, h, -1)[:, :, :l], 1, 2)


def _flash_core(q, k, v, bias, cos_t, sin_t, scale, causal, block_q,
                block_k, has_bias, rope_mode, layout="blhd"):
    if layout == "bhld":
        b, h, l, d = q.shape
    else:
        b, l, h, d = q.shape
    qf, kf, vf, bias_p, lp = _prep(q, k, v, bias, block_q, block_k, layout)
    # Softmax scale folded into q once ((L, d) elementwise, fused into
    # the prep reshuffle) instead of an (L, L) pass per score block.
    # Scaling commutes with the in-kernel rotation (both linear), so the
    # fold stays valid on the rope path.
    qf = qf * jnp.asarray(scale, qf.dtype)
    if rope_mode and cos_t.shape[1] != lp:
        # Zero-padded tables rotate the (already zero) padded rows to
        # zero; padded keys are excluded by causality or the pad bias
        # either way.
        pad = ((0, 0), (0, lp - cos_t.shape[1]), (0, 0))
        cos_t = jnp.pad(cos_t, pad)
        sin_t = jnp.pad(sin_t, pad)
    of, lse = _flash_fwd(qf, kf, vf, bias_p, cos_t, sin_t, causal=causal,
                         has_bias=has_bias, rope_mode=rope_mode,
                         block_q=block_q, block_k=block_k, num_heads=h)
    return ((_unprep(of, b, l, h, d, layout), _lse_public(lse, b, l, h)),
            (qf, kf, vf, of, lse, bias_p, cos_t, sin_t))


def _flash_fwd_rule(q, k, v, bias, cos_t, sin_t, scale, causal, block_q,
                    block_k, has_bias, rope_mode, layout):
    outs, res = _flash_core(q, k, v, bias, cos_t, sin_t, scale, causal,
                            block_q, block_k, has_bias, rope_mode, layout)
    # The saved tables are padded to Lp; the cotangents must match the
    # caller's (unpadded) table shape, so remember it.
    return outs, (res, q.shape, cos_t.shape)


def _flash_bwd_rule(scale, causal, block_q, block_k, has_bias, rope_mode,
                    layout, saved, cotangents):
    dout, dlse = cotangents
    (qf, kf, vf, of, lse, bias_p, cos_t, sin_t), shape, table_shape = saved
    if layout == "bhld":
        b, h, l, d = shape
    else:
        b, l, h, d = shape
    lp = qf.shape[1]
    do_f = _pad_bhld(dout, lp, layout)
    # A cotangent on the logsumexp folds into the backward as an offset on
    # delta: ds_ij = p_ij (dp_ij - delta_i + dlse_i), since dlse_i/ds_ij =
    # p_ij.  Zero-cotangent callers (plain attention) pay nothing.
    dlse_f = jnp.moveaxis(dlse.astype(jnp.float32), 1, 2).reshape(b * h, l)
    if lp != l:
        dlse_f = jnp.pad(dlse_f, ((0, 0), (0, lp - l)))
    partials_bytes = (lp // block_k) * qf.shape[0] * lp * d * 4
    bwd = (_flash_bwd_fused if partials_bytes <= _fused_bwd_max_bytes()
           else _flash_bwd)
    dqf, dkf, dvf = bwd(qf, kf, vf, of, do_f, lse, bias_p, cos_t, sin_t,
                        dlse_f, causal=causal, has_bias=has_bias,
                        rope_mode=rope_mode, block_q=block_q,
                        block_k=block_k, num_heads=h)
    # The kernels differentiate w.r.t. the pre-scaled q: dk comes out
    # exact (ds^T @ q_scaled), dq needs the one deferred scale.  On the
    # rope path the kernels already inverse-rotated at emit, so dq/dk
    # are w.r.t. the unrotated inputs here.
    dq = _unprep(dqf, b, l, h, d, layout) * jnp.asarray(scale, dqf.dtype)
    dk = _unprep(dkf, b, l, h, d, layout)
    dv = _unprep(dvf, b, l, h, d, layout)
    # The rope tables are position functions (int positions carry no
    # gradient); their zero cotangents DCE under jit.
    return (dq, dk, dv, jnp.zeros((b, l), jnp.float32),
            jnp.zeros(table_shape, cos_t.dtype),
            jnp.zeros(table_shape, sin_t.dtype))


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _jnp_attention(q, k, v, *, causal, kv_mask, scale, return_lse=False):
    """Materializing jnp path with the kernel's exact conventions (fp32
    softmax, masked rows emit zeros) — the cross-attention fallback and
    the interpret-mode stand-in under ``shard_map`` (see
    :func:`flash_attention`)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    visible = jnp.ones((q.shape[0], 1, q.shape[1], k.shape[1]), bool)
    if kv_mask is not None:
        visible = visible & kv_mask[:, None, None, :]
    if causal:
        qpos = jnp.arange(q.shape[1])[:, None]
        kpos = jnp.arange(k.shape[1])[None, :]
        visible = visible & (qpos >= kpos)[None, None]
    s = jnp.where(visible, s, NEG_INF)
    m = s.max(axis=-1, keepdims=True)
    p = jnp.where(visible, jnp.exp(s - m), 0.0)
    l = p.sum(axis=-1, keepdims=True)
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = jnp.einsum("bhqk,bkhd->bqhd", p / safe_l, v.astype(jnp.float32))
    out = out.astype(q.dtype)
    if not return_lse:
        return out
    lse = jnp.where(l[..., 0] == 0.0, NEG_INF,
                    m[..., 0] + jnp.log(safe_l[..., 0]))   # (b, h, lq)
    return out, jnp.moveaxis(lse, 1, 2)


def _default_block(l: int) -> int:
    """Default q/k block edge by sequence length: 512, growing to 1024 at
    L >= 2048 where fewer, larger grid steps measure ~18% faster on-chip
    (per-step overhead and the online-softmax stats updates amortize;
    B8·H12·L2048·d64 fwd 3.2 -> 2.6 ms, fwd+bwd 9.1 -> 7.5 ms; 2048
    blocks fail to compile with the fp32 score tile) — but only when the
    larger block adds no padding: for L not near a multiple of 1024 the
    padded sequence would grow, and the quadratic extra attention work
    erases the per-step win."""
    if l >= 2048 and _ceil_to(l, 1024) == _ceil_to(l, 512):
        return 1024
    return 512


@functools.lru_cache(maxsize=None)
def _warn_block_override(name: str, asked: int, got: int) -> None:
    """Once per distinct (name, asked, got): explicit block sizes are
    silently clamped/rounded to Mosaic tile granularity, which changes
    the blocking a tuner asked for — surface it (ADVICE r2)."""
    import warnings
    warnings.warn(
        f"flash_attention: {name}={asked} adjusted to {got} "
        f"(clamped to the padded sequence length and rounded to Mosaic "
        f"tile granularity: block_q to a multiple of 8, block_k to a "
        f"multiple of 128 — sub-128 k blocks miscompile on TPU)",
        stacklevel=3)


def _varying(x) -> bool:
    try:
        return bool(jax.typeof(x).vma)
    except Exception:
        pass
    # pre-VMA jax has no varying type to ask; any active mapped axis
    # (legacy shard_map / pmap trace) means x MAY be device-varying,
    # which is the same "don't run the pallas interpreter" situation
    # the VMA check routes around (and legacy check_rep has no
    # replication rule for pallas_call at all)
    try:
        from jax._src.core import get_axis_env
        return bool(get_axis_env().axis_sizes)
    except Exception:
        return False


def flash_attention(q, k, v, *, causal=False, kv_mask=None, scale=None,
                    block_q=None, block_k=None, return_lse=False,
                    layout="blhd", rope=None):
    """Blockwise exact attention, ``(B, L, H, D)`` convention.

    ``layout="bhld"`` instead takes/returns ``(B, H, L, D)`` — the
    transpose-free fast path for models whose projections emit
    head-major tensors (the relayout to the kernel's row view becomes a
    pure reshape; output and gradients likewise).  The logsumexp stays
    ``(B, L, H)`` in either layout.

    ``rope=(cos, sin)`` (tables from
    :func:`apex_tpu.ops.rope.rope_tables`, ``(B, L, 1, D/2)`` or
    ``(B, L, D/2)``) applies the rotary embedding to q and k *inside*
    the kernel: pass q/k unrotated, the rotation happens on VMEM blocks
    and the rotated tensors never exist in HBM (gradients are returned
    w.r.t. the unrotated inputs).  The tables themselves are treated as
    **non-differentiable position constants**: their cotangents are
    zero, so a learned-rotary variant differentiating through cos/sin
    would silently get zero table gradients — rotate outside the kernel
    for that case.  Requires self-attention (``Lq == Lk``).  With bf16
    activations the tables are cast to bf16 — the extra table rounding
    is the same class as the bf16 q/k storage itself (the fallback
    paths rotate in fp32 either way).

    Equivalent to the jnp reference path in :mod:`apex_tpu.attention`
    (scores never materialized; fp32 softmax; masked rows emit zeros).
    ``kv_mask``: optional ``(B, Lk)`` bool key mask (True = attend).
    ``block_q``/``block_k`` default by sequence length — 512, growing to
    1024 at L >= 2048 where fewer, larger grid steps measure ~18% faster
    on-chip (per-step overhead amortizes; 2048 blocks exceed VMEM with
    the fp32 score block) — and are clamped to the (padded) length,
    then rounded up to Mosaic tile granularity (``block_q`` to a
    multiple of 8, ``block_k`` to a multiple of 128 — narrower k blocks
    miscompile on hardware).
    Cross-attention (``Lq != Lk``) routes to an equivalent jnp path — the
    blockwise kernel packs q and k/v with one shared sequence length.

    With ``return_lse`` also returns the per-row logsumexp ``(B, L, H)``
    fp32 (``NEG_INF`` for fully-masked rows) — differentiable, so partial
    results can be merged online (ring attention's carry).
    """
    if layout not in ("blhd", "bhld"):
        raise ValueError(f"unknown layout {layout!r}")
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    seq_ax = 2 if layout == "bhld" else 1
    b, l = q.shape[0], q.shape[seq_ax]
    d_head = q.shape[-1]
    if rope is not None and k.shape[seq_ax] != l:
        raise ValueError("rope requires self-attention (Lq == Lk): q and "
                         "k share one position table")
    if k.shape[seq_ax] != l or (not on_tpu() and _varying(q)):
        # Cross-attention (blockwise packing needs one shared length) and
        # interpret-mode-under-shard_map (a VMA propagation limitation in
        # jax's pallas interpreter; compiled Mosaic is unaffected) route
        # to the equivalent jnp math, which speaks (B, L, H, D).
        if k.shape[seq_ax] != l and return_lse:
            raise ValueError("return_lse requires Lq == Lk (kernel path)")
        if layout == "bhld":
            qb, kb, vb = (jnp.moveaxis(t, 1, 2) for t in (q, k, v))
        else:
            qb, kb, vb = q, k, v
        if rope is not None:
            from apex_tpu.ops.rope import apply_rope_tables
            qb, kb = apply_rope_tables(qb, kb, rope)
        out = _jnp_attention(qb, kb, vb, causal=causal, kv_mask=kv_mask,
                             scale=float(scale), return_lse=return_lse)
        if layout == "bhld":
            if return_lse:
                return jnp.moveaxis(out[0], 1, 2), out[1]
            return jnp.moveaxis(out, 1, 2)
        return out
    explicit = (block_q, block_k)
    if block_q is None:
        block_q = _default_block(l)
    if block_k is None:
        block_k = _default_block(l)
    if rope is not None and jnp.dtype(q.dtype).itemsize == 4:
        # fp32 activations + rope tables: the fused backward at
        # 1024-blocks already sits near the 16 MB scoped-VMEM cliff in
        # fp32, and the table blocks push it over (measured: 16.93 MB,
        # +952 KB over the limit, on the O0 L2048 train step).  Cap the
        # *defaulted* blocks at 512; explicit requests stay the
        # caller's choice.
        if explicit[0] is None:
            block_q = min(block_q, 512)
        if explicit[1] is None:
            block_k = min(block_k, 512)
    block_q = min(block_q, _ceil_to(l, 128))
    block_k = min(block_k, _ceil_to(l, 128))
    # Mosaic tile granularity: the score tile is (block_q, block_k), so
    # block_q rides the 8-sublane dim and block_k the 128-lane dim.
    # Sub-lane-width k blocks (block_k < 128) compile but produce wrong
    # numerics on hardware (interpret mode hides it) and would waste the
    # VPU anyway — round both up to legal sizes.
    block_q = max(8, _ceil_to(int(block_q), 8))
    block_k = max(_LANES, _ceil_to(int(block_k), _LANES))
    for name, asked, got in (("block_q", explicit[0], block_q),
                             ("block_k", explicit[1], block_k)):
        if asked is not None and int(asked) != got:
            _warn_block_override(name, int(asked), got)
    if kv_mask is not None:
        bias = jnp.where(kv_mask, 0.0, NEG_INF).astype(jnp.float32)
    else:
        # Placeholder keeping the kernel input list static; with
        # has_bias=False the kernels never read it (no bias add, no
        # zeroing wheres).
        bias = jnp.zeros((b, l), jnp.float32)
    # _prep pads keys with a NEG_INF bias column; that only reaches the
    # kernels on the bias path, so non-causal padded lengths must take
    # it even without a user mask (else zero-padded keys attend and
    # inflate the normalizer).  Causal is safe bias-free: every padded
    # key sits at kpos >= l > qpos for every real row.
    padded = l % math.lcm(int(block_q), int(block_k)) != 0
    has_bias = kv_mask is not None or (padded and not causal)
    rope_mode = None
    cos_t = sin_t = jnp.zeros((), jnp.float32)   # unused placeholder
    if rope is not None:
        from apex_tpu.ops.rope import KernelRopeTables, rope_kernel_tables
        table_dtype = (jnp.bfloat16 if q.dtype == jnp.bfloat16
                       else jnp.float32)
        if isinstance(rope, KernelRopeTables):
            # Prebuilt kernel-format tables: callers with scanned/remat
            # layer bodies construct them once per step so the
            # concat/sign-fold/cast stays out of the compiled layer loop.
            cos_t = rope.cos_full.astype(table_dtype)
            sin_t = rope.sin_signed.astype(table_dtype)
            if cos_t.shape[0] != b:
                cos_t = jnp.broadcast_to(cos_t, (b,) + cos_t.shape[1:])
                sin_t = jnp.broadcast_to(sin_t, (b,) + sin_t.shape[1:])
        else:
            cos_t, sin_t = rope_kernel_tables(rope[0], rope[1], b, l,
                                              d_head, table_dtype)
        lp = _ceil_to(l, math.lcm(int(block_q), int(block_k)))
        per_side = 2 * lp * d_head * cos_t.dtype.itemsize
        rope_mode = ("resident"
                     if per_side <= _ROPE_RESIDENT_MAX_BYTES else "stream")
    out, lse = _flash(q, k, v, bias, cos_t, sin_t, float(scale),
                      bool(causal), int(block_q), int(block_k), has_bias,
                      rope_mode, layout)
    return (out, lse) if return_lse else out
