"""Pallas TPU kernels for the two-stage LAMB update.

TPU-native equivalents of ``csrc/multi_tensor_lamb_stage_1.cu:17-121`` and
``csrc/multi_tensor_lamb_stage_2.cu:18-92``.  The CUDA kernels resolve
per-tensor arguments (weight decay, trust ratio) through the block→tensor
table packed into kernel argument space; here the tensor list is packed
chunk-*aligned* (:func:`apex_tpu.ops.packing.pack_aligned`) so chunks never
straddle tensors, and the per-chunk scalar table sits whole in SMEM —
the direct analog of ``TensorListMetadata``'s block→tensor map living in
kernel argument space.

Stage boundaries mirror the CUDA split: stage 1 is the gradient
descale/clip → Adam moment update → ``update = m̂/(√v̂+ε) + decay·p`` pass;
per-tensor ‖p‖/‖update‖ norms feed stage 2 (the role of
``multi_tensor_l2norm``'s per-tensor output); stage 2 applies
``p ← p − ratio·update`` with the per-tensor trust ratio (lr folded in, with
the plain-lr fallback when either norm is zero).  All arithmetic is fp32.

Memory movement (round 6 retune): one grid step streams
``chunks_per_block`` chunks (shared selector,
:mod:`apex_tpu.ops.pallas.geometry`) instead of a single (8, 128) tile —
the geometry that left the stages at 0.13–0.17 of HBM peak while
mt_axpby's big blocks hit 0.81 on the same chip (KERNELBENCH_r05).  The
chunk sub-blocks are statically unrolled so each keeps its own SMEM
table scalars, and ragged chunk counts ride Mosaic's masked last block
(the scalar tables are padded to the grid so the dead tail indexes real
slots).  Stage 1 optionally FUSES the per-tensor norm reductions into
the streaming pass (``with_norms=True``): per-chunk ‖p‖²/‖update‖²
partials land in SMEM accumulator tables keyed by the existing
chunk→tensor map, saving the two extra full passes
(``per_tensor_sumsq_from_packed`` re-reading p and u, 8N bytes) the
driver paid between the stages.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import on_tpu, sds
from apex_tpu.ops.pallas import geometry
from apex_tpu.ops.pallas.multi_tensor_kernels import _LANES, _view2d

#: Base chunk size for aligned packing: one (8, 128) fp32 tile per chunk.
LAMB_CHUNK = 8 * 128

#: Upper bound on chunks per call — keeps the SMEM scalar tables (fp32 per
#: chunk) around 128 KiB against the ~1 MiB SMEM budget; drivers grow the
#: chunk size instead of the table (see fused_lamb._pallas_lamb_update).
MAX_CHUNKS = 32768

#: Upper bound on the grown chunk size: stage 1 streams 7 fp32 buffers per
#: grid step, so 64 Ki elements (256 KiB each) stays ~3.5 MiB double-buffered
#: against the ~16 MiB VMEM budget.  MAX_CHUNKS × LAMB_CHUNK_MAX ≈ 2.1 B
#: params is the Pallas path's capacity; beyond it drivers fall back to the
#: jnp path rather than fail Mosaic compilation.
LAMB_CHUNK_MAX = 64 * 1024


def grown_chunk(total: int) -> int:
    """Chunk size grown so at most MAX_CHUNKS chunks cover ``total``
    elements — THE formula shared by the LAMB driver's packer and its
    capacity predicate (they must agree or an over-budget tree reaches
    Mosaic and fails compilation)."""
    return LAMB_CHUNK * max(1, -(-total // (LAMB_CHUNK * MAX_CHUNKS)))


def tree_within_packed_capacity(ps) -> bool:
    """Shared capacity predicate for the whole-tree packed optimizer paths
    (LAMB stages, packed Adam — all stream 7-8 fp32 buffers per grid
    step): element total bounded by MAX_CHUNKS x LAMB_CHUNK_MAX (VMEM
    tiles) AND chunk count bounded by MAX_CHUNKS (SMEM per-chunk tables;
    aligned packing gives every leaf at least one chunk, so many tiny
    leaves can blow the table even at a small element total)."""
    from apex_tpu.ops.packing import aligned_chunk_count, leaf_sizes
    sizes = leaf_sizes(ps)
    total = sum(sizes)
    if total > MAX_CHUNKS * LAMB_CHUNK_MAX:
        return False
    return aligned_chunk_count(sizes, grown_chunk(total)) <= MAX_CHUNKS


def stage1_geometry(n: int, chunk_size: int,
                    chunks_per_block: "int | None" = None
                    ) -> geometry.StreamGeometry:
    """Stage-1 streaming geometry (7 fp32 streams: g+p+m+v in,
    u+m+v out) — shared by the kernel, its tests, and
    ``tools/kernel_bench.py``."""
    return geometry.chunked_geometry(n, chunk_size,
                                     row_bytes=_LANES * 4 * 7,
                                     lanes=_LANES,
                                     chunks_per_block=chunks_per_block)


def stage2_geometry(n: int, chunk_size: int, *, with_copy: bool,
                    chunks_per_block: "int | None" = None
                    ) -> geometry.StreamGeometry:
    """Stage-2 geometry (p+u in, p out, optional half writeback)."""
    return geometry.chunked_geometry(
        n, chunk_size,
        row_bytes=_LANES * (3 * 4 + (2 if with_copy else 0)),
        lanes=_LANES, chunks_per_block=chunks_per_block)


def _stage1_kernel(scalars_ref, decay_ref, bc1_ref, bc2_ref, g_ref, p_ref,
                   m_ref, v_ref, u_ref, out_m_ref, out_v_ref, *rest,
                   chunk_rows, chunks_per_block):
    beta1 = scalars_ref[0]
    beta2 = scalars_ref[1]
    eps = scalars_ref[2]
    inv_scale = scalars_ref[3]   # 1 / clip_factor (grads arrive descaled)
    i = pl.program_id(0)

    for j in range(chunks_per_block):
        # Per-tensor weight decay AND bias correction (1 - beta^step, or
        # 1.0) resolved through the chunk->tensor tables in SMEM — the
        # role of TensorListMetadata's block_to_tensor map
        # (multi_tensor_apply.cuh:17-24).  Bias correction is per tensor,
        # not a launch-wide scalar, because each param leaf carries its
        # own step count (reference fused_adam.py:119-125 state per
        # param).
        c = i * chunks_per_block + j
        decay = decay_ref[c]
        bc1 = bc1_ref[c]
        bc2 = bc2_ref[c]
        rows = slice(j * chunk_rows, (j + 1) * chunk_rows)

        g = g_ref[rows, :].astype(jnp.float32) * inv_scale
        p = p_ref[rows, :].astype(jnp.float32)
        m = beta1 * m_ref[rows, :].astype(jnp.float32) + (1.0 - beta1) * g
        v = beta2 * v_ref[rows, :].astype(jnp.float32) + (1.0 - beta2) * g * g
        update = (m / bc1) / (jnp.sqrt(v / bc2) + eps) + decay * p
        u_ref[rows, :] = update
        out_m_ref[rows, :] = m
        out_v_ref[rows, :] = v
        if rest:  # fused ‖p‖²/‖update‖² per-chunk partials (with_norms)
            rest[0][c] = (p * p).sum()
            rest[1][c] = (update * update).sum()


@functools.partial(jax.jit, static_argnames=("chunk_size", "chunks_per_block",
                                             "with_norms"))
def packed_lamb_stage1(g: jax.Array, p: jax.Array, m: jax.Array,
                       v: jax.Array, per_chunk_decay: jax.Array, *,
                       beta1, beta2, eps, inv_scale, bc1, bc2,
                       chunk_size: int = LAMB_CHUNK,
                       chunks_per_block: "int | None" = None,
                       with_norms: bool = False):
    """Stage 1 over chunk-aligned flat fp32 buffers.

    ``per_chunk_decay``: fp32 ``(n_chunks,)`` — weight decay per chunk (i.e.
    per tensor, via ``AlignedMeta.chunk_ids``).  ``bc1``/``bc2`` may be
    scalars (all tensors at the same step) or ``(n_chunks,)`` arrays
    (per-tensor step counts).  Returns ``(update, new_m, new_v)`` flat
    fp32 buffers — plus ``(p_sumsq, u_sumsq)`` per-chunk ``(n_chunks,)``
    tables when ``with_norms`` (the fused inter-stage norm partials; a
    segment add over ``AlignedMeta.chunk_ids`` turns them into the
    per-tensor norms, identical partials to
    ``multi_tensor.per_tensor_sumsq_from_packed`` without re-reading the
    flat buffers).
    """
    n = g.shape[0]
    n_chunks = n // chunk_size
    chunk_rows = chunk_size // _LANES
    geom = stage1_geometry(n, chunk_size, chunks_per_block)
    slots = geom.grid * geom.chunks_per_block
    scalars = jnp.stack([
        jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(inv_scale, jnp.float32),
    ])
    decay = geometry.pad_table(per_chunk_decay.astype(jnp.float32), slots)
    bc1 = geometry.pad_table(
        jnp.broadcast_to(jnp.asarray(bc1, jnp.float32), (n_chunks,)), slots)
    bc2 = geometry.pad_table(
        jnp.broadcast_to(jnp.asarray(bc2, jnp.float32), (n_chunks,)), slots)

    def spec():
        return pl.BlockSpec((geom.block_rows, _LANES), lambda i: (i, 0))

    out_specs = [spec(), spec(), spec()]
    out_shape = [sds((n // _LANES, _LANES), jnp.float32, g, p, m, v)
                 for _ in range(3)]
    if with_norms:
        # SMEM partial tables are revisited whole each grid step — the
        # grid must stay sequential ("arbitrary"); without them every
        # step touches disjoint blocks and the grid pipelines as
        # "parallel".
        out_specs += [pl.BlockSpec(memory_space=pltpu.SMEM)] * 2
        out_shape += [sds((slots,), jnp.float32, g, p, m, v)
                      for _ in range(2)]
        semantics = ("arbitrary",)
    else:
        semantics = ("parallel",)

    outs = pl.pallas_call(
        functools.partial(_stage1_kernel, chunk_rows=chunk_rows,
                          chunks_per_block=geom.chunks_per_block),
        grid=(geom.grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            spec(), spec(), spec(), spec(),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=semantics),
        interpret=not on_tpu(),
    )(scalars, decay, bc1, bc2, _view2d(g), _view2d(p), _view2d(m),
      _view2d(v))
    u, new_m, new_v = (o.reshape(-1) for o in outs[:3])
    if with_norms:
        return u, new_m, new_v, outs[3][:n_chunks], outs[4][:n_chunks]
    return u, new_m, new_v


def _stage2_kernel(ratio_ref, p_ref, u_ref, out_p_ref, *rest, chunk_rows,
                   chunks_per_block):
    i = pl.program_id(0)
    for j in range(chunks_per_block):
        # lr·trust ratio for this chunk's tensor
        ratio = ratio_ref[i * chunks_per_block + j]
        rows = slice(j * chunk_rows, (j + 1) * chunk_rows)
        p = p_ref[rows, :].astype(jnp.float32) - ratio * u_ref[rows, :]
        out_p_ref[rows, :] = p.astype(out_p_ref.dtype)
        if rest:  # optional half-precision param writeback
            rest[0][rows, :] = p.astype(rest[0].dtype)


@functools.partial(jax.jit, static_argnames=("chunk_size", "p_copy_dtype",
                                             "chunks_per_block"))
def packed_lamb_stage2(p: jax.Array, u: jax.Array,
                       per_chunk_ratio: jax.Array, *,
                       chunk_size: int = LAMB_CHUNK, p_copy_dtype=None,
                       chunks_per_block: "int | None" = None):
    """Stage 2: ``p ← p − ratio·update`` with the per-chunk (= per-tensor)
    trust ratio in SMEM.  Returns ``new_p`` (or ``(new_p, p_copy)``)."""
    n = p.shape[0]
    chunk_rows = chunk_size // _LANES
    geom = stage2_geometry(n, chunk_size, with_copy=p_copy_dtype is not None,
                           chunks_per_block=chunks_per_block)
    ratio = geometry.pad_table(per_chunk_ratio.astype(jnp.float32),
                       geom.grid * geom.chunks_per_block)

    def spec():
        return pl.BlockSpec((geom.block_rows, _LANES), lambda i: (i, 0))

    out_shape = [sds((n // _LANES, _LANES), p.dtype, p, u)]
    out_specs = [spec()]
    if p_copy_dtype is not None:
        out_shape.append(sds((n // _LANES, _LANES), p_copy_dtype, p, u))
        out_specs.append(spec())

    outs = pl.pallas_call(
        functools.partial(_stage2_kernel, chunk_rows=chunk_rows,
                          chunks_per_block=geom.chunks_per_block),
        grid=(geom.grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            spec(), spec(),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel",)),
        interpret=not on_tpu(),
    )(ratio, _view2d(p), _view2d(u))
    if p_copy_dtype is None:
        return outs[0].reshape(-1)
    return outs[0].reshape(-1), outs[1].reshape(-1)
