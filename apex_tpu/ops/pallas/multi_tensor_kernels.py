"""Pallas TPU kernels for the packed multi-tensor ops.

TPU-native equivalents of ``csrc/multi_tensor_scale_kernel.cu`` and
``csrc/multi_tensor_axpby_kernel.cu``.  The CUDA kernels grid-stride over
(tensor, chunk) pairs packed into kernel argument space; here the tensor list
is pre-packed into one flat HBM buffer (see :mod:`apex_tpu.ops.packing`)
viewed as ``(padded/128, 128)``, and a sequential 1-D grid walks chunk-sized
row blocks.  Mosaic requires block dims divisible by (8, 128), so the chunk
size must be a multiple of 1024 (the caller falls back to the jnp path
otherwise — see :func:`chunk_supported`).

The overflow flag is a single SMEM cell accumulated across the (sequential)
TPU grid — the analog of the ``noop_flag`` the CUDA kernels set on any
non-finite input (``multi_tensor_scale_kernel.cu:57-76``).  All arithmetic
runs in fp32 regardless of storage dtype, matching the CUDA functors.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import on_tpu, sds

_LANES = 128


def chunk_supported(chunk_size: int) -> bool:
    """Chunk must map to whole (8, 128) tiles."""
    return chunk_size % (8 * _LANES) == 0


def _view2d(flat: jax.Array):
    return flat.reshape(flat.shape[0] // _LANES, _LANES)


def _block(chunk_size: int):
    return (chunk_size // _LANES, _LANES)


def _scale_kernel(scale_ref, x_ref, out_ref, flag_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        flag_ref[0] = 0

    x = x_ref[...].astype(jnp.float32)
    out_ref[...] = (x * scale_ref[0]).astype(out_ref.dtype)
    nonfinite = jnp.logical_not(jnp.isfinite(x)).any()

    @pl.when(nonfinite)
    def _flag():
        flag_ref[0] = 1


@functools.partial(jax.jit, static_argnames=("chunk_size", "out_dtype"))
def packed_scale(flat: jax.Array, scale: jax.Array, chunk_size: int,
                 out_dtype) -> tuple[jax.Array, jax.Array]:
    """``out = flat * scale`` in one pass + non-finite flag.

    ``flat`` must be padded to a multiple of ``chunk_size`` (finite pad).
    Returns ``(out_flat, overflow_flag_int32)``.
    """
    n = flat.shape[0]
    n_chunks = n // chunk_size
    br = _block(chunk_size)
    out, flag = pl.pallas_call(
        _scale_kernel,
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(br, lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec(br, lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            sds((n // _LANES, _LANES), out_dtype, flat),
            sds((1,), jnp.int32, flat),
        ],
        # same-dtype scaling is in-place (reference semantics: the CUDA
        # multi-tensor ops write through their tensor lists) — each grid
        # step touches the same block index, so aliasing is hazard-free
        # and halves the HBM traffic; XLA copies if the input stays live
        input_output_aliases=(
            {1: 0} if jnp.dtype(out_dtype) == flat.dtype else {}),
        interpret=not on_tpu(),
    )(jnp.asarray(scale, jnp.float32).reshape(1), _view2d(flat))
    return out.reshape(-1), flag[0]


def _axpby_kernel(ab_ref, x_ref, y_ref, out_ref, flag_ref, *, arg_to_check):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        flag_ref[0] = 0

    x = x_ref[...].astype(jnp.float32)
    y = y_ref[...].astype(jnp.float32)
    out_ref[...] = (ab_ref[0] * x + ab_ref[1] * y).astype(out_ref.dtype)
    # arg_to_check policy from multi_tensor_axpby_kernel.cu:16-87:
    # -1 => check both, 0 => only x, 1 => only y.
    checks = []
    if arg_to_check in (-1, 0):
        checks.append(jnp.logical_not(jnp.isfinite(x)).any())
    if arg_to_check in (-1, 1):
        checks.append(jnp.logical_not(jnp.isfinite(y)).any())
    nonfinite = functools.reduce(jnp.logical_or, checks)

    @pl.when(nonfinite)
    def _flag():
        flag_ref[0] = 1


@functools.partial(jax.jit,
                   static_argnames=("chunk_size", "out_dtype", "arg_to_check"))
def packed_axpby(x_flat: jax.Array, y_flat: jax.Array, a: jax.Array,
                 b: jax.Array, chunk_size: int, out_dtype,
                 arg_to_check: int = -1) -> tuple[jax.Array, jax.Array]:
    """``out = a*x + b*y`` in one pass + non-finite flag on the selected arg."""
    n = x_flat.shape[0]
    n_chunks = n // chunk_size
    br = _block(chunk_size)
    ab = jnp.stack([jnp.asarray(a, jnp.float32), jnp.asarray(b, jnp.float32)])
    out, flag = pl.pallas_call(
        functools.partial(_axpby_kernel, arg_to_check=arg_to_check),
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(br, lambda i: (i, 0)),
            pl.BlockSpec(br, lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec(br, lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            sds((n // _LANES, _LANES), out_dtype, x_flat),
            sds((1,), jnp.int32, x_flat),
        ],
        # in-place onto x when dtypes match (see packed_scale)
        input_output_aliases=(
            {1: 0} if jnp.dtype(out_dtype) == x_flat.dtype else {}),
        interpret=not on_tpu(),
    )(ab, _view2d(x_flat), _view2d(y_flat))
    return out.reshape(-1), flag[0]


def _sumsq_kernel(x_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[0] = 0.0

    x = x_ref[...].astype(jnp.float32)
    acc_ref[0] += (x * x).sum()


#: Bound on the per-chunk sumsq SMEM table (fp32 per chunk, 128 KiB against
#: the ~1 MiB SMEM budget); beyond it drivers fall back to per-leaf jnp
#: reductions rather than fail Mosaic compilation.
MAX_SUMSQ_CHUNKS = 32768


def _sumsq_per_chunk_kernel(x_ref, acc_ref):
    x = x_ref[...].astype(jnp.float32)
    acc_ref[pl.program_id(0)] = (x * x).sum()


@functools.partial(jax.jit, static_argnames=("chunk_size",))
def packed_sumsq_per_chunk(flat: jax.Array, chunk_size: int) -> jax.Array:
    """Per-chunk sums of squares over a chunk-ALIGNED flat buffer — the
    per-tensor output half of ``multi_tensor_l2norm_kernel.cu:117-180``:
    with aligned packing every chunk belongs to one tensor, so a segment
    add over ``AlignedMeta.chunk_ids`` turns this ``(n_chunks,)`` table
    into per-tensor norms.  The table rides SMEM like the CUDA kernel's
    per-block ``output_per_tensor`` partials."""
    n = flat.shape[0]
    n_chunks = n // chunk_size
    br = _block(chunk_size)
    return pl.pallas_call(
        _sumsq_per_chunk_kernel,
        grid=(n_chunks,),
        in_specs=[pl.BlockSpec(br, lambda i: (i, 0))],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        out_shape=sds((n_chunks,), jnp.float32, flat),
        interpret=not on_tpu(),
    )(_view2d(flat))


@functools.partial(jax.jit, static_argnames=("chunk_size",))
def packed_sumsq(flat: jax.Array, chunk_size: int) -> jax.Array:
    """Total sum of squares over the flat buffer — the two-kernel reduction
    of ``multi_tensor_l2norm_kernel.cu:16-180`` collapsed into one pass with
    an SMEM accumulator riding the sequential grid."""
    n = flat.shape[0]
    n_chunks = n // chunk_size
    br = _block(chunk_size)
    acc = pl.pallas_call(
        _sumsq_kernel,
        grid=(n_chunks,),
        in_specs=[pl.BlockSpec(br, lambda i: (i, 0))],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        out_shape=sds((1,), jnp.float32, flat),
        interpret=not on_tpu(),
    )(_view2d(flat))
    return acc[0]
