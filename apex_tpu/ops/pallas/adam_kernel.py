"""Pallas TPU kernel for the fused Adam update.

TPU-native equivalent of ``csrc/fused_adam_cuda_kernel.cu:20-56``: one pass
over packed flat (p, m, v, g) buffers doing descale → moment update →
(eps-in/out-sqrt) → weight update → half-precision param writeback.  The
CUDA kernel grid-strides with ILP=4; here the flat buffers are viewed as
``(rows, LANES)`` and a sequential grid walks row-blocks, each block one VMEM
tile per operand.  ``step_size`` (with bias correction precomputed outside,
as in ``fused_adam_cuda_kernel.cu:83-91``), ``scale``, and ``weight_decay``
arrive as SMEM scalars so a changing loss scale never triggers recompilation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import on_tpu, sds
from apex_tpu.ops.pallas.multi_tensor_kernels import _LANES, _block, _view2d

#: Flat buffers must be padded to a multiple of this (8 sublanes × 128 lanes
#: × 8 rows of work per tile keeps every operand a well-formed fp32 tile).
ADAM_PAD = 8 * 1024


def _adam_kernel(scalars_ref, p_ref, m_ref, v_ref, g_ref,
                 out_p_ref, out_m_ref, out_v_ref, *rest, eps_mode):
    step_size = scalars_ref[0]
    beta1 = scalars_ref[1]
    beta2 = scalars_ref[2]
    eps = scalars_ref[3]
    inv_scale = scalars_ref[4]
    weight_decay = scalars_ref[5]

    p = p_ref[...].astype(jnp.float32)
    m = m_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32) * inv_scale
    g = g + weight_decay * p
    m = beta1 * m + (1.0 - beta1) * g
    v = beta2 * v + (1.0 - beta2) * g * g
    if eps_mode == 1:  # eps inside sqrt
        denom = jnp.sqrt(v + eps)
    else:
        denom = jnp.sqrt(v) + eps
    p = p - step_size * m / denom
    out_p_ref[...] = p.astype(out_p_ref.dtype)
    out_m_ref[...] = m.astype(out_m_ref.dtype)
    out_v_ref[...] = v.astype(out_v_ref.dtype)
    if rest:  # optional half p_copy (the fused fp16 writeback)
        rest[0][...] = p.astype(rest[0].dtype)


def _adam_tree_kernel(scalars_ref, step_ref, p_ref, m_ref, v_ref, g_ref,
                      out_p_ref, out_m_ref, out_v_ref, *, eps_mode,
                      with_decay):
    """Whole-tree variant: per-TENSOR step size (bias correction differs per
    leaf under per-leaf step counts) resolved through the chunk->tensor
    table in SMEM, like the LAMB kernels' decay/bc tables.

    ``1-beta`` arrives precomputed (not derived from the rounded f32 betas
    in-kernel) and the descale is a true division, so the element math is
    bit-identical to the jnp reference path — the L1 conformance contract.
    """
    beta1 = scalars_ref[0]
    beta2 = scalars_ref[1]
    om_beta1 = scalars_ref[2]    # 1 - beta1, rounded from the exact value
    om_beta2 = scalars_ref[3]
    eps = scalars_ref[4]
    scale = scalars_ref[5]
    weight_decay = scalars_ref[6]
    step_size = step_ref[pl.program_id(0)]

    p = p_ref[...].astype(jnp.float32)
    m = m_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32) / scale
    if with_decay:  # trace-time guard, mirroring the jnp path's
        g = g + weight_decay * p  # `if weight_decay:` (keeps -0.0 grads)
    m = beta1 * m + om_beta1 * g
    v = beta2 * v + om_beta2 * g * g
    if eps_mode == 1:
        denom = jnp.sqrt(v + eps)
    else:
        denom = jnp.sqrt(v) + eps
    out_p_ref[...] = p - step_size * m / denom
    out_m_ref[...] = m
    out_v_ref[...] = v


@functools.partial(
    jax.jit,
    static_argnames=("beta1", "beta2", "eps", "weight_decay", "eps_mode",
                     "chunk_size"))
def packed_adam_tree(p: jax.Array, m: jax.Array, v: jax.Array, g: jax.Array,
                     per_chunk_step_size: jax.Array, *, beta1: float,
                     beta2: float, eps: float, scale, weight_decay: float,
                     eps_mode: int, chunk_size: int):
    """One fused Adam pass over a whole chunk-ALIGNED packed tree — the
    TPU analog of the reference driving ``fused_adam_cuda.adam`` through
    ``multi_tensor_apply`` (``apex/optimizers/fused_adam.py:126-147``):
    hundreds of param leaves, one kernel launch, per-tensor bias
    correction riding the chunk→tensor SMEM table.

    All four buffers fp32, aligned to ``chunk_size`` (zero padding is
    harmless: 0-grads leave 0-moments and 0-params at 0 up to
    weight-decay, and padded lanes are sliced away by unpack).  Returns
    ``(new_p, new_m, new_v)`` flat fp32 buffers.
    """
    n = p.shape[0]
    n_chunks = n // chunk_size
    br = _block(chunk_size)
    scalars = jnp.stack([
        jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32),
        jnp.asarray(1.0 - beta1, jnp.float32),  # exact, then rounded once
        jnp.asarray(1.0 - beta2, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(scale, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
    ])

    def spec():
        return pl.BlockSpec(br, lambda i: (i, 0))

    outs = pl.pallas_call(
        functools.partial(_adam_tree_kernel, eps_mode=eps_mode,
                          with_decay=bool(weight_decay)),
        grid=(n_chunks,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  spec(), spec(), spec(), spec()],
        out_specs=[spec(), spec(), spec()],
        out_shape=[sds((n // _LANES, _LANES), jnp.float32, p, m, v, g)
                   for _ in range(3)],
        interpret=not on_tpu(),
    )(scalars, per_chunk_step_size.astype(jnp.float32), _view2d(p),
      _view2d(m), _view2d(v), _view2d(g))
    return tuple(o.reshape(-1) for o in outs)


@functools.partial(
    jax.jit,
    static_argnames=("beta1", "beta2", "eps", "weight_decay", "eps_mode",
                     "p_copy_dtype"))
def packed_adam(p: jax.Array, m: jax.Array, v: jax.Array, g: jax.Array,
                *, step_size, beta1: float, beta2: float, eps: float,
                scale, weight_decay: float, eps_mode: int,
                p_copy_dtype=None):
    """Fused Adam over flat buffers padded to a multiple of ``ADAM_PAD``.

    Returns ``(new_p, new_m, new_v)`` or ``(..., p_copy)`` when
    ``p_copy_dtype`` is set.
    """
    n = p.shape[0]
    assert n % ADAM_PAD == 0, f"pad flat buffers to {ADAM_PAD} (got {n})"
    lanes = 1024
    rows = n // lanes
    # (32, 1024) blocks measured +23% streaming bandwidth over (8, 1024)
    # on v5e (fewer grid steps amortize per-step overhead; ~2 MB of
    # VMEM double-buffered across the 8 operand/result streams); buffers
    # not divisible into 32-row blocks keep the 8-row tile floor
    block_rows = 32 if rows % 32 == 0 else 8
    grid = rows // block_rows

    scalars = jnp.stack([
        jnp.asarray(step_size, jnp.float32),
        jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        1.0 / jnp.asarray(scale, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
    ])

    def spec():
        return pl.BlockSpec((block_rows, lanes), lambda i: (i, 0))

    out_shape = [
        sds((rows, lanes), p.dtype, p, g, m, v),
        sds((rows, lanes), m.dtype, p, g, m, v),
        sds((rows, lanes), v.dtype, p, g, m, v),
    ]
    out_specs = [spec(), spec(), spec()]
    if p_copy_dtype is not None:
        out_shape.append(sds((rows, lanes), p_copy_dtype, p, g, m, v))
        out_specs.append(spec())

    outs = pl.pallas_call(
        functools.partial(_adam_kernel, eps_mode=eps_mode),
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  spec(), spec(), spec(), spec()],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=not on_tpu(),
    )(scalars, *(t.reshape(rows, lanes) for t in (p, m, v, g)))
    return tuple(o.reshape(-1) for o in outs)
