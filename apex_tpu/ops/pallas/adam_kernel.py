"""Pallas TPU kernel for the fused Adam update.

TPU-native equivalent of ``csrc/fused_adam_cuda_kernel.cu:20-56``: one pass
over packed flat (p, m, v, g) buffers doing descale → moment update →
(eps-in/out-sqrt) → weight update → half-precision param writeback.  The
CUDA kernel grid-strides with ILP=4; here the flat buffers are viewed as
``(rows, LANES)`` and a sequential grid walks row-blocks, each block one VMEM
tile per operand.  ``step_size`` (with bias correction precomputed outside,
as in ``fused_adam_cuda_kernel.cu:83-91``), ``scale``, and ``weight_decay``
arrive as SMEM scalars so a changing loss scale never triggers recompilation.

Memory movement (round 6 retune): the row-block geometry comes from the
shared selector (:mod:`apex_tpu.ops.pallas.geometry`) instead of the old
8/32-row special cases — the largest ladder block whose double-buffered
working set across all 8 operand/result streams fits the VMEM budget
(measured +23% for 8→32 rows on v5e; the selector typically lands on
128).  Ragged row counts no longer drop to the 8-row tile floor: Mosaic
masks the out-of-bounds tail of the last grid block, so the grid is a
plain ceiling division.  The grid is declared ``parallel`` (every step
touches disjoint blocks) so the pipeliner overlaps the next block's DMA
with this block's compute.  ``donate=True`` adds ``input_output_aliases``
on the (p, m, v) streams — in-place updates that halve the buffers XLA
must hold live — but it is OPT-IN: the production train step wraps the
optimizer in the loss-scale skip-``cond`` whose untaken branch returns
the old state, keeping p/m/v live across the update; XLA then inserts
full copies and the "win" inverts (measured on chip: BERT-large 105 →
54 seq/s with aliased LAMB kernels).  Donate only from drivers whose
inputs are genuinely dead at the call.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import on_tpu, sds
from apex_tpu.ops.packing import STREAM_LANES, STREAM_TILE_ROWS
from apex_tpu.ops.pallas import geometry
from apex_tpu.ops.pallas.multi_tensor_kernels import _LANES, _view2d

#: Lane width of the packed-Adam flat view (wider than the 128-lane chunk
#: view: the flat path has no per-chunk tables to respect) — THE packing
#: constants, so ``packing.streaming_pad`` and this kernel's alignment
#: assert can never desync.
_ADAM_LANES = STREAM_LANES

#: Flat buffers must be padded to a multiple of this: one (8, 1024) fp32
#: tile — the only alignment the retuned kernel still requires (ragged
#: row counts ride the masked last grid block).
ADAM_PAD = STREAM_TILE_ROWS * STREAM_LANES


def adam_geometry(n: int, *, with_copy: bool,
                  block_rows: "int | None" = None) -> geometry.StreamGeometry:
    """Resolved streaming geometry for :func:`packed_adam` at ``n``
    elements — THE function the kernel, its tests, and
    ``tools/kernel_bench.py`` share, so the artifact records exactly the
    shape the kernel ran."""
    rows = n // _ADAM_LANES
    # 4 fp32 reads (p, m, v, g) + 3 fp32 writes + optional half writeback
    row_bytes = _ADAM_LANES * (7 * 4 + (2 if with_copy else 0))
    br = block_rows or geometry.select_block_rows(rows, row_bytes)
    return geometry.StreamGeometry(block_rows=br, lanes=_ADAM_LANES,
                                   grid=-(-rows // br))


def _adam_kernel(scalars_ref, p_ref, m_ref, v_ref, g_ref,
                 out_p_ref, out_m_ref, out_v_ref, *rest, eps_mode):
    step_size = scalars_ref[0]
    beta1 = scalars_ref[1]
    beta2 = scalars_ref[2]
    eps = scalars_ref[3]
    inv_scale = scalars_ref[4]
    weight_decay = scalars_ref[5]

    p = p_ref[...].astype(jnp.float32)
    m = m_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32) * inv_scale
    g = g + weight_decay * p
    m = beta1 * m + (1.0 - beta1) * g
    v = beta2 * v + (1.0 - beta2) * g * g
    if eps_mode == 1:  # eps inside sqrt
        denom = jnp.sqrt(v + eps)
    else:
        denom = jnp.sqrt(v) + eps
    p = p - step_size * m / denom
    out_p_ref[...] = p.astype(out_p_ref.dtype)
    out_m_ref[...] = m.astype(out_m_ref.dtype)
    out_v_ref[...] = v.astype(out_v_ref.dtype)
    if rest:  # optional half p_copy (the fused fp16 writeback)
        rest[0][...] = p.astype(rest[0].dtype)


def _adam_tree_kernel(scalars_ref, step_ref, p_ref, m_ref, v_ref, g_ref,
                      out_p_ref, out_m_ref, out_v_ref, *, eps_mode,
                      with_decay, chunk_rows, chunks_per_block):
    """Whole-tree variant: per-TENSOR step size (bias correction differs per
    leaf under per-leaf step counts) resolved through the chunk->tensor
    table in SMEM, like the LAMB kernels' decay/bc tables.  One grid step
    streams ``chunks_per_block`` chunks (statically unrolled so every
    chunk keeps its own table scalar); the step table is padded to the
    grid outside, so the masked tail of a ragged last block reads a real
    (dead) slot instead of running off the table.

    ``1-beta`` arrives precomputed (not derived from the rounded f32 betas
    in-kernel) and the descale is a true division, so the element math is
    bit-identical to the jnp reference path — the L1 conformance contract.
    """
    beta1 = scalars_ref[0]
    beta2 = scalars_ref[1]
    om_beta1 = scalars_ref[2]    # 1 - beta1, rounded from the exact value
    om_beta2 = scalars_ref[3]
    eps = scalars_ref[4]
    scale = scalars_ref[5]
    weight_decay = scalars_ref[6]
    i = pl.program_id(0)

    for j in range(chunks_per_block):
        step_size = step_ref[i * chunks_per_block + j]
        rows = slice(j * chunk_rows, (j + 1) * chunk_rows)

        p = p_ref[rows, :].astype(jnp.float32)
        m = m_ref[rows, :].astype(jnp.float32)
        v = v_ref[rows, :].astype(jnp.float32)
        g = g_ref[rows, :].astype(jnp.float32) / scale
        if with_decay:  # trace-time guard, mirroring the jnp path's
            g = g + weight_decay * p  # `if weight_decay:` (keeps -0.0 grads)
        m = beta1 * m + om_beta1 * g
        v = beta2 * v + om_beta2 * g * g
        if eps_mode == 1:
            denom = jnp.sqrt(v + eps)
        else:
            denom = jnp.sqrt(v) + eps
        out_p_ref[rows, :] = p - step_size * m / denom
        out_m_ref[rows, :] = m
        out_v_ref[rows, :] = v


def adam_tree_geometry(n: int, chunk_size: int,
                       chunks_per_block: "int | None" = None
                       ) -> geometry.StreamGeometry:
    """Geometry for :func:`packed_adam_tree`: K aligned chunks per grid
    step (7 fp32 streams over the 128-lane chunk view)."""
    return geometry.chunked_geometry(n, chunk_size,
                                     row_bytes=_LANES * 4 * 7,
                                     lanes=_LANES,
                                     chunks_per_block=chunks_per_block)


@functools.partial(
    jax.jit,
    static_argnames=("beta1", "beta2", "eps", "weight_decay", "eps_mode",
                     "chunk_size", "chunks_per_block"))
def packed_adam_tree(p: jax.Array, m: jax.Array, v: jax.Array, g: jax.Array,
                     per_chunk_step_size: jax.Array, *, beta1: float,
                     beta2: float, eps: float, scale, weight_decay: float,
                     eps_mode: int, chunk_size: int,
                     chunks_per_block: "int | None" = None):
    """One fused Adam pass over a whole chunk-ALIGNED packed tree — the
    TPU analog of the reference driving ``fused_adam_cuda.adam`` through
    ``multi_tensor_apply`` (``apex/optimizers/fused_adam.py:126-147``):
    hundreds of param leaves, one kernel launch, per-tensor bias
    correction riding the chunk→tensor SMEM table.

    All four buffers fp32, aligned to ``chunk_size`` (zero padding is
    harmless: 0-grads leave 0-moments and 0-params at 0 up to
    weight-decay, and padded lanes are sliced away by unpack).  Returns
    ``(new_p, new_m, new_v)`` flat fp32 buffers.
    """
    n = p.shape[0]
    geom = adam_tree_geometry(n, chunk_size, chunks_per_block)
    chunk_rows = chunk_size // _LANES
    scalars = jnp.stack([
        jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32),
        jnp.asarray(1.0 - beta1, jnp.float32),  # exact, then rounded once
        jnp.asarray(1.0 - beta2, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(scale, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
    ])
    steps = geometry.pad_table(per_chunk_step_size.astype(jnp.float32),
                               geom.grid * geom.chunks_per_block)

    def spec():
        return pl.BlockSpec((geom.block_rows, _LANES), lambda i: (i, 0))

    outs = pl.pallas_call(
        functools.partial(_adam_tree_kernel, eps_mode=eps_mode,
                          with_decay=bool(weight_decay),
                          chunk_rows=chunk_rows,
                          chunks_per_block=geom.chunks_per_block),
        grid=(geom.grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  spec(), spec(), spec(), spec()],
        out_specs=[spec(), spec(), spec()],
        out_shape=[sds((n // _LANES, _LANES), jnp.float32, p, m, v, g)
                   for _ in range(3)],
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel",)),
        interpret=not on_tpu(),
    )(scalars, steps, _view2d(p), _view2d(m), _view2d(v), _view2d(g))
    return tuple(o.reshape(-1) for o in outs)


@functools.partial(
    jax.jit,
    static_argnames=("beta1", "beta2", "eps", "weight_decay", "eps_mode",
                     "p_copy_dtype", "block_rows", "donate"))
def packed_adam(p: jax.Array, m: jax.Array, v: jax.Array, g: jax.Array,
                *, step_size, beta1: float, beta2: float, eps: float,
                scale, weight_decay: float, eps_mode: int,
                p_copy_dtype=None, block_rows: "int | None" = None,
                donate: bool = False):
    """Fused Adam over flat buffers padded to a multiple of ``ADAM_PAD``.

    ``block_rows`` overrides the selector's row-block (the autotune
    sweep axis); ``donate=True`` aliases (p, m, v) in-place — see the
    module docstring for the production caveat before enabling it.
    Returns ``(new_p, new_m, new_v)`` or ``(..., p_copy)`` when
    ``p_copy_dtype`` is set.
    """
    n = p.shape[0]
    assert n % ADAM_PAD == 0, f"pad flat buffers to {ADAM_PAD} (got {n})"
    geom = adam_geometry(n, with_copy=p_copy_dtype is not None,
                         block_rows=block_rows)
    lanes = geom.lanes
    rows = n // lanes

    scalars = jnp.stack([
        jnp.asarray(step_size, jnp.float32),
        jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        1.0 / jnp.asarray(scale, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
    ])

    def spec():
        return pl.BlockSpec((geom.block_rows, lanes), lambda i: (i, 0))

    out_shape = [
        sds((rows, lanes), p.dtype, p, g, m, v),
        sds((rows, lanes), m.dtype, p, g, m, v),
        sds((rows, lanes), v.dtype, p, g, m, v),
    ]
    out_specs = [spec(), spec(), spec()]
    if p_copy_dtype is not None:
        out_shape.append(sds((rows, lanes), p_copy_dtype, p, g, m, v))
        out_specs.append(spec())

    outs = pl.pallas_call(
        functools.partial(_adam_kernel, eps_mode=eps_mode),
        grid=(geom.grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  spec(), spec(), spec(), spec()],
        out_specs=out_specs,
        out_shape=out_shape,
        # every grid step touches disjoint row blocks, so the in-place
        # aliasing (donate) is hazard-free under either semantics
        input_output_aliases={1: 0, 2: 1, 3: 2} if donate else {},
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel",)),
        interpret=not on_tpu(),
    )(scalars, *(t.reshape(rows, lanes) for t in (p, m, v, g)))
    return tuple(o.reshape(-1) for o in outs)
