"""Block-geometry selection shared by the streaming Pallas kernels.

Every optimizer/norm kernel in this package is an elementwise or
row-reduction pass whose roofline is HBM bandwidth, and the knob that
decides how close it gets is the ROW-BLOCK geometry: how many rows of
the 2-D flat-buffer view one grid step streams through VMEM.  Round 5
measured the fused Adam kernel gaining +23% going from 8-row to 32-row
blocks on v5e (KERNELBENCH_r05 vs the 8-row floor; fewer grid steps
amortize per-step DMA setup), while the LAMB kernels — pinned to one
(8, 128) chunk tile per step — sat at 0.13-0.17 of peak on the same
chip where mt_axpby's (512, 128) blocks reached 0.81.  This module
generalizes that measurement into one selector all streaming kernels
share, instead of each kernel hard-coding its own magic block.

Two selection surfaces:

- :func:`select_block_rows` — flat-view kernels (packed Adam, LayerNorm
  forward): the largest ladder block whose double-buffered working set
  across all operand/result streams fits the VMEM budget.  Ragged row
  counts need NO fallback to the tile floor: Mosaic masks the
  out-of-bounds tail of the last grid block (reads padded, writes
  dropped), so the grid is simply ``cdiv(rows, block_rows)``.
- :func:`select_chunks_per_block` — chunk-aligned kernels (LAMB stages,
  whole-tree Adam) whose per-tensor scalars ride chunk→tensor SMEM
  tables: the grid step grows to K chunks, statically unrolled inside
  the kernel so each chunk keeps its own table scalars (and its own
  partial-norm slot).  K is capped by ``max_unroll`` — Mosaic compile
  time scales with the unrolled sub-block count.

The VMEM budget is half the chip's ~16 MiB VMEM by default (the other
half belongs to Mosaic's own scratch and the double-buffer partner),
overridable via ``APEX_TPU_VMEM_BUDGET_MB`` for experiments; per-call
geometry overrides (the ``block_rows=`` / ``chunks_per_block=`` kwargs
on the kernels) are what ``tools/kernel_bench.py --autotune`` sweeps.

Selection never changes element math — blocks partition the same rows
with the same per-chunk scalars — so the L1 conformance contract
(pallas bit-identical to the jnp reference) is geometry-independent;
``tests/l0/test_kernel_geometry.py`` pins that across ragged shapes.
"""

from __future__ import annotations

import os
from typing import NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.ops.packing import round_up as _round_up

#: Descending candidate ladder for flat-view row blocks.  Powers of two
#: only: every rung is a multiple of both tile floors (8 fp32 / 16 bf16
#: sublanes), and halving steps keep the autotune sweep small.
BLOCK_ROWS_LADDER = (1024, 512, 256, 128, 64, 32, 16, 8)

#: Default streaming VMEM budget (bytes): half of the ~16 MiB core VMEM.
DEFAULT_VMEM_BUDGET = 8 * 1024 * 1024

#: Static-unroll cap for multi-chunk grid steps (compile-time bound).
DEFAULT_MAX_UNROLL = 8


def vmem_budget() -> int:
    """Streaming VMEM budget in bytes (``APEX_TPU_VMEM_BUDGET_MB`` or
    the 8 MiB default).  Malformed env values fall back silently — a
    typo'd override must degrade to the default, not crash a train
    step."""
    raw = os.environ.get("APEX_TPU_VMEM_BUDGET_MB")
    if raw:
        try:
            return max(1, int(float(raw) * 1024 * 1024))
        except ValueError:
            pass
    return DEFAULT_VMEM_BUDGET


def select_block_rows(rows: int, row_bytes: int, *, multiple_of: int = 8,
                      max_rows: int = 1024,
                      budget: "int | None" = None) -> int:
    """Largest ladder block (a multiple of ``multiple_of``) whose
    double-buffered working set ``2 * block_rows * row_bytes`` fits the
    VMEM budget, clamped to ``max_rows`` and to the data itself.

    ``row_bytes`` is the total bytes one row costs across EVERY
    operand/result stream the kernel touches per grid step (lanes ×
    Σ dtype sizes) — the quantity the double-buffer pipeline must hold
    twice.  The block never exceeds the data rounded up to
    ``multiple_of`` — small inputs step down the ladder instead of
    allocating a mostly-masked giant block (they may still take a
    multi-step grid: rows=100 selects 64, grid 2).
    """
    assert rows >= 1 and row_bytes >= 1
    cap = (budget if budget is not None else vmem_budget()) \
        // (2 * row_bytes)
    cap = min(cap, max_rows)
    for cand in BLOCK_ROWS_LADDER:
        if cand % multiple_of:
            continue
        if cand <= cap and cand <= _round_up(rows, multiple_of):
            return cand
    return multiple_of  # tile floor: always legal, never worse than today


def select_chunks_per_block(n_chunks: int, chunk_rows: int, row_bytes: int,
                            *, max_unroll: int = DEFAULT_MAX_UNROLL,
                            budget: "int | None" = None) -> int:
    """How many aligned chunks one grid step of a chunk-tabled kernel
    should stream: bounded by the VMEM budget (double-buffered), the
    static-unroll cap, and the chunk count itself.  Returns ≥ 1."""
    assert n_chunks >= 1 and chunk_rows >= 1
    cap_rows = (budget if budget is not None else vmem_budget()) \
        // (2 * row_bytes)
    k = max(1, cap_rows // chunk_rows)
    return max(1, min(k, max_unroll, n_chunks))


def chunked_geometry(n: int, chunk_size: int, row_bytes: int, *,
                     lanes: int, chunks_per_block: "int | None" = None,
                     max_unroll: int = DEFAULT_MAX_UNROLL
                     ) -> "StreamGeometry":
    """Resolved geometry for a chunk-tabled kernel at ``n`` elements —
    THE one body behind the per-kernel helpers (LAMB stage 1/2,
    whole-tree Adam): K chunks per grid step, ceiling grid, and the
    padded-table slot count derived as ``grid × chunks_per_block``.
    Keeping it single-sourced means the grid and the SMEM-table padding
    can never desync between kernels."""
    n_chunks = n // chunk_size
    chunk_rows = chunk_size // lanes
    k = chunks_per_block or select_chunks_per_block(
        n_chunks, chunk_rows, row_bytes, max_unroll=max_unroll)
    return StreamGeometry(block_rows=k * chunk_rows, lanes=lanes,
                          grid=-(-n_chunks // k), chunks_per_block=k)


def pad_table(t: jax.Array, slots: int) -> jax.Array:
    """Pad a per-chunk SMEM scalar table to the grid's slot count
    (``grid × chunks_per_block``) so the masked tail of a ragged last
    block indexes real (dead) entries instead of running off the table —
    shared by every chunk-tabled kernel (LAMB stages, whole-tree
    Adam)."""
    return t if t.shape[0] == slots else jnp.pad(t, (0, slots - t.shape[0]))


class StreamGeometry(NamedTuple):
    """Resolved geometry of one streaming pallas_call — recorded by
    ``tools/kernel_bench.py`` per kernel so every artifact states the
    shape it measured."""

    block_rows: int      # rows per grid step (chunks_per_block * chunk rows
                         # for chunk-tabled kernels)
    lanes: int           # width of the 2-D flat view
    grid: int            # number of grid steps (ceil division: ragged
                         # tails ride the masked last block)
    chunks_per_block: int = 1

    def asdict(self) -> dict:
        return {"block_rows": self.block_rows, "lanes": self.lanes,
                "grid": self.grid,
                "chunks_per_block": self.chunks_per_block}
