"""Rotary position embeddings — table building and reference application.

Lives under :mod:`apex_tpu.ops` (not in the GPT model) because the flash
attention kernel can apply the rotation *inside* the kernel
(``flash_attention(..., rope=(cos, sin))``): q/k blocks are rotated in
VMEM right before the score matmul, so the rotated tensors never hit HBM
and the head-major projection path stays a pure reshape end to end
(round-3 measured the out-of-kernel rotation re-materializing the layout,
net -3% on GPT — the motivation for the fused path).

The reference (2019-era apex) predates rotary embeddings entirely; this
is part of the long-context story (SURVEY.md §5.7).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class KernelRopeTables(NamedTuple):
    """Full-width kernel-format rope tables (see
    :func:`rope_kernel_tables`).  Passing this to
    ``flash_attention(rope=...)`` instead of the half-width ``(cos,
    sin)`` pair skips the per-call table build — callers with a
    scanned/remat layer body (GPT) construct it ONCE per step so the
    concat/sign-fold/cast stays out of the compiled layer loop."""

    cos_full: jax.Array   #: (B, L, D)
    sin_signed: jax.Array  #: (B, L, D) — low half negated


def rope_tables(positions: jax.Array, head_dim: int,
                theta: float) -> tuple:
    """(cos, sin) rotation tables ``(B, L, 1, head_dim//2)`` from *global*
    position indices — computed once per step and shared by q and k across
    every layer (they depend only on positions), so the transcendentals
    stay out of the scanned/remat layer body."""
    half = head_dim // 2
    freqs = jnp.exp(-jnp.log(theta)
                    * jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, :, None].astype(jnp.float32) * freqs  # (B, L, half)
    return jnp.cos(angles)[:, :, None, :], jnp.sin(angles)[:, :, None, :]


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate ``(B, L, H, D)`` by precomputed tables."""
    half = x.shape[-1] // 2
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


def _rope_rot_matrix(d: int) -> jax.Array:
    """Constant (D, D) matrix with ``x @ R == rotate_half(x)`` (i.e.
    ``concat(-x2, x1)``).  Entries are 0/±1, exact in bf16."""
    half = d // 2
    i = jnp.arange(half)
    r = jnp.zeros((d, d), jnp.float32)
    r = r.at[half + i, i].set(-1.0)
    r = r.at[i, half + i].set(1.0)
    return r


def apply_rope_mxu(x: jax.Array, cos_full: jax.Array,
                   sin_full: jax.Array) -> jax.Array:
    """Rotary embedding with the half-rotation as an MXU matmul.

    The concat-of-half-slices spelling (:func:`apply_rope`) creates
    minor-dim-32 lane slices whose fwd+bwd materialize as copies in the
    head-major layout (round-3 profile: 48 copies + fp32 backward
    copies per step).  ``x @ R`` with a constant 0/±1 matrix is the
    same permutation on the MXU — layout-neutral, exact, and its
    transpose is again a single matmul.  Tables are full-width:
    ``cos_full = concat(cos, cos)``, ``sin_full = concat(sin, sin)``.
    """
    r = _rope_rot_matrix(x.shape[-1]).astype(x.dtype)
    # precision="highest": with fp32 inputs the MXU's default bf16
    # passes would round what must be an exact permutation (0/±1 rows);
    # bf16 inputs are exact either way, and the matmul is tiny.
    xr = jnp.matmul(x, r, precision="highest")
    out = (x.astype(jnp.float32) * cos_full
           + xr.astype(jnp.float32) * sin_full)
    return out.astype(x.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """One-shot rotary embedding (tables + apply); positions are global
    indices, so a sequence-sharded rank rotates its local shard
    correctly."""
    cos, sin = rope_tables(positions, x.shape[-1], theta)
    return apply_rope(x, cos, sin)


def _apply_full_tables(x: jax.Array, cos_full: jax.Array,
                       sin_signed: jax.Array) -> jax.Array:
    """Out-of-kernel application of the kernel-format tables (the same
    lane-rotation formula the flash kernels run in VMEM): ``x·cos_full +
    rot_half(x)·sin_signed`` where ``rot_half`` maps lane ``j`` to
    ``x[(j + D/2) mod D]``.  ``x``: (..., L, H-or-1-broadcastable, D)
    with tables broadcast over the head axis."""
    half = x.shape[-1] // 2
    xr = jnp.concatenate([x[..., half:], x[..., :half]], axis=-1)
    out = (x.astype(jnp.float32) * cos_full.astype(jnp.float32)
           + xr.astype(jnp.float32) * sin_signed.astype(jnp.float32))
    return out.astype(x.dtype)


def apply_rope_tables(q: jax.Array, k: jax.Array, rope,
                      layout: str = "blhd") -> tuple:
    """Rotate q and k out-of-kernel from a public ``rope`` argument —
    either a half-width ``(cos, sin)`` pair (``(B, L, 1, D/2)`` or
    ``(B, L, D/2)``) or prebuilt :class:`KernelRopeTables` — the shared
    fallback stanza for paths that cannot fuse the rotation (jnp
    attention, interpret-under-shard_map).  Keeps the table-shape
    convention in one place next to :func:`rope_kernel_tables`.  Raises
    the same self-attention requirement the kernel path enforces."""
    seq_ax = 2 if layout == "bhld" else 1
    l = q.shape[seq_ax]
    if k.shape[seq_ax] != l:
        raise ValueError("rope requires self-attention (Lq == Lk): q and "
                         "k share one position table")
    if isinstance(rope, KernelRopeTables):
        cos4 = rope.cos_full[:, :, None, :]   # (B, L, 1, D)
        sin4 = rope.sin_signed[:, :, None, :]
        if layout == "bhld":
            cos4, sin4 = (jnp.moveaxis(t, 1, 2) for t in (cos4, sin4))
        return (_apply_full_tables(q, cos4, sin4),
                _apply_full_tables(k, cos4, sin4))
    half = q.shape[-1] // 2
    cos4 = rope[0].reshape(rope[0].shape[0], l, 1, half)
    sin4 = rope[1].reshape(rope[1].shape[0], l, 1, half)
    if layout == "bhld":
        cos4, sin4 = (jnp.moveaxis(t, 1, 2) for t in (cos4, sin4))
    return apply_rope(q, cos4, sin4), apply_rope(k, cos4, sin4)


def rope_kernel_tables(cos: jax.Array, sin: jax.Array, b: int, l: int,
                       d: int, dtype) -> KernelRopeTables:
    """Public (cos, sin) half-width tables → the flash kernel's
    ``(B, L, D)`` full-width pair ``(cos_full, sin_signed)``.

    The in-kernel rotation is spelled lane-rotation-style —
    ``rot(x) = x * cos_full + rotate_lanes(x, D/2) * sin_signed`` where
    ``rotate_lanes`` maps lane ``j`` to ``x[(j + D/2) mod D]`` — so the
    sign of the classic ``x1·cos − x2·sin`` low half is folded into the
    table: ``cos_full = [cos, cos]``, ``sin_signed = [−sin, sin]``.
    Table dtype follows the activation dtype (bf16 activations take bf16
    tables: the extra rounding is the same class as the bf16 q/k storage
    itself, and it halves the kernel's table DMA)."""
    cos = cos.reshape(cos.shape[0], l, d // 2)
    sin = sin.reshape(sin.shape[0], l, d // 2)
    if cos.shape[0] != b:
        cos = jnp.broadcast_to(cos, (b, l, d // 2))
        sin = jnp.broadcast_to(sin, (b, l, d // 2))
    cos_full = jnp.concatenate([cos, cos], axis=-1)
    sin_signed = jnp.concatenate([-sin, sin], axis=-1)
    return KernelRopeTables(cos_full.astype(dtype),
                            sin_signed.astype(dtype))
