"""Functional multi-tensor ops: scale / axpby / l2norm over tensor lists.

Port of the ``amp_C`` extension surface (``csrc/amp_C_frontend.cpp:43-54``):

- :func:`multi_tensor_scale` — fused copy × scale + overflow flag
  (``multi_tensor_scale_kernel.cu``); this is the engine of gradient
  unscaling (``apex/amp/scaler.py:113-116``) and master→model copies.
- :func:`multi_tensor_axpby` — ``out = a·x + b·y`` with a selectable
  inf-check argument (``multi_tensor_axpby_kernel.cu``); the
  gradient-accumulation path.
- :func:`multi_tensor_l2norm` — global and optional per-tensor L2 norms
  (``multi_tensor_l2norm_kernel.cu``).

JAX is functional, so instead of writing into output lists these return new
lists; the "overflow buffer" becomes a returned int32 flag (monotonic OR
across chunks, like the racy-but-monotonic CUDA flag writes —
``multi_tensor_scale_kernel.cu:71``).  Mixed-dtype input lists are grouped by
dtype and processed one packed launch per group (the analog of
``split_by_type``, ``apex/parallel/distributed.py:62-72``).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops import packing, use_pallas
from apex_tpu.ops.pallas import multi_tensor_kernels as ker

#: Default chunk size, matching the reference applier
#: (``apex/multi_tensor_apply/__init__.py:3``: 2048*32).
DEFAULT_CHUNK_SIZE = 2048 * 32


def _resolve_out_dtype(tensor_lists, out_dtype):
    if out_dtype is not None:
        return out_dtype
    if len(tensor_lists) > 1 and tensor_lists[-1]:
        t0 = tensor_lists[-1][0]
        return getattr(t0, "dtype", jnp.result_type(t0))
    return None  # same as input


def multi_tensor_scale(
    chunk_size: int,
    tensor_lists: Sequence[Sequence[jax.Array]],
    scale: Any,
    out_dtype=None,
) -> Tuple[List[jax.Array], jax.Array]:
    """``outs[i] = ins[i] * scale`` (cast to ``out_dtype``) + overflow flag.

    ``tensor_lists`` is ``[ins]`` or ``[ins, out_templates]`` — the second
    list, when given, only supplies the output dtype, mirroring the reference
    call shape ``[model_grads, master_grads]`` (``scaler.py:113-116``).
    Returns ``(outs, overflow_flag)`` with ``overflow_flag`` an int32 scalar
    (0 = all finite).
    """
    ins = list(tensor_lists[0])
    odt = _resolve_out_dtype(tensor_lists, out_dtype)
    scale = jnp.asarray(scale, jnp.float32)
    if not ins:
        return [], jnp.zeros((), jnp.int32)

    outs: List[Optional[jax.Array]] = [None] * len(ins)
    flag = jnp.zeros((), jnp.int32)
    for dtype, idxs in packing.group_by_dtype(ins).items():
        group = [ins[i] for i in idxs]
        godt = odt or dtype
        if use_pallas() and ker.chunk_supported(chunk_size):
            flat, meta = packing.pack(group, chunk_size)
            out_flat, gflag = ker.packed_scale(flat, scale, chunk_size, godt)
            gouts = packing.unpack(out_flat, meta)
        else:
            f32 = [t.astype(jnp.float32) for t in group]
            gouts = [(t * scale).astype(godt) for t in f32]
            finite = jnp.stack([jnp.isfinite(t).all() for t in f32]).all()
            gflag = jnp.where(finite, 0, 1).astype(jnp.int32)
        for i, o in zip(idxs, gouts):
            outs[i] = o
        flag = jnp.maximum(flag, gflag)
    return outs, flag


def multi_tensor_axpby(
    chunk_size: int,
    tensor_lists: Sequence[Sequence[jax.Array]],
    a: Any,
    b: Any,
    arg_to_check: int = -1,
    out_dtype=None,
) -> Tuple[List[jax.Array], jax.Array]:
    """``outs[i] = a*xs[i] + b*ys[i]`` + overflow flag on the selected arg.

    ``tensor_lists = [xs, ys]`` or ``[xs, ys, out_templates]``
    (reference ``scaler.py:167-172`` passes [model, stashed, master] with
    ``arg_to_check=0`` so stale stashed grads can't spuriously trip the flag).
    """
    xs, ys = list(tensor_lists[0]), list(tensor_lists[1])
    assert len(xs) == len(ys)
    odt = _resolve_out_dtype(tensor_lists, out_dtype) if len(tensor_lists) > 2 \
        else out_dtype
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    if not xs:
        return [], jnp.zeros((), jnp.int32)

    outs: List[Optional[jax.Array]] = [None] * len(xs)
    flag = jnp.zeros((), jnp.int32)
    for dtype, idxs in packing.group_by_dtype(xs).items():
        gx = [xs[i] for i in idxs]
        gy = [ys[i] for i in idxs]
        godt = odt or dtype
        if use_pallas() and ker.chunk_supported(chunk_size):
            xf, meta = packing.pack(gx, chunk_size)
            # y packs in fp32: the accumulator side must not lose precision
            # (the jnp path below also computes in fp32).
            yf, _ = packing.pack([t.astype(jnp.float32) for t in gy],
                                 chunk_size)
            out_flat, gflag = ker.packed_axpby(xf, yf, a, b, chunk_size, godt,
                                               arg_to_check=arg_to_check)
            gouts = packing.unpack(out_flat, meta)
        else:
            xs32 = [t.astype(jnp.float32) for t in gx]
            ys32 = [t.astype(jnp.float32) for t in gy]
            gouts = [(a * x + b * y).astype(godt) for x, y in zip(xs32, ys32)]
            checks = []
            if arg_to_check in (-1, 0):
                checks += [jnp.isfinite(x).all() for x in xs32]
            if arg_to_check in (-1, 1):
                checks += [jnp.isfinite(y).all() for y in ys32]
            finite = jnp.stack(checks).all()
            gflag = jnp.where(finite, 0, 1).astype(jnp.int32)
        for i, o in zip(idxs, gouts):
            outs[i] = o
        flag = jnp.maximum(flag, gflag)
    return outs, flag


def multi_tensor_l2norm(
    chunk_size: int,
    tensor_lists: Sequence[Sequence[jax.Array]],
    per_tensor: bool = False,
) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Global L2 norm over a tensor list, optionally per-tensor norms too
    (``multi_tensor_l2norm_kernel.cu:117-180`` returns both).

    Per-tensor norms run fused too: chunk-ALIGNED packing (every chunk
    belongs to exactly one tensor) + a per-chunk sum-of-squares kernel,
    segment-reduced through the chunk→tensor table — the TPU shape of the
    CUDA kernel's ``output_per_tensor`` path.  The global norm reuses the
    same partials (``sqrt(sum(per))``), one pass over HBM either way.
    """
    ins = list(tensor_lists[0])
    if not ins:
        z = jnp.zeros((), jnp.float32)
        return z, (jnp.zeros((0,), jnp.float32) if per_tensor else None)

    if use_pallas() and ker.chunk_supported(chunk_size):
        if per_tensor:
            # Per dtype group, fused when it pays: two gates (decided from
            # pack_aligned's own chunk-count formula, BEFORE any packing
            # work) — the SMEM per-chunk table bound, and padding waste.
            # Every leaf pads to a whole chunk, so a small-leaf-dominated
            # group would read far more HBM fused than the per-leaf jnp
            # reductions; cap the padded traffic at 2x the real elements.
            per_sq: List[Optional[jax.Array]] = [None] * len(ins)
            for dtype, idxs in packing.group_by_dtype(ins).items():
                group = [ins[i] for i in idxs]
                sizes = packing.leaf_sizes(group)
                n_chunks = packing.aligned_chunk_count(sizes, chunk_size)
                if (n_chunks <= ker.MAX_SUMSQ_CHUNKS
                        and n_chunks * chunk_size <= 2 * sum(sizes)):
                    flat, meta = packing.pack_aligned(group, chunk_size)
                    sums = per_tensor_sumsq_from_packed(flat, meta)
                    for j, i in enumerate(idxs):
                        per_sq[i] = sums[j]
                else:
                    for i in idxs:
                        per_sq[i] = jnp.sum(
                            jnp.square(ins[i].astype(jnp.float32)))
            per = jnp.stack(per_sq)
            return jnp.sqrt(per.sum()), jnp.sqrt(per)
        else:
            total = jnp.zeros((), jnp.float32)
            for dtype, idxs in packing.group_by_dtype(ins).items():
                flat, _ = packing.pack([ins[i] for i in idxs], chunk_size)
                total = total + ker.packed_sumsq(flat, chunk_size)
            return jnp.sqrt(total), None
    per = jnp.stack([jnp.sum(jnp.square(t.astype(jnp.float32))) for t in ins])
    return jnp.sqrt(per.sum()), (jnp.sqrt(per) if per_tensor else None)


def per_tensor_sumsq_from_packed(flat: jax.Array,
                                 meta: packing.AlignedMeta) -> jax.Array:
    """Per-tensor sums of squares of an already chunk-aligned flat buffer:
    the fused per-chunk kernel + a segment add over ``meta.chunk_ids``.
    Shared by :func:`multi_tensor_l2norm` and FusedLAMB's inter-stage
    ‖p‖/‖update‖ norms (the per-tensor l2norm output feeding LAMB stage 2
    in the reference)."""
    chunk_sums = ker.packed_sumsq_per_chunk(flat, meta.chunk_size)
    ids = jnp.asarray(np.array(meta.chunk_ids), jnp.int32)
    return jnp.zeros((len(meta.shapes),), jnp.float32).at[ids].add(chunk_sums)
