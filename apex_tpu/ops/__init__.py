"""apex_tpu.ops — fused TPU kernels (Pallas) and their jnp reference paths.

Layer L1/L2 of the design (SURVEY.md §7): every op here has
(a) a pure ``jax.numpy`` reference implementation — always correct, used on
    CPU and as the conformance oracle (the analog of the reference's
    Python-fallback paths), and
(b) a Pallas TPU kernel used on TPU for explicit single-pass fusion control
    (the analog of ``csrc/``).

Selection is automatic (`on_tpu()`), overridable via the environment variable
``APEX_TPU_KERNELS={pallas,jnp,auto}`` for A/B conformance testing — the port
of the reference L1 harness's ext-vs-no-ext install axis
(``tests/L1/common/run_test.sh``).
"""

import os

import jax


def kernel_mode() -> str:
    """'pallas' | 'jnp' | 'auto' from APEX_TPU_KERNELS (default auto)."""
    return os.environ.get("APEX_TPU_KERNELS", "auto")


def on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def use_pallas() -> bool:
    mode = kernel_mode()
    if mode == "pallas":
        return True
    if mode == "jnp":
        return False
    return on_tpu()


def sds(shape, dtype, *likes):
    """ShapeDtypeStruct for a pallas_call output, carrying the union of the
    varying-across-mesh-axes (vma) types of the ``likes`` operands —
    required when the kernel runs inside ``shard_map`` under VMA checking
    (multi-chip optimizer steps, sequence-parallel attention).  Pass every
    operand the output depends on; an output computed from any varying
    input is varying."""
    vma = None
    for like in likes:
        try:
            v = jax.typeof(like).vma
        except Exception:
            continue
        if v is not None:
            vma = frozenset(v) if vma is None else vma | frozenset(v)
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    # NB: an empty frozenset (fully replicated operands) must still be
    # passed through — under shard_map's VMA checking "vma=None" is an
    # error even for replicated outputs.
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
