"""Flat-buffer packing for multi-tensor ops.

The reference's key perf trick is ``multi_tensor_apply`` — packing pointers
for hundreds of small tensors into kernel argument space so one launch
processes them all (``csrc/multi_tensor_apply.cuh:14-125``).  TPU has no
per-launch overhead crisis, but touching hundreds of small HBM buffers in
separate fusions still wastes bandwidth; the TPU-native analog (SURVEY.md §7
"multi_tensor_apply economics") is to **concatenate the tensors into one flat
HBM buffer** padded to a chunk multiple, run a single Pallas grid over the
chunks, and slice the results back out.  The (sizes, offsets) metadata plays
the role of ``TensorListMetadata``.

Under ``jit`` the concatenate / slice pair is pure data movement that XLA
schedules once; for steady-state optimizer use the packed representation can
be kept across steps (see ``apex_tpu.optimizers.FP16Optimizer``, the analog
of the reference's flat-buffer ``apex/optimizers/fp16_optimizer.py:57-70``).
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


#: Lane width / sublane tile floor of the flat-view streaming kernels
#: (packed Adam and the persistent-flat FP16Optimizer layout derive
#: their alignment from THESE constants — ops/pallas/adam_kernel.py
#: imports them — so the padder and the kernel's assert can never
#: desync).
STREAM_LANES = 1024
STREAM_TILE_ROWS = 8


def round_up(x: int, multiple: int) -> int:
    """Smallest multiple of ``multiple`` ≥ ``x``."""
    return int(-(-x // multiple) * multiple)


def streaming_pad(total: int, *, lanes: int = STREAM_LANES,
                  tile_rows: int = STREAM_TILE_ROWS) -> int:
    """Padded length for a flat buffer feeding the streaming Pallas
    kernels: a whole number of ``(tile_rows, lanes)`` tiles — the ONLY
    alignment the retuned kernels still require.  Block geometry itself
    needs no padding: the selector's bigger row blocks ride Mosaic's
    masked last grid block over ragged row counts
    (:mod:`apex_tpu.ops.pallas.geometry`), so callers no longer pad to a
    block multiple, just to the dtype tile."""
    return round_up(max(total, 1), lanes * tile_rows)


class PackMeta(NamedTuple):
    """Static metadata describing a packed tensor list."""

    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    offsets: Tuple[int, ...]   # start offset of each tensor in the flat buffer
    total: int                 # unpadded total element count
    padded: int                # padded total (multiple of chunk)
    dtype: Any


def pack(tensors: Sequence[jax.Array], chunk_size: int) -> Tuple[jax.Array, PackMeta]:
    """Concatenate raveled tensors into one flat buffer padded to a multiple
    of ``chunk_size`` (pad value 0 — finite, so it never trips the overflow
    flag, matching the reference kernels which simply don't read past
    ``chunk_size`` remainders)."""
    assert len(tensors) > 0
    dtype = tensors[0].dtype
    sizes = tuple(int(np.prod(t.shape)) if t.shape else 1 for t in tensors)
    offsets = tuple(int(o) for o in np.cumsum((0,) + sizes[:-1]))
    total = int(sum(sizes))
    padded = int(-(-max(total, 1) // chunk_size) * chunk_size)
    flat = jnp.concatenate([jnp.ravel(t) for t in tensors])
    if padded != total:
        flat = jnp.pad(flat, (0, padded - total))
    meta = PackMeta(shapes=tuple(t.shape for t in tensors), sizes=sizes,
                    offsets=offsets, total=total, padded=padded, dtype=dtype)
    return flat, meta


def unpack(flat: jax.Array, meta: PackMeta) -> List[jax.Array]:
    """Slice a flat buffer back into the original shapes.

    Many direct slices off one large 1-D buffer trip a TPU AOT layout
    pathology (the buffer materializes as an (N/2, 2) pairs view whose
    (8,128) tiling pads the minor dim 64x — see ``unpack_aligned``), so
    when the padded buffer is lane-divisible each leaf is carved by
    slicing a 128-lane ROW window of the 2-D view first (bounded piece),
    then trimming the unaligned head/tail on the small piece only."""
    lanes = 128
    out = []
    if meta.padded % lanes == 0 and flat.shape[0] == meta.padded:
        rows = flat.reshape(-1, lanes)
        for shape, size, offset in zip(meta.shapes, meta.sizes,
                                       meta.offsets):
            r0 = offset // lanes
            r1 = -(-(offset + size) // lanes)
            piece = jax.lax.dynamic_slice_in_dim(rows, r0, r1 - r0, 0)
            head = offset - r0 * lanes
            piece = jax.lax.dynamic_slice_in_dim(
                piece.reshape(-1), head, size)
            out.append(piece.reshape(shape))
        return out
    for shape, size, offset in zip(meta.shapes, meta.sizes, meta.offsets):
        out.append(jax.lax.dynamic_slice_in_dim(flat, offset,
                                                size).reshape(shape))
    return out


def host_pack(arrays: Sequence[np.ndarray]) -> Tuple[np.ndarray, PackMeta]:
    """Flatten *host* (numpy) arrays into one buffer via the native runtime
    when built (``csrc/apex_tpu_C.cpp`` — the ``apex_C.flatten`` analog,
    multithreaded memcpy); use for checkpoint staging and pre-``device_put``
    coalescing, where :func:`pack`'s traced concatenate doesn't apply."""
    from apex_tpu import _native
    arrays = [np.asarray(a) for a in arrays]
    flat = _native.flatten(arrays)
    sizes = tuple(int(a.size) for a in arrays)
    offsets = tuple(int(o) for o in np.cumsum((0,) + sizes[:-1]))
    meta = PackMeta(shapes=tuple(a.shape for a in arrays), sizes=sizes,
                    offsets=offsets, total=int(flat.size),
                    padded=int(flat.size), dtype=flat.dtype)
    return flat, meta


def host_unpack(flat: np.ndarray, meta: PackMeta) -> List[np.ndarray]:
    """Inverse of :func:`host_pack` (``apex_C.unflatten`` analog)."""
    from apex_tpu import _native
    return _native.unflatten(np.asarray(flat)[:meta.total], meta.shapes)


class AlignedMeta(NamedTuple):
    """Metadata for a chunk-aligned packed tensor list (each tensor padded to
    a whole number of chunks, so every chunk belongs to exactly one tensor —
    the flat-buffer analog of ``TensorListMetadata``'s block→(tensor, chunk)
    table, ``csrc/multi_tensor_apply.cuh:17-24``)."""

    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]         # unpadded element counts
    offsets: Tuple[int, ...]       # aligned start offsets in the flat buffer
    chunk_size: int
    padded: int                    # flat buffer length (multiple of chunk)
    chunk_ids: Tuple[int, ...]     # chunk index -> tensor index
    dtype: Any


def leaf_sizes(tensors: Sequence[jax.Array]) -> List[int]:
    """Element counts as :func:`pack_aligned` sees them (scalars count 1)."""
    return [int(np.prod(t.shape)) if t.shape else 1 for t in tensors]


def aligned_chunk_count(sizes: Sequence[int], chunk_size: int) -> int:
    """Number of chunks :func:`pack_aligned` will produce — THE formula the
    capacity predicates (SMEM per-chunk tables) must share with the packer
    so they can never disagree with the actual layout."""
    return sum(-(-s // chunk_size) for s in sizes)


def pack_aligned(tensors: Sequence[jax.Array],
                 chunk_size: int) -> Tuple[jax.Array, AlignedMeta]:
    """Concatenate raveled tensors, padding EACH to a chunk multiple.

    Wastes at most ``chunk_size - 1`` elements per tensor but guarantees
    chunks never straddle tensors, so per-chunk scalar tables (weight decay,
    trust ratios) in SMEM index cleanly by ``program_id`` — exactly how the
    CUDA multi-tensor launcher resolves per-tensor arguments per block.
    """
    assert len(tensors) > 0
    dtype = tensors[0].dtype
    parts, shapes, sizes, offsets, chunk_ids = [], [], [], [], []
    off = 0
    for ti, t in enumerate(tensors):
        size = int(np.prod(t.shape)) if t.shape else 1
        n_chunks = -(-size // chunk_size)
        padded = n_chunks * chunk_size
        flat = jnp.ravel(t)
        if padded != size:
            flat = jnp.pad(flat, (0, padded - size))
        # Concatenate CHUNK-SHAPED 2-D pieces, not 1-D ravels: at ~100M+
        # elements the TPU AOT compiler lowers a many-way 1-D concat
        # through an (N/2, 2) intermediate whose (8,128)-tiled layout pads
        # the minor dim 2 -> 128 (observed 64x HBM blowup = 34 GB on a
        # bert-base param pack).  Chunk-wide rows tile cleanly.
        parts.append(flat.reshape(n_chunks, chunk_size))
        shapes.append(tuple(t.shape))
        sizes.append(size)
        offsets.append(off)
        chunk_ids.extend([ti] * n_chunks)
        off += padded
    meta = AlignedMeta(shapes=tuple(shapes), sizes=tuple(sizes),
                       offsets=tuple(offsets), chunk_size=chunk_size,
                       padded=off, chunk_ids=tuple(chunk_ids), dtype=dtype)
    return jnp.concatenate(parts, axis=0).reshape(-1), meta


def pack_into(tensors: Sequence[jax.Array], meta: AlignedMeta) -> jax.Array:
    """Pack a tensor list whose layout matches a precomputed
    :class:`AlignedMeta` (same shapes, same chunk size) — skips rebuilding
    the chunk table when several same-shaped lists share one layout, as the
    LAMB driver's g/p/m/v quadruple does."""
    parts = []
    for t, size, off, next_off in zip(
            tensors, meta.sizes, meta.offsets,
            meta.offsets[1:] + (meta.padded,)):
        flat = jnp.ravel(t)
        padded = next_off - off
        if padded != size:
            flat = jnp.pad(flat, (0, padded - size))
        # chunk-shaped 2-D pieces for the same layout reason as
        # pack_aligned (1-D many-way concat blows up on the TPU AOT
        # compiler at scale)
        parts.append(flat.reshape(-1, meta.chunk_size))
    return jnp.concatenate(parts, axis=0).reshape(-1)


def unpack_aligned(flat: jax.Array, meta: AlignedMeta) -> List[jax.Array]:
    """Slice an aligned flat buffer back into the original shapes.

    Slices CHUNK ROWS off the 2-D ``(n_chunks, chunk_size)`` view instead
    of offsets off the 1-D buffer: every tensor starts on a chunk boundary
    by construction, and at ~100M+ elements the TPU AOT compiler
    materializes a many-slice-consumed 1-D buffer through an (N/2, 2)
    intermediate whose (8,128)-tiled layout pads the minor dim 64x
    (the same pathology the 2-D concat in :func:`pack_aligned` avoids)."""
    rows = flat.reshape(-1, meta.chunk_size)
    out = []
    for shape, size, off in zip(meta.shapes, meta.sizes, meta.offsets):
        n_chunks = -(-size // meta.chunk_size)
        piece = jax.lax.dynamic_slice_in_dim(
            rows, off // meta.chunk_size, n_chunks, 0)
        flat_piece = piece.reshape(-1)
        if n_chunks * meta.chunk_size != size:
            flat_piece = jax.lax.slice_in_dim(flat_piece, 0, size)
        out.append(flat_piece.reshape(shape))
    return out


def group_by_dtype(tensors: Sequence[jax.Array]):
    """Indices grouped by dtype — the analog of the reference's
    ``split_by_type`` bucketing (``apex/parallel/distributed.py:62-72``);
    packed kernels run once per dtype group."""
    groups = {}
    for i, t in enumerate(tensors):
        groups.setdefault(jnp.asarray(t).dtype, []).append(i)
    return groups
