"""Policy-aware building-block layers.

The reference made ``torch.nn`` layers mixed-precision-aware by
monkey-patching the functions they call (``apex/amp/amp.py:90-101``); here
the layers call :mod:`apex_tpu.amp.ops` directly, so the active O1 policy
governs their compute dtype, and under O2/O3 they simply follow their
(cast) param dtypes.  Convolutions run channels-last (NHWC) — the TPU-native
layout (the reference needed dedicated ``_c_last`` CUDA kernels for this;
see ``csrc/welford.cu:586-829``).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.amp import ops as amp_ops


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


class Conv(nn.Module):
    """NHWC convolution whose compute routes through the policy-cast op
    layer (O1 whitelists conv, ``lists/functional_overrides.py:18-27``)."""

    features: int
    kernel_size: Union[int, Tuple[int, int]] = 3
    strides: Union[int, Tuple[int, int]] = 1
    padding: Union[str, int] = "SAME"
    use_bias: bool = False
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kh, kw = _pair(self.kernel_size)
        kernel = self.param(
            "kernel", nn.initializers.variance_scaling(2.0, "fan_out",
                                                       "normal"),
            (kh, kw, x.shape[-1], self.features), self.param_dtype)
        padding = self.padding
        if isinstance(padding, int):
            padding = [(padding, padding), (padding, padding)]
        y = amp_ops.conv_general_dilated(
            x, kernel, window_strides=_pair(self.strides), padding=padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), self.param_dtype)
            y = y + bias.astype(y.dtype)
        return y


class ConvTranspose(nn.Module):
    """NHWC transposed convolution (DCGAN generator upsampling)."""

    features: int
    kernel_size: Union[int, Tuple[int, int]] = 4
    strides: Union[int, Tuple[int, int]] = 2
    padding: Union[str, Sequence[Tuple[int, int]]] = "SAME"
    use_bias: bool = False
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kh, kw = _pair(self.kernel_size)
        kernel = self.param(
            "kernel", nn.initializers.variance_scaling(1.0, "fan_in",
                                                       "normal"),
            (kh, kw, x.shape[-1], self.features), self.param_dtype)
        y = amp_ops.conv_transpose(
            x, kernel, strides=_pair(self.strides), padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), self.param_dtype)
            y = y + bias.astype(y.dtype)
        return y


class Dense(nn.Module):
    """Linear layer via the policy-cast matmul."""

    features: int
    use_bias: bool = True
    param_dtype: Any = jnp.float32
    kernel_init: Any = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", self.kernel_init,
                            (x.shape[-1], self.features), self.param_dtype)
        bias = (self.param("bias", nn.initializers.zeros,
                           (self.features,), self.param_dtype)
                if self.use_bias else None)
        return amp_ops.linear(x, kernel, bias)


class HeadMajorQKVProj(nn.Module):
    """Fused qkv projection emitting head-major ``(3, B, H, L, D)``.

    Parameter shapes/init/paths are identical to ``Dense(3*E)`` (kernel
    ``(E, 3E)``, bias ``(3E,)``) so checkpoints and the non-fast path
    interchange freely; the head-major output permutation lives INSIDE
    the einsum, where the dot emits it for free — the reshape+transpose
    spelling after a plain Dense materialized as explicit copies
    (round-3 profile)."""

    hidden_size: int
    num_heads: int
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        e, h = self.hidden_size, self.num_heads
        d = e // h
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (e, 3 * e), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (3 * e,),
                          self.param_dtype)
        qkv = amp_ops.einsum("ble,eihd->ibhld", x,
                             kernel.reshape(e, 3, h, d))
        return qkv + bias.reshape(3, 1, h, 1, d).astype(qkv.dtype)


class HeadMajorOutProj(nn.Module):
    """Output projection reading head-major ``(B, H, L, D)`` directly
    (``Dense(E)``-compatible params; the input permutation folds into
    the dot)."""

    hidden_size: int
    num_heads: int
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, o):
        e, h = self.hidden_size, self.num_heads
        d = e // h
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (e, e), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (e,),
                          self.param_dtype)
        y = amp_ops.einsum("bhld,hde->ble", o, kernel.reshape(h, d, e))
        return y + bias.astype(y.dtype)
