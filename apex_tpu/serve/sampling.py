"""Fused sampling epilogue: temperature / top-k / top-p + PRNG chain,
entirely inside the compiled decode step.

The classic serving mistake is sampling on the host: the step returns
``(S, V)`` logits, Python applies temperature/top-k/top-p and feeds the
token back — a ``S×V`` device→host→device round trip per generated
token that serializes the decode loop against the Python thread.  Here
the whole epilogue is jax ops fused into the step (the engine's step
fetches only the ``(S,)`` sampled token ids it must stream anyway), and
every knob is a TRACED per-slot array:

- ``temperature (S,) f32`` — ``0`` selects greedy argmax for that slot
  (a ``where``, not a Python branch: mixing greedy and sampling slots
  in one batch never retraces);
- ``top_k (S,) i32`` — ``<= 0`` disables the cutoff;
- ``top_p (S,) f32`` — ``>= 1`` disables the nucleus cutoff; the
  highest-probability token always survives both filters.

One shared descending sort serves both filters; the categorical draw
chains per-slot PRNG keys (``keys (S, 2) uint32`` ride the step's
donated carry), so each slot's stream is reproducible regardless of
which other requests shared its batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.models.generate import NEG_INF, greedy_argmax


_ADVANCE = None


def advance_key(key: jax.Array, n: int) -> jax.Array:
    """The per-slot PRNG chain's position after ``n`` draws: each
    :func:`sample_tokens` call advances a slot's key exactly once
    (``nk, _ = split(key)`` — greedy slots included), so the key a
    request's slot holds after streaming ``n`` tokens (the prefill
    sample counts) is a pure function of ``(seed, n)``.  The router's
    replica-kill recovery re-derives lost device keys with this:
    ``advance_key(PRNGKey(req.seed), tokens_streamed)`` resumes the
    exact chain the dead replica was on.  The chain rolls in ONE
    compiled ``fori_loop`` dispatch (``n`` is a dynamic argument —
    failure recovery for a thousand-token stream must not pay a
    thousand eager splits)."""
    key = jnp.asarray(key, jnp.uint32)
    n = int(n)
    if n == 0:
        return key
    global _ADVANCE
    if _ADVANCE is None:
        _ADVANCE = jax.jit(lambda k, m: jax.lax.fori_loop(
            0, m, lambda _, kk: jax.random.split(kk)[0], k))
    return _ADVANCE(key, jnp.int32(n))


def sample_tokens(logits: jax.Array, keys: jax.Array,
                  temperature: jax.Array, top_k: jax.Array,
                  top_p: jax.Array):
    """``(tokens (S,) i32, new_keys (S, 2))`` sampled from ``logits
    (S, V)`` under per-slot knobs (see module docstring).  Pure and
    shape-stable: every knob is traced, so sweeping temperature or
    mixing greedy/sampling slots reuses the one compiled program."""
    logits = logits.astype(jnp.float32)
    s, v = logits.shape
    # tie-STABLE greedy pick (models.generate.greedy_argmax): a plain
    # jnp.argmax breaks exact logit ties differently depending on what
    # XLA fuses it with — observed flipping a tied bf16 logit pair
    # between this fused epilogue and solo generate()'s program, the
    # one way a bitwise-identical cache can still greedy-diverge
    greedy = greedy_argmax(logits)

    # temperature guard: the scaled logits only reach the output for
    # slots with temperature > 0, but the divide must stay finite for
    # the greedy slots sharing the batch
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp

    # one descending sort serves top-k (rank cutoff) and top-p
    # (cumulative-mass cutoff); temperature > 0 preserves the order,
    # so sorting the raw logits' order is the scaled order too
    order = jnp.argsort(-logits, axis=-1)
    sorted_scaled = jnp.take_along_axis(scaled, order, axis=-1)

    ranks = jnp.arange(v)[None, :]
    k_eff = jnp.where(top_k <= 0, v, jnp.minimum(top_k, v))[:, None]
    keep_k = ranks < k_eff

    probs = jax.nn.softmax(sorted_scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep ranks whose PRECEDING mass is under top_p: the first token
    # always survives, and the kept set is the smallest prefix whose
    # mass reaches top_p (the standard nucleus convention)
    keep_p = (cum - probs) < jnp.clip(top_p, 0.0, 1.0)[:, None]

    keep = (keep_k & keep_p).at[:, 0].set(True)
    masked = jnp.where(keep, sorted_scaled, NEG_INF)

    def draw(key, row):
        nk, sub = jax.random.split(key)
        return nk, jax.random.categorical(sub, row)

    new_keys, picked = jax.vmap(draw)(keys, masked)
    sampled = jnp.take_along_axis(order, picked[:, None], axis=-1)[:, 0]
    tokens = jnp.where(temperature > 0, sampled, greedy)
    return tokens.astype(jnp.int32), new_keys
