"""Disaggregated prefill/decode serving: separate mesh slices behind
one KV-shipping router.

The monolithic engine (:mod:`apex_tpu.serve.engine`) interleaves
prefill chunks and decode steps on ONE set of devices, so a bursty
long-prompt admission stalls every in-flight decode behind it.
Production fleets (the DistServe/Splitwise result) split the two
phases onto different replicas at equal chip count: prefill is
compute-bound and bursty, decode is HBM-bound and steady, and
separating them removes the interference that dominates decode p99.
This module is that topology, built from parts that already exist:

- the **prefill worker** (:class:`PrefillWorker`) is a
  :class:`~apex_tpu.serve.engine.ServeEngine` on its own mesh slice
  used only for its chunked paged prefill + first-token sample; the
  finished slot's KV blocks are gathered into a fixed-shape
  :class:`~apex_tpu.serve.transfer.KVShipment` and the slot is freed
  immediately — the worker's pool only ever holds in-flight prompts;

- each **decode replica** (:class:`DecodeReplica`) is the existing
  one-compiled-step engine on its own slice; a shipment installs
  through one donated scatter (page-table row and slot index TRACED —
  one executable per replica across every admit/transfer/retire), and
  the replica decodes exactly as the monolithic engine would;

- the **router** (:class:`DisaggRouter`) does admission control off
  the obs gauges the engines already export — per-replica queue
  depth, slot occupancy, block utilization, decode-p99 — ships
  finished prefill KV to the least-loaded eligible replica
  (``transfer="ship"``), or hands the original request to the replica
  to re-prefill locally (``transfer="recompute"`` — the
  recompute-on-miss fallback riding the same admission path the
  preempt-and-recompute machinery uses), and recovers from a replica
  death (:meth:`DisaggRouter.kill_replica`) by rebuilding
  continuation requests from its streamed-token log and re-prefilling
  them elsewhere: greedy outputs stay BITWISE equal to solo
  ``generate()`` through the kill, and sampled requests resume their
  exact PRNG chain via :func:`apex_tpu.serve.sampling.advance_key`.

Every replica cold-starts through ``ServeConfig.aot_cache``
(:mod:`apex_tpu.analysis.export`): a placed engine keys its cache
entry per-slice (the device ids join the mesh descriptor — a PJRT
executable is pinned to its devices, so a cross-slice load would be
wrong-device, not fast), so a restarted replica loads its slice's
lint-gated executable instead of compiling.

Everything here is host-side control: the compiled programs are the
engines' own (the graph-lint ``serve_prefill``/``serve_decode`` lanes
lint them), and every router metric is a host number recorded at a
step boundary — the syncs pass stays clean on every replica's step.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.serve import transfer
from apex_tpu.serve.engine import ServeConfig, ServeEngine
from apex_tpu.serve.paged import PoolExhausted
from apex_tpu.serve.sampling import advance_key
from apex_tpu.serve.scheduler import Request, validate_request
from apex_tpu.serve.transfer import (
    FleetSlices,
    KVShipment,
    place_tree,
    placement,
    slice_fleet,
)


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Fleet shape + policy knobs.  ``transfer`` picks the KV path:
    ``"ship"`` moves prefilled blocks device-to-device, ``"recompute"``
    re-prefills on the decode replica (the miss fallback, runnable as
    the whole policy for parity tests and transfer-starved topologies).
    ``admit_block_util`` is the admission-control headroom bar: a
    replica whose block-utilization gauge is at/over it takes no new
    admissions even with a free slot (whole-footprint allocation
    already guarantees no mid-decode death; the bar keeps headroom so
    a burst lands on the emptiest pool)."""

    n_decode_replicas: int = 2
    n_prefill_devices: int = 1
    devices_per_replica: int = 1
    transfer: str = "ship"
    admit_block_util: float = 0.97
    #: where :meth:`DisaggRouter.kill_replica` writes its incident
    #: record (schema :mod:`apex_tpu.resilience.incidents`, with the
    #: router's flight-recorder tail under the validated ``flight``
    #: field).  ``None`` = no artifact; the flight ring records either
    #: way.
    incident_path: Optional[str] = None
    #: declarative SLO objectives (a tuple of
    #: :class:`apex_tpu.obs.slo.SLObjective`) evaluated per replica
    #: over its OWN registry at every fleet step boundary — an
    #: SLO-violating replica loses admission ELIGIBILITY (the
    #: gauge-ranking hook, objective-driven) until its windowed burn
    #: rate recovers; insufficient windows never de-rank a fresh
    #: replica.  ``None`` = ranking only, no objectives.
    slo: Optional[tuple] = None
    #: continuous profiling (an :class:`apex_tpu.obs.contprof.
    #: ContProfConfig`): every decode replica gets its own profiler —
    #: capture phases STAGGERED across replicas (the tracer is
    #: process-global; a colliding window is skipped, not queued) —
    #: and its own :class:`~apex_tpu.obs.contprof.DriftSentinel`
    #: over the replica's registry.  A confirmed drift flips the
    #: replica's ``serve_profile_drift`` gauge (SLO-consumable via
    #: :func:`apex_tpu.obs.contprof.drift_objective`), notes the
    #: router's flight recorder, writes a ``profile-drift`` incident
    #: to ``incident_path``, and DE-RANKS the replica in admission
    #: (preferred last, never hard-blocked: a fleet whose every
    #: replica drifted must still serve).  ``None`` = no profiling.
    contprof: Optional[Any] = None
    #: sentinel band width / confirmation count for ``contprof``
    #: (the PR-13 band rule's fallback default; a caller with a
    #: recorded variance-derived width passes it here)
    contprof_band: float = 0.03
    contprof_k: int = 2

    def __post_init__(self):
        if self.transfer not in ("ship", "recompute"):
            raise ValueError(
                f"transfer={self.transfer!r}; pick 'ship' (KV block "
                f"shipment) or 'recompute' (re-prefill on the decode "
                f"replica)")
        if not 0.0 < self.admit_block_util <= 1.0:
            raise ValueError(
                f"admit_block_util={self.admit_block_util} outside "
                f"(0, 1]")


class PrefillWorker:
    """The prefill slice: a :class:`ServeEngine` whose decode step is
    never dispatched.  ``prefill()`` runs the existing chunked paged
    prefill + first-token sample for ONE request, gathers the slot's
    KV through its page table into the fixed shipment shape, frees
    the slot, and returns the shipment — or the finished output when
    the request ends at its first token (budget 1 / immediate EOS),
    which never needs a decode slice at all."""

    def __init__(self, params, cfg, serve_cfg: ServeConfig,
                 mesh, registry: Optional[obs_metrics.Registry] = None,
                 tracer: Optional[Any] = None):
        # the worker's pool only holds ONE in-flight prompt: one slot,
        # one slot's worth of blocks (+ trash).  Shapes that must agree
        # with the decode replicas (block_size, max_blocks_per_slot,
        # kv_dtype) are taken from the SAME ServeConfig the replicas
        # use, so a shipment always fits its destination.  aot_cache is
        # CLEARED: the engine's probe resolves the DECODE step, which
        # the worker never dispatches — probing here would eagerly
        # compile+export an executable nobody loads, making fleet cold
        # start slower, not faster.
        # prefix_cache is OFF on the worker: its pool holds exactly
        # one transient slot (freed after every gather), and the
        # router already short-circuits prefix-hit requests STRAIGHT
        # to a decode replica before they ever reach this worker —
        # sharing belongs to the replicas, whose installs register
        # shipped blocks in the DESTINATION index via arm().
        self.scfg = dataclasses.replace(
            serve_cfg, num_slots=1,
            num_blocks=serve_cfg.max_blocks_per_slot + 1,
            aot_cache=False, prefix_cache=False)
        self.mesh = mesh
        self.placement = placement(mesh)
        self.eng = ServeEngine(params, cfg, self.scfg,
                               registry=registry or obs_metrics.Registry(),
                               placement=self.placement,
                               tracer=tracer, trace_name="prefill")
        self.trace_counts = {"gather": 0}
        names = [n for n in ("kc", "vc", "ks", "vs")
                 if n in self.eng.carry]
        self._pool_names = names
        self._gather = transfer.make_gather(
            names, trace_counts=self.trace_counts)

    def prefill(self, req: Request):
        """``("done", tokens)`` when the request finished at its first
        sample, else ``("kv", KVShipment)`` with the slot already
        freed (the worker holds nothing between calls)."""
        eng, sched = self.eng, self.eng.sched
        # only the PROMPT's blocks: the worker never decodes, so the
        # generation budget's footprint belongs to the decode slice
        need = -(-len(req.prompt) // sched.block_size)
        blocks = sched.allocator.alloc(need, req)
        sched._install(0, req, blocks)
        eng._run_prefill(0, req)
        if sched.slots[0] is None:
            # finished at the prefill sample (_run_prefill retired it)
            out = eng._outputs.pop(req.uid)
            eng.metrics.tick()
            return ("done", out)
        slot = sched.slots[0]
        first = int(slot.emitted[0])
        plen = int(sched.lengths[0])
        kv = self._gather(eng.carry, jnp.asarray(sched.page_table[0]))
        key = eng.carry["keys"][0]
        shp = KVShipment(request=req, kv=kv, first_token=first,
                         prompt_len=plen, key=key,
                         nbytes=transfer.shipment_bytes(kv, key))
        # free, don't retire: the request's life continues elsewhere
        sched.allocator.free(blocks, req)
        sched._clear(0)
        sched._update_gauges()
        eng.metrics.tick()
        return ("kv", shp)


class DecodeReplica:
    """One decode slice: the existing engine plus the one donated
    install scatter that accepts shipments.  ``alive`` is the router's
    view — a killed replica takes no work and steps no more."""

    def __init__(self, index: int, params, cfg, serve_cfg: ServeConfig,
                 mesh, registry: Optional[obs_metrics.Registry] = None,
                 tracer: Optional[Any] = None):
        self.index = index
        self.mesh = mesh
        self.placement = placement(mesh)
        self.eng = ServeEngine(params, cfg, serve_cfg,
                               registry=registry or obs_metrics.Registry(),
                               placement=self.placement,
                               tracer=tracer,
                               trace_name=f"replica{index}")
        self.alive = True
        self.trace_counts = {"install": 0}
        names = [n for n in ("kc", "vc", "ks", "vs")
                 if n in self.eng.carry]
        self._install = transfer.make_install(
            names, trace_counts=self.trace_counts)
        self._hist = self.eng.metrics.histogram(
            "serve_decode_step_seconds")
        #: histogram window mark taken after the replica's FIRST
        #: decode step (the compile): the p99 the router ranks and
        #: exports is steady-state, exactly how bench.py windows the
        #: same histogram — a compile outlier must not steer
        #: admissions away from a fresh replica for its first 100
        #: steps
        self._p99_window = None

    # -- admission ----------------------------------------------------

    def can_admit(self, req: Request) -> bool:
        """A free slot and the whole footprint coverable, without
        side effects (the router checks BEFORE paying the wire).
        Reclaimable = free + refcount-0 cached prefix blocks — the
        allocator reclaims its LRU cache transparently inside
        ``alloc``, so counting only the free list would wedge a
        replica whose whole pool had parked in the prefix cache."""
        sched = self.eng.sched
        return bool(self.alive and sched.free_slots()
                    and sched.blocks_needed(req)
                    <= sched.allocator.reclaimable_count)

    def admit_shipment(self, shp: KVShipment) -> Optional[int]:
        """Install a prefilled request: allocate its FULL footprint,
        scatter the shipped blocks into this replica's pools through
        the assigned page-table row, drop the PRNG key at the slot,
        and arm the slot for decode — one donated executable across
        every installation (the slot index and row are traced).
        Returns the slot index, or ``None`` when the replica could
        not take the shipment (dead / no slot / capacity race)."""
        eng, sched = self.eng, self.eng.sched
        free = sched.free_slots()
        if not self.alive or not free:
            return None
        req = shp.request
        try:
            blocks = sched.allocator.alloc(sched.blocks_needed(req), req)
        except PoolExhausted:
            return None
        slot = free[0]
        sched._install(slot, req, blocks)
        eng.carry = self._install(
            eng.carry, jnp.asarray(sched.page_table[slot]), shp.kv,
            jnp.int32(slot), shp.key)
        # the install scatter is an admission dispatch like a prefill
        # chunk: bump the engine's contamination marker so a shipment
        # landing inside a replica's capture window discards that
        # window (its scatter ops would misattribute into the decode
        # buckets)
        eng._admission_dispatches += 1
        sched.arm(slot, shp.first_token, shp.prompt_len)
        return slot

    def submit(self, req: Request) -> None:
        """The recompute path: the replica re-prefills locally through
        its own admission machinery (exactly what a transfer miss
        falls back to)."""
        self.eng.submit(req)

    # -- stepping / introspection -------------------------------------

    def step(self) -> Dict[str, np.ndarray]:
        if not self.alive:
            return {}
        out = self.eng.step()
        if self._p99_window is None and self._hist.count > 0:
            self._p99_window = self._hist.state()
        return out

    def idle(self) -> bool:
        return (not self.alive) or self.eng.sched.idle()

    def p99(self) -> float:
        """Steady-state decode-step p99 (first step — the compile —
        windowed out); ``nan`` before any post-window observation."""
        if self._p99_window is None:
            return math.nan
        return self._hist.quantile(0.99, since=self._p99_window)

    def load(self) -> tuple:
        """The admission-control score, read from the obs gauges the
        engine already exports (lower = preferred): outstanding work
        (queue + active slots), then block utilization, then the
        steady-state decode-step p99 this replica has been
        delivering."""
        reg = self.eng.metrics
        q = reg.gauge("serve_queue_depth").value
        occ = reg.gauge("serve_slot_occupancy").value
        util = reg.gauge("serve_block_utilization").value
        p99 = self.p99()
        return (q + occ * self.eng.scfg.num_slots, util,
                0.0 if math.isnan(p99) else p99)


class DisaggRouter:
    """The fleet front door.  ``submit()`` then ``step()``/``run()``
    exactly like a single engine; behind it requests prefill on the
    prefill slice, their KV ships to a decode slice, and the decode
    replicas run the one-compiled-step engine unchanged.

    >>> router = DisaggRouter(params, cfg, ServeConfig(num_slots=4, ...))
    >>> router.submit(Request("a", prompt, max_new_tokens=32))
    >>> outputs = router.run()       # {"a": generated ids}

    ``serve_cfg`` describes ONE decode replica (every replica is
    identical; the prefill worker derives its single-slot config from
    it).  Failure semantics: :meth:`kill_replica` loses a replica's
    device state mid-stream; the router rebuilds each in-flight
    request as a continuation from its streamed-token log (prompt +
    emitted tokens, remaining budget, the PRNG chain re-derived by
    draw count) and re-prefills it elsewhere — the recompute-on-miss
    machinery, pointed at a death instead of a cache miss."""

    def __init__(self, params, cfg, serve_cfg: ServeConfig,
                 router_cfg: Optional[RouterConfig] = None,
                 devices: Optional[Sequence] = None,
                 registry: Optional[obs_metrics.Registry] = None,
                 slices: Optional[FleetSlices] = None,
                 tracer: Optional[Any] = None,
                 flight: Optional[Any] = None):
        self.rcfg = router_cfg or RouterConfig()
        self.scfg = serve_cfg
        #: per-request lifecycle tracer (apex_tpu.obs.reqtrace): the
        #: router MINTS the request id at admission and hands the one
        #: tracer to the prefill worker ("prefill") and every replica
        #: ("replica{i}"); None = tracing off
        self.tracer = tracer
        #: incident flight recorder (apex_tpu.obs.flight): the ring
        #: kill_replica dumps into its incident record; None = off
        self.flight = flight
        self.slices = slices if slices is not None else slice_fleet(
            devices,
            n_prefill_devices=self.rcfg.n_prefill_devices,
            n_decode_replicas=self.rcfg.n_decode_replicas,
            devices_per_replica=self.rcfg.devices_per_replica)
        if len(self.slices.decode) != self.rcfg.n_decode_replicas:
            raise ValueError(
                f"slices carry {len(self.slices.decode)} decode "
                f"replicas, RouterConfig says "
                f"{self.rcfg.n_decode_replicas}")
        self.metrics = registry if registry is not None \
            else obs_metrics.DEFAULT
        self.prefill = PrefillWorker(params, cfg, serve_cfg,
                                     self.slices.prefill,
                                     tracer=tracer)
        self.replicas: List[DecodeReplica] = [
            DecodeReplica(i, params, cfg, serve_cfg, mesh,
                          tracer=tracer)
            for i, mesh in enumerate(self.slices.decode)]
        self.queue: List[Request] = []
        self._outputs: Dict[str, np.ndarray] = {}
        # -- router telemetry (apex_tpu.obs): host numbers recorded at
        # step boundaries — never on any replica's compiled step path
        self._m_queue = self.metrics.gauge(
            "serve_router_queue_depth",
            "requests held by the router (admission control: no "
            "eligible replica under the block-utilization bar)")
        self._m_ship = self.metrics.counter(
            "serve_kv_shipments_total",
            "prefilled requests shipped to a decode replica")
        self._m_bytes = self.metrics.counter(
            "serve_kv_transfer_bytes",
            "device-to-device bytes of shipped prefill KV (pools + "
            "PRNG key; zero under transfer='recompute')")
        self._m_reroute = self.metrics.counter(
            "serve_reroute_total",
            "requests rebuilt from the streamed-token log and "
            "re-prefilled elsewhere after a replica death")
        self._m_rep_q = [
            self.metrics.gauge(
                f"serve_replica{i}_queue_depth",
                f"replica {i} engine-local queue (recompute "
                f"admissions + preemption continuations)")
            for i in range(len(self.replicas))]
        self._m_rep_occ = [
            self.metrics.gauge(
                f"serve_replica{i}_slot_occupancy",
                f"replica {i} active slots / num_slots")
            for i in range(len(self.replicas))]
        self._m_rep_util = [
            self.metrics.gauge(
                f"serve_replica{i}_block_utilization",
                f"replica {i} live KV blocks / usable pool")
            for i in range(len(self.replicas))]
        self._m_rep_p99 = [
            self.metrics.gauge(
                f"serve_replica{i}_decode_p99_seconds",
                f"replica {i} decode-step p99 (from its own "
                f"serve_decode_step_seconds histogram)")
            for i in range(len(self.replicas))]
        # -- prefix sharing (per-replica indexes): mirrors of each
        # replica's own prefix gauges at the same lag-resolved
        # boundary as every fleet gauge above, plus the router's
        # straight-to-decode counter — all host bookkeeping, zero new
        # syncs on any compiled step
        self._m_prefix_direct = None
        self._m_rep_hit: List = []
        self._m_rep_shared: List = []
        if serve_cfg.prefix_cache:
            self._m_prefix_direct = self.metrics.counter(
                "serve_prefix_direct_admissions_total",
                "prefix-hit requests admitted STRAIGHT to a decode "
                "replica — no prefill-slice time, no KV shipment for "
                "the shared span")
            self._m_rep_hit = [
                self.metrics.gauge(
                    f"serve_replica{i}_prefix_hit_rate",
                    f"replica {i} prefix-cache hit rate (mirror of "
                    f"its serve_prefix_hit_rate gauge)")
                for i in range(len(self.replicas))]
            self._m_rep_shared = [
                self.metrics.gauge(
                    f"serve_replica{i}_prefix_shared_blocks",
                    f"replica {i} blocks mapped by more than one slot "
                    f"(mirror of its serve_prefix_shared_blocks "
                    f"gauge)")
                for i in range(len(self.replicas))]
        # -- SLO admission (apex_tpu.obs.slo): one evaluator per
        # replica over its OWN registry, judged at the same boundary
        # _record_metrics already owns — resolved host state only,
        # zero new host syncs on any replica's compiled step
        self.slo_evals = None
        self._m_rep_slo = []
        if self.rcfg.slo:
            from apex_tpu.obs.slo import SLOEvaluator
            self.slo_evals = [SLOEvaluator(rep.eng.metrics,
                                           self.rcfg.slo)
                              for rep in self.replicas]
            self._m_rep_slo = [
                self.metrics.gauge(
                    f"serve_replica{i}_slo_ok",
                    f"replica {i} SLO eligibility (1 = no objective "
                    f"violated in its window; 0 = de-ranked from "
                    f"admission)")
                for i in range(len(self.replicas))]
        # -- continuous profiling (apex_tpu.obs.contprof): one
        # profiler + drift sentinel per replica, phases staggered so
        # fleet windows never collide on the process-global tracer
        self.profilers = None
        self.sentinels = None
        self._m_rep_drift = []
        if self.rcfg.contprof is not None:
            import dataclasses as _dc

            from apex_tpu.obs import contprof as contprof_lib
            n = len(self.replicas)
            stride = max(self.rcfg.contprof.capture_steps + 1,
                         self.rcfg.contprof.capture_every // max(n, 1))
            self.profilers, self.sentinels = [], []
            for i, rep in enumerate(self.replicas):
                sent = contprof_lib.DriftSentinel(
                    band=self.rcfg.contprof_band,
                    k=self.rcfg.contprof_k,
                    registry=rep.eng.metrics,
                    flight=self.flight,
                    incident_path=self.rcfg.incident_path,
                    name="serve")
                cfg_i = _dc.replace(
                    self.rcfg.contprof,
                    phase=self.rcfg.contprof.phase + i * stride)
                self.sentinels.append(sent)
                self.profilers.append(contprof_lib.serve_profiler(
                    rep.eng, config=cfg_i, sentinel=sent))
            self._m_rep_drift = [
                self.metrics.gauge(
                    f"serve_replica{i}_profile_drift",
                    f"replica {i} confirmed-unrecovered op-level "
                    f"drift (mirror of its serve_profile_drift "
                    f"gauge; drifting replicas rank last in "
                    f"admission)")
                for i in range(len(self.replicas))]

    # -- submission ----------------------------------------------------

    def submit(self, req: Request) -> None:
        """Validate against ONE decode replica's shapes
        (:func:`~apex_tpu.serve.scheduler.validate_request` — the
        scheduler's own check; every replica is identical) and
        enqueue, so a request no replica could ever hold is rejected
        here, not deadlocked later."""
        validate_request(req, self.scfg.block_size,
                         self.scfg.max_blocks_per_slot,
                         self.scfg.num_blocks)
        self.queue.append(req)
        if self.tracer is not None:
            # router admission is the request id's birthplace: mint
            # the trace here, then every engine the request touches
            # appends to the SAME lifecycle under its own label
            self.tracer.mint(req.uid)
            self.tracer.record("enqueue", req.uid, "router",
                               queue_depth=len(self.queue))
        self._m_queue.set(float(len(self.queue)))

    # -- routing -------------------------------------------------------

    def _eligible(self, req: Request) -> List[tuple]:
        """``(load, replica)`` for every replica that may take ``req``
        this boundary: alive, a free slot + footprint coverage, block
        utilization under the admission bar, SLO window clean."""
        scored = [((self._drifting(r),) + r.load(), r)
                  for r in self.replicas
                  if r.can_admit(req) and not self._slo_violating(r)]
        return [(load, r) for load, r in scored
                if load[2] < self.rcfg.admit_block_util]

    def _pick_replica(self, req: Request) -> Optional[DecodeReplica]:
        """Least-loaded eligible replica, from the obs gauges: alive,
        a free slot + footprint coverage, block utilization under the
        admission bar; ranked by (outstanding work, utilization,
        decode p99)."""
        eligible = self._eligible(req)
        if not eligible:
            return None
        return min(eligible, key=lambda lr: lr[0])[1]

    def _pick_prefix_replica(self, req: Request):
        """Straight-to-decode probe: ``(replica, matched_tokens)`` for
        the eligible replica whose prefix index covers the most
        leading prompt tokens (load breaks ties), or ``(None, 0)``
        when no index covers any — per-replica indexes, so the probe
        asks each replica's OWN scheduler.  Side-effect-free:
        ``probe_prefix_tokens`` touches no refcounts."""
        best = None
        for load, r in self._eligible(req):
            hit = r.eng.sched.probe_prefix_tokens(req.prompt)
            if hit > 0 and (best is None or (-hit, load) < best[0]):
                best = ((-hit, load), r)
        if best is None:
            return None, 0
        return best[1], -best[0][0]

    def _drifting(self, rep: DecodeReplica) -> bool:
        """True when the replica's drift sentinel holds a confirmed,
        unrecovered op-level drift — it ranks LAST in admission (a
        soft de-rank, not a block: a fleet whose every replica
        drifted must still serve)."""
        if self.sentinels is None:
            return False
        return self.sentinels[rep.index].drifting

    def _slo_violating(self, rep: DecodeReplica) -> bool:
        """True when the replica's LAST boundary evaluation has a
        violated objective — it keeps decoding what it holds, but
        takes no new admissions until the window recovers."""
        if self.slo_evals is None:
            return False
        return self.slo_evals[rep.index].violated()

    def _route_one(self) -> bool:
        """Route the head-of-queue request; False = held (admission
        control: no eligible replica this boundary)."""
        req = self.queue[0]
        # prefix hit → STRAIGHT to the decode replica holding the
        # match: its own admission increfs the shared span and
        # prefills only the unmatched suffix locally — no prefill
        # slice, no shipment for bytes the destination already holds.
        # kill_replica recovery re-enqueues continuations through this
        # same probe, so a rerouted request re-prefills only what the
        # surviving replicas' indexes don't cover.
        hit_rep, hit_tokens = self._pick_prefix_replica(req)
        if hit_rep is not None:
            self.queue.pop(0)
            hit_rep.submit(req)
            self._m_prefix_direct.inc()
            if self.tracer is not None:
                self.tracer.record("prefix_direct", req.uid, "router",
                                   to_replica=hit_rep.index,
                                   matched_tokens=hit_tokens)
            return True
        rep = self._pick_replica(req)
        if rep is None:
            return False
        self.queue.pop(0)
        if self.rcfg.transfer == "recompute":
            rep.submit(req)
            return True
        verdict = self.prefill.prefill(req)
        if verdict[0] == "done":
            self._outputs[req.uid] = verdict[1]
            return True
        shp = transfer.ship(verdict[1], rep.placement)
        if self.tracer is not None:
            self.tracer.record("kv_ship", req.uid, "router",
                               to_replica=rep.index,
                               nbytes=int(shp.nbytes))
        slot = rep.admit_shipment(shp)
        if slot is not None:
            self._m_ship.inc()
            self._m_bytes.inc(shp.nbytes)
            if self.tracer is not None:
                self.tracer.record("kv_install", req.uid,
                                   f"replica{rep.index}", slot=slot)
        else:
            # transfer miss (the capacity check raced a same-boundary
            # admission): recompute-on-miss — the ORIGINAL request
            # re-prefills through the replica's own machinery
            rep.submit(req)
        return True

    def step(self) -> Dict[str, np.ndarray]:
        """One fleet step boundary: route admissions (prefill + ship),
        then one decode step on every live replica; returns the
        requests that finished this boundary."""
        while self.queue and self._route_one():
            pass
        finished: Dict[str, np.ndarray] = {}
        for rep in self.replicas:
            finished.update(rep.step())
        self._outputs.update(finished)
        self._record_metrics()
        return finished

    def _record_metrics(self) -> None:
        self._m_queue.set(float(len(self.queue)))
        for i, rep in enumerate(self.replicas):
            reg = rep.eng.metrics
            self._m_rep_q[i].set(reg.gauge("serve_queue_depth").value)
            self._m_rep_occ[i].set(
                reg.gauge("serve_slot_occupancy").value)
            self._m_rep_util[i].set(
                reg.gauge("serve_block_utilization").value)
            p99 = rep.p99()
            self._m_rep_p99[i].set(0.0 if math.isnan(p99) else p99)
            if self._m_rep_hit:
                self._m_rep_hit[i].set(
                    reg.gauge("serve_prefix_hit_rate").value)
                self._m_rep_shared[i].set(
                    reg.gauge("serve_prefix_shared_blocks").value)
            if self.slo_evals is not None and rep.alive:
                self.slo_evals[i].evaluate()
                self._m_rep_slo[i].set(
                    0.0 if self.slo_evals[i].violated() else 1.0)
            if self.sentinels is not None:
                self._m_rep_drift[i].set(
                    1.0 if self.sentinels[i].drifting else 0.0)
        self.metrics.tick()

    def slo_summary(self) -> "Optional[dict]":
        """Per-replica SLO verdicts from the last boundary (the block
        the serving tools record into their artifacts); ``None`` when
        no objectives are configured."""
        if self.slo_evals is None:
            return None
        return {f"replica{i}": ev.summary()
                for i, ev in enumerate(self.slo_evals)}

    def idle(self) -> bool:
        return not self.queue and all(r.idle() for r in self.replicas)

    def run(self, max_steps: int = 100_000) -> Dict[str, np.ndarray]:
        """Drain the fleet; ``{uid: generated token ids}`` for every
        request ever submitted (prompt not repeated)."""
        steps = 0
        try:
            while not self.idle():
                outstanding = len(self.queue) + sum(
                    r.eng.sched.n_active() + len(r.eng.sched.queue)
                    for r in self.replicas if r.alive)
                self.step()
                steps += 1
                if steps > max_steps:
                    raise RuntimeError(
                        f"router loop exceeded {max_steps} steps with "
                        f"{outstanding} request(s) outstanding")
        finally:
            if self.profilers is not None:
                for prof in self.profilers:
                    prof.abort_window()
        return dict(self._outputs)

    # -- failure semantics --------------------------------------------

    def kill_replica(self, index: int) -> List[str]:
        """Lose replica ``index`` mid-stream (its device state —
        pools, keys — is gone).  Every in-flight request is rebuilt
        from the router's streamed-token log as a continuation
        (original prompt + every token streamed so far, remaining
        budget, PRNG chain re-derived by draw count via
        :func:`~apex_tpu.serve.sampling.advance_key`) and re-queued
        AT THE FRONT to re-prefill on a live replica; the replica's
        engine-local queue re-queues as-is.  Returns the rerouted
        uids; greedy outputs stay bitwise equal to solo
        ``generate()`` through the whole event."""
        rep = self.replicas[index]
        if not rep.alive:
            return []
        rep.alive = False
        if self.profilers is not None:
            # a dead replica steps no more, so its open capture window
            # would hold the process-global capture lock forever and
            # silently stop fleet-wide profiling during exactly the
            # incident the sentinel exists for
            self.profilers[index].abort_window()
        if self.flight is not None:
            self.flight.note("replica_kill", replica=index,
                             active=rep.eng.sched.n_active(),
                             queued=len(rep.eng.sched.queue))
        rerouted: List[Request] = []
        sched = rep.eng.sched
        for slot in range(sched.num_slots):
            s = sched.slots[slot]
            if s is None:
                continue
            req = s.request
            if req.max_new_tokens - len(s.emitted) < 1:
                continue           # retired the same boundary it died
            # one PRNG draw per streamed token (prefill sample
            # included) — the chain position is the draw count, so a
            # lost device key is re-derivable from the seed; the
            # continuation record itself is the scheduler's own
            # (preempt's builder — one contract for both interrupts)
            draws = len(req.prior_tokens) + len(s.emitted)
            key = advance_key(jax.random.PRNGKey(req.seed), draws)
            rerouted.append(
                sched.continuation(slot, np.asarray(key)))
        # engine-local queue (recompute admissions, preemption
        # continuations): nothing emitted since queuing — re-route
        # them unchanged
        rerouted.extend(sched.queue)
        self.queue[:0] = rerouted
        for r in rerouted:
            if self.tracer is not None:
                # every reroute NAMES the killed replica — the TRACE
                # schema rejects a reroute citing a live one
                self.tracer.record("reroute", r.uid, "router",
                                   from_replica=index)
            if self.flight is not None:
                self.flight.note("reroute", uid=r.uid,
                                 from_replica=index)
        self._m_reroute.inc(len(rerouted))
        self._m_queue.set(float(len(self.queue)))
        if self.rcfg.incident_path:
            self._write_kill_incident(index, [r.uid for r in rerouted])
        return [r.uid for r in rerouted]

    def _write_kill_incident(self, index: int,
                             rerouted: List[str]) -> None:
        """The replica death's black box: a schema-valid incident
        record carrying the resolved router metrics AND the flight
        ring's tail (the events that led here — admissions, ships,
        the kill, the reroutes), so the chaos drill interrogates a
        history instead of two end-state gauges."""
        from apex_tpu.resilience import incidents as incidents_lib
        extra: Dict[str, Any] = {
            "artifact": "disagg-router replica-death record",
            "replica": index, "rerouted": rerouted,
            "metrics": self.metrics.snapshot(),
        }
        if self.flight is not None:
            extra["flight"] = self.flight.dump()
        try:
            incidents_lib.write_incident(
                self.rcfg.incident_path, "replica-killed",
                f"decode replica {index} lost mid-stream; "
                f"{len(rerouted)} request(s) rebuilt from the "
                f"streamed-token log and re-prefilled elsewhere",
                [f"replica {index} killed with "
                 f"{len(rerouted)} in-flight/queued request(s)",
                 {"rerouted_uids": rerouted}],
                **extra)
        except Exception:
            import traceback
            traceback.print_exc()   # the drill must not die on its
            #                         own forensics
