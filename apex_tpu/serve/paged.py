"""Paged KV cache: a physical block pool read through per-slot page
tables.

The monolithic decode cache (``apex_tpu.models.generate``) allocates
``(L, B, M, H, D)`` up front — every slot pays ``max_seq`` whether it
holds an 8-token or an 8K-token request, so HBM scales with
``B × max_seq`` instead of live tokens.  This module is the
PagedAttention-style (Kwon et al., SOSP '23) replacement the serve
engine reads through:

- the **pool** is ``(L, num_blocks, block_size, H, D)`` — one physical
  allocation shared by every slot; a sequence owns a list of blocks,
  and memory scales with the tokens actually cached;
- each slot's **page table** row ``(max_blocks_per_slot,)`` maps its
  logical block ``j`` (token positions ``j*block_size ..``) to a
  physical block id, so the device-side read is one gather:
  ``pool[layer][page_table]`` linearizes back to the exact monolithic
  ``(S, M, H, D)`` layout — the indirection is pure data movement, so
  it bitwise-matches the monolithic cache on the same token stream
  (pinned in ``tests/l0/test_serve_paged.py``);
- **physical block 0 is the trash block**: the allocator never hands
  it out, every empty page-table entry points at it, and inactive
  slots' masked writes land there — a scatter needs *some* in-range
  target under XLA's static shapes, and routing to a reserved block
  keeps garbage out of every real sequence without a branch.

Allocation (:class:`BlockAllocator`) is host-side bookkeeping — a free
list with ownership tracking, exercised BETWEEN decode steps by the
scheduler, so the compiled step never sees it.  Eviction is a
scheduler policy built on ``free()`` (preempt-and-recompute, see
:mod:`apex_tpu.serve.scheduler`).

**Cross-request prefix sharing** (vLLM-class prefix caching) extends
the allocator with refcounted, content-addressed blocks:

- a FULL aligned block can be **registered** under a chain hash of its
  token ids (:func:`prefix_block_hashes` — each block's hash chains
  over every preceding block's, so equal token runs at different
  positions never alias).  KV content at position ``p`` is a
  deterministic function of the whole token history ``0..p`` (layer
  ``l > 0`` activations attend over everything before them), so chain-
  hash-equal blocks hold bitwise-identical KV — sharing them is exact,
  int8 scale pools included (quantization is deterministic too);
- a registered block is **immutable**: the prefix index maps its chain
  hash to its physical id, and a write would silently poison every
  current and future reader.  ``assert_writable`` refuses writes into
  registered or multiply-referenced blocks — a writer must
  **copy-on-write fork** instead (allocate a private block, device-copy
  the pool contents, swap its page-table entry, decref the shared one);
- ``free()`` DECREFS: a block returns to the free list only when its
  last holder releases it, and a registered block at refcount 0 parks
  in an LRU **cached** list instead — still matchable by the index, so
  a hot system prompt stays resident across the whole stream.
  ``alloc()`` reclaims LRU cached blocks (unregistering them) before
  raising :class:`PoolExhausted`, which keeps the scheduler's
  preempt-youngest eviction the LAST resort, after every
  refcount-0 cached block is gone.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

#: physical block id reserved as the write target for masked/inactive
#: lanes; never allocated, never mapped by a live page-table entry
TRASH_BLOCK = 0


class PoolExhausted(RuntimeError):
    """Raised by :meth:`BlockAllocator.alloc` when the pool cannot
    serve the request; the scheduler catches it to drive eviction."""


def chain_seed(block_size: int) -> bytes:
    """Root of every prefix hash chain: a domain tag binding the block
    size, so the same tokens under a different block geometry never
    alias."""
    return hashlib.sha256(b"apex-tpu-prefix:%d" % block_size).digest()


def chain_step(h: bytes, tokens: Sequence[int]) -> bytes:
    """Extend chain hash ``h`` by one FULL block of token ids."""
    return hashlib.sha256(
        h + b"".join(int(t).to_bytes(8, "little", signed=True)
                     for t in tokens)).digest()


def prefix_block_hashes(tokens: Sequence[int],
                        block_size: int) -> List[bytes]:
    """Chain hashes of every FULL aligned block of ``tokens``: entry
    ``i`` is ``sha256(hash[i-1] || tokens[i*bs:(i+1)*bs])`` seeded
    with a domain tag and the block size, so a block's identity covers
    its ENTIRE token history — equal token runs at different positions
    (or under different block sizes) never alias.  Only full blocks
    hash: the partial tail is always private to its slot."""
    out: List[bytes] = []
    h = chain_seed(block_size)
    for i in range(len(tokens) // block_size):
        h = chain_step(h, tokens[i * block_size:(i + 1) * block_size])
        out.append(h)
    return out


class BlockAllocator:
    """Host-side refcounted free-list allocator over the physical
    block pool, with an optional content-addressed prefix index (see
    the module docstring for the sharing model).

    Invariants (enforced, tested):

    - block 0 (:data:`TRASH_BLOCK`) is never allocated, shared, or
      registered;
    - ``alloc`` never hands out a live block; ``free`` decrefs and
      rejects blocks the caller doesn't hold (double-free and
      cross-owner frees raise ``ValueError``, atomically);
    - a registered block is immutable (``assert_writable`` refuses it)
      and parks in the LRU cached list at refcount 0 instead of the
      free list; ``alloc`` reclaims cached blocks LRU-first before
      raising :class:`PoolExhausted`;
    - ``free_count + live_count + cached_count == num_blocks - 1`` at
      all times.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 physical blocks (1 trash + 1 usable), got "
                f"{num_blocks}")
        self.num_blocks = num_blocks
        # pop() hands out low ids first — deterministic layouts in tests
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        #: block -> holder list; refcount == len (one entry per slot
        #: mapping the block; the same holder may not hold twice)
        self._refs: Dict[int, List[object]] = {}
        #: content addressing: registered block -> chain hash, and the
        #: prefix index chain hash -> block (live or cached)
        self._hash: Dict[int, bytes] = {}
        self._index: Dict[bytes, int] = {}
        #: refcount-0 registered blocks, least-recently-freed first —
        #: the LRU eviction order alloc() reclaims in
        self._cached: "OrderedDict[int, None]" = OrderedDict()
        #: lifetime telemetry the prefix artifacts/gauges read
        self.cached_evictions = 0

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def live_count(self) -> int:
        return len(self._refs)

    @property
    def cached_count(self) -> int:
        return len(self._cached)

    @property
    def reclaimable_count(self) -> int:
        """Blocks an ``alloc`` can hand out right now: the free list
        plus every refcount-0 cached block (reclaimed LRU-first)."""
        return len(self._free) + len(self._cached)

    @property
    def shared_count(self) -> int:
        """Blocks currently mapped by MORE than one holder — the
        ``serve_prefix_shared_blocks`` gauge's raw value."""
        return sum(1 for hs in self._refs.values() if len(hs) > 1)

    def refcount(self, block: int) -> int:
        return len(self._refs.get(block, ()))

    def is_registered(self, block: int) -> bool:
        return block in self._hash

    def alloc(self, n: int, owner: object) -> List[int]:
        """``n`` private (refcount-1, unregistered) block ids now held
        by ``owner``; reclaims LRU cached blocks once the free list
        runs dry, and raises :class:`PoolExhausted` (allocating — and
        reclaiming — nothing) when ``n`` exceeds even that."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > self.reclaimable_count:
            raise PoolExhausted(
                f"need {n} blocks, {len(self._free)} free + "
                f"{len(self._cached)} cached "
                f"(pool {self.num_blocks}, 1 reserved)")
        blocks: List[int] = []
        for _ in range(n):
            if self._free:
                blocks.append(self._free.pop())
            else:
                # LRU-over-refcount==0: the least-recently-freed
                # cached block loses its registration and is reused —
                # BEFORE the scheduler ever preempts a live request
                victim, _ = self._cached.popitem(last=False)
                del self._index[self._hash.pop(victim)]
                self.cached_evictions += 1
                blocks.append(victim)
        for b in blocks:
            self._refs[b] = [owner]
        return blocks

    def free(self, blocks: Sequence[int], owner: object) -> None:
        """Decref ``blocks``; every block must currently be held by
        ``owner`` (the whole call is rejected atomically otherwise — a
        bad free must not half-release a sequence).  A block whose
        LAST reference drops returns to the free list, or — when
        registered — parks in the LRU cached list, still matchable."""
        for b in blocks:
            if not any(h is owner for h in self._refs.get(b, ())):
                raise ValueError(
                    f"block {b} not owned by {owner!r} "
                    f"(holders={self._refs.get(b)!r}) — double free or "
                    f"cross-owner free")
        for b in blocks:
            hs = self._refs[b]
            for i, h in enumerate(hs):
                if h is owner:
                    hs.pop(i)
                    break
            if not hs:
                del self._refs[b]
                if b in self._hash:
                    self._cached[b] = None      # most-recently-freed last
                else:
                    self._free.append(b)

    def share(self, block: int, owner: object) -> None:
        """Incref a REGISTERED block for ``owner`` (a prefix-index
        hit mapping it into another slot's page table); revives a
        cached (refcount-0) block back to live."""
        if block not in self._hash:
            raise ValueError(
                f"block {block} is not registered — only "
                f"content-addressed blocks can be shared")
        if any(h is owner for h in self._refs.get(block, ())):
            raise ValueError(
                f"block {block} already held by {owner!r}")
        self._cached.pop(block, None)
        self._refs.setdefault(block, []).append(owner)

    def register(self, block: int, chain_hash: bytes) -> bool:
        """Mark a LIVE block content-addressed under ``chain_hash``
        (immutable from here on; parks in the cached list at refcount
        0).  Returns False — leaving the block a plain private one —
        when the index already maps the hash to ANOTHER block (the
        first registration stays canonical).  Re-registering the same
        block under the same hash is a no-op; under a different hash
        it raises (content addressing would lie)."""
        if block == TRASH_BLOCK or block not in self._refs:
            raise ValueError(
                f"block {block} is not live — register after alloc, "
                f"before free")
        have = self._hash.get(block)
        if have is not None:
            if have != chain_hash:
                raise ValueError(
                    f"block {block} already registered under a "
                    f"different chain hash")
            return True
        if chain_hash in self._index:
            return False
        self._hash[block] = chain_hash
        self._index[chain_hash] = block
        return True

    def lookup(self, chain_hash: bytes) -> Optional[int]:
        """The live-or-cached block registered under ``chain_hash``,
        or None — the prefix index probe (no side effects)."""
        return self._index.get(chain_hash)

    def assert_writable(self, block: int, owner: object) -> None:
        """Refuse a write into a block the writer doesn't privately
        own: registered (content-addressed — immutable) or
        multiply-referenced blocks need a copy-on-write fork first,
        and writing someone else's block is always a bug."""
        if not any(h is owner for h in self._refs.get(block, ())):
            raise ValueError(
                f"block {block} not held by {owner!r} — cannot write")
        if len(self._refs[block]) > 1:
            raise ValueError(
                f"block {block} is shared ({len(self._refs[block])} "
                f"holders) — fork it (copy-on-write) before writing")
        if block in self._hash:
            raise ValueError(
                f"block {block} is registered (content-addressed, "
                f"immutable) — fork it (copy-on-write) before writing")

    def owned_by(self, owner: object) -> List[int]:
        return sorted(b for b, hs in self._refs.items()
                      if any(h is owner for h in hs))


def make_pools(num_layers: int, num_blocks: int, block_size: int,
               num_heads: int, head_dim: int, dtype):
    """Zeroed ``(kc, vc)`` block pools
    ``(L, num_blocks, block_size, H, D)``."""
    shape = (num_layers, num_blocks, block_size, num_heads, head_dim)
    kc = jnp.zeros(shape, dtype)
    return kc, jnp.zeros_like(kc)


def make_scale_pools(num_layers: int, num_blocks: int, block_size: int):
    """Zeroed ``(ks, vs)`` per-position scale pools
    ``(L, num_blocks, block_size)`` f32 — the int8 KV format's
    companion to :func:`make_pools` (one absmax scale per cached
    token-slot per layer; unwritten slots dequantize to exact zeros)."""
    shape = (num_layers, num_blocks, block_size)
    ks = jnp.zeros(shape, jnp.float32)
    return ks, jnp.zeros_like(ks)


def gather_slot_kv(pool_l: jax.Array, page_table: jax.Array) -> jax.Array:
    """Linearize every slot's cache through its page table:
    ``pool_l (num_blocks, bs, H, D)`` gathered by ``page_table (S,
    max_blocks)`` → ``(S, max_blocks*bs, H, D)`` — position ``p`` of
    slot ``s`` lands at ``[s, p]`` exactly as the monolithic layout
    stores it (the bitwise-parity contract)."""
    g = pool_l[page_table]                   # (S, MB, bs, H, D)
    s, mb, bs, h, d = g.shape
    return g.reshape(s, mb * bs, h, d)


def gather_slot_scales(pool_s: jax.Array,
                       page_table: jax.Array) -> jax.Array:
    """Linearize the per-position scale pool the same way:
    ``pool_s (num_blocks, bs)`` gathered by ``page_table (S,
    max_blocks)`` → ``(S, max_blocks*bs)`` — scale ``[s, p]`` belongs
    to cache position ``[s, p]`` of :func:`gather_slot_kv`'s output."""
    g = pool_s[page_table]                   # (S, MB, bs)
    s, mb, bs = g.shape
    return g.reshape(s, mb * bs)


def token_write_coords(lengths: jax.Array, page_table: jax.Array,
                       block_size: int, active: jax.Array):
    """``(blocks, offsets)`` each ``(S,)`` for writing every slot's
    NEXT token (global position ``lengths[s]``) into the pool; inactive
    slots route to :data:`TRASH_BLOCK`."""
    mb = page_table.shape[1]
    idx = jnp.clip(lengths // block_size, 0, mb - 1)
    blocks = jnp.take_along_axis(page_table, idx[:, None], axis=1)[:, 0]
    blocks = jnp.where(active, blocks, TRASH_BLOCK)
    return blocks, lengths % block_size


def paged_attention(q: jax.Array, k_lin: jax.Array, v_lin: jax.Array,
                    valid: jax.Array, scale: float,
                    k_scale=None, v_scale=None) -> jax.Array:
    """fp32-softmax attention of ``q (S, Lq, H, D)`` against the
    linearized per-slot caches ``(S, M, H, D)`` under the boolean mask
    ``valid (S, Lq, M)`` (True = attend; a per-slot batch dim so every
    slot attends to its own live length).  Delegates to
    :func:`apex_tpu.models.generate._attn_cached` — the serve-vs-solo
    bitwise-parity contract requires the math to exist exactly once.
    ``k_scale``/``v_scale`` ``(S, M)`` are the int8 KV format's
    per-position dequant scales (from :func:`gather_slot_scales`)."""
    from apex_tpu.models.generate import _attn_cached
    return _attn_cached(q, k_lin, v_lin, valid, scale,
                        k_scale=k_scale, v_scale=v_scale)
