"""Paged KV cache: a physical block pool read through per-slot page
tables.

The monolithic decode cache (``apex_tpu.models.generate``) allocates
``(L, B, M, H, D)`` up front — every slot pays ``max_seq`` whether it
holds an 8-token or an 8K-token request, so HBM scales with
``B × max_seq`` instead of live tokens.  This module is the
PagedAttention-style (Kwon et al., SOSP '23) replacement the serve
engine reads through:

- the **pool** is ``(L, num_blocks, block_size, H, D)`` — one physical
  allocation shared by every slot; a sequence owns a list of blocks,
  and memory scales with the tokens actually cached;
- each slot's **page table** row ``(max_blocks_per_slot,)`` maps its
  logical block ``j`` (token positions ``j*block_size ..``) to a
  physical block id, so the device-side read is one gather:
  ``pool[layer][page_table]`` linearizes back to the exact monolithic
  ``(S, M, H, D)`` layout — the indirection is pure data movement, so
  it bitwise-matches the monolithic cache on the same token stream
  (pinned in ``tests/l0/test_serve_paged.py``);
- **physical block 0 is the trash block**: the allocator never hands
  it out, every empty page-table entry points at it, and inactive
  slots' masked writes land there — a scatter needs *some* in-range
  target under XLA's static shapes, and routing to a reserved block
  keeps garbage out of every real sequence without a branch.

Allocation (:class:`BlockAllocator`) is host-side bookkeeping — a free
list with ownership tracking, exercised BETWEEN decode steps by the
scheduler, so the compiled step never sees it.  Eviction is a
scheduler policy built on ``free()`` (preempt-and-recompute, see
:mod:`apex_tpu.serve.scheduler`).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp

#: physical block id reserved as the write target for masked/inactive
#: lanes; never allocated, never mapped by a live page-table entry
TRASH_BLOCK = 0


class PoolExhausted(RuntimeError):
    """Raised by :meth:`BlockAllocator.alloc` when the pool cannot
    serve the request; the scheduler catches it to drive eviction."""


class BlockAllocator:
    """Host-side free-list allocator over the physical block pool.

    Invariants (enforced, tested):

    - block 0 (:data:`TRASH_BLOCK`) is never allocated;
    - a block has at most one owner; ``alloc`` never hands out a live
      block, ``free`` rejects blocks the owner doesn't hold
      (double-free and cross-owner frees raise ``ValueError``);
    - ``free_count + live_count == num_blocks - 1`` at all times.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 physical blocks (1 trash + 1 usable), got "
                f"{num_blocks}")
        self.num_blocks = num_blocks
        # pop() hands out low ids first — deterministic layouts in tests
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._owner: Dict[int, object] = {}

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def live_count(self) -> int:
        return len(self._owner)

    def alloc(self, n: int, owner: object) -> List[int]:
        """``n`` physical block ids now owned by ``owner``; raises
        :class:`PoolExhausted` (allocating nothing) when fewer than
        ``n`` are free."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} blocks, {len(self._free)} free "
                f"(pool {self.num_blocks}, 1 reserved)")
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._owner[b] = owner
        return blocks

    def free(self, blocks: Sequence[int], owner: object) -> None:
        """Return ``blocks`` to the pool; every block must currently be
        owned by ``owner`` (the whole call is rejected atomically
        otherwise — a bad free must not half-release a sequence)."""
        for b in blocks:
            if self._owner.get(b) is not owner:
                raise ValueError(
                    f"block {b} not owned by {owner!r} "
                    f"(owner={self._owner.get(b)!r}) — double free or "
                    f"cross-owner free")
        for b in blocks:
            del self._owner[b]
            self._free.append(b)

    def owned_by(self, owner: object) -> List[int]:
        return sorted(b for b, o in self._owner.items() if o is owner)


def make_pools(num_layers: int, num_blocks: int, block_size: int,
               num_heads: int, head_dim: int, dtype):
    """Zeroed ``(kc, vc)`` block pools
    ``(L, num_blocks, block_size, H, D)``."""
    shape = (num_layers, num_blocks, block_size, num_heads, head_dim)
    kc = jnp.zeros(shape, dtype)
    return kc, jnp.zeros_like(kc)


def make_scale_pools(num_layers: int, num_blocks: int, block_size: int):
    """Zeroed ``(ks, vs)`` per-position scale pools
    ``(L, num_blocks, block_size)`` f32 — the int8 KV format's
    companion to :func:`make_pools` (one absmax scale per cached
    token-slot per layer; unwritten slots dequantize to exact zeros)."""
    shape = (num_layers, num_blocks, block_size)
    ks = jnp.zeros(shape, jnp.float32)
    return ks, jnp.zeros_like(ks)


def gather_slot_kv(pool_l: jax.Array, page_table: jax.Array) -> jax.Array:
    """Linearize every slot's cache through its page table:
    ``pool_l (num_blocks, bs, H, D)`` gathered by ``page_table (S,
    max_blocks)`` → ``(S, max_blocks*bs, H, D)`` — position ``p`` of
    slot ``s`` lands at ``[s, p]`` exactly as the monolithic layout
    stores it (the bitwise-parity contract)."""
    g = pool_l[page_table]                   # (S, MB, bs, H, D)
    s, mb, bs, h, d = g.shape
    return g.reshape(s, mb * bs, h, d)


def gather_slot_scales(pool_s: jax.Array,
                       page_table: jax.Array) -> jax.Array:
    """Linearize the per-position scale pool the same way:
    ``pool_s (num_blocks, bs)`` gathered by ``page_table (S,
    max_blocks)`` → ``(S, max_blocks*bs)`` — scale ``[s, p]`` belongs
    to cache position ``[s, p]`` of :func:`gather_slot_kv`'s output."""
    g = pool_s[page_table]                   # (S, MB, bs)
    s, mb, bs = g.shape
    return g.reshape(s, mb * bs)


def token_write_coords(lengths: jax.Array, page_table: jax.Array,
                       block_size: int, active: jax.Array):
    """``(blocks, offsets)`` each ``(S,)`` for writing every slot's
    NEXT token (global position ``lengths[s]``) into the pool; inactive
    slots route to :data:`TRASH_BLOCK`."""
    mb = page_table.shape[1]
    idx = jnp.clip(lengths // block_size, 0, mb - 1)
    blocks = jnp.take_along_axis(page_table, idx[:, None], axis=1)[:, 0]
    blocks = jnp.where(active, blocks, TRASH_BLOCK)
    return blocks, lengths % block_size


def paged_attention(q: jax.Array, k_lin: jax.Array, v_lin: jax.Array,
                    valid: jax.Array, scale: float,
                    k_scale=None, v_scale=None) -> jax.Array:
    """fp32-softmax attention of ``q (S, Lq, H, D)`` against the
    linearized per-slot caches ``(S, M, H, D)`` under the boolean mask
    ``valid (S, Lq, M)`` (True = attend; a per-slot batch dim so every
    slot attends to its own live length).  Delegates to
    :func:`apex_tpu.models.generate._attn_cached` — the serve-vs-solo
    bitwise-parity contract requires the math to exist exactly once.
    ``k_scale``/``v_scale`` ``(S, M)`` are the int8 KV format's
    per-position dequant scales (from :func:`gather_slot_scales`)."""
    from apex_tpu.models.generate import _attn_cached
    return _attn_cached(q, k_lin, v_lin, valid, scale,
                        k_scale=k_scale, v_scale=v_scale)
