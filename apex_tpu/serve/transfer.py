"""KV shipment between mesh slices: the transfer path of the
disaggregated prefill/decode fleet.

A disaggregated fleet runs prefill (compute-bound, bursty) and decode
(HBM-bound, steady) on SEPARATE mesh slices — disjoint device subsets
of one platform, each wrapped in its own :class:`jax.sharding.Mesh`
(SNIPPETS [2]/[3]: ``NamedSharding`` placement over
``create_device_mesh``-style slices; ``--xla_force_host_platform_
device_count`` makes the whole topology CPU-testable).  A request
prefills once on the prefill slice and decodes on a decode slice, so
its KV cache must MOVE between block pools that live on different
devices.  This module is that move:

- **slice layout** (:func:`slice_fleet`): carve the platform's devices
  into one prefill slice plus N decode slices, disjoint by
  construction; each replica places its params and pools with
  ``NamedSharding(mesh, P())`` (replicated within the slice — the
  within-slice model sharding story composes later, the BETWEEN-slice
  topology is what this module owns).  Committed placement is what
  pins execution: jax runs a program where its donated carry lives;

- **shipment format** (:class:`KVShipment`): one FIXED-shape bundle
  per prefilled request — every pool of the engine carry gathered
  through the slot's page-table row into ``(L, max_blocks_per_slot,
  block_size, ...)`` (trash-padded rows gather trash-block garbage
  that the destination scatter routes straight back into ITS trash
  block), plus the first sampled token, the prompt length, and the
  slot's live PRNG key.  Fixed shape is the point: one gather program
  and one install program serve every prompt length, so transfer
  never retraces a replica (the one-trace pins in
  ``tests/l0/test_serve_disagg.py``);

- **the wire** (:func:`ship`): ``jax.device_put`` of the bundle onto
  the destination slice's placement — the device-to-device copy
  (ICI/DMA on a real fleet, a buffer copy on the CPU platform) —
  with the byte count returned for the router's
  ``serve_kv_transfer_bytes`` counter;

- **install** (:func:`make_install`): one donated scatter on the
  destination replica writes the shipped blocks into its own pool at
  the page-table row its allocator assigned and drops the PRNG key
  into the keys carry at a TRACED slot index (a static slot would
  mint one executable per slot).

Recompute-on-miss is the fallback, not a mode of this module: when a
shipment cannot be installed (or the router runs ``transfer=
"recompute"``), the ORIGINAL request goes to the decode replica's own
admission path and re-prefills there through the existing
preempt-and-recompute machinery — bitwise the same tokens, paid in
decode-slice compute instead of transfer bytes
(:mod:`apex_tpu.serve.router`).

Prefix sharing composes ON TOP of shipment, not inside it.  A
prefix-HIT request never reaches this module: the router admits it
straight to the decode replica holding the match, which prefills only
the unmatched suffix locally — zero shipped bytes for the shared
span.  A shipped (miss) request still feeds the sharing machinery at
its destination: ``admit_shipment`` arms through the scheduler, whose
``arm()`` registers the installed full blocks in the DESTINATION
replica's content index, so the next same-prefix request hits there.
The shipment format and the gather/install programs are untouched.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


@dataclasses.dataclass(frozen=True)
class FleetSlices:
    """The fleet's device topology: ONE prefill slice plus
    ``len(decode)`` decode slices, pairwise-disjoint device subsets of
    one platform.  ``placement(mesh)`` is the committed sharding a
    replica pins its params/pools with."""

    prefill: Mesh
    decode: tuple

    @property
    def n_devices(self) -> int:
        return len(self.prefill.devices.ravel()) + sum(
            len(m.devices.ravel()) for m in self.decode)

    def describe(self) -> dict:
        """JSON-friendly slice table (the SERVE_DISAGG artifact's
        ``topology`` block cites it)."""
        return {
            "prefill": [d.id for d in self.prefill.devices.ravel()],
            "decode": [[d.id for d in m.devices.ravel()]
                       for m in self.decode],
        }


def placement(mesh: Mesh) -> NamedSharding:
    """Replicated-within-the-slice placement: the committed sharding
    that pins a replica's arrays (and therefore its compiled programs)
    to its own slice."""
    return NamedSharding(mesh, PartitionSpec())


def slice_fleet(devices: Optional[Sequence] = None,
                n_prefill_devices: int = 1,
                n_decode_replicas: int = 2,
                devices_per_replica: int = 1) -> FleetSlices:
    """Carve ``devices`` (default: every local device) into the fleet
    topology.  Slices are DISJOINT by construction — a prefill burst
    must not steal a decode replica's cycles, which is the whole
    disaggregation claim — and a short device list is an error, never
    a silent overlap."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    need = n_prefill_devices + n_decode_replicas * devices_per_replica
    if n_prefill_devices < 1 or n_decode_replicas < 1 \
            or devices_per_replica < 1:
        raise ValueError(
            f"need >= 1 prefill device, >= 1 decode replica, >= 1 "
            f"device per replica; got {n_prefill_devices}/"
            f"{n_decode_replicas}/{devices_per_replica}")
    if len(devices) < need:
        raise ValueError(
            f"fleet topology needs {need} devices "
            f"({n_prefill_devices} prefill + {n_decode_replicas} x "
            f"{devices_per_replica} decode), have {len(devices)} — "
            f"overlapping slices would fake the disaggregation")
    prefill = Mesh(np.array(devices[:n_prefill_devices]), ("slice",))
    decode = []
    off = n_prefill_devices
    for _ in range(n_decode_replicas):
        decode.append(Mesh(
            np.array(devices[off:off + devices_per_replica]),
            ("slice",)))
        off += devices_per_replica
    return FleetSlices(prefill=prefill, decode=tuple(decode))


def place_tree(tree: Any, sharding: NamedSharding) -> Any:
    """``device_put`` every leaf onto ``sharding`` (committed — the
    arrays, and every program consuming them, belong to the slice)."""
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


# ---------------------------------------------------------------------------
# shipment
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class KVShipment:
    """One prefilled request, packaged for a decode slice: the
    fixed-shape per-pool gathers ``{name: (L, max_blocks_per_slot,
    block_size, ...)}``, the first sampled token, the prompt length
    (= the destination slot's starting ``lengths`` entry), the live
    per-request PRNG key ``(2,) uint32``, and the original
    :class:`~apex_tpu.serve.scheduler.Request` (the destination
    allocates the request's FULL footprint — remaining budget
    included — exactly as its own admission path would)."""

    request: Any
    kv: Dict[str, jax.Array]
    first_token: int
    prompt_len: int
    key: jax.Array
    #: device-visible bytes of the kv bundle (counted at gather time,
    #: recorded by the router when the wire copy actually happens)
    nbytes: int = 0

    @property
    def uid(self) -> str:
        return self.request.uid


def shipment_bytes(kv: Dict[str, jax.Array], key: jax.Array) -> int:
    """Bytes the wire moves for one shipment (pools + key; the token
    and length ride the host-side control message)."""
    total = int(np.asarray(key).nbytes)
    for arr in kv.values():
        total += arr.size * arr.dtype.itemsize
    return total


def make_gather(pool_names: Sequence[str],
                trace_counts: Optional[dict] = None,
                count_key: str = "gather"):
    """The prefill worker's one compiled extraction: gather every pool
    of ``carry`` through a page-table ``row (max_blocks_per_slot,)``
    into the fixed shipment shape ``(L, mb, bs, ...)``.  Trash-padded
    row entries gather trash-block contents — garbage by contract,
    masked out at the destination by the slot's ``lengths`` validity
    window and re-routed into the destination's own trash block by
    the install scatter.  ``trace_counts[count_key]`` increments per
    python trace (the one-trace pin's probe)."""
    names = tuple(pool_names)

    def gather(carry, row):
        if trace_counts is not None:
            trace_counts[count_key] += 1
        return {n: jnp.take(carry[n], row, axis=1) for n in names}

    return jax.jit(gather)


def make_install(pool_names: Sequence[str],
                 trace_counts: Optional[dict] = None,
                 count_key: str = "install"):
    """The decode replica's one compiled installation: scatter every
    shipped pool into the replica's own pools at its allocator's
    page-table ``row`` and drop the PRNG ``key`` into the keys carry
    at a TRACED ``slot`` index.  The carry is DONATED — installation
    updates the pools in place, exactly like a decode step — and
    every index is traced, so one executable serves every slot, every
    block layout, and every request of the replica's lifetime.
    ``trace_counts[count_key]`` increments per python trace."""
    names = tuple(pool_names)

    def install(carry, row, shipped, slot, key):
        if trace_counts is not None:
            trace_counts[count_key] += 1
        out = dict(carry)
        for n in names:
            # duplicate trash entries in `row` collapse onto the trash
            # block (last-writer-wins over garbage — block 0 is never
            # read through a live page table)
            out[n] = carry[n].at[:, row].set(shipped[n])
        out["keys"] = carry["keys"].at[slot].set(key)
        return out

    return jax.jit(install, donate_argnums=(0,))


def ship(shipment: KVShipment, dst: NamedSharding) -> KVShipment:
    """The wire: copy the shipment's device payload onto the
    destination slice's placement (device-to-device — jax moves
    buffers directly between devices of one platform) and return the
    shipment re-pointed at the destination copies, ``nbytes``
    stamped for the router's ``serve_kv_transfer_bytes`` counter."""
    kv = {n: jax.device_put(a, dst) for n, a in shipment.kv.items()}
    key = jax.device_put(shipment.key, dst)
    return dataclasses.replace(
        shipment, kv=kv, key=key,
        nbytes=shipment_bytes(kv, key))
