"""apex_tpu.serve — continuous-batching decode serving.

Production serving over the training checkpoint: a fixed-slot
continuous-batching scheduler (:mod:`apex_tpu.serve.scheduler`), a
paged block-pool KV cache read through per-slot page tables
(:mod:`apex_tpu.serve.paged`), a fused on-device sampling epilogue
(:mod:`apex_tpu.serve.sampling`), the engine tying them into ONE
compiled decode step that never retraces across admission, retirement,
or preemption (:mod:`apex_tpu.serve.engine`), and the disaggregated
fleet layer running prefill and decode on SEPARATE mesh slices behind
one KV-shipping router (:mod:`apex_tpu.serve.transfer`,
:mod:`apex_tpu.serve.router`).  See ``docs/source/serving.rst``.
"""

from apex_tpu.serve.engine import ServeConfig, ServeEngine
from apex_tpu.serve.paged import (
    BlockAllocator,
    PoolExhausted,
    TRASH_BLOCK,
    gather_slot_kv,
    make_pools,
    paged_attention,
    token_write_coords,
)
from apex_tpu.serve.router import (
    DecodeReplica,
    DisaggRouter,
    PrefillWorker,
    RouterConfig,
)
from apex_tpu.serve.sampling import advance_key, sample_tokens
from apex_tpu.serve.scheduler import Request, SlotScheduler
from apex_tpu.serve.spec import SpecConfig, SpecEngine, truncated_draft
from apex_tpu.serve.transfer import (
    FleetSlices,
    KVShipment,
    ship,
    slice_fleet,
)

__all__ = [
    "BlockAllocator",
    "DecodeReplica",
    "DisaggRouter",
    "FleetSlices",
    "KVShipment",
    "PoolExhausted",
    "PrefillWorker",
    "Request",
    "RouterConfig",
    "ServeConfig",
    "ServeEngine",
    "SlotScheduler",
    "SpecConfig",
    "SpecEngine",
    "TRASH_BLOCK",
    "advance_key",
    "gather_slot_kv",
    "make_pools",
    "paged_attention",
    "sample_tokens",
    "ship",
    "slice_fleet",
    "token_write_coords",
    "truncated_draft",
]
