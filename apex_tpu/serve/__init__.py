"""apex_tpu.serve — continuous-batching decode serving.

Production serving over the training checkpoint: a fixed-slot
continuous-batching scheduler (:mod:`apex_tpu.serve.scheduler`), a
paged block-pool KV cache read through per-slot page tables
(:mod:`apex_tpu.serve.paged`), a fused on-device sampling epilogue
(:mod:`apex_tpu.serve.sampling`), and the engine tying them into ONE
compiled decode step that never retraces across admission, retirement,
or preemption (:mod:`apex_tpu.serve.engine`).  See
``docs/source/serving.rst``.
"""

from apex_tpu.serve.engine import ServeConfig, ServeEngine
from apex_tpu.serve.paged import (
    BlockAllocator,
    PoolExhausted,
    TRASH_BLOCK,
    gather_slot_kv,
    make_pools,
    paged_attention,
    token_write_coords,
)
from apex_tpu.serve.sampling import sample_tokens
from apex_tpu.serve.scheduler import Request, SlotScheduler

__all__ = [
    "BlockAllocator",
    "PoolExhausted",
    "Request",
    "ServeConfig",
    "ServeEngine",
    "SlotScheduler",
    "TRASH_BLOCK",
    "gather_slot_kv",
    "make_pools",
    "paged_attention",
    "sample_tokens",
    "token_write_coords",
]
