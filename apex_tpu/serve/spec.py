"""Speculative decoding for the serve engine: a draft proposes, the
target verifies a whole block of tokens in ONE compiled step.

int8 KV (PR 8) halved decode's HBM traffic; this module converts spare
decode bandwidth into tokens/step the classic way (Leviathan et al.
2023; Chen & Borgeaud et al. 2023): a cheap **draft** model proposes
``k`` tokens per slot, the **target** scores all of them in one
``b×(k+1)`` forward — the chunked multi-token cached path PR 6 built
for prefill, pointed at generation — and per-slot acceptance keeps a
prefix and emits the target's own token at the first rejection.  Every
accepted token saves one full decode dispatch of the target.

**Exactness, the strong form.**  Classic speculative decoding argues
distribution-level exactness: rejection sampling over draft/target
probabilities leaves the OUTPUT DISTRIBUTION exactly the target's.
This engine pins something stronger — bitwise STREAM equality with the
non-speculative engine (and, greedily, with solo
:func:`apex_tpu.models.generate.generate`) — by exploiting a property
the serve engine already has: per-slot PRNG chains advance exactly one
draw per emitted token (:func:`apex_tpu.serve.sampling.advance_key`).
The verifier therefore KNOWS every draw the non-spec engine would have
made: position ``i`` of the verified block is sampled with the slot
chain's key at position ``n+i`` through the very same fused epilogue
(:func:`~apex_tpu.serve.sampling.sample_tokens`, one ``(S, V)`` row at
a time — the exact program shape the baseline step samples with).
Acceptance is *token match*: proposal ``d_i`` is accepted iff it
equals the target's own draw ``e_i``; at the first mismatch the
target's draw IS the emitted token (the "resample" — drawn from the
target distribution at the rejected position, as rejection sampling
requires).  Emitted streams are then token-for-token the non-spec
engine's — greedy slots match solo ``generate()`` bitwise (the
tie-stable :func:`~apex_tpu.models.generate.greedy_argmax` +
:func:`~apex_tpu.models.generate.pin_logits` discipline), sampled
slots match the baseline engine bitwise, and the distribution-
exactness argument is a one-liner: the stream *is* the target's
stream.  The draft model can be arbitrarily wrong and only ever costs
acceptance rate, never correctness.

**KV rollback without copies.**  The verify step writes target KV for
all ``k+1`` fed tokens at positions ``L..L+k`` through the paged block
pool.  When only ``j <= k`` proposals are accepted the per-slot
LENGTH simply rewinds to ``L+j+1``: positions beyond hold stale
rejected-token KV, but the validity mask (``cache position <=
slot length``) re-masks them and the next round's writes overwrite
them before they could ever be unmasked — the same trash-block
discipline that already covers inactive slots.  No copy, no scatter,
no shape change.

**Static shapes, two programs.**  The draft's ``k``-token proposal
loop is ONE compiled step (a ``lax.scan`` over ``k`` single-token
paged decode steps on the draft's own pools, sharing the slot page
tables), and the verifier is ONE compiled ``b×(k+1)`` step; both are
shaped by config alone, so admission/retirement/preemption never
retrace either (``trace_counts`` pins it at runtime; the graph-lint
``serve_verify`` lane pins the verifier statically).

The draft shares the target's page-table geometry: its pools are
``(L_draft, num_blocks, block_size, H_draft, D_draft)`` indexed by the
SAME page-table rows, so block accounting stays the scheduler's one
allocator.  :func:`truncated_draft` builds the classic self-
speculative draft — the target checkpoint's first ``n`` layers with
the shared embedding/head — which needs no second training run and
keeps proposals correlated with the target.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.generate import (
    _ln,
    _stack_layer_params,
    pin_logits,
)
from apex_tpu.models.gpt import GPTConfig
from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.obs import spans
from apex_tpu.ops.rope import rope_tables
from apex_tpu.serve import paged, sampling
from apex_tpu.serve.engine import (
    ServeConfig,
    ServeEngine,
    _paged_block,
    chunk_prefill_math,
)
from apex_tpu.serve.paged import TRASH_BLOCK


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Speculative-decoding knobs.  ``k`` proposals per round: each
    verify round emits between 1 (immediate rejection — the baseline
    rate) and ``k + 1`` (all accepted + the bonus draw) tokens per
    active slot, so tokens/step scales with the draft's acceptance
    rate and never drops below the non-speculative engine's."""

    k: int = 4

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"k={self.k}; speculative decoding needs "
                             f">= 1 draft proposal per round")


def truncated_draft(params, cfg: GPTConfig, num_layers: int):
    """``(draft_params, draft_cfg)``: the target checkpoint's first
    ``num_layers`` transformer blocks with the SHARED embedding,
    final norm, and lm head — the classic self-speculative draft
    (layer-skip): no second checkpoint, free vocabulary agreement,
    and proposals stay correlated with the target because they share
    most of its weights."""
    if not 1 <= num_layers < cfg.num_layers:
        raise ValueError(
            f"truncated draft needs 1 <= num_layers < {cfg.num_layers}; "
            f"got {num_layers}")
    stacked = _stack_layer_params(params, cfg.num_layers)
    head = jax.tree.map(lambda x: x[:num_layers], stacked)
    draft = {k: v for k, v in params.items()
             if not k.startswith("block_") and k != "layers"}
    draft["layers"] = {"block": head}
    return draft, dataclasses.replace(cfg, num_layers=num_layers)


class SpecEngine(ServeEngine):
    """The serve engine with speculative decoding: same scheduler,
    same paged pools, same submit/run front door — ``step()`` runs one
    draft round + one verify round instead of one decode step.

    >>> draft_p, draft_cfg = truncated_draft(params, cfg, 1)
    >>> eng = SpecEngine(params, cfg, ServeConfig(), draft_p, draft_cfg,
    ...                  SpecConfig(k=4))
    >>> eng.submit(Request("a", prompt_ids, max_new_tokens=16))
    >>> outputs = eng.run()

    The base engine's single-token decode step still exists (it is the
    graph-lint ``serve_step`` lane's program) but is never dispatched;
    ``ServeConfig.aot_cache`` is therefore forced off for the base
    engine so a fleet-wide ``APEX_TPU_AOT_CACHE`` cannot make startup
    eagerly compile+export an executable nobody runs (the prefill
    worker plays the same trick) — the draft/verify steps' own AOT
    entries are a follow-up, not an accident of inheriting the wrong
    program's cache key."""

    def __init__(self, params, cfg: GPTConfig, serve_cfg: ServeConfig,
                 draft_params, draft_cfg: GPTConfig,
                 spec_cfg: Optional[SpecConfig] = None,
                 registry: Optional[obs_metrics.Registry] = None,
                 placement: Optional[Any] = None,
                 tracer: Optional[Any] = None,
                 trace_name: str = "engine"):
        if draft_cfg.vocab_size != cfg.vocab_size:
            raise ValueError(
                f"draft vocab {draft_cfg.vocab_size} != target vocab "
                f"{cfg.vocab_size}: proposals would not be token ids "
                f"of the target's vocabulary")
        super().__init__(params, cfg,
                         dataclasses.replace(serve_cfg, aot_cache=False),
                         registry=registry, placement=placement,
                         tracer=tracer, trace_name=trace_name)
        self.spec = spec_cfg or SpecConfig()
        self.dcfg = draft_cfg
        self.dstacked = _stack_layer_params(draft_params,
                                            draft_cfg.num_layers)
        self.dtop = {k: v for k, v in draft_params.items()
                     if not k.startswith("block_") and k != "layers"}
        d_head = draft_cfg.hidden_size // draft_cfg.num_heads
        dtype = self.dtop["tok_emb"]["embedding"].dtype
        # the draft cache stays DENSE even under an int8 target cache:
        # the draft only produces proposals (guesses), so its cache
        # precision buys acceptance rate, not correctness — and the
        # draft is small, so its bytes are not the regime's bottleneck
        dkc, dvc = paged.make_pools(
            draft_cfg.num_layers, serve_cfg.num_blocks,
            serve_cfg.block_size, draft_cfg.num_heads, d_head, dtype)
        self.dcarry = {"kc": dkc, "vc": dvc}
        if placement is not None:
            from apex_tpu.serve.transfer import place_tree
            self.dtop = place_tree(self.dtop, placement)
            self.dstacked = place_tree(self.dstacked, placement)
            self.dcarry = place_tree(self.dcarry, placement)
        self.trace_counts.update(draft=0, verify=0, draft_prefill=0)
        self._draft_step = jax.jit(self._draft_body,
                                   donate_argnums=(2,))
        self._verify_step = jax.jit(self._verify_body,
                                    donate_argnums=(2,))
        self._draft_prefill = jax.jit(self._draft_prefill_body,
                                      donate_argnums=(2, 3))
        # -- speculative telemetry (apex_tpu.obs): host numbers from
        # the (S,) n_emit fetch the host loop needs anyway, recorded
        # at the existing step boundary — lag-resolved like every
        # other serve metric, zero added host syncs
        self._m_rounds = self.metrics.counter(
            "serve_spec_rounds_total",
            "draft+verify speculative rounds dispatched")
        self._m_draft_steps = self.metrics.counter(
            "serve_spec_draft_steps_total",
            "draft single-token steps (k + 1 per round inside the one "
            "compiled program: k proposals + the cache-fill step for "
            "the last proposal's KV)")
        self._m_proposed = self.metrics.counter(
            "serve_spec_proposed_total",
            "draft tokens proposed (k x active slots per round)")
        self._m_accepted = self.metrics.counter(
            "serve_spec_accepted_total",
            "draft tokens the target's own draws confirmed")
        self._m_accept_rate = self.metrics.gauge(
            "serve_spec_acceptance_rate",
            "accepted / proposed over the engine's whole history "
            "(tokens per verify round = 1 + k x this)")

    # -- compiled bodies ----------------------------------------------

    def _draft_body(self, dtop, dstacked, dcarry, keys, tokens, lengths,
                    active, page_table, temp, top_k, top_p):
        """ONE compiled program proposing ``k`` tokens per slot: a
        ``lax.scan`` of ``k + 1`` single-token paged decode steps of
        the DRAFT model over the draft pools (same page tables, same
        masks as the target's step).  The extra step exists for its
        CACHE WRITE alone: a fully-accepted round advances the slot
        to ``L + k + 1``, so the draft cache must hold position
        ``L + k`` (the last proposal's KV) — otherwise every
        all-accept round leaves a permanent never-overwritten hole
        the draft attends zeros through for the rest of the stream;
        the extra step's sampled token is discarded.  Writes past
        the slot's context reach route to the trash block (``pos <
        m`` joins the active mask — at the end of a budget the
        clip+modulo coordinates would otherwise WRAP onto live
        positions).  Proposals are drawn through the
        same fused epilogue with the slot's REAL key ladder — the
        keys the verifier will draw with — so a draft that models the
        target well reproduces the target's sampled draws too, which
        is what acceptance measures.  The ladder is recomputed by the
        verifier; the slot chain itself only advances per EMITTED
        token, so draft usage costs no chain positions."""
        self.trace_counts["draft"] += 1
        with spans.span("serve/spec_draft", registry=self.metrics):
            c = self.dcfg
            bs = self.scfg.block_size
            head_dim = c.hidden_size // c.num_heads
            scale = 1.0 / float(head_dim) ** 0.5
            m = self.scfg.max_blocks_per_slot * bs
            kc, vc = dcarry["kc"], dcarry["vc"]

            def one_step(carry, i):
                tok, keys, kc, vc = carry
                pos = lengths + i                          # (S,)
                x = dtop["tok_emb"]["embedding"][tok][:, None]
                cos, sin = rope_tables(pos[:, None], head_dim,
                                       c.rope_theta)
                blocks, offs = paged.token_write_coords(
                    pos, page_table, bs, active & (pos < m))
                valid = ((jnp.arange(m)[None, :] <= pos[:, None])
                         & active[:, None])[:, None, :]

                def layer(lcarry, inputs):
                    x, kc, vc = lcarry
                    p_l, layer_i = inputs
                    x, kc, vc, _ks, _vs, _err = _paged_block(
                        x, p_l, c, kc, vc, layer_i, cos, sin, blocks,
                        offs, page_table, valid, scale)
                    return (x, kc, vc), None

                (x, kc, vc), _ = jax.lax.scan(
                    layer, (x, kc, vc),
                    (dstacked, jnp.arange(c.num_layers)))
                x = _ln(x[:, -1:], dtop["ln_f"], c.layer_norm_eps)
                logits = pin_logits(x[:, 0] @ dtop["lm_head"]["kernel"])
                nxt, keys = sampling.sample_tokens(logits, keys, temp,
                                                   top_k, top_p)
                nxt = jnp.where(active, nxt, tok)
                return (nxt, keys, kc, vc), nxt

            (_, _, kc, vc), proposals = jax.lax.scan(
                one_step, (tokens, keys, kc, vc),
                jnp.arange(self.spec.k + 1))
            # step k's token is discarded (it ran for the cache write
            # at position L+k); (k, S) -> (S, k)
            return {"kc": kc, "vc": vc}, \
                jnp.moveaxis(proposals[:self.spec.k], 0, 1)

    def _verify_body(self, top, stacked, carry, proposals, tokens,
                     lengths, active, page_table, temp, top_k, top_p):
        """The ``b×(k+1)`` verifier — ONE compiled step: feed every
        slot ``[last_tok, d_1..d_k]`` at positions ``L..L+k`` through
        the chunked multi-token cached path (KV written for all rows,
        causal-vs-cache mask per row), draw the target's token at
        every position with the slot's key ladder, and accept the
        longest proposal prefix the draws confirm.  Returns ``(carry',
        candidates (S, k+1), n_emit (S,))``: the host emits
        ``candidates[s, :n_emit[s]]`` — accepted proposals plus the
        target's own draw at the first rejection (or the bonus draw
        when everything was accepted)."""
        self.trace_counts["verify"] += 1
        with spans.span("serve/spec_verify", registry=self.metrics):
            c = self.cfg
            bs = self.scfg.block_size
            mb = self.scfg.max_blocks_per_slot
            k = self.spec.k
            kc, vc, keys = carry["kc"], carry["vc"], carry["keys"]
            ks, vs = carry.get("ks"), carry.get("vs")
            head_dim = c.hidden_size // c.num_heads
            scale = 1.0 / float(head_dim) ** 0.5
            s_ = tokens.shape[0]
            m = mb * bs

            q_tokens = jnp.concatenate([tokens[:, None], proposals],
                                       axis=1)              # (S, k+1)
            positions = lengths[:, None] + jnp.arange(k + 1)  # (S, k+1)
            x = top["tok_emb"]["embedding"][q_tokens]       # (S,k+1,E)
            cos, sin = rope_tables(positions, head_dim, c.rope_theta)
            flat_pos = positions.reshape(-1)                # (S*(k+1),)
            rows = jnp.repeat(jnp.arange(s_), k + 1)
            blocks = page_table[rows, jnp.clip(flat_pos // bs, 0,
                                               mb - 1)]
            # rows past the slot's context reach write to TRASH: at
            # the end of a request's budget ``L + k`` can exceed the
            # last allocated position, and the clip+modulo coordinate
            # would WRAP onto a live position — silently corrupting
            # history the in-range rows attend to in this very step
            # (their writes land first, reads gather after).  Those
            # overflow rows' own draws are garbage but can never be
            # emitted: the budget cap retires the slot before them.
            blocks = jnp.where(jnp.repeat(active, k + 1)
                               & (flat_pos < m), blocks, TRASH_BLOCK)
            offs = flat_pos % bs
            # row i attends to cache positions <= its own global
            # position (history + causal-within-block, exactly the
            # chunked-prefill mask); inactive lanes mask out
            valid = (jnp.arange(m)[None, None, :]
                     <= positions[:, :, None]) \
                & active[:, None, None]                     # (S,k+1,M)

            def layer(lcarry, inputs):
                x, kc, vc, ks, vs = lcarry
                p_l, layer_i = inputs
                x, kc, vc, ks, vs, _err = _paged_block(
                    x, p_l, c, kc, vc, layer_i, cos, sin, blocks, offs,
                    page_table, valid, scale, ks=ks, vs=vs)
                return (x, kc, vc, ks, vs), None

            (x, kc, vc, ks, vs), _ = jax.lax.scan(
                layer, (x, kc, vc, ks, vs),
                (stacked, jnp.arange(c.num_layers)))
            x = _ln(x, top["ln_f"], c.layer_norm_eps)       # (S,k+1,E)
            logits = pin_logits(
                x @ top["lm_head"]["kernel"])               # (S,k+1,V)

            # the target's draw at every position, one (S, V) row at a
            # time through the SAME fused epilogue the baseline step
            # samples with (same program shape per row, same key
            # ladder -> bitwise the draws the non-spec engine makes)
            def draw(keys, logits_row):
                toks, nk = sampling.sample_tokens(logits_row, keys,
                                                  temp, top_k, top_p)
                return nk, (toks, nk)

            _, (cand, key_ladder) = jax.lax.scan(
                draw, keys, jnp.moveaxis(logits, 1, 0))
            # cand (k+1, S): cand[i] = target token at position L+i+1;
            # accepted prefix = proposals the draws confirm
            matches = cand[:k] == jnp.moveaxis(proposals, 0, 1)  # (k,S)
            accepted = jnp.cumprod(
                matches.astype(jnp.int32), axis=0).sum(0)   # (S,) = j
            n_emit = jnp.where(active, accepted + 1, 0)
            # slot key after its LAST emitted draw: ladder[j] is the
            # key state after drawing cand[j] = the (j+1)-th emission
            new_keys = jnp.take_along_axis(
                key_ladder, accepted[None, :, None], axis=0)[0]
            new_keys = jnp.where(active[:, None], new_keys, keys)
            out = {"kc": kc, "vc": vc, "keys": new_keys}
            if ks is not None:
                out["ks"], out["vs"] = ks, vs
            return out, jnp.moveaxis(cand, 0, 1), n_emit

    def _draft_prefill_body(self, dtop, dstacked, kc, vc, table_row,
                            chunk_ids, start, n_valid):
        """The draft's prompt prefill: one ``(1, prefill_chunk)``
        chunk written through the slot's page table into the DRAFT
        pools — the SAME chunked-prefill math as the engine's chunk
        (:func:`apex_tpu.serve.engine.chunk_prefill_math`, one copy
        of the coordinate/mask discipline), just the draft model over
        dense pools; the logits are discarded (only the KV is needed
        so the first draft proposal attends to the prompt) and XLA
        dead-code-eliminates the head matmul."""
        self.trace_counts["draft_prefill"] += 1
        with spans.span("serve/spec_draft_prefill",
                        registry=self.metrics):
            kc, vc, _ks, _vs, _logits, _err = chunk_prefill_math(
                self.dcfg, self.scfg.block_size,
                self.scfg.max_blocks_per_slot, dtop, dstacked, kc, vc,
                None, None, table_row, chunk_ids, start, n_valid)
            return kc, vc

    # -- host loop -----------------------------------------------------

    def _run_prefill(self, slot, req) -> None:
        """Admission: prefill the DRAFT pools over the same prompt
        chunks, then the target prefill + first-token sample exactly
        as the base engine does (continuations — preemption resumes,
        replica-kill reroutes — ride the same path, so the draft
        cache is rebuilt wherever the target's is)."""
        cpc = self.scfg.prefill_chunk
        prompt = np.asarray(req.prompt, np.int32)
        n = len(prompt)
        padded = np.zeros((-(-n // cpc)) * cpc, np.int32)
        padded[:n] = prompt
        table_row = jnp.asarray(self.sched.page_table[slot])
        dkc, dvc = self.dcarry["kc"], self.dcarry["vc"]
        for j in range(0, len(padded), cpc):
            dkc, dvc = self._draft_prefill(
                self.dtop, self.dstacked, dkc, dvc, table_row,
                jnp.asarray(padded[None, j:j + cpc]),
                jnp.int32(j), jnp.int32(min(cpc, n - j)))
        self.dcarry = {"kc": dkc, "vc": dvc}
        super()._run_prefill(slot, req)

    def step(self) -> Dict[str, np.ndarray]:
        """One speculative step boundary: admit/evict, ONE draft
        round (k proposals per slot), ONE verify round, then emit
        1..k+1 tokens per active slot through the scheduler's normal
        per-token bookkeeping (budget/EOS checked per token, so a
        mid-block finish retires exactly like the baseline)."""
        self._admit_and_evict()
        sched = self.sched
        if not sched.active.any():
            return {}
        # same continuous-profiler contract as the base step(): a
        # captured round (draft + verify dispatches) records into
        # serve_profiled_step_seconds, never the gated histogram
        in_window = self._profiler_begin()
        t0 = time.perf_counter()
        args = (jnp.asarray(sched.last_tok), jnp.asarray(sched.lengths),
                jnp.asarray(sched.active), jnp.asarray(sched.page_table),
                jnp.asarray(sched.temperature), jnp.asarray(sched.top_k),
                jnp.asarray(sched.top_p))
        self.dcarry, proposals = self._draft_step(
            self.dtop, self.dstacked, self.dcarry,
            self.carry["keys"], *args)
        self.carry, cand, n_emit = self._verify_step(
            self.top, self.stacked, self.carry, proposals, *args)
        cand = np.asarray(cand)
        n_emit = np.asarray(n_emit)
        self._observe_step_wall(time.perf_counter() - t0, in_window)
        n_act = int(sched.active.sum())
        k = self.spec.k
        self._m_rounds.inc()
        self._m_draft_steps.inc(k + 1)
        self._m_proposed.inc(k * n_act)
        self._m_accepted.inc(int((n_emit - 1)[n_emit > 0].sum()))
        if self._m_proposed.value:
            self._m_accept_rate.set(
                self._m_accepted.value / self._m_proposed.value)
        self._steps_dispatched += 1
        finished: Dict[str, np.ndarray] = {}
        emitted = 0
        for slot in range(sched.num_slots):
            if not sched.active[slot]:
                continue
            uid = sched.slots[slot].request.uid
            slot_emitted = 0
            retired = None
            for t in range(int(n_emit[slot])):
                emitted += 1
                slot_emitted += 1
                if sched.record_token(slot, int(cand[slot, t])):
                    retired = sched.retire(slot)
                    break
            if self.tracer is not None:
                # the speculative round's per-slot attribution: the
                # draft's proposals and the verify outcome (accepted
                # count + emitted tokens incl. the target's own draw)
                # — all host numbers off the (S,) n_emit fetch the
                # loop needs anyway
                self.tracer.record(
                    "spec_draft", uid, self.trace_name,
                    step=self._steps_dispatched, proposed=k)
                self.tracer.record(
                    "spec_verify", uid, self.trace_name,
                    step=self._steps_dispatched,
                    accepted=int(n_emit[slot]) - 1,
                    tokens=slot_emitted)
            if retired is not None:
                finished[retired[0]] = retired[1]
                if self.tracer is not None:
                    self.tracer.record(
                        "retire", retired[0], self.trace_name,
                        tokens_out=int(retired[1].shape[0]))
        self._m_tokens.inc(emitted)
        self._outputs.update(finished)
        self.metrics.tick()
        return finished
