"""Continuous-batching decode engine over the paged KV cache.

One compiled decode step serves the whole request stream.  The step
function is shaped by ``ServeConfig`` alone — ``num_slots`` lanes, the
``(L, num_blocks, block_size, H, D)`` pools, ``max_blocks_per_slot``
page-table columns — and every per-request quantity (tokens, lengths,
page-table rows, sampling knobs, the active mask) is a TRACED array
mutated between steps by the scheduler, so admission and retirement
never change a shape and XLA never retraces (``trace_counts`` pins it
at runtime; the graph-lint serve lane pins it statically).

Step anatomy (all device, one dispatch per generated token per batch):

1. embed every slot's pending token at its own global position
   (per-slot rope tables);
2. layer scan (one compiled body): qkv projection, rope, paged cache
   write at ``(layer, page_table[slot, t // bs], t % bs)`` — inactive
   lanes write to the trash block — then attention of the 1-token
   query against the page-table-gathered per-slot caches under the
   per-slot validity mask (:func:`apex_tpu.serve.paged.paged_attention`,
   op-for-op the monolithic decode math);
3. the fused sampling epilogue (:mod:`apex_tpu.serve.sampling`) draws
   every slot's next token inside the step and the PRNG keys ride the
   donated carry — the host fetches only the ``(S,)`` token ids it
   must stream anyway.

The pools, page tables and keys are DONATED carries: the step updates
them in place, the engine holds only the returned handles.

Prefill is admitted in fixed-size chunks (``prefill_chunk`` tokens,
padded, one compiled program regardless of prompt length) writing
through the same page table — the chunked analog of
:func:`apex_tpu.models.generate._forward_cached`'s chunked-prefill
path, so a request enters mid-stream without a full-sequence recompute
and without disturbing the running batch's shapes.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.generate import (
    _ln,
    _stack_layer_params,
    pin_logits,
)
from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.obs import spans
from apex_tpu.models.gpt import GPTConfig
from apex_tpu.ops.rope import apply_rope, rope_tables
from apex_tpu.serve import paged, sampling
from apex_tpu.serve.paged import TRASH_BLOCK
from apex_tpu.serve.scheduler import Request, SlotScheduler


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Shapes of the compiled serving step.  ``num_blocks`` includes
    the reserved trash block, so ``num_blocks - 1`` blocks are usable;
    per-slot context is ``max_blocks_per_slot * block_size`` tokens.
    ``kv_dtype=None`` stores KV in the parameter dtype (bf16 under the
    O2 serving cast — DECODE_DECOMPOSE_r01 attributes the b8 decode
    step to cache reads, so the cache dtype IS the ceiling knob; int8
    KV rides the fp8/int8 roadmap item)."""

    num_slots: int = 4
    block_size: int = 16
    num_blocks: int = 33
    max_blocks_per_slot: int = 8
    prefill_chunk: int = 16
    kv_dtype: Optional[Any] = None
    #: cross-request prefix-cache KV sharing (scheduler module
    #: docstring has the model): admission probes the allocator's
    #: content index, matched full blocks map in by refcount and their
    #: prefill chunks are never dispatched; a full-prompt match
    #: device-copies ONE block (copy-on-write fork) and re-dispatches
    #: one token.  Sharing is host-side page-table construction plus
    #: that one extra executable — the compiled decode/prefill steps
    #: are untouched and every stream stays bitwise-equal to solo
    #: ``generate()``.  Default ON; the disaggregated prefill worker
    #: runs with it off (single transient slot — nothing to share).
    prefix_cache: bool = True
    #: directory of the content-addressed AOT executable cache
    #: (:mod:`apex_tpu.analysis.export`).  When set — explicitly, or
    #: fleet-wide via the ``APEX_TPU_AOT_CACHE`` env var when this
    #: field is ``None`` — engine startup PROBES the cache for the
    #: compiled decode step: a verified key hit loads the serialized
    #: executable instead of paying XLA compilation (the dominant
    #: scale-out latency of a serving replica); a miss (or a corrupted
    #: entry, skipped with a warning) compiles fresh, relints under
    #: the export gate, and populates the cache for the next replica.
    #: ``None`` with no env var keeps the plain jit path; ``False``
    #: disables probing outright, env var included (the disaggregated
    #: prefill worker's engine never runs the decode step the probe
    #: would compile).
    aot_cache: Optional[Any] = None

    @property
    def int8_kv(self) -> bool:
        """True when ``kv_dtype`` selects the int8 KV format ("int8"
        or ``jnp.int8``): int8 pools + per-position f32 scale pools,
        quantize-on-write, dequant fused into the attention read
        (:mod:`apex_tpu.quant.int8`) — half the cache bytes of bf16,
        the ~2x lift of the HBM-bound decode ceiling."""
        if self.kv_dtype is None:
            return False
        if isinstance(self.kv_dtype, str):
            return self.kv_dtype == "int8"
        return jnp.dtype(self.kv_dtype) == jnp.int8


def _paged_block(x, p_l, cfg: GPTConfig, kc, vc, layer_i, cos, sin,
                 blocks, offs, table, valid, scale, ks=None, vs=None):
    """One transformer block over ``x (B, Lq, E)`` reading/writing the
    paged pools — op-for-op the math of
    :func:`apex_tpu.models.generate._block` (the bitwise-parity
    contract with solo ``generate()`` lives or dies here; keep the
    three in sync through THIS one function).  The decode step calls
    it at ``(B=num_slots, Lq=1)``, the prefill chunk at ``(B=1,
    Lq=chunk)``; either way the per-token write coordinates are the
    flattened ``blocks``/``offs`` ``(B*Lq,)`` and ``valid`` is the
    ``(B, Lq, M)`` causal-vs-cache mask.  ``ks``/``vs`` are the int8
    format's ``(L, num_blocks, bs)`` scale pools (None = dense)."""
    c = cfg
    head_dim = c.hidden_size // c.num_heads
    b, lq = x.shape[0], x.shape[1]
    h = _ln(x, p_l["ln1"], c.layer_norm_eps)
    qkv = h @ p_l["attention"]["qkv"]["kernel"] \
        + p_l["attention"]["qkv"]["bias"].astype(h.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, lq, c.num_heads, head_dim)
    k = k.reshape(b, lq, c.num_heads, head_dim)
    v = v.reshape(b, lq, c.num_heads, head_dim)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    kg_scale = vg_scale = None
    err = None
    if ks is not None:
        from apex_tpu.quant import int8 as int8_lib
        qk, sk = int8_lib.quantize_kv(
            k.reshape(b * lq, c.num_heads, head_dim))
        qv, sv = int8_lib.quantize_kv(
            v.reshape(b * lq, c.num_heads, head_dim))
        # per-write relative quantization error — the admission-time
        # KV-quality gauge's raw signal (device scalar; resolved with
        # lag by the registry, never a host sync on the step path)
        kf = k.reshape(b * lq, c.num_heads, head_dim).astype(jnp.float32)
        vf = v.reshape(b * lq, c.num_heads, head_dim).astype(jnp.float32)
        num = (jnp.mean(jnp.abs(
                   kf - int8_lib.dequantize_int8(qk, sk[:, None, None])))
               + jnp.mean(jnp.abs(
                   vf - int8_lib.dequantize_int8(qv, sv[:, None, None]))))
        den = jnp.mean(jnp.abs(kf)) + jnp.mean(jnp.abs(vf)) + 1e-12
        err = num / den
        kc = kc.at[layer_i, blocks, offs].set(qk)
        vc = vc.at[layer_i, blocks, offs].set(qv)
        ks = ks.at[layer_i, blocks, offs].set(sk)
        vs = vs.at[layer_i, blocks, offs].set(sv)
        kg_scale = paged.gather_slot_scales(
            jax.lax.dynamic_index_in_dim(ks, layer_i, 0,
                                         keepdims=False), table)
        vg_scale = paged.gather_slot_scales(
            jax.lax.dynamic_index_in_dim(vs, layer_i, 0,
                                         keepdims=False), table)
    else:
        kc = kc.at[layer_i, blocks, offs].set(
            k.reshape(b * lq, c.num_heads, head_dim).astype(kc.dtype))
        vc = vc.at[layer_i, blocks, offs].set(
            v.reshape(b * lq, c.num_heads, head_dim).astype(vc.dtype))
    kg = paged.gather_slot_kv(
        jax.lax.dynamic_index_in_dim(kc, layer_i, 0, keepdims=False),
        table)
    vg = paged.gather_slot_kv(
        jax.lax.dynamic_index_in_dim(vc, layer_i, 0, keepdims=False),
        table)
    o = paged.paged_attention(q, kg, vg, valid, scale,
                              k_scale=kg_scale, v_scale=vg_scale)
    o = o.reshape(b, lq, c.hidden_size)
    x = x + (o @ p_l["attention"]["out"]["kernel"]
             + p_l["attention"]["out"]["bias"].astype(o.dtype))
    h = _ln(x, p_l["ln2"], c.layer_norm_eps)
    h = h @ p_l["ffn_in"]["kernel"] \
        + p_l["ffn_in"]["bias"].astype(h.dtype)
    h = jax.nn.gelu(h)
    x = x + (h @ p_l["ffn_out"]["kernel"]
             + p_l["ffn_out"]["bias"].astype(h.dtype))
    return x, kc, vc, ks, vs, err


def chunk_prefill_math(cfg: GPTConfig, block_size: int,
                       max_blocks_per_slot: int, top, stacked, kc, vc,
                       ks, vs, table_row, chunk_ids, start, n_valid):
    """One ``(1, C)`` prompt chunk written through a slot's page-table
    row at global positions ``start..``, returning ``(kc, vc, ks, vs,
    last-valid-token logits (1, V), kv_err)``.  Rows past ``n_valid``
    are padding: their writes route to the trash block and their
    outputs are never read.  The ONE copy of the chunked-prefill
    coordinate/mask discipline — the engine's prefill chunk AND the
    speculative draft's prompt prefill (a different model over its
    own pools, which discards the logits so XLA dead-code-eliminates
    the head matmul) both delegate here; the parity-critical paged
    write/mask logic must not fork per caller."""
    c = cfg
    bs = block_size
    mb = max_blocks_per_slot
    head_dim = c.hidden_size // c.num_heads
    scale = 1.0 / float(head_dim) ** 0.5
    _, lq = chunk_ids.shape
    m = mb * bs

    x = top["tok_emb"]["embedding"][chunk_ids]             # (1,C,E)
    pos = start + jnp.arange(lq)                           # (C,)
    cos, sin = rope_tables(pos[None, :], head_dim, c.rope_theta)
    in_chunk = jnp.arange(lq) < n_valid
    blocks = jnp.where(
        in_chunk, table_row[jnp.clip(pos // bs, 0, mb - 1)],
        TRASH_BLOCK)
    offs = pos % bs
    # causal-vs-cache mask: cache slots <= the row's global
    # position (history AND in-chunk causality at once)
    valid = (jnp.arange(m)[None, :] <= pos[:, None])[None]  # (1,C,M)

    def layer(lcarry, inputs):
        x, kc, vc, ks, vs, esum = lcarry
        p_l, layer_i = inputs
        x, kc, vc, ks, vs, err = _paged_block(
            x, p_l, c, kc, vc, layer_i, cos, sin, blocks, offs,
            table_row[None], valid, scale, ks=ks, vs=vs)
        esum = esum + (err if err is not None else 0.0)
        return (x, kc, vc, ks, vs, esum), None

    (x, kc, vc, ks, vs, esum), _ = jax.lax.scan(
        layer, (x, kc, vc, ks, vs, jnp.asarray(0.0, jnp.float32)),
        (stacked, jnp.arange(c.num_layers)))
    x_last = jax.lax.dynamic_slice_in_dim(x, n_valid - 1, 1, axis=1)
    x_last = _ln(x_last, top["ln_f"], c.layer_norm_eps)
    logits = pin_logits(
        x_last[:, 0] @ top["lm_head"]["kernel"])           # (1,V)
    return kc, vc, ks, vs, logits, esum / c.num_layers


class ServeEngine:
    """Continuous-batching serving over a GPT training checkpoint (the
    same parameter tree :func:`apex_tpu.models.generate.generate`
    decodes — no weight conversion).

    >>> eng = ServeEngine(params, cfg, ServeConfig())
    >>> eng.submit(Request("a", prompt_ids, max_new_tokens=16))
    >>> outputs = eng.run()          # {"a": generated token ids}

    ``submit`` may be called at any time (between ``step()`` calls of a
    live loop); ``run()`` drains queue and slots.
    """

    def __init__(self, params, cfg: GPTConfig, serve_cfg: ServeConfig,
                 registry: Optional[obs_metrics.Registry] = None,
                 placement: Optional[Any] = None,
                 tracer: Optional[Any] = None,
                 trace_name: str = "engine",
                 profiler: Optional[Any] = None):
        self.cfg = cfg
        self.scfg = serve_cfg
        #: continuous profiler (:mod:`apex_tpu.obs.contprof`) — when
        #: set (usually via :func:`apex_tpu.obs.contprof.
        #: serve_profiler`), ``step()`` drives its
        #: ``step_begin``/``step_end`` hooks and steps inside a
        #: capture window record their latency into
        #: ``serve_profiled_step_seconds`` INSTEAD of
        #: ``serve_decode_step_seconds`` — SLO burn rates and the
        #: bench latency gates never judge a profiled step.  The
        #: compiled program is untouched either way (the
        #: contprof-instrumented serve lane stays syncs-clean,
        #: OBS_r03's evidence).
        self.profiler = profiler
        #: admission-dispatch cursor the profiler uses to discard
        #: contaminated capture windows — counts EVERY non-decode-step
        #: executable dispatched into this engine's stream: prefill
        #: chunks here, and the fleet's KV-install scatters
        #: (``DecodeReplica.admit_shipment`` bumps it), whose
        #: instruction names collide with the decode program's
        self._admission_dispatches = 0
        #: per-request lifecycle tracer (apex_tpu.obs.reqtrace) + this
        #: engine's component label in the fleet ("prefill",
        #: "replica0", ...).  None = tracing off: every hook below is
        #: one `is not None` check.  Tracing is strictly host-side at
        #: the existing step boundaries — the compiled programs are
        #: untouched, which is why the graph-lint syncs pass stays
        #: clean on the instrumented lanes (OBS_r02's evidence).
        self.tracer = tracer
        self.trace_name = trace_name
        #: committed sharding pinning this engine to one mesh slice
        #: (the disaggregated fleet's replica isolation —
        #: :mod:`apex_tpu.serve.transfer`); None = process default
        self.placement = placement
        #: telemetry (apex_tpu.obs) — shared with the scheduler; every
        #: update is host-side bookkeeping at a step boundary, and the
        #: step-latency observation times a dispatch+fetch the host
        #: performs anyway (the (S,) sampled tokens must be streamed),
        #: so instrumentation adds no host sync to the compiled step
        self.metrics = registry if registry is not None \
            else obs_metrics.DEFAULT
        self._m_step_s = self.metrics.histogram(
            "serve_decode_step_seconds",
            "wall seconds per decode step (dispatch + token fetch); "
            "p50/p99 via Histogram.quantile — bench and serve share "
            "this percentile math")
        self._m_tokens = self.metrics.counter(
            "serve_tokens_total", "tokens generated (active slots x "
            "decode steps + prefill first-tokens)")
        self._m_prefill = self.metrics.counter(
            "serve_prefill_chunks_total",
            "fixed-size prefill chunks dispatched")
        #: created lazily on the first profiled step so an
        #: unprofiled engine's metric catalog is unchanged
        self._m_profiled_s = None
        self._m_cow = None
        if serve_cfg.prefix_cache:
            self._m_cow = self.metrics.counter(
                "serve_prefix_cow_copies_total",
                "copy-on-write forks of a shared full-prompt-match "
                "block (one device copy + one re-dispatched token "
                "each)")
        self.sched = SlotScheduler(
            num_slots=serve_cfg.num_slots,
            num_blocks=serve_cfg.num_blocks,
            block_size=serve_cfg.block_size,
            max_blocks_per_slot=serve_cfg.max_blocks_per_slot,
            registry=self.metrics,
            prefix_cache=serve_cfg.prefix_cache)
        self.stacked = _stack_layer_params(params, cfg.num_layers)
        self.top = {k: v for k, v in params.items()
                    if not k.startswith("block_") and k != "layers"}
        dtype = self.top["tok_emb"]["embedding"].dtype
        head_dim = cfg.hidden_size // cfg.num_heads
        keys = jnp.zeros((serve_cfg.num_slots, 2), jnp.uint32)
        if serve_cfg.int8_kv:
            kc, vc = paged.make_pools(
                cfg.num_layers, serve_cfg.num_blocks,
                serve_cfg.block_size, cfg.num_heads, head_dim, jnp.int8)
            ks, vs = paged.make_scale_pools(
                cfg.num_layers, serve_cfg.num_blocks,
                serve_cfg.block_size)
            self.carry = {"kc": kc, "vc": vc, "ks": ks, "vs": vs,
                          "keys": keys}
            self._m_kv_err = self.metrics.gauge(
                "serve_kv_quant_error",
                "relative int8 KV quantization error of the latest "
                "admitted prefill chunk (mean |x - deq(q(x))| / "
                "mean |x| over K and V; device value, lag-resolved)")
        else:
            kv_dtype = serve_cfg.kv_dtype or dtype
            kc, vc = paged.make_pools(
                cfg.num_layers, serve_cfg.num_blocks,
                serve_cfg.block_size, cfg.num_heads, head_dim, kv_dtype)
            self.carry = {"kc": kc, "vc": vc, "keys": keys}
            self._m_kv_err = None
        if placement is not None:
            # pin the engine to its slice: COMMITTED params and carry
            # make every dispatched program (and its donated updates)
            # execute on these devices — jax follows the committed
            # operands, so nothing else needs a device annotation
            from apex_tpu.serve.transfer import place_tree
            self.top = place_tree(self.top, placement)
            self.stacked = place_tree(self.stacked, placement)
            self.carry = place_tree(self.carry, placement)
        #: python-body executions of each traced function — a retrace
        #: (shape drift across admit/retire) increments these past 1;
        #: tests assert they stay there across a whole mixed stream
        self.trace_counts = {"decode": 0, "prefill": 0, "sample1": 0}
        #: decode-step dispatches (the per-request trace's ``step``
        #: index — host bookkeeping, not the compiled program's)
        self._steps_dispatched = 0
        self._decode_step = jax.jit(self._decode_body,
                                    donate_argnums=(2,))
        #: what step() dispatches: the jit wrapper by default, or the
        #: AOT-cache-resolved ``jax.stages.Compiled`` after a probe.
        #: ``_decode_step`` itself always stays the jit — it is the
        #: lowering surface the graph-lint serve lane and the export
        #: tool build their lane from, probe or no probe.
        self._decode_exec = self._decode_step
        self._prefill_chunk = jax.jit(self._prefill_body,
                                      donate_argnums=(2, 3, 4, 5))
        self._sample_one = jax.jit(self._sample1_body)
        #: copy-on-write block fork: its own trace counter, NOT a
        #: ``trace_counts`` key — the dict pins the three always-hot
        #: programs' exact shape contract and tests compare it whole;
        #: the fork is admission-path-only and dispatches at most once
        #: per full-prompt hit (src/dst block ids are traced scalars,
        #: so every fork reuses the one executable)
        self.cow_trace_count = 0
        self._cow_copy = jax.jit(self._cow_body, donate_argnums=(0,))
        self._outputs: Dict[str, np.ndarray] = {}
        #: cold-start provenance when ``serve_cfg.aot_cache`` is set:
        #: ``{"source": "cache"|"compile", "key": ..., "load_s"|
        #: "compile_s": ...}`` (None on the plain jit path)
        self.aot_info: Optional[Dict[str, Any]] = None
        import os
        from apex_tpu.analysis.export import CACHE_ENV
        # None = fall back to the fleet-wide env var; False = probing
        # EXPLICITLY disabled, env var included (the disaggregated
        # prefill worker: its engine never dispatches the decode step
        # the probe would compile+export)
        aot_cache = serve_cfg.aot_cache
        if aot_cache is None:
            aot_cache = os.environ.get(CACHE_ENV)
        if aot_cache:
            self._probe_aot_cache(aot_cache)

    def _probe_aot_cache(self, cache_dir: str) -> None:
        """Resolve the decode step AOT at startup: one lowering, one
        content-addressed cache probe (:func:`apex_tpu.analysis.
        export.probe`).  A verified hit replaces the lazy jit with the
        deserialized executable — the engine serves its first token
        without paying XLA compilation; a miss compiles here (eagerly
        — the same compile the first ``step()`` would have paid),
        relints, and exports so the NEXT replica hits.  Either way the
        resolved executable's calling convention is exactly the jit's:
        same donated carry, same shapes, bitwise-identical tokens."""
        from apex_tpu.analysis import export as aot

        s = self.sched
        args = (self.top, self.stacked, self.carry,
                jnp.asarray(s.last_tok), jnp.asarray(s.lengths),
                jnp.asarray(s.active), jnp.asarray(s.page_table),
                jnp.asarray(s.temperature), jnp.asarray(s.top_k),
                jnp.asarray(s.top_p))
        # a PLACED engine (one replica of the disaggregated fleet)
        # keys its entry per mesh slice: a PJRT executable is pinned
        # to the devices it was compiled for, so a load across slices
        # would be a wrong-device executable, not a faster cold start
        # — the device ids join the mesh descriptor and each slice's
        # replicas share (and restart from) their own entry
        mesh = None
        if self.placement is not None:
            devs = sorted(d.id for d in self.placement.device_set)
            mesh = (f"{jax.default_backend()}[{len(devs)}]"
                    f"@{','.join(str(d) for d in devs)}")
        compiled, info = aot.probe(
            self._decode_step, *args, cache_dir=cache_dir, mesh=mesh,
            lane="serve_step", export_on_miss=True)
        self._decode_exec = compiled
        self.aot_info = info

    # -- compiled bodies ----------------------------------------------

    def _sample1_body(self, logits, key, temp, top_k, top_p):
        self.trace_counts["sample1"] += 1
        return sampling.sample_tokens(logits, key, temp, top_k, top_p)

    def _decode_body(self, top, stacked, carry, tokens, lengths, active,
                     page_table, temp, top_k, top_p):
        """One continuous-batching decode step over every slot; returns
        ``(carry', sampled (S,))``.  The body runs under a trace span:
        inside tracing that contributes HLO metadata only (the
        ``serve/decode_step`` scope names every op in captured
        xplanes), never a host callback — the graph-lint serve lane
        lints this instrumented program."""
        self.trace_counts["decode"] += 1
        with spans.span("serve/decode_step", registry=self.metrics):
            return self._decode_math(top, stacked, carry, tokens,
                                     lengths, active, page_table, temp,
                                     top_k, top_p)

    def _decode_math(self, top, stacked, carry, tokens, lengths, active,
                     page_table, temp, top_k, top_p):
        c = self.cfg
        bs = self.scfg.block_size
        kc, vc, keys = carry["kc"], carry["vc"], carry["keys"]
        ks, vs = carry.get("ks"), carry.get("vs")
        head_dim = c.hidden_size // c.num_heads
        scale = 1.0 / float(head_dim) ** 0.5
        s = tokens.shape[0]
        m = self.scfg.max_blocks_per_slot * bs

        x = top["tok_emb"]["embedding"][tokens][:, None]       # (S,1,E)
        positions = lengths[:, None]                           # (S,1)
        cos, sin = rope_tables(positions, head_dim, c.rope_theta)
        blocks, offs = paged.token_write_coords(lengths, page_table,
                                                bs, active)
        # keys at cache positions <= the fed token's position (the one
        # this step writes) are attendable; inactive lanes mask out
        valid = (jnp.arange(m)[None, :] <= lengths[:, None]) \
            & active[:, None]                                  # (S,M)
        valid = valid[:, None, :]                              # (S,1,M)

        def layer(lcarry, inputs):
            x, kc, vc, ks, vs = lcarry
            p_l, layer_i = inputs
            x, kc, vc, ks, vs, _err = _paged_block(
                x, p_l, c, kc, vc, layer_i, cos, sin, blocks, offs,
                page_table, valid, scale, ks=ks, vs=vs)
            return (x, kc, vc, ks, vs), None

        (x, kc, vc, ks, vs), _ = jax.lax.scan(
            layer, (x, kc, vc, ks, vs),
            (stacked, jnp.arange(c.num_layers)))
        x = _ln(x[:, -1:], top["ln_f"], c.layer_norm_eps)
        logits = pin_logits(x[:, 0] @ top["lm_head"]["kernel"])  # (S,V)
        toks, new_keys = sampling.sample_tokens(logits, keys, temp,
                                                top_k, top_p)
        toks = jnp.where(active, toks, tokens)
        out = {"kc": kc, "vc": vc, "keys": new_keys}
        if ks is not None:
            out["ks"], out["vs"] = ks, vs
        return out, toks

    def _prefill_body(self, top, stacked, kc, vc, ks, vs, table_row,
                      chunk_ids, start, n_valid):
        """Write one ``(1, prefill_chunk)`` prompt chunk of a single
        slot through its page table at global positions ``start..`` and
        return ``(kc, vc, ks, vs, last-valid-token logits (1, V),
        kv_err)``.  Rows past ``n_valid`` are padding: their cache
        writes route to the trash block and their outputs are never
        read.  ``kv_err`` is the layer-mean relative int8 quantization
        error of this chunk's writes (0 under a dense cache) — the
        admission-time KV-quality gauge's device value."""
        self.trace_counts["prefill"] += 1
        with spans.span("serve/prefill_chunk", registry=self.metrics):
            return self._prefill_math(top, stacked, kc, vc, ks, vs,
                                      table_row, chunk_ids, start,
                                      n_valid)

    def _prefill_math(self, top, stacked, kc, vc, ks, vs, table_row,
                      chunk_ids, start, n_valid):
        return chunk_prefill_math(
            self.cfg, self.scfg.block_size,
            self.scfg.max_blocks_per_slot, top, stacked, kc, vc, ks,
            vs, table_row, chunk_ids, start, n_valid)

    def _cow_body(self, carry, src, dst):
        """Copy block ``src``'s rows into block ``dst`` across every
        pool in the donated carry (KV pools, and the int8 format's
        scale pools with them — a forked block carries its scales, so
        the dequantized read is bitwise-identical to the source's)."""
        self.cow_trace_count += 1
        out = dict(carry)
        for name in ("kc", "vc", "ks", "vs"):
            pool = carry.get(name)
            if pool is not None:
                out[name] = pool.at[:, dst].set(pool[:, src])
        return out

    # -- host loop -----------------------------------------------------

    def submit(self, req: Request) -> None:
        self.sched.submit(req)
        if self.tracer is not None:
            self.tracer.record("enqueue", req.uid, self.trace_name,
                               queue_depth=len(self.sched.queue))

    def _run_prefill(self, slot: int, req: Request) -> None:
        c = self.scfg.prefill_chunk
        prompt = np.asarray(req.prompt, np.int32)
        n = len(prompt)
        # prefix-cache skip: tokens covered by shared blocks never
        # dispatch a prefill chunk — chunking starts at the first
        # unmatched token.  A full-prompt match first forks its last
        # block copy-on-write (one device copy), then re-dispatches
        # exactly ONE token (position n-1: the first-token logits need
        # the last prompt token's forward pass, and that rewrite —
        # bitwise-identical KV, content is a function of the token
        # history — must land in the private fork, never the shared
        # source).  resume == 0 is the sharing-off path, verbatim.
        s = self.sched.slots[slot]
        resume = 0
        if s.cow_src is not None:
            bs = self.scfg.block_size
            src = s.cow_src
            dst = int(self.sched.page_table[slot, (n - 1) // bs])
            self.carry = self._cow_copy(self.carry, jnp.int32(src),
                                        jnp.int32(dst))
            self.sched.finish_cow(slot)
            self._m_cow.inc()
            self._admission_dispatches += 1
            resume = n - 1
            if self.tracer is not None:
                self.tracer.record("cow_fork", req.uid,
                                   self.trace_name, src_block=src,
                                   dst_block=dst)
        elif s.prefix_len:
            resume = s.prefix_len
        if resume and self.tracer is not None:
            self.tracer.record("prefix_hit", req.uid, self.trace_name,
                               matched_tokens=s.prefix_len,
                               prompt_len=n)
        m = n - resume
        padded = np.zeros((-(-m // c)) * c, np.int32)
        padded[:m] = prompt[resume:]
        table_row = jnp.asarray(self.sched.page_table[slot])
        kc, vc = self.carry["kc"], self.carry["vc"]
        ks, vs = self.carry.get("ks"), self.carry.get("vs")
        logits = None
        kv_err = None
        for j in range(0, len(padded), c):
            n_valid = min(c, m - j)
            kc, vc, ks, vs, logits, kv_err = self._prefill_chunk(
                self.top, self.stacked, kc, vc, ks, vs, table_row,
                jnp.asarray(padded[None, j:j + c]),
                jnp.int32(resume + j), jnp.int32(n_valid))
            self._m_prefill.inc()
            self._admission_dispatches += 1
            if self.tracer is not None:
                self.tracer.record("prefill_chunk", req.uid,
                                   self.trace_name, start=resume + j,
                                   n_valid=n_valid)
        if self._m_kv_err is not None and kv_err is not None:
            # admission-time KV quantization-error gauge: a DEFERRED
            # device value resolved by the registry's lag machinery at
            # the next tick — no host sync added here
            self._m_kv_err.set(kv_err)
        if req.resume_key is not None:
            key = jnp.asarray(req.resume_key, jnp.uint32)[None]
        else:
            key = jax.random.PRNGKey(req.seed)[None].astype(jnp.uint32)
        tok, new_key = self._sample_one(
            logits, key,
            jnp.full((1,), req.temperature, jnp.float32),
            jnp.full((1,), req.top_k, jnp.int32),
            jnp.full((1,), req.top_p, jnp.float32))
        keys = self.carry["keys"].at[slot].set(new_key[0])
        self.carry = {"kc": kc, "vc": vc, "keys": keys}
        if ks is not None:
            self.carry["ks"], self.carry["vs"] = ks, vs
        self.sched.arm(slot, int(np.asarray(tok)[0]), n)
        self._m_tokens.inc(1)          # the prefill's sampled token
        # a 1-token budget (or an immediate EOS) finishes on the
        # prefill sample itself — retire before the slot wastes a
        # decode step past its budget
        first = int(np.asarray(tok)[0])
        if self.tracer is not None:
            self.tracer.record("admit", req.uid, self.trace_name,
                               slot=slot, first_token=first,
                               prompt_len=n, tokens=1)
        done = req.max_new_tokens <= 1 or (
            req.eos_id is not None and first == req.eos_id)
        if done:
            uid, out = self.sched.retire(slot)
            self._outputs[uid] = out
            if self.tracer is not None:
                self.tracer.record("retire", uid, self.trace_name,
                                   tokens_out=int(out.shape[0]))

    def _admit_and_evict(self) -> None:
        while True:
            plan = self.sched.plan()
            if plan is None:
                return
            if plan[0] == "evict":
                slot = plan[1]
                uid = self.sched.slots[slot].request.uid
                resume_key = np.asarray(self.carry["keys"][slot])
                self.sched.preempt(slot, resume_key)
                if self.tracer is not None:
                    self.tracer.record("preempt", uid,
                                       self.trace_name, slot=slot)
            else:
                _, slot, req = plan
                self._run_prefill(slot, req)

    def decode_step_args(self) -> tuple:
        """The exact argument tuple ``step()`` dispatches the compiled
        decode step with — the ONE place the (params, carry,
        scheduler-state) calling convention lives.  graph_lint's serve
        lane, the contprof classifier builder, and the obs_report lint
        lanes all lower with it, so a carry/scheduler field change can
        never silently diverge them from the dispatched program."""
        sched = self.sched
        return (self.top, self.stacked, self.carry,
                jnp.asarray(sched.last_tok), jnp.asarray(sched.lengths),
                jnp.asarray(sched.active),
                jnp.asarray(sched.page_table),
                jnp.asarray(sched.temperature),
                jnp.asarray(sched.top_k), jnp.asarray(sched.top_p))

    def _profiler_begin(self) -> bool:
        """Continuous-profiler window hook before a step dispatch;
        True = this step is being captured (its latency must go to
        the profiled histogram so the SLO/latency gates never judge a
        profiled step).  The calling step's OWN admissions ran before
        this (before start_trace); the marker catches later steps'
        admissions landing inside the window."""
        if self.profiler is None:
            return False
        return self.profiler.step_begin(
            marker=self._admission_dispatches)

    def _observe_step_wall(self, dt: float, in_window: bool) -> None:
        """Record one step's wall seconds into exactly one of the two
        partitions, then close the profiler hook — shared by the base
        and speculative step loops so the exclusion contract holds on
        both."""
        if in_window:
            if self._m_profiled_s is None:
                self._m_profiled_s = self.metrics.histogram(
                    "serve_profiled_step_seconds",
                    "wall seconds of decode steps inside a "
                    "continuous-profiler capture window — EXCLUDED "
                    "from serve_decode_step_seconds so latency gates "
                    "and SLO burn rates never judge a profiled step")
            self._m_profiled_s.observe(dt)
        else:
            # dispatch + the token fetch the host needs anyway — the
            # decode-step latency the serve bench gates p50/p99 on
            self._m_step_s.observe(dt)
        if self.profiler is not None:
            self.profiler.step_end(
                dt, marker=self._admission_dispatches)

    def step(self) -> Dict[str, np.ndarray]:
        """One step boundary: admit/evict, then one compiled decode
        step over every slot; returns the requests that FINISHED this
        step (``{uid: generated token ids}``)."""
        self._admit_and_evict()
        sched = self.sched
        if not sched.active.any():
            return {}
        n_act = int(sched.active.sum())
        in_window = self._profiler_begin()
        t0 = time.perf_counter()
        self.carry, toks = self._decode_exec(*self.decode_step_args())
        toks = np.asarray(toks)
        dt = time.perf_counter() - t0
        self._observe_step_wall(dt, in_window)
        self._m_tokens.inc(n_act)
        self._steps_dispatched += 1
        finished: Dict[str, np.ndarray] = {}
        for slot in range(sched.num_slots):
            if not sched.active[slot]:
                continue
            if self.tracer is not None:
                # per-slot token attribution of this decode-step batch
                # (host values: the (S,) fetch above is the stream the
                # host needs anyway — the PR-7 zero-extra-sync contract)
                self.tracer.record(
                    "decode_step", sched.slots[slot].request.uid,
                    self.trace_name, step=self._steps_dispatched,
                    token=int(toks[slot]), batch=n_act, tokens=1)
            if sched.record_token(slot, int(toks[slot])):
                uid, out = sched.retire(slot)
                finished[uid] = out
                if self.tracer is not None:
                    self.tracer.record("retire", uid, self.trace_name,
                                       tokens_out=int(out.shape[0]))
        self._outputs.update(finished)
        # step boundary for the registry's lag machinery: deferred
        # device values (the int8 KV admission gauge) resolve in
        # batched fetches >= 1 step behind dispatch — zero added syncs
        self.metrics.tick()
        return finished

    def run(self, max_steps: int = 100_000) -> Dict[str, np.ndarray]:
        """Drain the queue and every slot; returns
        ``{uid: generated token ids}`` for every request ever
        submitted (the prompt is not repeated in the output)."""
        steps = 0
        try:
            while not self.sched.idle():
                before = self.sched.n_active() + len(self.sched.queue)
                self.step()
                steps += 1
                if steps > max_steps:
                    raise RuntimeError(
                        f"serve loop exceeded {max_steps} steps with "
                        f"{before} request(s) outstanding")
        finally:
            if self.profiler is not None:
                # a window still open at drain would leak the
                # process-global tracer into the next loop
                self.profiler.abort_window()
        return dict(self._outputs)
