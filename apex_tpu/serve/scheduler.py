"""Continuous-batching scheduler: fixed decode slots, iteration-level
admission/retirement, block accounting, preempt-and-recompute eviction.

Orca-style (Yu et al., OSDI '22) iteration-level batching under XLA's
static-shape constraint: the compiled decode step always sees the SAME
``num_slots``-wide arrays — requests are admitted into free slots and
retired out of finished ones BETWEEN steps by mutating the host-side
slot tables (page table rows, lengths, sampling knobs, active mask),
never the program.  One lowering serves the whole stream; the engine's
trace counter and the graph-lint serve lane both pin that.

Scheduling policy (deliberately simple, deterministic, and tested —
not clever):

- **admission**: FIFO; a request is admitted when a slot is free AND
  the allocator can cover its whole worst-case footprint
  (``ceil((prompt + max_new) / block_size)`` blocks) up front, so a
  running request can never die mid-decode for blocks;
- **eviction**: when a slot is free but blocks are short, the
  YOUNGEST-admitted active request is preempted (recompute-on-resume,
  the vLLM recovery mode): its blocks return to the pool and a
  continuation request — original prompt + every token generated so
  far, remaining budget, the slot's live PRNG key — goes to the back
  of the queue.  The oldest active request is never evicted
  (progress guarantee), nothing is evicted just because the queue is
  long — only a block shortage triggers it — and a CONTINUATION never
  evicts anyone (a preempted request reclaiming its seat by preempting
  its evictor ping-pongs the pool forever; with the guard, total
  evictions are bounded by the number of fresh submissions);
- **retirement**: a slot retires when its budget is spent or its
  request's ``eos_id`` appears; its blocks free immediately and the
  slot is admissible the same step boundary.

With ``prefix_cache=True`` (the engine's ``ServeConfig.prefix_cache``)
admission grows a cross-request sharing stage on top of the same
policy: the prompt's full aligned blocks are chain-hashed
(:func:`apex_tpu.serve.paged.prefix_block_hashes`) and probed against
the allocator's prefix index — matched blocks map into the new slot's
page table by INCREF (no prefill dispatch for the matched span; the
engine starts chunking at the first unmatched token), a full aligned
match forks its LAST block copy-on-write (the first-token logits need
the last prompt token's forward pass, and that rewrite must not land
in a shared block), and retirement/preemption DECREF instead of free,
parking refcount-0 registered blocks in the allocator's LRU cache —
matchable until block pressure reclaims them, which keeps the
preempt-youngest eviction above the last resort it always was.
Sharing is exact: chain-hash-equal blocks hold bitwise-identical KV
(content is a deterministic function of the full token history), so
every output stays bitwise-equal to its solo ``generate()`` run.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.serve.paged import (
    TRASH_BLOCK,
    BlockAllocator,
    PoolExhausted,
    chain_seed,
    chain_step,
    prefix_block_hashes,
)


@dataclasses.dataclass
class Request:
    """One generation request.  ``temperature=0`` is greedy;
    ``top_k<=0`` / ``top_p>=1`` disable those cutoffs; ``seed`` starts
    the slot's PRNG chain (per-request — reproducible regardless of
    batch-mates)."""

    uid: str
    prompt: np.ndarray
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    eos_id: Optional[int] = None
    #: preemption internals: tokens generated before the last
    #: preemption (already part of ``prompt`` for recompute), and the
    #: PRNG key the slot held when preempted (resumes the chain)
    prior_tokens: Tuple[int, ...] = ()
    resume_key: Optional[np.ndarray] = None


def validate_request(req: Request, block_size: int,
                     max_blocks_per_slot: int, num_blocks: int) -> None:
    """Reject a request that can NEVER run on a pool of these shapes
    (empty prompt / zero budget, context over the per-slot page-table
    reach, footprint over the whole usable pool) — at submission, not
    deadlocked later.  ONE implementation serves both front doors:
    :meth:`SlotScheduler.submit` and the disaggregated router's
    ``submit`` (every decode replica is identical, so the router
    validates against the same shapes its replicas hold)."""
    if len(req.prompt) < 1 or req.max_new_tokens < 1:
        raise ValueError(
            f"{req.uid}: need a non-empty prompt and "
            f"max_new_tokens >= 1")
    total = len(req.prompt) + req.max_new_tokens
    max_context = max_blocks_per_slot * block_size
    if total > max_context:
        raise ValueError(
            f"{req.uid}: prompt+max_new = {total} exceeds the "
            f"per-slot context {max_context} "
            f"({max_blocks_per_slot} blocks x {block_size})")
    need = -(-total // block_size)
    if need > num_blocks - 1:
        raise ValueError(
            f"{req.uid}: needs {need} blocks, pool has "
            f"{num_blocks - 1} usable")


@dataclasses.dataclass
class _Slot:
    request: Request
    blocks: List[int]
    emitted: List[int]
    admit_seq: int
    #: prefix-cache state: tokens covered by shared (or forked) blocks
    #: — the engine starts prefill at the first unmatched token
    prefix_len: int = 0
    #: copy-on-write source: the registered block whose content the
    #: engine device-copies into this slot's private block at row
    #: ``prefix_len // block_size - 1`` before the full-match
    #: last-token re-dispatch; held (increfed) until ``finish_cow``
    cow_src: Optional[int] = None
    #: incremental chain-hash cursor for registration: the hash after
    #: ``hashed_blocks`` full blocks of this slot's token history
    chain_hash: bytes = b""
    hashed_blocks: int = 0


class SlotScheduler:
    """Host-side slot/queue/block bookkeeping for the serve engine (see
    the module docstring for the policy).  Owns the fixed-shape numpy
    tables the compiled step consumes; the engine owns the device
    carries (pools, keys) and executes the admissions/evictions this
    class plans."""

    def __init__(self, num_slots: int, num_blocks: int, block_size: int,
                 max_blocks_per_slot: int,
                 registry: Optional[obs_metrics.Registry] = None,
                 prefix_cache: bool = False):
        if num_slots < 1:
            raise ValueError(f"num_slots={num_slots}")
        self.num_slots = num_slots
        self.block_size = block_size
        self.max_blocks_per_slot = max_blocks_per_slot
        self.max_context = max_blocks_per_slot * block_size
        self.allocator = BlockAllocator(num_blocks)
        #: cross-request prefix sharing (see module docstring); the
        #: probe/hit counters below are host bookkeeping the prefix
        #: gauges and the PREFIXCACHE artifact re-derive from
        self.prefix_cache = prefix_cache
        self.prefix_probes = 0
        self.prefix_hits = 0
        self.prefix_hit_tokens = 0
        #: per-admission spans (uid, prompt_len, matched, dispatched)
        #: — the contradiction-rejecting artifact re-derives skipped
        #: tokens from these; bounded so a long-lived engine can't
        #: grow without bound
        self.prefix_events: List[dict] = []
        self.queue: Deque[Request] = deque()
        self.slots: List[Optional[_Slot]] = [None] * num_slots
        self._admit_seq = 0
        # the fixed-shape tables the compiled step reads every step
        self.page_table = np.full((num_slots, max_blocks_per_slot),
                                  TRASH_BLOCK, np.int32)
        self.lengths = np.zeros(num_slots, np.int32)
        self.last_tok = np.zeros(num_slots, np.int32)
        self.active = np.zeros(num_slots, bool)
        self.temperature = np.zeros(num_slots, np.float32)
        self.top_k = np.zeros(num_slots, np.int32)
        self.top_p = np.ones(num_slots, np.float32)
        # -- telemetry (apex_tpu.obs): every count below is a host-side
        # bookkeeping update at a step boundary — never on the compiled
        # step path.  A continuation re-admission counts as an
        # admission again (total admissions = submissions + preemptions).
        reg = registry if registry is not None else obs_metrics.DEFAULT
        self.metrics = reg
        self._m_admit = reg.counter(
            "serve_admissions_total", "requests installed into a slot "
            "(continuation re-admissions included)")
        self._m_retire = reg.counter(
            "serve_retirements_total", "requests finished and freed")
        self._m_preempt = reg.counter(
            "serve_preemptions_total",
            "evictions (recompute-on-resume continuations queued)")
        self._m_queue = reg.gauge("serve_queue_depth",
                                  "requests waiting for a slot")
        self._m_occ = reg.gauge("serve_slot_occupancy",
                                "active slots / num_slots")
        self._m_blocks = reg.gauge(
            "serve_block_utilization",
            "live KV blocks / usable pool (trash block excluded)")
        self._m_hit_rate = self._m_shared = None
        if prefix_cache:
            self._m_hit_rate = reg.gauge(
                "serve_prefix_hit_rate",
                "admissions whose prompt matched >=1 full cached "
                "block / admissions probed (cumulative; host "
                "bookkeeping at admission time)")
            self._m_shared = reg.gauge(
                "serve_prefix_shared_blocks",
                "physical blocks currently mapped by more than one "
                "slot (refcount > 1)")
        self._update_gauges()

    def _update_gauges(self) -> None:
        self._m_queue.set(float(len(self.queue)))
        self._m_occ.set(self.n_active() / self.num_slots)
        usable = max(self.allocator.num_blocks - 1, 1)
        self._m_blocks.set(self.allocator.live_count / usable)
        if self._m_hit_rate is not None:
            self._m_hit_rate.set(
                self.prefix_hits / self.prefix_probes
                if self.prefix_probes else 0.0)
            self._m_shared.set(float(self.allocator.shared_count))

    # -- queue side ----------------------------------------------------

    def blocks_needed(self, req: Request) -> int:
        total = len(req.prompt) + req.max_new_tokens
        return -(-total // self.block_size)

    def submit(self, req: Request) -> None:
        """Validate (:func:`validate_request`) and enqueue — requests
        that can NEVER run are rejected here, not deadlocked later."""
        validate_request(req, self.block_size, self.max_blocks_per_slot,
                         self.allocator.num_blocks)
        self.queue.append(req)
        self._m_queue.set(float(len(self.queue)))

    # -- step-boundary planning ---------------------------------------

    def free_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is None]

    def n_active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def plan(self):
        """The next step-boundary action, or ``None`` to just decode:
        ``("admit", slot, request)`` (blocks already allocated, tables
        set — the engine runs the prefill) or ``("evict", slot)`` (the
        engine snapshots the slot's PRNG key, then calls
        :meth:`preempt`)."""
        if not self.queue:
            return None
        free = self.free_slots()
        if not free:
            return None
        req = self.queue[0]
        need = self.blocks_needed(req)
        try:
            blocks, prefix_len, cow_src = self._alloc_with_prefix(req)
        except PoolExhausted:
            # a preempted request must not preempt others: without
            # this, a continuation and its evictor ping-pong the pool
            # forever (observed in development) — each FRESH request
            # may force at most one eviction chain, so total evictions
            # are bounded by the number of submissions
            if req.prior_tokens:
                return None
            victim = self._eviction_victim(need)
            if victim is None:
                return None
            return ("evict", victim)
        self.queue.popleft()
        slot = free[0]
        self._install(slot, req, blocks, prefix_len=prefix_len,
                      cow_src=cow_src)
        return ("admit", slot, req)

    def _alloc_with_prefix(self, req: Request):
        """The admission allocation: probe the prefix index over the
        prompt's full aligned blocks, INCREF every matched block into
        the new slot's row, allocate the rest fresh.  Returns
        ``(row blocks, prefix_len, cow_src)``; atomic — a
        :class:`PoolExhausted` mid-way rolls the increfs back so a
        failed admission holds nothing.  A full aligned match pops its
        LAST block into ``cow_src`` (pinned by an incref until the
        engine's device copy finishes): the first-token logits need
        the last prompt token's forward pass, whose KV rewrite must
        land in a private copy-on-write fork, never a shared block."""
        need = self.blocks_needed(req)
        a = self.allocator
        if not self.prefix_cache:
            return a.alloc(need, req), 0, None
        prompt = np.asarray(req.prompt)
        matched: List[int] = []
        for h in prefix_block_hashes(prompt, self.block_size):
            b = a.lookup(h)
            if b is None:
                break
            matched.append(b)
        n = len(prompt)
        cow_src = None
        if matched and len(matched) * self.block_size == n:
            cow_src = matched.pop()
        # incref matched FIRST: a matched block parked in the
        # refcount-0 cache must not be reclaimed by our own fresh
        # alloc below
        taken: List[int] = []
        try:
            for b in matched:
                a.share(b, req)
                taken.append(b)
            if cow_src is not None:
                a.share(cow_src, req)
                taken.append(cow_src)
            fresh = a.alloc(need - len(matched), req)
        except PoolExhausted:
            for b in reversed(taken):
                a.free([b], req)
            raise
        prefix_len = n if cow_src is not None \
            else len(matched) * self.block_size
        # full match still re-dispatches ONE token (the CoW rewrite)
        skipped = n - 1 if cow_src is not None else prefix_len
        self.prefix_probes += 1
        if prefix_len > 0:
            self.prefix_hits += 1
            self.prefix_hit_tokens += skipped
        if len(self.prefix_events) < 10_000:
            self.prefix_events.append(
                {"uid": req.uid, "prompt_len": n,
                 "matched": prefix_len, "dispatched": n - skipped})
        return matched + fresh, prefix_len, cow_src

    def probe_prefix_tokens(self, prompt) -> int:
        """Side-effect-free prefix probe: how many leading prompt
        tokens the index covers right now (0 when sharing is off) —
        the disaggregated router's straight-to-decode routing signal."""
        if not self.prefix_cache:
            return 0
        m = 0
        for h in prefix_block_hashes(np.asarray(prompt),
                                     self.block_size):
            if self.allocator.lookup(h) is None:
                break
            m += 1
        return m * self.block_size

    def _eviction_victim(self, need: int) -> Optional[int]:
        """Youngest-admitted active slot whose blocks would make the
        admission possible; never the only active slot.  Only the
        victim's PRIVATE references count as freed — a shared block
        survives its decref, and the allocator's refcount-0 cache is
        already reclaimable without anyone's eviction."""
        if self.n_active() < 2:
            return None
        cands = [(s.admit_seq, i) for i, s in enumerate(self.slots)
                 if s is not None]
        _seq, victim = max(cands)
        s = self.slots[victim]
        freed = sum(1 for b in s.blocks
                    if self.allocator.refcount(b) == 1)
        if s.cow_src is not None \
                and self.allocator.refcount(s.cow_src) == 1:
            freed += 1
        if self.allocator.reclaimable_count + freed < need:
            return None
        return victim

    def _install(self, slot: int, req: Request,
                 blocks: List[int], prefix_len: int = 0,
                 cow_src: Optional[int] = None) -> None:
        self.slots[slot] = _Slot(request=req, blocks=blocks, emitted=[],
                                 admit_seq=self._admit_seq,
                                 prefix_len=prefix_len, cow_src=cow_src,
                                 chain_hash=chain_seed(self.block_size))
        self._admit_seq += 1
        row = np.full(self.max_blocks_per_slot, TRASH_BLOCK, np.int32)
        row[:len(blocks)] = blocks
        self.page_table[slot] = row
        self.lengths[slot] = 0          # engine sets after prefill
        self.active[slot] = False       # engine arms after prefill
        self.temperature[slot] = req.temperature
        self.top_k[slot] = req.top_k
        self.top_p[slot] = req.top_p
        self._m_admit.inc()
        self._update_gauges()

    # -- engine callbacks ---------------------------------------------

    def arm(self, slot: int, first_token: int, prompt_len: int) -> None:
        """Prefill done: record the first sampled token and enter the
        slot into the decode batch.  Under prefix sharing the prompt's
        full aligned blocks register in the content index here — a
        shipment install (the disaggregated fleet's admission path)
        arms through this same method, so installed blocks join the
        destination replica's index with no extra call."""
        self.slots[slot].emitted.append(int(first_token))
        self.last_tok[slot] = int(first_token)
        self.lengths[slot] = prompt_len
        self.active[slot] = True
        self._advance_registration(slot)

    def record_token(self, slot: int, token: int) -> bool:
        """Append one decoded token; returns True when the slot is
        finished (budget spent or EOS)."""
        s = self.slots[slot]
        s.emitted.append(int(token))
        self.last_tok[slot] = int(token)
        self.lengths[slot] += 1
        if self.prefix_cache and self.lengths[slot] % self.block_size == 0:
            # a decode-filled block just completed: register it so a
            # multi-turn follow-up (prompt = this conversation's
            # history) matches generated spans too, not just prompts
            self._advance_registration(slot)
        done = len(s.emitted) >= s.request.max_new_tokens
        if s.request.eos_id is not None and int(token) == s.request.eos_id:
            done = True
        return done

    def _advance_registration(self, slot: int) -> None:
        """Register every fully-WRITTEN block of ``slot`` not yet
        content-addressed: chain-hash the slot's token history block
        by block (position ``p`` holds ``prompt[p]`` below the prompt
        length and ``emitted[p - prompt_len]`` above it) and offer
        each to the allocator's index — a hash already mapped to
        another block leaves this one private (first registration is
        canonical), which is exactly what keeps a CoW fork out of the
        index its source owns."""
        if not self.prefix_cache:
            return
        s = self.slots[slot]
        bs = self.block_size
        full = int(self.lengths[slot]) // bs
        if s.hashed_blocks >= full:
            return
        n = len(s.request.prompt)
        prompt = np.asarray(s.request.prompt)
        while s.hashed_blocks < full:
            i = s.hashed_blocks
            toks = [int(prompt[p]) if p < n else s.emitted[p - n]
                    for p in range(i * bs, (i + 1) * bs)]
            s.chain_hash = chain_step(s.chain_hash, toks)
            self.allocator.register(int(s.blocks[i]), s.chain_hash)
            s.hashed_blocks += 1
        self._update_gauges()

    def finish_cow(self, slot: int) -> None:
        """The engine's device copy of the CoW fork landed: drop the
        pin on the fork source (it stays registered/cached for the
        next hit; this slot's private copy at the same row is now the
        write target)."""
        s = self.slots[slot]
        if s.cow_src is not None:
            self.allocator.free([s.cow_src], s.request)
            s.cow_src = None
            self._update_gauges()

    def _release_blocks(self, s: _Slot) -> None:
        """Decref everything a slot holds — its page-table row AND a
        still-pinned CoW source (a retire/preempt racing the fork must
        not leak the pin)."""
        blocks = list(s.blocks)
        if s.cow_src is not None:
            blocks.append(s.cow_src)
            s.cow_src = None
        self.allocator.free(blocks, s.request)

    def retire(self, slot: int) -> Tuple[str, np.ndarray]:
        """Free the slot and its blocks; returns ``(uid, tokens)`` with
        the request's FULL generated stream (pre-preemption tokens
        included)."""
        s = self.slots[slot]
        self._release_blocks(s)
        self._clear(slot)
        self._m_retire.inc()
        self._update_gauges()
        toks = list(s.request.prior_tokens) + s.emitted
        return s.request.uid, np.asarray(toks, np.int32)

    def continuation(self, slot: int,
                     resume_key: np.ndarray) -> Request:
        """The recompute-on-resume continuation record for a live
        slot: original prompt extended with every generated token,
        remaining budget, ``prior_tokens`` carried, and the PRNG key
        the stream resumes with.  ONE builder serves both interrupt
        paths — :meth:`preempt` (the slot's live key, snapshotted)
        and the router's replica-kill recovery (the key re-derived by
        draw count) — so the continuation contract cannot drift
        between them."""
        s = self.slots[slot]
        req = s.request
        done_tokens = list(req.prior_tokens) + s.emitted
        remaining = req.max_new_tokens - len(s.emitted)
        if remaining < 1:
            raise RuntimeError(
                f"{req.uid}: continuing a finished slot (bug: retire "
                f"should have run first)")
        return dataclasses.replace(
            req,
            prompt=np.concatenate(
                [np.asarray(req.prompt, np.int32),
                 np.asarray(s.emitted, np.int32)]),
            max_new_tokens=remaining,
            prior_tokens=tuple(int(t) for t in done_tokens),
            resume_key=np.asarray(resume_key),
        )

    def preempt(self, slot: int, resume_key: np.ndarray) -> Request:
        """Evict ``slot`` (recompute-on-resume): blocks free, and the
        :meth:`continuation` — original prompt + generated tokens,
        remaining budget, the live PRNG key — joins the BACK of the
        queue.  Returns the continuation."""
        cont = self.continuation(slot, resume_key)
        s = self.slots[slot]
        self._release_blocks(s)
        self._clear(slot)
        self.queue.append(cont)
        self._m_preempt.inc()
        self._update_gauges()
        return cont

    def _clear(self, slot: int) -> None:
        self.slots[slot] = None
        self.page_table[slot] = TRASH_BLOCK
        self.lengths[slot] = 0
        self.last_tok[slot] = 0
        self.active[slot] = False
        self.temperature[slot] = 0.0
        self.top_k[slot] = 0
        self.top_p[slot] = 1.0

    def idle(self) -> bool:
        return not self.queue and self.n_active() == 0
