"""Continuous-batching scheduler: fixed decode slots, iteration-level
admission/retirement, block accounting, preempt-and-recompute eviction.

Orca-style (Yu et al., OSDI '22) iteration-level batching under XLA's
static-shape constraint: the compiled decode step always sees the SAME
``num_slots``-wide arrays — requests are admitted into free slots and
retired out of finished ones BETWEEN steps by mutating the host-side
slot tables (page table rows, lengths, sampling knobs, active mask),
never the program.  One lowering serves the whole stream; the engine's
trace counter and the graph-lint serve lane both pin that.

Scheduling policy (deliberately simple, deterministic, and tested —
not clever):

- **admission**: FIFO; a request is admitted when a slot is free AND
  the allocator can cover its whole worst-case footprint
  (``ceil((prompt + max_new) / block_size)`` blocks) up front, so a
  running request can never die mid-decode for blocks;
- **eviction**: when a slot is free but blocks are short, the
  YOUNGEST-admitted active request is preempted (recompute-on-resume,
  the vLLM recovery mode): its blocks return to the pool and a
  continuation request — original prompt + every token generated so
  far, remaining budget, the slot's live PRNG key — goes to the back
  of the queue.  The oldest active request is never evicted
  (progress guarantee), nothing is evicted just because the queue is
  long — only a block shortage triggers it — and a CONTINUATION never
  evicts anyone (a preempted request reclaiming its seat by preempting
  its evictor ping-pongs the pool forever; with the guard, total
  evictions are bounded by the number of fresh submissions);
- **retirement**: a slot retires when its budget is spent or its
  request's ``eos_id`` appears; its blocks free immediately and the
  slot is admissible the same step boundary.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.serve.paged import BlockAllocator, PoolExhausted, TRASH_BLOCK


@dataclasses.dataclass
class Request:
    """One generation request.  ``temperature=0`` is greedy;
    ``top_k<=0`` / ``top_p>=1`` disable those cutoffs; ``seed`` starts
    the slot's PRNG chain (per-request — reproducible regardless of
    batch-mates)."""

    uid: str
    prompt: np.ndarray
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    eos_id: Optional[int] = None
    #: preemption internals: tokens generated before the last
    #: preemption (already part of ``prompt`` for recompute), and the
    #: PRNG key the slot held when preempted (resumes the chain)
    prior_tokens: Tuple[int, ...] = ()
    resume_key: Optional[np.ndarray] = None


def validate_request(req: Request, block_size: int,
                     max_blocks_per_slot: int, num_blocks: int) -> None:
    """Reject a request that can NEVER run on a pool of these shapes
    (empty prompt / zero budget, context over the per-slot page-table
    reach, footprint over the whole usable pool) — at submission, not
    deadlocked later.  ONE implementation serves both front doors:
    :meth:`SlotScheduler.submit` and the disaggregated router's
    ``submit`` (every decode replica is identical, so the router
    validates against the same shapes its replicas hold)."""
    if len(req.prompt) < 1 or req.max_new_tokens < 1:
        raise ValueError(
            f"{req.uid}: need a non-empty prompt and "
            f"max_new_tokens >= 1")
    total = len(req.prompt) + req.max_new_tokens
    max_context = max_blocks_per_slot * block_size
    if total > max_context:
        raise ValueError(
            f"{req.uid}: prompt+max_new = {total} exceeds the "
            f"per-slot context {max_context} "
            f"({max_blocks_per_slot} blocks x {block_size})")
    need = -(-total // block_size)
    if need > num_blocks - 1:
        raise ValueError(
            f"{req.uid}: needs {need} blocks, pool has "
            f"{num_blocks - 1} usable")


@dataclasses.dataclass
class _Slot:
    request: Request
    blocks: List[int]
    emitted: List[int]
    admit_seq: int


class SlotScheduler:
    """Host-side slot/queue/block bookkeeping for the serve engine (see
    the module docstring for the policy).  Owns the fixed-shape numpy
    tables the compiled step consumes; the engine owns the device
    carries (pools, keys) and executes the admissions/evictions this
    class plans."""

    def __init__(self, num_slots: int, num_blocks: int, block_size: int,
                 max_blocks_per_slot: int,
                 registry: Optional[obs_metrics.Registry] = None):
        if num_slots < 1:
            raise ValueError(f"num_slots={num_slots}")
        self.num_slots = num_slots
        self.block_size = block_size
        self.max_blocks_per_slot = max_blocks_per_slot
        self.max_context = max_blocks_per_slot * block_size
        self.allocator = BlockAllocator(num_blocks)
        self.queue: Deque[Request] = deque()
        self.slots: List[Optional[_Slot]] = [None] * num_slots
        self._admit_seq = 0
        # the fixed-shape tables the compiled step reads every step
        self.page_table = np.full((num_slots, max_blocks_per_slot),
                                  TRASH_BLOCK, np.int32)
        self.lengths = np.zeros(num_slots, np.int32)
        self.last_tok = np.zeros(num_slots, np.int32)
        self.active = np.zeros(num_slots, bool)
        self.temperature = np.zeros(num_slots, np.float32)
        self.top_k = np.zeros(num_slots, np.int32)
        self.top_p = np.ones(num_slots, np.float32)
        # -- telemetry (apex_tpu.obs): every count below is a host-side
        # bookkeeping update at a step boundary — never on the compiled
        # step path.  A continuation re-admission counts as an
        # admission again (total admissions = submissions + preemptions).
        reg = registry if registry is not None else obs_metrics.DEFAULT
        self.metrics = reg
        self._m_admit = reg.counter(
            "serve_admissions_total", "requests installed into a slot "
            "(continuation re-admissions included)")
        self._m_retire = reg.counter(
            "serve_retirements_total", "requests finished and freed")
        self._m_preempt = reg.counter(
            "serve_preemptions_total",
            "evictions (recompute-on-resume continuations queued)")
        self._m_queue = reg.gauge("serve_queue_depth",
                                  "requests waiting for a slot")
        self._m_occ = reg.gauge("serve_slot_occupancy",
                                "active slots / num_slots")
        self._m_blocks = reg.gauge(
            "serve_block_utilization",
            "live KV blocks / usable pool (trash block excluded)")
        self._update_gauges()

    def _update_gauges(self) -> None:
        self._m_queue.set(float(len(self.queue)))
        self._m_occ.set(self.n_active() / self.num_slots)
        usable = max(self.allocator.num_blocks - 1, 1)
        self._m_blocks.set(self.allocator.live_count / usable)

    # -- queue side ----------------------------------------------------

    def blocks_needed(self, req: Request) -> int:
        total = len(req.prompt) + req.max_new_tokens
        return -(-total // self.block_size)

    def submit(self, req: Request) -> None:
        """Validate (:func:`validate_request`) and enqueue — requests
        that can NEVER run are rejected here, not deadlocked later."""
        validate_request(req, self.block_size, self.max_blocks_per_slot,
                         self.allocator.num_blocks)
        self.queue.append(req)
        self._m_queue.set(float(len(self.queue)))

    # -- step-boundary planning ---------------------------------------

    def free_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is None]

    def n_active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def plan(self):
        """The next step-boundary action, or ``None`` to just decode:
        ``("admit", slot, request)`` (blocks already allocated, tables
        set — the engine runs the prefill) or ``("evict", slot)`` (the
        engine snapshots the slot's PRNG key, then calls
        :meth:`preempt`)."""
        if not self.queue:
            return None
        free = self.free_slots()
        if not free:
            return None
        req = self.queue[0]
        need = self.blocks_needed(req)
        try:
            blocks = self.allocator.alloc(need, req)
        except PoolExhausted:
            # a preempted request must not preempt others: without
            # this, a continuation and its evictor ping-pong the pool
            # forever (observed in development) — each FRESH request
            # may force at most one eviction chain, so total evictions
            # are bounded by the number of submissions
            if req.prior_tokens:
                return None
            victim = self._eviction_victim(need)
            if victim is None:
                return None
            return ("evict", victim)
        self.queue.popleft()
        slot = free[0]
        self._install(slot, req, blocks)
        return ("admit", slot, req)

    def _eviction_victim(self, need: int) -> Optional[int]:
        """Youngest-admitted active slot whose blocks would make the
        admission possible; never the only active slot."""
        if self.n_active() < 2:
            return None
        cands = [(s.admit_seq, i) for i, s in enumerate(self.slots)
                 if s is not None]
        _seq, victim = max(cands)
        freed = len(self.slots[victim].blocks)
        if self.allocator.free_count + freed < need:
            return None
        return victim

    def _install(self, slot: int, req: Request,
                 blocks: List[int]) -> None:
        self.slots[slot] = _Slot(request=req, blocks=blocks, emitted=[],
                                 admit_seq=self._admit_seq)
        self._admit_seq += 1
        row = np.full(self.max_blocks_per_slot, TRASH_BLOCK, np.int32)
        row[:len(blocks)] = blocks
        self.page_table[slot] = row
        self.lengths[slot] = 0          # engine sets after prefill
        self.active[slot] = False       # engine arms after prefill
        self.temperature[slot] = req.temperature
        self.top_k[slot] = req.top_k
        self.top_p[slot] = req.top_p
        self._m_admit.inc()
        self._update_gauges()

    # -- engine callbacks ---------------------------------------------

    def arm(self, slot: int, first_token: int, prompt_len: int) -> None:
        """Prefill done: record the first sampled token and enter the
        slot into the decode batch."""
        self.slots[slot].emitted.append(int(first_token))
        self.last_tok[slot] = int(first_token)
        self.lengths[slot] = prompt_len
        self.active[slot] = True

    def record_token(self, slot: int, token: int) -> bool:
        """Append one decoded token; returns True when the slot is
        finished (budget spent or EOS)."""
        s = self.slots[slot]
        s.emitted.append(int(token))
        self.last_tok[slot] = int(token)
        self.lengths[slot] += 1
        done = len(s.emitted) >= s.request.max_new_tokens
        if s.request.eos_id is not None and int(token) == s.request.eos_id:
            done = True
        return done

    def retire(self, slot: int) -> Tuple[str, np.ndarray]:
        """Free the slot and its blocks; returns ``(uid, tokens)`` with
        the request's FULL generated stream (pre-preemption tokens
        included)."""
        s = self.slots[slot]
        self.allocator.free(s.blocks, s.request)
        self._clear(slot)
        self._m_retire.inc()
        self._update_gauges()
        toks = list(s.request.prior_tokens) + s.emitted
        return s.request.uid, np.asarray(toks, np.int32)

    def continuation(self, slot: int,
                     resume_key: np.ndarray) -> Request:
        """The recompute-on-resume continuation record for a live
        slot: original prompt extended with every generated token,
        remaining budget, ``prior_tokens`` carried, and the PRNG key
        the stream resumes with.  ONE builder serves both interrupt
        paths — :meth:`preempt` (the slot's live key, snapshotted)
        and the router's replica-kill recovery (the key re-derived by
        draw count) — so the continuation contract cannot drift
        between them."""
        s = self.slots[slot]
        req = s.request
        done_tokens = list(req.prior_tokens) + s.emitted
        remaining = req.max_new_tokens - len(s.emitted)
        if remaining < 1:
            raise RuntimeError(
                f"{req.uid}: continuing a finished slot (bug: retire "
                f"should have run first)")
        return dataclasses.replace(
            req,
            prompt=np.concatenate(
                [np.asarray(req.prompt, np.int32),
                 np.asarray(s.emitted, np.int32)]),
            max_new_tokens=remaining,
            prior_tokens=tuple(int(t) for t in done_tokens),
            resume_key=np.asarray(resume_key),
        )

    def preempt(self, slot: int, resume_key: np.ndarray) -> Request:
        """Evict ``slot`` (recompute-on-resume): blocks free, and the
        :meth:`continuation` — original prompt + generated tokens,
        remaining budget, the live PRNG key — joins the BACK of the
        queue.  Returns the continuation."""
        cont = self.continuation(slot, resume_key)
        s = self.slots[slot]
        self.allocator.free(s.blocks, s.request)
        self._clear(slot)
        self.queue.append(cont)
        self._m_preempt.inc()
        self._update_gauges()
        return cont

    def _clear(self, slot: int) -> None:
        self.slots[slot] = None
        self.page_table[slot] = TRASH_BLOCK
        self.lengths[slot] = 0
        self.last_tok[slot] = 0
        self.active[slot] = False
        self.temperature[slot] = 0.0
        self.top_k[slot] = 0
        self.top_p[slot] = 1.0

    def idle(self) -> bool:
        return not self.queue and self.n_active() == 0
