"""Process-group helpers for SyncBatchNorm sub-grouping.

Port of ``apex/parallel/__init__.py:21-92`` (``convert_syncbn_model`` /
``create_syncbn_process_group``).  On TPU a "process group" is an
``axis_index_groups`` partition of a mesh axis — no communicator objects to
construct, and unlike the reference there is no requirement that every rank
execute the construction (it's just a list).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import flax.linen as nn


def create_syncbn_process_group(group_size: int,
                                world_size: int) -> Optional[List[List[int]]]:
    """Partition ``world_size`` ranks into contiguous groups of
    ``group_size`` for BN-stat reduction (``parallel/__init__.py:55-92``).

    Returns ``axis_index_groups`` for ``lax.all_gather`` /
    ``SyncBatchNorm(process_group=...)``, or None for group_size 0 (= whole
    world, reference behavior).
    """
    if group_size == 0:
        return None
    if group_size > world_size:
        raise ValueError(
            f"group_size {group_size} exceeds world size {world_size}")
    if world_size % group_size != 0:
        raise ValueError(
            f"world size {world_size} must be divisible by group_size "
            f"{group_size} (reference asserts the same)")
    return [list(range(g * group_size, (g + 1) * group_size))
            for g in range(world_size // group_size)]


#: Fields a model must expose (and thread to its BatchNorms) for
#: convert_syncbn_model to work; apex_tpu.models follows this convention.
SYNC_BN_FIELDS = ("bn_axis_name", "bn_process_group")


def convert_syncbn_model(module: nn.Module, axis_name: str = "data",
                         process_group: Optional[Sequence[Sequence[int]]] = None
                         ) -> nn.Module:
    """Return a copy of ``module`` with its BatchNorms synchronized
    (reference ``convert_syncbn_model``, ``parallel/__init__.py:21-53``).

    linen modules are immutable dataclasses, so instead of recursively
    swapping submodule instances (the torch approach), the model declares
    ``bn_axis_name`` / ``bn_process_group`` fields that it threads into its
    :class:`~apex_tpu.parallel.SyncBatchNorm` layers; this returns
    ``module.clone()`` with those fields set.  Because
    ``SyncBatchNorm(axis_name=None)`` *is* the local BatchNorm, the param and
    batch_stats pytrees are identical before and after conversion — running
    stats and affine params carry over exactly as the reference requires.
    """
    missing = [f for f in SYNC_BN_FIELDS if not hasattr(module, f)]
    if missing:
        raise TypeError(
            f"{type(module).__name__} does not declare {missing}; models "
            "must thread bn_axis_name/bn_process_group into their BatchNorm "
            "layers to be convertible (see apex_tpu.models.resnet).")
    return module.clone(bn_axis_name=axis_name, bn_process_group=process_group)
