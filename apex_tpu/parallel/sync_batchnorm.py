"""Synchronized BatchNorm over mesh axes.

Port of the reference SyncBatchNorm family (``apex/parallel/
optimized_sync_batchnorm*.py`` + ``csrc/welford.cu``, with the Python
fallback ``sync_batchnorm*.py`` semantics — including returning the output,
which the fork's Python path failed to do, SURVEY.md §0.2).

Statistics pipeline, matching the optimized path (§3.5 call stack):

1. local per-channel (count, mean, biased var) — single-pass Welford on
   device (``welford.cu:257-293``; on TPU a fused XLA reduction in fp32);
2. ``all_gather`` of per-device stats over the mesh axis, honoring
   ``process_group`` sub-grouping via ``axis_index_groups``
   (``optimized_sync_batchnorm_kernel.py:33-38``);
3. Chan's generalized merge → global (mean, biased var, invstd)
   (``welford_kernel_parallel``, ``welford.cu:557-585``);
4. running stats EMA with the unbiased ``m/(m-1)`` correction, written in the
   running-buffer dtype (fp16 running buffers honored,
   ``optimized_sync_batchnorm_kernel.py:48-51``);
5. elementwise normalize in fp32, cast back to input dtype.

The backward defaults to the reference's hand-written two-stage split:
train-mode normalization goes through :func:`_bn_train_apply`, a
``custom_vjp`` whose backward runs ``reduce_bn → allreduce →
batchnorm_backward`` (``welford.cu:323-411``).  Plain autodiff of the fp32
stats graph would save fp32 activation-sized residuals (double the HBM
traffic of a bf16 model); the custom VJP saves only the input at its own
dtype plus per-channel fp32 vectors, measured ~3-4% faster ResNet-50
steps on one chip.  Trade-offs: like the reference, the fused backward
supports reverse-mode AD only (``jax.jvp``/``jacfwd`` through a training
graph raises; eval mode is unaffected) — ``fused_backward=False``
switches to plain autodiff (same total derivative, forward-mode capable,
not available with BN ``process_group`` sub-groups whose gathered stats
cannot be transposed under shard_map VMA checking).

TPU note: channels-last is the native layout (the reference needed separate
``_c_last`` CUDA kernels; here any ``channel_axis`` compiles equally well).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax import lax

from apex_tpu.utils.jax_compat import pvary as _pvary


def local_mean_var(x: jax.Array, reduce_axes: Sequence[int]):
    """Local per-channel (mean, biased var, count) in fp32.

    Computed as the one-pass ``E[x^2] - E[x]^2`` pair — NOT Welford's
    update: both reductions read ``x`` once and XLA fuses them into a
    single pass (often into the producing conv's epilogue).  The
    two-pass centered formulation (``x.var()``) re-reads the full
    activation to square the residuals — measured +7% on the whole RN50
    b256 step (round 3).

    Numerics regime: single-pass cancellation loses ``~2*log2(|mean|/
    std)`` bits of the variance.  fp32 accumulation (24 mantissa bits)
    over BN-scale activations (|mean|/std of order 1-10^2, as produced
    by normalized nets) keeps that loss ≤ ~14 bits — far above the
    1e-5 tolerance SyncBN guarantees (BASELINE.md); the same trade
    cuDNN and flax make.  A pathological |mean|/std ≳ 10^3 regime would
    bite, but can't arise between BN layers that themselves normalize.
    The *cross-device* merge stays Chan's algorithm
    (:func:`welford_parallel`), which is where single-pass numerics
    would actually bite (large disjoint populations)."""
    x32 = x.astype(jnp.float32)
    count = 1
    for a in reduce_axes:
        count *= x.shape[a]
    mean = x32.mean(axis=tuple(reduce_axes))
    mean_sq = jnp.square(x32).mean(axis=tuple(reduce_axes))
    var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)  # biased
    return mean, var, count


#: Reference-parity export spelling (``syncbn.welford_mean_var``,
#: SURVEY §2.1 #19).  The NAME is historical — the reference's local
#: stats kernel is Welford (`welford.cu`); this implementation is the
#: one-pass pair documented in :func:`local_mean_var` (ADVICE r3: keep
#: the parity spelling, name the real algorithm honestly).
welford_mean_var = local_mean_var


def welford_parallel(means: jax.Array, vars_: jax.Array,
                     counts: jax.Array):
    """Chan's generalized merge of per-device (mean, biased var, count)
    stacked on axis 0 (``syncbn.welford_parallel``, ``welford.cu:557-585``).

    Returns (mean, biased var) per channel.
    """
    counts = counts.astype(jnp.float32)
    if counts.ndim == 1:
        counts = counts[:, None]
    total = counts.sum(axis=0)
    mean = (counts * means).sum(axis=0) / total
    m2 = (counts * vars_).sum(axis=0) \
        + (counts * jnp.square(means - mean[None, :])).sum(axis=0)
    return mean, m2 / total


def batchnorm_forward(x: jax.Array, mean: jax.Array, invstd: jax.Array,
                      weight: Optional[jax.Array],
                      bias: Optional[jax.Array],
                      channel_axis: int) -> jax.Array:
    """Elementwise normalize (``syncbn.batchnorm_forward[_c_last]``)."""
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    y = (x.astype(jnp.float32) - mean.reshape(shape)) * invstd.reshape(shape)
    if weight is not None:
        y = y * weight.reshape(shape).astype(jnp.float32)
    if bias is not None:
        y = y + bias.reshape(shape).astype(jnp.float32)
    return y.astype(x.dtype)


def reduce_bn(grad_out: jax.Array, x: jax.Array, mean: jax.Array,
              invstd: jax.Array, weight: Optional[jax.Array],
              channel_axis: int):
    """Local backward reductions (``syncbn.reduce_bn[_c_last]``,
    ``welford.cu:323-384``): per-channel ``(mean_dy, mean_dy_xmu,
    grad_weight, grad_bias)`` from local data.  The reference allreduces the
    two means between this and :func:`batchnorm_backward`; under autodiff the
    same split falls out of the traced forward, but the pieces are exported
    for manual composition and conformance tests."""
    ch = channel_axis % x.ndim
    reduce_axes = tuple(a for a in range(x.ndim) if a != ch)
    count = 1
    for a in reduce_axes:
        count *= x.shape[a]
    shape = [1] * x.ndim
    shape[ch] = x.shape[ch]
    dy = grad_out.astype(jnp.float32)
    xmu = x.astype(jnp.float32) - mean.reshape(shape)
    sum_dy = dy.sum(axis=reduce_axes)
    sum_dy_xmu = (dy * xmu).sum(axis=reduce_axes)
    # grad_weight/grad_bias are computed unconditionally from the same sums
    # (the reference kernel always produces them; welford.cu:323-384) — a
    # bias-only BN still needs grad_bias.
    grad_weight = sum_dy_xmu * invstd
    grad_bias = sum_dy
    return sum_dy / count, sum_dy_xmu / count, grad_weight, grad_bias


def batchnorm_backward(grad_out: jax.Array, x: jax.Array, mean: jax.Array,
                       invstd: jax.Array, weight: Optional[jax.Array],
                       mean_dy: jax.Array, mean_dy_xmu: jax.Array,
                       channel_axis: int) -> jax.Array:
    """Elementwise grad_input from globally-reduced means
    (``syncbn.batchnorm_backward[_c_last]``, ``welford.cu:385-411``)."""
    ch = channel_axis % x.ndim
    shape = [1] * x.ndim
    shape[ch] = x.shape[ch]
    dy = grad_out.astype(jnp.float32)
    xmu = x.astype(jnp.float32) - mean.reshape(shape)
    iv = invstd.reshape(shape)
    gi = (dy - mean_dy.reshape(shape)
          - xmu * jnp.square(iv) * mean_dy_xmu.reshape(shape)) * iv
    if weight is not None:
        gi = gi * weight.reshape(shape).astype(jnp.float32)
    return gi.astype(x.dtype)


# _c_last spellings: NHWC is TPU's native layout, so the reference's separate
# channels-last kernels (welford.cu:586-829) collapse to channel_axis=-1 —
# same code, exported under the reference names for inventory parity.
def welford_mean_var_c_last(x: jax.Array):
    return welford_mean_var(x, tuple(range(x.ndim - 1)))


def batchnorm_forward_c_last(x, mean, invstd, weight, bias):
    return batchnorm_forward(x, mean, invstd, weight, bias, channel_axis=-1)


def reduce_bn_c_last(grad_out, x, mean, invstd, weight):
    return reduce_bn(grad_out, x, mean, invstd, weight, channel_axis=-1)


def batchnorm_backward_c_last(grad_out, x, mean, invstd, weight,
                              mean_dy, mean_dy_xmu):
    return batchnorm_backward(grad_out, x, mean, invstd, weight,
                              mean_dy, mean_dy_xmu, channel_axis=-1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _bn_train_apply(channel_axis, axis_name, process_group,
                    x, mean, invstd, weight, bias):
    """Normalize with batch statistics, with the reference's hand-written
    backward (``reduce_bn → allreduce → batchnorm_backward``,
    ``optimized_sync_batchnorm_kernel.py:83-101``) as a ``custom_vjp``.

    The backward formula is the *total* derivative through the batch
    statistics (mean/invstd are functions of x over the global batch), so
    the saved-for-backward residuals are just the input at its own dtype
    plus per-channel fp32 vectors — plain autodiff of the fp32 stats graph
    instead saves fp32 activation-sized intermediates, doubling HBM traffic
    for bf16 models.  Cotangents for ``mean``/``invstd`` are defined zero:
    their dependence on ``x`` is folded into ``grad_input`` analytically.
    """
    return batchnorm_forward(x, mean, invstd, weight, bias, channel_axis)


def _bn_train_fwd(channel_axis, axis_name, process_group,
                  x, mean, invstd, weight, bias):
    y = batchnorm_forward(x, mean, invstd, weight, bias, channel_axis)
    return y, (x, mean, invstd, weight, bias)


def _bn_train_bwd(channel_axis, axis_name, process_group, res, dy):
    x, mean, invstd, weight, bias = res
    mean_dy, mean_dy_xmu, gw, gb = reduce_bn(dy, x, mean, invstd, weight,
                                             channel_axis)
    if axis_name is not None:
        # Global means of dy / dy·(x-µ): allreduce + divide by world size
        # (kernel.py:91-97); equal per-rank counts assumed, as the
        # reference does.  Grouped reductions ride all_gather + local mean,
        # the same recipe (and VMA-compatibility reason) as the forward.
        if process_group is not None:
            # already a tuple-of-tuples (normalized by the caller; must be
            # hashable as a nondiff arg)
            mean_dy = lax.all_gather(
                mean_dy, axis_name,
                axis_index_groups=process_group).mean(axis=0)
            mean_dy_xmu = lax.all_gather(
                mean_dy_xmu, axis_name,
                axis_index_groups=process_group).mean(axis=0)
        else:
            mean_dy = lax.pmean(mean_dy, axis_name)
            mean_dy_xmu = lax.pmean(mean_dy_xmu, axis_name)
    gi = batchnorm_backward(dy, x, mean, invstd, weight,
                            mean_dy, mean_dy_xmu, channel_axis)
    if axis_name is not None:
        # weight/bias are replicated across the whole axis (even with BN
        # sub-groups), so their cotangent is the full-axis sum — what
        # autodiff's transpose-of-broadcast inserts implicitly.
        if weight is not None:
            gw = lax.psum(gw, axis_name)
        if bias is not None:
            gb = lax.psum(gb, axis_name)
    return (gi,
            jnp.zeros_like(mean),
            jnp.zeros_like(invstd),
            gw.astype(weight.dtype) if weight is not None else None,
            gb.astype(bias.dtype) if bias is not None else None)


_bn_train_apply.defvjp(_bn_train_fwd, _bn_train_bwd)


class SyncBatchNorm(nn.Module):
    """Cross-device BatchNorm (``apex.parallel.SyncBatchNorm``).

    Attributes mirror the reference module (``optimized_sync_batchnorm.py:
    9-84``) adapted to flax conventions:

    - ``axis_name``: mesh axis to synchronize over; ``None`` degrades to
      ordinary (local) BatchNorm — the single-process fallback the reference
      has (``sync_batchnorm.py:86-91``).
    - ``process_group``: ``axis_index_groups`` — the
      ``create_syncbn_process_group`` capability (sub-pod BN groups).
    - ``channel_axis``: -1 (NHWC, TPU-native) by default; the reference's
      ``channel_last=True`` path.  Any axis works.
    - running stats live in the ``batch_stats`` collection; ``momentum``
      follows torch semantics: ``new = (1-momentum)·old + momentum·batch``.
    """

    use_running_average: Optional[bool] = None
    momentum: float = 0.1
    epsilon: float = 1e-5
    affine: bool = True
    axis_name: Optional[str] = None
    process_group: Optional[Sequence[Sequence[int]]] = None
    channel_axis: int = -1
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    running_dtype: Any = jnp.float32
    #: Use the hand-written two-stage backward (``reduce_bn`` →
    #: allreduce → ``batchnorm_backward``) instead of plain autodiff
    #: through the stats graph.  Both produce the same total derivative;
    #: back-to-back A/B on one chip measures the fused backward ~3-4%
    #: faster on ResNet-50 steps (smaller residuals: x at its own dtype +
    #: per-channel fp32 vectors vs the autodiff-saved fp32 stats graph),
    #: so it is the default.  ``False`` enables forward-mode AD; invalid
    #: with ``process_group`` (grouped gathered stats cannot be
    #: transposed under shard_map VMA checking).
    fused_backward: bool = True

    @nn.compact
    def __call__(self, x: jax.Array,
                 use_running_average: Optional[bool] = None) -> jax.Array:
        use_ra = nn.merge_param("use_running_average",
                                self.use_running_average, use_running_average)
        ch_axis = self.channel_axis % x.ndim
        num_features = x.shape[ch_axis]
        reduce_axes = [a for a in range(x.ndim) if a != ch_axis]

        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((num_features,),
                                                  self.running_dtype))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((num_features,),
                                                self.running_dtype))
        if self.affine:
            weight = self.param("scale", nn.initializers.ones,
                                (num_features,), self.param_dtype)
            bias = self.param("bias", nn.initializers.zeros,
                              (num_features,), self.param_dtype)
        else:
            weight = bias = None

        if use_ra:
            # Eval: normalize with running stats (reference falls back to
            # F.batch_norm, sync_batchnorm_kernel.py:82-85).
            mean = ra_mean.value.astype(jnp.float32)
            var = ra_var.value.astype(jnp.float32)
            invstd = lax.rsqrt(var + self.epsilon)
            return batchnorm_forward(x, mean, invstd, weight, bias, ch_axis)

        with jax.named_scope("sync_bn_welford"):  # reference nvtx range
            local_mean, local_var, local_count = welford_mean_var(
                x, reduce_axes)

        # During init there is no bound mesh axis to reduce over; local stats
        # are fine (flax's BatchNorm does the same).
        sync = self.axis_name is not None and not self.is_initializing()
        if sync and self.process_group is None:
            # Whole-axis sync: Chan's merge expressed as two psum rounds —
            # the same math as gathering per-rank stats and merging
            # (welford.cu:557-585), but psum outputs are replication-typed,
            # which shard_map's VMA checker can verify, so running stats stay
            # provably replicated.
            c = _pvary(jnp.asarray(float(local_count), jnp.float32),
                       (self.axis_name,))
            total_count = lax.psum(c, self.axis_name)
            mean = lax.psum(local_mean * c, self.axis_name) / total_count
            m2 = lax.psum(c * local_var + c * jnp.square(local_mean - mean),
                          self.axis_name)
            var = m2 / total_count
        elif sync:
            # Grouped sync: grouped psum is unsupported under VMA checking,
            # so use the reference's own recipe — all_gather per-group stats
            # then Chan-merge locally (optimized_sync_batchnorm_kernel.py:
            # 33-39).  Results (and running stats) genuinely differ across
            # groups, i.e. they are device-varying by construction.
            groups = self.process_group
            counts = jnp.full((1,), float(local_count), jnp.float32)
            g_mean = lax.all_gather(local_mean, self.axis_name,
                                    axis_index_groups=groups)
            g_var = lax.all_gather(local_var, self.axis_name,
                                   axis_index_groups=groups)
            g_count = lax.all_gather(counts, self.axis_name,
                                     axis_index_groups=groups)
            mean, var = welford_parallel(g_mean, g_var, g_count)
            total_count = g_count.sum()
        else:
            mean, var = local_mean, local_var
            total_count = jnp.asarray(float(local_count), jnp.float32)

        invstd = lax.rsqrt(var + self.epsilon)

        if not self.is_initializing():
            # Unbiased correction m/(m-1) for the running var
            # (sync_batchnorm.py:92-128).
            unbiased = var * total_count / jnp.maximum(total_count - 1.0, 1.0)
            m = self.momentum
            ra_mean.value = ((1.0 - m) * ra_mean.value.astype(jnp.float32)
                             + m * lax.stop_gradient(mean)
                             ).astype(self.running_dtype)
            ra_var.value = ((1.0 - m) * ra_var.value.astype(jnp.float32)
                            + m * lax.stop_gradient(unbiased)
                            ).astype(self.running_dtype)

        # Train-mode normalize with the hand-written backward: residuals are
        # x (own dtype) + per-channel fp32 vectors, not the fp32 stats graph.
        if not self.fused_backward:
            if sync and self.process_group is not None:
                raise ValueError(
                    "fused_backward=False is unsupported with a BN "
                    "process_group: autodiff would transpose the grouped "
                    "all_gather of stats into a grouped reduction, which "
                    "shard_map VMA checking rejects (see the grouped-sync "
                    "forward comment)")
            # Plain autodiff through the stats graph — same total
            # derivative, and forward-mode capable.
            return batchnorm_forward(x, mean, invstd, weight, bias, ch_axis)
        groups = (tuple(map(tuple, self.process_group))
                  if sync and self.process_group is not None else None)
        return _bn_train_apply(ch_axis, self.axis_name if sync else None,
                               groups, x, lax.stop_gradient(mean),
                               lax.stop_gradient(invstd), weight, bias)


# Local BatchNorm is the axis_name=None degenerate case; exported under the
# familiar name for model code.
BatchNorm = SyncBatchNorm
