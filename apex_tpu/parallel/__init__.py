"""apex_tpu.parallel — data parallelism over TPU meshes.

Reference surface (``apex/parallel/__init__.py``): ``DistributedDataParallel``,
``Reducer``, ``SyncBatchNorm``, ``convert_syncbn_model``,
``create_syncbn_process_group``, ``ReduceOp``, ``LARC``.
"""

from apex_tpu.optimizers.larc import LARC, larc
from apex_tpu.parallel import mesh, multiproc
from apex_tpu.parallel.moe import moe_apply, top1_routing
from apex_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
)
from apex_tpu.parallel.distributed import (
    DistributedDataParallel,
    ReduceConfig,
    ReduceOp,
    Reducer,
    all_gather,
    all_reduce,
    broadcast,
    pvary_params,
    reduce_gradients,
)
from apex_tpu.parallel.groups import (
    convert_syncbn_model,
    create_syncbn_process_group,
)
from apex_tpu.parallel.mesh import (
    DATA_AXIS,
    batch_sharding,
    data_parallel_mesh,
    intended_specs,
    make_mesh,
    partition_spec_of,
    replicated_sharding,
    world_size,
)
from apex_tpu.parallel.sync_batchnorm import (
    BatchNorm,
    SyncBatchNorm,
    batchnorm_backward,
    batchnorm_backward_c_last,
    batchnorm_forward,
    batchnorm_forward_c_last,
    reduce_bn,
    reduce_bn_c_last,
    welford_mean_var,
    welford_mean_var_c_last,
    welford_parallel,
)

__all__ = [
    "DistributedDataParallel", "Reducer", "ReduceConfig", "ReduceOp",
    "all_reduce", "all_gather", "broadcast", "reduce_gradients",
    "pvary_params",
    "pipeline_apply", "stack_stage_params",
    "moe_apply", "top1_routing",
    "SyncBatchNorm", "BatchNorm", "convert_syncbn_model",
    "create_syncbn_process_group",
    "welford_mean_var", "welford_parallel", "batchnorm_forward",
    "reduce_bn", "batchnorm_backward", "welford_mean_var_c_last",
    "batchnorm_forward_c_last", "reduce_bn_c_last",
    "batchnorm_backward_c_last",
    "LARC", "larc",
    "mesh", "multiproc", "make_mesh", "data_parallel_mesh", "batch_sharding",
    "replicated_sharding", "world_size", "DATA_AXIS",
    "intended_specs", "partition_spec_of",
]
