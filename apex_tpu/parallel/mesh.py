"""Device-mesh construction helpers.

The reference's process-group runtime (``torch.distributed`` init, NCCL
communicators) has no TPU analog — SPMD over a ``jax.sharding.Mesh`` replaces
it (SURVEY.md §5.8).  These helpers build the meshes the rest of the package
assumes:

- a 1-D ``("data",)`` mesh is the apex-DDP world;
- a 2-D ``("data", "model")`` mesh is available for pjit-style tensor
  sharding beyond the reference's capabilities.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"


def make_mesh(shape: Optional[Tuple[int, ...]] = None,
              axis_names: Sequence[str] = (DATA_AXIS,),
              devices=None) -> Mesh:
    """Build a mesh over all (or the given) devices.

    ``shape=None`` puts every device on the first axis.  Axis sizes must
    multiply to the device count.
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, axis_names=tuple(axis_names))


def data_parallel_mesh(num_devices: Optional[int] = None) -> Mesh:
    """The DDP-equivalent mesh: all devices on one ``"data"`` axis."""
    devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return make_mesh(devices=devices)


def batch_sharding(mesh: Mesh, axis_name: str = DATA_AXIS) -> NamedSharding:
    """Sharding that splits the leading (batch) dim over ``axis_name``."""
    return NamedSharding(mesh, PartitionSpec(axis_name))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated sharding (parameters under pure DP)."""
    return NamedSharding(mesh, PartitionSpec())


def world_size(mesh: Mesh, axis_name: str = DATA_AXIS) -> int:
    return mesh.shape[axis_name]


def partition_spec_of(x) -> Optional[PartitionSpec]:
    """The :class:`PartitionSpec` carried by ``x`` — a spec itself, a
    :class:`NamedSharding`, or an array committed to one; ``None`` when
    ``x`` declares nothing."""
    if isinstance(x, PartitionSpec):
        return x
    if isinstance(x, NamedSharding):
        return x.spec
    s = getattr(x, "sharding", None)
    return s.spec if isinstance(s, NamedSharding) else None


def intended_specs(tree) -> dict:
    """Flatten a pytree of specs / shardings / committed arrays into the
    ``{arg-path: PartitionSpec}`` intent mapping the graph lint's
    sharding pass takes (``analysis.analyze(..., options={"sharding":
    {"intended": ...}})``): entries whose spec actually shards something
    are kept, replicated/undeclared leaves are dropped.  Declaring the
    intent from the same tree you ``device_put`` keeps the lint and the
    placement from drifting apart."""
    flat, _ = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=lambda l: isinstance(l, (PartitionSpec,
                                               NamedSharding)))
    out = {}
    for path, leaf in flat:
        spec = partition_spec_of(leaf)
        if spec is not None and any(e is not None for e in tuple(spec)):
            out[jax.tree_util.keystr(path)] = spec
    return out
