"""Expert parallelism: switch-style top-1 MoE over a mesh axis.

Beyond the reference (apex predates MoE — SURVEY.md section 2 "NOT
present"), but part of the full parallelism surface (dp/tp/pp/sp/ep) this
framework validates.  The design is the standard TPU dispatch/combine:
capacity-bounded one-hot dispatch tensors turn routing into dense einsums
(MXU work, static shapes — no scatter), and ``lax.all_to_all`` moves token
slots to the ranks that host their experts and back over ICI.

Call :func:`moe_apply` inside ``shard_map``: tokens are sharded over
``axis_name`` (data-parallel shard), experts are sharded over the same axis
(``n_experts = n_ranks * experts_per_rank``).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.models.generate import greedy_argmax
from apex_tpu.utils.jax_compat import axis_size as _axis_size


def top1_routing(logits: jax.Array, capacity: int
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Switch top-1 router on ``(T, E)`` logits.

    Returns ``(dispatch, combine, aux_loss)``: ``dispatch`` is a bool
    ``(T, E, C)`` one-hot (token t occupies slot c of expert e), ``combine``
    is the same mask scaled by the router probability, and ``aux_loss`` is
    the switch load-balancing loss (mean fraction-routed times mean router
    prob per expert, scaled by E).  Tokens beyond an expert's capacity are
    dropped (standard switch semantics): their combine weights are zero, so
    they pass through the residual path untouched.
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    # reassociation-proof routing: jnp.argmax's tie-break can differ
    # between the dispatch and combine consumers under refusion, and a
    # router tie that flips experts between the two poisons the
    # capacity bookkeeping (det-tie-argmax)
    expert = greedy_argmax(probs)                            # (T,)
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)    # (T, E)
    # position of each token within its expert's queue (zero on the E-1
    # non-selected columns so the row-sum is exactly the queue index)
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot        # (T, E)
    slot = jnp.sum(pos, axis=-1).astype(jnp.int32)           # (T,)
    keep = slot < capacity
    dispatch = (jax.nn.one_hot(slot, capacity, dtype=jnp.float32)
                [:, None, :] * onehot[:, :, None]
                * keep[:, None, None].astype(jnp.float32))   # (T, E, C)
    gate = jnp.sum(probs * onehot, axis=-1)                  # (T,)
    combine = dispatch * gate[:, None, None]
    # load-balancing aux loss (Switch Transformer eq. 4-6)
    frac_routed = jnp.mean(onehot, axis=0)
    frac_prob = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac_routed * frac_prob)
    return dispatch, combine, aux


def moe_apply(
    expert_fn: Callable[[Any, jax.Array], jax.Array],
    expert_params: Any,
    router_w: jax.Array,
    x: jax.Array,
    axis_name: str = "expert",
    capacity_factor: float = 2.0,
) -> Tuple[jax.Array, jax.Array]:
    """Top-1 MoE layer with experts sharded over ``axis_name``.

    Call inside ``shard_map``.  Args:
      expert_fn: ``(one_expert_params, (tokens, d)) -> (tokens, d)``.
      expert_params: this rank's experts — leading axis ``E_local``.
      router_w: ``(d, E_global)`` router weights (replicated).
      x: local token shard ``(T_local, d)``.
      capacity_factor: per-expert slots = ``ceil(cf * T_local / E_global)``
        per rank's token shard.

    Returns ``(y, aux_loss)`` with ``y`` shaped like ``x`` (dropped tokens
    produce zeros — add the residual outside), ``aux_loss`` a scalar
    (psum-averaged over ranks).
    """
    import math
    n_ranks = _axis_size(axis_name)
    t_local, d = x.shape
    e_local = jax.tree.leaves(expert_params)[0].shape[0]
    e_global = n_ranks * e_local
    capacity = max(1, math.ceil(capacity_factor * t_local / e_global))

    logits = x @ router_w.astype(x.dtype)                    # (T, E_global)
    dispatch, combine, aux = top1_routing(logits, capacity)

    # (T,E,C) x (T,d) -> (E, C, d): dense dispatch, MXU-friendly
    sent = jnp.einsum("tec,td->ecd", dispatch.astype(jnp.float32),
                      x.astype(jnp.float32))
    # split expert axis across ranks: (E_global, C, d) ->
    # (n_ranks, E_local, C, d) -all_to_all-> (E_local, n_ranks*C, d)
    sent = sent.reshape(n_ranks, e_local, capacity, d)
    recv = lax.all_to_all(sent, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)                        # (n*E_l, C, d)
    recv = recv.reshape(n_ranks, e_local, capacity, d).transpose(1, 0, 2, 3)
    recv = recv.reshape(e_local, n_ranks * capacity, d)

    out = jax.vmap(expert_fn)(expert_params, recv.astype(x.dtype))
    out = out.astype(jnp.float32)

    # return path mirrors the dispatch
    out = out.reshape(e_local, n_ranks, capacity, d).transpose(1, 0, 2, 3)
    out = out.reshape(n_ranks * e_local, capacity, d)
    back = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)                        # (E_global,C,d)
    y = jnp.einsum("tec,ecd->td", combine.astype(jnp.float32), back)
    aux = lax.pmean(aux, axis_name)
    return y.astype(x.dtype), aux
