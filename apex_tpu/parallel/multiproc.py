"""Multi-host launch helper.

Port of ``apex/parallel/multiproc.py:1-35`` (the one-process-per-GPU
spawner).  On TPU the launch model is one process per *host*, each seeing its
local chips, coordinated by ``jax.distributed.initialize`` — there is nothing
to spawn per chip on a Cloud TPU VM.  This module provides:

- :func:`initialize` — the per-process entry (``jax.distributed``
  wrapper honoring the reference's env-var contract);
- :func:`spawn` / ``python -m apex_tpu.parallel.multiproc script.py …`` —
  the reference's local spawner, for multi-process runs on one machine
  (e.g. N CPU-backend processes, or one process per local accelerator
  runtime).  Matching the reference: rank 0 inherits stdout, every other
  rank logs to ``PROC_<i>.log`` (the reference's ``GPU_<i>.log``,
  ``multiproc.py:30``), ``--world-size``/``--rank`` style overrides via
  ``WORLD_SIZE``, and the launcher waits for all workers.  Unlike the
  reference it also exports ``COORDINATOR_ADDRESS``/``WORLD_SIZE``/``RANK``
  so the spawned script just calls :func:`initialize`.
"""

from __future__ import annotations

import hashlib
import inspect
import itertools
import json
import os
import subprocess
import sys
import time
from typing import Any, List, Optional, Sequence

import jax


class ClusterInitError(RuntimeError):
    """Cluster formation failed within the configured timeout/retry
    budget — with the expected world shape and candidate missing ranks
    in the message, instead of an indefinite hang."""


class SpmdPreflightError(ClusterInitError):
    """The SPMD preflight barrier found a rank whose lowered program
    diverges from its peers — the message names the first differing
    collective in both spellings.  Raised on EVERY rank (all ranks see
    the same all-gathered digests), so the whole fleet aborts with a
    diagnosis instead of wedging in the first mismatched collective."""


#: per-process preflight round counter (namespaces the KV-store keys so
#: a re-run barrier never reads a previous round's digests)
_PREFLIGHT_SEQ = itertools.count()


def _kv_client():
    """The cluster coordination-service KV client, or ``None`` when the
    process is not distributed-initialized (or the internal API moved —
    the caller then falls back to an all-gather exchange)."""
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:  # noqa: BLE001 - internal API; degrade, don't crash
        return None


def spmd_preflight(program: Any, label: str = "train_step") -> dict:
    """Cross-rank SPMD consistency barrier: hash this rank's lowered
    module + serialized collective schedule, all-gather the 32-byte
    digest, and abort with a named schedule diff if any rank diverges.

    ``program`` is a lowering (``jitted.lower(...)``), its module text,
    or a zero-arg callable returning either (the callable form lets
    :func:`initialize` run the preflight right after cluster formation,
    when the global devices the lowering needs first exist).

    The exchange is two-phase and tiny, and runs over the cluster
    coordination service's key-value store — the same gRPC channel
    cluster formation used, deliberately NOT an accelerator collective:
    the divergence detector must never itself wedge in the mismatched
    collective it exists to diagnose (and the CPU backend can't run
    cross-process XLA computations at all).  One digest per rank on the
    match path; only on a mismatch does a second exchange move the
    serialized schedules so the error can name the first differing op —
    rank 7's sign-compressed bucket surfaces as ``all-reduce(f32, ...)``
    vs ``all-reduce(bf16, ...)``, not as a fleet-wide hang.  If the KV
    client is unavailable the exchange falls back to a 32-byte
    all-gather.  With one process the check degenerates to recording
    the hashes (so the same code path runs in single-host tests and
    utilities).  A peer that never reaches the barrier surfaces as
    :class:`ClusterInitError` after ``APEX_TPU_PREFLIGHT_TIMEOUT_S``
    (default 120).

    Returns the per-rank record ``{label, rank, n_ranks, module_hash,
    schedule_hash, n_collectives, ok}``; raises
    :class:`SpmdPreflightError` on divergence."""
    import numpy as np

    from apex_tpu.analysis import spmd as spmd_mod

    as_text = getattr(program, "as_text", None)
    if callable(program) and not callable(as_text) \
            and not isinstance(program, str):
        program = program()
        as_text = getattr(program, "as_text", None)
    text = as_text() if callable(as_text) else program
    if not isinstance(text, str):
        raise TypeError(
            "spmd_preflight expects a lowering, module text, or a "
            f"zero-arg callable returning one; got {type(program).__name__}")

    sched = spmd_mod.collective_schedule(text)
    payload = spmd_mod.serialize_schedule(sched).encode("utf-8")
    module_hash = hashlib.sha256(text.encode("utf-8")).hexdigest()
    schedule_hash = hashlib.sha256(payload).hexdigest()
    record = {"label": label, "rank": int(jax.process_index()),
              "n_ranks": int(jax.process_count()),
              "module_hash": module_hash, "schedule_hash": schedule_hash,
              "n_collectives": len(sched), "ok": True}
    if record["n_ranks"] <= 1:
        return record

    me, n = record["rank"], record["n_ranks"]
    combined = hashlib.sha256(
        (module_hash + schedule_hash).encode("utf-8")).hexdigest()
    client = _kv_client()
    if client is not None:
        timeout_ms = max(
            1000, int(_env_float("APEX_TPU_PREFLIGHT_TIMEOUT_S", 120.0)
                      * 1000))
        # the sequence number keeps repeated preflights (resilience
        # rewinds re-run the barrier) from reading a stale round's keys;
        # every rank calls symmetrically, so the counters agree
        prefix = (f"apex_tpu/spmd_preflight/{label}/"
                  f"{next(_PREFLIGHT_SEQ)}")
        try:
            client.key_value_set(f"{prefix}/digest/{me}", combined,
                                 allow_overwrite=True)
            digests = [client.blocking_key_value_get(
                f"{prefix}/digest/{r}", timeout_ms) for r in range(n)]
        except RuntimeError as e:
            raise ClusterInitError(
                f"SPMD preflight barrier for {label!r} timed out on rank "
                f"{me}: a peer never published its schedule digest "
                f"({e}).  Tune via APEX_TPU_PREFLIGHT_TIMEOUT_S."
            ) from e
        divergent = [r for r in range(n) if digests[r] != digests[0]]
        if not divergent:
            return record
        # digest mismatch: move the schedules so the abort names ops
        client.key_value_set(f"{prefix}/sched/{me}",
                             payload.decode("utf-8"), allow_overwrite=True)
        other = 0 if me in divergent else divergent[0]
        try:
            theirs = json.loads(client.blocking_key_value_get(
                f"{prefix}/sched/{other}", timeout_ms))
        except (RuntimeError, ValueError):
            theirs = []
    else:
        # no coordination-service client (exotic init path): fall back
        # to a 32-byte all-gather.  Safe even across diverging programs
        # — the gather's own shape is rank-invariant by construction.
        from jax.experimental import multihost_utils

        digest = np.frombuffer(
            hashlib.sha256(combined.encode("utf-8")).digest(),
            dtype=np.uint8).copy()
        rows = np.asarray(multihost_utils.process_allgather(digest))
        divergent = [r for r in range(rows.shape[0])
                     if not np.array_equal(rows[r], rows[0])]
        if not divergent:
            return record
        lengths = np.asarray(multihost_utils.process_allgather(
            np.asarray([len(payload)], dtype=np.int32)))
        maxlen = int(lengths.max())
        padded = np.zeros(maxlen, dtype=np.uint8)
        padded[:len(payload)] = np.frombuffer(payload, dtype=np.uint8)
        payloads = np.asarray(multihost_utils.process_allgather(padded))
        other = 0 if me in divergent else divergent[0]
        try:
            theirs = json.loads(bytes(
                payloads[other][:int(lengths[other][0])]).decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            theirs = []
    d = spmd_mod.first_divergence(json.loads(payload.decode("utf-8")),
                                  theirs)
    if d is None:
        detail = (f"collective schedules agree ({len(sched)} op(s)) but "
                  f"module hashes differ — the divergence is in "
                  f"non-collective compute (this rank "
                  f"{module_hash[:12]}, rank {other} differs)")
    else:
        i, mine_spell, theirs_spell = d
        detail = (f"first differing collective is op #{i}: rank {me} "
                  f"issues {mine_spell} but rank {other} issues "
                  f"{theirs_spell}")
    raise SpmdPreflightError(
        f"SPMD preflight failed for {label!r}: rank(s) {divergent} "
        f"lowered a program diverging from rank 0 — {detail}.  "
        f"Aborting before the first step instead of deadlocking the "
        f"fleet in a mismatched collective.")


def _env_float(name: str, default: float) -> float:
    val = os.environ.get(name)
    return float(val) if val not in (None, "") else default


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               timeout_s: Optional[float] = None,
               retries: Optional[int] = None,
               backoff_s: Optional[float] = None,
               preflight: Any = None,
               preflight_label: str = "train_step") -> Optional[dict]:
    """Initialize multi-host JAX (the ``torch.distributed.launch`` /
    ``multiproc.py`` analog).

    Arguments default from the environment (``COORDINATOR_ADDRESS``,
    ``WORLD_SIZE``, ``RANK`` — the reference's env contract,
    ``_amp_state.py:38-40``); on Cloud TPU all three are auto-detected and
    ``jax.distributed.initialize()`` needs no arguments.

    Unlike the raw ``jax.distributed.initialize`` (which blocks until its
    coordinator timeout) this call is **bounded**: each attempt runs with
    ``timeout_s`` (env ``APEX_TPU_INIT_TIMEOUT_S``, default 300) and is
    retried ``retries`` times (``APEX_TPU_INIT_RETRIES``, default 2) with
    exponential backoff starting at ``backoff_s``
    (``APEX_TPU_INIT_BACKOFF_S``, default 5) — a peer that never arrives
    (the r02 failure shape: a killed worker whose lease was never
    released) surfaces as a :class:`ClusterInitError` naming the ranks
    that can be missing, not as a wedged process.

    ``preflight`` opts into the SPMD consistency barrier: a zero-arg
    callable (invoked after cluster formation, when the global devices
    exist) returning the lowering of the step this process is about to
    run, or the lowering / module text itself.  Each rank hashes its
    lowered module + collective schedule and cross-checks via one tiny
    all-gather (:func:`spmd_preflight`); a divergent rank raises
    :class:`SpmdPreflightError` naming the first differing collective
    in both spellings, instead of wedging the fleet in the first
    mismatched collective.  Returns the preflight record when the
    barrier ran, else ``None``.
    """
    kwargs = {}
    addr = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if addr:
        kwargs["coordinator_address"] = addr
    ws = num_processes or os.environ.get("WORLD_SIZE")
    if ws:
        kwargs["num_processes"] = int(ws)
    rank = process_id if process_id is not None else os.environ.get("RANK")
    if rank is not None and rank != "":  # RANK="" falls through to
        kwargs["process_id"] = int(rank)  # auto-detection like the others

    timeout_s = timeout_s if timeout_s is not None else \
        _env_float("APEX_TPU_INIT_TIMEOUT_S", 300.0)
    retries = int(retries if retries is not None else
                  _env_float("APEX_TPU_INIT_RETRIES", 2))
    backoff_s = backoff_s if backoff_s is not None else \
        _env_float("APEX_TPU_INIT_BACKOFF_S", 5.0)

    # older jax has no per-call timeout knob; feature-detect once
    if "initialization_timeout" in inspect.signature(
            jax.distributed.initialize).parameters:
        kwargs["initialization_timeout"] = max(1, int(timeout_s))

    attempts = retries + 1
    last_error: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            jax.distributed.initialize(**kwargs)
        except (RuntimeError, OSError, ValueError, jax.errors.JaxRuntimeError
                ) as e:
            # a double-initialize is a programming error, not weather:
            # retrying it burns the whole backoff schedule and then
            # reports a phantom missing-peer problem
            if "already initialized" in str(e).lower():
                raise
            last_error = e
            if attempt + 1 < attempts:
                time.sleep(backoff_s * (2.0 ** attempt))
            continue
        # deliberately OUTSIDE the retry net: a preflight divergence is
        # a program bug, not weather — retrying it re-diverges forever
        if preflight is None:
            return None
        return spmd_preflight(preflight, label=preflight_label)

    n = kwargs.get("num_processes")
    r = kwargs.get("process_id")
    if n is not None:
        others = sorted(set(range(int(n))) - ({int(r)} if r is not None
                                              else set()))
        shape = (f"this is rank {r} of {n}; the missing peer(s) are among "
                 f"ranks {others}" if r is not None else
                 f"expected {n} processes (ranks {others})")
    else:
        shape = "world size unknown (no WORLD_SIZE/num_processes given)"
    raise ClusterInitError(
        f"cluster init failed after {attempts} attempt(s) x {timeout_s:g}s "
        f"(coordinator {kwargs.get('coordinator_address', '<auto>')}): "
        f"{shape}.  Last error: {last_error!r}.  Tune via "
        "APEX_TPU_INIT_TIMEOUT_S / APEX_TPU_INIT_RETRIES / "
        "APEX_TPU_INIT_BACKOFF_S.") from last_error


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _stderr_tail(path: str, limit: int = 2000) -> str:
    """Last ``limit`` chars of a captured stderr file (the diagnosis a
    dying rank left behind), or a placeholder when there is none."""
    try:
        with open(path, "r", errors="replace") as f:
            data = f.read().strip()
    except OSError:
        return "<no stderr captured>"
    return data[-limit:] if data else "<stderr empty>"


def spawn(argslist: Sequence[str], world_size: Optional[int] = None,
          coordinator_port: Optional[int] = None,
          log_prefix: str = "PROC_") -> List[int]:
    """Launch ``world_size`` copies of ``python argslist...`` with the
    distributed env set, wait for all, and return their exit codes
    (reference ``multiproc.py:22-35``).

    ``world_size`` defaults to ``WORLD_SIZE`` in the environment; it must
    be given one way or the other (the reference defaulted to the local
    GPU count, but enumerating devices here would initialize the JAX
    runtime *in the launcher* and wedge the accelerator before the
    workers fork).  ``coordinator_port`` defaults to ``COORDINATOR_PORT``
    in the environment, else a freshly bound free port — which makes a
    collision between concurrent spawns on one machine unlikely (not
    impossible: the port is released before the coordinator re-binds it).

    If any worker exits non-zero, the remaining workers are terminated
    rather than left blocking on cluster formation, and a
    :class:`ClusterInitError` is raised naming the first failing rank
    WITH the tail of its captured stderr (every rank's stderr goes to
    ``{log_prefix}{i}.err``) — a rank that died pre-barrier used to be
    indistinguishable from one that never started.  The same cleanup
    (terminate, reap, close logs) runs if the launcher is interrupted or
    a launch step fails.

    Zombie-peer reaping: a rank that wedges in a collective AFTER a
    peer exited cleanly (its partner is gone, so the collective can
    never complete — the all-zero twin of the crash case above) is
    bounded by a grace window instead of hanging the launcher forever.
    Once the first rank exits 0, the stragglers get
    ``max(APEX_TPU_SPAWN_GRACE_S, elapsed runtime so far)`` seconds to
    follow (env default 60 — the scaling gives a workload that ran for
    minutes a proportional allowance for legitimately skewed per-rank
    epilogues); then they are terminated (SIGTERM, 5s, SIGKILL), and
    spawn raises a :class:`ClusterInitError` naming the wedged ranks —
    within the watchdog budget, not past test teardown.  **Caller
    contract change vs the pre-reaping spawn:** ranks that
    legitimately finish further apart than the scaled window are now
    reaped and reported as wedged; such callers must raise
    ``APEX_TPU_SPAWN_GRACE_S``, or set it ``<= 0`` to disable reaping
    entirely (restoring the old wait-forever behavior).
    """
    argslist = list(argslist)
    if world_size is None:
        ws_env = os.environ.get("WORLD_SIZE")
        if not ws_env:
            raise ValueError(
                "spawn() needs world_size= or the WORLD_SIZE env var "
                "(not derived from the device count: that would "
                "initialize the JAX runtime inside the launcher)")
        world_size = int(ws_env)
    if coordinator_port is None:
        coordinator_port = int(os.environ.get("COORDINATOR_PORT")
                               or _free_port())

    workers: List[subprocess.Popen] = []
    logs = []
    err_paths: List[str] = []

    def _raise_first_failure(codes: List[Optional[int]]) -> None:
        bad = [i for i, c in enumerate(codes) if c not in (None, 0)]
        first = bad[0]
        raise ClusterInitError(
            f"rank {first} exited with code {codes[first]} "
            f"(failing ranks: {bad}; exit codes: {codes}).  "
            f"rank {first} stderr tail ({err_paths[first]}):\n"
            f"{_stderr_tail(err_paths[first])}")

    try:
        for i in range(world_size):
            env = dict(os.environ,
                       COORDINATOR_ADDRESS=f"localhost:{coordinator_port}",
                       WORLD_SIZE=str(world_size), RANK=str(i))
            # rank 0 inherits stdout; others log to files (multiproc.py:30)
            stdout = None
            if i != 0:
                stdout = open(f"{log_prefix}{i}.log", "w")
                logs.append(stdout)
            # every rank's stderr is captured: a dying rank's traceback
            # is the diagnosis the launcher surfaces
            stderr = open(f"{log_prefix}{i}.err", "w")
            logs.append(stderr)
            err_paths.append(f"{log_prefix}{i}.err")
            workers.append(subprocess.Popen([sys.executable] + argslist,
                                            stdout=stdout, stderr=stderr,
                                            env=env))
        # Poll rather than wait sequentially: a crashed rank would leave
        # the rest of the cluster blocked in jax.distributed.initialize
        # waiting for it — fail fast and tear the others down instead.
        import time
        grace_s = float(os.environ.get("APEX_TPU_SPAWN_GRACE_S", "60"))
        launch_t = time.monotonic()
        first_done: Optional[float] = None
        grace_eff = grace_s
        while True:
            codes = [p.poll() for p in workers]
            if all(c is not None for c in codes):
                if any(c != 0 for c in codes):
                    _raise_first_failure(codes)
                return codes
            if grace_s > 0 and any(c == 0 for c in codes):
                if first_done is None:
                    first_done = time.monotonic()
                    # skew allowance scales with observed runtime: a
                    # workload that ran for minutes may legitimately
                    # drain its per-rank epilogues minutes apart, while
                    # a quick run's zombie is still reaped at the base
                    grace_eff = max(grace_s, first_done - launch_t)
                elif time.monotonic() - first_done > grace_eff:
                    # zombie peers: their partner is gone, the pending
                    # collective can never complete — reap, don't hang
                    wedged = [i for i, c in enumerate(codes) if c is None]
                    for p in workers:
                        if p.poll() is None:
                            p.terminate()
                    for p in workers:
                        try:
                            p.wait(timeout=5)
                        except subprocess.TimeoutExpired:
                            p.kill()
                            p.wait()
                    raise ClusterInitError(
                        f"ranks {wedged} still running {grace_eff:g}s after "
                        f"rank {codes.index(0)} exited cleanly (exit codes "
                        f"{codes}): wedged in a collective whose peer is "
                        f"gone; terminated.  rank {wedged[0]} stderr tail "
                        f"({err_paths[wedged[0]]}):\n"
                        f"{_stderr_tail(err_paths[wedged[0]])}")
            if any(c not in (None, 0) for c in codes):
                first_bad = list(codes)   # snapshot at detection time:
                for p in workers:         # peers killed below get -15,
                    if p.poll() is None:  # which must not masquerade as
                        p.terminate()     # the original failure
                for p in workers:  # timed: a SIGTERM-ignoring worker must
                    try:           # not wedge the fail-fast path
                        p.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        p.kill()
                        p.wait()
                _raise_first_failure(first_bad)
            time.sleep(0.2)
    finally:
        for p in workers:
            if p.poll() is None:
                p.terminate()
        for p in workers:  # reap: no zombies in a long-lived parent
            if p.poll() is None:
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
        for f in logs:
            f.close()


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print("usage: python -m apex_tpu.parallel.multiproc script.py ...",
              file=sys.stderr)
        return 2
    try:
        codes = spawn(argv)
    except ClusterInitError as e:
        print(f"multiproc: {e}", file=sys.stderr)
        return 1
    # a signal-killed worker has a negative returncode; never mask it
    return 0 if all(c == 0 for c in codes) else 1


if __name__ == "__main__":
    sys.exit(main())
