"""Multi-host launch helper.

Port of ``apex/parallel/multiproc.py:1-35`` (the one-process-per-GPU
spawner).  On TPU the launch model is one process per *host*, each seeing its
local chips, coordinated by ``jax.distributed.initialize`` — there is nothing
to spawn per chip on a Cloud TPU VM.  This module provides:

- :func:`initialize` — the per-process entry (``jax.distributed``
  wrapper honoring the reference's env-var contract);
- :func:`spawn` / ``python -m apex_tpu.parallel.multiproc script.py …`` —
  the reference's local spawner, for multi-process runs on one machine
  (e.g. N CPU-backend processes, or one process per local accelerator
  runtime).  Matching the reference: rank 0 inherits stdout, every other
  rank logs to ``PROC_<i>.log`` (the reference's ``GPU_<i>.log``,
  ``multiproc.py:30``), ``--world-size``/``--rank`` style overrides via
  ``WORLD_SIZE``, and the launcher waits for all workers.  Unlike the
  reference it also exports ``COORDINATOR_ADDRESS``/``WORLD_SIZE``/``RANK``
  so the spawned script just calls :func:`initialize`.
"""

from __future__ import annotations

import inspect
import os
import subprocess
import sys
import time
from typing import List, Optional, Sequence

import jax


class ClusterInitError(RuntimeError):
    """Cluster formation failed within the configured timeout/retry
    budget — with the expected world shape and candidate missing ranks
    in the message, instead of an indefinite hang."""


def _env_float(name: str, default: float) -> float:
    val = os.environ.get(name)
    return float(val) if val not in (None, "") else default


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               timeout_s: Optional[float] = None,
               retries: Optional[int] = None,
               backoff_s: Optional[float] = None) -> None:
    """Initialize multi-host JAX (the ``torch.distributed.launch`` /
    ``multiproc.py`` analog).

    Arguments default from the environment (``COORDINATOR_ADDRESS``,
    ``WORLD_SIZE``, ``RANK`` — the reference's env contract,
    ``_amp_state.py:38-40``); on Cloud TPU all three are auto-detected and
    ``jax.distributed.initialize()`` needs no arguments.

    Unlike the raw ``jax.distributed.initialize`` (which blocks until its
    coordinator timeout) this call is **bounded**: each attempt runs with
    ``timeout_s`` (env ``APEX_TPU_INIT_TIMEOUT_S``, default 300) and is
    retried ``retries`` times (``APEX_TPU_INIT_RETRIES``, default 2) with
    exponential backoff starting at ``backoff_s``
    (``APEX_TPU_INIT_BACKOFF_S``, default 5) — a peer that never arrives
    (the r02 failure shape: a killed worker whose lease was never
    released) surfaces as a :class:`ClusterInitError` naming the ranks
    that can be missing, not as a wedged process.
    """
    kwargs = {}
    addr = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if addr:
        kwargs["coordinator_address"] = addr
    ws = num_processes or os.environ.get("WORLD_SIZE")
    if ws:
        kwargs["num_processes"] = int(ws)
    rank = process_id if process_id is not None else os.environ.get("RANK")
    if rank is not None and rank != "":  # RANK="" falls through to
        kwargs["process_id"] = int(rank)  # auto-detection like the others

    timeout_s = timeout_s if timeout_s is not None else \
        _env_float("APEX_TPU_INIT_TIMEOUT_S", 300.0)
    retries = int(retries if retries is not None else
                  _env_float("APEX_TPU_INIT_RETRIES", 2))
    backoff_s = backoff_s if backoff_s is not None else \
        _env_float("APEX_TPU_INIT_BACKOFF_S", 5.0)

    # older jax has no per-call timeout knob; feature-detect once
    if "initialization_timeout" in inspect.signature(
            jax.distributed.initialize).parameters:
        kwargs["initialization_timeout"] = max(1, int(timeout_s))

    attempts = retries + 1
    last_error: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            jax.distributed.initialize(**kwargs)
            return
        except (RuntimeError, OSError, ValueError, jax.errors.JaxRuntimeError
                ) as e:
            # a double-initialize is a programming error, not weather:
            # retrying it burns the whole backoff schedule and then
            # reports a phantom missing-peer problem
            if "already initialized" in str(e).lower():
                raise
            last_error = e
            if attempt + 1 < attempts:
                time.sleep(backoff_s * (2.0 ** attempt))

    n = kwargs.get("num_processes")
    r = kwargs.get("process_id")
    if n is not None:
        others = sorted(set(range(int(n))) - ({int(r)} if r is not None
                                              else set()))
        shape = (f"this is rank {r} of {n}; the missing peer(s) are among "
                 f"ranks {others}" if r is not None else
                 f"expected {n} processes (ranks {others})")
    else:
        shape = "world size unknown (no WORLD_SIZE/num_processes given)"
    raise ClusterInitError(
        f"cluster init failed after {attempts} attempt(s) x {timeout_s:g}s "
        f"(coordinator {kwargs.get('coordinator_address', '<auto>')}): "
        f"{shape}.  Last error: {last_error!r}.  Tune via "
        "APEX_TPU_INIT_TIMEOUT_S / APEX_TPU_INIT_RETRIES / "
        "APEX_TPU_INIT_BACKOFF_S.") from last_error


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def spawn(argslist: Sequence[str], world_size: Optional[int] = None,
          coordinator_port: Optional[int] = None,
          log_prefix: str = "PROC_") -> List[int]:
    """Launch ``world_size`` copies of ``python argslist...`` with the
    distributed env set, wait for all, and return their exit codes
    (reference ``multiproc.py:22-35``).

    ``world_size`` defaults to ``WORLD_SIZE`` in the environment; it must
    be given one way or the other (the reference defaulted to the local
    GPU count, but enumerating devices here would initialize the JAX
    runtime *in the launcher* and wedge the accelerator before the
    workers fork).  ``coordinator_port`` defaults to ``COORDINATOR_PORT``
    in the environment, else a freshly bound free port — which makes a
    collision between concurrent spawns on one machine unlikely (not
    impossible: the port is released before the coordinator re-binds it).

    If any worker exits non-zero, the remaining workers are terminated
    rather than left blocking on cluster formation; the same cleanup
    (terminate, reap, close logs) runs if the launcher is interrupted or
    a launch step fails.
    """
    argslist = list(argslist)
    if world_size is None:
        ws_env = os.environ.get("WORLD_SIZE")
        if not ws_env:
            raise ValueError(
                "spawn() needs world_size= or the WORLD_SIZE env var "
                "(not derived from the device count: that would "
                "initialize the JAX runtime inside the launcher)")
        world_size = int(ws_env)
    if coordinator_port is None:
        coordinator_port = int(os.environ.get("COORDINATOR_PORT")
                               or _free_port())

    workers: List[subprocess.Popen] = []
    logs = []
    try:
        for i in range(world_size):
            env = dict(os.environ,
                       COORDINATOR_ADDRESS=f"localhost:{coordinator_port}",
                       WORLD_SIZE=str(world_size), RANK=str(i))
            # rank 0 inherits stdout; others log to files (multiproc.py:30)
            stdout = None
            if i != 0:
                stdout = open(f"{log_prefix}{i}.log", "w")
                logs.append(stdout)
            workers.append(subprocess.Popen([sys.executable] + argslist,
                                            stdout=stdout, env=env))
        # Poll rather than wait sequentially: a crashed rank would leave
        # the rest of the cluster blocked in jax.distributed.initialize
        # waiting for it — fail fast and tear the others down instead.
        import time
        while True:
            codes = [p.poll() for p in workers]
            if all(c is not None for c in codes):
                return codes
            if any(c not in (None, 0) for c in codes):
                for p in workers:
                    if p.poll() is None:
                        p.terminate()
                results = []
                for p in workers:  # timed: a SIGTERM-ignoring worker must
                    try:           # not wedge the fail-fast path
                        results.append(p.wait(timeout=5))
                    except subprocess.TimeoutExpired:
                        p.kill()
                        results.append(p.wait())
                return results
            time.sleep(0.2)
    finally:
        for p in workers:
            if p.poll() is None:
                p.terminate()
        for p in workers:  # reap: no zombies in a long-lived parent
            if p.poll() is None:
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
        for f in logs:
            f.close()


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print("usage: python -m apex_tpu.parallel.multiproc script.py ...",
              file=sys.stderr)
        return 2
    codes = spawn(argv)
    # a signal-killed worker has a negative returncode; never mask it
    return 0 if all(c == 0 for c in codes) else 1


if __name__ == "__main__":
    sys.exit(main())
