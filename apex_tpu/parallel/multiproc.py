"""Multi-host launch helper.

Port of ``apex/parallel/multiproc.py:1-35`` (the one-process-per-GPU
spawner).  On TPU the launch model is one process per *host*, each seeing its
local chips, coordinated by ``jax.distributed.initialize`` — there is nothing
to spawn per chip.  This module provides the initialization wrapper plus the
reference's env-var conventions.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Initialize multi-host JAX (the ``torch.distributed.launch`` /
    ``multiproc.py`` analog).

    Arguments default from the environment (``COORDINATOR_ADDRESS``,
    ``WORLD_SIZE``, ``RANK`` — the reference's env contract,
    ``_amp_state.py:38-40``); on Cloud TPU all three are auto-detected and
    ``jax.distributed.initialize()`` needs no arguments.
    """
    kwargs = {}
    addr = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if addr:
        kwargs["coordinator_address"] = addr
    ws = num_processes or os.environ.get("WORLD_SIZE")
    if ws:
        kwargs["num_processes"] = int(ws)
    rank = process_id if process_id is not None else os.environ.get("RANK")
    if rank is not None:
        kwargs["process_id"] = int(rank)
    jax.distributed.initialize(**kwargs)
