"""Pipeline parallelism over a mesh axis.

Beyond the reference (2019-era apex has no pipeline parallelism — SURVEY.md
section 2 "NOT present"), but required of a complete TPU framework: stage
params live on their pipeline rank, microbatch activations flow stage to
stage over ICI with ``lax.ppermute``, and the backward pipeline falls out of
autodiff (the transpose of ``ppermute`` is the reverse permutation), giving
a GPipe-style schedule: all microbatches forward, then all backward.

Design notes (TPU-first):

- SPMD: every rank runs the same compiled program; "which stage am I" is
  ``lax.axis_index``, so there is no per-stage program or coordinator —
  XLA overlaps the ``ppermute`` transfers with the next tick's compute.
- The schedule is expressed as one ``lax.scan`` over ``M + S - 1`` ticks
  (M microbatches, S stages) — compiler-friendly control flow, no Python
  loop over devices.
- Stage functions must be shape-preserving on the activation
  ``(microbatch, ...) -> (microbatch, ...)`` so the rotating buffer has a
  static shape; width changes belong inside a stage.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.utils.jax_compat import axis_size as _axis_size
from apex_tpu.utils.jax_compat import pvary as _pvary


def stack_stage_params(params_list: Sequence[Any]) -> Any:
    """Stack per-stage param pytrees along a new leading "stage" axis, the
    layout expected by :func:`pipeline_apply` (shard it ``P("pipe", ...)``
    so each rank holds exactly its stage's slice)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    axis_name: str = "pipe",
    n_microbatches: Optional[int] = None,
    stacked: bool = True,
) -> jax.Array:
    """Run ``x`` through ``S = axis_size(axis_name)`` pipeline stages.

    Call **inside** ``shard_map`` over a mesh with ``axis_name``.

    Args:
      stage_fn: ``(one_stage_params, activation) -> activation``,
        shape-preserving.
      stage_params: this rank's stage params — the per-rank slice of a
        :func:`stack_stage_params` tree sharded over ``axis_name``, i.e.
        every leaf carries a leading stage axis of local size 1, which is
        squeezed (checked).  Pass ``stacked=False`` for a tree already at
        per-stage shape.
      x: the full batch ``(batch, ...)``, identical on every rank
        (replicated in_spec).  Split into ``n_microbatches`` equal
        microbatches along axis 0.
      n_microbatches: defaults to ``S``.

    Returns:
      ``(batch, ...)`` outputs of the final stage, identical on every rank
      (so an ``out_specs=P()`` works directly).
    """
    S = _axis_size(axis_name)
    s = lax.axis_index(axis_name)
    M = n_microbatches or S
    batch = x.shape[0]
    if batch % M:
        raise ValueError(f"batch {batch} not divisible into {M} microbatches")

    if stacked:
        # squeeze the local slice of the stacked stage axis (always present
        # and of size 1 in a stack_stage_params tree sharded over the axis)
        def _squeeze(leaf):
            if not leaf.ndim or leaf.shape[0] != 1:
                raise ValueError(
                    f"stacked stage param has local leading dim "
                    f"{leaf.shape}; expected size 1 — shard the "
                    f"stack_stage_params tree over {axis_name!r}, or pass "
                    "stacked=False for per-stage-shaped params")
            return leaf[0]
        params = jax.tree.map(_squeeze, stage_params)
    else:
        params = stage_params

    mb = batch // M
    micro = x.reshape((M, mb) + x.shape[1:])
    # the rotating buffer and the fed microbatches are device-varying over
    # the pipe axis (each rank holds different activations); type them so
    # (replicated x comes in unvarying and the scan carry stays stable)
    micro = _pvary(micro, (axis_name,))
    zero = _pvary(jnp.zeros((mb,) + x.shape[1:], x.dtype), (axis_name,))
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        buf = carry
        # stage 0 ingests microbatch t (while t < M); later stages consume
        # what the previous tick's ppermute delivered.
        feed = lax.dynamic_index_in_dim(micro, jnp.minimum(t, M - 1), 0,
                                        keepdims=False)
        inp = jnp.where(s == 0, feed, buf)
        out = stage_fn(params, inp)
        nxt = lax.ppermute(out, axis_name, fwd_perm)
        # the last stage's output at tick t is microbatch t - (S-1)
        return nxt, out

    _, outs = lax.scan(tick, zero, jnp.arange(M + S - 1))
    # Valid final-stage outputs live at ticks S-1 .. S-1+M-1 on rank S-1.
    tail = lax.dynamic_slice_in_dim(outs, S - 1, M, axis=0)
    y_last = tail.reshape((batch,) + x.shape[1:])
    # Broadcast the last stage's result to every rank so callers can use
    # replicated out_specs; ranks contribute zero except S-1.
    y = jnp.where(s == S - 1, y_last, jnp.zeros_like(y_last))
    return lax.psum(y, axis_name)
