"""Data-parallel gradient reduction.

Port of ``apex/parallel/distributed.py``.  The reference's 511 lines are
mostly machinery to overlap NCCL allreduce with backward compute: grad-hook
bucketing by ``message_size``, a dedicated reduction stream, rank-0 bucket
structure broadcast, out-of-order bucket draining.  Under jit-compiled JAX
**all of that is the compiler's job**: gradients reduced with
``jax.lax.psum`` inside the step function are scheduled asynchronously by XLA
and overlapped with remaining backward compute (SURVEY.md §2 "TPU mapping
note").  What must be ported is the *semantics knob set* (``distributed.py:
134-177``):

- ``gradient_average`` — divide by world size after the sum;
- ``gradient_predivide_factor`` — pre-divide by ``f``, post-multiply by
  ``f / world_size`` for dynamic-range management at large world sizes
  (``distributed.py:379-398``; the post-scale applies only when
  ``gradient_average`` is on — with averaging off, grads deliver at
  ``sum/f``, matching the reference exactly);
- ``allreduce_always_fp32`` — upcast half grads to fp32 for the wire;
- ``compression="sign"`` — optional 1-bit sign compression of buckets before
  the collective.  This is the *intent* of the fork's broken
  ``param_signsgd`` hack (``distributed.py:41-43``, SURVEY.md §0); correct
  uncompressed reduction is the default and sign compression is opt-in.

Collectives ride mesh axes: use these reducers inside ``shard_map`` /
``pmap`` with the mesh from :mod:`apex_tpu.parallel.mesh`.  Under pure
``pjit`` auto-sharding you don't need a reducer at all — XLA inserts the
collective from the sharding specs; ``DistributedDataParallel`` here is for
the explicit-SPMD style that matches apex's semantics exactly.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.utils.jax_compat import axis_size as _axis_size
from apex_tpu.utils.jax_compat import pvary as _pvary


class ReduceOp(enum.Enum):
    """Reference re-exports torch.distributed.ReduceOp
    (``apex/parallel/__init__.py:3-8``)."""
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


def all_reduce(x: Any, axis_name: str, op: ReduceOp = ReduceOp.SUM) -> Any:
    """``torch.distributed.all_reduce`` → mesh-axis collective."""
    fn = {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax,
          ReduceOp.MIN: lax.pmin}.get(op)
    if fn is None:
        raise NotImplementedError(f"ReduceOp {op} not supported on TPU mesh")
    return jax.tree.map(lambda t: fn(t, axis_name), x)


def all_gather(x: Any, axis_name: str) -> Any:
    return jax.tree.map(lambda t: lax.all_gather(t, axis_name), x)


def broadcast(x: Any, axis_name: str, root: int = 0) -> Any:
    """Rank-``root``'s value to everyone (the reference's param-init
    broadcast, ``distributed.py:242``).  Under SPMD with replicated init this
    is usually unnecessary; provided for parity."""
    def bc(t):
        masked = jnp.where(lax.axis_index(axis_name) == root, t,
                           jnp.zeros_like(t))
        return lax.psum(masked, axis_name)
    return jax.tree.map(bc, x)


@dataclasses.dataclass(frozen=True)
class ReduceConfig:
    """The DDP knob set (``distributed.py:134-177`` constructor args that
    still have meaning under XLA; ``message_size``/``delay_allreduce``/
    ``num_allreduce_streams`` are scheduling hints the XLA latency-hiding
    scheduler subsumes)."""

    gradient_average: bool = True
    gradient_predivide_factor: float = 1.0
    allreduce_always_fp32: bool = False
    compression: Optional[str] = None  # None | "sign"


def _fold_reduce_config(self) -> None:
    """Shared constructor logic for the wrappers that accept the reference
    knob spellings: fold them into ``config`` when none is given, reject
    conflicting specifications.  Knob fields default to ``None`` ("not
    passed") so an explicit knob equal to the ReduceConfig default still
    conflicts detectably with an explicit ``config``."""
    knobs = {k: getattr(self, k)
             for k in ("gradient_average", "gradient_predivide_factor",
                       "allreduce_always_fp32", "compression")}
    passed = {k: v for k, v in knobs.items() if v is not None}
    if self.config is None:
        object.__setattr__(self, "config", ReduceConfig(**passed))
        return
    if passed:
        raise ValueError(
            f"pass the reduction knobs either via config= or directly, "
            f"not both (got config={self.config} and {passed})")


def pvary_params(params: Any, axis_name: str) -> Any:
    """Mark replicated params as device-varying so gradients materialize
    *per-rank* instead of being auto-``psum``'d by shard_map's autodiff.

    Under modern SPMD autodiff, the cotangent of a replicated value is summed
    across the mesh automatically (the transpose of broadcast).  That is
    correct but leaves no per-rank gradient to apply apex's wire-format knobs
    (predivide, fp32 upcast, sign compression) to.  Calling this on the
    params before ``jax.grad`` restores the reference's model: per-rank grads
    (``allreduce_hook`` inputs) that the caller then reduces explicitly with
    :func:`reduce_gradients`.  No data movement — it only tags the values.
    """
    return jax.tree.map(lambda p: _pvary(p, (axis_name,)), params)


def reduce_gradients(grads: Any, axis_name: str,
                     config: ReduceConfig = ReduceConfig()) -> Any:
    """Flat-semantics allreduce of a *per-rank* grad pytree
    (``allreduce_bucket``, ``distributed.py:379-398``).

    Expects unreduced (device-varying) grads — i.e. grads of params passed
    through :func:`pvary_params`; reducing already-summed grads would
    multiply them by the world size.
    """
    world = _axis_size(axis_name)

    @jax.named_scope("ddp_allreduce")
    def reduce_leaf(g):
        orig_dtype = g.dtype
        if config.allreduce_always_fp32:
            g = g.astype(jnp.float32)
        if config.compression == "sign":
            g = jnp.sign(g)
        if config.gradient_predivide_factor != 1.0:
            g = g / config.gradient_predivide_factor
        g = lax.psum(g, axis_name)
        # Reference parity (``distributed.py:387-393``): the post-scale
        # runs ONLY under gradient_average; with averaging off the grads
        # stay at sum/f — the predivide is part of the delivered scale,
        # not cancelled.
        if config.gradient_average:
            post = config.gradient_predivide_factor / world
            if post != 1.0:
                g = g * post
        return g.astype(orig_dtype)

    return jax.tree.map(reduce_leaf, grads)


@dataclasses.dataclass(frozen=True)
class DistributedDataParallel:
    """Gradient-reducing wrapper (``distributed.py:134``), usable two ways:

    - ``ddp.reduce(grads)`` inside your step function (the steady-state hook
      path, fired automatically by ``amp.make_train_step(reduce_fn=ddp.reduce)``);
    - ``ddp.reduce_fn`` passed to :func:`apex_tpu.amp.make_train_step`.

    With ``delay_allreduce`` semantics (grad accumulation every N steps),
    simply don't call ``reduce`` on non-boundary steps — the reference's
    ``Reducer`` manual-trigger pattern (``distributed.py:94-131``).

    ``message_size`` is kept for the reference's bucketing knob
    (``distributed.py:167``): XLA schedules collective overlap itself, but
    :meth:`plan_buckets` exposes the same greedy assignment (native-backed)
    for callers that reduce in explicit groups — e.g. ``Reducer`` cadences
    that want one collective per ~message_size elements.
    """

    axis_name: str = "data"
    config: Optional[ReduceConfig] = None
    message_size: int = 10_000_000
    # Reference-constructor spellings (distributed.py:167-177); folded into
    # ``config`` when one isn't given explicitly.
    gradient_average: Optional[bool] = None
    gradient_predivide_factor: Optional[float] = None
    allreduce_always_fp32: Optional[bool] = None
    compression: Optional[str] = None

    def __post_init__(self):
        _fold_reduce_config(self)

    def reduce(self, grads: Any) -> Any:
        return reduce_gradients(grads, self.axis_name, self.config)

    def plan_buckets(self, grads: Any,
                     triggers: Optional[Any] = None):
        """Greedy in-order bucket ids for the leaves of ``grads``
        (first-iteration bucket construction,
        ``apex/parallel/distributed.py:339-362``; planning runs in the
        native host library when built, ``csrc/apex_tpu_C.cpp``)."""
        from apex_tpu import _native
        leaves = jax.tree.leaves(grads)
        numels = [int(l.size) for l in leaves]
        trig = jax.tree.leaves(triggers) if triggers is not None else None
        return _native.plan_buckets(numels, self.message_size, trig)

    @property
    def reduce_fn(self) -> Callable[[Any], Any]:
        return self.reduce

    def pvary(self, params: Any) -> Any:
        """See :func:`pvary_params` — apply to params before ``jax.grad`` so
        grads arrive per-rank for :meth:`reduce`."""
        return pvary_params(params, self.axis_name)

    def broadcast_params(self, params: Any, root: int = 0) -> Any:
        """Initial param sync (``distributed.py:242``)."""
        return broadcast(params, self.axis_name, root)


@dataclasses.dataclass(frozen=True)
class Reducer:
    """Manual-trigger variant (``distributed.py:94-131``): user decides when
    to reduce (e.g. every N accumulation steps)."""

    axis_name: str = "data"
    config: Optional[ReduceConfig] = None
    gradient_average: Optional[bool] = None
    gradient_predivide_factor: Optional[float] = None
    allreduce_always_fp32: Optional[bool] = None
    compression: Optional[str] = None

    def __post_init__(self):
        _fold_reduce_config(self)

    def reduce(self, grads: Any) -> Any:
        return reduce_gradients(grads, self.axis_name, self.config)
