"""Dtype-conversion and master-param utilities.

Port of ``apex/fp16_utils/fp16util.py``.  PyTorch modules become param
pytrees: "convert the network" means casting leaves, "prep param lists" means
building an fp32 master copy (optionally flattened into a single vector —
the reference's ``flat_master`` mode, ``fp16util.py:90-133``).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.frontend import default_keep_fp32_filter
from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops.multi_tensor import multi_tensor_scale


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def tree_to_half(params: Any, half_dtype=jnp.bfloat16) -> Any:
    """Cast every floating leaf to the half dtype (reference ``tofp16`` /
    ``network_to_half``, ``fp16util.py:7-41``)."""
    return jax.tree.map(
        lambda x: x.astype(half_dtype) if _is_float(x) else x, params)


def tree_to_float(params: Any) -> Any:
    """Cast every floating leaf to fp32 (reference ``convert_module(float)``)."""
    return jax.tree.map(
        lambda x: x.astype(jnp.float32) if _is_float(x) else x, params)


def convert_network(params: Any, dtype,
                    keep_fp32_filter: Callable = default_keep_fp32_filter) -> Any:
    """Batchnorm-safe network conversion (reference ``convert_network``,
    ``fp16util.py:44-70``): cast floating leaves to ``dtype`` except params on
    normalization paths, which stay fp32."""
    def cast(path, x):
        if not _is_float(x):
            return x
        if keep_fp32_filter(path):
            return x.astype(jnp.float32)
        return x.astype(dtype)
    return jax.tree_util.tree_map_with_path(cast, params)


# alias matching the reference name for the BN-only piece
def BN_convert_float(params: Any,
                     keep_fp32_filter: Callable = default_keep_fp32_filter) -> Any:
    """Force normalization-path leaves back to fp32 (``fp16util.py:22-32``)."""
    def cast(path, x):
        if _is_float(x) and keep_fp32_filter(path):
            return x.astype(jnp.float32)
        return x
    return jax.tree_util.tree_map_with_path(cast, params)


def convert_module(params: Any, dtype) -> Any:
    """Cast one module's param subtree to ``dtype`` unconditionally
    (reference ``convert_module``, ``fp16util.py:44-57`` — the per-module
    worker ``convert_network`` drives; batchnorm exemption is the *caller's*
    recursion decision there, and :func:`convert_network`'s filter here)."""
    return tree_to_half(params, dtype)


class FP16Model:
    """Half-precision wrapper around an apply function (reference
    ``FP16Model``, ``fp16util.py:73-84``: ``network_to_half`` the module,
    cast inputs to half in ``forward``).

    >>> m = FP16Model(model.apply)
    >>> half_params = m.convert(params)      # network_to_half
    >>> y = m(half_params, x)                # inputs cast to half
    """

    def __init__(self, apply_fn: Callable, half_dtype=jnp.bfloat16):
        self.apply_fn = apply_fn
        self.half_dtype = half_dtype

    def convert(self, params: Any) -> Any:
        return tree_to_half(params, self.half_dtype)

    def __call__(self, params: Any, *args, **kwargs):
        import numpy as np

        def cast(x):
            # Only array inputs are tensor data; Python-scalar kwargs are
            # hyperparameters and must stay static (the reference casts only
            # the input tensor, fp16util.py:83).
            if isinstance(x, (jax.Array, np.ndarray)) and jnp.issubdtype(
                    jnp.asarray(x).dtype, jnp.floating):
                return jnp.asarray(x).astype(self.half_dtype)
            return x
        args, kwargs = jax.tree.map(cast, (args, kwargs))
        return self.apply_fn(params, *args, **kwargs)


def prep_param_lists(params: Any, flat_master: bool = False
                     ) -> Tuple[Any, Any]:
    """Build (model_params, master_params) (reference ``prep_param_lists``,
    ``fp16util.py:90-133``).

    With ``flat_master`` the fp32 master is a single flat vector — the memory
    layout the fused flat-buffer optimizer uses.  Returns
    ``(params, master)`` where ``master`` is either a matching pytree of fp32
    leaves or ``(flat_vector, unravel_fn)``.
    """
    if flat_master:
        leaves, treedef = jax.tree.flatten(params)
        float_idx = [i for i, l in enumerate(leaves) if _is_float(l)]
        if not float_idx:
            raise ValueError("no floating params to build a flat master from")
        flat = jnp.concatenate([jnp.ravel(leaves[i]).astype(jnp.float32)
                                for i in float_idx])
        shapes = [leaves[i].shape for i in float_idx]

        def unravel(vec):
            # Non-float leaves (step counters, index tables) pass through
            # unchanged; only float leaves live in the flat master.
            out = list(leaves)
            off = 0
            for i, s in zip(float_idx, shapes):
                n = 1
                for d in s:
                    n *= d
                out[i] = vec[off:off + n].reshape(s)
                off += n
            return jax.tree.unflatten(treedef, out)

        return params, (flat, unravel)
    master = tree_to_float(params)
    return params, master


def model_grads_to_master_grads(model_grads: Any) -> Any:
    """fp16 model grads → fp32 master grads in one fused pass
    (``fp16util.py:136-154``)."""
    leaves, treedef = jax.tree.flatten(model_grads)
    outs, _ = multi_tensor_applier(multi_tensor_scale, [leaves], 1.0,
                                   out_dtype=jnp.float32)
    return jax.tree.unflatten(treedef, outs)


def master_params_to_model_params(master_params: Any, model_dtype) -> Any:
    """fp32 masters → model-dtype params in one fused pass
    (``fp16util.py:157-172``)."""
    leaves, treedef = jax.tree.flatten(master_params)
    outs, _ = multi_tensor_applier(multi_tensor_scale, [leaves], 1.0,
                                   out_dtype=model_dtype)
    return jax.tree.unflatten(treedef, outs)


def to_python_float(t) -> float:
    """Host-side scalar extraction (``fp16util.py:176-180``).  This *is* a
    device sync — never call it inside the hot loop."""
    return float(jax.device_get(t))


def clip_grad_norm(grads: Any, max_norm: float, norm_type: float = 2.0
                   ) -> Tuple[Any, jax.Array]:
    """Global-norm gradient clipping (reference re-exports torch's
    ``clip_grad_norm``, ``fp16util.py:182-187``).  Returns (clipped, norm)."""
    leaves = jax.tree.leaves(grads)
    if norm_type == 2.0:
        norm = jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                            for l in leaves))
    else:
        norm = sum(jnp.sum(jnp.abs(l.astype(jnp.float32)) ** norm_type)
                   for l in leaves) ** (1.0 / norm_type)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda l: (l * scale).astype(l.dtype), grads), norm
