"""The general master-weight optimizer wrapper.

Port of ``apex/fp16_utils/fp16_optimizer.py`` (the 643-line explicit wrapper
with the ``optimizer.backward(loss)`` API).  The reference docs mark it
deprecated in favor of amp; it is kept here for the same reason it is kept
there — an explicit, inspectable master-weight flow with manual control of
unscale / clip / step.  Functionally it is a thin veneer over the same state
machine :class:`apex_tpu.amp.Amp` uses, with the reference's method names.

All methods are traceable; drive them inside your own ``jax.jit``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import optax

from apex_tpu.amp.scaler import LossScaler, LossScaleState
from apex_tpu.fp16_utils.fp16util import clip_grad_norm as _clip_grad_norm
from apex_tpu.fp16_utils.fp16util import tree_to_float


class FP16OptimizerState(NamedTuple):
    master_params: Any          # fp32
    opt_state: Any
    scaler_state: LossScaleState


@dataclasses.dataclass(frozen=True)
class FP16Optimizer:
    """Master-weight wrapper around any optax transformation
    (reference ``fp16_utils/fp16_optimizer.py:13``).

    Args mirror the reference constructor: ``static_loss_scale`` /
    ``dynamic_loss_scale`` / ``dynamic_loss_args``
    (``fp16_optimizer.py:134-172``).
    """

    tx: optax.GradientTransformation
    static_loss_scale: float = 1.0
    dynamic_loss_scale: bool = False
    model_dtype: Any = jnp.bfloat16
    scale_window: int = 1000          # legacy DynamicLossScaler default
    init_scale: float = 2.0 ** 16

    def _scaler(self) -> LossScaler:
        if self.dynamic_loss_scale:
            return LossScaler(loss_scale="dynamic", init_scale=self.init_scale,
                              scale_window=self.scale_window)
        return LossScaler(loss_scale=self.static_loss_scale)

    def init(self, model_params: Any) -> FP16OptimizerState:
        """fp32 master clones of the (possibly half) model params
        (``fp16_optimizer.py:190-230`` master construction)."""
        master = tree_to_float(model_params)
        return FP16OptimizerState(
            master_params=master,
            opt_state=self.tx.init(master),
            scaler_state=self._scaler().init_state(),
        )

    def model_params(self, state: FP16OptimizerState) -> Any:
        """Half view of the masters (the master→model copy)."""
        return jax.tree.map(
            lambda x: x.astype(self.model_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            state.master_params)

    def backward(self, state: FP16OptimizerState, loss_fn: Callable,
                 *args) -> Tuple[jax.Array, Any]:
        """Scaled-loss gradient (reference ``backward``,
        ``fp16_optimizer.py:462-523``).  Returns ``(loss, model_grads)`` with
        grads at model dtype, still scaled."""
        params_c = self.model_params(state)

        def scaled(p):
            loss = loss_fn(p, *args)
            return (loss.astype(jnp.float32)
                    * state.scaler_state.loss_scale), loss

        grads, loss = jax.grad(scaled, has_aux=True)(params_c)
        return loss, grads

    def update_master_grads(self, state: FP16OptimizerState, model_grads: Any
                            ) -> Tuple[Any, jax.Array]:
        """Unscale model grads into fp32 master grads + finite flag
        (``update_master_grads``, ``fp16_optimizer.py:525-578``)."""
        return self._scaler().unscale(model_grads, state.scaler_state)

    def clip_master_grads(self, master_grads: Any, max_norm: float,
                          norm_type: float = 2.0) -> Tuple[Any, jax.Array]:
        """Global-norm clip on the fp32 master grads (``clip_master_grads``,
        ``fp16_optimizer.py:274-296``)."""
        return _clip_grad_norm(master_grads, max_norm, norm_type)

    def step(self, state: FP16OptimizerState, model_grads: Any,
             clip_norm: Optional[float] = None
             ) -> Tuple[FP16OptimizerState, dict]:
        """unscale → (clip) → overflow-gated inner step
        (``fp16_optimizer.py:423-460`` step + overflow skip)."""
        scaler = self._scaler()
        master_grads, finite = self.update_master_grads(state, model_grads)
        if clip_norm is not None:
            master_grads, _ = self.clip_master_grads(master_grads, clip_norm)
        new_sstate, overflow = scaler.update(state.scaler_state, finite)

        def do_step(operand):
            master, opt_state = operand
            updates, new_opt = self.tx.update(master_grads, opt_state, master)
            return optax.apply_updates(master, updates), new_opt

        master, opt_state = jax.lax.cond(
            overflow, lambda o: o, do_step,
            (state.master_params, state.opt_state))
        return (FP16OptimizerState(master, opt_state, new_sstate),
                {"overflow": overflow, "loss_scale": new_sstate.loss_scale})

    def step_with_closure(self, state: FP16OptimizerState,
                          loss_fn: Callable, *args,
                          clip_norm: Optional[float] = None
                          ) -> Tuple[FP16OptimizerState, jax.Array, dict]:
        """Closure-driven step (reference ``step(closure)``,
        ``fp16_optimizer.py:361-460``): evaluate the scaled backward and
        the conditional update in one call, returning
        ``(new_state, loss, info)``.  optax transformations evaluate
        gradients exactly once per step, so the closure runs once — the
        reference re-invokes it only for multi-evaluation optimizers
        (LBFGS-style), which have no optax counterpart here.
        """
        loss, grads = self.backward(state, loss_fn, *args)
        new_state, info = self.step(state, grads, clip_norm=clip_norm)
        return new_state, loss, info

    # -- checkpointing (``fp16_optimizer.py:298-359``) -------------------

    def state_dict(self, state: FP16OptimizerState) -> dict:
        """Persistable dict: fp32 masters + inner state + scaler state — the
        reference's "save masters separately" option 2, and it closes the
        reference's gap of not persisting amp scaler state (SURVEY.md §5.4)."""
        return {
            "master_params": state.master_params,
            "opt_state": state.opt_state,
            "loss_scale": state.scaler_state.loss_scale,
            "unskipped": state.scaler_state.unskipped,
        }

    def load_state_dict(self, d: dict) -> FP16OptimizerState:
        return FP16OptimizerState(
            master_params=d["master_params"],
            opt_state=d["opt_state"],
            scaler_state=LossScaleState(
                loss_scale=jnp.asarray(d["loss_scale"], jnp.float32),
                unskipped=jnp.asarray(d["unskipped"], jnp.int32)),
        )
