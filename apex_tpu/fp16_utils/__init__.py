"""apex_tpu.fp16_utils — manual mixed-precision utilities.

Port of ``apex/fp16_utils/__init__.py:1-16`` (11 utility functions +
``FP16_Optimizer`` + the legacy scalers).
"""

from apex_tpu.fp16_utils.fp16_optimizer import FP16Optimizer, FP16OptimizerState
from apex_tpu.fp16_utils.fp16util import (
    BN_convert_float,
    FP16Model,
    clip_grad_norm,
    convert_module,
    convert_network,
    master_params_to_model_params,
    model_grads_to_master_grads,
    prep_param_lists,
    to_python_float,
    tree_to_float,
    tree_to_half,
)
from apex_tpu.fp16_utils.loss_scaler import DynamicLossScaler, LossScaler

# Reference-spelling aliases (fp16util.py:7-41; fp16_optimizer.py class
# name with the underscore, `apex/fp16_utils/__init__.py:14`).
tofp16 = tree_to_half
network_to_half = tree_to_half
FP16_Optimizer = FP16Optimizer

__all__ = [
    "FP16Optimizer", "FP16OptimizerState", "FP16_Optimizer",
    "BN_convert_float", "FP16Model", "clip_grad_norm", "convert_module",
    "convert_network",
    "master_params_to_model_params", "model_grads_to_master_grads",
    "prep_param_lists", "to_python_float", "tree_to_float", "tree_to_half",
    "tofp16", "network_to_half",
    "DynamicLossScaler", "LossScaler",
]
