"""Legacy loss scalers.

Port of ``apex/fp16_utils/loss_scaler.py``: the static ``LossScaler``
(``:10-45``) and ``DynamicLossScaler`` (``:47-132``, init ``2**32``, factor 2,
window 1000 — note these legacy defaults differ from amp's scaler).  Kept for
API parity with the reference's deprecated-but-present surface; new code
should use :class:`apex_tpu.amp.LossScaler`, whose state lives on device.

These legacy classes are *host-side stateful* like the originals: calling
:meth:`update_scale` with a host boolean mutates Python attributes.  That is
only usable outside jit (e.g. in eager experimentation loops).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


class LossScaler:
    """Static loss scaler (``loss_scaler.py:10-45``)."""

    def __init__(self, scale: float = 1.0):
        self.cur_scale = float(scale)

    def has_overflow(self, params) -> bool:
        return False

    @property
    def loss_scale(self) -> float:
        return self.cur_scale

    def scale_gradient(self, grads):
        return jax.tree.map(lambda g: g * self.loss_scale, grads)

    def backward(self, loss_fn, params, *args):
        """Gradient of ``loss * scale`` (the eager analog of
        ``scaled_loss.backward()``)."""
        return jax.grad(
            lambda p: loss_fn(p, *args).astype(jnp.float32) * self.loss_scale
        )(params)

    def update_scale(self, overflow: bool) -> None:
        pass


class DynamicLossScaler:
    """Dynamic legacy scaler (``loss_scaler.py:47-132``)."""

    def __init__(self, init_scale: float = 2.0 ** 32, scale_factor: float = 2.0,
                 scale_window: int = 1000):
        self.cur_scale = float(init_scale)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.cur_iter = 0
        self.last_overflow_iter = -1

    def has_overflow(self, grads: Any) -> bool:
        """Host-side per-param overflow scan (``loss_scaler.py:57-76``)."""
        for leaf in jax.tree.leaves(grads):
            if not bool(jnp.isfinite(leaf).all()):
                return True
        return False

    @property
    def loss_scale(self) -> float:
        return self.cur_scale

    def scale_gradient(self, grads):
        return jax.tree.map(lambda g: g * self.loss_scale, grads)

    def backward(self, loss_fn, params, *args):
        return jax.grad(
            lambda p: loss_fn(p, *args).astype(jnp.float32) * self.loss_scale
        )(params)

    def update_scale(self, overflow: bool) -> None:
        """(``loss_scaler.py:94-110``): halve on overflow; double after
        ``scale_window`` clean iterations."""
        if overflow:
            self.cur_scale = max(self.cur_scale / self.scale_factor, 1.0)
            self.last_overflow_iter = self.cur_iter
        elif (self.cur_iter - self.last_overflow_iter) % self.scale_window == 0:
            self.cur_scale *= self.scale_factor
        self.cur_iter += 1
